"""Fleet-wide KV intelligence (ISSUE 12): the prefix-cache directory,
cache-hit-maximizing routing, and prefill/decode disaggregation with
int8 KV handoff.

The acceptance spine: a role-split fleet (prefill-heavy + decode-heavy
replicas) moves every long prompt's KV from the prefill replica to its
decode home through ``PagedKVManager.export_blocks`` /
``import_blocks`` — token-identical to offline ``generate_fast``, with
paired ``kv_handoff_out``/``kv_handoff_in`` events (the
``check_handoff_balance`` trace rule), ``handoff_ms`` lifecycle
attribution on the destination engine, and ~4x cheaper bytes when the
wire rides the PR 9 int8 codec.  Around it: export/import round-trip
properties on both managers (f32 + int8 pools, COW-shared blocks,
truncate-after-import, byte budgets), the PrefixDirectory unit
surface (register/lookup/TTL/evict/drop), directory-first routing
(hit/steal/miss/stale verdicts, back-compat ``prefix_misses``), chaos
directory-kill degradation to exact PR 8 affinity behavior with zero
token loss, and the ``hetu_top --fleet`` role + directory columns.

All CPU-harness, all smoke-tier (tiny random-weight GPTs — the
contract is placement and data movement, not model quality).
"""

import os

import numpy as np
import pytest

import hetu_tpu as ht  # noqa: F401  (platform forcing + compat shims)
import jax.numpy as jnp
from hetu_tpu import quant, telemetry
from hetu_tpu.models import GPTConfig
from hetu_tpu.models.gpt_decode import generate_fast
from hetu_tpu.ps import faults
from hetu_tpu.serving import (
    KVCacheManager, PagedKVManager, PrefixDirectory, Request,
    ServingEngine, ServingRouter, prefix_hash, resolve_handoff_quant,
)
from hetu_tpu.telemetry import top
from hetu_tpu.telemetry.trace import (
    check_handoff_balance, check_span_balance, read_events,
)

pytestmark = pytest.mark.smoke


def _rand_gpt(name="fk", L=2, H=2, Dh=8, V=61, S=32, seed=0):
    """Deterministic random params in generate_fast's naming contract
    (mirrors test_router's helper; kept local so the files stay
    independently runnable)."""
    rng = np.random.RandomState(seed)
    hd = H * Dh
    p = {f"{name}_wte_table": rng.randn(V, hd) * 0.05,
         f"{name}_wpe": rng.randn(S, hd) * 0.05,
         f"{name}_ln_f_scale": np.ones(hd),
         f"{name}_ln_f_bias": np.zeros(hd)}
    for i in range(L):
        us = f"{name}_h{i}"
        for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                       ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                       ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
            p[f"{us}_{w}_weight"] = rng.randn(*shp) * 0.05
            p[f"{us}_{w}_bias"] = np.zeros(shp[1])
        for ln in ("ln1", "ln2"):
            p[f"{us}_{ln}_scale"] = np.ones(hd)
            p[f"{us}_{ln}_bias"] = np.zeros(hd)
    cfg = GPTConfig(vocab_size=V, hidden_size=hd, num_hidden_layers=L,
                    num_attention_heads=H, max_position_embeddings=S,
                    batch_size=1, seq_len=S, dropout_rate=0.0)
    return p, cfg


@pytest.fixture(scope="module")
def model():
    return _rand_gpt()


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.setenv("HETU_TELEMETRY", "1")
    monkeypatch.delenv("HETU_CHAOS", raising=False)
    monkeypatch.delenv("HETU_HANDOFF_QUANT", raising=False)
    faults.reset_plans()
    telemetry.reset()
    yield
    faults.reset_plans()
    telemetry.reset()


def _factory(model, **kw):
    p, cfg = model
    kw.setdefault("slots", 2)
    kw.setdefault("queue_limit", 16)
    kw.setdefault("fast_path", False)
    kw.setdefault("paged", True)
    kw.setdefault("kv_block", 8)
    kw.setdefault("prefix_share", True)
    return lambda i: ServingEngine(p, cfg, **kw)


def _offline(model, req):
    p, cfg = model
    return generate_fast(p, cfg, [req.prompt],
                         num_tokens=req.max_new_tokens)[0].tolist()


def _mgr(**kw):
    base = dict(layers=2, heads=2, head_dim=8, slots=2, max_seq_len=32,
                block=8, prefix_share=True)
    base.update(kw)
    return PagedKVManager(**base)


def _fill(m, seed=0):
    """Random content into EVERY pool block so gathered spans are
    distinguishable (int8 pools get a (payload, scales) pair)."""
    rng = np.random.RandomState(seed)

    def one(cache):
        if isinstance(cache, tuple):
            q = rng.randint(-127, 128, cache[0].shape).astype(np.int8)
            s = (rng.rand(*cache[1].shape) + 0.01).astype(np.float32)
            return (jnp.asarray(q), jnp.asarray(s))
        return jnp.asarray(rng.randn(*cache.shape).astype(np.float32))

    m.cache_k = one(m.cache_k)
    m.cache_v = one(m.cache_v)


def _span_f32(m, slot):
    """The slot's filled span as dequantized f32 host arrays."""
    n = m.blocks_needed(int(m.lengths[slot]))
    idx = [int(b) for b in m.tables[slot, :n]]

    def one(cache):
        if isinstance(cache, tuple):
            return np.asarray(quant.kv_decode(
                jnp.asarray(np.asarray(cache[0])[:, idx]),
                jnp.asarray(np.asarray(cache[1])[:, idx])))
        return np.asarray(cache)[:, idx]

    return one(m.cache_k), one(m.cache_v)


# --------------------------------------------------------------------- #
# export/import round-trip properties (satellite 1)
# --------------------------------------------------------------------- #

class TestHandoffWire:
    def test_resolve_handoff_quant_modes(self, monkeypatch):
        assert resolve_handoff_quant("auto") == "auto"
        assert resolve_handoff_quant("int8") == "int8"
        assert resolve_handoff_quant("off") is None
        assert resolve_handoff_quant("0") is None
        monkeypatch.setenv("HETU_HANDOFF_QUANT", "int8")
        assert resolve_handoff_quant() == "int8"
        with pytest.raises(ValueError):
            resolve_handoff_quant("fp4")

    def test_paged_f32_round_trip_bit_identical(self):
        """Exact pool, auto wire: the imported span is bit-identical,
        the source untouched (pure read), and the byte budget adds up
        on both sides."""
        src, dst = _mgr(), _mgr()
        _fill(src, seed=1)
        prompt = list(range(1, 12))                       # 11 tokens
        slot, _ = src.alloc("r0", prompt, len(prompt))
        src.advance(slot, len(prompt))
        ref_before = src.ref.copy()
        pay = src.export_blocks(slot)
        assert pay["layout"] == "paged" and pay["quant"] is None
        assert pay["length"] == 11 and pay["k"].shape[1] == 2
        assert np.array_equal(src.ref, ref_before)        # pure read
        assert src.exports == 1 and src.export_bytes == pay["nbytes"]
        slot2 = dst.import_blocks(pay, "r0", prompt=prompt)
        assert slot2 is not None
        assert int(dst.lengths[slot2]) == 11
        k0, v0 = _span_f32(src, slot)
        k1, v1 = _span_f32(dst, slot2)
        assert np.array_equal(k0, k1) and np.array_equal(v0, v1)
        assert dst.imports == 1 and dst.import_bytes == pay["nbytes"]
        assert dst.stats()["imports"] == 1
        assert src.stats()["export_bytes"] == pay["nbytes"]

    def test_paged_int8_pool_native_wire(self):
        """int8 pool to int8 pool: the native (payload, scales) pair IS
        the wire — no requantization, bit-identical on arrival."""
        src, dst = _mgr(dtype=jnp.int8), _mgr(dtype=jnp.int8)
        _fill(src, seed=2)
        prompt = list(range(1, 10))
        slot, _ = src.alloc("r0", prompt, len(prompt))
        src.advance(slot, len(prompt))
        pay = src.export_blocks(slot)
        assert pay["quant"] == "int8"
        assert isinstance(pay["k"], tuple) and pay["k"][0].dtype == np.int8
        assert pay["nbytes"] < pay["raw_nbytes"] / 2
        slot2 = dst.import_blocks(pay, "r0")
        k0, v0 = _span_f32(src, slot)
        k1, v1 = _span_f32(dst, slot2)
        assert np.array_equal(k0, k1) and np.array_equal(v0, v1)

    def test_paged_forced_int8_wire_cheap_and_close(self):
        """f32 pools with a forced int8 wire: ~4x fewer bytes (scale
        planes ride along), small quantization error, and the mixed
        direction (int8 wire -> exact pool) dequantizes."""
        src, dst = _mgr(head_dim=16), _mgr(head_dim=16)
        _fill(src, seed=3)
        prompt = list(range(1, 14))
        slot, _ = src.alloc("r0", prompt, len(prompt))
        src.advance(slot, len(prompt))
        pay = src.export_blocks(slot, quant_mode="int8")
        assert pay["quant"] == "int8"
        assert pay["nbytes"] < pay["raw_nbytes"] / 3
        slot2 = dst.import_blocks(pay, "r0")
        k0, v0 = _span_f32(src, slot)
        k1, v1 = _span_f32(dst, slot2)
        assert float(np.abs(k0 - k1).max()) < 0.05
        assert float(np.abs(v0 - v1).max()) < 0.05

    def test_cow_shared_blocks_survive_export_and_reregister(self):
        """A COW-shared prefix stays shared on the source after export
        (refcounts untouched), and ``import_blocks(prompt=...)``
        re-registers it on the destination so the next admission there
        attaches the imported blocks refcounted."""
        src, dst = _mgr(), _mgr()
        _fill(src, seed=4)
        p16 = list(range(1, 17))                          # 2 full blocks
        slot, _ = src.alloc("a", p16 + [40], 20)
        src.advance(slot, 17)
        src.register_prefix(p16 + [40], slot)
        shared = src.blocks_shared
        assert shared >= 2                                # prefix holds refs
        pay = src.export_blocks(slot)
        assert src.blocks_shared == shared                # untouched
        slot2 = dst.import_blocks(pay, "a", prompt=p16 + [40])
        assert dst.stats()["prefix_entries"] >= 1
        dst.release(slot2)                                # prefix keeps blocks
        free_before = dst.free_blocks
        slot3, cached = dst.alloc("b", p16 + [41], 20)
        assert cached == 16                               # warm attach
        assert dst.free_blocks == free_before - 1         # only the tail
        k0, _ = _span_f32(src, slot)
        k1, _ = _span_f32(dst, slot3)
        assert np.array_equal(k0[:, :2], k1[:, :2])       # shared blocks

    def test_truncate_after_import(self):
        """Speculative rollback composes with a handoff: an imported
        slot truncates at refcount discipline — the reservation is
        KEPT (a replay holds the same blocks), the surviving span's
        content is intact, and release returns everything."""
        src, dst = _mgr(), _mgr()
        _fill(src, seed=5)
        prompt = list(range(1, 18))                       # 3 blocks
        slot, _ = src.alloc("r", prompt, len(prompt))
        src.advance(slot, len(prompt))
        pay = src.export_blocks(slot)
        slot2 = dst.import_blocks(pay, "r", reserve=24)
        free_after_import = dst.free_blocks
        dst.truncate(slot2, 9)                            # roll back 8
        assert int(dst.lengths[slot2]) == 9
        assert dst.free_blocks == free_after_import       # reservation kept
        k0, _ = _span_f32(src, slot)
        k1, _ = _span_f32(dst, slot2)
        assert np.array_equal(k0[:, :1], k1[:, :1])
        dst.release(slot2)
        assert dst.free_blocks == dst.capacity_blocks

    def test_import_backpressure_and_validation(self):
        src = _mgr()
        _fill(src, seed=6)
        prompt = list(range(1, 10))
        slot, _ = src.alloc("r", prompt, len(prompt))
        src.advance(slot, len(prompt))
        pay = src.export_blocks(slot)
        tiny = _mgr(slots=1, pool_blocks=2)               # 1 usable block
        assert tiny.import_blocks(pay, "r") is None       # blocks short
        with pytest.raises(ValueError):
            _mgr(block=16).import_blocks(pay, "r")        # block mismatch
        with pytest.raises(ValueError):
            _mgr().import_blocks(pay, "r", reserve=4)     # below length
        with pytest.raises(ValueError):
            _mgr().import_blocks(dict(pay, layout="contiguous"), "r")

    def test_contiguous_manager_parity(self):
        """The slot-contiguous manager has span export parity: the
        same payload contract, both wire modes."""
        src = KVCacheManager(layers=2, heads=2, head_dim=8, slots=2,
                             max_seq_len=32)
        dst = KVCacheManager(layers=2, heads=2, head_dim=8, slots=2,
                             max_seq_len=32)
        rng = np.random.RandomState(7)
        src.cache_k = jnp.asarray(
            rng.randn(*src.cache_k.shape).astype(np.float32))
        src.cache_v = jnp.asarray(
            rng.randn(*src.cache_v.shape).astype(np.float32))
        slot = src.alloc("r", 11)
        src.lengths[slot] = 11
        pay = src.export_blocks(slot)
        assert pay["layout"] == "contiguous" and pay["length"] == 11
        slot2 = dst.import_blocks(pay, "r")
        assert np.array_equal(np.asarray(src.cache_k)[:, slot, :11],
                              np.asarray(dst.cache_k)[:, slot2, :11])
        pay8 = src.export_blocks(slot, quant_mode="int8")
        assert pay8["quant"] == "int8"
        assert pay8["nbytes"] < pay["nbytes"]
        with pytest.raises(ValueError):
            _mgr().import_blocks(pay, "r")                # layout mismatch


# --------------------------------------------------------------------- #
# the directory (tentpole unit surface)
# --------------------------------------------------------------------- #

class TestPrefixDirectory:
    def test_register_lookup_longest_cut(self):
        d = PrefixDirectory()
        kv = _mgr()
        d.attach(0, kv)
        _fill(kv)
        p16 = list(range(1, 17))
        slot, _ = kv.alloc("a", p16 + [40], 20)
        kv.advance(slot, 17)
        kv.register_prefix(p16 + [40], slot)              # feeds the map
        assert d.registrations > 0
        hint, outcome = d.lookup(p16 + [41, 42])
        assert outcome is None and hint == (0, 16)        # longest cut
        hint, outcome = d.lookup(list(range(50, 60)))
        assert hint is None and outcome == "miss"
        assert d.hit_rate == 0.0                          # router stamps hits
        assert d.misses == 1

    def test_short_prompt_never_hints(self):
        d = PrefixDirectory()
        assert d.lookup([1, 2, 3]) == (None, "miss")

    def test_eviction_and_drop_replica_clear_entries(self):
        d = PrefixDirectory()
        kv = _mgr(slots=2, pool_blocks=5)                 # tight pool
        d.attach(0, kv)
        _fill(kv)
        p8 = list(range(1, 9))
        slot, _ = kv.alloc("a", p8 + [30], 10)
        kv.advance(slot, 9)
        kv.register_prefix(p8 + [30], slot)
        assert d.snapshot()["entries"] > 0
        kv.release(slot)
        # churn until the LRU eviction fires and the callback drains
        for i in range(3):
            s, _ = kv.alloc("b%d" % i, [40 + i] * 9, 18)
            if s is None:
                break
            kv.advance(s, 9)
            kv.release(s)
        assert d.evictions > 0
        d2 = PrefixDirectory()
        d2.attach(1, _mgr())
        d2.register(1, (1, 2, 3, 4, 5, 6, 7, 8))
        assert d2.snapshot()["entries"] == 1
        d2.drop_replica(1)
        assert d2.snapshot()["entries"] == 0

    def test_ttl_staleness(self):
        clock = [0.0]
        d = PrefixDirectory(ttl=5.0, now=lambda: clock[0])
        d.attach(0, _mgr())                               # fleet block size
        d.register(0, tuple(range(8)))
        hint, outcome = d.lookup(list(range(8)) + [9])
        assert hint == (0, 8) and outcome is None
        clock[0] = 10.0                                   # past the TTL
        hint, outcome = d.lookup(list(range(8)) + [9])
        assert hint is None and outcome == "stale"
        assert d.stale == 1
        # re-registration refreshes the stamp
        d.register(0, tuple(range(8)))
        hint, outcome = d.lookup(list(range(8)) + [9])
        assert hint == (0, 8) and outcome is None

    def test_prefix_hash_stable(self):
        assert prefix_hash([1, 2, 3]) == prefix_hash((1, 2, 3))
        assert prefix_hash([1, 2, 3]) != prefix_hash([1, 2, 4])


# --------------------------------------------------------------------- #
# directory-first routing
# --------------------------------------------------------------------- #

class TestDirectoryRouting:
    def test_warm_wave_hits_and_snapshot_surface(self, model):
        """Wave 1 warms a shared system prompt; wave 2 (different
        sessions) gets directory hits, the hit rate lands in
        ``snapshot()``, and the route events carry the verdicts."""
        router = ServingRouter(_factory(model), replicas=2)
        sys_p = list(range(1, 18))
        w1 = [Request(prompt=sys_p + [20 + i], max_new_tokens=3,
                      session_id=f"a{i}") for i in range(3)]
        res1 = router.run(w1)
        w2 = [Request(prompt=sys_p + [30 + i], max_new_tokens=3,
                      session_id=f"b{i}") for i in range(4)]
        res2 = router.run(w2)
        snap = router.snapshot()
        assert snap["directory"]["hits"] > 0
        assert snap["directory_hit_rate"] > 0
        assert snap["directory_killed"] is False
        # back-compat: the split counter still answers to the old key
        assert snap["prefix_misses"] == snap["affinity_prefix_misses"]
        assert router.prefix_misses == snap["affinity_prefix_misses"]
        routes = [e for e in telemetry.get_sink().recent()
                  if e.get("event") == "router_route"]
        verdicts = {e.get("directory") for e in routes} - {None}
        assert "hit" in verdicts
        for r in w1 + w2:
            got = (res1 if r in w1 else res2)[r.request_id]
            assert got.tokens.tolist() == _offline(model, r)

    def test_directory_off_is_pr8_fleet(self, model):
        """``directory=False`` (or a kill) is exactly the PR 8 fleet:
        no directory in the snapshot, affinity-only routing."""
        router = ServingRouter(_factory(model), replicas=2,
                               directory=False)
        res = router.run([Request(prompt=list(range(1, 18)),
                                  max_new_tokens=3)])
        snap = router.snapshot()
        assert snap["directory"] is None
        assert snap["directory_hit_rate"] is None
        assert len(res) == 1

    def test_chaos_kill_degrades_with_zero_loss(self, model,
                                                monkeypatch, tmp_path):
        """A seeded chaos kill of the DIRECTORY mid-trace: the fleet
        degrades to plain affinity, loses zero requests, stays
        token-identical to offline, and records the kill (failure
        event + flight dump + snapshot flag)."""
        flog = str(tmp_path / "failure.jsonl")
        flt = str(tmp_path / "flight.jsonl")
        monkeypatch.setenv("HETU_FAILURE_LOG", flog)
        monkeypatch.setenv("HETU_FLIGHT_LOG", flt)
        monkeypatch.setenv("HETU_CHAOS", "seed=5,kill=3,role=directory")
        faults.reset_plans()
        router = ServingRouter(_factory(model), replicas=2)
        sys_p = list(range(1, 18))
        reqs = [Request(prompt=sys_p + [50 + i], max_new_tokens=3,
                        session_id=f"c{i}") for i in range(8)]
        res = router.run(reqs)
        snap = router.snapshot()
        assert snap["directory_killed"] is True
        assert snap["directory"] is None
        assert snap["lost"] == 0 and len(res) == 8
        for r in reqs:
            assert res[r.request_id].tokens.tolist() == _offline(model, r)
        events, bad = read_events([flog])
        assert bad == 0
        kills = [e for e in events
                 if e.get("event") == "directory_killed"]
        assert len(kills) == 1 and "reason" in kills[0]
        assert os.path.exists(flt)                        # black box dumped

    def test_roles_validation(self, model):
        with pytest.raises(ValueError):
            ServingRouter(_factory(model), replicas=2, roles="warp")


# --------------------------------------------------------------------- #
# prefill/decode disaggregation (tentpole)
# --------------------------------------------------------------------- #

class TestHandoffRouting:
    def test_roles_handoff_token_identical(self, model):
        """The full disaggregated path: long prompts prefill on the
        prefill-heavy replica, the KV span hands off to a decode-heavy
        home, outputs stay token-identical to offline, events pair,
        and the destination engine carries handoff_ms attribution."""
        router = ServingRouter(_factory(model), replicas=2,
                               roles="prefill,decode")
        assert router.roles == ["prefill", "decode"]
        assert router.replicas[0].kind == "prefill"
        sys_p = list(range(1, 18))
        reqs = [Request(prompt=sys_p + [20 + i], max_new_tokens=4,
                        session_id=f"s{i}") for i in range(6)]
        res = router.run(reqs)
        snap = router.snapshot()
        assert snap["handoffs"] == 6
        assert snap["handoff_failed"] == 0
        assert snap["handoffs_skipped"] == 0    # affinity yields to roles
        assert snap["handoff_bytes"] > 0
        for r in reqs:
            assert res[r.request_id].tokens.tolist() == _offline(model, r)
        ev = telemetry.get_sink().recent()
        outs = [e for e in ev if e.get("event") == "kv_handoff_out"]
        ins = [e for e in ev if e.get("event") == "kv_handoff_in"]
        assert len(outs) == 6 and len(ins) == 6
        assert all(e["replica"] == 0 and e["to_replica"] == 1
                   for e in outs)
        assert check_handoff_balance(ev) == []
        assert check_span_balance(ev) == []
        # both phases route-logged, hop-free
        routes = [e for e in ev if e.get("event") == "router_route"]
        phases = {e.get("phase") for e in routes}
        assert phases == {"prefill", "decode"}
        comp = router.replicas[1].engine.metrics.snapshot()["components"]
        assert comp["handoff_ms"]["p99_ms"] > 0
        # the decode replica admits warm: its pool saw real imports
        assert router.replicas[1].engine.kv.stats()["imports"] == 6

    def test_short_prompts_skip_the_detour(self, model):
        """Prompts at or under one block never disaggregate — the
        detour only pays for itself when a real prefix span moves."""
        router = ServingRouter(_factory(model), replicas=2,
                               roles="prefill,decode")
        res = router.run([Request(prompt=[3, 4, 5], max_new_tokens=3)
                          for _ in range(3)])
        snap = router.snapshot()
        assert snap["handoffs"] == 0 and len(res) == 3

    def test_int8_wire_cheaper_than_auto(self, model):
        """Forcing the int8 wire moves ~4x fewer bytes than the exact
        f32 wire on the same trace (scale planes included)."""
        sys_p = list(range(1, 18))

        def run_one(hq):
            telemetry.reset()
            router = ServingRouter(_factory(model), replicas=2,
                                   roles="prefill,decode",
                                   handoff_quant=hq)
            reqs = [Request(prompt=sys_p + [20 + i], max_new_tokens=3)
                    for i in range(3)]
            res = router.run(reqs)
            assert len(res) == 3
            snap = router.snapshot()
            assert snap["handoffs"] == 3
            return snap["handoff_bytes"]

        exact = run_one("off")
        cheap = run_one("int8")
        # Dh=8 here: (8 + 4) / 32 per value — bigger heads do better
        assert cheap < exact / 2

    def test_mixed_fleet_roles_inactive(self, model):
        """A roles string without both phases never disaggregates."""
        router = ServingRouter(_factory(model), replicas=2,
                               roles="prefill,mixed")
        assert router._roles_active is False
        res = router.run([Request(prompt=list(range(1, 18)),
                                  max_new_tokens=3)])
        assert router.snapshot()["handoffs"] == 0 and len(res) == 1


# --------------------------------------------------------------------- #
# the trace rule (satellite 2)
# --------------------------------------------------------------------- #

class TestHandoffTraceRule:
    def _rec(self, kind, **f):
        return {"t": 1.0, "event": kind, **f}

    def _pair(self, rid="r1"):
        return [self._rec("kv_handoff_out", request=rid, replica=0,
                          to_replica=1),
                self._rec("kv_handoff_in", request=rid, replica=1,
                          from_replica=0)]

    def _finishes(self, rid="r1", n=2):
        return [self._rec("serve_finish", request=rid, reason="length",
                          n_generated=2, replica=i % 2)
                for i in range(n)]

    def test_paired_stream_clean(self):
        assert check_handoff_balance(
            self._pair() + self._finishes()) == []

    def test_out_without_in_flagged(self):
        stream = [self._rec("kv_handoff_out", request="r1", replica=0,
                            to_replica=1)]
        problems = check_handoff_balance(stream)
        assert len(problems) == 1 and "never landed" in problems[0]

    def test_in_without_out_flagged(self):
        stream = [self._rec("kv_handoff_in", request="r1", replica=1,
                            from_replica=0)]
        problems = check_handoff_balance(stream)
        assert len(problems) == 1 and "never exported" in problems[0]

    def test_double_retire_flagged_hop_exempt(self):
        bad = self._pair() + self._finishes(n=3)
        problems = check_handoff_balance(bad)
        assert len(problems) == 1 and "retired 3" in problems[0]
        exempt = bad + [self._rec("router_hop", request="r1",
                                  to_replica=1)]
        assert check_handoff_balance(exempt) == []

    def test_flight_dump_stream_exempt(self):
        stream = [self._rec("flight_dump", reason="x"),
                  self._rec("kv_handoff_out", request="r1", replica=0,
                            to_replica=1)]
        assert check_handoff_balance(stream) == []

    def test_drop_records_not_paired(self):
        stream = [self._rec("kv_handoff_drop", request="r1", replica=0)]
        assert check_handoff_balance(stream) == []

    def test_cli_check_reports_handoff_violations(self, model,
                                                  tmp_path,
                                                  monkeypatch, capsys):
        """``hetu_trace --check`` over a real disaggregated run is
        green and counts handoff violations in the summary."""
        from hetu_tpu.telemetry.trace import main as trace_main
        slog = str(tmp_path / "serve.jsonl")
        monkeypatch.setenv("HETU_SERVE_LOG", slog)
        router = ServingRouter(_factory(model), replicas=2,
                               roles="prefill,decode")
        router.run([Request(prompt=list(range(1, 18)) + [30 + i],
                            max_new_tokens=3) for i in range(2)])
        assert router.snapshot()["handoffs"] == 2
        rc = trace_main([slog, "--check"])
        out = capsys.readouterr().out
        assert rc == 0
        assert '"handoff_violations": 0' in out


# --------------------------------------------------------------------- #
# hetu_top --fleet columns (satellite 3)
# --------------------------------------------------------------------- #

class TestFleetTopKV:
    def test_fleet_rows_carry_role_and_directory(self, model, tmp_path,
                                                 monkeypatch, capsys):
        slog = str(tmp_path / "serve.jsonl")
        monkeypatch.setenv("HETU_SERVE_LOG", slog)
        router = ServingRouter(_factory(model), replicas=2,
                               roles="prefill,decode")
        sys_p = list(range(1, 18))
        router.run([Request(prompt=sys_p + [20 + i], max_new_tokens=3,
                            session_id=f"s{i}") for i in range(4)])
        stats = top.summarize_fleet(read_events([slog])[0])
        rows = {r["replica"]: r for r in stats["replicas"]}
        assert rows[0]["role"] == "prefill"
        assert rows[1]["role"] == "decode"
        assert stats["handoffs"] == 4
        pre = stats["prefix"]
        assert pre["misses"] > 0                  # cold storm, all misses
        rc = top.main([slog, "--fleet", "--once"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "breaker" in out and "requeued" in out
        assert "dir%" in out and "prefix" in out and "handoffs" in out
        assert "prefill" in out and "decode" in out
        assert "\n  0 " in out and "\n  1 " in out

    def test_directory_hit_rate_column(self, model, tmp_path,
                                       monkeypatch):
        slog = str(tmp_path / "serve.jsonl")
        monkeypatch.setenv("HETU_SERVE_LOG", slog)
        router = ServingRouter(_factory(model), replicas=2)
        sys_p = list(range(1, 18))
        router.run([Request(prompt=sys_p + [20], max_new_tokens=3,
                            session_id="a")])
        router.run([Request(prompt=sys_p + [30 + i], max_new_tokens=3,
                            session_id=f"b{i}") for i in range(3)])
        stats = top.summarize_fleet(read_events([slog])[0])
        hit_rates = [r["dir_hit_rate"] for r in stats["replicas"]
                     if r["dir_hit_rate"] is not None]
        assert stats["prefix"]["hits"] > 0
        assert any(h > 0 for h in hit_rates)
