"""Hybrid/PS training through the Executor (the reference's headline
capability: comm_mode routing, optimizer.py:145-164 backward_hook;
ParameterServerCommunicate.py:38-57 push-pull; executor.py:253-258 cache
wiring).  The trajectory contract: at staleness 0 every PS/Hybrid mode
must reproduce the dense single-device run exactly."""

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.ps.server import PSServer
import hetu_tpu.ps.client as psc


def fresh_ps():
    PSServer._instance = None
    psc.PSClient._instance = None


def build_model(optimizer=None):
    ids = ht.placeholder_op("ids")
    y = ht.placeholder_op("y")
    emb = ht.init.random_normal((50, 8), stddev=0.1, name="emb_table")
    emb.is_embed = True
    e = ht.array_reshape_op(ht.embedding_lookup_op(emb, ids), [-1, 16])
    w = ht.init.xavier_uniform((16, 2), name="w")
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(e, w), y), axes=0)
    opt = optimizer or ht.optim.SGDOptimizer(learning_rate=0.1)
    train = opt.minimize(loss)
    return ids, y, loss, train


def make_batches(n=8, batch=16, vocab=50, seed=0, learnable=False):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        a = rng.randint(0, vocab, (batch, 2)).astype(np.int32)
        if learnable:   # label linear in the first id's row: loss can drop
            lab = (a[:, 0] % 2).astype(np.int64)
        else:
            lab = rng.randint(0, 2, batch)
        out.append((a, np.eye(2, dtype=np.float32)[lab]))
    return out


def run_trajectory(executor, ids, y, batches):
    return [float(np.asarray(
        executor.run("train", feed_dict={ids: a, y: c})[0]))
        for a, c in batches]


@pytest.fixture()
def dense_baseline():
    ids, y, loss, train = build_model()
    ex = ht.Executor({"train": [loss, train]})
    w0 = ex.return_tensor_values()
    batches = make_batches()
    base = run_trajectory(ex, ids, y, batches)
    return w0, batches, base


class TestHybridEquivalence:
    @pytest.mark.parametrize("kwargs", [
        dict(comm_mode="Hybrid"),
        dict(comm_mode="Hybrid", cstable_policy="LFUOpt", cache_bound=64),
        dict(comm_mode="Hybrid", cstable_policy="LRU", cache_bound=8),
        dict(comm_mode="Hybrid", async_push=True),
        dict(comm_mode="Hybrid", cstable_policy="LFUOpt", cache_bound=64,
             async_push=True),
        dict(comm_mode="PS"),
        dict(comm_mode="PS", use_sparse_pull=False),
    ], ids=["hybrid", "hybrid+lfuopt", "hybrid+lru-tiny",
            "hybrid+async", "hybrid+lfuopt+async", "ps", "ps-full"])
    def test_trajectory_matches_dense(self, dense_baseline, kwargs):
        w0, batches, base = dense_baseline
        fresh_ps()
        ids, y, loss, train = build_model()
        ex = ht.Executor({"train": [loss, train]}, **kwargs)
        ex.load_dict(w0)
        tr = run_trajectory(ex, ids, y, batches)
        np.testing.assert_allclose(tr, base, atol=1e-5)

    def test_adam_embeddings_via_server(self, dense_baseline):
        """Server-side Adam on sparse grads == device lazy Adam... not
        exactly: server Adam merges rows and keeps a global t; the device
        path is lazy per-row.  The reference has the same split
        (OptimizersSparse.cu vs server/optimizer.h), so assert the hybrid
        run *trains* (loss drops) rather than bitwise parity."""
        fresh_ps()
        ids, y, loss, train = build_model(
            ht.optim.AdamOptimizer(learning_rate=0.05))
        ex = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid")
        batches = make_batches(n=40, learnable=True)
        tr = run_trajectory(ex, ids, y, batches)
        assert np.mean(tr[-5:]) < np.mean(tr[:5]) - 0.02

    def test_hybrid_through_native_van_matches_dense(self, dense_baseline):
        """r5 (VERDICT r4 item 2): with the van autoserving, the
        Executor's hybrid phases A/B reach the C++ tier — the SAME code
        path the throughput bench measures — and the trajectory still
        equals the dense run exactly."""
        from hetu_tpu.ps.van import van_available
        if not van_available():
            pytest.skip("no C++ toolchain")
        w0, batches, base = dense_baseline
        fresh_ps()
        srv = PSServer.get()
        srv.enable_van_autoserve()
        try:
            ids, y, loss, train = build_model()
            ex = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid")
            ex.load_dict(w0)
            tr = run_trajectory(ex, ids, y, batches)
            np.testing.assert_allclose(tr, base, atol=1e-5)
            # the embedding table really is van-served, and the client
            # really opened a fast-tier socket (phase A/B used it)
            assert srv._van_keys, "no table reached the van"
            st = getattr(ex.ps_comm._van_local, "state", None)
            assert st is not None and st["cli"] is not None, \
                "hybrid phases never routed through the van"
        finally:
            srv.shutdown()
            fresh_ps()

    def test_hybrid_van_adam_trains(self):
        """r5: the van now applies the full server-optimizer family —
        an Adam embedding table qualifies for the fast tier and the
        hybrid run still learns."""
        from hetu_tpu.ps.van import van_available
        if not van_available():
            pytest.skip("no C++ toolchain")
        fresh_ps()
        srv = PSServer.get()
        srv.enable_van_autoserve()
        try:
            ids, y, loss, train = build_model(
                ht.optim.AdamOptimizer(learning_rate=0.05))
            ex = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid")
            batches = make_batches(n=40, learnable=True)
            tr = run_trajectory(ex, ids, y, batches)
            assert np.mean(tr[-5:]) < np.mean(tr[:5]) - 0.02
            assert "emb_table" in srv._van_keys   # adam table van-served
        finally:
            srv.shutdown()
            fresh_ps()

    def test_momentum_dense_ps_matches(self):
        """PS mode with Momentum: server-side dense momentum must equal the
        device update exactly."""
        opt = ht.optim.MomentumOptimizer(learning_rate=0.05, momentum=0.9)
        ids, y, loss, train = build_model(opt)
        ex = ht.Executor({"train": [loss, train]})
        w0 = ex.return_tensor_values()
        batches = make_batches()
        base = run_trajectory(ex, ids, y, batches)

        fresh_ps()
        opt = ht.optim.MomentumOptimizer(learning_rate=0.05, momentum=0.9)
        ids, y, loss, train = build_model(opt)
        ex2 = ht.Executor({"train": [loss, train]}, comm_mode="PS")
        ex2.load_dict(w0)
        tr = run_trajectory(ex2, ids, y, batches)
        np.testing.assert_allclose(tr, base, atol=1e-5)


class TestDedupPath:
    def test_heavy_duplication_matches_dense(self):
        """Device-side dedup (unique-row feed + segment-summed grads):
        with a tiny vocab every batch is dominated by duplicate ids, so
        a double-count or dropped duplicate shows up immediately against
        the dense baseline."""
        fresh_ps()
        vocab = 5
        ids, y, loss, train = build_model()
        ex = ht.Executor({"train": [loss, train]})
        w0 = ex.return_tensor_values()
        rng = np.random.RandomState(3)
        batches = [(rng.randint(0, vocab, (16, 2)).astype(np.int32),
                    np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)])
                   for _ in range(6)]
        base = run_trajectory(ex, ids, y, batches)

        fresh_ps()
        ids, y, loss, train = build_model()
        ex2 = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid")
        ex2.load_dict(w0)
        got = run_trajectory(ex2, ids, y, batches)
        np.testing.assert_allclose(got, base, atol=1e-6)


class TestCacheBehavior:
    def test_cache_hit_rate_counted(self):
        fresh_ps()
        ids, y, loss, train = build_model()
        ex = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid",
                         cstable_policy="LFUOpt", cache_bound=64)
        batches = make_batches(n=6)
        run_trajectory(ex, ids, y, batches)
        perf = ex.ps_perf_summary()["emb_table"]
        assert perf["lookups"] == 6
        # vocab 50 fits in 64 lines: after warm-up everything hits
        assert perf["hit_rate"] > 0.3
        assert perf["pushed_rows"] > 0

    def test_tiny_cache_evicts_correctly(self, dense_baseline):
        """Eviction write-back must not lose updates (trajectory already
        covered above; here assert evictions actually happened)."""
        w0, batches, base = dense_baseline
        fresh_ps()
        ids, y, loss, train = build_model()
        ex = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid",
                         cstable_policy="LFU", cache_bound=4)
        ex.load_dict(w0)
        tr = run_trajectory(ex, ids, y, batches)
        np.testing.assert_allclose(tr, base, atol=1e-5)
        assert ex.ps_perf_summary()["emb_table"]["evictions"] > 0

    def test_cache_rejects_non_sgd(self):
        fresh_ps()
        ids, y, loss, train = build_model(
            ht.optim.AdamOptimizer(learning_rate=0.01))
        with pytest.raises(NotImplementedError):
            ht.Executor({"train": [loss, train]}, comm_mode="Hybrid",
                        cstable_policy="LFUOpt")


class TestPrefetch:
    def test_dataloader_prefetch_trajectory(self, dense_baseline):
        """Prefetched (overlapped) lookups must not change the math."""
        w0, batches, base = dense_baseline
        id_data = np.concatenate([a for a, _ in batches])
        y_data = np.concatenate([c for _, c in batches])

        def build_dl():
            dl_ids = ht.dataloader_op([ht.Dataloader(id_data, 16, "train")])
            dl_y = ht.dataloader_op([ht.Dataloader(y_data, 16, "train")])
            emb = ht.init.random_normal((50, 8), stddev=0.1,
                                        name="emb_table")
            emb.is_embed = True
            e = ht.array_reshape_op(
                ht.embedding_lookup_op(emb, dl_ids), [-1, 16])
            w = ht.init.xavier_uniform((16, 2), name="w")
            loss = ht.reduce_mean_op(
                ht.softmaxcrossentropy_op(ht.matmul_op(e, w), dl_y),
                axes=0)
            train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
            return loss, train

        for prefetch in (False, True):
            fresh_ps()
            loss, train = build_dl()
            ex = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid",
                             cstable_policy="LFUOpt", cache_bound=64,
                             prefetch=prefetch)
            ex.load_dict(w0)
            tr = [float(np.asarray(ex.run("train")[0]))
                  for _ in range(len(batches))]
            np.testing.assert_allclose(tr, base, atol=1e-5)


class TestCheckpointAndKnobs:
    def test_checkpoint_roundtrip_with_ps(self, tmp_path, dense_baseline):
        w0, batches, base = dense_baseline
        fresh_ps()
        ids, y, loss, train = build_model()
        ex = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid",
                         cstable_policy="LFUOpt", cache_bound=64)
        ex.load_dict(w0)
        run_trajectory(ex, ids, y, batches[:4])
        ex.save(str(tmp_path), "ckpt.pkl")

        fresh_ps()
        ids, y, loss, train = build_model()
        ex2 = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid",
                          cstable_policy="LFUOpt", cache_bound=64)
        ex2.load(str(tmp_path), "ckpt.pkl")
        tr = run_trajectory(ex2, ids, y, batches[4:])
        np.testing.assert_allclose(tr, base[4:], atol=1e-5)

    def test_bsp_barrier_single_worker(self, dense_baseline):
        w0, batches, base = dense_baseline
        fresh_ps()
        ids, y, loss, train = build_model()
        ex = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid",
                         bsp=0)
        ex.load_dict(w0)
        tr = run_trajectory(ex, ids, y, batches)
        np.testing.assert_allclose(tr, base, atol=1e-5)

    def test_bad_knobs_raise(self):
        ids, y, loss, train = build_model()
        with pytest.raises(ValueError):
            ht.Executor({"train": [loss, train]}, comm_mode="nccl")
        ids, y, loss, train = build_model()
        with pytest.raises(ValueError):
            ht.Executor({"train": [loss, train]},
                        cstable_policy="LFUOpt")  # needs PS/Hybrid
        ids, y, loss, train = build_model()
        with pytest.raises(NotImplementedError):
            ht.Executor({"train": [loss, train]}, use_preduce=True)
        ids, y, loss, train = build_model()
        with pytest.raises(ValueError):
            ht.Executor({"train": [loss, train]}, pipeline="zigzag")
        # pipeline + PS/Hybrid comm is the one unwired combination
        ids, y, loss, train = build_model()
        with pytest.raises(NotImplementedError):
            ht.Executor({"train": [loss, train]}, pipeline="gpipe",
                        comm_mode="Hybrid")

    @staticmethod
    def _shared_table_model():
        ids1 = ht.placeholder_op("ids1")
        ids2 = ht.placeholder_op("ids2")
        y = ht.placeholder_op("y")
        emb = ht.init.random_normal((20, 4), stddev=0.1, name="emb_shared")
        emb.is_embed = True
        e1 = ht.array_reshape_op(ht.embedding_lookup_op(emb, ids1),
                                 [-1, 8])      # ids1: (B, 2) -> (B, 8)
        e2 = ht.array_reshape_op(ht.embedding_lookup_op(emb, ids2),
                                 [-1, 12])     # ids2: (B, 3) -> (B, 12)
        w = ht.init.xavier_uniform((20, 2), name="w")
        h = ht.concat_op(e1, e2, axis=1)
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(ht.matmul_op(h, w), y), axes=0)
        train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
        return ids1, ids2, y, loss, train

    @staticmethod
    def _shared_batches(n=6):
        rng = np.random.RandomState(0)
        return [(rng.randint(0, 20, (8, 2)).astype(np.int32),
                 rng.randint(0, 20, (8, 3)).astype(np.int32),
                 np.eye(2, dtype=np.float32)[rng.randint(0, 2, 8)])
                for _ in range(n)]

    def test_shared_table_two_lookups_on_ps(self):
        """VERDICT r2 item 8: a table consumed by TWO lookups (different
        id shapes, overlapping ids) lives on the PS — the adjoints merge
        sparsely, phase A fetches the union once — and the trajectory
        equals the dense run exactly."""
        batches = self._shared_batches()
        fresh_ps()
        ids1, ids2, y, loss, train = self._shared_table_model()
        ex1 = ht.Executor({"train": [loss, train]})
        w0 = ex1.return_tensor_values()
        base = [float(np.asarray(ex1.run("train", feed_dict={
            ids1: a, ids2: b, y: c})[0])) for a, b, c in batches]

        fresh_ps()
        ids1, ids2, y, loss, train = self._shared_table_model()
        ex2 = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid")
        assert "emb_shared" in ex2.ps_sparse_vars
        assert len(ex2.subexecutor["train"].ps_lookups) == 2
        ex2.load_dict(w0)
        tr = [float(np.asarray(ex2.run("train", feed_dict={
            ids1: a, ids2: b, y: c})[0])) for a, b, c in batches]
        np.testing.assert_allclose(tr, base, atol=1e-5)
        # the PS copy is the trained source of truth
        fresh_ps_val = np.asarray(ex2.ps_comm.pull("emb_shared"))
        assert not np.allclose(fresh_ps_val, w0["emb_shared"])

    def test_shared_table_two_lookups_through_cache(self):
        """Same shared-table model through the HET cache at staleness 0:
        still exact."""
        batches = self._shared_batches()
        fresh_ps()
        ids1, ids2, y, loss, train = self._shared_table_model()
        ex1 = ht.Executor({"train": [loss, train]})
        w0 = ex1.return_tensor_values()
        base = [float(np.asarray(ex1.run("train", feed_dict={
            ids1: a, ids2: b, y: c})[0])) for a, b, c in batches]
        fresh_ps()
        ids1, ids2, y, loss, train = self._shared_table_model()
        ex2 = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid",
                          cstable_policy="lru", cache_bound=20)
        assert "emb_shared" in ex2.cstables
        ex2.load_dict(w0)
        tr = [float(np.asarray(ex2.run("train", feed_dict={
            ids1: a, ids2: b, y: c})[0])) for a, b, c in batches]
        np.testing.assert_allclose(tr, base, atol=1e-5)

    def test_cache_path_scheduled_lr(self, dense_baseline):
        """VERDICT r2 item 8: scheduled-LR SGD on the cache path — each
        push scales by the pushing step's LR, so the trajectory equals
        the dense run with the same schedule."""
        batches = make_batches()
        sched = ht.lr.ExponentialScheduler(0.2, gamma=0.7, step_size=2)
        ids, y, loss, train = build_model(
            ht.optim.SGDOptimizer(learning_rate=sched))
        ex1 = ht.Executor({"train": [loss, train]})
        w0 = ex1.return_tensor_values()
        base = run_trajectory(ex1, ids, y, batches)
        fresh_ps()
        sched2 = ht.lr.ExponentialScheduler(0.2, gamma=0.7, step_size=2)
        ids, y, loss, train = build_model(
            ht.optim.SGDOptimizer(learning_rate=sched2))
        ex2 = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid",
                          cstable_policy="lfu", cache_bound=50)
        ex2.load_dict(w0)
        tr = run_trajectory(ex2, ids, y, batches)
        np.testing.assert_allclose(tr, base, atol=1e-5)

    def test_return_tensor_values_includes_ps_tables(self, dense_baseline):
        w0, batches, base = dense_baseline
        fresh_ps()
        ids, y, loss, train = build_model()
        ex = ht.Executor({"train": [loss, train]}, comm_mode="PS")
        ex.load_dict(w0)
        run_trajectory(ex, ids, y, batches[:2])
        vals = ex.return_tensor_values()
        assert "emb_table" in vals and "w" in vals
        # dense-PS var must be the server's (post-step) value, not the
        # stale device copy
        np.testing.assert_allclose(
            vals["w"], np.asarray(ex.ps_comm.pull("w")), atol=0)

    def test_save_returns_copies_not_views(self):
        """Regression: np.asarray over a donated jax CPU buffer is a view;
        checkpoints and return_tensor_values must deep-copy or they rot
        when the next step reuses the buffer."""
        ids, y, loss, train = build_model()
        ex = ht.Executor({"train": [loss, train]})
        snap = ex.return_tensor_values()
        before = {k: v.copy() for k, v in snap.items()}
        for a, c in make_batches(n=3):
            ex.run("train", feed_dict={ids: a, y: c})
        for k in snap:
            np.testing.assert_array_equal(snap[k], before[k])
