"""Async dataloader prefetch ring (reference: 3-deep pinned ring with
background workers, python/hetu/dataloader.py:30-100).

The ring must (1) preserve the exact batch sequence incl. epoch-seeded
shuffles, (2) overlap host-side batch assembly with the consumer, (3)
hand the executor device-resident (sharded) batches, (4) surface producer
errors, and (5) leave PS-embedding-feeding loaders host-side."""

import time

import numpy as np
import pytest

import jax
import hetu_tpu as ht
from hetu_tpu.dataloader import Dataloader


def _data(n=64, d=4, seed=0):
    return np.random.RandomState(seed).randn(n, d).astype(np.float32)


class TestRing:
    def test_order_identical_to_serial(self):
        X = _data()
        serial = Dataloader(X, 8, "train", shuffle=True, seed=7)
        ringed = Dataloader(X, 8, "train", shuffle=True, seed=7)
        ringed.start_prefetch()
        want = [serial.get_arr() for _ in range(20)]   # 2.5 epochs
        got = [ringed.get_arr() for _ in range(20)]
        for a, b in zip(want, got):
            np.testing.assert_array_equal(a, b)
        ringed.stop_prefetch()

    def test_peek_then_get_consistent(self):
        dl = Dataloader(_data(), 8, "train")
        dl.start_prefetch()
        p = dl.peek_arr()
        g = dl.get_arr()
        np.testing.assert_array_equal(p, g)
        # next batch differs (no shuffle, sequential slices)
        assert not np.array_equal(g, dl.get_arr())
        dl.stop_prefetch()

    def test_overlaps_producer_work(self):
        """With a slow transform (stand-in for host slicing + device_put),
        the ring hides most of the producer latency behind consumer
        compute."""
        delay = 0.01
        X = _data(256)

        def slow(batch):
            time.sleep(delay)
            return batch

        serial = Dataloader(X, 8, "train")
        t0 = time.perf_counter()
        for _ in range(10):
            slow(serial.get_arr())
            time.sleep(delay)          # consumer "compute"
        t_serial = time.perf_counter() - t0

        ringed = Dataloader(X, 8, "train")
        ringed.start_prefetch(transform=slow)
        ringed.peek_arr()              # warm the ring
        t0 = time.perf_counter()
        for _ in range(10):
            ringed.get_arr()
            time.sleep(delay)          # consumer "compute"
        t_ring = time.perf_counter() - t0
        ringed.stop_prefetch()
        # serial pays producer+consumer; ring pays ~max of the two
        assert t_ring < t_serial * 0.8, (t_ring, t_serial)

    def test_producer_error_surfaces(self):
        dl = Dataloader(_data(16), 8, "train")

        def boom(batch):
            raise RuntimeError("producer exploded")

        dl.start_prefetch(transform=boom)
        with pytest.raises(RuntimeError, match="exploded"):
            dl.get_arr()


class TestExecutorIntegration:
    def _build(self):
        X = _data(64, 4, seed=1)
        Y = np.eye(2, dtype=np.float32)[(X[:, 0] > 0).astype(int)]
        dlx = ht.dataloader_op([ht.Dataloader(X, 8, "train")])
        dly = ht.dataloader_op([ht.Dataloader(Y, 8, "train")])
        w = ht.init.xavier_uniform((4, 2), name="pf_w")
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(ht.matmul_op(dlx, w), dly), axes=0)
        train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
        return loss, train

    def test_prefetch_matches_no_prefetch(self):
        loss, train = self._build()
        ex1 = ht.Executor({"train": [loss, train]}, prefetch=False)
        w0 = ex1.return_tensor_values()
        base = [float(np.asarray(ex1.run("train")[0])) for _ in range(12)]

        loss, train = self._build()
        ex2 = ht.Executor({"train": [loss, train]}, prefetch=True)
        ex2.load_dict(w0)
        tr = [float(np.asarray(ex2.run("train")[0])) for _ in range(12)]
        np.testing.assert_allclose(tr, base, atol=1e-6)

    def test_batches_arrive_device_resident(self, monkeypatch):
        """Above the size threshold the ring's transform device_puts with
        the feed sharding, so the loop pops jax.Arrays (H2D off the
        critical path).  (Below it, assembly stays host-only — cheaper
        than the thread contention, measured on the v5e tunnel.)"""
        import hetu_tpu.executor as exe
        monkeypatch.setattr(exe, "_RING_DEVICE_PUT_MIN_BYTES", 0)
        from hetu_tpu.parallel.mesh import make_mesh
        loss, train = self._build()
        mesh = make_mesh({"dp": 8})
        ex = ht.Executor({"train": [loss, train]}, mesh=mesh)
        ex.run("train")
        sub = ex.subexecutor["train"]
        dl_op = sub.dataloader_ops[0]
        loader = dl_op.dataloaders["train"]
        assert loader._ring is not None
        batch = loader.peek_arr()
        assert isinstance(batch, jax.Array)
        assert len(batch.sharding.device_set) == 8

    def test_ps_feeding_loader_stays_host_side(self):
        """Ids consumed by a PS embedding lookup must remain numpy (phase
        A gathers rows host-side from the ids)."""
        from tests.test_hybrid import fresh_ps
        fresh_ps()
        rng = np.random.RandomState(3)
        ids = rng.randint(0, 32, (64, 4)).astype(np.int32)
        Y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 64)]
        dl_ids = ht.dataloader_op([ht.Dataloader(ids, 8, "train")])
        dl_y = ht.dataloader_op([ht.Dataloader(Y, 8, "train")])
        emb = ht.layers.Embedding(32, 8, name="pf_emb")
        h = ht.embedding_lookup_op(emb.embedding_table, dl_ids)
        h = ht.reduce_mean_op(h, [1])
        logits = ht.matmul_op(h, ht.init.xavier_uniform((8, 2),
                                                        name="pf_head"))
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(logits, dl_y), axes=0)
        train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
        ex = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid")
        for _ in range(3):
            out = ex.run("train")
            assert np.isfinite(float(np.asarray(out[0])))
        sub = ex.subexecutor["train"]
        ids_loader = sub.dataloader_ops[0].dataloaders["train"]
        # the ids loader ring has no device_put transform
        if ids_loader._ring is not None:
            assert isinstance(ids_loader.peek_arr(), np.ndarray)


class TestMidEpochResume:
    """Checkpoint captures the dataloader position: a resumed run pops
    the EXACT batch stream the uninterrupted run would have (incl. the
    epoch-seeded reshuffles) — the reference restarts its iterator."""

    def _build(self, tag):
        X = _data(40, 4, seed=21)
        Y = np.eye(2, dtype=np.float32)[(X[:, 0] > 0).astype(int)]
        dlx = ht.dataloader_op([ht.Dataloader(X, 8, "train",
                                              shuffle=True, seed=4)])
        dly = ht.dataloader_op([ht.Dataloader(Y, 8, "train",
                                              shuffle=True, seed=4)])
        w = ht.init.xavier_uniform((4, 2), name=f"mr_w_{tag}")
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(ht.matmul_op(dlx, w), dly), axes=0)
        train = ht.optim.AdamOptimizer(learning_rate=0.05).minimize(loss)
        return loss, train

    def test_resume_continues_batch_stream(self, tmp_path):
        # uninterrupted: 12 steps (2+ epochs of 5 batches, reshuffles)
        loss, train = self._build("a")
        ex = ht.Executor({"train": [loss, train]}, prefetch=False)
        w0 = ex.return_tensor_values()
        full = [float(np.asarray(ex.run("train")[0])) for _ in range(12)]

        # interrupted at step 7, checkpoint, fresh process resumes
        loss, train = self._build("a")
        ex1 = ht.Executor({"train": [loss, train]}, prefetch=False)
        ex1.load_dict(w0)
        part1 = [float(np.asarray(ex1.run("train")[0])) for _ in range(7)]
        ex1.save(str(tmp_path))

        loss, train = self._build("a")
        ex2 = ht.Executor({"train": [loss, train]}, prefetch=False)
        ex2.load(str(tmp_path))
        part2 = [float(np.asarray(ex2.run("train")[0])) for _ in range(5)]
        np.testing.assert_allclose(part1 + part2, full, atol=1e-6)

    def test_resume_with_prefetch_ring(self, tmp_path):
        """The ring prefetches ahead, but _consumed tracks the trainer's
        position, so resume is exact with prefetch on too."""
        loss, train = self._build("b")
        ex = ht.Executor({"train": [loss, train]}, prefetch=False)
        w0 = ex.return_tensor_values()
        full = [float(np.asarray(ex.run("train")[0])) for _ in range(10)]

        loss, train = self._build("b")
        ex1 = ht.Executor({"train": [loss, train]}, prefetch=True)
        ex1.load_dict(w0)
        part1 = [float(np.asarray(ex1.run("train")[0])) for _ in range(6)]
        ex1.save(str(tmp_path))

        loss, train = self._build("b")
        ex2 = ht.Executor({"train": [loss, train]}, prefetch=True)
        ex2.load(str(tmp_path))
        part2 = [float(np.asarray(ex2.run("train")[0])) for _ in range(4)]
        np.testing.assert_allclose(part1 + part2, full, atol=1e-6)


class TestResumeRobustness:
    def test_load_midsession_with_ring_running(self, tmp_path):
        """Executor.load() after training started (prefetch ring live)
        must drain + restart the ring at the restored position, not
        crash."""
        mk = TestMidEpochResume()
        loss, train = mk._build("rb")
        ex = ht.Executor({"train": [loss, train]}, prefetch=True)
        w0 = ex.return_tensor_values()
        full = [float(np.asarray(ex.run("train")[0])) for _ in range(9)]

        loss, train = mk._build("rb")
        ex1 = ht.Executor({"train": [loss, train]}, prefetch=True)
        ex1.load_dict(w0)
        part1 = [float(np.asarray(ex1.run("train")[0])) for _ in range(5)]
        ex1.save(str(tmp_path))
        # keep training past the save, then roll BACK mid-session — the
        # ring is running and ahead of the restored position
        for _ in range(3):
            ex1.run("train")
        ex1.load(str(tmp_path))
        part2 = [float(np.asarray(ex1.run("train")[0])) for _ in range(4)]
        np.testing.assert_allclose(part1 + part2, full, atol=1e-6)

    def test_seed_mismatch_rejected(self):
        dl = Dataloader(_data(32), 8, "t", shuffle=True, seed=4)
        dl.get_arr()
        st = dl.state_dict()
        other = Dataloader(_data(32), 8, "t", shuffle=True, seed=5)
        with pytest.raises(ValueError, match="seed"):
            other.load_state_dict(st)
