"""Tier-1 op unit tests vs numpy (reference tests/test_ops.py covers ~40 ops
this way, test_ops.py:7-80)."""

import numpy as np
import pytest

import hetu_tpu as ht
from tester import HetuTester


def softmax_np(x, axis=-1):
    x = x - np.max(x, axis=axis, keepdims=True)
    e = np.exp(x)
    return e / e.sum(axis=axis, keepdims=True)


# ---------------- elementwise ---------------- #

@pytest.mark.parametrize("factory,np_fn,n", [
    (ht.add_op, lambda a, b: a + b, 2),
    (ht.minus_op, lambda a, b: a - b, 2),
    (ht.mul_op, lambda a, b: a * b, 2),
    (ht.div_op, lambda a, b: a / b, 2),
    (ht.exp_op, np.exp, 1),
    (ht.abs_op, np.abs, 1),
    (ht.sqrt_op, lambda a: np.sqrt(np.abs(a) + 1), 1),
    (ht.sin_op, np.sin, 1),
    (ht.cos_op, np.cos, 1),
    (ht.floor_op, np.floor, 1),
    (ht.opposite_op, lambda a: -a, 1),
    (ht.sigmoid_op, lambda a: 1 / (1 + np.exp(-a)), 1),
    (ht.tanh_op, np.tanh, 1),
    (ht.relu_op, lambda a: np.maximum(a, 0), 1),
])
def test_elementwise(factory, np_fn, n):
    shapes = [(4, 5)] * n
    if factory is ht.sqrt_op:
        t = HetuTester(lambda x: factory(ht.addbyconst_op(ht.abs_op(x), 1)), 1)
        t.test(shapes, np_fn, rtol=1e-5)
    elif factory is ht.div_op:
        t = HetuTester(lambda a, b: factory(a, ht.addbyconst_op(ht.abs_op(b), 1)), 2)
        t.test(shapes, lambda a, b: a / (np.abs(b) + 1), rtol=1e-5)
    else:
        HetuTester(factory, n).test(shapes, np_fn, rtol=1e-4, atol=1e-5)


def test_const_ops():
    HetuTester(ht.addbyconst_op, 1, 3.5).test([(3, 4)], lambda a: a + 3.5)
    HetuTester(ht.mul_byconst_op, 1, -2.0).test([(3, 4)], lambda a: a * -2.0)
    HetuTester(ht.pow_op, 1, 3.0).test([(3, 4)], lambda a: np.power(a, 3.0),
                                       rtol=1e-4, atol=1e-5)
    HetuTester(ht.clamp_op, 1, -0.5, 0.5).test(
        [(3, 4)], lambda a: np.clip(a, -0.5, 0.5))


def test_gelu():
    def gelu_np(x):
        return 0.5 * x * (1 + np.tanh(np.sqrt(2 / np.pi) * (x + 0.044715 * x ** 3)))
    HetuTester(ht.gelu_op, 1).test([(8, 16)], gelu_np, rtol=1e-4, atol=1e-5)


def test_leaky_relu():
    HetuTester(ht.leaky_relu_op, 1, 0.1).test(
        [(5, 5)], lambda a: np.where(a > 0, a, 0.1 * a))


def test_softmax():
    HetuTester(ht.softmax_op, 1).test([(4, 10)], softmax_np, rtol=1e-5)


def test_where():
    t = HetuTester(ht.where_op, 3)
    cond = (np.random.RandomState(0).rand(4, 4) > 0.5).astype(np.float32)
    a = np.random.RandomState(1).randn(4, 4).astype(np.float32)
    b = np.random.RandomState(2).randn(4, 4).astype(np.float32)
    feeds, out, ex = t.build(None)
    (res,) = ex.run("test", feed_dict=dict(zip(feeds, [cond, a, b])),
                    convert_to_numpy_ret_vals=True)
    np.testing.assert_allclose(res, np.where(cond > 0.5, a, b))


# ---------------- matmul ---------------- #

def test_matmul():
    HetuTester(ht.matmul_op, 2).test([(4, 6), (6, 8)], np.matmul, rtol=1e-4)
    HetuTester(ht.matmul_op, 2, True, False).test(
        [(6, 4), (6, 8)], lambda a, b: a.T @ b, rtol=1e-4)
    HetuTester(ht.matmul_op, 2, False, True).test(
        [(4, 6), (8, 6)], lambda a, b: a @ b.T, rtol=1e-4)


def test_batch_matmul():
    HetuTester(ht.batch_matmul_op, 2).test(
        [(3, 4, 5), (3, 5, 6)], np.matmul, rtol=1e-4)


def test_linear():
    HetuTester(ht.linear_op, 3).test(
        [(4, 6), (6, 8), (8,)], lambda a, w, b: a @ w + b, rtol=1e-4)


# ---------------- shape ---------------- #

def test_reshape_transpose():
    HetuTester(ht.array_reshape_op, 1, (2, 12)).test(
        [(4, 6)], lambda a: a.reshape(2, 12))
    HetuTester(ht.transpose_op, 1, (1, 0)).test([(4, 6)], lambda a: a.T)


def test_broadcast_reduce():
    HetuTester(ht.reduce_sum_op, 1, 0).test([(4, 6)], lambda a: a.sum(0),
                                            rtol=1e-5)
    HetuTester(ht.reduce_mean_op, 1, [1], True).test(
        [(4, 6)], lambda a: a.mean(1, keepdims=True), rtol=1e-5)
    HetuTester(ht.broadcast_shape_op, 1, (3, 4, 6)).test(
        [(4, 6)], lambda a: np.broadcast_to(a, (3, 4, 6)))


def test_concat_split():
    HetuTester(ht.concat_op, 2, 1).test(
        [(3, 4), (3, 5)], lambda a, b: np.concatenate([a, b], 1))
    HetuTester(ht.split_op, 1, [1], [1], [2]).test(
        [(4, 6)], lambda a: a[:, 3:])


def test_slice_pad():
    HetuTester(ht.slice_op, 1, (1, 2), (2, 3)).test(
        [(4, 6)], lambda a: a[1:3, 2:5])
    HetuTester(ht.pad_op, 1, [(1, 1), (2, 2)]).test(
        [(3, 3)], lambda a: np.pad(a, [(1, 1), (2, 2)]))


def test_gather_onehot_argmax():
    rng = np.random.RandomState(0)
    idx = rng.randint(0, 4, (5,)).astype(np.float32)
    x = rng.randn(4, 3).astype(np.float32)
    t = HetuTester(ht.indexing_op, 2)
    feeds, out, ex = t.build(None)
    (res,) = ex.run("test", feed_dict=dict(zip(feeds, [x, idx])),
                    convert_to_numpy_ret_vals=True)
    np.testing.assert_allclose(res, x[idx.astype(int)])

    HetuTester(ht.one_hot_op, 1, 10, dtypes=[np.int32]).test(
        [(7,)], lambda a: np.eye(10, dtype=np.float32)[a])
    HetuTester(ht.argmax_op, 1, -1).test(
        [(6, 5)], lambda a: np.argmax(a, -1).astype(np.float32))


def test_cumsum_topk():
    # reference CumSum.py: cumsum(x) + bias — the bias is applied ONCE
    # after the inclusive cumsum (with bias=-1 over a one-hot routing
    # mask this is each token's 0-based arrival slot at its expert)
    HetuTester(ht.cumsum_with_bias_op, 1, -1.0, 0).test(
        [(5, 4)], lambda a: np.cumsum(a, 0) - 1.0, rtol=1e-5)
    x = np.random.RandomState(0).randn(6, 8).astype(np.float32)
    t = HetuTester(ht.topk_val_op, 1, 3)
    feeds, out, ex = t.build(None)
    (res,) = ex.run("test", feed_dict={feeds[0]: x},
                    convert_to_numpy_ret_vals=True)
    np.testing.assert_allclose(res, -np.sort(-x, -1)[:, :3], rtol=1e-6)


# ---------------- losses ---------------- #

def test_softmax_cross_entropy():
    rng = np.random.RandomState(0)
    logits = rng.randn(6, 10).astype(np.float32)
    labels = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 6)]

    def np_fn(x, y):
        p = softmax_np(x)
        return -np.sum(y * np.log(p), -1)
    t = HetuTester(ht.softmaxcrossentropy_op, 2)
    feeds, out, ex = t.build(None)
    (res,) = ex.run("test", feed_dict=dict(zip(feeds, [logits, labels])),
                    convert_to_numpy_ret_vals=True)
    np.testing.assert_allclose(res, np_fn(logits, labels), rtol=1e-5)


def test_softmax_cross_entropy_sparse():
    rng = np.random.RandomState(0)
    logits = rng.randn(6, 10).astype(np.float32)
    labels = rng.randint(0, 10, 6).astype(np.int32)
    labels[2] = -1  # ignored

    t = HetuTester(ht.softmaxcrossentropy_sparse_op, 2, -1)
    feeds, out, ex = t.build(None)
    (res,) = ex.run("test", feed_dict=dict(zip(feeds, [logits, labels])),
                    convert_to_numpy_ret_vals=True)
    p = softmax_np(logits)
    exp = -np.log(p[np.arange(6), np.where(labels < 0, 0, labels)])
    exp[labels < 0] = 0
    np.testing.assert_allclose(res, exp, rtol=1e-5)


def test_bce():
    rng = np.random.RandomState(0)
    p = rng.rand(8).astype(np.float32) * 0.9 + 0.05
    y = (rng.rand(8) > 0.5).astype(np.float32)
    t = HetuTester(ht.binarycrossentropy_op, 2)
    feeds, out, ex = t.build(None)
    (res,) = ex.run("test", feed_dict=dict(zip(feeds, [p, y])),
                    convert_to_numpy_ret_vals=True)
    np.testing.assert_allclose(
        res, -(y * np.log(p) + (1 - y) * np.log(1 - p)), rtol=1e-4)


# ---------------- conv/pool/norm ---------------- #

def _conv2d_np(x, w, stride=1, padding=0):
    n, c, h, ww = x.shape
    o, _, kh, kw = w.shape
    xp = np.pad(x, [(0, 0), (0, 0), (padding, padding), (padding, padding)])
    oh = (h + 2 * padding - kh) // stride + 1
    ow = (ww + 2 * padding - kw) // stride + 1
    out = np.zeros((n, o, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * stride:i * stride + kh, j * stride:j * stride + kw]
            out[:, :, i, j] = np.einsum("nchw,ochw->no", patch, w)
    return out


def test_conv2d():
    HetuTester(ht.conv2d_op, 2, 1, 1).test(
        [(2, 3, 8, 8), (4, 3, 3, 3)],
        lambda x, w: _conv2d_np(x, w, 1, 1), rtol=1e-3, atol=1e-4)


def test_pools():
    x = np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32)

    def maxpool_np(x):
        return x.reshape(2, 3, 4, 2, 4, 2).max((3, 5))

    def avgpool_np(x):
        return x.reshape(2, 3, 4, 2, 4, 2).mean((3, 5))

    for op, ref in [(ht.max_pool2d_op, maxpool_np), (ht.avg_pool2d_op, avgpool_np)]:
        t = HetuTester(op, 1, 2, 2, 0, 2)
        feeds, out, ex = t.build(None)
        (res,) = ex.run("test", feed_dict={feeds[0]: x},
                        convert_to_numpy_ret_vals=True)
        np.testing.assert_allclose(res, ref(x), rtol=1e-5)


def test_layer_norm():
    x = np.random.RandomState(0).randn(4, 16).astype(np.float32)
    scale = np.ones(16, np.float32)
    bias = np.zeros(16, np.float32)
    t = HetuTester(ht.layer_normalization_op, 3, 1e-5)
    feeds, out, ex = t.build(None)
    (res,) = ex.run("test", feed_dict=dict(zip(feeds, [x, scale, bias])),
                    convert_to_numpy_ret_vals=True)
    exp = (x - x.mean(-1, keepdims=True)) / np.sqrt(x.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(res, exp, rtol=1e-4, atol=1e-5)


def test_embedding_lookup():
    rng = np.random.RandomState(0)
    table_np = rng.randn(10, 4).astype(np.float32)
    ids = rng.randint(0, 10, (6,)).astype(np.int32)
    table = ht.Variable("table_emb", value=table_np)
    x = ht.placeholder_op("ids")
    out = ht.embedding_lookup_op(table, x)
    ex = ht.Executor({"test": [out]})
    (res,) = ex.run("test", feed_dict={x: ids}, convert_to_numpy_ret_vals=True)
    np.testing.assert_allclose(res, table_np[ids])


def test_tied_lm_head_xent_chunked_equivalence():
    """tied_lm_head_xent_op == linear_op(trans_B) + sparse xent, through
    training: losses AND trained (table, bias) match the unfused
    composition, including an ignored row and a pad-needing N."""
    import hetu_tpu as ht

    rng = np.random.RandomState(0)
    N, H, V = 48, 16, 37          # N % n_chunks != 0 -> padding path
    hv = rng.randn(N, H).astype(np.float32)
    Wv = (rng.randn(V, H) * 0.1).astype(np.float32)
    bv = (rng.randn(V) * 0.1).astype(np.float32)
    yv = rng.randint(0, V, N).astype(np.int32)
    yv[5] = -1                    # ignored row contributes nothing

    def build(fused):
        h = ht.placeholder_op("h")
        y = ht.placeholder_op("y")
        W = ht.Variable("W", value=Wv.copy())
        b = ht.Variable("b", value=bv.copy())
        if fused:
            vec = ht.tied_lm_head_xent_op(h, W, b, y, n_chunks=16)
        else:
            vec = ht.softmaxcrossentropy_sparse_op(
                ht.linear_op(h, W, b, trans_B=True), y)
        loss = ht.reduce_mean_op(vec, axes=0)
        train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
        ex = ht.Executor({"train": [loss, train]})
        ls = [float(np.asarray(ex.run("train",
                                      feed_dict={h: hv, y: yv})[0]))
              for _ in range(5)]
        return ls, np.asarray(ex.var_values["W"]), \
            np.asarray(ex.var_values["b"])

    l_ref, W_ref, b_ref = build(False)
    l_fus, W_fus, b_fus = build(True)
    np.testing.assert_allclose(l_ref, l_fus, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(W_ref, W_fus, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(b_ref, b_fus, rtol=2e-4, atol=2e-5)


def test_tied_lm_head_xent_chunked_bf16_parity():
    """Under bf16 mixed precision the fused head must track the unfused
    composition closely (both keep bf16 [*, V] blocks; the fused path's
    reductions run in fp32, so it may only be MORE accurate)."""
    import hetu_tpu as ht

    rng = np.random.RandomState(1)
    N, H, V = 64, 16, 29
    hv = rng.randn(N, H).astype(np.float32)
    Wv = (rng.randn(V, H) * 0.2).astype(np.float32)
    bv = (rng.randn(V) * 0.1).astype(np.float32)
    yv = rng.randint(0, V, N).astype(np.int32)

    def build(fused):
        h = ht.placeholder_op("h")
        y = ht.placeholder_op("y")
        W = ht.Variable("W", value=Wv.copy())
        b = ht.Variable("b", value=bv.copy())
        if fused:
            vec = ht.tied_lm_head_xent_op(h, W, b, y, n_chunks=4)
        else:
            vec = ht.softmaxcrossentropy_sparse_op(
                ht.linear_op(h, W, b, trans_B=True), y)
        loss = ht.reduce_mean_op(vec, axes=0)
        train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
        ex = ht.Executor({"train": [loss, train]},
                         mixed_precision="bf16")
        ls = [float(np.asarray(ex.run("train",
                                      feed_dict={h: hv, y: yv})[0]))
              for _ in range(3)]
        return ls, np.asarray(ex.var_values["W"])

    l_ref, W_ref = build(False)
    l_fus, W_fus = build(True)
    # bf16 tolerance: one bf16 ulp on O(1) losses is ~8e-3
    np.testing.assert_allclose(l_ref, l_fus, rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(W_ref, W_fus, rtol=5e-2, atol=5e-3)
