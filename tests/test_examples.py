"""Smoke tests for the example scripts (reference: examples are the
de-facto integration suite; these run the new round-2 ones in-process at
tiny scale)."""

import importlib.util
import os
import sys

import pytest

_EX = os.path.join(os.path.dirname(__file__), "..", "examples")


def _load(relpath, name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_EX, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _run_main(mod, argv):
    old = sys.argv
    sys.argv = ["prog"] + argv
    try:
        return mod.main()
    finally:
        sys.argv = old


def test_finetune_bert_glue_accuracy_improves():
    mod = _load("nlp/finetune_bert_glue.py", "ex_glue")
    acc = _run_main(mod, ["--num-steps", "25", "--num-layers", "1",
                          "--hidden", "64", "--heads", "2",
                          "--batch-size", "32", "--seq-len", "16",
                          "--eval-every", "25"])
    assert acc > 0.52        # above chance on the learnable synthetic task


def test_gcn_example_generalizes_through_graph():
    mod = _load("gnn/train_gcn.py", "ex_gcn")
    acc = _run_main(mod, ["--nodes", "128", "--epochs", "40",
                          "--mesh", "dp2xtp2"])
    assert acc > 0.9         # held-out nodes classified via propagation


def test_gcn_hybrid_example_learns_embeddings_on_ps():
    """run_dist_hybrid.py role: PS-served node embeddings + 1.5-D mesh
    compute; structure is the only signal, so held-out accuracy above
    chance proves the hybrid table actually learned."""
    from hetu_tpu.ps.server import PSServer
    import hetu_tpu.ps.client as psc
    PSServer._instance = None
    psc.PSClient._instance = None
    try:
        mod = _load("gnn/train_gcn_hybrid.py", "ex_gcn_hybrid")
        acc = _run_main(mod, ["--nodes", "128", "--epochs", "150",
                              "--learning-rate", "0.4",
                              "--mesh", "dp2xtp2"])
        assert acc > 0.6     # well above the 0.25 chance level
    finally:
        PSServer._instance = None
        psc.PSClient._instance = None


def test_plan_bert_example_runs():
    mod = _load("nlp/plan_bert.py", "ex_plan")
    _run_main(mod, ["--hidden", "32", "--layers", "2", "--heads", "2",
                    "--seq-len", "16", "--vocab", "100",
                    "--global-batch", "16", "--steps", "1"])


def test_plan_gpt_example_runs():
    mod = _load("nlp/plan_gpt.py", "ex_plan_gpt")
    _run_main(mod, ["--hidden", "32", "--layers", "2", "--heads", "2",
                    "--seq-len", "16", "--vocab", "100",
                    "--global-batch", "16", "--steps", "1"])


def test_transformer_mt_learns():
    mod = _load("nlp/train_transformer.py", "ex_mt")
    acc = _run_main(mod, ["--num-steps", "80", "--log-every", "80"])
    assert acc > 0.05    # chance is ~1/62 on the synthetic MT task


def test_long_context_example_tiny():
    mod = _load("nlp/train_long_context.py", "ex_lc")
    toks = _run_main(mod, ["--seq-len", "256", "--tiny"])
    assert toks > 0


def test_gpt_example_learns():
    """Decoder-only causal LM example trains the synthetic next-token
    task to near-zero loss (the loss value is returned via logging;
    re-run the final loss check in-process instead)."""
    import numpy as np
    import hetu_tpu as ht
    from hetu_tpu.models import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=151, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=2, max_position_embeddings=24,
                    batch_size=4, seq_len=24, dropout_rate=0.0)
    m = GPTForCausalLM(cfg)
    ids = ht.placeholder_op("g_ids")
    labels = ht.placeholder_op("g_labels")
    loss, logits = m(ids, labels=labels)
    train = ht.optim.AdamWOptimizer(learning_rate=3e-3,
                                    weight_decay=0.0).minimize(loss)
    ex = ht.Executor({"train": [loss, train]})
    rng = np.random.RandomState(0)
    first = last = None
    for _ in range(150):
        x = rng.randint(0, 151, (4, 24)).astype(np.int32)
        y = ((3 * x + 7) % 151).astype(np.int32)
        out = ex.run("train", feed_dict={ids: x, labels: y})
        last = float(np.asarray(out[0]))
        first = first if first is not None else last
    assert last < first * 0.5, (first, last)


def test_bert_moe_example_script_runs_on_ep_mesh():
    mod = _load("nlp/train_bert_moe.py", "ex_bert_moe")
    last = _run_main(mod, ["--vocab-size", "97", "--batch-size", "4",
                           "--seq-len", "8", "--num-layers", "2",
                           "--hidden", "32", "--heads", "2",
                           "--num-experts", "4", "--ep", "4", "--dp", "2",
                           "--num-steps", "3"])
    import numpy as np
    assert np.isfinite(last)


def test_gpt_example_script_runs():
    mod = _load("nlp/train_gpt.py", "ex_gpt")
    _run_main(mod, ["--vocab-size", "97", "--batch-size", "2",
                    "--seq-len", "16", "--num-layers", "1",
                    "--num-steps", "3"])


def test_serve_gpt_example_chains_decode():
    """Serving demo: the trained +1 chain decodes correctly through the
    continuous-batching engine for every request in the mixed burst —
    with --spec on (speculative decoding is token-identical by
    construction, so the chain must survive it; the plain engine path
    is pinned by tests/test_serving.py and suite stage 00c)."""
    mod = _load("nlp/serve_gpt.py", "ex_serve")
    frac = _run_main(mod, ["--train-steps", "250", "--requests", "5",
                           "--slots", "2", "--spec", "2"])
    assert frac == 1.0


def test_serve_ctr_example_survives_ps_kill():
    """Embedding serving demo: a zipf CTR trace scores through the
    cache-fronted engine with the PS killed for the middle third —
    every request still scores (stale/zero degradation, zero loss)."""
    mod = _load("ctr/serve_ctr.py", "ex_serve_ctr")
    frac = _run_main(mod, ["--requests", "24", "--wave", "4",
                           "--kill-ps"])
    assert frac == 1.0


def test_gpt_greedy_generation():
    """Inference path: after training next=(x+1)%V, greedy decoding must
    reproduce the arithmetic chain from a prompt (eval subgraph shares
    the trained weights; causal masking makes the padded tail inert)."""
    import numpy as np
    import hetu_tpu as ht
    from hetu_tpu.models import GPTConfig, GPTForCausalLM
    from hetu_tpu.models.gpt import greedy_generate

    cfg = GPTConfig(vocab_size=61, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=2, max_position_embeddings=16,
                    batch_size=4, seq_len=16, dropout_rate=0.0)
    m = GPTForCausalLM(cfg)
    ids = ht.placeholder_op("gg_ids")
    labels = ht.placeholder_op("gg_labels")
    loss, _ = m(ids, labels=labels)
    train = ht.optim.AdamOptimizer(learning_rate=3e-3).minimize(loss)
    gen_ids = ht.placeholder_op("gg_gen_ids")
    logits_gen = m(gen_ids)
    ex = ht.Executor({"train": [loss, train], "gen": [logits_gen]})
    rng = np.random.RandomState(1)
    for _ in range(200):
        iv = rng.randint(0, 61, (4, 16)).astype(np.int32)
        lv = ((iv + 1) % 61).astype(np.int32)
        ex.run("train", feed_dict={ids: iv, labels: lv})
    seq = greedy_generate(ex, "gen", gen_ids, 0, [7, 8, 9], 8, 16)
    assert seq == list(range(7, 18)), seq
