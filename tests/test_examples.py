"""Smoke tests for the example scripts (reference: examples are the
de-facto integration suite; these run the new round-2 ones in-process at
tiny scale)."""

import importlib.util
import os
import sys

import pytest

_EX = os.path.join(os.path.dirname(__file__), "..", "examples")


def _load(relpath, name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_EX, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _run_main(mod, argv):
    old = sys.argv
    sys.argv = ["prog"] + argv
    try:
        return mod.main()
    finally:
        sys.argv = old


def test_finetune_bert_glue_accuracy_improves():
    mod = _load("nlp/finetune_bert_glue.py", "ex_glue")
    acc = _run_main(mod, ["--num-steps", "25", "--num-layers", "1",
                          "--hidden", "64", "--heads", "2",
                          "--batch-size", "32", "--seq-len", "16",
                          "--eval-every", "25"])
    assert acc > 0.52        # above chance on the learnable synthetic task


def test_gcn_example_generalizes_through_graph():
    mod = _load("gnn/train_gcn.py", "ex_gcn")
    acc = _run_main(mod, ["--nodes", "128", "--epochs", "40",
                          "--mesh", "dp2xtp2"])
    assert acc > 0.9         # held-out nodes classified via propagation


def test_plan_bert_example_runs():
    mod = _load("nlp/plan_bert.py", "ex_plan")
    _run_main(mod, ["--hidden", "32", "--layers", "2", "--heads", "2",
                    "--seq-len", "16", "--vocab", "100",
                    "--global-batch", "16", "--steps", "1"])


def test_transformer_mt_learns():
    mod = _load("nlp/train_transformer.py", "ex_mt")
    acc = _run_main(mod, ["--num-steps", "80", "--log-every", "80"])
    assert acc > 0.05    # chance is ~1/62 on the synthetic MT task


def test_long_context_example_tiny():
    mod = _load("nlp/train_long_context.py", "ex_lc")
    toks = _run_main(mod, ["--seq-len", "256", "--tiny"])
    assert toks > 0
