"""KV-cached fast decoding (models/gpt_decode.py): one compiled scan
with a preallocated cache must reproduce (a) the graph executor's
full-forward greedy_generate on a trained model and (b) HuggingFace's
generate() on imported weights."""

import numpy as np
import pytest

import jax.numpy as jnp

import hetu_tpu as ht
from hetu_tpu.models import GPTConfig, GPTForCausalLM
from hetu_tpu.models.gpt import greedy_generate
from hetu_tpu.models.gpt_decode import generate_fast


@pytest.fixture(scope="module")
def trained():
    return _trained_model()


def _trained_model():
    cfg = GPTConfig(vocab_size=61, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=2, max_position_embeddings=16,
                    batch_size=4, seq_len=16, dropout_rate=0.0)
    m = GPTForCausalLM(cfg, name="fd")
    ids = ht.placeholder_op("fd_ids")
    labels = ht.placeholder_op("fd_labels")
    loss, _ = m(ids, labels=labels)
    train = ht.optim.AdamOptimizer(learning_rate=3e-3).minimize(loss)
    gen_ids = ht.placeholder_op("fd_gen_ids")
    logits_gen = m(gen_ids)
    ex = ht.Executor({"train": [loss, train], "gen": [logits_gen]})
    rng = np.random.RandomState(1)
    for _ in range(200):
        iv = rng.randint(0, 61, (4, 16)).astype(np.int32)
        lv = ((iv + 1) % 61).astype(np.int32)
        ex.run("train", feed_dict={ids: iv, labels: lv})
    return cfg, ex, gen_ids


class TestFastDecode:
    def test_matches_graph_greedy_generate(self, trained):
        """Same trained weights: the KV-cached scan and the per-token
        full-forward path must emit the identical greedy sequence."""
        cfg, ex, gen_ids = trained
        slow = greedy_generate(ex, "gen", gen_ids, 0, [7, 8, 9], 8, 16)
        cfg1 = GPTConfig(vocab_size=61, hidden_size=32,
                         num_hidden_layers=2, num_attention_heads=2,
                         max_position_embeddings=16, batch_size=1,
                         seq_len=16, dropout_rate=0.0)
        fast = generate_fast(ex.var_values, cfg1, [7, 8, 9],
                             num_tokens=8)
        assert fast[0].tolist() == slow
        # the trained arithmetic chain actually decoded
        assert slow == list(range(7, 18))

    def test_matches_hf_generate_on_imported_weights(self):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        from transformers import GPT2Config as HFC
        from transformers import GPT2LMHeadModel
        hf_cfg = HFC(vocab_size=97, n_embd=32, n_layer=2, n_head=2,
                     n_positions=24, resid_pdrop=0.0, embd_pdrop=0.0,
                     attn_pdrop=0.0)
        torch.manual_seed(3)
        hf = GPT2LMHeadModel(hf_cfg).eval()
        cfg = GPTConfig(vocab_size=97, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=2,
                        max_position_embeddings=24, batch_size=1,
                        seq_len=24, dropout_rate=0.0)
        params = ht.hf.convert_gpt2(hf.state_dict(),
                                    prefix="transformer.")
        prompt = [5, 11, 17]
        ours = generate_fast(params, cfg, prompt, num_tokens=10)
        with torch.no_grad():
            want = hf.generate(torch.tensor([prompt]),
                               max_new_tokens=10, do_sample=False,
                               pad_token_id=0)
        assert ours[0].tolist() == want[0].tolist()

    def test_sampling_contract(self, trained):
        cfg, ex, _ = trained
        cfg1 = GPTConfig(vocab_size=61, hidden_size=32,
                         num_hidden_layers=2, num_attention_heads=2,
                         max_position_embeddings=16, batch_size=1,
                         seq_len=16, dropout_rate=0.0)
        params = ex.var_values
        a = generate_fast(params, cfg1, [3, 4], num_tokens=6,
                          temperature=0.8, top_k=4, seed=7)
        b = generate_fast(params, cfg1, [3, 4], num_tokens=6,
                          temperature=0.8, top_k=4, seed=7)
        c = generate_fast(params, cfg1, [3, 4], num_tokens=6,
                          temperature=0.8, top_k=4, seed=8)
        np.testing.assert_array_equal(a, b)       # seed-deterministic
        assert a.shape == (1, 8)
        assert a.max() < 61 and a.min() >= 0
        assert (a[0, :2] == [3, 4]).all()         # prompt preserved
        assert not np.array_equal(a, c) or True   # different seed free

    def test_batched_prompts(self, trained):
        cfg, ex, _ = trained
        cfg2 = GPTConfig(vocab_size=61, hidden_size=32,
                         num_hidden_layers=2, num_attention_heads=2,
                         max_position_embeddings=16, batch_size=2,
                         seq_len=16, dropout_rate=0.0)
        out = generate_fast(ex.var_values, cfg2,
                            [[7, 8, 9], [20, 21, 22]],
                            num_tokens=6)
        assert out[0].tolist() == list(range(7, 16))
        assert out[1].tolist() == list(range(20, 29))

    def test_eos_stops_generation(self, trained):
        """eos_id regression: on the trained +1 chain [7,8,9] -> 10,11,
        12,... an eos_id of 12 must emit 10,11,12 then pad the rest of
        the requested span (shape contract unchanged); rows that never
        sample EOS run the full span as before."""
        cfg, ex, _ = trained
        cfg2 = GPTConfig(vocab_size=61, hidden_size=32,
                         num_hidden_layers=2, num_attention_heads=2,
                         max_position_embeddings=16, batch_size=2,
                         seq_len=16, dropout_rate=0.0)
        out = generate_fast(ex.var_values, cfg2,
                            [[7, 8, 9], [20, 21, 22]], num_tokens=6,
                            eos_id=12, pad_id=0)
        # row 0 hits EOS after 3 generated tokens; pad after
        assert out[0].tolist() == [7, 8, 9, 10, 11, 12, 0, 0, 0]
        # row 1 never samples 12 inside its span: untouched
        assert out[1].tolist() == list(range(20, 29))
        # eos only triggers PAST the prompt: a 12 inside the prompt is
        # teacher-forced context, not a stop
        out2 = generate_fast(ex.var_values, cfg2,
                             [[11, 12, 13], [30, 31, 32]], num_tokens=4,
                             eos_id=12, pad_id=0)
        assert out2[0].tolist() == [11, 12, 13, 14, 15, 16, 17]
        # custom pad_id lands in the padded tail
        out3 = generate_fast(ex.var_values, cfg2,
                             [[7, 8, 9], [7, 8, 9]], num_tokens=6,
                             eos_id=10, pad_id=59)
        assert out3[0].tolist() == [7, 8, 9, 10, 59, 59, 59, 59, 59]

    def test_overlong_request_raises(self, trained):
        cfg, ex, _ = trained
        with pytest.raises(ValueError):
            generate_fast(ex.var_values, cfg, [1, 2], num_tokens=100)
        with pytest.raises(ValueError):
            generate_fast(ex.var_values, cfg, [], num_tokens=4)
        with pytest.raises(ValueError):
            generate_fast(ex.var_values, cfg, [1, 2], num_tokens=0)


class TestTensorParallelDecode:
    """Multi-chip serving: tp_shard_params places the weights Megatron-
    style and GSPMD propagates the split through the whole decode scan —
    the sharded run must emit the identical greedy sequence."""

    def test_tp4_matches_unsharded(self):
        from hetu_tpu.models.gpt_decode import tp_shard_params
        from hetu_tpu.parallel.mesh import make_mesh
        cfg = GPTConfig(vocab_size=61, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=4,
                        max_position_embeddings=16, batch_size=4,
                        seq_len=16, dropout_rate=0.0)
        m = GPTForCausalLM(cfg, name="tq")
        ids = ht.placeholder_op("tq_ids")
        labels = ht.placeholder_op("tq_labels")
        loss, _ = m(ids, labels=labels)
        train = ht.optim.AdamOptimizer(learning_rate=3e-3).minimize(loss)
        ex = ht.Executor({"train": [loss, train]})
        rng = np.random.RandomState(1)
        for _ in range(150):
            iv = rng.randint(0, 61, (4, 16)).astype(np.int32)
            ex.run("train", feed_dict={
                ids: iv, labels: ((iv + 1) % 61).astype(np.int32)})
        base = generate_fast(ex.var_values, cfg, [7, 8, 9],
                             num_tokens=6)
        mesh = make_mesh({"tp": 4})
        sharded = tp_shard_params(ex.var_values, mesh, cfg)
        # the placed weights really are split over tp
        w = sharded["tq_h0_attn_q_weight"]
        assert {s.data.shape for s in w.addressable_shards} == {(32, 8)}
        out = generate_fast(sharded, cfg, [7, 8, 9], num_tokens=6)
        assert out[0].tolist() == base[0].tolist()
        assert out[0].tolist() == list(range(7, 16))

    def test_head_divisibility_guard(self):
        from hetu_tpu.models.gpt_decode import tp_shard_params
        from hetu_tpu.parallel.mesh import make_mesh
        cfg = GPTConfig(vocab_size=61, hidden_size=30,
                        num_hidden_layers=1, num_attention_heads=3,
                        max_position_embeddings=8, batch_size=1,
                        seq_len=8, dropout_rate=0.0)
        mesh = make_mesh({"tp": 4})
        with pytest.raises(ValueError):
            tp_shard_params({"g_wte_table": np.zeros((61, 30))},
                            mesh, cfg)


def test_prep_param_preserves_sharding():
    """Regression pin for the silent-TP-kill bug: a NamedSharding placed
    by tp_shard_params must SURVIVE generate_fast's param prep (an
    np.asarray round-trip would re-place it replicated)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from hetu_tpu.models.gpt_decode import _prep_param
    from hetu_tpu.parallel.mesh import make_mesh
    mesh = make_mesh({"tp": 4})
    arr = jax.device_put(np.ones((8, 16), np.float32),
                         NamedSharding(mesh, P(None, "tp")))
    out = _prep_param(arr)
    assert out is arr                       # untouched, placement intact
    assert isinstance(out.sharding, NamedSharding)
    assert out.sharding.spec == P(None, "tp")
    # non-jax inputs still land as f32 jax arrays
    out2 = _prep_param(np.ones((4,), np.float64))
    assert out2.dtype == jnp.float32


def test_bf16_decode_matches_f32_greedy(trained):
    """dtype=bfloat16 halves weights + KV cache (LN statistics stay
    f32); on the near-deterministic trained chain the greedy sequence
    is unchanged — the f32 sequence is already pinned to the same
    literal by test_matches_graph_greedy_generate."""
    cfg, ex, _ = trained
    cfg1 = GPTConfig(vocab_size=61, hidden_size=32,
                     num_hidden_layers=2, num_attention_heads=2,
                     max_position_embeddings=16, batch_size=1,
                     seq_len=16, dropout_rate=0.0)
    bf16 = generate_fast(ex.var_values, cfg1, [7, 8, 9], num_tokens=6,
                         dtype=jnp.bfloat16)
    assert bf16[0].tolist() == list(range(7, 16))
