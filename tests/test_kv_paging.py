"""Block-table paged KV cache: the block-pool allocator (free list,
refcounts, copy-on-write prefix sharing, LRU eviction), the block-table
decode kernel against the contiguous oracle, chunked prefill, and the
paged engine's end-to-end greedy parity with the contiguous reference —
each contract pinned separately.

The load-bearing claims:
- allocator: blocks free only at refcount zero; a shared prefix is
  stored ONCE; a mid-block shared tail is COW-forked; exhaustion is
  backpressure (requeue/QueueFull), never corruption;
- kernel: ``paged_block_decode_attention`` over an arbitrarily permuted
  block pool equals the contiguous masked oracle;
- engine: greedy outputs are token-identical across contiguous vs paged,
  shared vs unshared prefix, chunked vs whole prefill, fast vs masked —
  and to offline ``generate_fast``.

Everything runs on the CPU harness (kernels in interpret mode) —
``smoke`` tier.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import hetu_tpu as ht  # noqa: F401  (platform forcing + compat shims)
from hetu_tpu import telemetry
from hetu_tpu.kernels.decode_attention import (
    masked_decode_reference, paged_block_decode_attention,
    paged_block_decode_reference,
)
from hetu_tpu.models import GPTConfig
from hetu_tpu.models.gpt_decode import generate_fast
from hetu_tpu.serving import (
    KVCacheManager, PagedKVManager, QueueFull, Request, ServingEngine,
    resolve_kv_block,
)


def _rand_gpt(name="pg", L=2, H=2, Dh=8, V=61, S=32, seed=0):
    """Deterministic random params in generate_fast's naming contract
    (mirrors test_serving's helper; kept local so the files stay
    independently runnable)."""
    rng = np.random.RandomState(seed)
    hd = H * Dh
    p = {f"{name}_wte_table": rng.randn(V, hd) * 0.05,
         f"{name}_wpe": rng.randn(S, hd) * 0.05,
         f"{name}_ln_f_scale": np.ones(hd),
         f"{name}_ln_f_bias": np.zeros(hd)}
    for i in range(L):
        us = f"{name}_h{i}"
        for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                       ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                       ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
            p[f"{us}_{w}_weight"] = rng.randn(*shp) * 0.05
            p[f"{us}_{w}_bias"] = np.zeros(shp[1])
        for ln in ("ln1", "ln2"):
            p[f"{us}_{ln}_scale"] = np.ones(hd)
            p[f"{us}_{ln}_bias"] = np.zeros(hd)
    cfg = GPTConfig(vocab_size=V, hidden_size=hd, num_hidden_layers=L,
                    num_attention_heads=H, max_position_embeddings=S,
                    batch_size=1, seq_len=S, dropout_rate=0.0)
    return p, cfg


@pytest.fixture(scope="module")
def model():
    return _rand_gpt()


def _mgr(**kw):
    base = dict(layers=1, heads=1, head_dim=4, slots=2, max_seq_len=32,
                block=8)
    base.update(kw)
    return PagedKVManager(**base)


@pytest.mark.smoke
class TestPagedAllocator:
    def test_alloc_release_refcount_cycle(self):
        m = _mgr(prefix_share=False)
        assert m.table_width == 4 and m.n_blocks == 2 * 4 + 1
        cap0 = m.free_blocks
        slot, cached = m.alloc("r0", [1, 2, 3], 3 + 9)     # 2 blocks
        assert slot is not None and cached == 0
        assert m.free_blocks == cap0 - 2
        assert all(m.ref[int(b)] == 1 for b in m.tables[slot, :2])
        assert int(m.n_table[slot]) == 2
        m.advance(slot, 3)
        assert m.lengths[slot] == 3
        m.release(slot)
        assert m.free_blocks == cap0 and m.owner[slot] is None
        with pytest.raises(ValueError):
            m.release(slot)                                # double free
        with pytest.raises(ValueError):
            m.alloc("r1", [1], 99)                         # > S_max

    def test_scratch_block_never_allocated(self):
        m = _mgr(prefix_share=False)
        seen = set()
        while True:
            slot, _ = m.alloc("r", [1] * 8, 32)
            if slot is None:
                break
            seen.update(int(b) for b in m.tables[slot, :4])
        assert 0 not in seen

    def test_prefix_share_stores_blocks_once(self):
        m = _mgr(prefix_share=True)
        p16 = list(range(1, 17))                           # block-aligned
        slot, cached = m.alloc("a", p16 + [40], 20)
        assert cached == 0
        m.register_prefix(p16 + [40], slot)
        free_before = m.free_blocks
        slot2, cached2 = m.alloc("b", p16 + [41], 20)
        # 16 shared tokens = 2 full blocks attached, NOT recomputed:
        # only the private remainder (1 block for tokens 17..20) is new
        assert cached2 == 16
        assert m.free_blocks == free_before - 1
        assert m.prefix_hits == 1
        # shared blocks are the same physical ids
        assert list(m.tables[slot, :2]) == list(m.tables[slot2, :2])
        # retiring the ORIGINAL leaves the shared blocks resident
        m.release(slot)
        assert all(m.ref[int(b)] > 0 for b in m.tables[slot2, :2])

    def test_cow_fork_on_midblock_tail(self):
        m = _mgr(prefix_share=True)
        p17 = list(range(1, 18))                           # 17 = 2*8 + 1
        slot, _ = m.alloc("a", p17, 20)
        m.register_prefix(p17, slot)
        slot2, cached2 = m.alloc("b", p17 + [50, 51], 24)
        assert cached2 == 17
        assert m.cow_copies == 1
        # full blocks shared, the straddle block forked private
        assert list(m.tables[slot, :2]) == list(m.tables[slot2, :2])
        assert int(m.tables[slot, 2]) != int(m.tables[slot2, 2])
        assert m.ref[int(m.tables[slot2, 2])] == 1

    def test_exhaustion_and_lru_eviction(self):
        m = _mgr(slots=4, pool_blocks=5, prefix_share=True)  # 4 usable
        p8 = list(range(1, 9))
        slot, _ = m.alloc("a", p8, 16)                     # 2 blocks
        m.register_prefix(p8, slot)
        m.release(slot)                   # cache still holds 1 block
        assert m.free_blocks == 3
        # a full-pool request forces the registered prefix out
        slot2, _ = m.alloc("b", [9] * 8, 32)               # 4 blocks
        assert slot2 is not None and m.evictions >= 1
        assert not m._prefix
        # now truly exhausted: next alloc must refuse, not corrupt
        assert m.alloc("c", [1], 8) == (None, 0)
        m.release(slot2)
        assert m.alloc("c", [1], 8)[0] is not None

    def test_full_prompt_reuse_recomputes_last_position(self):
        """An identical full prompt hits the cache but keeps its final
        position to recompute — sampling needs the logits there."""
        m = _mgr(prefix_share=True)
        p10 = list(range(1, 11))
        slot, _ = m.alloc("a", p10, 16)
        m.register_prefix(p10, slot)
        _, cached = m.alloc("b", p10, 16)
        assert cached < len(p10)


@pytest.mark.smoke
class TestBucketPromptPosCap:
    def test_bucket_clamped_to_pos_cap(self):
        """Regression: pow2 bucketing must never pad a prompt past the
        position-table cap when s_max was capped to a non-pow2 size."""
        m = KVCacheManager(layers=1, heads=1, head_dim=4, slots=2,
                           max_seq_len=20, pos_cap=24)
        assert m.s_max == 24                  # capped, non-pow2
        assert m.bucket_prompt(17) <= 24      # pow2 round-up alone -> 32
        assert m.bucket_prompt(3) == 8
        pm = PagedKVManager(layers=1, heads=1, head_dim=4, slots=2,
                            max_seq_len=20, pos_cap=24, block=8)
        assert pm.bucket_prompt(17) <= 24
        assert pm.bucket_prompt(23) <= 24

    def test_resolve_kv_block(self, monkeypatch):
        assert resolve_kv_block(False) == 0
        assert resolve_kv_block(True) > 0
        assert resolve_kv_block(None, 8) == 8
        monkeypatch.setenv("HETU_KV_BLOCK", "32")
        assert resolve_kv_block(None) == 32
        monkeypatch.setenv("HETU_KV_BLOCK", "0")
        assert resolve_kv_block(None) == 0
        assert resolve_kv_block(True) == 16   # paged forced: 0 invalid
        monkeypatch.setenv("HETU_KV_BLOCK", "auto")
        want = 16 if jax.default_backend() == "tpu" else 0
        assert resolve_kv_block(None) == want


@pytest.mark.smoke
class TestBlockTableKernel:
    def _permuted_pool(self, B, S, H, Dh, bs, seed=0, dtype=jnp.float32):
        """A logical [B, S] cache scattered into a permuted block pool:
        the kernel must reassemble it through the tables."""
        rng = np.random.RandomState(seed)
        T = S // bs
        N = B * T + 1
        perm = rng.permutation(N - 1)[:B * T] + 1
        tables = perm.reshape(B, T)
        k_log = rng.randn(B, S, H, Dh).astype(np.float32)
        v_log = rng.randn(B, S, H, Dh).astype(np.float32)
        pool_k = np.zeros((N, bs, H, Dh), np.float32)
        pool_v = np.zeros((N, bs, H, Dh), np.float32)
        for b in range(B):
            for j in range(T):
                pool_k[tables[b, j]] = k_log[b, j * bs:(j + 1) * bs]
                pool_v[tables[b, j]] = v_log[b, j * bs:(j + 1) * bs]
        q = jnp.asarray(rng.randn(B, H, Dh), dtype)
        return (q, jnp.asarray(pool_k, dtype), jnp.asarray(pool_v, dtype),
                jnp.asarray(tables, jnp.int32),
                jnp.asarray(k_log), jnp.asarray(v_log))

    def test_parity_contiguous_vs_block_table(self):
        B, S, H, Dh, bs = 4, 64, 2, 8, 16
        q, pk, pv, tables, k_log, v_log = self._permuted_pool(
            B, S, H, Dh, bs)
        for lens in ([1, 17, 33, 64], [16, 16, 5, 48]):
            lens = jnp.asarray(lens, jnp.int32)
            got = paged_block_decode_attention(q, pk, pv, lens, tables)
            want = masked_decode_reference(q, k_log, v_log, lens)
            ref = paged_block_decode_reference(q, pk, pv, lens, tables)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-5, atol=1e-5)
            np.testing.assert_allclose(np.asarray(ref), np.asarray(want),
                                       rtol=1e-5, atol=1e-5)

    def test_zero_length_slot_returns_zeros(self):
        B, S, H, Dh, bs = 2, 32, 2, 8, 8
        q, pk, pv, tables, k_log, v_log = self._permuted_pool(
            B, S, H, Dh, bs, seed=3)
        lens = jnp.asarray([0, 9], jnp.int32)
        got = np.asarray(paged_block_decode_attention(q, pk, pv, lens,
                                                      tables))
        assert np.all(got[0] == 0.0) and np.all(np.isfinite(got))
        want = masked_decode_reference(q, k_log, v_log, lens)
        np.testing.assert_allclose(got, np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_bf16_accumulates_f32(self):
        B, S, H, Dh, bs = 4, 64, 2, 8, 16
        q, pk, pv, tables, k_log, v_log = self._permuted_pool(
            B, S, H, Dh, bs, seed=5, dtype=jnp.bfloat16)
        lens = jnp.asarray([3, 17, 40, 64], jnp.int32)
        got = paged_block_decode_attention(q, pk, pv, lens, tables)
        assert got.dtype == jnp.bfloat16
        want = masked_decode_reference(
            q.astype(jnp.float32), k_log, v_log, lens)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want), rtol=0.06, atol=0.06)

    def test_under_jit(self):
        B, S, H, Dh, bs = 2, 32, 2, 8, 8
        q, pk, pv, tables, k_log, v_log = self._permuted_pool(
            B, S, H, Dh, bs, seed=7)
        lens = jnp.asarray([5, 30], jnp.int32)
        got = jax.jit(paged_block_decode_attention)(q, pk, pv, lens,
                                                    tables)
        want = masked_decode_reference(q, k_log, v_log, lens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


TRACE = [([7, 8, 9], 6), ([3, 4], 11), ([1, 2, 3, 4, 5], 4),
         ([11], 7), ([20, 21, 22, 23], 9), ([40], 3)]


def _run(p, cfg, trace, **kw):
    eng = ServingEngine(p, cfg, queue_limit=32, **kw)
    reqs = [Request(prompt=pr, max_new_tokens=n) for pr, n in trace]
    res = eng.run(reqs)
    return eng, {tuple(r.prompt): res[r.request_id].tokens.tolist()
                 for r in reqs}


@pytest.mark.smoke
class TestPagedEngineParity:
    def test_greedy_identical_to_contiguous_and_offline(self, model):
        """Acceptance: mixed-length greedy trace, paged == contiguous ==
        offline, token for token — across block sizes, slot counts, and
        both attention paths."""
        p, cfg = model
        _, ref = _run(p, cfg, TRACE, slots=4, paged=False)
        for kw in (dict(kv_block=16), dict(kv_block=8),
                   dict(kv_block=8, slots=2),
                   dict(kv_block=8, fast_path=True),
                   dict(kv_block=8, fast_path=False)):
            eng, got = _run(p, cfg, TRACE, slots=kw.pop("slots", 4),
                            paged=True, **kw)
            assert eng.paged and got == ref, kw
        for pr, n in TRACE:
            want = generate_fast(p, cfg, [pr], num_tokens=n,
                                 prefill="scan")[0]
            assert ref[tuple(pr)] == want.tolist()

    def test_shared_vs_unshared_prefix_identical(self, model):
        """Prefix sharing is a MEMORY optimization: greedy outputs are
        bit-identical with it on or off, while the shared run stores
        the common blocks once (and COW-forks the straddle)."""
        p, cfg = model
        sysp = list(np.arange(1, 18) % 60)        # 17 tokens: straddle
        trace = [(sysp + [30 + i], 6) for i in range(4)]
        trace.append((sysp + [30, 31, 32], 5))    # extends a full prompt
        eng_s, shared = _run(p, cfg, trace, slots=4, paged=True,
                             kv_block=8, prefix_share=True)
        eng_u, unshared = _run(p, cfg, trace, slots=4, paged=True,
                               kv_block=8, prefix_share=False)
        assert shared == unshared
        st = eng_s.kv.stats()
        assert st["prefix_hits"] >= 3, st
        assert st["cow_copies"] >= 1, st
        assert eng_u.kv.stats()["prefix_hits"] == 0

    def test_chunked_vs_whole_prefill_identical(self, model):
        p, cfg = model
        trace = [(list(range(1, 20)), 5), ([3, 4], 6),
                 (list(range(5, 29)), 4)]
        _, whole = _run(p, cfg, trace, slots=4, paged=True, kv_block=8,
                        prefill_chunk=0)
        for chunk in (4, 8, 16):
            eng, got = _run(p, cfg, trace, slots=4, paged=True,
                            kv_block=8, prefill_chunk=chunk)
            assert got == whole, chunk
            assert eng.prefill_chunks >= sum(
                -(-len(pr) // chunk) for pr, _ in trace) - 1

    def test_chunked_prefill_interleaves_with_decode(self, model):
        """A long prompt filling chunk by chunk must NOT stall running
        generations: short requests keep producing tokens while the
        straggler's prompt is still being written."""
        p, cfg = model
        long_prompt = list(range(1, 25))          # 24 tokens, chunk 4
        eng = ServingEngine(p, cfg, slots=4, queue_limit=16, paged=True,
                            kv_block=8, prefill_chunk=4,
                            prefix_share=False)
        short = Request(prompt=[7, 8], max_new_tokens=8)
        eng.submit(short)
        eng.step()                                # short is decoding
        lng = Request(prompt=long_prompt, max_new_tokens=3)
        eng.submit(lng)
        eng.step()                                # one chunk + decode
        slot = [s for s in eng.kv.live()
                if eng._reqs[s] is lng][0]
        assert eng._gen[slot] is None             # still prefilling...
        assert len(eng._gen[[s for s in eng.kv.live()
                             if eng._reqs[s] is short][0]]) >= 2
        out = eng.run()                           # ...and both finish
        assert len(out) == 2
        want = generate_fast(p, cfg, [long_prompt], num_tokens=3)[0]
        assert out[lng.request_id].tokens.tolist() == want.tolist()

    def test_bf16_and_sampling_compose(self, model):
        p, cfg = model
        _, ref = _run(p, cfg, TRACE, slots=4, paged=False,
                      dtype=jnp.bfloat16)
        _, got = _run(p, cfg, TRACE, slots=4, paged=True, kv_block=8,
                      dtype=jnp.bfloat16)
        assert got == ref
        # per-request rng streams survive the paged scheduler: sampled
        # outputs identical across layouts
        reqs = lambda: [Request(prompt=[3, 4], max_new_tokens=6,
                                temperature=0.9, top_k=5, seed=11),
                        Request(prompt=[7, 8, 9], max_new_tokens=5,
                                temperature=0.7, top_k=3, seed=22)]
        a = ServingEngine(p, cfg, slots=2, paged=False).run(reqs())
        b = ServingEngine(p, cfg, slots=2, paged=True,
                          kv_block=8).run(reqs())
        assert sorted(r.tokens.tolist() for r in a.values()) == \
            sorted(r.tokens.tolist() for r in b.values())


@pytest.mark.smoke
class TestPoolBackpressure:
    def test_exhaustion_queuefull_then_drain(self, model):
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=4, queue_limit=2, paged=True,
                            kv_block=8, pool_blocks=4,
                            prefix_share=False)       # 3 usable blocks
        eng.submit(Request(prompt=list(range(1, 11)), max_new_tokens=12))
        eng.submit(Request(prompt=[5] * 9, max_new_tokens=10))
        with pytest.raises(QueueFull):
            eng.submit(Request(prompt=[6] * 9, max_new_tokens=10))
        assert eng.metrics.rejected == 1
        # a request that can NEVER fit the pool is rejected outright
        with pytest.raises(ValueError):
            eng.submit(Request(prompt=[1] * 20, max_new_tokens=12))
        out = eng.run()
        assert len(out) == 2 and eng.metrics.finished == 2
        assert eng.kv.free_blocks == eng.kv.capacity_blocks

    def test_more_slots_than_contiguous_at_equal_bytes(self, model):
        """The capacity claim, engine-level: at a pool sized to the
        CONTIGUOUS layout's bytes, the paged engine holds every short
        request concurrently while contiguous is capped at its slot
        count."""
        p, cfg = model
        sysp = list(np.arange(1, 10) % 60)        # 9 shared tokens
        trace = [(sysp + [20 + i], 4) for i in range(8)]
        # contiguous: 2 slots x S_max=32 tokens = 64 token-slots
        eng_c, ref = _run(p, cfg, trace, slots=2, paged=False)
        # paged, same bytes: 64 tokens / block 8 = 8 blocks (+ scratch)
        eng_p, got = _run(p, cfg, trace, slots=16, paged=True,
                          kv_block=8, pool_blocks=9)
        assert got == ref
        assert eng_c.peak_live <= 2
        assert eng_p.peak_live >= 2 * eng_c.peak_live


@pytest.mark.smoke
class TestPagedTelemetry:
    def test_pool_metrics_and_kv_alloc_span(self, model, tmp_path,
                                            monkeypatch):
        import json
        p, cfg = model
        tlog = str(tmp_path / "telemetry.jsonl")
        monkeypatch.setenv("HETU_TELEMETRY_LOG", tlog)
        telemetry.get_sink()  # sink re-reads env per emit; just ensure up
        sysp = list(np.arange(1, 18) % 60)
        log = str(tmp_path / "serve.jsonl")
        eng = ServingEngine(p, cfg, slots=4, queue_limit=16, paged=True,
                            kv_block=8, prefill_chunk=4, log_path=log)
        eng.run([Request(prompt=sysp + [30 + i], max_new_tokens=4)
                 for i in range(3)])
        snap = telemetry.snapshot()
        assert snap["gauges"].get("serve.blocks_free") is not None
        assert snap["gauges"].get("serve.blocks_shared") is not None
        assert snap["counters"].get("serve.prefill_chunks", 0) >= 1
        assert "span.serve.kv_alloc" in snap["histograms"]
        # the span records land in the merged stream for --export
        with open(tlog) as f:
            recs = [json.loads(line) for line in f]
        spans = [r for r in recs if r.get("event") == "span"
                 and r.get("name") == "serve.kv_alloc"]
        assert spans, "kv_alloc span missing from merged telemetry log"
        # serve-stream records stay contract-conforming on the paged path
        with open(log) as f:
            serve = [json.loads(line) for line in f]
        assert serve
        for r in serve:
            assert telemetry.validate_record(r) == [], r
