"""Auto-parallel planner tests (Galvatron-equivalent, SURVEY.md §2.6).

Covers: strategy enumeration, memory/time cost model monotonicity, the
knapsack DP (optimality on a hand-checkable instance + memory-pressure
behavior), end-to-end search on a transformer stack, and applying a plan
to an Executor on the 8-device CPU mesh.
"""

import os

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.planner import (AutoParallel, ClusterSpec, DPAlg, LayerSpec,
                              MemoryCostModel, ParallelStrategy,
                              PlannerSearch, TimeCostModel,
                              candidate_strategies, pipeline_division_even,
                              plan_to_json)


def _cluster(**kw):
    kw.setdefault("n_devices", 8)
    return ClusterSpec(**kw)


class TestStrategyEnumeration:
    def test_covers_dp_tp_pp_corners(self):
        cands = {str(s) for s in candidate_strategies(8)}
        # the reference's 8-GPU baselines (dp_utils.py:41-46)
        assert "1-1-8" in cands       # pure DP
        assert "1-8-1" in cands       # pure TP
        assert "8-1-1" in cands       # pure PP
        assert "1-1-8f" in cands      # DP + fsdp

    def test_device_count_conserved(self):
        for n in (1, 2, 4, 8, 16):
            for s in candidate_strategies(n):
                assert s.n_devices == n

    def test_flags_restrict_space(self):
        no_fsdp = candidate_strategies(8, allow_fsdp=False)
        assert all(not s.fsdp for s in no_fsdp)
        no_cp = candidate_strategies(8, allow_cp=False)
        assert all(s.cp == 1 for s in no_cp)
        tp_capped = candidate_strategies(8, max_tp=2)
        assert all(s.tp <= 2 for s in tp_capped)


class TestCostModels:
    LAYER = LayerSpec.transformer_encoder(1024, 512)

    def test_tp_divides_params_and_fsdp_divides_states(self):
        c = _cluster()
        base = MemoryCostModel(ParallelStrategy(), self.LAYER, 8, c)
        tp = MemoryCostModel(ParallelStrategy(tp=8), self.LAYER, 8, c)
        fsdp = MemoryCostModel(ParallelStrategy(dp=8, fsdp=True),
                               self.LAYER, 8, c)
        assert tp.model_states == pytest.approx(base.model_states / 8)
        assert fsdp.model_states < base.model_states / 4  # 1/8 + bias
        assert fsdp.model_states > base.model_states / 8

    def test_dp_divides_activations(self):
        c = _cluster()
        base = MemoryCostModel(ParallelStrategy(), self.LAYER, 64, c)
        dp = MemoryCostModel(ParallelStrategy(dp=8), self.LAYER, 64, c)
        assert dp.activation == pytest.approx(base.activation / 8)

    def test_time_dp_speedup_with_comm_cost(self):
        c = _cluster()
        t1 = TimeCostModel(ParallelStrategy(), self.LAYER, 64, c).total
        t8 = TimeCostModel(ParallelStrategy(dp=8), self.LAYER, 64,
                           c).total
        assert t8 < t1                 # dp-8 is faster end-to-end
        assert t8 > t1 / 8             # but not ideal: grad allreduce

    def test_fsdp_costs_more_time_than_dp(self):
        c = _cluster()
        dp = TimeCostModel(ParallelStrategy(dp=8), self.LAYER, 64, c)
        fs = TimeCostModel(ParallelStrategy(dp=8, fsdp=True), self.LAYER,
                           64, c)
        assert fs.comm > dp.comm

    def test_slow_interconnect_penalizes_tp(self):
        fast = _cluster(ici_bandwidth=45e9)
        slow = _cluster(ici_bandwidth=1e9)
        s = ParallelStrategy(tp=8)
        t_fast = TimeCostModel(s, self.LAYER, 64, fast).total
        t_slow = TimeCostModel(s, self.LAYER, 64, slow).total
        assert t_slow > t_fast


class TestDPAlg:
    def test_picks_cheapest_when_memory_free(self):
        alg = DPAlg(max_mem=100, layer_num=3, strategy_num=2)
        v = np.ones((3, 2), dtype=np.int64)
        intra = np.array([[1.0, 5.0]] * 3)
        inter = np.zeros((3, 2, 2))
        alg.set_v_and_cost(v, intra, inter)
        cost, idx, left = alg.fit()
        assert idx == [0, 0, 0]
        assert cost == pytest.approx(3.0)

    def test_memory_pressure_forces_expensive_strategy(self):
        # strategy 0: fast but huge; strategy 1: slow but small
        alg = DPAlg(max_mem=6, layer_num=3, strategy_num=2)
        v = np.array([[4, 1]] * 3, dtype=np.int64)
        intra = np.array([[1.0, 2.0]] * 3)
        inter = np.zeros((3, 2, 2))
        alg.set_v_and_cost(v, intra, inter)
        cost, idx, _ = alg.fit()
        # only one layer can afford strategy 0 (4 + 1 + 1 = 6 fits)
        assert sorted(idx) == [0, 1, 1]
        assert cost == pytest.approx(1.0 + 2.0 + 2.0)

    def test_infeasible_returns_inf(self):
        alg = DPAlg(max_mem=2, layer_num=2, strategy_num=1)
        alg.set_v_and_cost(np.full((2, 1), 5, dtype=np.int64),
                           np.ones((2, 1)), np.zeros((2, 1, 1)))
        cost, idx, _ = alg.fit()
        assert cost == np.inf and idx is None

    def test_switch_cost_discourages_mixing(self):
        # equal intra costs; any mixing pays the switch penalty
        alg = DPAlg(max_mem=100, layer_num=4, strategy_num=2)
        v = np.ones((4, 2), dtype=np.int64)
        intra = np.ones((4, 2))
        inter = np.full((4, 2, 2), 0.5)
        for i in range(4):
            np.fill_diagonal(inter[i], 0.0)
        alg.set_v_and_cost(v, intra, inter)
        cost, idx, _ = alg.fit()
        assert len(set(idx)) == 1
        assert cost == pytest.approx(4.0)


class TestPipelineDivision:
    def test_even(self):
        assert pipeline_division_even(8, 4) == [[0, 1], [2, 3], [4, 5],
                                                [6, 7]]

    def test_uneven_front_loaded(self):
        stages = pipeline_division_even(10, 4)
        assert [len(s) for s in stages] == [3, 3, 2, 2]
        assert sum(stages, []) == list(range(10))


class TestEndToEndSearch:
    def test_small_model_prefers_data_parallel(self):
        layers = [LayerSpec.transformer_encoder(256, 128, name=f"l{i}")
                  for i in range(4)]
        plan = PlannerSearch(layers, global_batch_size=64,
                             cluster=_cluster()).search()
        assert plan is not None
        assert all(s.dp >= 4 for s in plan.strategies)

    def test_memory_pressure_moves_off_pure_dp(self):
        # params so large that replicated model states exceed HBM
        big = LayerSpec(name="big", param_bytes=3e9,
                        flops_per_sample=1e9,
                        act_bytes_per_sample=1e6, seq_len=512, hidden=4096)
        layers = [big] * 4
        plan = PlannerSearch(layers, global_batch_size=8,
                             cluster=_cluster(hbm_bytes=16e9)).search()
        assert plan is not None
        # 4 layers x 3GB x4 states = 48GB replicated: must shard states
        assert all(s.tp > 1 or s.fsdp or s.pp > 1
                   for s in plan.strategies), plan.describe()

    def test_plan_json_roundtrippable(self):
        layers = [LayerSpec.transformer_encoder(256, 128, name=f"l{i}")
                  for i in range(2)]
        plan = PlannerSearch(layers, global_batch_size=16,
                             cluster=_cluster()).search()
        d = plan_to_json(plan)
        assert len(d["layers"]) == 2
        assert set(d["mesh"]) == {"pp", "tp", "dp", "cp"}


class TestAutoParallelStrategy:
    def test_plan_shards_executor_variables(self):
        layers = [LayerSpec.transformer_encoder(64, 16, name=f"l{i}")
                  for i in range(2)]
        # force a TP-ish plan by making DP look terrible
        cluster = _cluster(hbm_bytes=1e18)
        plan = PlannerSearch(layers, global_batch_size=16,
                             cluster=cluster, allow_cp=False,
                             max_pp=1).search()
        # override to a known uniform tp=2 dp=4 plan for the apply test
        from hetu_tpu.planner import ParallelPlan
        strategies = [ParallelStrategy(tp=2, dp=4)] * 2
        plan = ParallelPlan(strategies, layers, 0.0, cluster)

        x = ht.placeholder_op("x")
        w0 = ht.init.xavier_uniform((64, 128), name="l0_ffn_wi")
        w1 = ht.init.xavier_uniform((128, 64), name="l0_ffn_wo")
        h = ht.matmul_op(ht.matmul_op(x, w0), w1)
        loss = ht.reduce_mean_op(ht.reduce_sum_op(ht.mul_op(h, h), [1]),
                                 [0])
        train = ht.optim.SGDOptimizer(learning_rate=0.01).minimize(loss)
        ex = ht.Executor({"train": [loss, train]},
                         dist_strategy=AutoParallel(plan))
        out = ex.run("train", feed_dict={
            x: np.random.RandomState(0).randn(8, 64).astype(np.float32)})
        assert np.isfinite(float(np.asarray(out[0])))
        specs = {n: v.sharding_spec for n, v in ex.variables.items()}
        assert specs["l0_ffn_wi"] == __import__(
            "jax").sharding.PartitionSpec(None, "tp")
        assert specs["l0_ffn_wo"] == __import__(
            "jax").sharding.PartitionSpec("tp", None)


class TestClosedLoop:
    """The full Galvatron loop in one test: profile a REAL graph-built
    layer -> calibrate the cost models -> search -> apply -> execute on
    the 8-device CPU mesh (reference: test_env scripts ->
    cost-model configs -> search_layerwise_hp -> Galvatron runtime)."""

    H, S, L, V, GBS = 32, 16, 4, 100, 16

    def _specs(self):
        return [LayerSpec.transformer_encoder(self.H, self.S,
                                              name=f"l{i}")
                for i in range(self.L)]

    def test_profile_calibrate_search_apply_run(self):
        from hetu_tpu.models.bert import BertConfig, BertLayer, \
            BertForSequenceClassification
        from hetu_tpu.planner import calibrate_layers, graph_layer_fn, \
            measure_cluster

        # 1. profile a real encoder block built from the graph API
        cfg = BertConfig(vocab_size=self.V, hidden_size=self.H,
                         num_hidden_layers=1, num_attention_heads=2,
                         intermediate_size=4 * self.H, seq_len=self.S,
                         batch_size=4, hidden_dropout_prob=0.0,
                         attention_probs_dropout_prob=0.0)
        xin = ht.placeholder_op("cl_profile_x")
        fn = graph_layer_fn(BertLayer(cfg, name="cl_profile")(xin), xin)

        # 2. calibrate cluster + layer specs from measurements
        cluster = measure_cluster(n_devices=8, probe_dim=128)
        assert cluster.flops_per_sec > 0
        layers = self._specs()
        calibrate_layers(layers, [lambda x: fn(
            x.reshape(-1, self.H))], batch=4)
        assert all(l.fwd_time_per_sample and l.fwd_time_per_sample > 0
                   for l in layers)

        # 3. memory pressure: pure DP must NOT fit, so the search is
        # forced off the naive strategy ("beats naive DP" concretely:
        # naive DP is infeasible, the plan is feasible and executes)
        pure_dp = ParallelStrategy(dp=8)
        dp_mem = MemoryCostModel(pure_dp, layers[0], self.GBS,
                                 cluster).total
        cluster.hbm_bytes = dp_mem * 0.8 / 0.9   # cap below pure-DP need
        search = PlannerSearch(layers, global_batch_size=self.GBS,
                               cluster=cluster, mem_unit=4 * 1024,
                               allow_cp=False)
        plan = search.search()
        assert plan is not None, "no feasible plan found"
        assert all(str(s) != str(pure_dp) for s in plan.strategies)
        assert np.isfinite(plan.cost)

        # 4-5. apply + run: build the real model, train under the plan
        pp = plan.mesh_axes().get("pp", 1)
        num_mb = 2 * pp if pp > 1 else 1
        mcfg = BertConfig(vocab_size=self.V, hidden_size=self.H,
                          num_hidden_layers=self.L,
                          num_attention_heads=2,
                          intermediate_size=4 * self.H, seq_len=self.S,
                          batch_size=self.GBS // num_mb,
                          hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0)
        ids = ht.placeholder_op("cl_ids")
        labels = ht.placeholder_op("cl_labels")
        model = BertForSequenceClassification(mcfg, num_labels=2)
        loss, _ = model(ids, labels=labels)
        train = ht.optim.AdamOptimizer(learning_rate=1e-3).minimize(loss)
        from hetu_tpu.planner import AutoParallel
        ex = ht.Executor({"train": [loss, train]},
                         dist_strategy=AutoParallel(plan))
        rng = np.random.RandomState(0)
        for _ in range(2):
            xb = rng.randint(0, self.V,
                             (self.GBS, self.S)).astype(np.int32)
            yb = rng.randint(0, 2, (self.GBS,)).astype(np.int32)
            out = ex.run("train", feed_dict={ids: xb, labels: yb})
            assert np.isfinite(float(np.asarray(out[0])))

    def test_pp_plan_drives_pipeline_mode(self):
        """A plan with pp>1 turns on Executor(pipeline='gpipe')."""
        from hetu_tpu.planner import ParallelPlan
        layers = self._specs()
        strat = ParallelStrategy(pp=2, dp=4)
        plan = ParallelPlan([strat] * self.L, layers, 1e-3, _cluster())
        from test_pipeline_executor import build_model
        x, y, loss, train = build_model()
        ex = ht.Executor({"train": [loss, train]},
                         dist_strategy=AutoParallel(plan))
        assert ex.config.pipeline == "gpipe"
        sub = ex.subexecutor["train"]
        assert sub.spmd    # uniform residual-MLP body on the pp mesh
        xb = np.random.RandomState(1).randn(16, 8).astype(np.float32)
        yb = np.eye(4, dtype=np.float32)[np.random.RandomState(2)
                                         .randint(0, 4, 16)]
        out = ex.run("train", feed_dict={x: xb, y: yb})
        assert np.isfinite(float(np.asarray(out[0])))


class TestChipCalibration:
    """VERDICT r2 item 4 machinery: single-chip calibration artifact +
    measured plan-vs-naive delta + ClusterSpec loader (run on the real
    chip by `python -m hetu_tpu.planner.chip_calibration`, artifact
    CALIBRATION_TPU.json)."""

    def test_calibrate_structure_and_loader(self, tmp_path):
        import json
        from hetu_tpu.planner.chip_calibration import (
            calibrate_chip, load_calibration)
        art = calibrate_chip(small=True)
        for key in ("matmul_tflops_bf16", "matmul_tflops_bf16_raw",
                    "matmul_clamped_to_spec", "host_link", "overlap",
                    "flash_blocks", "plan_vs_naive", "cluster_spec",
                    "unmeasurable_on_one_chip"):
            assert key in art, key
        # clamp bookkeeping: a clamped dim must have raw > recorded
        for d, clamped in art["matmul_clamped_to_spec"].items():
            if clamped:
                assert art["matmul_tflops_bf16_raw"][d] > \
                    art["matmul_tflops_bf16"][d]
        assert 0.0 <= art["overlap"]["overlap_h2d"] <= 1.0
        assert art["flash_blocks"]["chosen"] in \
            art["flash_blocks"]["step_ms"]
        p = tmp_path / "cal.json"
        p.write_text(json.dumps(art))
        spec = load_calibration(str(p), n_devices=4)
        assert spec.n_devices == 4
        assert spec.overlap == art["overlap"]["overlap_h2d"]
        assert spec.flops_per_sec == art["cluster_spec"]["flops_per_sec"]

    def test_search_consumes_calibration(self, tmp_path):
        """The DP search runs against a loaded calibration spec."""
        import json
        from hetu_tpu.planner.chip_calibration import (
            calibrate_chip, load_calibration)
        from hetu_tpu.planner.search import PlannerSearch
        from hetu_tpu.planner.cost_model import LayerSpec
        art = calibrate_chip(small=True)
        p = tmp_path / "cal.json"
        p.write_text(json.dumps(art))
        spec = load_calibration(str(p), n_devices=8)
        layers = [LayerSpec.transformer_encoder(64, 32)
                  for _ in range(4)]
        plan = PlannerSearch(layers, global_batch_size=32,
                             cluster=spec).search()
        assert plan is not None

    def test_search_consumes_checked_in_tpu_artifact(self):
        """The REAL CALIBRATION_TPU.json measured on the v5e drives a
        search end-to-end: the artifact's curve must be physical (no
        reading above the device's spec-sheet peak) and its ClusterSpec
        must produce a plan."""
        import os
        from hetu_tpu.planner.chip_calibration import (
            CALIBRATION_FILE, load_calibration, SPEC_PEAKS)
        from hetu_tpu.planner.search import PlannerSearch
        from hetu_tpu.planner.cost_model import LayerSpec
        if not os.path.exists(CALIBRATION_FILE):
            import pytest
            pytest.skip("no checked-in calibration artifact")
        import json
        with open(CALIBRATION_FILE) as f:
            art = json.load(f)
        if art.get("platform") == "cpu":
            import pytest
            pytest.skip("artifact is a CPU small-mode placeholder")
        kind = art["device_kind"].lower()
        spec_peak = next((p for sub, p in SPEC_PEAKS if sub in kind),
                         None)
        if spec_peak is not None:
            for d, v in art["matmul_tflops_bf16"].items():
                assert v is None or v <= spec_peak, (d, v)
        spec = load_calibration(n_devices=8)
        assert spec.flops_per_sec > 1e12   # a real chip, not a CPU
        layers = [LayerSpec.transformer_encoder(768, 512)
                  for _ in range(12)]
        plan = PlannerSearch(layers, global_batch_size=256,
                             cluster=spec).search()
        assert plan is not None


class TestExecConfigPlanner:
    """Single-chip execution-config ranking closed over the measured
    ablation sweep (VERDICT r3 item 6; reference Galvatron profiles
    components then ranks full configs, utils/cost_model.py:38-60)."""

    @staticmethod
    def _synthetic_sweep(noise=0.0, seed=0):
        """Rows from a known generative model: per-sample base 2ms,
        flash +0.5ms/sample, fused head +0.3ms/sample, fixed 5ms."""
        import numpy as np
        rng = np.random.RandomState(seed)
        rows = []
        for b in (8, 16, 32, 64):
            for a in ("xla", "flash"):
                for h in ("materialized", "fused"):
                    t = b * (2.0 + 0.5 * (a == "flash")
                             + 0.3 * (h == "fused")) + 5.0
                    rows.append({"batch": b, "attention": a, "head": h,
                                 "step_time_ms":
                                     t * (1 + noise * rng.randn())})
        return rows

    def test_model_recovers_generative_components(self):
        from hetu_tpu.planner.exec_plan import ExecConfigModel
        import numpy as np
        m = ExecConfigModel().fit(self._synthetic_sweep())
        # generative model has no quadratic term: c2 must fit ~0
        np.testing.assert_allclose(
            m.coef, [2.0, 0.0, 0.5, 0.3, 5.0], atol=1e-7)

    def test_argmax_match_with_heldout_winner(self):
        """The strict split: the measured-best config is EXCLUDED from
        the fit and the model must still predict it on top."""
        from hetu_tpu.planner.exec_plan import validate_against_sweep
        rep = validate_against_sweep(self._synthetic_sweep(noise=0.02))
        assert rep["ok"], rep
        assert rep["regret"] <= rep["regret_tol"]
        assert rep["spearman_rho"] > 0.9
        assert rep["n_fit"] == rep["n_configs"] - 1

    def test_checked_in_sweep_artifact_validates(self):
        """SWEEP_BERT_BASE.json (written by HETU_BENCH_SWEEP=1
        bench.py) must carry a planner_validation whose argmax matches —
        the closed loop the VERDICT asked for, on whatever platform
        measured the artifact."""
        import json
        import os
        path = os.path.join(os.path.dirname(__file__), "..",
                            "SWEEP_BERT_BASE.json")
        if not os.path.exists(path):
            import pytest
            pytest.skip("no sweep artifact checked in")
        with open(path) as f:
            art = json.load(f)
        pv = art.get("planner_validation", {})
        assert pv.get("ok") is True, pv
        # re-validate from the raw rows (don't trust the embedded field)
        from hetu_tpu.planner.exec_plan import validate_against_sweep
        rep = validate_against_sweep(art)
        assert rep["ok"], rep
        assert rep["regret"] <= rep["regret_tol"], rep

    def test_negative_extrapolation_ranks_last(self):
        from hetu_tpu.planner.exec_plan import ExecConfigModel
        m = ExecConfigModel()
        m.coef = [0.1, 0.0, 0.0, 0.0, -100.0]  # negative times, small b
        import numpy as np
        m.coef = np.asarray(m.coef)
        cfg = {"batch": 4, "attention": "xla", "head": "materialized"}
        assert m.predict_throughput(cfg) == 0.0


class TestPlanAssumedConstants:
    """ICI/DCN constants the one-chip calibration cannot measure are
    flagged in plan output (VERDICT r3 item 6 tail)."""

    def test_load_calibration_marks_provenance(self, tmp_path):
        import json
        from hetu_tpu.planner.chip_calibration import (calibrate_chip,
                                                       load_calibration)
        art = calibrate_chip(small=True)
        p = tmp_path / "cal.json"
        p.write_text(json.dumps(art))
        spec = load_calibration(str(p), n_devices=8)
        assert spec.provenance["flops_per_sec"] == "measured"
        assert spec.provenance["ici_bandwidth"] == "spec-assumed"
        assert spec.provenance["dcn_bandwidth"] == "spec-assumed"
        assumed = spec.assumed_constants()
        assert "ici_bandwidth" in assumed
        assert "flops_per_sec" not in assumed

    def test_plan_json_and_describe_surface_assumptions(self, tmp_path):
        import json
        from hetu_tpu.planner import (LayerSpec, PlannerSearch,
                                      plan_to_json)
        from hetu_tpu.planner.chip_calibration import (calibrate_chip,
                                                       load_calibration)
        art = calibrate_chip(small=True)
        p = tmp_path / "cal.json"
        p.write_text(json.dumps(art))
        spec = load_calibration(str(p), n_devices=8)
        layers = [LayerSpec.transformer_encoder(64, 32) for _ in range(4)]
        plan = PlannerSearch(layers, global_batch_size=32,
                             cluster=spec).search()
        j = plan_to_json(plan)
        assert "ici_bandwidth" in j["assumed_constants"]
        assert j["assumed_constants"]["ici_bandwidth"]["provenance"] == \
            "spec-assumed"
        assert "NOT from measurement" in plan.describe()
        # planner honesty (VERDICT next #6): the banner is PROMINENT —
        # a top-level WARNING key in the json, the FIRST line of
        # describe() — not a footnote
        assert "unvalidated on hardware" in j["WARNING"]
        assert "ici_bandwidth" in j["WARNING"]
        desc = plan.describe()
        assert desc.splitlines()[0].startswith("*** WARNING")
        assert "unvalidated on hardware" in desc.splitlines()[0]


class TestEnvProfiler:
    """Environment profiler CLI (reference tools/Galvatron/test_env
    bandwidth/overlap scripts): per-axis collective bandwidths + overlap
    coefficient measured on the current mesh."""

    def test_profile_env_structure(self, tmp_path):
        from hetu_tpu.planner.env_profile import profile_env
        art = profile_env({"dp": 2, "tp": 2}, size_mb=1, compute_dim=128)
        assert set(art["axes"]) == {"dp", "tp"}
        for ax in ("dp", "tp"):
            c = art["axes"][ax]["collectives"]
            for key in ("allreduce_bytes_per_s", "allgather_bytes_per_s",
                        "alltoall_bytes_per_s", "ppermute_bytes_per_s"):
                assert c[key] > 0, (ax, key)
            ov = art["axes"][ax]["overlap"]
            assert 0.0 <= ov["overlap"] <= 1.0
        assert art["matmul_tflops_bf16"] > 0

    def test_cpu_profile_refuses_chip_label(self):
        """Planner honesty (VERDICT next #6): a CPU-platform profile is
        host-characterizing — labeled so in the artifact with a WARNING
        banner, and a 'chip' claim is refused outright."""
        from hetu_tpu.planner.env_profile import profile_env
        art = profile_env({"dp": 1}, size_mb=1, compute_dim=64)
        assert art["platform"] == "cpu"
        assert art["characterizes"] == "host"
        assert "characterize the HOST" in art["WARNING"]
        with pytest.raises(ValueError, match="refusing to label"):
            profile_env({"dp": 1}, size_mb=1, compute_dim=64,
                        claim="chip")

    def test_cli_writes_artifact(self, tmp_path):
        import json
        import subprocess
        import sys
        out = tmp_path / "env.json"
        r = subprocess.run(
            [sys.executable, "-m", "hetu_tpu.planner.env_profile",
             "--axes", "dp=2", "--size-mb", "1", "--compute-dim", "128",
             "--out", str(out)],
            capture_output=True, text=True, timeout=600,
            env={**os.environ,
                 "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                 "JAX_PLATFORMS": "cpu"},
            cwd=os.path.join(os.path.dirname(__file__), ".."))
        assert r.returncode == 0, r.stderr[-500:]
        art = json.loads(out.read_text())
        assert "dp" in art["axes"]


class TestDecoderLayerSpec:
    def test_decoder_vs_encoder(self):
        enc = LayerSpec.transformer_encoder(64, 32)
        dec = LayerSpec.transformer_decoder(64, 32)
        assert dec.param_bytes == enc.param_bytes
        # causal halves the 2*2*S^2*H attention flops
        assert dec.flops_per_sample == \
            enc.flops_per_sample - 2 * 32 * 32 * 64
        assert dec.tp_comm_factor == 6 and enc.tp_comm_factor == 4
