"""BERT tokenizer tests (reference tokenizers/bert_tokenizer.py)."""

import os
import tempfile

import pytest

from hetu_tpu.tokenizers import (BasicTokenizer, BertTokenizer,
                                 WordpieceTokenizer, load_vocab,
                                 whitespace_tokenize)

VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]", "the", "quick",
         "brown", "fox", "jump", "##ed", "##s", "over", "lazy", "dog",
         "un", "##aff", "##able", "run", "##ning", ",", "."]


@pytest.fixture(scope="module")
def vocab_file():
    d = tempfile.mkdtemp()
    p = os.path.join(d, "vocab.txt")
    with open(p, "w") as f:
        f.write("\n".join(VOCAB) + "\n")
    return p


class TestBasic:
    def test_lower_and_punct(self):
        t = BasicTokenizer()
        assert t.tokenize("The quick, brown FOX.") == \
            ["the", "quick", ",", "brown", "fox", "."]

    def test_accents_stripped(self):
        assert BasicTokenizer().tokenize("Héllo") == ["hello"]

    def test_chinese_chars_split(self):
        assert BasicTokenizer().tokenize("ab一亍cd") == \
            ["ab", "一", "亍", "cd"]

    def test_never_split(self):
        assert BasicTokenizer().tokenize("[CLS] hi [SEP]") == \
            ["[CLS]", "hi", "[SEP]"]

    def test_whitespace_tokenize(self):
        assert whitespace_tokenize("  a  b\tc\n") == ["a", "b", "c"]


class TestWordpiece:
    def test_greedy_longest_match(self, vocab_file):
        wp = WordpieceTokenizer(load_vocab(vocab_file))
        assert wp.tokenize("unaffable") == ["un", "##aff", "##able"]
        assert wp.tokenize("jumped") == ["jump", "##ed"]

    def test_unknown_word(self, vocab_file):
        wp = WordpieceTokenizer(load_vocab(vocab_file))
        assert wp.tokenize("xyzzy") == ["[UNK]"]


class TestBertTokenizer:
    def test_roundtrip_ids(self, vocab_file):
        tok = BertTokenizer(vocab_file)
        tokens = tok.tokenize("The quick brown fox jumps.")
        ids = tok.convert_tokens_to_ids(tokens)
        assert tok.convert_ids_to_tokens(ids) == tokens
        assert tokens == ["the", "quick", "brown", "fox", "jump", "##s",
                          "."]

    def test_encode_pair_with_padding(self, vocab_file):
        tok = BertTokenizer(vocab_file)
        enc = tok.encode("the fox", "lazy dog", max_length=12)
        assert len(enc["input_ids"]) == 12
        assert enc["input_ids"][0] == tok.vocab["[CLS]"]
        assert enc["token_type_ids"][:4] == [0, 0, 0, 0]
        assert 1 in enc["token_type_ids"]
        assert enc["attention_mask"][-1] == 0  # padded tail

    def test_encode_truncates(self, vocab_file):
        tok = BertTokenizer(vocab_file)
        enc = tok.encode("the quick brown fox jumped over the lazy dog",
                         max_length=6)
        assert len(enc["input_ids"]) == 6

    def test_from_pretrained_dir(self, vocab_file):
        tok = BertTokenizer.from_pretrained(os.path.dirname(vocab_file))
        assert tok.tokenize("fox") == ["fox"]

    def test_missing_vocab_raises(self):
        with pytest.raises(ValueError):
            BertTokenizer("/nonexistent/vocab.txt")

    def test_crlf_vocab_and_sequential_ids(self):
        # regression: CRLF endings must strip; blank lines must not shift
        # ids relative to the embedding rows
        d = tempfile.mkdtemp()
        p = os.path.join(d, "v.txt")
        with open(p, "wb") as f:
            f.write(b"[PAD]\r\n[UNK]\r\n\r\nhello\r\n")
        v = load_vocab(p)
        assert v["[PAD]"] == 0 and v["[UNK]"] == 1 and v["hello"] == 3
