"""Scripted checks for the bin/ + bench provenance fixes (ISSUE 1
satellites; ADVICE findings).

- ``bin/summarize_onchip.py``: A/B ranking must read each stage's OWN
  config row from the headline's nested matrix (the top-level headline
  value is the stale bert_base number on subset runs) and must not
  declare a winner on an all-equal group (string tie-break regression).
- ``bin/tpu_watchdog.sh``: only the suite's distinctive flock-refusal
  exit code (75) is exempt from the MAX_FIRES budget; a genuine exit-1
  must count, or the watchdog re-fires the battery forever.
- ``bench.py``: the outlier re-probe records the DISCARDED reading
  (never a duplicate of the kept one), and HETU_BENCH_FORCE_FLASH
  stamps ``flash_forced`` provenance into the result row.
"""

import json
import os
import stat
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_stage(logdir, name, headline):
    with open(os.path.join(logdir, name + ".log"), "w") as f:
        f.write("noise line\n")
        f.write(json.dumps(headline) + "\n")


def _headline(matrix_rows):
    """A bench.py headline as emitted on a CONFIGS=subset run: the
    top-level value is the stale bert_base row; per-config truth lives
    in the nested matrix."""
    return {
        "metric": "bert_base_seq512_train_throughput",
        "value": 100.0, "unit": "samples/sec/chip", "mfu": 0.5,
        "platform": "tpu",
        "matrix": {"bert_base": {"value": 100.0,
                                 "unit": "samples/sec/chip",
                                 "mfu": 0.5},
                   **matrix_rows},
    }


@pytest.mark.smoke
class TestSummarizeOnchip:
    def _run(self, logdir):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bin",
                                          "summarize_onchip.py"), logdir],
            capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, r.stderr
        return r.stdout

    def test_winner_uses_per_config_matrix_value(self, tmp_path):
        """Regression (ADVICE high): with the stale bert_base headline
        identical across variants, the winner must come from the
        per-config rows — here lc 1024x2048 despite 512x1024 sorting
        last... and first lexicographically."""
        d = str(tmp_path)
        _write_stage(d, "lc_512x1024", _headline(
            {"long_context": {"value": 5.0, "unit": "tok/s", "mfu": 0.2}}))
        _write_stage(d, "lc_1024x2048", _headline(
            {"long_context": {"value": 7.0, "unit": "tok/s", "mfu": 0.3}}))
        out = self._run(d)
        assert "long-context winner: blocks 1024,2048 (7.0)" in out
        # the per-stage table shows each variant's own number, not 100.0
        assert "lc_512x1024" in out and "5.0" in out

    def test_all_equal_group_prints_no_winner(self, tmp_path):
        """The old code max()ed identical values and crowned a winner by
        label string comparison; an all-equal group must print none."""
        d = str(tmp_path)
        for tok in ("1024", "2048", "4096"):
            _write_stage(d, f"moe_t{tok}", _headline(
                {"moe": {"value": 3.0, "unit": "tok/s", "mfu": 0.1}}))
        out = self._run(d)
        assert "moe winner" not in out
        assert "no winner to re-run" in out

    def test_bert4l_flash_ab_ranks_measurements(self, tmp_path):
        d = str(tmp_path)
        _write_stage(d, "bert4l_noflash", _headline(
            {"bert4l": {"value": 1987.0, "unit": "samples/sec/chip"}}))
        _write_stage(d, "bert4l_flash", _headline(
            {"bert4l": {"value": 630.0, "unit": "samples/sec/chip"}}))
        out = self._run(d)
        # noflash measured faster: flash=0 wins (old code: '1' > '0'
        # string tie-break always crowned flash)
        assert "bert4l winner: flash=0 (1987.0)" in out


class TestWatchdogExitCodes:
    def _run_watchdog(self, tmp_path, suite_rc, timeout_s):
        d = str(tmp_path)
        counter = os.path.join(d, "fires")
        stub = os.path.join(d, "suite_stub.sh")
        with open(stub, "w") as f:
            f.write("#!/bin/bash\n"
                    f"echo x >> {counter}\n"
                    f"exit {suite_rc}\n")
        os.chmod(stub, os.stat(stub).st_mode | stat.S_IEXEC)
        env = dict(os.environ,
                   MAX_FIRES="2",
                   PROBE_CMD="true",
                   SUITE_CMD=f"bash {stub}",
                   DONE_FILE=os.path.join(d, "done"))
        r = subprocess.run(
            ["timeout", str(timeout_s), "bash",
             os.path.join(REPO, "bin", "tpu_watchdog.sh"), "0.1", d],
            capture_output=True, text=True, env=env,
            timeout=timeout_s + 30)
        fires = 0
        if os.path.exists(counter):
            with open(counter) as f:
                fires = len(f.readlines())
        return r, fires

    def test_lock_refusal_75_never_counts(self, tmp_path):
        """rc=75 (flock refusal) keeps re-probing past MAX_FIRES — the
        watchdog must still be alive (killed by our timeout, rc 124)
        after more firings than the budget."""
        r, fires = self._run_watchdog(tmp_path, suite_rc=75, timeout_s=5)
        assert r.returncode == 124, (r.returncode, r.stdout, r.stderr)
        assert fires > 2

    def test_genuine_failure_counts_and_gives_up(self, tmp_path):
        """rc=1 (a real early failure) must consume the budget: exactly
        MAX_FIRES firings, then exit 2 (give up) — the regression was
        rc=1 being treated as 'not an attempt' and re-firing forever."""
        r, fires = self._run_watchdog(tmp_path, suite_rc=1, timeout_s=20)
        assert r.returncode == 2, (r.returncode, r.stdout, r.stderr)
        assert fires == 2

    def test_suite_flock_refusal_is_75(self, tmp_path):
        """bin/run_onchip_suite.sh itself exits 75 when the lock is
        held.  The holder script must NOT tail-exec the suite (bash
        would hand the locked fd over and the re-open would release
        it), so the suite runs mid-script with commands after it."""
        script = (
            "cd %s || exit 98\n"
            "exec 9>.tpu_watchdog.lock\n"
            "flock -n 9 || exit 99\n"
            "bash bin/run_onchip_suite.sh %s/log\n"
            "ec=$?\n"
            "exit $ec\n" % (REPO, tmp_path))
        r = subprocess.run(["bash", "-c", script], capture_output=True,
                           text=True, timeout=60)
        assert r.returncode == 75, (r.returncode, r.stdout, r.stderr)
        assert "refusing" in r.stderr


@pytest.mark.smoke
class TestBenchProvenance:
    def test_retry_recorder_keeps_better_and_records_discarded(self):
        import bench

        # retry wins: kept value updated, FIRST reading recorded
        probes, numeric = {48: 64.6}, {48: 64.6}
        bench._record_retry_probe(probes, numeric, 48, 64.6, 216.0)
        assert probes[48] == numeric[48] == 216.0
        assert probes["48_first_reading"] == 64.6
        assert "48_retry_reading" not in probes

        # retry loses: kept value unchanged, RETRY reading recorded —
        # never a duplicate of the kept value (the ADVICE regression)
        probes, numeric = {48: 216.0}, {48: 216.0}
        bench._record_retry_probe(probes, numeric, 48, 216.0, 60.0)
        assert probes[48] == numeric[48] == 216.0
        assert probes["48_retry_reading"] == 60.0
        assert "48_first_reading" not in probes

        # failed/skipped retry records nothing
        probes, numeric = {48: 216.0}, {48: 216.0}
        bench._record_retry_probe(probes, numeric, 48, 216.0,
                                  "probe timed out (tunnel degraded?)")
        assert set(probes) == {48}

    def test_bench_lm_records_flash_forced(self, monkeypatch):
        import bench

        monkeypatch.setattr(bench, "_build_lm",
                            lambda *a, **kw: None)
        monkeypatch.setattr(bench, "_time_steps",
                            lambda fn, iters, loss_fn: (0.1, 0.0))
        monkeypatch.delenv("HETU_BENCH_FORCE_FLASH", raising=False)
        out = bench._bench_lm("cpu", True, layers_n=2, seq=64,
                              per_chip_batch=2, iters=2)
        assert "flash_forced" not in out

        monkeypatch.setenv("HETU_BENCH_FORCE_FLASH", "1")
        out = bench._bench_lm("cpu", True, layers_n=2, seq=64,
                              per_chip_batch=2, iters=2)
        assert out["flash_forced"] is True and out["flash_attention"]

        monkeypatch.setenv("HETU_BENCH_FORCE_FLASH", "0")
        out = bench._bench_lm("cpu", True, layers_n=2, seq=64,
                              per_chip_batch=2, iters=2)
        assert out["flash_forced"] is True
        assert out["flash_attention"] is False
