"""Tier-3 multi-host SPMD: two real jax processes (one CPU device each)
form one dp=2 mesh via jax.distributed (Gloo collectives over the
loopback — the CPU stand-in for ICI/DCN), train the same fixed-weight
model through the Executor, and must reproduce the single-process
trajectory exactly.

This is the live counterpart of the reference's multi-node NCCL/MPI path
(SURVEY §5.8; communicator/mpi_nccl_comm.py bootstrap + worker ranks):
`hetu_tpu.launcher.distributed_init` does the same bring-up from heturun
env vars.
"""

import multiprocessing as mp
import os
import socket

import numpy as np
import pytest

STEPS = 6
BATCH, IN, OUT = 8, 6, 3


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _make_data():
    rng = np.random.RandomState(0)
    W1 = rng.randn(IN, 16).astype(np.float32)
    W2 = rng.randn(16, OUT).astype(np.float32)
    batches = []
    for _ in range(STEPS):
        x = rng.randn(BATCH, IN).astype(np.float32)
        y = np.eye(OUT, dtype=np.float32)[rng.randint(0, OUT, BATCH)]
        batches.append((x, y))
    return W1, W2, batches


def _build_and_run_cp(mesh):
    """Ring-attention causal LM: the 'cp' axis spans the two processes,
    so every KV rotation is a cross-process ppermute (ICI/DCN stand-in)."""
    import hetu_tpu as ht

    Hh, Dh, S, vocab = 2, 4, 8, 16
    D, B = Hh * Dh, 4
    rng = np.random.RandomState(1)
    batches = [(rng.randn(B, S, D).astype(np.float32),
                rng.randint(0, vocab, (B, S)).astype(np.int32))
               for _ in range(STEPS)]
    x = ht.placeholder_op("cx")
    y = ht.placeholder_op("cy")

    def proj(name):
        w = ht.Variable(name, value=np.eye(D, dtype=np.float32)
                        + 0.01 * np.arange(D * D, dtype=np.float32)
                        .reshape(D, D) / (D * D))
        return ht.array_reshape_op(
            ht.matmul_op(ht.array_reshape_op(x, [B * S, D]), w),
            [B, S, Hh, Dh])

    head = ht.Variable("c_head", value=np.linspace(
        -0.1, 0.1, D * vocab).astype(np.float32).reshape(D, vocab))
    attn = ht.ring_attention_op(proj("c_wq"), proj("c_wk"),
                                proj("c_wv"), mesh=mesh, causal=True)
    logits = ht.matmul_op(ht.array_reshape_op(attn, [B * S, D]), head)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_sparse_op(
        logits, ht.array_reshape_op(y, [B * S])), axes=0)
    train = ht.optim.AdamOptimizer(learning_rate=0.02).minimize(loss)
    return _run_traj(loss, train, mesh, None, x, y, batches)


def _run_traj(loss, train, mesh, strategy, x, y, batches):
    import hetu_tpu as ht

    ex = ht.Executor({"train": [loss, train]}, mesh=mesh,
                     dist_strategy=strategy)
    return [float(np.asarray(ex.run("train", feed_dict={x: a, y: b})[0]))
            for a, b in batches]


def _build_and_run(mesh, layout="dp"):
    """Identical graph build + trajectory on every process."""
    import hetu_tpu as ht

    if layout == "cp":
        return _build_and_run_cp(mesh)
    W1, W2, batches = _make_data()
    x = ht.placeholder_op("x")
    y = ht.placeholder_op("y")
    w1 = ht.Variable("w1", value=W1)
    w2 = ht.Variable("w2", value=W2)
    h = ht.relu_op(ht.matmul_op(x, w1))
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(h, w2), y), axes=0)
    train = ht.optim.AdamOptimizer(learning_rate=0.05).minimize(loss)
    strategy = None
    if layout == "tp":
        from jax.sharding import PartitionSpec as P
        # Megatron col/row split: each process holds HALF of each weight
        strategy = ht.dist.ShardingPlan({"w1": P(None, "tp"),
                                         "w2": P("tp", None)})
    return _run_traj(loss, train, mesh, strategy, x, y, batches)


def _build_and_run_loader(mesh):
    """Dataloader-fed DP model.  Multi-host: each process's loader must
    produce ONLY its addressable batch rows (VERDICT r2 item 5 — the
    identical-global-batch convention does not scale host feed work).
    Returns (losses, shard) where shard is the loader's (lo, hi) row
    range (None single-process)."""
    import hetu_tpu as ht

    W1, W2, batches = _make_data()
    xs = np.concatenate([a for a, _ in batches])
    ys = np.concatenate([b for _, b in batches])
    x = ht.dataloader_op([ht.Dataloader(xs, BATCH, "train")])
    y = ht.dataloader_op([ht.Dataloader(ys, BATCH, "train")])
    w1 = ht.Variable("w1", value=W1)
    w2 = ht.Variable("w2", value=W2)
    h = ht.relu_op(ht.matmul_op(x, w1))
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(h, w2), y), axes=0)
    train = ht.optim.AdamOptimizer(learning_rate=0.05).minimize(loss)
    ex = ht.Executor({"train": [loss, train]}, mesh=mesh)
    losses = [float(np.asarray(ex.run("train")[0]))
              for _ in range(STEPS)]
    shard = x.dataloaders["train"]._shard
    return losses, shard


def _worker(rank, port, layout, q):
    try:
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        # the heturun env-var contract (launcher._worker_env)
        os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        os.environ["HETU_NUM_PROCESSES"] = "2"
        os.environ["HETU_PROCESS_ID"] = str(rank)
        import jax
        jax.config.update("jax_platforms", "cpu")
        from hetu_tpu.launcher import distributed_init
        distributed_init()
        from hetu_tpu.parallel.mesh import make_mesh
        if layout == "dp_loader":
            losses, shard = _build_and_run_loader(make_mesh({"dp": 2}))
            q.put((rank, {"losses": losses, "shard": shard}))
            return
        mesh = make_mesh({layout: 2})        # one device per process
        losses = _build_and_run(mesh, layout)
        q.put((rank, losses))
    except BaseException as e:  # surface the failure in the parent
        q.put((rank, f"ERROR: {type(e).__name__}: {e}"))


def _ha2a_worker(rank, port, q):
    try:
        # 2 local devices per process: 'ici' stays intra-process, 'dcn'
        # crosses the process boundary — the real topology the
        # hierarchical exchange is designed for
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        os.environ["HETU_NUM_PROCESSES"] = "2"
        os.environ["HETU_PROCESS_ID"] = str(rank)
        import jax
        jax.config.update("jax_platforms", "cpu")
        from hetu_tpu.launcher import distributed_init
        distributed_init()
        import jax.numpy as jnp
        from jax import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P
        import hetu_tpu as ht
        from hetu_tpu.parallel.mesh import make_mesh
        from hetu_tpu.graph.ops_moe import halltoall_op
        from hetu_tpu.graph.node import TraceContext

        mesh = make_mesh({"dcn": 2, "ici": 2})
        node = ht.placeholder_op("t")
        h = halltoall_op(node, axes=("ici", "dcn"))
        xs = np.arange(16 * 2, dtype=np.float32).reshape(16, 2)
        sh = NamedSharding(mesh, P(("dcn", "ici")))
        glob = jax.make_array_from_callback(xs.shape, sh,
                                            lambda idx: xs[idx])

        def body(x):
            tc = TraceContext(axis_env=("ici", "dcn"))
            return h.compute([x], tc)

        run = jax.jit(shard_map(body, mesh=mesh, in_specs=P(("dcn", "ici")),
                                out_specs=P(("dcn", "ici"))))
        out = run(glob)
        out2 = run(out)

        def flat(x):
            parts = x.reshape(4, x.shape[0] // 4, *x.shape[1:])
            return jax.lax.all_to_all(
                parts, ("dcn", "ici"), split_axis=0,
                concat_axis=0).reshape(x.shape)

        flat_out = jax.jit(shard_map(
            flat, mesh=mesh, in_specs=P(("dcn", "ici")),
            out_specs=P(("dcn", "ici"))))(glob)

        def local(a):
            return np.concatenate(
                [np.asarray(s.data) for s in a.addressable_shards])
        involution_ok = bool(np.array_equal(local(out2), local(glob)))
        moved = not np.array_equal(local(out), local(glob))
        flat_match = bool(np.array_equal(local(out), local(flat_out)))
        q.put((rank, {"involution": involution_ok, "moved": moved,
                      "flat_match": flat_match}))
    except BaseException as e:
        q.put((rank, f"ERROR: {type(e).__name__}: {e}"))


def test_hierarchical_a2a_crosses_process_boundary():
    """halltoall over ('ici','dcn') where 'dcn' spans two REAL processes
    (reference dlarrayHAllToAll crosses node boundaries the same way,
    mpi_nccl_communication.cu:152-243): intra-process exchange over
    'ici', inter-process over 'dcn'; composition == one flat a2a and is
    an involution."""
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = _free_port()
    procs = [ctx.Process(target=_ha2a_worker, args=(r, port, q))
             for r in range(2)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(2):
            rank, val = q.get(timeout=240)
            results[rank] = val
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    for rank, val in results.items():
        assert isinstance(val, dict), f"rank {rank}: {val}"
        assert val == {"involution": True, "moved": True,
                       "flat_match": True}, f"rank {rank}: {val}"


def test_heturun_spawns_spmd_workers(tmp_path):
    """`heturun -w 2 python train.py` end-to-end: the launcher provides
    the coordinator env, each worker's distributed_init() joins the
    2-process mesh, and both train the same dp=2 trajectory."""
    import subprocess
    import sys
    import json

    script = tmp_path / "train.py"
    script.write_text(f"""
import os, json
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
from hetu_tpu.launcher import distributed_init
distributed_init()
sys.path.insert(0, os.path.dirname({str(__file__)!r}))
from test_multiprocess import _build_and_run
from hetu_tpu.parallel.mesh import make_mesh
losses = _build_and_run(make_mesh({{"dp": 2}}))
rank = os.environ["HETU_PROCESS_ID"]
with open({str(tmp_path)!r} + "/out_" + rank + ".json", "w") as f:
    json.dump(losses, f)
""")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    # own session: on timeout we must kill the launcher's worker
    # grandchildren too, or a wedged Gloo peer outlives the test holding
    # the coordinator port
    proc = subprocess.Popen(
        [sys.executable, "-m", "hetu_tpu.launcher", "-w", "2",
         sys.executable, str(script)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, start_new_session=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        out, err = proc.communicate(timeout=240)
    except subprocess.TimeoutExpired:
        import signal
        os.killpg(proc.pid, signal.SIGKILL)
        proc.communicate()
        raise
    assert proc.returncode == 0, (out[-2000:], err[-2000:])
    t0 = json.loads((tmp_path / "out_0.json").read_text())
    t1 = json.loads((tmp_path / "out_1.json").read_text())
    np.testing.assert_allclose(t0, t1, rtol=0, atol=0)
    np.testing.assert_allclose(t0, _build_and_run(None), atol=1e-5)


def test_per_process_loader_shards_are_disjoint_and_equivalent():
    """VERDICT r2 item 5: dataloader-fed multi-host DP — each process's
    loader produces only its addressable batch rows (disjoint, covering
    the batch), and the trajectory still matches the single-process
    loader run exactly."""
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = _free_port()
    procs = [ctx.Process(target=_worker, args=(r, port, "dp_loader", q))
             for r in range(2)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(2):
            rank, val = q.get(timeout=240)
            results[rank] = val
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    for rank, val in results.items():
        assert isinstance(val, dict), f"rank {rank}: {val}"
    s0, s1 = results[0]["shard"], results[1]["shard"]
    assert s0 is not None and s1 is not None, (s0, s1)
    # disjoint and jointly covering the global batch
    assert sorted([tuple(s0), tuple(s1)]) == [(0, BATCH // 2),
                                              (BATCH // 2, BATCH)]
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=0, atol=0)
    base, base_shard = _build_and_run_loader(None)
    assert base_shard is None
    np.testing.assert_allclose(results[0]["losses"], base, atol=1e-5)


@pytest.mark.parametrize("layout", ["dp", "tp", "cp"])
def test_two_process_matches_single_process(layout):
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = _free_port()
    procs = [ctx.Process(target=_worker, args=(r, port, layout, q))
             for r in range(2)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(2):
            rank, val = q.get(timeout=240)
            results[rank] = val
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    for rank, val in results.items():
        assert isinstance(val, list), f"rank {rank}: {val}"
    # both processes saw the identical (replicated) loss trajectory
    np.testing.assert_allclose(results[0], results[1], rtol=0, atol=0)

    # and it matches the single-process ground truth (the conftest's
    # in-process 8-device CPU backend; cp baseline = ring over one
    # device, which degenerates to exact attention)
    if layout == "cp":
        import jax
        from hetu_tpu.parallel.mesh import make_mesh
        base = _build_and_run(
            make_mesh({"cp": 1}, devices=jax.devices()[:1]), "cp")
    else:
        base = _build_and_run(None)
    np.testing.assert_allclose(results[0], base, atol=1e-5)
