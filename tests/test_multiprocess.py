"""Tier-3 multi-host SPMD: two real jax processes (one CPU device each)
form one dp=2 mesh via jax.distributed (Gloo collectives over the
loopback — the CPU stand-in for ICI/DCN), train the same fixed-weight
model through the Executor, and must reproduce the single-process
trajectory exactly.

This is the live counterpart of the reference's multi-node NCCL/MPI path
(SURVEY §5.8; communicator/mpi_nccl_comm.py bootstrap + worker ranks):
`hetu_tpu.launcher.distributed_init` does the same bring-up from heturun
env vars.
"""

import multiprocessing as mp
import os
import socket

import numpy as np
import pytest

STEPS = 6
BATCH, IN, OUT = 8, 6, 3


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _make_data():
    rng = np.random.RandomState(0)
    W1 = rng.randn(IN, 16).astype(np.float32)
    W2 = rng.randn(16, OUT).astype(np.float32)
    batches = []
    for _ in range(STEPS):
        x = rng.randn(BATCH, IN).astype(np.float32)
        y = np.eye(OUT, dtype=np.float32)[rng.randint(0, OUT, BATCH)]
        batches.append((x, y))
    return W1, W2, batches


def _build_and_run(mesh, layout="dp"):
    """Identical graph build + trajectory on every process."""
    import hetu_tpu as ht

    W1, W2, batches = _make_data()
    x = ht.placeholder_op("x")
    y = ht.placeholder_op("y")
    w1 = ht.Variable("w1", value=W1)
    w2 = ht.Variable("w2", value=W2)
    h = ht.relu_op(ht.matmul_op(x, w1))
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(h, w2), y), axes=0)
    train = ht.optim.AdamOptimizer(learning_rate=0.05).minimize(loss)
    strategy = None
    if layout == "tp":
        from jax.sharding import PartitionSpec as P
        # Megatron col/row split: each process holds HALF of each weight
        strategy = ht.dist.ShardingPlan({"w1": P(None, "tp"),
                                         "w2": P("tp", None)})
    ex = ht.Executor({"train": [loss, train]}, mesh=mesh,
                     dist_strategy=strategy)
    return [float(np.asarray(ex.run("train", feed_dict={x: a, y: b})[0]))
            for a, b in batches]


def _worker(rank, port, layout, q):
    try:
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        # the heturun env-var contract (launcher._worker_env)
        os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        os.environ["HETU_NUM_PROCESSES"] = "2"
        os.environ["HETU_PROCESS_ID"] = str(rank)
        import jax
        jax.config.update("jax_platforms", "cpu")
        from hetu_tpu.launcher import distributed_init
        distributed_init()
        from hetu_tpu.parallel.mesh import make_mesh
        mesh = make_mesh({layout: 2})        # one device per process
        losses = _build_and_run(mesh, layout)
        q.put((rank, losses))
    except BaseException as e:  # surface the failure in the parent
        q.put((rank, f"ERROR: {type(e).__name__}: {e}"))


@pytest.mark.parametrize("layout", ["dp", "tp"])
def test_two_process_matches_single_process(layout):
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = _free_port()
    procs = [ctx.Process(target=_worker, args=(r, port, layout, q))
             for r in range(2)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(2):
            rank, val = q.get(timeout=240)
            results[rank] = val
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    for rank, val in results.items():
        assert isinstance(val, list), f"rank {rank}: {val}"
    # both processes saw the identical (replicated) loss trajectory
    np.testing.assert_allclose(results[0], results[1], atol=0)

    # and it matches the single-process ground truth (the conftest's
    # in-process 8-device CPU backend, mesh-free run)
    base = _build_and_run(None)
    np.testing.assert_allclose(results[0], base, atol=1e-5)
