"""Request-lifecycle observability (ISSUE 7 tentpole).

The acceptance spine: a trace-replay run (the ``HETU_BENCH_SERVE``
harness shape — seeded mixed-length requests through the continuous-
batching engine) exports a Perfetto trace where each request has its
OWN track showing its queue/kv_alloc/prefill/decode lifecycle with
flow arrows into the engine's fused-step wave spans;
``explain_tail()`` names the component that dominates p99 TTFT; a
deliberately-undersized SLO flips ``engine.health()`` to "breach" and
emits ``slo_violation`` events; and the flight recorder dumps
contract-valid JSONL on engine exceptions and QueueFull storms (the
chaos kill/reset dump lives in tests/test_faults.py, next to the rest
of the HETU_CHAOS suite).

Satellites pinned here too: the one interpolating percentile helper
(registry Histogram and ServingMetrics now agree, p95 included),
bounded ``ServingMetrics.events``, gauge records exporting as Chrome
"C" counter tracks, the ``hetu_trace --check`` span-balance rule, and
the ``hetu_top`` dashboard.

All CPU-harness, all smoke-tier.
"""

import json
import os

import numpy as np
import pytest

import hetu_tpu as ht  # noqa: F401  (platform forcing + compat shims)
from hetu_tpu import telemetry
from hetu_tpu.models import GPTConfig
from hetu_tpu.serving import (
    COMPONENTS, QueueFull, Request, ServingEngine, ServingMetrics, SLO,
    SLOMonitor,
)
from hetu_tpu.telemetry import top
from hetu_tpu.telemetry.flight import RECORDER
from hetu_tpu.telemetry.metrics import Histogram, percentile
from hetu_tpu.telemetry.trace import (
    check_span_balance, main as trace_main, read_events,
)

pytestmark = pytest.mark.smoke


def _rand_gpt(name="rt", L=2, H=2, Dh=8, V=61, S=32, seed=0):
    """Deterministic random params in generate_fast's naming contract."""
    rng = np.random.RandomState(seed)
    hd = H * Dh
    p = {f"{name}_wte_table": rng.randn(V, hd) * 0.05,
         f"{name}_wpe": rng.randn(S, hd) * 0.05,
         f"{name}_ln_f_scale": np.ones(hd),
         f"{name}_ln_f_bias": np.zeros(hd)}
    for i in range(L):
        us = f"{name}_h{i}"
        for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                       ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                       ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
            p[f"{us}_{w}_weight"] = rng.randn(*shp) * 0.05
            p[f"{us}_{w}_bias"] = np.zeros(shp[1])
        for ln in ("ln1", "ln2"):
            p[f"{us}_{ln}_scale"] = np.ones(hd)
            p[f"{us}_{ln}_bias"] = np.zeros(hd)
    cfg = GPTConfig(vocab_size=V, hidden_size=hd, num_hidden_layers=L,
                    num_attention_heads=H, max_position_embeddings=S,
                    batch_size=1, seq_len=S, dropout_rate=0.0)
    return p, cfg


@pytest.fixture(scope="module")
def model():
    return _rand_gpt()


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.setenv("HETU_TELEMETRY", "1")
    telemetry.reset()
    yield
    telemetry.reset()


def _mixed_trace(n_req=10, seed=1234, vocab=61):
    """Seeded mixed-length trace, the HETU_BENCH_SERVE harness shape:
    mostly short requests, a longer straggler every 5th."""
    rng = np.random.RandomState(seed)
    trace = []
    for i in range(n_req):
        P = int(rng.randint(2, 7))
        gen = 12 if i % 5 == 4 else int(rng.randint(2, 7))
        trace.append(([int(t) for t in rng.randint(0, vocab, P)], gen))
    return trace


@pytest.fixture(scope="module")
def replay(model, tmp_path_factory):
    """ONE trace-replay run with the merged telemetry log configured;
    read-only tests (export / tail / balance / top) share it."""
    d = tmp_path_factory.mktemp("reqtrace")
    log = str(d / "merged.jsonl")
    old = os.environ.get("HETU_TELEMETRY_LOG")
    os.environ["HETU_TELEMETRY_LOG"] = log
    os.environ.setdefault("HETU_TELEMETRY", "1")
    telemetry.reset()
    try:
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=2, queue_limit=64,
                            fast_path=False)
        reqs = [Request(prompt=pr, max_new_tokens=g)
                for pr, g in _mixed_trace()]
        res = eng.run(reqs)
    finally:
        if old is None:
            os.environ.pop("HETU_TELEMETRY_LOG", None)
        else:
            os.environ["HETU_TELEMETRY_LOG"] = old
    assert len(res) == 10
    return {"eng": eng, "results": res, "log": log, "dir": str(d)}


def _export(log, out):
    rc = trace_main([log, "--export", str(out)])
    assert rc == 0
    with open(out) as f:
        return json.load(f)


def _track(trace, name):
    for e in trace["traceEvents"]:
        if e.get("ph") == "M" and e["args"].get("name") == name:
            return e["pid"], e["tid"]
    return None


# --------------------------------------------------------------------- #
# tentpole (a): lifecycle tracing -> per-request Perfetto tracks
# --------------------------------------------------------------------- #

class TestLifecycleTrace:
    def test_every_request_gets_a_track(self, replay, tmp_path):
        trace = _export(replay["log"], tmp_path / "t.json")
        for rid in replay["results"]:
            assert _track(trace, f"req:{rid}") is not None, rid

    def test_request_track_shows_lifecycle_phases(self, replay,
                                                  tmp_path):
        """Acceptance: an individual request's track reads queue ->
        kv_alloc -> prefill -> decode, start-ordered."""
        trace = _export(replay["log"], tmp_path / "t.json")
        rid = next(r for r, res in replay["results"].items()
                   if res.n_generated > 1)
        pid, tid = _track(trace, f"req:{rid}")
        xs = sorted((e for e in trace["traceEvents"]
                     if e.get("ph") == "X" and e["pid"] == pid
                     and e["tid"] == tid), key=lambda e: e["ts"])
        names = [e["name"] for e in xs]
        assert set(names) == {"queue", "kv_alloc", "prefill", "decode"}
        order = {n: i for i, n in enumerate(names)}
        assert order["queue"] < order["prefill"] < order["decode"]
        for e in xs:
            assert e["dur"] >= 0

    def test_flow_arrows_into_wave_spans(self, replay, tmp_path):
        """The decode span flows (s -> t* -> f) into the engine's
        fused-step wave spans the request actually rode."""
        trace = _export(replay["log"], tmp_path / "t.json")
        evs = trace["traceEvents"]
        waves = [e for e in evs
                 if e.get("ph") == "X" and e["name"] == "serve.decode"]
        assert waves
        rid = next(r for r, res in replay["results"].items()
                   if res.n_generated > 2)
        flows = sorted((e for e in evs if e.get("cat") == "req"
                        and e.get("id") == str(rid)),
                       key=lambda e: e["ts"])
        assert flows, "no flow events for the request"
        assert flows[0]["ph"] == "s" and flows[-1]["ph"] == "f"
        steps = [e for e in flows if e["ph"] == "t"]
        assert steps
        for s in steps:
            assert any(w["pid"] == s["pid"] and w["tid"] == s["tid"]
                       and w["ts"] <= s["ts"] <= w["ts"] + w["dur"]
                       for w in waves), "flow step outside every wave"

    def test_all_records_contract_valid(self, replay):
        events, bad = read_events([replay["log"]])
        assert bad == 0 and events
        for rec in events:
            assert telemetry.validate_record(rec) == [], rec
        kinds = {r["event"] for r in events}
        assert {"serve_submit", "serve_admit", "req_span", "req_retire",
                "serve_finish", "gauge"} <= kinds

    def test_req_retire_carries_breakdown(self, replay):
        events, _ = read_events([replay["log"]])
        retires = [r for r in events if r["event"] == "req_retire"]
        assert len(retires) == len(replay["results"])
        for r in retires:
            for c in COMPONENTS:
                assert isinstance(r.get(c), (int, float)), (c, r)
            assert r["ttft_ms"] > 0


# --------------------------------------------------------------------- #
# tentpole (b): tail-latency decomposition
# --------------------------------------------------------------------- #

class TestTailDecomposition:
    def test_components_in_snapshot(self, replay):
        snap = replay["eng"].metrics.snapshot()
        comps = snap["components"]
        assert set(comps) == set(COMPONENTS)
        for c, agg in comps.items():
            assert set(agg) == {"p50_ms", "p95_ms", "p99_ms", "mean_ms"}
            assert agg["p50_ms"] <= agg["p95_ms"] <= agg["p99_ms"]
        assert snap["ttft_p95_s"] is not None
        assert snap["tpot_p50_s"] is not None and snap["tpot_p50_s"] > 0

    def test_explain_tail_names_dominant_component(self, replay):
        """Acceptance: explain_tail() attributes p99 TTFT to a NAMED
        component."""
        et = replay["eng"].metrics.explain_tail()
        assert et is not None
        assert et["dominant_component"] in COMPONENTS
        assert et["dominant_component"] != "decode_ms"   # TTFT only
        assert 0 < et["dominant_share"] <= 1.0
        assert et["n_tail"] >= 1
        assert et["dominant_component"].replace("_ms", "") \
            in et["summary"]
        assert et["ttft_p_ms"] >= et["ttft_p50_ms"]

    def test_explain_tail_empty_engine(self, model):
        m = ServingMetrics(log_path=None)
        assert m.explain_tail() is None

    def test_paged_requeue_component(self, model):
        """A paged pool that fits ONE request at a time: the second
        request's wait shows up as requeue_ms, not queue_ms."""
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=2, paged=True, kv_block=4,
                            pool_blocks=4, prefix_share=False,
                            fast_path=False)
        reqs = [Request(prompt=[1, 2, 3], max_new_tokens=8),
                Request(prompt=[4, 5, 6], max_new_tokens=8)]
        res = eng.run(reqs)
        assert len(res) == 2
        bd = {b["request"]: b for b in eng.metrics.breakdowns}
        assert bd[reqs[1].request_id]["requeue_ms"] > 0
        assert bd[reqs[0].request_id]["requeue_ms"] == 0

    def test_chunked_prefill_stall_component(self, model):
        """Chunked prefill interleaves with decode waves: the prefill
        phase records >1 dispatch and a non-negative stall share."""
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=2, paged=True, kv_block=4,
                            prefill_chunk=4, fast_path=False)
        long_req = Request(prompt=list(range(1, 13)), max_new_tokens=3)
        res = eng.run([Request(prompt=[7, 8], max_new_tokens=10),
                       long_req])
        assert len(res) == 2
        spans = [e for e in eng.metrics.events
                 if e["event"] == "req_span"
                 and e["request"] == long_req.request_id
                 and e["phase"] == "prefill"]
        assert len(spans) == 1
        assert spans[0]["dispatches"] >= 2        # chunked
        assert spans[0]["stall_ms"] >= 0
        bd = {b["request"]: b for b in eng.metrics.breakdowns}
        assert bd[long_req.request_id]["chunk_stall_ms"] >= 0
        assert bd[long_req.request_id]["prefill_ms"] > 0


# --------------------------------------------------------------------- #
# tentpole (c): SLO classes + engine health()
# --------------------------------------------------------------------- #

class TestSLOHealth:
    def _run(self, model, **kw):
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=2, fast_path=False, **kw)
        eng.run([Request(prompt=[1 + i, 2 + i], max_new_tokens=4)
                 for i in range(4)])
        return eng

    def test_undersized_slo_breaches(self, model):
        """Acceptance: a deliberately-undersized SLO flips health() to
        breach and emits slo_violation events."""
        mon = SLOMonitor([SLO("ttft", "latency", 1e-6)])
        eng = self._run(model, slo=mon)
        assert eng.health() == "breach"
        viol = [e for e in eng.metrics.events
                if e["event"] == "slo_violation"]
        assert len(viol) == 4
        for v in viol:
            assert v["slo"] == "ttft" and v["value"] > v["target"]
            assert telemetry.validate_record(v) == []
        trans = [e for e in eng.metrics.events
                 if e["event"] == "slo_health"]
        assert trans and trans[-1]["state"] == "breach"
        snap = mon.snapshot()
        assert snap["slos"]["ttft"]["burn_rate"] >= 2.0

    def test_generous_slo_stays_ok(self, model):
        eng = self._run(model, slo=[SLO("ttft", "latency", 1e9)])
        assert eng.health() == "ok"
        assert not [e for e in eng.metrics.events
                    if e["event"] == "slo_violation"]

    def test_throughput_slo(self, model):
        """Per-stream decode rate: an impossible tok/s target breaches,
        a trivial one passes."""
        bad = self._run(model, slo=[SLO("tps", "throughput", 1e12)])
        assert bad.health() == "breach"
        ok = self._run(model, slo=[SLO("tps", "throughput", 1e-9)])
        assert ok.health() == "ok"

    def test_env_declared_slo(self, model, monkeypatch):
        monkeypatch.setenv("HETU_SLO_TTFT_MS", "0.000001")
        eng = self._run(model)
        assert eng.health() == "breach"
        assert eng.slo.violations == 4

    def test_no_slo_always_ok(self, model, monkeypatch):
        monkeypatch.delenv("HETU_SLO_TTFT_MS", raising=False)
        monkeypatch.delenv("HETU_SLO_TPS", raising=False)
        eng = self._run(model)
        assert eng.health() == "ok" and eng.slo.slos == []

    def test_degraded_between_ok_and_breach(self):
        """Burn in [1, breach_burn) reads degraded: 2 bad of 100 at a
        0.95 objective is burn 0.4 (ok); 6 bad is burn 1.2
        (degraded); 11 bad is burn 2.2 (breach)."""
        for n_bad, want in ((2, "ok"), (6, "degraded"), (11, "breach")):
            mon = SLOMonitor([SLO("ttft", "latency", 10.0,
                                  objective=0.95)], window=100)
            for i in range(100):
                mon.observe(ttft_ms=100.0 if i < n_bad else 1.0)
            assert mon.health() == want, (n_bad, mon.health())

    def test_bad_slo_kind_rejected(self):
        with pytest.raises(ValueError):
            SLO("x", "availability", 1.0)
        with pytest.raises(ValueError):
            SLO("x", "latency", 1.0, objective=1.5)


# --------------------------------------------------------------------- #
# tentpole (d): flight recorder (engine triggers; chaos kill/reset
# live in tests/test_faults.py)
# --------------------------------------------------------------------- #

class TestFlightRecorder:
    def test_dump_on_engine_exception(self, model, tmp_path,
                                      monkeypatch):
        flog = str(tmp_path / "flight.jsonl")
        monkeypatch.setenv("HETU_FLIGHT_LOG", flog)
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=2, fast_path=False)
        eng.submit(Request(prompt=[1, 2, 3], max_new_tokens=4))

        def boom(*a, **k):
            raise RuntimeError("injected decode fault")
        monkeypatch.setattr(eng, "_decode", boom)
        with pytest.raises(RuntimeError, match="injected"):
            eng.step()
        recs = [json.loads(ln) for ln in open(flog) if ln.strip()]
        assert recs[0]["event"] == "flight_dump"
        assert recs[0]["reason"] == "engine_exception"
        assert "injected decode fault" in recs[0]["error"]
        assert recs[0]["records"] == len(recs) - 1
        for rec in recs:
            assert telemetry.validate_record(rec) == [], rec
        # the records leading up to the fault are there
        kinds = {r["event"] for r in recs}
        assert "serve_submit" in kinds

    def test_dump_on_queue_storm(self, model, tmp_path, monkeypatch):
        flog = str(tmp_path / "storm.jsonl")
        monkeypatch.setenv("HETU_FLIGHT_LOG", flog)
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=1, queue_limit=1,
                            fast_path=False)
        eng.submit(Request(prompt=[1], max_new_tokens=2))
        for i in range(9):
            with pytest.raises(QueueFull):
                eng.submit(Request(prompt=[2], max_new_tokens=2))
        recs = [json.loads(ln) for ln in open(flog) if ln.strip()]
        headers = [r for r in recs if r["event"] == "flight_dump"]
        assert len(headers) == 1          # once per storm, not per reject
        assert headers[0]["reason"] == "queue_storm"
        assert headers[0]["rejects"] == 8
        assert any(r["event"] == "serve_queue_reject" for r in recs)

    def test_queue_full_does_not_dump_engine_exception(self, model,
                                                       tmp_path,
                                                       monkeypatch):
        flog = str(tmp_path / "qf.jsonl")
        monkeypatch.setenv("HETU_FLIGHT_LOG", flog)
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=1, queue_limit=1,
                            fast_path=False)
        eng.submit(Request(prompt=[1], max_new_tokens=2))
        with pytest.raises(QueueFull):
            eng.submit(Request(prompt=[2], max_new_tokens=2))
        assert not os.path.exists(flog)   # one reject != a storm

    def test_no_sink_is_noop(self, monkeypatch):
        monkeypatch.delenv("HETU_FLIGHT_LOG", raising=False)
        telemetry.emit("span", name="x", ms=1.0)
        assert RECORDER.dump("test") is None

    def test_ring_is_bounded_and_always_on(self, monkeypatch, tmp_path):
        monkeypatch.setenv("HETU_FLIGHT_DEPTH", "4")
        monkeypatch.setenv("HETU_TELEMETRY", "0")   # recorder ignores it
        telemetry.reset()                           # picks up the depth
        for i in range(10):
            telemetry.emit("worker_exit", _stream="failure", rank=i,
                           rc=1)
        assert len(RECORDER) == 4
        flog = str(tmp_path / "ring.jsonl")
        assert RECORDER.dump("test", path=flog) == flog
        recs = [json.loads(ln) for ln in open(flog) if ln.strip()]
        assert recs[0]["records"] == 4
        assert [r["rank"] for r in recs[1:]] == [6, 7, 8, 9]


# --------------------------------------------------------------------- #
# satellite: gauge/counter export as Chrome "C" tracks
# --------------------------------------------------------------------- #

class TestCounterExport:
    def test_serve_step_and_gauges_render_as_counters(self, replay,
                                                      tmp_path):
        trace = _export(replay["log"], tmp_path / "t.json")
        cs = [e for e in trace["traceEvents"] if e.get("ph") == "C"]
        names = {e["name"] for e in cs}
        assert {"serve.queue_depth", "serve.live",
                "serve.occupancy", "serve.slots_free"} <= names
        for e in cs:
            assert isinstance(e["args"]["value"], (int, float))

    def test_paged_pool_gauges_export(self, model, tmp_path,
                                      monkeypatch):
        log = str(tmp_path / "paged.jsonl")
        monkeypatch.setenv("HETU_TELEMETRY_LOG", log)
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=2, paged=True, kv_block=4,
                            fast_path=False)
        eng.run([Request(prompt=[1, 2, 3], max_new_tokens=3)])
        trace = _export(log, tmp_path / "t.json")
        names = {e["name"] for e in trace["traceEvents"]
                 if e.get("ph") == "C"}
        assert {"serve.blocks_free", "serve.blocks_shared"} <= names


# --------------------------------------------------------------------- #
# satellite: hetu_trace --check span-balance rule
# --------------------------------------------------------------------- #

class TestSpanBalance:
    def test_balanced_replay_passes(self, replay, capsys):
        assert trace_main([replay["log"], "--check"]) == 0
        out = capsys.readouterr().out.strip().splitlines()[-1]
        assert json.loads(out)["span_balance_violations"] == 0

    def test_admit_without_finish_fails(self, tmp_path, capsys):
        log = tmp_path / "unbalanced.jsonl"
        recs = [
            telemetry.make_record("serve_submit", request="r-9",
                                  queue_depth=0),
            telemetry.make_record("serve_admit", request="r-9", slot=0,
                                  ttft_s=0.01),
        ]
        log.write_text("".join(json.dumps(r) + "\n" for r in recs))
        assert trace_main([str(log), "--check"]) == 1
        out = capsys.readouterr().out
        assert "span-balance" in out and "r-9" in out

    def test_finish_without_admit_fails(self):
        evs = [telemetry.make_record("serve_finish", request="r-3",
                                     reason="length", n_generated=2)]
        problems = check_span_balance(evs)
        assert problems and "without a matching admit" in problems[0]

    def test_flight_dump_snapshot_is_exempt(self):
        evs = [
            telemetry.make_record("flight_dump", reason="chaos_kill"),
            telemetry.make_record("serve_admit", request="r-1", slot=0,
                                  ttft_s=0.01),
        ]
        assert check_span_balance(evs) == []


# --------------------------------------------------------------------- #
# tentpole (e): hetu_top dashboard
# --------------------------------------------------------------------- #

class TestHetuTop:
    def test_summarize_replay(self, replay):
        events, _ = read_events([replay["log"]])
        stats = top.summarize(events, window=0)
        assert stats["requests"]["submitted"] == 10
        assert stats["requests"]["finished"] == 10
        assert stats["ttft_p50_ms"] is not None
        assert stats["ttft_p50_ms"] <= stats["ttft_p99_ms"]
        assert stats["tpot_p50_ms"] is not None
        assert stats["occupancy"] is not None
        assert stats["queue_depth"] is not None
        assert stats["slots"] == 2
        assert stats["slo"]["state"] == "ok"

    def test_render_frame(self, replay):
        events, _ = read_events([replay["log"]])
        frame = top.render(top.summarize(events, window=0), clock=0.0)
        for needle in ("hetu_top", "occupancy", "TTFT", "TPOT", "SLO",
                       "[ OK ]"):
            assert needle in frame, needle

    def test_cli_once(self, replay, capsys):
        assert top.main([replay["log"], "--once"]) == 0
        out = capsys.readouterr().out
        assert "hetu_top" in out and "submitted 10" in out

    def test_cli_requires_paths(self, monkeypatch):
        for env in ("HETU_TELEMETRY_LOG", "HETU_SERVE_LOG",
                    "HETU_FAILURE_LOG", "HETU_VALIDATE_LOG"):
            monkeypatch.delenv(env, raising=False)
        with pytest.raises(SystemExit):
            top.main(["--once"])


# --------------------------------------------------------------------- #
# satellite: ONE percentile implementation (+ p95 in Histogram)
# --------------------------------------------------------------------- #

class TestPercentileUnification:
    def test_matches_numpy_linear_interpolation(self):
        rng = np.random.RandomState(7)
        xs = list(rng.randn(173) * 10)
        for q in (50, 90, 95, 99):
            assert percentile(xs, q) == pytest.approx(
                float(np.percentile(xs, q)))

    def test_edge_cases(self):
        assert percentile([], 50) is None
        assert percentile([5.0], 99) == 5.0
        assert percentile([1, 2], 50) == 1.5

    def test_histogram_summary_has_p95(self):
        h = Histogram("x")
        for v in range(1, 101):
            h.observe(float(v))
        s = h.summary()
        assert s["p95"] == pytest.approx(float(np.percentile(
            np.arange(1.0, 101.0), 95)))
        assert s["p50"] <= s["p95"] <= s["p99"]

    def test_serving_metrics_uses_same_helper(self, model):
        """Registry histograms and serving snapshots now agree on what
        a percentile is (they used to differ: nearest-rank vs numpy)."""
        m = ServingMetrics(log_path=None)
        m.ttfts = [float(v) for v in range(1, 51)]
        snap_p99 = m.snapshot()["ttft_p99_s"]
        assert snap_p99 == pytest.approx(percentile(m.ttfts, 99))
        assert snap_p99 == pytest.approx(
            float(np.percentile(m.ttfts, 99)))


# --------------------------------------------------------------------- #
# satellite: bounded ServingMetrics.events
# --------------------------------------------------------------------- #

class TestBoundedEvents:
    def test_ring_without_log_path(self, monkeypatch):
        monkeypatch.delenv("HETU_SERVE_LOG", raising=False)
        monkeypatch.setenv("HETU_TELEMETRY_BUFFER", "8")
        m = ServingMetrics()
        for i in range(50):
            m.record_submit(f"r-{i}", i)
        assert m.submitted == 50          # aggregates keep counting
        assert len(m.events) == 8         # memory stays bounded
        assert m.events[-1]["request"] == "r-49"

    def test_full_history_with_log_path(self, tmp_path):
        m = ServingMetrics(log_path=str(tmp_path / "s.jsonl"))
        for i in range(50):
            m.record_submit(f"r-{i}", i)
        assert len(m.events) == 50        # deliberate observation
