"""Graphboard tests (reference python/graphboard/graph2fig.py)."""

import urllib.request

import numpy as np

import hetu_tpu as ht
from hetu_tpu import graphboard


def _small_graph():
    x = ht.placeholder_op("x")
    w = ht.Variable("w", value=np.ones((4, 2), np.float32))
    y = ht.relu_op(ht.matmul_op(x, w))
    loss = ht.reduce_mean_op(ht.reduce_sum_op(y, [1]), [0])
    train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
    return x, loss, train


class TestGraphboard:
    def test_dot_contains_nodes_and_edges(self):
        x, loss, train = _small_graph()
        dot = graphboard.to_dot([loss, train])
        assert dot.startswith("digraph")
        assert "Matmul" in dot and "->" in dot
        # all four node kinds colored
        assert "#C6F7D0" in dot     # placeholder
        assert "#FFE9A8" in dot     # variable
        assert "#FFC4C4" in dot     # optimizer

    def test_html_self_contained(self):
        x, loss, train = _small_graph()
        page = graphboard.to_html([loss])
        assert "<svg" in page
        # no external assets (image has no egress): no src= or CDN links
        assert "src=" not in page and "cdn" not in page.lower()

    def test_show_serves_and_close_stops(self):
        x, loss, _ = _small_graph()
        ex = ht.Executor({"f": [loss]})
        url = graphboard.show(ex, port=9941)
        body = urllib.request.urlopen(url, timeout=5).read().decode()
        assert "Matmul" in body
        graphboard.close()
