"""Tiered KV (ISSUE 17): the HBM -> host-RAM ring -> sharded-PS cold
store ladder behind the paged pool (serving/kv_tiers.py).

The acceptance spine: evicting a refcount-zero prefix SPILLS its int8
handoff wire payload down the ladder instead of dropping it, an
admission miss FETCHES it back up through ``import_blocks`` token-
identically, the directory's tier column keeps demoted prefixes
routable, and a chaos PS kill mid-traffic degrades the whole ladder to
today's drop-on-evict with zero request loss.  Around it: ring LRU
eviction order and host->PS demotion, the refresh-no-double-spill
ledger rule and its ``hetu_trace --check`` tier-balance twin
(synthetic violations + clean pass), the retire-path spill fallback
when no peer can absorb a hot prefix, ShardedPSClient kv_* round
trips, and both-knobs-off == byte-identical drop-on-evict.

All CPU-harness, all smoke-tier (tiny random-weight GPTs — the
contract is data movement and accounting, not model quality).
"""

import numpy as np
import pytest

import hetu_tpu as ht  # noqa: F401  (platform forcing + compat shims)
import jax.numpy as jnp
from hetu_tpu import telemetry
from hetu_tpu.models import GPTConfig
from hetu_tpu.models.gpt_decode import generate_fast
from hetu_tpu.ps import faults
from hetu_tpu.ps.server import PSServer
from hetu_tpu.ps.sharded import ShardedPSClient
from hetu_tpu.serving import (
    PagedKVManager, PrefixDirectory, Request, ServingEngine,
    ServingRouter, TieredKVStore, prefix_hash,
)
from hetu_tpu.serving.kv_tiers import PS_NAMESPACE
from hetu_tpu.serving.replica import RETIRED
from hetu_tpu.telemetry.trace import check_tier_balance, read_events

pytestmark = pytest.mark.smoke


def _rand_gpt(name="kt", L=2, H=2, Dh=8, V=61, S=32, seed=0):
    """Deterministic random params in generate_fast's naming contract
    (mirrors test_fleet_kv's helper; kept local so the files stay
    independently runnable)."""
    rng = np.random.RandomState(seed)
    hd = H * Dh
    p = {f"{name}_wte_table": rng.randn(V, hd) * 0.05,
         f"{name}_wpe": rng.randn(S, hd) * 0.05,
         f"{name}_ln_f_scale": np.ones(hd),
         f"{name}_ln_f_bias": np.zeros(hd)}
    for i in range(L):
        us = f"{name}_h{i}"
        for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                       ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                       ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
            p[f"{us}_{w}_weight"] = rng.randn(*shp) * 0.05
            p[f"{us}_{w}_bias"] = np.zeros(shp[1])
        for ln in ("ln1", "ln2"):
            p[f"{us}_{ln}_scale"] = np.ones(hd)
            p[f"{us}_{ln}_bias"] = np.zeros(hd)
    cfg = GPTConfig(vocab_size=V, hidden_size=hd, num_hidden_layers=L,
                    num_attention_heads=H, max_position_embeddings=S,
                    batch_size=1, seq_len=S, dropout_rate=0.0)
    return p, cfg


@pytest.fixture(scope="module")
def model():
    return _rand_gpt()


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.setenv("HETU_TELEMETRY", "1")
    monkeypatch.delenv("HETU_CHAOS", raising=False)
    monkeypatch.delenv("HETU_KV_HOST_BYTES", raising=False)
    monkeypatch.delenv("HETU_KV_PS_TIER", raising=False)
    faults.reset_plans()
    telemetry.reset()
    yield
    faults.reset_plans()
    telemetry.reset()


def _factory(model, **kw):
    p, cfg = model
    kw.setdefault("slots", 2)
    kw.setdefault("queue_limit", 16)
    kw.setdefault("fast_path", False)
    kw.setdefault("paged", True)
    kw.setdefault("kv_block", 8)
    kw.setdefault("prefix_share", True)
    return lambda i: ServingEngine(p, cfg, **kw)


def _offline(model, req):
    p, cfg = model
    return generate_fast(p, cfg, [req.prompt],
                         num_tokens=req.max_new_tokens)[0].tolist()


def _mgr(**kw):
    base = dict(layers=2, heads=2, head_dim=8, slots=2, max_seq_len=32,
                block=8, prefix_share=True)
    base.update(kw)
    return PagedKVManager(**base)


def _fill(m, seed=0):
    """Random content into EVERY pool block so gathered spans are
    distinguishable."""
    rng = np.random.RandomState(seed)

    def one(cache):
        if isinstance(cache, tuple):
            q = rng.randint(-127, 128, cache[0].shape).astype(np.int8)
            s = (rng.rand(*cache[1].shape) + 0.01).astype(np.float32)
            return (jnp.asarray(q), jnp.asarray(s))
        return jnp.asarray(rng.randn(*cache.shape).astype(np.float32))

    m.cache_k = one(m.cache_k)
    m.cache_v = one(m.cache_v)


def _register(m, prompt, rid="r0"):
    """Alloc + register + release so the prefix sits refcount-held in
    the pool's prefix cache with no live slot (the spillable state)."""
    slot, _ = m.alloc(rid, prompt, len(prompt))
    assert slot is not None
    m.advance(slot, len(prompt))
    m.register_prefix(prompt, slot)
    m.release(slot)
    return tuple(int(t) for t in prompt)


def _store(m, replica=0, **kw):
    """A wired store over one manager (attach sets the spill hook)."""
    st = TieredKVStore(**kw)
    st.attach(replica, m)
    return st


def _pay_eq(a, b):
    ka = a["k"][0] if isinstance(a["k"], tuple) else a["k"]
    kb = b["k"][0] if isinstance(b["k"], tuple) else b["k"]
    return (a["length"] == b["length"]
            and np.array_equal(np.asarray(ka), np.asarray(kb)))


# --------------------------------------------------------------------- #
# the ladder: spill/fetch round trips (tentpole)
# --------------------------------------------------------------------- #

class TestLadder:
    def test_evict_spills_to_host_and_fetch_is_identical(self):
        """LRU pressure spills the evicted prefix's payload into the
        host ring; the fetched payload is the byte-identical wire form
        the pool would have exported, and the ledger pairs one spill
        with one fetch."""
        m = _mgr(slots=2, max_seq_len=32)
        _fill(m, seed=1)
        st = _store(m, host_bytes=1 << 20)
        p1 = list(range(1, 9))
        toks = _register(m, p1, "a")
        ref = m.export_prefix(toks, count=False)
        # fill the pool with fresh prompts until p1's blocks evict
        nxt = 30
        while tuple(toks) in m._prefix:
            _register(m, [nxt + i for i in range(8)], f"f{nxt}")
            nxt += 10
        assert m.spills == 1 and st.spills["host"] == 1
        hit = st.lookup(p1 + [99], m.block)
        assert hit is not None and hit[0] == toks and hit[2] == "host"
        pay = st.fetch(toks)
        assert pay is not None and _pay_eq(pay, ref)
        assert st.fetches["host"] == 1
        assert st.lookup(p1 + [99], m.block) is None     # popped
        ev = [e for e in telemetry.get_sink().recent()
              if e.get("event") in ("kv_spill", "kv_fetch")]
        assert [e["event"] for e in ev] == ["kv_spill", "kv_fetch"]
        assert ev[0]["prefix"] == ev[1]["prefix"] == prefix_hash(toks)

    def test_ring_overflow_demotes_to_ps_in_lru_order(self):
        """A byte-capped ring demotes its OLDEST resident to the PS
        rung (insertion-ordered LRU); the demoted payload fetches back
        from the cold store intact, and the demotion is a counter, not
        a second ledger entry."""
        m = _mgr(slots=4, max_seq_len=32, pool_blocks=16)
        _fill(m, seed=2)
        probe = _register(m, list(range(1, 9)), "p")
        one_bytes = m.export_prefix(probe, count=False)["nbytes"]
        srv = PSServer()
        st = _store(m, host_bytes=2 * one_bytes, ps_tier=True,
                    ps=ShardedPSClient(servers=[srv]))
        pays, toks = {}, []
        for j in range(3):
            t = tuple(range(10 * j + 1, 10 * j + 9))
            pays[t] = m._export_span(
                np.asarray([j], np.int32), 8, None, count=False)
            assert st.spill(t, pays[t])
            toks.append(t)
        # oldest (toks[0]) demoted; two newest still in the ring
        assert st.demotes == 1 and st.spills == {"host": 3, "ps": 0}
        assert st.lookup(list(toks[0]) + [99], m.block)[2] == "ps"
        assert st.lookup(list(toks[1]) + [99], m.block)[2] == "host"
        assert srv.kv_keys() == [PS_NAMESPACE + prefix_hash(toks[0])]
        got = st.fetch(toks[0])
        assert got is not None and _pay_eq(got, pays[toks[0]])
        assert st.fetches == {"host": 0, "ps": 1}
        assert srv.kv_keys() == []                       # popped cold too
        st.close()
        bal = check_tier_balance(
            [e for e in telemetry.get_sink().recent()])
        assert bal == []                                 # demote != event

    def test_refresh_is_one_residency_one_ledger_entry(self):
        """Re-spilling a resident prefix refreshes its LRU stamp —
        refreshed entries outlive older unrefreshed ones — and emits
        NO second kv_spill (the tier-balance rule would flag it)."""
        m = _mgr()
        _fill(m, seed=3)
        probe = _register(m, list(range(1, 9)), "p")
        pay = m.export_prefix(probe, count=False)
        st = _store(m, host_bytes=2 * pay["nbytes"])
        a, b = tuple(range(1, 9)), tuple(range(11, 19))
        assert st.spill(a, pay) and st.spill(b, pay)
        assert st.spill(a, pay)                          # refresh a
        assert st.refreshes == 1 and st.spills["host"] == 2
        st.spill(tuple(range(21, 29)), pay)              # overflow: b dies
        assert st.lookup(list(a) + [99], m.block) is not None
        assert st.lookup(list(b) + [99], m.block) is None
        assert st.drops["host"] == 1                     # no PS rung
        st.close()
        assert check_tier_balance(
            [e for e in telemetry.get_sink().recent()]) == []

    def test_host_bytes_zero_is_byte_identical_drop_on_evict(self, model):
        """Both knobs off: from_env wires NOTHING — no store, no spill
        hook, no tier events, counters byte-identical to the pre-tier
        fleet."""
        assert TieredKVStore.from_env() is None
        router = ServingRouter(_factory(model), replicas=1)
        assert router.kv_tiers is None
        kv = router.replicas[0].engine.kv
        assert kv.on_prefix_spill is None and kv.tier_store is None
        res = router.run([Request(prompt=list(range(1, 12)) + [20 + i],
                                  max_new_tokens=3, request_id=f"z{i}")
                          for i in range(6)])
        assert len(res) == 6 and router.snapshot()["lost"] == 0
        assert router.snapshot()["kv_tiers"] is None
        assert kv.spills == 0
        assert not [e for e in telemetry.get_sink().recent()
                    if e.get("event", "").startswith("kv_spill")]


# --------------------------------------------------------------------- #
# fleet integration: storm -> spill -> tier fetch, token identity
# --------------------------------------------------------------------- #

class TestFleetTiering:
    def test_storm_tier_fetch_token_identical(self, model):
        """A working set larger than the pool: wave 1's prefixes evict
        to the host ring under wave 2's pressure; re-asking wave 1
        routes through the directory's tier column, admission fetches
        the span back, and outputs stay token-identical to offline."""
        store = TieredKVStore(host_bytes=8 << 20)
        router = ServingRouter(_factory(model, slots=2, pool_blocks=8),
                               replicas=1, kv_tiers=store)
        assert router.directory.tiered is True
        heads = [list(range(1, 9)),
                 [9, 10, 11, 12, 13, 14, 15, 16],
                 [17, 18, 19, 20, 21, 22, 23, 24],
                 [25, 26, 27, 28, 29, 30, 31, 32]]
        w1 = [Request(prompt=h + [40 + i], max_new_tokens=3,
                      request_id=f"s{i}", session_id=f"s{i}")
              for i, h in enumerate(heads)]
        res = dict(router.run(w1))
        # wave 2 re-asks the same heads from NEW sessions: the pool is
        # far too small to still hold them all, so the directory's
        # tier column routes at least one through the ladder
        w2 = [Request(prompt=h + [50 + i], max_new_tokens=3,
                      request_id=f"t{i}", session_id=f"t{i}")
              for i, h in enumerate(heads)]
        res.update(router.run(w2))
        reqs = w1 + w2
        assert router.snapshot()["lost"] == 0
        st = router.snapshot()["kv_tiers"]
        assert st["spills"]["host"] > 0
        assert st["fetches"]["host"] > 0                 # warmth came back
        assert router.directory.tier_hits > 0
        routes = [e for e in telemetry.get_sink().recent()
                  if e.get("event") == "router_route"]
        assert "tier" in {e.get("directory") for e in routes}
        for r in reqs:
            assert res[r.request_id].tokens.tolist() == _offline(model, r)
        kv = router.replicas[0].engine.kv
        assert kv.prefix_hit_tokens > 0                  # recompute saved
        store.close()
        assert check_tier_balance(
            [e for e in telemetry.get_sink().recent()]) == []

    def test_retire_with_no_peer_room_spills_not_drops(self, model):
        """The retire-path fix (satellite): when the best UP peer's
        pool has no room for the retiring replica's hot prefixes, the
        export falls back to a tier SPILL instead of dropping them —
        pre-tier behavior lost the warmth — and the replica_retired
        event counts the spills."""
        store = TieredKVStore(host_bytes=8 << 20)
        router = ServingRouter(_factory(model, slots=2, pool_blocks=8),
                               replicas=2, kv_tiers=store)
        head = list(range(1, 9))
        router.run([Request(prompt=head + [20 + i], max_new_tokens=3,
                            session_id="same") for i in range(3)])
        victim = next(r for r in router.replicas
                      if r.engine.kv._prefix)
        peer = next(r for r in router.replicas
                    if r.index != victim.index)
        # wedge the peer's pool: live slots pin every block and slot,
        # so the retire-path prefix ship cannot land there
        kvp = peer.engine.kv
        pin = 0
        while kvp._free_slots:
            slot, _ = kvp.alloc(f"pin{pin}", [100 + pin], 8)
            if slot is None:
                break
            pin += 1
        assert not kvp._free_slots
        router.retire_replica(victim.index, reason="scale_down")
        assert router.replicas[victim.index].state == RETIRED
        assert store.spills["host"] > 0
        assert store.lookup(head + [99], 8) is not None  # still warm
        retired = [e for e in telemetry.get_sink().recent()
                   if e.get("event") == "replica_retired"]
        assert retired and retired[-1]["spilled_prefixes"] > 0
        assert retired[-1]["exported_prefixes"] == 0

    def test_ps_chaos_kill_degrades_to_drop_with_zero_loss(
            self, model, monkeypatch, tmp_path):
        """A seeded chaos kill at the PS rung mid-storm: resident cold
        entries take their terminal drops, future spills stop at the
        host ring, the fleet loses ZERO requests and stays token-
        identical, and the kill is recorded (failure event + flight
        dump + ps_dead in the snapshot)."""
        flog = str(tmp_path / "failure.jsonl")
        monkeypatch.setenv("HETU_FAILURE_LOG", flog)
        monkeypatch.setenv("HETU_FLIGHT_LOG",
                           str(tmp_path / "flight.jsonl"))
        monkeypatch.setenv("HETU_CHAOS", "seed=3,kill=2,role=kvtier")
        faults.reset_plans()
        srv = PSServer()
        store = TieredKVStore(host_bytes=1, ps_tier=True,
                              ps=ShardedPSClient(servers=[srv]))
        router = ServingRouter(_factory(model, slots=2, pool_blocks=8),
                               replicas=1, kv_tiers=store)
        heads = [list(range(8 * j + 1, 8 * j + 9)) for j in range(4)]
        reqs = [Request(prompt=h + [40 + i], max_new_tokens=3,
                        request_id=f"c{i}", session_id=f"c{i}")
                for i, h in enumerate(heads * 2)]
        res = router.run(reqs)
        assert router.snapshot()["lost"] == 0 and len(res) == len(reqs)
        for r in reqs:
            assert res[r.request_id].tokens.tolist() == _offline(model, r)
        st = router.snapshot()["kv_tiers"]
        assert st["ps_dead"] is True and st["ps_entries"] == 0
        # the kill must degrade the TIER, not crash the engine it was
        # spilling for — no replica death/respawn rides along
        assert all(x["restarts"] == 0
                   for x in router.snapshot()["replicas"])
        events, bad = read_events([flog])
        assert bad == 0
        assert [e for e in events
                if e.get("event") == "kvtier_ps_killed"]
        store.close()
        assert check_tier_balance(
            [e for e in telemetry.get_sink().recent()]) == []

    def test_ring_corruption_degrades_to_cold_admit(self, monkeypatch):
        """A drawn drop at the ring-read seam: the corrupted entry is
        dropped (never landed into a pool), counted, and the fetch
        degrades to a miss — the ledger still balances."""
        monkeypatch.setenv("HETU_CHAOS", "seed=1,drop=1.0,role=kvtier")
        faults.reset_plans()
        m = _mgr()
        _fill(m, seed=4)
        toks = _register(m, list(range(1, 9)), "p")
        pay = m.export_prefix(toks, count=False)
        st = _store(m, host_bytes=1 << 20)
        assert st.spill(toks, pay)
        assert st.fetch(toks) is None                    # corrupted
        assert st.corruptions == 1 and st.drops["host"] == 1
        assert check_tier_balance(
            [e for e in telemetry.get_sink().recent()]) == []


# --------------------------------------------------------------------- #
# directory tier column (satellite)
# --------------------------------------------------------------------- #

class TestDirectoryTierColumn:
    def test_evict_demotes_then_clear_deletes(self):
        """With tiering on, the last holder's eviction DEMOTES a tier-
        stamped entry (still routable via the tier verdict) instead of
        deleting it; clear_tier restores delete semantics."""
        d = PrefixDirectory()
        d.tiered = True
        d._block = 8
        toks = tuple(range(1, 9))
        d.register(0, toks)
        d.set_tier(toks, "host")
        d.evict(0, toks)
        assert d.demotions == 1 and d.known(toks)
        hint, outcome = d.lookup(list(toks) + [99])
        assert outcome == "tier" and hint == (None, 8)
        assert d.tier_hits == 1
        snap = d.snapshot()
        assert snap["tiered"] is True and snap["tier_entries"] == 1
        d.clear_tier(toks)
        assert not d.known(toks)
        assert d.lookup(list(toks) + [99])[1] == "miss"

    def test_tiering_off_keeps_delete_semantics(self):
        """The stock directory (tiered=False) deletes on last-holder
        eviction even when a tier stamp exists — satellite back-compat
        guarantee."""
        d = PrefixDirectory()
        d._block = 8
        toks = tuple(range(1, 9))
        d.register(0, toks)
        d.set_tier(toks, "host")
        d.evict(0, toks)
        assert not d.known(toks) and d.demotions == 0

    def test_fresh_holder_beats_tier_column(self):
        """A live replica claim wins over the tier column — the tier
        verdict only fires when NO pool holds the cut."""
        d = PrefixDirectory()
        d.tiered = True
        d._block = 8
        toks = tuple(range(1, 9))
        d.register(1, toks)
        d.set_tier(toks, "ps")
        hint, outcome = d.lookup(list(toks) + [99])
        assert outcome is None and hint == (1, 8)
        d.drop_replica(1)
        assert d.known(toks)                             # tier survives
        assert d.lookup(list(toks) + [99])[1] == "tier"


# --------------------------------------------------------------------- #
# the trace rule (satellite)
# --------------------------------------------------------------------- #

def _ev(kind, h, tier="host"):
    e = {"event": kind, "prefix": h, "tier": tier, "t": 0.0}
    if kind != "kv_tier_drop":
        e["length"] = 8
    return e


class TestTierBalanceRule:
    def test_clean_ledger_passes(self):
        evs = [_ev("kv_spill", "a"), _ev("kv_fetch", "a"),
               _ev("kv_spill", "b"), _ev("kv_tier_drop", "b"),
               _ev("kv_spill", "a"), _ev("kv_fetch", "a")]
        assert check_tier_balance(evs) == []

    def test_double_spill_is_violation(self):
        evs = [_ev("kv_spill", "a"), _ev("kv_spill", "a"),
               _ev("kv_fetch", "a"), _ev("kv_fetch", "a")]
        out = check_tier_balance(evs)
        assert len(out) == 1 and "already tier-resident" in out[0]

    def test_fetch_without_spill_is_violation(self):
        out = check_tier_balance([_ev("kv_fetch", "a")])
        assert len(out) == 1 and "no open tier residency" in out[0]

    def test_open_residency_at_end_is_violation(self):
        out = check_tier_balance([_ev("kv_spill", "a")])
        assert len(out) == 1 and "still tier-resident" in out[0]

    def test_flight_dump_stream_exempt(self):
        evs = [{"event": "flight_dump", "reason": "x", "t": 0.0},
               _ev("kv_fetch", "a")]
        assert check_tier_balance(evs) == []

    def test_cli_reports_tier_violations(self, tmp_path, capsys):
        import json
        from hetu_tpu.telemetry import trace
        log = tmp_path / "serve.jsonl"
        log.write_text(json.dumps(
            {"event": "kv_spill", "prefix": "a", "tier": "host",
             "length": 8, "t": 0.0}) + "\n")
        rc = trace.main([str(log), "--check"])
        out = capsys.readouterr().out
        assert rc == 1
        assert json.loads(out.strip().splitlines()[-1])[
            "tier_balance_violations"] == 1


# --------------------------------------------------------------------- #
# PS cold store plumbing (sharded client)
# --------------------------------------------------------------------- #

class TestPSColdStore:
    def test_sharded_kv_round_trip_and_keys(self):
        """kv_put/get/del route whole by key hash across two local
        servers; kv_keys unions the shards without replica keys."""
        servers = [PSServer(), PSServer()]
        cli = ShardedPSClient(servers=servers)
        pay = {"nbytes": 4, "length": 8, "k": [1], "v": [2]}
        assert cli.kv_put("__kvcold__x", pay, version=3)
        got = cli.kv_get("__kvcold__x")
        assert got is not None
        assert got[0]["k"] == [1] and int(got[1]) == 3
        assert cli.kv_get("__kvcold__missing") is None
        assert cli.kv_keys() == ["__kvcold__x"]
        assert sum(len(s.kv_cold) for s in servers) == 1  # one home
        assert cli.kv_del("__kvcold__x") is True
        assert cli.kv_del("__kvcold__x") is False
        assert cli.kv_keys() == []

    def test_version_skew_refuses_stale_cold_entry(self):
        """A cold entry overwritten behind the store's back (version
        mismatch) is refused at fetch — dropped, never landed."""
        m = _mgr()
        _fill(m, seed=5)
        toks = _register(m, list(range(1, 9)), "p")
        pay = m.export_prefix(toks, count=False)
        srv = PSServer()
        st = _store(m, host_bytes=0, ps_tier=True,
                    ps=ShardedPSClient(servers=[srv]))
        assert st.spill(toks, pay)
        assert st.spills["ps"] == 1
        key = PS_NAMESPACE + prefix_hash(toks)
        srv.kv_put(key, pay, version=999)                # intruder write
        assert st.fetch(toks) is None
        assert st.drops["ps"] == 1 and st.fetches["ps"] == 0
        assert check_tier_balance(
            [e for e in telemetry.get_sink().recent()]) == []

    def test_close_terminates_all_residencies(self):
        """close() gives every resident its terminal drop (host + PS)
        and best-effort deletes the cold blobs — a completed run's
        ledger balances by construction."""
        m = _mgr()
        _fill(m, seed=6)
        probe = _register(m, list(range(1, 9)), "p")
        pay = m.export_prefix(probe, count=False)
        srv = PSServer()
        st = _store(m, host_bytes=pay["nbytes"], ps_tier=True,
                    ps=ShardedPSClient(servers=[srv]))
        # two spills through the public path: the second overflows the
        # one-entry ring, demoting the first to the cold store
        assert st.spill(tuple(range(1, 9)), pay)
        assert st.spill(tuple(range(11, 19)), pay)
        assert st.demotes == 1 and st.stats()["ps_entries"] == 1
        assert srv.kv_keys() != []
        st.close()
        assert st.stats()["host_entries"] == 0
        assert st.stats()["ps_entries"] == 0
        assert srv.kv_keys() == []
        assert check_tier_balance(
            [e for e in telemetry.get_sink().recent()]) == []
