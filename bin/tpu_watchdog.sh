#!/bin/bash
# TPU-recovery watchdog: probe the backend periodically and fire the
# on-chip measurement battery (bin/run_onchip_suite.sh) unattended on
# the first successful probe.  Exists because two rounds of tunnel
# outage were missed for want of someone watching (VERDICT r4 item 1):
# a recovery window mid-outage must trigger capture automatically.
#
#   nohup bash bin/tpu_watchdog.sh [interval_s] [logdir] &
#
# Idempotent/safe: run_onchip_suite.sh itself holds a flock on
# .tpu_watchdog.lock, so watchdog-fired and manual suite runs are
# serialized at the one place that matters; a completed capture writes
# .tpu_watchdog.done and the watchdog exits.  Remove the .done file to
# arm it again.
set -u
cd "$(dirname "$0")/.."
INTERVAL=${1:-600}
LOGDIR=${2:-/tmp/onchip_watchdog}
# each firing is a multi-hour battery on the one chip: if validation
# keeps failing (e.g. the bert_base stage errors on-chip), stop after a
# few attempts instead of monopolizing the chip forever
MAX_FIRES=${MAX_FIRES:-3}
LOCK=.tpu_watchdog.lock
# DONE_FILE / SUITE_CMD / PROBE_CMD are overridable for the scripted
# self-test (tests/test_bin_tools.py) — production runs use defaults
DONE=${DONE_FILE:-.tpu_watchdog.done}
SUITE=${SUITE_CMD:-bash bin/run_onchip_suite.sh}
mkdir -p "$LOGDIR"
fires=0

probe() {
  # a wedged tunnel HANGS rather than erroring — bound the probe hard.
  # The device_kind read forces a real backend round-trip, not just
  # plugin discovery.
  if [ -n "${PROBE_CMD:-}" ]; then eval "$PROBE_CMD"; return $?; fi
  timeout -k 10 120 python - <<'EOF' >/dev/null 2>&1
import jax
d = jax.devices()[0]
assert d.platform == "tpu", d.platform
_ = d.device_kind
EOF
}

# A validated capture = the bert_base ROW was freshly measured on-chip
# at full scale since this watchdog started.  Judge the row only — its
# own stamp, device_kind, and scale: bench.py merge-preserves rows from
# older runs, and trailing subset stages rewrite top-level platform and
# measured_at last-writer-wins, so the top-level fields say nothing
# about this row.  Checked BEFORE firing too, so a manual suite run
# that already banked a fresh capture disarms the watchdog instead of
# triggering a redundant multi-hour battery.
validated() {
  [ "$(stat -c %Y BENCH_MATRIX.json 2>/dev/null || echo 0)" \
    -gt "$START_TS" ] || return 1
  START_TS="$START_TS" python - <<'EOF'
import json, os, sys
from datetime import datetime, timezone
m = json.load(open("BENCH_MATRIX.json"))
bert = m.get("configs", {}).get("bert_base", {})
measured = datetime.strptime(
    bert.get("measured_at", "1970-01-01 00:00 UTC"), "%Y-%m-%d %H:%M %Z"
).replace(tzinfo=timezone.utc).timestamp()
ok = ("error" not in bert and bert.get("value")
      and bert.get("device_kind", "").startswith("TPU")
      and not bert.get("reduced_scale")
      and measured >= float(os.environ["START_TS"]) - 60)
sys.exit(0 if ok else 1)
EOF
}

echo "watchdog: probing every ${INTERVAL}s (logs: $LOGDIR)"
START_TS=$(date +%s)
while true; do
  if [ -f "$DONE" ]; then
    echo "watchdog: capture already recorded ($DONE) — exiting"
    exit 0
  fi
  if validated; then
    date -u +%FT%TZ > "$DONE"
    echo "watchdog: fresh on-chip capture already in the matrix — done"
    exit 0
  fi
  if probe; then
    echo "watchdog: backend up at $(date -u +%FT%TZ) — firing suite"
    # the suite itself holds the one flock ($LOCK): a manual run in
    # progress makes it refuse (rc=75) and we just re-probe later
    $SUITE "$LOGDIR/suite_$(date -u +%m%d_%H%M)"
    rc=$?
    if [ "$rc" -eq 0 ]; then
      # run() swallows stage rcs, so suite rc=0 means only "the script
      # finished" — validated() decides whether the capture is real (a
      # false .done would disarm the watchdog forever, re-creating the
      # missed-window failure this script prevents)
      if validated; then
        date -u +%FT%TZ > "$DONE"
        echo "watchdog: tpu matrix captured — done"
        exit 0
      fi
      echo "watchdog: suite ran but matrix lacks a fresh on-chip" \
           "bert_base row; re-arming"
    fi
    # ONLY the suite's distinctive flock-refusal code (75) is "not an
    # attempt"; any other nonzero (including a genuine early exit-1,
    # e.g. a set -u abort) must count toward MAX_FIRES or the watchdog
    # would re-fire the multi-hour battery forever
    if [ "$rc" -ne 75 ]; then
      fires=$((fires + 1))
      if [ "$fires" -ge "$MAX_FIRES" ]; then
        echo "watchdog: $fires suite firings without a validated" \
             "capture — giving up (read $LOGDIR, fix, restart)" >&2
        exit 2
      fi
    fi
  fi
  sleep "$INTERVAL"
done
