#!/usr/bin/env python
"""Repo lint gate CLI (hetu_tpu/analysis/lint.py rules).

    python bin/hetu_lint.py hetu_tpu/ bench.py      # lint, exit != 0 on findings
    python bin/hetu_lint.py --env-table             # HETU_* doc table (markdown)
    python bin/hetu_lint.py --rules env-registry hetu_tpu/

Runs without jax/device initialization: the rules are pure-AST, so this
is safe (and fast) as the first stage of the on-chip suite.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hetu_tpu.analysis.lint import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
