#!/usr/bin/env python
"""CLI for the telemetry streams: merge/tail JSONL, export Perfetto
traces, check the event contract.  Logic lives in
hetu_tpu/telemetry/trace.py; see its docstring for the format."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from hetu_tpu.telemetry.trace import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
