#!/usr/bin/env python
"""Live terminal dashboard for the telemetry streams: occupancy,
queue depth, KV pool, TTFT/TPOT percentiles, SLO health; --fleet adds
per-replica role + directory hit-rate columns and fleet prefix/handoff
totals.  Logic lives in hetu_tpu/telemetry/top.py; see its docstring
for the panels."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from hetu_tpu.telemetry.top import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
