#!/bin/bash
# The on-chip measurement battery, in priority order (VERDICT r3 items
# 1/2/4/5/6 measurement halves; see round4 COMPONENTS.md closure table).
# Run when a TPU answers; every stage is guarded against clobbering
# full-scale records with degraded runs, so re-running is always safe.
#
#   bash bin/run_onchip_suite.sh [logdir]
set -u
cd "$(dirname "$0")/.."
# one suite at a time: manual runs and the watchdog (bin/tpu_watchdog.sh)
# share this lock — two concurrent batteries would interleave matrix
# writes and contend for the single chip
exec 9>.tpu_watchdog.lock
if ! flock -n 9; then
  echo "another on-chip suite holds .tpu_watchdog.lock — refusing to" \
       "run concurrently" >&2
  # distinctive code (EX_TEMPFAIL): the watchdog must distinguish "lock
  # held, not an attempt" from a genuine early failure (exit 1), which
  # MUST count toward its MAX_FIRES retry cap
  exit 75
fi
LOG=${1:-/tmp/onchip_$(date -u +%H%M)}
mkdir -p "$LOG"
echo "logging to $LOG"

run() {  # name, timeout_s, cmd... — a re-wedged tunnel mid-stage must
  local name=$1; shift       # cost ONE stage, not the whole recovery
  local budget=$1; shift     # window (every stage is rerunnable)
  echo "=== $name (<=${budget}s): $* ==="
  (time timeout -k 60 "$budget" "$@") >"$LOG/$name.log" 2>&1
  local rc=$?
  tail -2 "$LOG/$name.log"
  echo "=== $name rc=$rc ==="
}

# 00. static gate: lint + a build-time verification pass, BEFORE any
#     chip time.  The lint is pure-AST (no jax init) and the verifier
#     builds/validates a representative graph on CPU in seconds; a
#     miswired tree must cost this stage, not a TPU allocation.
run lint 300 python bin/hetu_lint.py hetu_tpu/ bench.py bin/
if grep -q 'finding(s)' "$LOG/lint.log"; then
  echo "lint gate FAILED — fix findings before burning chip time" >&2
  exit 1
fi
run verify 600 env HETU_VALIDATE=1 JAX_PLATFORMS=cpu python - <<'PYEOF'
import numpy as np, hetu_tpu as ht
x = ht.placeholder_op("x")
w = ht.init.xavier_uniform((64, 64), name="vg_w")
h = ht.relu_op(ht.matmul_op(x, w))
loss = ht.reduce_mean_op(ht.reduce_mean_op(h, axes=1), axes=0)
train = ht.optim.AdamOptimizer(learning_rate=1e-3).minimize(loss)
ex = ht.Executor({"train": [loss, train]})
ex.run("train", feed_dict={x: np.ones((8, 64), np.float32)})
print("verify gate OK")
PYEOF
if ! grep -q 'verify gate OK' "$LOG/verify.log"; then
  echo "verification gate FAILED — see $LOG/verify.log" >&2
  exit 1
fi

# 00b. telemetry gate: one instrumented CPU train step + the event
#      pipeline end to end — spans land in the merged JSONL, the
#      contract checks clean, and bin/hetu_trace.py exports a loadable
#      Perfetto trace.  Measurement plumbing is proven BEFORE any chip
#      time; the exported trace is the window's first artifact.
run telemetry 600 env HETU_TELEMETRY=1 \
    HETU_TELEMETRY_LOG="$LOG/telemetry.jsonl" JAX_PLATFORMS=cpu \
    python - <<'PYEOF'
import numpy as np, hetu_tpu as ht
x = ht.placeholder_op("x")
w = ht.init.xavier_uniform((64, 64), name="tg_w")
h = ht.relu_op(ht.matmul_op(x, w))
loss = ht.reduce_mean_op(ht.reduce_mean_op(h, axes=1), axes=0)
train = ht.optim.AdamOptimizer(learning_rate=1e-3).minimize(loss)
ex = ht.Executor({"train": [loss, train]})
for _ in range(3):
    ex.run("train", feed_dict={x: np.ones((8, 64), np.float32)})
from hetu_tpu import telemetry
snap = telemetry.snapshot()
assert snap["counters"].get("exec.steps") == 3, snap["counters"]
print("telemetry gate OK")
PYEOF
if ! grep -q 'telemetry gate OK' "$LOG/telemetry.log"; then
  echo "telemetry gate FAILED — see $LOG/telemetry.log" >&2
  exit 1
fi
run trace_export 300 python bin/hetu_trace.py "$LOG/telemetry.jsonl" \
    --export "$LOG/trace.json"
if ! python -c "
import json
t = json.load(open('$LOG/trace.json'))
spans = [e for e in t['traceEvents'] if e.get('ph') == 'X']
assert spans, 'exported trace has no duration events'
print('trace artifact OK:', len(t['traceEvents']), 'events,',
      len(spans), 'spans')
"; then
  echo "trace-artifact sanity check FAILED — see $LOG/trace.json" >&2
  exit 1
fi
python bin/hetu_trace.py "$LOG/telemetry.jsonl" --check \
    > "$LOG/trace_contract.log" || {
  echo "event-contract check FAILED — see $LOG/trace_contract.log" >&2
  exit 1
}

# 00c. request-observability gate: a tiny CPU serving trace-replay must
#      produce a balanced request stream (every admit has its retire —
#      hetu_trace --check's span-balance rule), per-request lifecycle
#      spans, and an exportable trace with request tracks, BEFORE any
#      chip-time serving stage trusts those records.
run serve_trace 600 env HETU_TELEMETRY=1 \
    HETU_TELEMETRY_LOG="$LOG/serve_trace.jsonl" JAX_PLATFORMS=cpu \
    python - <<'PYEOF'
import numpy as np
import hetu_tpu as ht  # noqa: F401
from hetu_tpu.models import GPTConfig
from hetu_tpu.serving import Request, ServingEngine

rng, hd = np.random.RandomState(0), 16
p = {"og_wte_table": rng.randn(61, hd) * 0.05,
     "og_wpe": rng.randn(32, hd) * 0.05,
     "og_ln_f_scale": np.ones(hd), "og_ln_f_bias": np.zeros(hd)}
for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
               ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
               ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
    p[f"og_h0_{w}_weight"] = rng.randn(*shp) * 0.05
    p[f"og_h0_{w}_bias"] = np.zeros(shp[1])
for ln in ("ln1", "ln2"):
    p[f"og_h0_{ln}_scale"] = np.ones(hd)
    p[f"og_h0_{ln}_bias"] = np.zeros(hd)
cfg = GPTConfig(vocab_size=61, hidden_size=hd, num_hidden_layers=1,
                num_attention_heads=2, max_position_embeddings=32,
                batch_size=1, seq_len=32, dropout_rate=0.0)
eng = ServingEngine(p, cfg, slots=2, fast_path=False)
res = eng.run([Request(prompt=[7, 8, 9], max_new_tokens=4, seed=s)
               for s in range(3)])
assert len(res) == 3
assert eng.metrics.explain_tail() is not None
print("serve trace gate OK")
PYEOF
if ! grep -q 'serve trace gate OK' "$LOG/serve_trace.log"; then
  echo "serving trace gate FAILED — see $LOG/serve_trace.log" >&2
  exit 1
fi
python bin/hetu_trace.py "$LOG/serve_trace.jsonl" --check \
    > "$LOG/serve_trace_contract.log" || {
  echo "serving span-balance/contract check FAILED — see" \
       "$LOG/serve_trace_contract.log" >&2
  exit 1
}
run serve_trace_export 300 python bin/hetu_trace.py \
    "$LOG/serve_trace.jsonl" --export "$LOG/serve_trace_export.json"

# 00d. router trace-replay gate: an N=2 CPU fleet with a seeded chaos
#      kill of one replica mid-trace must retire EVERY request exactly
#      once (requeued to the peer, never lost), leave contract-valid
#      failure events + a flight dump on the killed replica, and a
#      serve stream that passes the fleet span-balance rule — the
#      router's robustness contract proven BEFORE chip-time serving.
run router_trace 600 env HETU_TELEMETRY=1 \
    HETU_TELEMETRY_LOG="$LOG/router_trace.jsonl" \
    HETU_FAILURE_LOG="$LOG/router_failure.jsonl" \
    HETU_FLIGHT_LOG="$LOG/router_flight.jsonl" \
    HETU_CHAOS="seed=3,kill=4,role=replica1" JAX_PLATFORMS=cpu \
    python - <<'PYEOF'
import numpy as np
import hetu_tpu as ht  # noqa: F401
from hetu_tpu.models import GPTConfig
from hetu_tpu.serving import Request, ServingEngine, ServingRouter

rng, hd = np.random.RandomState(0), 16
p = {"rg_wte_table": rng.randn(61, hd) * 0.05,
     "rg_wpe": rng.randn(32, hd) * 0.05,
     "rg_ln_f_scale": np.ones(hd), "rg_ln_f_bias": np.zeros(hd)}
for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
               ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
               ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
    p[f"rg_h0_{w}_weight"] = rng.randn(*shp) * 0.05
    p[f"rg_h0_{w}_bias"] = np.zeros(shp[1])
for ln in ("ln1", "ln2"):
    p[f"rg_h0_{ln}_scale"] = np.ones(hd)
    p[f"rg_h0_{ln}_bias"] = np.zeros(hd)
cfg = GPTConfig(vocab_size=61, hidden_size=hd, num_hidden_layers=1,
                num_attention_heads=2, max_position_embeddings=32,
                batch_size=1, seq_len=32, dropout_rate=0.0)
router = ServingRouter(
    lambda i: ServingEngine(p, cfg, slots=2, fast_path=False),
    replicas=2, restart_backoff=0.01)
treq = np.random.RandomState(11)
reqs = [Request(prompt=[int(t) for t in treq.randint(0, 61, 3)],
                max_new_tokens=4, seed=s) for s in range(8)]
res = router.run(reqs)
snap = router.snapshot()
assert len(res) == 8, f"retired {len(res)}/8"
assert snap["lost"] == 0 and snap["duplicates"] == 0, snap
assert snap["requeued"] >= 1, "the kill never cost a requeue?"
print("router gate OK: finished", snap["finished"],
      "requeued", snap["requeued"])
PYEOF
if ! grep -q 'router gate OK' "$LOG/router_trace.log"; then
  echo "router fleet gate FAILED — see $LOG/router_trace.log" >&2
  exit 1
fi
python bin/hetu_trace.py "$LOG/router_trace.jsonl" \
    "$LOG/router_failure.jsonl" --check \
    > "$LOG/router_trace_contract.log" || {
  echo "router span-balance/contract check FAILED — see" \
       "$LOG/router_trace_contract.log" >&2
  exit 1
}
python bin/hetu_trace.py "$LOG/router_flight.jsonl" --check \
    > "$LOG/router_flight_contract.log" || {
  echo "router flight-dump contract check FAILED — see" \
       "$LOG/router_flight_contract.log" >&2
  exit 1
}

# 00e. fleet-KV gate (ISSUE 12): a role-split N=2 CPU fleet with the
#      prefix directory on and a seeded chaos kill of the DIRECTORY
#      mid-trace must retire every request token-identical to offline
#      generate_fast (the handoff payloads in flight still land; the
#      fleet degrades to PR 8 affinity routing), record the kill
#      (failure event + flight dump), and leave a serve stream that
#      passes the KV-handoff pairing rule (hetu_trace --check: every
#      kv_handoff_out has its kv_handoff_in, one retirement per
#      admission) — the fleet-KV contract proven before chip time.
run fleet_kv_gate 600 env HETU_TELEMETRY=1 \
    HETU_TELEMETRY_LOG="$LOG/fleet_kv_trace.jsonl" \
    HETU_FAILURE_LOG="$LOG/fleet_kv_failure.jsonl" \
    HETU_FLIGHT_LOG="$LOG/fleet_kv_flight.jsonl" \
    HETU_CHAOS="seed=5,kill=3,role=directory" JAX_PLATFORMS=cpu \
    python - <<'PYEOF'
import numpy as np
import hetu_tpu as ht  # noqa: F401
from hetu_tpu.models import GPTConfig
from hetu_tpu.models.gpt_decode import generate_fast
from hetu_tpu.serving import Request, ServingEngine, ServingRouter

rng, hd = np.random.RandomState(0), 16
p = {"fg_wte_table": rng.randn(61, hd) * 0.05,
     "fg_wpe": rng.randn(32, hd) * 0.05,
     "fg_ln_f_scale": np.ones(hd), "fg_ln_f_bias": np.zeros(hd)}
for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
               ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
               ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
    p[f"fg_h0_{w}_weight"] = rng.randn(*shp) * 0.05
    p[f"fg_h0_{w}_bias"] = np.zeros(shp[1])
for ln in ("ln1", "ln2"):
    p[f"fg_h0_{ln}_scale"] = np.ones(hd)
    p[f"fg_h0_{ln}_bias"] = np.zeros(hd)
cfg = GPTConfig(vocab_size=61, hidden_size=hd, num_hidden_layers=1,
                num_attention_heads=2, max_position_embeddings=32,
                batch_size=1, seq_len=32, dropout_rate=0.0)
router = ServingRouter(
    lambda i: ServingEngine(p, cfg, slots=2, fast_path=False,
                            paged=True, kv_block=8, prefix_share=True),
    replicas=2, roles="prefill,decode")
sys_p = list(range(1, 18))          # shared long prompt (> one block)
reqs = [Request(prompt=sys_p + [20 + i], max_new_tokens=4,
                session_id=f"t{i}") for i in range(10)]
res = {}
for i in range(0, 10, 5):           # two waves: warm, then consult
    res.update(router.run(reqs[i:i + 5]))
snap = router.snapshot()
assert len(res) == 10, f"retired {len(res)}/10"
assert snap["lost"] == 0 and snap["duplicates"] == 0, snap
assert snap["directory_killed"], "the chaos kill never fired"
assert snap["handoffs"] > 0, "role-split fleet moved zero KV spans"
for r in reqs:                      # zero token loss, bit-for-bit
    want = generate_fast(p, cfg, [r.prompt], num_tokens=4)[0].tolist()
    got = res[r.request_id].tokens.tolist()
    assert got == want, (r.request_id, got, want)
print("fleet kv gate OK: finished", snap["finished"],
      "handoffs", snap["handoffs"], "killed", snap["directory_killed"])
PYEOF
if ! grep -q 'fleet kv gate OK' "$LOG/fleet_kv_gate.log"; then
  echo "fleet KV gate FAILED — see $LOG/fleet_kv_gate.log" >&2
  exit 1
fi
python bin/hetu_trace.py "$LOG/fleet_kv_trace.jsonl" \
    "$LOG/fleet_kv_failure.jsonl" --check \
    > "$LOG/fleet_kv_contract.log" || {
  echo "fleet KV handoff/contract check FAILED — see" \
       "$LOG/fleet_kv_contract.log" >&2
  exit 1
}
python bin/hetu_trace.py "$LOG/fleet_kv_flight.jsonl" --check \
    > "$LOG/fleet_kv_flight_contract.log" || {
  echo "fleet KV flight-dump contract check FAILED — see" \
       "$LOG/fleet_kv_flight_contract.log" >&2
  exit 1
}

# 00f. embedding-serving gate (ISSUE 14): a zipf(1.05) CTR trace
#      replayed through the cache-fronted EmbedServingEngine on CPU,
#      with the PS killed for the middle third of the trace — every
#      request must still score (stale hits + zero-vector misses,
#      ZERO loss), the cache counters must show the outage engaged,
#      and the merged serve stream must pass hetu_trace --check
#      including the gather span-balance rule — the second workload's
#      contract proven before any chip time.
run embed_serve_gate 600 env HETU_TELEMETRY=1 \
    HETU_TELEMETRY_LOG="$LOG/embed_trace.jsonl" \
    JAX_PLATFORMS=cpu \
    python - <<'PYEOF'
import numpy as np
import hetu_tpu as ht  # noqa: F401
from hetu_tpu.cache.cstable import CacheSparseTable
from hetu_tpu.ps.client import PSConnectionError
from hetu_tpu.ps.server import PSServer
from hetu_tpu.serving import EmbedRequest, EmbedServingEngine


class KillablePS:
    def __init__(self, server):
        self._server, self.down = server, False

    def __getattr__(self, name):
        fn = getattr(self._server, name)

        def w(*a, **kw):
            if self.down:
                raise PSConnectionError("PS down (chaos)")
            return fn(*a, **kw)
        return w


server = PSServer()
server.param_init("snd_order_embedding", (512, 8), "normal", 0.0, 1.0,
                  seed=3)
comm = KillablePS(server)
table = CacheSparseTable(limit=128, vocab_size=512, width=8,
                         key="snd_order_embedding", comm=comm,
                         policy="LRU")
rng = np.random.RandomState(0)
params = {"W1": rng.randn(13, 16) * .3, "W2": rng.randn(16, 16) * .3,
          "W3": rng.randn(16, 16) * .3,
          "W4": rng.randn(26 * 8 + 16, 1) * .3}
eng = EmbedServingEngine(params, {"snd_order_embedding": table},
                         model="wdl", wave=4, queue_limit=64)
treq = np.random.RandomState(42)
reqs = [EmbedRequest(item_ids=(treq.zipf(1.05, (2, 26)) - 1) % 512,
                     dense_features=treq.randn(2, 13).astype(np.float32))
        for _ in range(30)]
res = {}
res.update(eng.run(reqs[:10]))        # warm
comm.down = True                      # mid-trace PS kill
res.update(eng.run(reqs[10:20]))      # dark: stale/zero, zero loss
comm.down = False                     # recovery
res.update(eng.run(reqs[20:]))
s = table.perf_summary()
assert len(res) == 30, f"retired {len(res)}/30"
assert all(r.finish_reason == "scored" for r in res.values())
assert s["ps_failures"] > 0, "the kill never fired"
assert s["stale_served_rows"] + s["zero_served_rows"] > 0, s
assert s["hit_rate"] > 0.2, s
snap = eng.metrics.snapshot()
assert snap["requests_finished"] == 30, snap
print("embed serve gate OK: scored", snap["requests_finished"],
      "hit_rate", round(s["hit_rate"], 3),
      "ps_failures", s["ps_failures"])
PYEOF
if ! grep -q 'embed serve gate OK' "$LOG/embed_serve_gate.log"; then
  echo "embed serving gate FAILED — see $LOG/embed_serve_gate.log" >&2
  exit 1
fi
python bin/hetu_trace.py "$LOG/embed_trace.jsonl" --check \
    > "$LOG/embed_serve_contract.log" || {
  echo "embed serve span/gather contract check FAILED — see" \
       "$LOG/embed_serve_contract.log" >&2
  exit 1
}

# 00g. rolling-swap gate (ISSUE 15): an N=2 CPU fleet runs TWO v1 -> v2
#      rollouts mid-trace in one process.  The first is chaos-killed
#      mid-drain (HETU_CHAOS role=swap) and must fail CLEANLY — zero
#      request loss, fleet back on v1 (the corpse respawns on the
#      committed version), a flight dump holding the swap timeline.
#      The chaos kill is one-shot, so the second rollout must LAND:
#      fleet on v2, every Result version-stamped, and a trace stream
#      that passes the span-balance AND version-coherence rules.
run swap_gate 600 env HETU_TELEMETRY=1 \
    HETU_TELEMETRY_LOG="$LOG/swap_trace.jsonl" \
    HETU_FAILURE_LOG="$LOG/swap_failure.jsonl" \
    HETU_FLIGHT_LOG="$LOG/swap_flight.jsonl" \
    HETU_CHAOS="seed=5,kill=2,role=swap" JAX_PLATFORMS=cpu \
    python - <<'PYEOF'
import time
import numpy as np
import hetu_tpu as ht  # noqa: F401
from hetu_tpu.models import GPTConfig
from hetu_tpu.serving import (Request, ServingEngine, ServingRouter,
                              WeightSyncCoordinator)

def mk_params(seed):
    rng, hd = np.random.RandomState(seed), 16
    p = {"sw_wte_table": rng.randn(61, hd) * 0.05,
         "sw_wpe": rng.randn(32, hd) * 0.05,
         "sw_ln_f_scale": np.ones(hd), "sw_ln_f_bias": np.zeros(hd)}
    for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                   ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                   ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
        p[f"sw_h0_{w}_weight"] = rng.randn(*shp) * 0.05
        p[f"sw_h0_{w}_bias"] = np.zeros(shp[1])
    for ln in ("ln1", "ln2"):
        p[f"sw_h0_{ln}_scale"] = np.ones(hd)
        p[f"sw_h0_{ln}_bias"] = np.zeros(hd)
    return p

p1, p2 = mk_params(0), mk_params(1)
cfg = GPTConfig(vocab_size=61, hidden_size=16, num_hidden_layers=1,
                num_attention_heads=2, max_position_embeddings=32,
                batch_size=1, seq_len=32, dropout_rate=0.0)
router = ServingRouter(
    lambda i: ServingEngine(p1, cfg, slots=2, fast_path=False),
    replicas=2, restart_backoff=0.01)
coord = WeightSyncCoordinator(router, p1, version=1)

def trace(n, seed):
    rng = np.random.RandomState(seed)
    return [Request(prompt=[int(t) for t in rng.randint(0, 61, 3)],
                    max_new_tokens=4) for _ in range(n)]

# rollout 1: the seeded kill fires at replica 0's drain seam
assert coord.begin(p2, 2)
res1 = router.run(trace(8, 11))
coord.drain()
assert len(res1) == 8, f"retired {len(res1)}/8 under the chaos kill"
assert coord.state == "rolled_back", coord.last
deadline = time.time() + 10.0
while len(coord.fleet_versions()) < 2 and time.time() < deadline:
    router.step(); time.sleep(0.005)
assert coord.fleet_versions() == {0: 1, 1: 1}, coord.fleet_versions()

# rollout 2: the one-shot kill is spent — this one must land
assert coord.begin(p2, 2)
res2 = router.run(trace(8, 12))
coord.drain()
assert len(res2) == 8, f"retired {len(res2)}/8 in the clean rollout"
assert coord.state == "done", coord.last
assert coord.fleet_versions() == {0: 2, 1: 2}, coord.fleet_versions()
assert all(r.weight_version in (1, 2) for r in res2.values())
snap = router.snapshot()
assert snap["lost"] == 0 and snap["duplicates"] == 0, snap
print("rolling swap gate OK: failed+rolled_back then done,"
      " fleet v2, finished", snap["finished"])
PYEOF
if ! grep -q 'rolling swap gate OK' "$LOG/swap_gate.log"; then
  echo "rolling-swap gate FAILED — see $LOG/swap_gate.log" >&2
  exit 1
fi
python bin/hetu_trace.py "$LOG/swap_trace.jsonl" \
    "$LOG/swap_failure.jsonl" --check \
    > "$LOG/swap_trace_contract.log" || {
  echo "swap span-balance/version-coherence check FAILED — see" \
       "$LOG/swap_trace_contract.log" >&2
  exit 1
}
python bin/hetu_trace.py "$LOG/swap_flight.jsonl" --check \
    > "$LOG/swap_flight_contract.log" || {
  echo "swap flight-dump contract check FAILED — see" \
       "$LOG/swap_flight_contract.log" >&2
  exit 1
}

# 00h. elastic-fleet gate (ISSUE 16): one CPU process runs the three
#      autoscale chaos phases back to back.  Phase A: a burn-driven
#      scale-up whose bring-up is chaos-killed (role=autoscale takes
#      out the BUSIEST PEER mid-warm) — zero request loss, and every
#      finished request token-identical to an offline decode of the
#      same specs.  Phase B: a diurnal trough walks the fleet down,
#      then a flash crowd lands on the shrunken fleet — it must grow
#      back, still zero loss.  Phase C: a drain whose SUBJECT is
#      chaos-killed mid-drain (fresh one-shot plan) — the requeue reads
#      the router's records, never the corpse.  The combined stream
#      must pass the hetu_trace scale-balance rule (every scale_up
#      paired with replica_ready, every scale_down with
#      replica_retired, drained rids retiring exactly once on a peer).
run autoscale_gate 600 env HETU_TELEMETRY=1 \
    HETU_TELEMETRY_LOG="$LOG/autoscale_trace.jsonl" \
    HETU_FAILURE_LOG="$LOG/autoscale_failure.jsonl" \
    HETU_FLIGHT_LOG="$LOG/autoscale_flight.jsonl" \
    HETU_CHAOS="seed=11,kill=1,role=autoscale" JAX_PLATFORMS=cpu \
    python - <<'PYEOF'
import os
import numpy as np
import hetu_tpu as ht  # noqa: F401
from hetu_tpu.models import GPTConfig
from hetu_tpu.ps import faults
from hetu_tpu.serving import (SLO, FleetAutoscaler, Request,
                              ServingEngine, ServingRouter,
                              TrafficGenerator, replay)

def mk_params(seed=0):
    rng, hd = np.random.RandomState(seed), 16
    p = {"el_wte_table": rng.randn(61, hd) * 0.05,
         "el_wpe": rng.randn(32, hd) * 0.05,
         "el_ln_f_scale": np.ones(hd), "el_ln_f_bias": np.zeros(hd)}
    for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                   ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                   ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
        p[f"el_h0_{w}_weight"] = rng.randn(*shp) * 0.05
        p[f"el_h0_{w}_bias"] = np.zeros(shp[1])
    for ln in ("ln1", "ln2"):
        p[f"el_h0_{ln}_scale"] = np.ones(hd)
        p[f"el_h0_{ln}_bias"] = np.zeros(hd)
    return p

p = mk_params()
cfg = GPTConfig(vocab_size=61, hidden_size=16, num_hidden_layers=1,
                num_attention_heads=2, max_position_embeddings=32,
                batch_size=1, seq_len=32, dropout_rate=0.0)

def mk_router(replicas, slo_ms=None):
    def factory(i):
        slo = [SLO("ttft", "latency", slo_ms)] if slo_ms else None
        return ServingEngine(p, cfg, slots=4, queue_limit=8,
                             max_seq_len=32, paged=True, kv_block=4,
                             prefix_share=True, slo=slo)
    return ServingRouter(factory, replicas=replicas, directory=True,
                         shed_on_slo=False, restart_backoff=0.01)

# ---- phase A: chaos-killed scale-up, burn-driven --------------------
r = mk_router(2, slo_ms=0.001)   # any traffic burns the tight budget
auto = FleetAutoscaler(r, fleet_min=1, fleet_max=3, up_ticks=2,
                       down_ticks=10**6, cooldown=3)
specs = TrafficGenerator(seed=7, vocab=61, s_max=32, horizon_s=2.0,
                         base_rps=2.0, peak_rps=40.0, cycle_s=2.0,
                         n_sessions=4, prefix_len=8).trace(dt=0.05)
res, rep = replay(r, specs, step_s=0.01, tail_s=1.0)
snap = r.snapshot()
assert auto.scale_ups >= 1, auto.snapshot()
assert snap["lost"] == 0, snap
assert len(res) + len(rep["shed"]) + len(rep["rejected"]) == len(specs)
assert any(row["restarts"] >= 1 for row in snap["replicas"]), \
    "the scale-up chaos kill never fired"
eng = ServingEngine(p, cfg, slots=4, queue_limit=len(specs) + 1,
                    max_seq_len=32)
off = eng.run([sp.to_request() for sp in specs if sp.request_id in res])
for rid, x in res.items():
    assert list(x.tokens) == list(off[rid].tokens), rid
a_ups, a_fin = auto.scale_ups, snap["finished"]

# ---- phase B: flash crowd lands on the scaled-down fleet ------------
os.environ.pop("HETU_CHAOS", None)
faults.reset_plans()
r = mk_router(1)
auto = FleetAutoscaler(r, fleet_min=1, fleet_max=2, up_pressure=0.2,
                       up_ticks=2, down_pressure=0.1, down_ticks=25,
                       cooldown=10)
specs = TrafficGenerator(seed=21, vocab=61, s_max=32, horizon_s=4.0,
                         base_rps=1.0, peak_rps=80.0, cycle_s=2.0,
                         n_sessions=8, prefix_len=8,
                         flash=((1.9, 0.4, 25.0),)).trace(dt=0.05)
res, rep = replay(r, specs, step_s=0.01, tail_s=3.0)
snap = r.snapshot()
assert snap["lost"] == 0, snap
assert auto.scale_ups >= 2 and auto.scale_downs >= 1, auto.snapshot()
acts = [e["action"] for e in auto.timeline]
assert "scale_up" in acts[acts.index("scale_down"):], \
    f"no regrowth after the scale-down: {acts}"
assert len(res) + len(rep["shed"]) + len(rep["rejected"]) == len(specs)
b_ups, b_downs = auto.scale_ups, auto.scale_downs

# ---- phase C: drain whose subject is chaos-killed mid-drain ---------
os.environ["HETU_CHAOS"] = "seed=12,kill=1,role=autoscale"
faults.reset_plans()
r = mk_router(2)
reqs = [Request(prompt=[2 + i, 5, 9], max_new_tokens=6,
                request_id=f"c{i}") for i in range(8)]
for q in reqs:
    r.submit(q)
out = {}
for _ in range(3):
    for x in r.step():
        out[x.request_id] = x
r.retire_replica(1, reason="scale_down")
assert "chaos autoscale kill" in (r.replicas[1].exit_error or ""), \
    "the drain chaos kill never fired"
for _ in range(4000):
    if not r.pending:
        break
    for x in r.step():
        out[x.request_id] = x
assert r.snapshot()["lost"] == 0
assert set(out) == {q.request_id for q in reqs}
print("autoscale gate OK: chaos scale-up (ups", a_ups, "finished",
      a_fin, ") flash regrowth (ups", b_ups, "downs", b_downs,
      ") chaos drain retired 8/8, zero loss everywhere")
PYEOF
if ! grep -q 'autoscale gate OK' "$LOG/autoscale_gate.log"; then
  echo "elastic-fleet gate FAILED — see $LOG/autoscale_gate.log" >&2
  exit 1
fi
python bin/hetu_trace.py "$LOG/autoscale_trace.jsonl" \
    "$LOG/autoscale_failure.jsonl" --check \
    > "$LOG/autoscale_contract.log" || {
  echo "autoscale scale-balance/span check FAILED — see" \
       "$LOG/autoscale_contract.log" >&2
  exit 1
}
python bin/hetu_trace.py "$LOG/autoscale_flight.jsonl" --check \
    > "$LOG/autoscale_flight_contract.log" || {
  echo "autoscale flight-dump contract check FAILED — see" \
       "$LOG/autoscale_flight_contract.log" >&2
  exit 1
}

# 00i. tiered-KV gate (ISSUE 17): one CPU process runs the prefix
#      storm twice through a starved paged pool (2 slots x 8 blocks vs
#      a 12-session zipf working set) behind the full spill ladder
#      (host-RAM ring -> 2-shard PS cold store).  Phase A: the ladder
#      cycles (spills, fetches, ring->PS demotions), zero loss, every
#      finished request token-identical to an offline decode of the
#      same specs.  Phase B: the same storm with HETU_CHAOS
#      role=kvtier killing the PS rung mid-storm — the store must mark
#      the cold rung dead and degrade to drop-on-evict with zero loss,
#      identity intact, and WITHOUT taking the replica down with it.
#      The combined stream must pass the hetu_trace tier-balance rule
#      (every kv_spill closes with exactly one kv_fetch or
#      kv_tier_drop), and the kill must land in the failure log.
run kvtier_gate 600 env HETU_TELEMETRY=1 \
    HETU_TELEMETRY_LOG="$LOG/kvtier_trace.jsonl" \
    HETU_FAILURE_LOG="$LOG/kvtier_failure.jsonl" \
    HETU_FLIGHT_LOG="$LOG/kvtier_flight.jsonl" JAX_PLATFORMS=cpu \
    python - <<'PYEOF'
import os
import numpy as np
import hetu_tpu as ht  # noqa: F401
from hetu_tpu.models import GPTConfig
from hetu_tpu.ps import faults
from hetu_tpu.ps.server import PSServer
from hetu_tpu.ps.sharded import ShardedPSClient
from hetu_tpu.serving import (ServingEngine, ServingRouter,
                              TieredKVStore, TrafficGenerator, replay)

def mk_params(seed=0):
    rng, hd = np.random.RandomState(seed), 16
    p = {"kt_wte_table": rng.randn(61, hd) * 0.05,
         "kt_wpe": rng.randn(32, hd) * 0.05,
         "kt_ln_f_scale": np.ones(hd), "kt_ln_f_bias": np.zeros(hd)}
    for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                   ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                   ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
        p[f"kt_h0_{w}_weight"] = rng.randn(*shp) * 0.05
        p[f"kt_h0_{w}_bias"] = np.zeros(shp[1])
    for ln in ("ln1", "ln2"):
        p[f"kt_h0_{ln}_scale"] = np.ones(hd)
        p[f"kt_h0_{ln}_bias"] = np.zeros(hd)
    return p

p = mk_params()
cfg = GPTConfig(vocab_size=61, hidden_size=16, num_hidden_layers=1,
                num_attention_heads=2, max_position_embeddings=32,
                batch_size=1, seq_len=32, dropout_rate=0.0)

def mk_store():
    return TieredKVStore(
        host_bytes=4096, ps_tier=True,
        ps=ShardedPSClient(servers=[PSServer(), PSServer()]))

def mk_router(store):
    def factory(i):
        return ServingEngine(p, cfg, slots=2, queue_limit=64,
                             max_seq_len=32, paged=True, kv_block=8,
                             pool_blocks=8, prefix_share=True)
    return ServingRouter(factory, replicas=1, kv_tiers=store)

specs = TrafficGenerator(seed=31, vocab=61, s_max=32, horizon_s=2.0,
                         base_rps=12.0, peak_rps=12.0, cycle_s=2.0,
                         n_sessions=12, zipf_a=1.3,
                         prefix_len=8).trace(dt=0.05)
eng = ServingEngine(p, cfg, slots=2, queue_limit=len(specs) + 1,
                    max_seq_len=32)
off = eng.run([sp.to_request() for sp in specs])

# ---- phase A: the full ladder under the storm, no chaos -------------
store = mk_store()
r = mk_router(store)
res, rep = replay(r, specs, step_s=0.01)
snap = r.snapshot()
assert snap["lost"] == 0 and not rep["shed"] and not rep["rejected"]
st = snap["kv_tiers"]
assert sum(st["spills"].values()) > 0, st
assert sum(st["fetches"].values()) > 0, st
assert st["demotes"] > 0, st
for rid, x in res.items():
    assert list(x.tokens) == list(off[rid].tokens), rid
store.close("kvtier_gate_phase_a_done")
a_spills = sum(st["spills"].values())
a_fetches = sum(st["fetches"].values())

# ---- phase B: PS rung chaos-killed mid-storm ------------------------
os.environ["HETU_CHAOS"] = "seed=5,kill=2,role=kvtier"
faults.reset_plans()
store = mk_store()
r = mk_router(store)
res, rep = replay(r, specs, step_s=0.01)
snap = r.snapshot()
os.environ.pop("HETU_CHAOS", None)
faults.reset_plans()
assert snap["lost"] == 0 and not rep["shed"] and not rep["rejected"]
assert snap["kv_tiers"]["ps_dead"] is True, snap["kv_tiers"]
assert all(x["restarts"] == 0 for x in snap["replicas"]), \
    "the PS kill took a replica down with it"
for rid, x in res.items():
    assert list(x.tokens) == list(off[rid].tokens), rid
store.close("kvtier_gate_phase_b_done")
print("kvtier gate OK: ladder cycled (spills", a_spills, "fetches",
      a_fetches, ") then PS chaos kill degraded to drop-on-evict,",
      "zero loss + token identity in both phases")
PYEOF
if ! grep -q 'kvtier gate OK' "$LOG/kvtier_gate.log"; then
  echo "tiered-KV gate FAILED — see $LOG/kvtier_gate.log" >&2
  exit 1
fi
python bin/hetu_trace.py "$LOG/kvtier_trace.jsonl" \
    "$LOG/kvtier_failure.jsonl" --check \
    > "$LOG/kvtier_contract.log" || {
  echo "tiered-KV tier-balance check FAILED — see" \
       "$LOG/kvtier_contract.log" >&2
  exit 1
}
if ! grep -q 'kvtier_ps_killed' "$LOG/kvtier_failure.jsonl"; then
  echo "tiered-KV gate: PS chaos kill missing from the failure log" >&2
  exit 1
fi

# 00j. mixed-mode ragged-dispatch gate (ISSUE 18): one CPU process
#      replays a chunked-prefill + decode trace through the engine
#      twice — phase-split (ragged=False) and mixed-mode (ragged=True,
#      arrivals + chunk continuations + decode packed into ONE ragged
#      wave per step) — and requires greedy TOKEN-IDENTICAL outputs,
#      zero chunk_stall in the mixed arm (folded by construction), and
#      a serve stream that passes hetu_trace --check (incl. the
#      spec-attribution rule: a third arm runs spec=2 THROUGH the
#      mixed wave at acceptance 1.0).  The on-chip HETU_BENCH_SERVE
#      run (stage 4c) banks ragged_ab with the native kernel — that
#      run is the A/B of record; this gate proves the path first.
run mixed_gate 900 env HETU_TELEMETRY=1 \
    HETU_TELEMETRY_LOG="$LOG/mixed_trace.jsonl" JAX_PLATFORMS=cpu \
    python - <<'PYEOF'
import numpy as np
import hetu_tpu as ht  # noqa: F401
from hetu_tpu.models import GPTConfig
from hetu_tpu.serving import Request, ServingEngine

rng, hd, L = np.random.RandomState(0), 16, 2
p = {"mxg_wte_table": rng.randn(61, hd) * 0.05,
     "mxg_wpe": rng.randn(64, hd) * 0.05,
     "mxg_ln_f_scale": np.ones(hd), "mxg_ln_f_bias": np.zeros(hd)}
for i in range(L):
    for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                   ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                   ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
        p[f"mxg_h{i}_{w}_weight"] = rng.randn(*shp) * 0.05
        p[f"mxg_h{i}_{w}_bias"] = np.zeros(shp[1])
    for ln in ("ln1", "ln2"):
        p[f"mxg_h{i}_{ln}_scale"] = np.ones(hd)
        p[f"mxg_h{i}_{ln}_bias"] = np.zeros(hd)
cfg = GPTConfig(vocab_size=61, hidden_size=hd, num_hidden_layers=L,
                num_attention_heads=2, max_position_embeddings=64,
                batch_size=1, seq_len=64, dropout_rate=0.0)
tr = np.random.RandomState(18)
# long prompts (> chunk) riding next to short decode streams: every
# step mixes a chunk continuation with decode rows — the wave shape
# the phase barrier penalizes
mk = lambda: [Request(prompt=[int(t) for t in
                              tr.randint(0, 61, 4 + 3 * (s % 5))],
                      max_new_tokens=6 + (s % 3) * 4, seed=s)
              for s in range(8)]
kw = dict(slots=3, paged=True, kv_block=8, prefill_chunk=4,
          queue_limit=16)
tr = np.random.RandomState(18)
plain = ServingEngine(p, cfg, **kw, ragged=False).run(mk())
tr = np.random.RandomState(18)
eng = ServingEngine(p, cfg, **kw, ragged=True)
res = eng.run(mk())
assert eng.ragged and eng.metrics.mixed_mode
a = sorted(r.tokens.tolist() for r in plain.values())
b = sorted(r.tokens.tolist() for r in res.values())
assert a == b, "mixed-mode greedy diverged from the phase-split engine"
snap = eng.metrics.snapshot()
stall = snap["components"].get("chunk_stall_ms")
assert stall is None or stall["p99_ms"] == 0.0, stall
assert eng.prefill_chunks > 0, "trace never exercised chunked prefill"
# spec THROUGH the mixed wave at acceptance 1.0 (post-draft layer
# output-zeroed): identity must hold and the serve stream must pass
# the spec-attribution rule downstream
sp = dict(p)
for wn in ("attn_proj_weight", "attn_proj_bias",
           "ffn_wo_weight", "ffn_wo_bias"):
    sp[f"mxg_h1_{wn}"] = np.zeros_like(p[f"mxg_h1_{wn}"])
tr = np.random.RandomState(18)
sp_plain = ServingEngine(sp, cfg, **kw, ragged=False).run(mk())
tr = np.random.RandomState(18)
se = ServingEngine(sp, cfg, **kw, ragged=True, spec=2,
                   spec_adapt=False, spec_draft_layers=1)
sp_res = se.run(mk())
sa = sorted(r.tokens.tolist() for r in sp_plain.values())
sb = sorted(r.tokens.tolist() for r in sp_res.values())
assert sa == sb, "mixed-mode spec greedy diverged"
assert se.spec_accepted == se.spec_proposed > 0, \
    (se.spec_accepted, se.spec_proposed)
print("mixed gate OK: identity over", len(res), "requests,",
      "chunks", eng.prefill_chunks, "spec accepted",
      se.spec_accepted, "/", se.spec_proposed)
PYEOF
if ! grep -q 'mixed gate OK' "$LOG/mixed_gate.log"; then
  echo "mixed-mode ragged gate FAILED — see $LOG/mixed_gate.log" >&2
  exit 1
fi
python bin/hetu_trace.py "$LOG/mixed_trace.jsonl" --check \
    > "$LOG/mixed_trace_contract.log" || {
  echo "mixed-mode trace contract check FAILED — see" \
       "$LOG/mixed_trace_contract.log" >&2
  exit 1
}

# 00k. concurrency gate (ISSUE 19): the sanitizer itself, on CPU.
#      Green half: the deterministic interleaving fuzzer must be a
#      pure function of its seed (planted lost-update race reproduces
#      same-seed-twice across a sweep, pinned CI seed loses updates,
#      TracedLock'd variant exact on every seed), then the cstable/PS
#      hammer runs under seeded preemption with LOCKDEP ARMED — every
#      delta lands exactly once (cache == PS row for row) and the
#      acquisition-order graph stays clean; the merged stream must
#      pass hetu_trace --check including the lockdep rule.  Red half:
#      a second process plants a lock-order inversion and its stream
#      must FAIL the same check — the rule is proven live, not just
#      absent.
run concurrency_gate 600 env HETU_TELEMETRY=1 HETU_LOCKDEP=1 \
    HETU_TELEMETRY_LOG="$LOG/concurrency_trace.jsonl" \
    JAX_PLATFORMS=cpu python - <<'PYEOF'
import numpy as np
from hetu_tpu import locks
from hetu_tpu.analysis.concurrency import (assert_lockdep_clean,
                                           run_interleaved)
from hetu_tpu.cache.cstable import CacheSparseTable
from hetu_tpu.ps.server import PSServer

VOCAB, W, CI_SEED = 64, 4, 3

def racy(seed):
    state = {"n": 0}
    def worker():
        for _ in range(10):
            v = state["n"]
            locks.sched_point()
            state["n"] = v + 1
    run_interleaved(worker, worker, worker, seed=seed)
    return state["n"]

def locked(seed):
    state = {"n": 0}
    mu = locks.TracedLock("gate.counter")
    def worker():
        for _ in range(10):
            with mu:
                v = state["n"]
                locks.sched_point()
                state["n"] = v + 1
    run_interleaved(worker, worker, worker, seed=seed)
    return state["n"]

results = set()
for seed in range(6):
    a, b = racy(seed), racy(seed)
    assert a == b, f"seed {seed} not reproducible: {a} vs {b}"
    results.add(a)
    assert locked(seed) == 30, f"locked counter lost updates, seed {seed}"
assert racy(CI_SEED) < 30, "CI seed failed to surface the planted race"
assert len(results) >= 2, "seed sweep explored a single schedule"

class YieldingComm:
    # hands the scheduler token away inside every RPC: preemption
    # lands mid-transaction, where the bugs live
    def __init__(self, server):
        self._server = server
    def __getattr__(self, name):
        fn = getattr(self._server, name)
        def wrapper(*a, **kw):
            locks.sched_point()
            return fn(*a, **kw)
        return wrapper

for seed in range(4):
    server = PSServer()
    server.param_init("emb", (VOCAB, W), "normal", 0.0, 1.0, seed=3)
    t = CacheSparseTable(limit=32, vocab_size=VOCAB, width=W,
                         key="emb", comm=YieldingComm(server),
                         policy="LRU", push_bound=0)
    rngs = [np.random.RandomState(100 * seed + i) for i in range(2)]
    def lookups(rng=rngs[0]):
        for _ in range(6):
            assert t.embedding_lookup(
                rng.randint(0, VOCAB, 8)).shape == (8, W)
    def updates(rng=rngs[1]):
        for _ in range(6):
            ids = rng.randint(0, VOCAB, 4)
            t.embedding_update(ids,
                               rng.randn(4, W).astype(np.float32) * .01)
    run_interleaved(lookups, updates, seed=seed)
    t.flush()
    ids = np.arange(VOCAB)
    np.testing.assert_allclose(t.embedding_lookup(ids),
                               server.sparse_pull("emb", ids),
                               rtol=1e-4, atol=1e-5)
assert_lockdep_clean("suite cstable/PS hammer")
print("concurrency gate OK: fuzzer seed-exact over 6 seeds,",
      "cstable/PS hammer clean over 4 seeds under lockdep")
PYEOF
if ! grep -q 'concurrency gate OK' "$LOG/concurrency_gate.log"; then
  echo "concurrency gate FAILED — see $LOG/concurrency_gate.log" >&2
  exit 1
fi
python bin/hetu_trace.py "$LOG/concurrency_trace.jsonl" --check \
    > "$LOG/concurrency_trace_contract.log" || {
  echo "concurrency trace contract/lockdep check FAILED — see" \
       "$LOG/concurrency_trace_contract.log" >&2
  exit 1
}
run lockdep_red 300 env HETU_TELEMETRY=1 HETU_LOCKDEP=1 \
    HETU_TELEMETRY_LOG="$LOG/lockdep_red.jsonl" \
    JAX_PLATFORMS=cpu python - <<'PYEOF'
from hetu_tpu import locks
a = locks.TracedLock("red.A")
b = locks.TracedLock("red.B")
with a:
    with b:
        pass
with b:
    with a:                 # the planted inversion
        pass
(v,) = locks.lockdep_violations()
assert v["kind"] == "order"
rep = locks.format_violation(v)
assert "red.A" in rep and "red.B" in rep
print("lockdep red gate OK: inversion detected and emitted")
PYEOF
if ! grep -q 'lockdep red gate OK' "$LOG/lockdep_red.log"; then
  echo "lockdep red gate FAILED — see $LOG/lockdep_red.log" >&2
  exit 1
fi
if python bin/hetu_trace.py "$LOG/lockdep_red.jsonl" --check \
    > "$LOG/lockdep_red_contract.log" 2>&1; then
  echo "lockdep trace rule FAILED to flag a planted inversion — see" \
       "$LOG/lockdep_red_contract.log" >&2
  exit 1
fi

# 00l. MoE serving gate (ISSUE 20): one CPU process decodes the MoE
#      GPT (top-2 of 4 experts, alternating blocks) through the engine
#      across THREE cache configurations — contiguous fast path,
#      block-table paged, paged + int8 KV — and requires greedy
#      TOKEN-IDENTICAL outputs vs offline generate_fast in every one,
#      plus the routing-attribution invariant on the engine counters
#      (routed + dropped == tokens x top_k x MoE layers).  A second,
#      capacity-starved run (cf=0.25) must actually DROP and its serve
#      stream must still pass hetu_trace --check — the MoE attribution
#      rule is proven against overflow, not just the easy case.  The
#      on-chip HETU_BENCH_SERVE run (stage 4c) banks moe_ab with
#      native kernels — that run is the A/B of record; this gate
#      proves the path before chip time is spent.
run moe_gate 900 env HETU_TELEMETRY=1 \
    HETU_TELEMETRY_LOG="$LOG/moe_trace.jsonl" JAX_PLATFORMS=cpu \
    python - <<'PYEOF'
import numpy as np
import hetu_tpu as ht  # noqa: F401
from hetu_tpu.models.gpt_decode import generate_fast
from hetu_tpu.models.moe_decode import (MoEDecodeConfig,
                                        init_moe_params, moe_spec_of)
from hetu_tpu.serving import Request, ServingEngine

cfg = MoEDecodeConfig(
    vocab_size=97, hidden_size=32, num_hidden_layers=4,
    num_attention_heads=2, ffn_mult=2, seq_len=48, dropout_rate=0.0,
    max_position_embeddings=48, num_experts=4, top_k=2,
    capacity_factor=2.0, moe_every=2)
p = init_moe_params(cfg, name="moe", seed=0)
prompts = [[5, 9, 2], [7, 1, 4, 3, 8], [11, 6], [13, 2, 2, 7]]
NEW = 8
ref = {i: [int(t) for t in np.asarray(
           generate_fast(p, cfg, [pr], NEW, temperature=0.0, seed=0,
                         name="moe"))[0][len(pr):]]
       for i, pr in enumerate(prompts)}
n_moe = moe_spec_of(cfg).moe_layers(cfg.num_hidden_layers)
mk = lambda: [Request(request_id=str(i), prompt=pr, max_new_tokens=NEW,
                      temperature=0.0, seed=0)
              for i, pr in enumerate(prompts)]
configs = [("contiguous", dict(fast_path=True)),
           ("paged", dict(fast_path=True, paged=16)),
           ("paged_int8", dict(fast_path=True, paged=16,
                               kv_quant="int8"))]
for label, kw in configs:
    eng = ServingEngine(p, cfg, slots=4, name="moe", **kw)
    out = eng.run(mk())
    got = {int(i): [int(t) for t in np.asarray(r.tokens)[r.prompt_len:]]
           for i, r in out.items()}
    assert got == ref, f"{label}: engine diverged from offline"
    tot = int(eng.expert_load.sum() + eng.expert_drops.sum())
    assert tot == eng.moe_tokens * cfg.top_k * n_moe, label
# capacity-overflow arm: cf=0.25 must drop; identity is NOT claimed
# here (dropped tokens ride the residual) but the accounting must
# still close and the stream must pass the trace contract below
scfg = MoEDecodeConfig(
    vocab_size=97, hidden_size=32, num_hidden_layers=4,
    num_attention_heads=2, ffn_mult=2, seq_len=48, dropout_rate=0.0,
    max_position_embeddings=48, num_experts=4, top_k=2,
    capacity_factor=0.25, moe_every=2)
seng = ServingEngine(p, scfg, slots=4, name="moe", fast_path=True,
                     paged=16)
seng.run(mk())
assert int(seng.expert_drops.sum()) > 0, \
    "cf=0.25 dropped nothing — the overflow path went untested"
stot = int(seng.expert_load.sum() + seng.expert_drops.sum())
assert stot == seng.moe_tokens * scfg.top_k * n_moe
print("moe gate OK: identity over", len(configs), "cache configs,",
      "overflow drops", int(seng.expert_drops.sum()),
      "accounted, imbalance",
      round(float(seng.expert_imbalance), 3))
PYEOF
if ! grep -q 'moe gate OK' "$LOG/moe_gate.log"; then
  echo "MoE serving gate FAILED — see $LOG/moe_gate.log" >&2
  exit 1
fi
python bin/hetu_trace.py "$LOG/moe_trace.jsonl" --check \
    > "$LOG/moe_trace_contract.log" || {
  echo "MoE trace contract check FAILED — see" \
       "$LOG/moe_trace_contract.log" >&2
  exit 1
}

# 4e (ordered with the 00-gates: pure-CPU via JAX_PLATFORMS=cpu, so it
#     must pass BEFORE any chip time is spent).  Speculative-decoding
#     trace-replay gate: the draft-propose / batched-verify path must
#     produce GREEDY TOKEN-IDENTICAL outputs vs the plain engine at
#     acceptance 1.0 (layers past the draft output-zeroed so draft
#     logits == target logits), retire every request in fewer waves
#     than tokens, and leave a serve stream that passes the
#     spec-attribution rule (hetu_trace --check: accepted + bonus + 1
#     == n_generated per request).  The on-chip HETU_BENCH_SERVE run
#     (stage 4c) banks spec_ab with native kernels — that run is the
#     A/B of record; this gate proves the path before it is trusted.
run spec_gate 900 env HETU_TELEMETRY=1 \
    HETU_TELEMETRY_LOG="$LOG/spec_trace.jsonl" JAX_PLATFORMS=cpu \
    python - <<'PYEOF'
import numpy as np
import hetu_tpu as ht  # noqa: F401
from hetu_tpu.models import GPTConfig
from hetu_tpu.serving import Request, ServingEngine

rng, hd, L = np.random.RandomState(0), 16, 2
p = {"spg_wte_table": rng.randn(61, hd) * 0.05,
     "spg_wpe": rng.randn(64, hd) * 0.05,
     "spg_ln_f_scale": np.ones(hd), "spg_ln_f_bias": np.zeros(hd)}
for i in range(L):
    for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                   ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                   ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
        p[f"spg_h{i}_{w}_weight"] = rng.randn(*shp) * 0.05
        p[f"spg_h{i}_{w}_bias"] = np.zeros(shp[1])
    for ln in ("ln1", "ln2"):
        p[f"spg_h{i}_{ln}_scale"] = np.ones(hd)
        p[f"spg_h{i}_{ln}_bias"] = np.zeros(hd)
# zero the post-draft layer's outputs: draft logits == target logits,
# acceptance 1.0 — the high-acceptance endpoint of the A/B
for wn in ("attn_proj_weight", "attn_proj_bias",
           "ffn_wo_weight", "ffn_wo_bias"):
    p[f"spg_h1_{wn}"] = np.zeros_like(p[f"spg_h1_{wn}"])
cfg = GPTConfig(vocab_size=61, hidden_size=hd, num_hidden_layers=L,
                num_attention_heads=2, max_position_embeddings=64,
                batch_size=1, seq_len=64, dropout_rate=0.0)
treq = np.random.RandomState(11)
mk = lambda: [Request(prompt=[int(t) for t in treq.randint(0, 61, 4)],
                      max_new_tokens=12, seed=s) for s in range(6)]
treq = np.random.RandomState(11)
plain = ServingEngine(p, cfg, slots=2, fast_path=False).run(mk())
treq = np.random.RandomState(11)
eng = ServingEngine(p, cfg, slots=2, fast_path=False, spec=3,
                    spec_adapt=False, spec_draft_layers=1)
res = eng.run(mk())
a = sorted(r.tokens.tolist() for r in plain.values())
b = sorted(r.tokens.tolist() for r in res.values())
assert a == b, "speculative greedy diverged from the plain engine"
assert eng.spec_proposed > 0 and \
    eng.spec_accepted == eng.spec_proposed, \
    (eng.spec_accepted, eng.spec_proposed)
total = sum(r.n_generated for r in res.values())
assert eng.spec_waves < total, (eng.spec_waves, total)
print("spec gate OK: waves", eng.spec_waves, "of", total, "tokens,",
      "accepted", eng.spec_accepted, "/", eng.spec_proposed)
PYEOF
if ! grep -q 'spec gate OK' "$LOG/spec_gate.log"; then
  echo "speculative-decoding gate FAILED — see $LOG/spec_gate.log" >&2
  exit 1
fi
python bin/hetu_trace.py "$LOG/spec_trace.jsonl" --check \
    > "$LOG/spec_trace_contract.log" || {
  echo "spec-attribution/contract check FAILED — see" \
       "$LOG/spec_trace_contract.log" >&2
  exit 1
}

# 0. the rows a mid-capture wedge has previously cost us: the Aug-2
#    recovery window measured bert_base/bert4l/gpt/resnet18 fresh, then
#    the tunnel wedged INSIDE ctr_hybrid — so a fresh window banks the
#    still-stale rows first, before the long full-matrix pass
run matrix_gap 3600 env HETU_BENCH_CONFIGS=ctr_hybrid,moe,long_context \
    python bench.py

# 1. full matrix under honest accounting (bert_base probes pick the
#    batch; pin with HETU_BENCH_BERT_BATCH=32 if probes misbehave)
run matrix 7200 python bench.py

# 2. the (batch x attention x head) ablation sweep + planner validation
HETU_BENCH_SWEEP=1 run sweep 5400 python bench.py

# 3. max embedding rows per chip (1M..256M ladder)
HETU_BENCH_CTR_ROWS=1 run ctr_rows 5400 python bench.py

# 4. refresh the chip calibration artifact (raw + clamped curves)
run calibration 3600 python -m hetu_tpu.planner.chip_calibration

# 4b. KV-cached serving throughput (BENCH_DECODE.json)
HETU_BENCH_DECODE=1 run decode 3600 python bench.py

# 4c. continuous-batching engine vs static batching on the seeded
#     mixed-length trace, PLUS the serving fast-path A/B — masked
#     reference vs ragged (flash prefill + paged decode kernel) on the
#     mixed AND prefill-heavy traces with per-phase prefill/decode
#     timings, and the phase micro A/B (decode step at 25%/50% fill,
#     prefill scan-vs-flash at P=128) — all in one invocation
#     (BENCH_SERVE.json fast_path_ab / prefill_heavy / phase_ab; this
#     on-chip run is the A/B of record — the CPU harness emulates the
#     kernels in interpret mode), PLUS the paged-vs-contiguous KV A/B
#     of record (paged_ab: prefix-heavy trace at equal cache bytes —
#     block-table pool + prefix sharing vs slot rows; on chip the
#     block-table decode kernel runs native and HETU_KV_BLOCK=auto
#     selects paged), PLUS the speculative-decoding A/B of record
#     (spec_ab: draft-propose / batched-verify vs plain decoding at
#     equal slots, acceptance-rate sweep via temperature, greedy
#     token-identity and the tok/s floor asserted in-bench; the
#     multi-token verify kernel runs native here — the CPU stage-4e
#     gate only proves the path), PLUS the fleet prefix A/B
#     (fleet_prefix_ab: affinity-only vs PrefixDirectory routing vs
#     directory + prefill/decode roles with KV handoff on a
#     prefix-storm trace at equal fleet slots — tok/s and TTFT p99
#     floors and greedy token-identity asserted in-bench; the CPU
#     stage-00e gate proves the chaos-kill degradation path), PLUS the
#     mixed-mode ragged-dispatch A/B of record (ragged_ab: ONE ragged
#     wave per step — arrivals + chunk continuations + spec-verify +
#     decode through kernels/ragged_attention.py — vs the phase-split
#     scheduler on a prefill-heavy + decode-heavy mixed trace; greedy
#     token-identity and the chunk_stall==0 floor asserted in-bench
#     everywhere, and the strict tok/s no-worse floor binds HERE
#     because it is gated to TPU — the CPU harness pays union-width
#     padding in the masked path and the stage-00j gate only proves
#     the path), PLUS the MoE-vs-dense A/B of record (moe_ab: top-2 of
#     4 experts at EQUAL ACTIVE PARAMS — expert_size = ffn_size /
#     top_k — on the same trace/engine config; tok/s + TTFT p99 per
#     arm, per-expert load, imbalance and drop rate in the artifact;
#     greedy identity vs offline and the zero-drop-at-serving-cf floor
#     asserted in-bench, capacity-binding probe must drop with the
#     accounting invariant intact; the CPU harness pays the full
#     E-expert einsum whatever the routing, so THIS on-chip row is the
#     throughput number of record — the stage-00l gate only proves the
#     path).  Runs after decode so the scan compile is already in
#     the shared compilation cache.
HETU_BENCH_SERVE=1 run serve 3600 python bench.py

# 4d. quantized-bytes A/Bs of record (ISSUE 9).  The serving half rides
#     stage 4c's invocation (BENCH_SERVE.json quant_ab: int8 KV vs f32
#     at equal HBM bytes — peak concurrent slots + tok/s, the
#     tolerance-gated greedy top-1 check, and the >=1.9x slot-capacity
#     floor asserted in-bench; on chip the int8 decode kernels run
#     native instead of interpret mode, making THIS the tok/s number of
#     record).  This stage measures the training half: int8 PS
#     push/pull vs the exact f32 wire — bytes via the PR 5
#     ps.rpc.bytes_* counters + step time, >=3.5x reduction asserted —
#     merged into BENCH_PS_SCALING.json as its quant_ab section.
run ps_quant 1800 python examples/ctr/bench_ps_scaling.py --quant-only

# 5. long-context tile tuning: A/B a couple of block shapes at 32k
for blocks in "512,1024" "1024,1024" "1024,2048" "512,2048"; do
  HETU_BENCH_LC_BLOCKS=$blocks HETU_BENCH_CONFIGS=long_context \
    run "lc_${blocks/,/x}" 2700 python bench.py
done

# 6. MoE chip-fill A/B (the recorded config underfilled the chip)
for tok in 1024 2048 4096; do
  HETU_BENCH_MOE_TOKENS=$tok HETU_BENCH_CONFIGS=moe \
    run "moe_t${tok}" 2700 python bench.py
done

# 7. bert4l attention A/B: the Aug-2 fresh row (630/s, flash OFF via
#    the seq>=1024 crossover) is 3x below the Jul-30 record (1987/s,
#    flash ON at seq 128) — decide whether the crossover heuristic is
#    wrong for short sequences.  The winner's flash setting should be
#    folded back into _bench_lm's use_flash rule.  The hypothesized
#    winner (flash) runs LAST so an unattended pass leaves the
#    likely-best row in the matrix, not the suspected loser.
HETU_BENCH_FORCE_FLASH=0 HETU_BENCH_CONFIGS=bert4l \
  run bert4l_noflash 2700 python bench.py
HETU_BENCH_FORCE_FLASH=1 HETU_BENCH_CONFIGS=bert4l \
  run bert4l_flash 2700 python bench.py

# NOTE: stages 5/6/7 leave the LAST A/B variant in BENCH_MATRIX.json —
# read the logs, then re-run the winning setting (its env + the config
# name) so the matrix records the best measured configuration.

echo "done; artifacts: BENCH_MATRIX.json SWEEP_BERT_BASE.json \
BENCH_CTR_ROWS.json CALIBRATION_TPU.json BENCH_DECODE.json \
BENCH_SERVE.json (logs in $LOG)"
