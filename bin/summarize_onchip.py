#!/usr/bin/env python
"""Summarize an on-chip suite log directory (bin/run_onchip_suite.sh).

Each stage log's last JSON line is the bench headline for that stage;
the lc_* / moe_* stages are A/B variants whose WINNER must be re-run
last so BENCH_MATRIX.json records the best measured configuration
(see the NOTE in run_onchip_suite.sh).  This tool extracts every
stage's headline, ranks the A/B groups, and prints the exact re-run
command for each winner.

Usage: python bin/summarize_onchip.py [logdir]
"""
import json
import os
import re
import sys


def headline(path):
    """Last parseable JSON object line of a stage log, or None."""
    try:
        with open(path, errors="replace") as f:
            lines = f.read().splitlines()
    except OSError:
        return None
    for ln in reversed(lines):
        ln = ln.strip()
        if ln.startswith("{") and ln.endswith("}"):
            try:
                return json.loads(ln)
            except ValueError:
                continue
    return None


# A/B stage-name -> the bench config its measurement lives under in the
# headline's nested per-config matrix.  The TOP-LEVEL headline value
# cannot be used: bench.py's headline always reports bert_base whenever
# a bert_base row exists in the merged matrix (even for CONFIGS=subset
# runs), so every variant of an A/B group would show the identical stale
# number and max() would pick "winners" by string tie-break.
_STAGE_CONFIG = (
    (re.compile(r"lc_(\d+)x(\d+)$"), "long_context"),
    (re.compile(r"moe_t(\d+)$"), "moe"),
    (re.compile(r"bert4l_(no)?flash$"), "bert4l"),
)


def stage_value(name, h):
    """(config-or-None, value) for one stage: A/B stages read their own
    config's row from the nested matrix; other stages keep the headline
    number."""
    for rx, cfg in _STAGE_CONFIG:
        if rx.match(name):
            row = h.get("matrix", {}).get(cfg, {})
            return cfg, row.get("value")
    return None, h.get("value")


def rank_ab(group):
    """Winner of one A/B group [(value, label), ...], or None when the
    group is empty or ALL values are equal (ties would be decided by a
    meaningless string comparison on the label)."""
    if not group or len({v for v, _ in group}) <= 1:
        return None
    return max(group)


def main():
    if len(sys.argv) > 1:
        logdir = sys.argv[1]
    else:
        # no canonical default exists: the suite defaults to
        # /tmp/onchip_<HHMM> and the watchdog to /tmp/onchip_watchdog
        sys.exit(f"usage: {sys.argv[0]} <suite-logdir>\n"
                 "(the logdir bin/run_onchip_suite.sh printed at start)")
    if not os.path.isdir(logdir):
        sys.exit(f"{logdir}: not a directory")
    stages = sorted(
        f[:-4] for f in os.listdir(logdir) if f.endswith(".log"))
    ab = {"lc": [], "moe": [], "bert4l": []}
    print(f"{'stage':<14} {'value':>12} {'unit':<28} {'mfu':>7} platform")
    for name in stages:
        h = headline(os.path.join(logdir, name + ".log"))
        if h is None:
            print(f"{name:<14} {'—':>12} (no JSON line — read the log)")
            continue
        cfg, val = stage_value(name, h)
        row = h.get("matrix", {}).get(cfg, {}) if cfg else h
        unit, mfu = row.get("unit", ""), row.get("mfu")
        print(f"{name:<14} {val if val is not None else '—':>12} "
              f"{unit:<28} {mfu if mfu is not None else '—':>7} "
              f"{h.get('platform', '?')}")
        m = re.match(r"lc_(\d+)x(\d+)$", name)
        if m and isinstance(val, (int, float)):
            ab["lc"].append((val, f"{m.group(1)},{m.group(2)}"))
        m = re.match(r"moe_t(\d+)$", name)
        if m and isinstance(val, (int, float)):
            ab["moe"].append((val, m.group(1)))
        m = re.match(r"bert4l_(no)?flash$", name)
        if m and isinstance(val, (int, float)):
            ab["bert4l"].append((val, "0" if m.group(1) else "1"))
    win = rank_ab(ab["lc"])
    if win:
        v, blocks = win
        print(f"\nlong-context winner: blocks {blocks} ({v})\n"
              f"  re-run: HETU_BENCH_LC_BLOCKS={blocks} "
              f"HETU_BENCH_CONFIGS=long_context python bench.py")
    win = rank_ab(ab["moe"])
    if win:
        v, tok = win
        print(f"moe winner: tokens {tok} ({v})\n"
              f"  re-run: HETU_BENCH_MOE_TOKENS={tok} "
              f"HETU_BENCH_CONFIGS=moe python bench.py")
    win = rank_ab(ab["bert4l"])
    if win:
        v, flash = win
        print(f"bert4l winner: flash={flash} ({v})\n"
              f"  re-run: HETU_BENCH_FORCE_FLASH={flash} "
              f"HETU_BENCH_CONFIGS=bert4l python bench.py\n"
              f"  then fold the winner into _bench_lm's use_flash rule")
    for key, label in (("lc", "long-context"), ("moe", "moe"),
                       ("bert4l", "bert4l")):
        if ab[key] and rank_ab(ab[key]) is None:
            print(f"{label}: all variants measured equal "
                  f"({ab[key][0][0]}) — no winner to re-run")


if __name__ == "__main__":
    main()
