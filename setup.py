from setuptools import setup, find_packages

setup(
    name="hetu_tpu",
    version="0.1.0",
    description=("TPU-native distributed deep-learning framework with the "
                 "capabilities of Hetu (dataflow graph API, DP/TP/PP/EP/CP "
                 "parallelism over JAX meshes, parameter server with "
                 "HET-style embedding cache, MoE, auto-parallel planner)"),
    packages=find_packages(include=["hetu_tpu", "hetu_tpu.*"]),
    package_data={"hetu_tpu.native": ["*.so", "*.cpp"]},
    python_requires=">=3.10",
    install_requires=["jax", "numpy"],
    entry_points={"console_scripts":
                  ["heturun=hetu_tpu.launcher:main"]},
)
