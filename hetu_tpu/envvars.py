"""Central typed registry for every ``HETU_*`` environment variable.

Before this module the repo had ~60 scattered ``os.environ`` reads with
per-site defaults and per-site parsing (``!= "0"`` here, ``bool(get())``
there, ``.lower() not in (...)`` elsewhere) — undocumented drift the
README could not keep up with.  Now every knob is REGISTERED once with a
type, default, and help string, and every read goes through a typed
getter; ``bin/hetu_lint.py`` (rule ``env-registry``) rejects any new raw
``os.environ['HETU_*']`` read outside this file, and ``--env-table``
regenerates the README's knob table from the registry.

Getters re-read ``os.environ`` on every call (no import-time caching):
tests and the chaos harness toggle vars at runtime and must observe the
change.  Reading an UNREGISTERED name raises — adding the registry row
(one line, with help text) is the price of a new knob.

Boolean parsing is uniform: unset → default; ``"" / 0 / false / no /
off`` (case-insensitive) → False; anything else → True.  This subsumes
the three ad-hoc spellings the call sites used to have.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

_FALSY = ("", "0", "false", "no", "off")
_MISSING = object()


@dataclass(frozen=True)
class EnvVar:
    name: str
    type: str          # 'str' | 'int' | 'float' | 'bool' | 'path' | 'list'
    default: object
    help: str
    section: str = "general"


REGISTRY: dict[str, EnvVar] = {}


def _reg(name, type_, default, help_, section):
    REGISTRY[name] = EnvVar(name, type_, default, help_, section)


# --------------------------------------------------------------------- #
# static checks (this PR's subsystem)
# --------------------------------------------------------------------- #
_reg("HETU_VALIDATE", "bool", False,
     "Run the pre-trace graph verifier + parallelism checker at executor/"
     "engine build and before each new feed-shape compile (analysis/). "
     "Default-on under pytest (tests/conftest.py).", "validate")
_reg("HETU_VALIDATE_LOG", "path", None,
     "JSONL sink for verifier/shard-check reports, in the launcher's "
     "failure-log record shape ({t, event, ...}).", "validate")

# --------------------------------------------------------------------- #
# concurrency sanitizer (hetu_tpu/locks.py + analysis/concurrency.py)
# --------------------------------------------------------------------- #
_reg("HETU_LOCKDEP", "bool", False,
     "Lock-order/deadlock sanitizer: every TracedLock acquisition "
     "records the per-thread held stack into a global lock-order "
     "graph; a cycle (potential deadlock), blocking work under a lock "
     "(note_blocking: PS RPC, big wire encodes), or an over-threshold "
     "hold is reported as a lockdep_violation event.  Also feeds the "
     "per-lock-class lock.hold_ms.* histograms.  0 = wrappers are "
     "plain pass-throughs (near-zero overhead).", "concurrency")
_reg("HETU_SCHED_FUZZ", "int", None,
     "Deterministic interleaving fuzz seed (the HETU_CHAOS analog for "
     "thread schedules): analysis/concurrency.run_interleaved drives "
     "registered threads through a seeded cooperative scheduler, so a "
     "race found on seed N reproduces on seed N.  Unset = threads run "
     "free (byte-identical no-op).", "concurrency")
_reg("HETU_LOCKDEP_HOLD_MS", "float", 0.0,
     "> 0 with HETU_LOCKDEP=1: any single lock hold longer than this "
     "many milliseconds is reported as a long_hold lockdep_violation "
     "(0 = histogram only, no per-hold threshold).", "concurrency")

# --------------------------------------------------------------------- #
# telemetry (hetu_tpu/telemetry/)
# --------------------------------------------------------------------- #
_reg("HETU_TELEMETRY", "bool", True,
     "Master switch for telemetry spans + metric instrumentation "
     "(executor step phases, PS RPC, cache, dataloader ring, serving). "
     "0 = no-op spans and no metric recording; the explicit event "
     "streams (failure/serve/validate) still flow.", "telemetry")
_reg("HETU_TELEMETRY_LOG", "path", None,
     "Merged run-wide JSONL sink: EVERY stream's records (failure/"
     "serve/validate + telemetry spans) also append here — the one "
     "file bin/hetu_trace.py merges, tails, and exports to a "
     "Chrome/Perfetto trace.", "telemetry")
_reg("HETU_TELEMETRY_BUFFER", "int", 4096,
     "In-memory event-ring capacity behind telemetry.snapshot(); also "
     "bounds ServingMetrics' in-memory event list when no serve log "
     "path is configured.", "telemetry")
_reg("HETU_FLIGHT_LOG", "path", None,
     "JSONL sink the flight recorder dumps to on engine exception, "
     "QueueFull storm, PS retry exhaustion, launcher terminal failure, "
     "or a HETU_CHAOS kill (telemetry/flight.py: a flight_dump header "
     "record + the last HETU_FLIGHT_DEPTH records leading up to the "
     "fault).  Unset = recording still on, dumps disabled.", "telemetry")
_reg("HETU_FLIGHT_DEPTH", "int", 512,
     "Flight-recorder ring capacity: how many recent telemetry records "
     "each dump carries.", "telemetry")

# --------------------------------------------------------------------- #
# serving SLOs (telemetry/slo.py)
# --------------------------------------------------------------------- #
_reg("HETU_SLO_TTFT_MS", "float", None,
     "Latency-bound SLO: finished requests must reach their first "
     "token within this many milliseconds (submit to first token, "
     "queue wait included).  Unset = no latency SLO.", "slo")
_reg("HETU_SLO_TPS", "float", None,
     "Throughput-bound SLO: each finished request's per-stream decode "
     "rate (tokens/second after the first token) must be at least "
     "this.  Unset = no throughput SLO.", "slo")
_reg("HETU_SLO_OBJECTIVE", "float", 0.99,
     "Fraction of requests that must meet each SLO target (the error "
     "budget is 1 - objective).", "slo")
_reg("HETU_SLO_WINDOW", "int", 256,
     "Sliding-window size (finished requests) for SLO burn-rate "
     "tracking.", "slo")

# --------------------------------------------------------------------- #
# multi-process / TPU bring-up
# --------------------------------------------------------------------- #
_reg("HETU_TPU_COORDINATOR", "str", None,
     "jax.distributed coordinator address for multi-host TPU bring-up "
     "(ht.init() calls jax.distributed.initialize when set).", "cluster")
_reg("HETU_TPU_NUM_PROCS", "int", 1,
     "Process count for jax.distributed.initialize.", "cluster")
_reg("HETU_TPU_PROC_ID", "int", 0,
     "This process's index for jax.distributed.initialize.", "cluster")
_reg("HETU_NUM_PROCESSES", "int", 1,
     "Launcher-stamped world size for jax.distributed bring-up in "
     "spawned workers.", "cluster")
_reg("HETU_PROCESS_ID", "int", None,
     "Launcher-stamped process index (required in launcher-spawned "
     "multi-process workers).", "cluster")

# --------------------------------------------------------------------- #
# parameter server: addressing + transport
# --------------------------------------------------------------------- #
_reg("HETU_PS_ADDR", "str", None,
     "host:port of a single PS server; unset = in-process local "
     "transport.", "ps")
_reg("HETU_PS_ADDRS", "list", (),
     "Comma-separated server-group addresses; >1 activates the sharded "
     "client.", "ps")
_reg("HETU_PS_PORT", "int", 23455,
     "Port a PS server binds (serve_from_env) / the launcher's base "
     "port for sequential server slots.", "ps")
_reg("HETU_PS_RANK", "int", 0, "This worker's rank for PS traffic.", "ps")
_reg("HETU_PS_NRANK", "int", 1, "Worker count for PS barriers/SSP.", "ps")
_reg("HETU_PS_TIMEOUT", "float", 60.0,
     "Per-RPC timeout (seconds).", "ps")
_reg("HETU_PS_CONNECT_TIMEOUT", "float", 10.0,
     "TCP connect timeout (seconds).", "ps")
_reg("HETU_PS_RETRIES", "int", 3,
     "Resend attempts before PSConnectionError surfaces.", "ps")
_reg("HETU_PS_BACKLOG_STEPS", "int", 32,
     "Max training steps of push traffic buffered through a PS outage "
     "(direct hybrid path) before the run fails.", "ps")
_reg("HETU_PS_REPLICATE", "bool", False,
     "Ring-replicate every key to its backup server ((s+1) % N) and "
     "fail over on primary loss (sharded client, N > 1).", "ps")
_reg("HETU_PS_USE_VAN", "bool", True,
     "Allow the native-van fast tier when the server offers it; 0 pins "
     "the python wire.", "ps")
_reg("HETU_PS_VAN", "bool", False,
     "serve_from_env: start the native van and auto-register "
     "qualifying tables.", "ps")
_reg("HETU_PS_VAN_PORT", "int", 0,
     "Port for the native van listener (0 = ephemeral).", "ps")
_reg("HETU_PS_VAN_BIND_ALL", "bool", False,
     "Expose the (authentication-free) van beyond loopback for real "
     "multi-host deployments.", "ps")

# --------------------------------------------------------------------- #
# scheduler rendezvous + liveness
# --------------------------------------------------------------------- #
_reg("HETU_SCHEDULER_ADDR", "str", None,
     "host:port of the rendezvous scheduler; servers register, workers "
     "resolve the group.", "scheduler")
_reg("HETU_SCHEDULER_PORT", "int", 23454,
     "Port the scheduler binds (serve_from_env).", "scheduler")
_reg("HETU_PS_NSERVERS", "int", None,
     "Expected server-group size for scheduler rendezvous (required "
     "with HETU_SCHEDULER_ADDR and no static addresses).", "scheduler")
_reg("HETU_PS_INDEX", "int", 0,
     "This server's index when registering with the scheduler.",
     "scheduler")
_reg("HETU_PS_ADVERTISE", "str", None,
     "Address a server advertises to the scheduler (default "
     "hostname:port).", "scheduler")
_reg("HETU_HEARTBEAT_INTERVAL", "float", 5.0,
     "Seconds between liveness beats to the scheduler.", "scheduler")

# --------------------------------------------------------------------- #
# launcher / supervisor
# --------------------------------------------------------------------- #
_reg("HETU_SUPERVISE", "bool", True,
     "heturun supervisor: respawn dead PS servers/workers; 0 restores "
     "fire-and-wait.", "launcher")
_reg("HETU_RESTART_LIMIT", "int", 3,
     "Per-slot restart budget under the supervisor.", "launcher")
_reg("HETU_RESTART_BACKOFF", "float", 0.5,
     "Base seconds of exponential restart backoff.", "launcher")
_reg("HETU_RESTART_COUNT", "int", 0,
     "Stamped into respawned children (0 = first incarnation); gates "
     "one-shot chaos kills.", "launcher")
_reg("HETU_LIVENESS_STALE", "float", 0.0,
     "> 0: supervisor kills a server whose scheduler heartbeat is "
     "staler than this many seconds (wedge detection).", "launcher")
_reg("HETU_FAILURE_LOG", "path", None,
     "JSONL sink for launcher failure/restart events ({t, event, ...} "
     "records).", "launcher")

# --------------------------------------------------------------------- #
# chaos harness
# --------------------------------------------------------------------- #
_reg("HETU_CHAOS", "str", None,
     "Deterministic fault-injection spec for the PS transports "
     "(ps/faults.py grammar: seed=/drop=/dup=/reset=/delay=/slow=/"
     "kill=/role=).", "chaos")
_reg("HETU_CHAOS_ROLE", "str", "",
     "This process's role tag (server:<idx> / worker:<rank>) for "
     "role-scoped chaos plans.", "chaos")

# --------------------------------------------------------------------- #
# embedding cache
# --------------------------------------------------------------------- #
_reg("HETU_CACHE_MAX_STALE", "int", 100,
     "Consecutive failed sync RPCs a cache tolerates before raising.",
     "cache")
_reg("HETU_CACHE_BACKLOG_ROWS", "int", 100000,
     "Max dirty rows buffered through a PS outage before raising.",
     "cache")

# --------------------------------------------------------------------- #
# serving
# --------------------------------------------------------------------- #
_reg("HETU_SERVE_FAST", "str", "auto",
     "Serving fast path: 1 forces flash-prefill + ragged decode "
     "kernels, 0 the masked/scan reference, auto = fast on TPU.",
     "serving")
_reg("HETU_SERVE_RAGGED", "str", "auto",
     "Mixed-mode ragged dispatch: 1 packs arrivals, chunk "
     "continuations, spec-verify, and decode streams into ONE ragged "
     "wave per engine step (per-slot q_len; no prefill/decode phase "
     "barrier, chunk_stall ~ 0), 0 keeps the phase-split scheduler, "
     "auto = mixed on TPU.  Greedy outputs are token-identical either "
     "way.", "serving")
_reg("HETU_SERVE_LOG", "path", None,
     "JSONL sink for serving engine events (same record shape as "
     "HETU_FAILURE_LOG).", "serving")
_reg("HETU_KV_BLOCK", "str", "auto",
     "Paged KV cache: an integer enables the block-table paged "
     "allocator at that block size (tokens per block), 0 pins the "
     "slot-contiguous layout, auto = paged with block 16 on TPU, "
     "contiguous elsewhere.", "serving")
_reg("HETU_KV_PREFIX_SHARE", "bool", True,
     "Paged KV: refcounted copy-on-write sharing of common prompt "
     "prefixes — N requests with the same system prompt store its KV "
     "blocks once (registered prefixes are LRU-evicted under pool "
     "pressure).", "serving")
_reg("HETU_SPEC_K", "int", 0,
     "Speculative decoding: a truncated-layer draft proposes up to this "
     "many tokens per slot per wave and the target verifies all k+1 "
     "positions in ONE batched step (longest-prefix acceptance + bonus "
     "token; outputs token-identical to plain decoding).  0 = off; "
     "ServingEngine(spec=)/generate_fast(spec=) override.", "serving")
_reg("HETU_SPEC_ADAPT", "bool", True,
     "Adaptive speculation depth: a sliding acceptance-rate window "
     "moves the per-wave draft length through the pow2 ladder "
     "1..HETU_SPEC_K (raise on sustained high acceptance, back off on "
     "low).  0 pins the configured k.", "serving")
_reg("HETU_SPEC_DRAFT_LAYERS", "int", 0,
     "Truncated-layer draft depth: the draft model is the target's "
     "first N blocks plus the shared final LN and tied embedding head "
     "(no separate weights or tokenizer).  0 = auto: max(1, L // 4).",
     "serving")
_reg("HETU_KV_CHUNK", "int", 0,
     "Paged KV chunked prefill: prompts fill their blocks in chunks of "
     "this many tokens interleaved with decode waves, so a long prompt "
     "does not stall running generations (0 = whole prompt in one "
     "pass).", "serving")
_reg("HETU_KV_HOST_BYTES", "int", 0,
     "Tiered KV: host-RAM ring capacity in bytes for refcount-zero "
     "prefix blocks spilled out of the HBM pool (LRU; oldest entries "
     "demote to the PS cold store when enabled, else tier-drop).  "
     "0 = tier off — eviction drops blocks exactly as before.",
     "serving")
_reg("HETU_KV_PS_TIER", "bool", False,
     "Tiered KV: enable the sharded-PS cold-store rung below the host "
     "ring (prefix payloads keyed by prefix hash, versioned put/get).  "
     "A dead/killed PS degrades the ladder to drop-on-evict with zero "
     "request loss — never an error.", "serving")
_reg("HETU_MOE_CAPACITY", "float", 0.0,
     "MoE serving: capacity-factor override for routed expert "
     "dispatch — per-expert slots per wave are top_k * ceil(tokens / "
     "num_experts * cf).  Tokens past capacity take the residual path "
     "(dropped, counted in serve.expert_drops) — never a wrong token.  "
     "0 = use the model config's own capacity_factor.", "serving")
_reg("HETU_MOE_QUANT", "str", None,
     "MoE expert-parallel dispatch/combine all-to-all wire format "
     "('int8' = symmetric per-row int8 payload + f32 scales over the "
     "expert exchange, the HETU_COMM_QUANT codec; empty/0/off = full "
     "precision).  Applies to the explicit shard_map EP reference "
     "path.", "serving")
_reg("HETU_EMBED_WAVE", "int", 8,
     "Embedding serving: max requests the engine claims per scoring "
     "wave (one embedding gather + one jitted tower forward per wave; "
     "EmbedServingEngine(wave=) overrides).", "serving")
_reg("HETU_EMBED_QUEUE", "int", 64,
     "Embedding serving: bounded admission-queue depth — submit "
     "raises QueueFull past it (EmbedServingEngine(queue_limit=) "
     "overrides).", "serving")

# --------------------------------------------------------------------- #
# serving fleet router (serving/router.py)
# --------------------------------------------------------------------- #
_reg("HETU_REPLICAS", "int", 2,
     "Default fleet size for ServingRouter: how many supervised "
     "ServingEngine replicas the router builds from its factory "
     "(constructor replicas= overrides).", "router")
_reg("HETU_ROUTER_AFFINITY", "bool", True,
     "Session affinity: hash Request.session_id to a stable home "
     "replica so a returning session's shared-prefix KV blocks stay "
     "hot (remapped with a prefix_misses count when the home replica "
     "is unroutable).", "router")
_reg("HETU_ROUTER_STALE", "float", 0.0,
     "> 0: the router kills, drains, and requeues a replica whose "
     "step heartbeat is staler than this many seconds — wedged-replica "
     "detection, the serving analog of HETU_LIVENESS_STALE.", "router")
_reg("HETU_ROUTER_BREAKER", "int", 3,
     "Per-replica circuit breaker: consecutive failures "
     "(deaths/wedge kills) that eject the replica from routing; a "
     "half-open probe request readmits it after the cooldown.",
     "router")
_reg("HETU_ROUTER_BREAKER_COOLDOWN", "float", 0.5,
     "Base seconds an open circuit breaker holds before the half-open "
     "probe (doubles per failure past the threshold).", "router")
_reg("HETU_ROUTER_RETRY_LIMIT", "int", 5,
     "Placement retries the router grants a request it holds "
     "(requeued off a dead replica / fleet full) before declaring it "
     "lost — a terminal failure with a flight dump.", "router")
_reg("HETU_ROUTER_RETRY_BACKOFF", "float", 0.02,
     "Base seconds of exponential backoff between a held request's "
     "placement retries.", "router")
_reg("HETU_ROUTER_SHED_QUEUE", "float", 0.75,
     "Fleet queue-fill fraction at which SLO-class load shedding "
     "starts: throughput-class submissions are shed (RouterShed) while "
     "latency-class requests keep admitting until hard-full.", "router")
_reg("HETU_ROUTER_SHED_ON_SLO", "bool", True,
     "Also shed throughput-class traffic while any replica's SLO "
     "health is at breach (frees capacity to pull latency-class TTFT "
     "back inside budget).", "router")
_reg("HETU_ROUTER_DIRECTORY", "bool", True,
     "Fleet prefix-cache directory: route a request whose prompt "
     "prefix is resident on replica R to R (a directory hit) before "
     "falling back to the session-affinity hash.  Entries are hints — "
     "a stale hit degrades to a cold admission, and disabling (or "
     "chaos-killing) the directory degrades the fleet to exact "
     "affinity-only routing.", "router")
_reg("HETU_ROUTER_ROLES", "str", None,
     "Prefill/decode disaggregation: comma-separated role per replica "
     "index ('prefill', 'decode', or 'mixed'; unlisted replicas are "
     "mixed).  With both roles present, long prompts prefill on a "
     "prefill-heavy replica and their KV blocks are handed off to a "
     "decode-heavy one (export_blocks/import_blocks).  Unset = every "
     "replica mixed, no handoffs.", "router")
_reg("HETU_DIRECTORY_TTL", "float", 0.0,
     "> 0: seconds an un-refreshed directory entry stays routable; "
     "expired entries are skipped (counted stale) until re-registered. "
     "0 = hints never expire (the replica's token-verified match still "
     "catches every lie).", "router")

# --------------------------------------------------------------------- #
# live weight sync (serving/weight_sync.py — rolling zero-downtime swaps)
# --------------------------------------------------------------------- #
_reg("HETU_SWAP_PROBE_TOKENS", "int", 4,
     "Greedy probe-decode length (tokens) a freshly swapped replica "
     "must retire on the NEW weight version before the rollout "
     "readmits it — the half-open check of a rolling swap.", "swap")
_reg("HETU_SWAP_DRAIN_STEPS", "int", 2000,
     "Max router steps a quiesced replica may take to drain its "
     "in-flight requests before the rollout is marked failed (and the "
     "fleet auto-rolls back).", "swap")
_reg("HETU_SWAP_ROLLBACK", "bool", True,
     "Auto-roll already-swapped replicas back to the last COMMITTED "
     "version when a rollout fails mid-swap.  0 leaves them on the new "
     "version (the rollout is still marked failed); dead replicas "
     "respawn on the committed version either way.", "swap")

# --------------------------------------------------------------------- #
# elastic fleet (serving/autoscaler.py — SLO-burn-driven autoscaling)
# --------------------------------------------------------------------- #
_reg("HETU_FLEET_MIN", "int", 1,
     "Fewest replicas the autoscaler may run: scale-down never drops "
     "the fleet below this floor (and never retires the last UP "
     "replica regardless).", "fleet")
_reg("HETU_FLEET_MAX", "int", 4,
     "Most replicas the autoscaler may run: scale-up stops at this "
     "ceiling (the equal-peak-capacity bound the autoscale_ab bench "
     "sizes its static arm to).", "fleet")
_reg("HETU_AUTOSCALE_UP_BURN", "float", 1.0,
     "Worst-replica SLO burn rate at or above which a tick counts as "
     "hot (burn >= 1 = an error budget spending faster than it "
     "refills); HETU_AUTOSCALE_UP_TICKS consecutive hot ticks trigger "
     "a scale-up.", "fleet")
_reg("HETU_AUTOSCALE_UP_PRESSURE", "float", 0.75,
     "Aggregate queue-fill fraction at or above which a tick counts "
     "as hot even without an SLO burn signal — queue pressure leads "
     "latency, so the fleet grows before the breach.", "fleet")
_reg("HETU_AUTOSCALE_UP_TICKS", "int", 3,
     "Consecutive hot ticks (one tick per router step) required to "
     "scale up — the hysteresis that keeps a one-step spike from "
     "spawning a replica.", "fleet")
_reg("HETU_AUTOSCALE_DOWN_PRESSURE", "float", 0.15,
     "Aggregate queue-fill fraction at or below which a tick counts "
     "as idle (with burn < 1 and nothing router-held); "
     "HETU_AUTOSCALE_DOWN_TICKS consecutive idle ticks trigger a "
     "scale-down.", "fleet")
_reg("HETU_AUTOSCALE_DOWN_TICKS", "int", 50,
     "Consecutive idle ticks required to scale down — deliberately "
     "much slower than scale-up (growing late sheds traffic; "
     "shrinking late only burns replica-seconds).", "fleet")
_reg("HETU_AUTOSCALE_COOLDOWN", "int", 20,
     "Refractory ticks after ANY scale action during which the "
     "autoscaler only observes — a bursty signal cannot flap the "
     "fleet.", "fleet")
_reg("HETU_AUTOSCALE_WARM_PREFIXES", "int", 4,
     "Hottest directory-known prefixes moved per membership change: "
     "imported into a joining replica before it takes traffic "
     "(scale-up warming) and exported from a retiring replica to its "
     "best peer (scale-down).  0 disables prefix movement.", "fleet")

# --------------------------------------------------------------------- #
# quantization (hetu_tpu/quant.py — one layer, three seams)
# --------------------------------------------------------------------- #
_reg("HETU_PS_QUANT", "str", None,
     "PS transport quantization: 'int8' ships push/pull payloads as "
     "symmetric per-chunk int8 + f32 scales over the wire (~3.7x fewer "
     "bytes; dequantized server-side before the optimizer step, "
     "symmetrically on pull).  Unset/0 = exact f32 wire (default).",
     "quant")
_reg("HETU_COMM_QUANT", "str", None,
     "Collective quantization: 'int8' makes DataParallel emit the "
     "quantize→all_gather→dequantize comm-op pair for dp gradient "
     "aggregation (int8 payload on the interconnect under shard_map "
     "execution; fake-quant annotation under pjit, where XLA owns the "
     "collective).  Unset/0 = plain f32 collectives (default).",
     "quant")
_reg("HETU_KV_QUANT", "str", None,
     "Serving KV-cache quantization: 'int8' stores the KV pool as int8 "
     "with per-(position, head) f32 scales (~3.7x more tokens per HBM "
     "byte; dequantized inside the decode kernels' online-softmax "
     "loop).  Unset/0 = the cache follows the weight dtype (default).",
     "quant")
_reg("HETU_QUANT_CHUNK", "int", 256,
     "Elements per f32 scale for the flat (PS wire / comm pair) int8 "
     "codec; the KV cache always scales per (position, head).", "quant")
_reg("HETU_HANDOFF_QUANT", "str", "auto",
     "Replica-to-replica KV handoff wire (export_blocks/import_blocks): "
     "'auto' ships the pool's native bytes (an int8 pool's payload + "
     "scales already are the cheap wire), 'int8' forces quantizing an "
     "exact pool's export through the per-head codec (~4x fewer "
     "bytes), '0'/'off' pins the exact wire.", "quant")

# --------------------------------------------------------------------- #
# graph/ops knobs
# --------------------------------------------------------------------- #
_reg("HETU_MOE_SCATTER_DISPATCH", "bool", False,
     "MoE dispatch formulation: row scatter-add instead of the GShard "
     "one-hot matmul (read ONCE at op construction).", "ops")

# --------------------------------------------------------------------- #
# data / planner
# --------------------------------------------------------------------- #
_reg("HETU_DATA_HOME", "path", "~/.hetu_data",
     "Dataset download/cache directory.", "data")
_reg("HETU_CALIB_SMALL", "bool", False,
     "Chip-calibration: reduced ladder for smoke runs.", "planner")
_reg("HETU_COMPILE_CACHE_DIR", "path", "/tmp/hetu_xla_cache",
     "Persistent XLA compilation-cache directory for bench runs.",
     "planner")

# --------------------------------------------------------------------- #
# bench.py
# --------------------------------------------------------------------- #
_reg("HETU_BENCH_SMALL", "bool", False,
     "Force the reduced (CPU-scale) bench configs.", "bench")
_reg("HETU_BENCH_CONFIGS", "str", None,
     "Comma-separated subset of bench matrix configs to run.", "bench")
_reg("HETU_BENCH_SWEEP", "bool", False,
     "Run the (batch x attention x head) ablation sweep.", "bench")
_reg("HETU_BENCH_DECODE", "bool", False,
     "Run the KV-cached decode benchmark.", "bench")
_reg("HETU_BENCH_SERVE", "bool", False,
     "Run the continuous-batching serving benchmark.", "bench")
_reg("HETU_BENCH_EMBED_SERVE", "bool", False,
     "Run the embedding-cache recommendation-serving benchmark "
     "(zipf cache-limit ladder, int8-pull A/B, PS-kill chaos).",
     "bench")
_reg("HETU_BENCH_CTR_ROWS", "bool", False,
     "Run the max-embedding-rows-per-chip ladder.", "bench")
_reg("HETU_BENCH_CTR_FP32", "bool", False,
     "CTR hybrid: pin full-width fp32 host-link transfers (default "
     "ships bf16).", "bench")
_reg("HETU_BENCH_FORCE_FLASH", "str", None,
     "Pin the attention impl for sweeps: 1 = flash kernel, 0 = XLA "
     "batched attention (unset = size-based crossover).", "bench")
_reg("HETU_BENCH_FUSED_HEAD", "bool", False,
     "A/B the chunked fused LM head (memory tool) against the "
     "materialized-logits default.", "bench")
_reg("HETU_BENCH_BERT_BATCH", "int", None,
     "Pin the BERT-base per-chip batch instead of probing.", "bench")
_reg("HETU_BENCH_MOE_BATCH", "int", None,
     "Override the MoE bench batch (chip-fill tuning).", "bench")
_reg("HETU_BENCH_MOE_TOKENS", "int", None,
     "Override the MoE bench tokens-per-sample.", "bench")
_reg("HETU_BENCH_LC_BLOCKS", "str", None,
     "Long-context flash tile override, 'bq,bk'.", "bench")
_reg("HETU_BENCH_NO_COMPILE_CACHE", "bool", False,
     "Opt out of the persistent XLA compile cache.", "bench")


# --------------------------------------------------------------------- #
# typed getters
# --------------------------------------------------------------------- #

def _spec(name) -> EnvVar:
    try:
        return REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unregistered env var {name!r}: every HETU_* knob must be "
            f"declared in hetu_tpu/envvars.py (one _reg line with type, "
            f"default, and help text)") from None


def _raw(name, default):
    spec = _spec(name)
    v = os.environ.get(name)
    if v is None:
        return spec.default if default is _MISSING else default
    return v


def is_set(name) -> bool:
    """True when the var is present AND non-empty in the environment."""
    _spec(name)
    return bool(os.environ.get(name))


def get_str(name, default=_MISSING):
    v = _raw(name, default)
    return v if v is None else str(v)


def get_int(name, default=_MISSING):
    v = _raw(name, default)
    return v if v is None else int(v)


def get_float(name, default=_MISSING):
    v = _raw(name, default)
    return v if v is None else float(v)


def get_bool(name, default=_MISSING) -> bool:
    v = _raw(name, default)
    if isinstance(v, bool) or v is None:
        return bool(v)
    return str(v).strip().lower() not in _FALSY


def get_path(name, default=_MISSING):
    v = _raw(name, default)
    return v if v is None else os.path.expanduser(str(v))


def get_list(name, default=_MISSING) -> list:
    """Comma-separated list; empty items dropped."""
    v = _raw(name, default)
    if v is None:
        return []
    if isinstance(v, (list, tuple)):
        return list(v)
    return [a.strip() for a in str(v).split(",") if a.strip()]


def get_raw(name):
    """The raw environment string (or None), no typing or defaulting —
    for save/restore of env state around A/B sweeps, where "unset" and
    "set to the default" must stay distinguishable."""
    _spec(name)
    return os.environ.get(name)


def require_int(name) -> int:
    """get_int that raises when the var is unset (launcher contracts)."""
    _spec(name)
    if os.environ.get(name) is None:
        raise EnvironmentError(f"required env var {name} is not set")
    return int(os.environ[name])


# --------------------------------------------------------------------- #
# documentation table (bin/hetu_lint.py --env-table; README section)
# --------------------------------------------------------------------- #

def env_table() -> str:
    """Markdown table of the full registry, grouped by section."""
    lines = ["| Variable | Type | Default | Description |",
             "|---|---|---|---|"]
    by_sec = {}
    for var in REGISTRY.values():
        by_sec.setdefault(var.section, []).append(var)
    for sec in sorted(by_sec):
        for var in sorted(by_sec[sec], key=lambda v: v.name):
            d = var.default
            if d is None:
                d = "unset"
            elif isinstance(d, bool):
                d = "1" if d else "0"
            elif isinstance(d, (tuple, list)):
                d = ",".join(d) or "unset"
            lines.append(f"| `{var.name}` | {var.type} | `{d}` | "
                         f"{var.help} |")
    return "\n".join(lines)
