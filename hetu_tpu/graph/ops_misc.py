"""Variables, placeholders, constants, and the generic VJP gradient op.

Reference counterparts: gpu_ops/Variable.py (PlaceholderOp at Variable.py:19),
gpu_ops/OnesLike.py / ZerosLike.py, gpu_ops/Arange.py, gpu_ops/Full.py.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .node import Op, SimpleOp, TraceContext


class PlaceholderOp(Op):
    """A leaf node: either a fed input (shape unknown until feed) or a
    variable (trainable parameter / non-trainable state) with a value or an
    initializer.  Reference: Variable.py:19-63.

    ``is_embed`` marks embedding tables routed to the parameter-server path
    in Hybrid mode (Variable.py:57-63).  ``reshape_in_mp`` model-parallel
    repartition (Variable.py:83-120) is unnecessary here — sharding specs
    partition parameters without touching their logical shape.
    """

    def __init__(self, name, value=None, initializer=None, trainable=True,
                 dtype=jnp.float32, ctx=None, is_embed=False):
        super().__init__(name=name, ctx=ctx)
        self.name = name  # placeholders keep their exact user name
        if dtype is np.float32:
            dtype = jnp.float32
        self.dtype = dtype
        self.is_embed = is_embed
        # sharding hint: optional PartitionSpec-like tuple set by strategies
        self.sharding_spec = None
        if value is None and initializer is None:
            trainable = False
            self.shape = None
        elif value is not None:
            assert initializer is None, "value given; initializer must be None"
            value = np.asarray(value, dtype=np.dtype(dtype) if dtype != jnp.bfloat16 else np.float32)
            self.shape = tuple(value.shape)
        else:
            self.shape = tuple(initializer.shape)
        self.tensor_value = value
        self.initializer = initializer
        self.trainable = trainable

    @property
    def is_variable(self):
        return self.tensor_value is not None or self.initializer is not None

    def init_value(self, seed: int) -> jnp.ndarray:
        """Materialize the initial value (host side, before jit).

        The stream is keyed by the variable NAME, not the global node-id
        counter: ids shift with every graph built earlier in the process,
        which would make init values depend on build order (and diverge
        across jax processes building the same model after different
        warm-up work).  Names are unique per executor."""
        if self.tensor_value is not None:
            return jnp.asarray(self.tensor_value, dtype=self.dtype)
        import zlib
        key = jax.random.fold_in(jax.random.PRNGKey(seed),
                                 zlib.crc32(self.name.encode()) & 0x7FFFFFFF)
        return self.initializer.generate(key, self.dtype)

    def compute(self, input_vals, tc: TraceContext):
        raise AssertionError(
            f"placeholder {self.name} must be fed or bound by the executor")

    def gradient(self, output_grad):
        return None

    def infer_shape(self, input_shapes, input_dtypes=None):
        assert self.shape is not None, f"feed shape needed for {self.name}"
        return self.shape


def Variable(name, value=None, initializer=None, trainable=True,
             dtype=jnp.float32, ctx=None):
    """Reference Variable.py:8-16."""
    return PlaceholderOp(name, value, initializer, trainable, dtype, ctx)


def placeholder_op(name, value=None, initializer=None, trainable=True,
                   dtype=jnp.float32, ctx=None):
    return PlaceholderOp(name, value, initializer, trainable, dtype, ctx)


class VJPOp(Op):
    """Generic cotangent node: grad of ``orig``'s ``input_index``-th input.

    The forward is recomputed inside ``jax.vjp`` at trace time; XLA CSE
    merges it with the original forward computation, so the compiled program
    contains each forward op once.  This one node replaces the majority of
    hand-written backward kernels in the reference (src/ops/*.cu)."""

    def __init__(self, orig: Op, output_grad: Op, input_index: int):
        super().__init__(*orig.inputs, output_grad,
                         name=f"grad_{orig.name}_in{input_index}")
        self._orig = orig
        self._idx = input_index

    def compute(self, input_vals, tc: TraceContext):
        *xs, g = input_vals
        # sandbox the recomputed forward: stateful ops (e.g. BatchNorm
        # running stats) write to tc.extra_outputs, and writes from inside
        # the vjp trace would leak inner tracers into the outer jit trace.
        inner_tc = TraceContext(
            params=tc.params, rng=tc._rng, training=tc.training,
            mesh=tc.mesh, axis_env=tc.axis_env, config=tc.config,
            step=tc.step)
        # same RNG stream ids as the outer trace — the recomputed forward
        # must see the identical dropout mask the primal forward used
        inner_tc.rng_ids = tc.rng_ids

        def primal(*a):
            return self._orig.compute(list(a), inner_tc)

        primal_out, vjp = jax.vjp(primal, *xs)
        cot = vjp(jnp.asarray(g, dtype=primal_out.dtype))
        return cot[self._idx]

    def gradient(self, output_grad):
        raise NotImplementedError("second-order autodiff not supported")


class SumOp(Op):
    """Merge partial adjoints (reference executor.py:1393 sum_node_list via
    gpu_ops/Sum.py). Dense inputs sum elementwise; IndexedSlices-style
    sparse adjoints are densified first (sparse path: ops_embed)."""

    def __init__(self, nodes, ctx=None):
        super().__init__(*nodes, name="Sum", ctx=ctx)

    def jax_fn(self, *vals):
        out = vals[0]
        for v in vals[1:]:
            out = out + v
        return out

    def gradient(self, output_grad):
        return [output_grad for _ in self.inputs]


def sum_op(nodes, ctx=None):
    return SumOp(nodes, ctx=ctx)


class OnesLikeOp(Op):
    def __init__(self, node, ctx=None):
        super().__init__(node, name="OnesLike", ctx=ctx)

    def jax_fn(self, x):
        return jnp.ones_like(x)

    def gradient(self, output_grad):
        return [None]


class ZerosLikeOp(Op):
    def __init__(self, node, ctx=None):
        super().__init__(node, name="ZerosLike", ctx=ctx)

    def jax_fn(self, x):
        return jnp.zeros_like(x)

    def gradient(self, output_grad):
        return [None]


def oneslike_op(node, ctx=None):
    return OnesLikeOp(node, ctx=ctx)


def zeroslike_op(node, ctx=None):
    return ZerosLikeOp(node, ctx=ctx)


def full_op(shape, fill_value, ctx=None):
    op = SimpleOp(lambda: jnp.full(shape, fill_value), name="Full", ctx=ctx)
    op.gradient = lambda output_grad: []
    return op


def full_like_op(node, fill_value, ctx=None):
    op = SimpleOp(lambda x: jnp.full_like(x, fill_value), node,
                  name="FullLike", ctx=ctx)
    op.gradient = lambda output_grad: [None]
    return op


def arange_op(start, end, step=1, ctx=None):
    op = SimpleOp(lambda: jnp.arange(start, end, step, dtype=jnp.float32),
                  name="Arange", ctx=ctx)
    op.gradient = lambda output_grad: []
    return op


class RandOp(Op):
    """Uniform [0,1) random tensor, fresh each step (reference gpu_ops/Rand.py)."""

    def __init__(self, shape, ctx=None):
        super().__init__(name="Rand", ctx=ctx)
        self.shape = tuple(shape)

    def compute(self, input_vals, tc: TraceContext):
        return jax.random.uniform(tc.rng_for(self), self.shape)

    def gradient(self, output_grad):
        return []


def rand_op(shape, ctx=None):
    return RandOp(shape, ctx=ctx)
