"""Conv / pooling / normalization / dropout op factories.

Reference: gpu_ops/Conv2d*.py, MaxPool.py, AvgPool.py, BatchNorm.py,
LayerNorm.py, InstanceNorm2d.py, Dropout.py (cuDNN kernels in
src/ops/Cudnn*.cu).  Layout is NCHW / OIHW to match the reference API; XLA
re-lays-out internally for the MXU so this costs nothing.

BatchNorm running stats are *graph state*: the op owns hidden non-trainable
state variables threaded through the jitted step by the executor (the
reference mutates kernel-side buffers instead, src/ops/CudnnBn.cu).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .node import Op, TraceContext
from .ops_math import _simple
from .ops_misc import PlaceholderOp


_DIMNUMS = ("NCHW", "OIHW", "NCHW")


def conv2d_op(a, w, stride=1, padding=0, ctx=None):
    if not isinstance(stride, (list, tuple)):
        stride = (stride, stride)
    if not isinstance(padding, (list, tuple)):
        padding = (padding, padding)

    def f(x, k):
        # no preferred_element_type: conv's transpose rule feeds the f32
        # cotangent back into a conv with the bf16 filter and trips the
        # same-dtype check (unlike dot_general's); the MXU accumulates
        # conv partials in f32 regardless, so nothing is lost
        return jax.lax.conv_general_dilated(
            x, k, window_strides=tuple(stride),
            padding=[(padding[0], padding[0]), (padding[1], padding[1])],
            dimension_numbers=_DIMNUMS)
    return _simple("Conv2d", f, a, w, ctx=ctx)


def conv2d_add_bias_op(a, w, bias, stride=1, padding=0, ctx=None):
    if not isinstance(stride, (list, tuple)):
        stride = (stride, stride)
    if not isinstance(padding, (list, tuple)):
        padding = (padding, padding)

    def f(x, k, b):
        # see conv2d_op on the absent preferred_element_type
        y = jax.lax.conv_general_dilated(
            x, k, window_strides=tuple(stride),
            padding=[(padding[0], padding[0]), (padding[1], padding[1])],
            dimension_numbers=_DIMNUMS)
        return y + b.reshape(1, -1, 1, 1)
    return _simple("Conv2dAddBias", f, a, w, bias, ctx=ctx)


def conv2d_broadcastto_op(bias, target, ctx=None):
    """(C,) -> (N,C,H,W) broadcast (reference gpu_ops/Conv2dBroadcast.py)."""
    return _simple("Conv2dBroadcastTo",
                   lambda b, t: jnp.broadcast_to(b.reshape(1, -1, 1, 1), t.shape),
                   bias, target, ctx=ctx)


def conv2d_reducesum_op(a, ctx=None):
    """Sum over N,H,W — bias gradient (reference gpu_ops/Conv2dReduceSum.py)."""
    return _simple("Conv2dReduceSum", lambda x: jnp.sum(x, axis=(0, 2, 3)), a,
                   ctx=ctx)


def max_pool2d_op(a, kernel_H, kernel_W, padding=0, stride=1, ctx=None):
    if not isinstance(stride, (list, tuple)):
        stride = (stride, stride)
    if not isinstance(padding, (list, tuple)):
        padding = (padding, padding)

    def f(x):
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max,
            window_dimensions=(1, 1, kernel_H, kernel_W),
            window_strides=(1, 1) + tuple(stride),
            padding=((0, 0), (0, 0),
                     (padding[0], padding[0]), (padding[1], padding[1])))
    return _simple("MaxPool2d", f, a, ctx=ctx)


def avg_pool2d_op(a, kernel_H, kernel_W, padding=0, stride=1, ctx=None):
    if not isinstance(stride, (list, tuple)):
        stride = (stride, stride)
    if not isinstance(padding, (list, tuple)):
        padding = (padding, padding)

    def f(x):
        s = jax.lax.reduce_window(
            x, 0.0, jax.lax.add,
            window_dimensions=(1, 1, kernel_H, kernel_W),
            window_strides=(1, 1) + tuple(stride),
            padding=((0, 0), (0, 0),
                     (padding[0], padding[0]), (padding[1], padding[1])))
        return s / (kernel_H * kernel_W)
    return _simple("AvgPool2d", f, a, ctx=ctx)


class BatchNormOp(Op):
    """BatchNorm over NCHW with running-stat state variables.

    Reference gpu_ops/BatchNorm.py (momentum/eps defaults match
    batch_normalization_op(x, scale, bias, momentum=0.99, eps=0.01); the
    ResNet example passes momentum=0.9, eps=1e-5).  Train mode uses batch
    stats and updates running stats; eval mode uses running stats.
    """

    def __init__(self, x, scale, bias, momentum=0.99, eps=0.01, ctx=None):
        super().__init__(x, scale, bias, name="BatchNorm", ctx=ctx)
        self.momentum = momentum
        self.eps = eps
        c = scale.shape[0] if scale.shape else None
        # state names derive from the scale param's (user-stable) name,
        # NOT the auto node id — otherwise running stats silently fail to
        # reload from a checkpoint in a fresh process.  A reused scale
        # (same BatchNorm layer applied twice) gets a per-use suffix so
        # the two ops' states don't collide.
        base = getattr(scale, "name", self.name)
        uses = getattr(scale, "_bn_uses", 0)
        if isinstance(scale, PlaceholderOp):
            scale._bn_uses = uses + 1
        if uses:
            base = f"{base}_{uses}"
        self.running_mean = PlaceholderOp(
            f"{base}_running_mean",
            value=jnp.zeros((c,)) if c else None, trainable=False)
        self.running_var = PlaceholderOp(
            f"{base}_running_var",
            value=jnp.ones((c,)) if c else None, trainable=False)
        self.state_vars = [self.running_mean, self.running_var]

    def compute(self, input_vals, tc: TraceContext):
        x, scale, bias = input_vals
        rm = tc.params[self.running_mean]
        rv = tc.params[self.running_var]
        if tc.training:
            axes = (0, 2, 3) if x.ndim == 4 else (0,)
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            m = self.momentum
            tc.extra_outputs[self.running_mean] = m * rm + (1 - m) * mean
            tc.extra_outputs[self.running_var] = m * rv + (1 - m) * var
        else:
            mean, var = rm, rv
        shape = (1, -1, 1, 1) if x.ndim == 4 else (1, -1)
        inv = jax.lax.rsqrt(var.reshape(shape) + self.eps)
        return (x - mean.reshape(shape)) * inv * scale.reshape(shape) \
            + bias.reshape(shape)

    def gradient(self, output_grad):
        from .node import vjp_gradient
        return vjp_gradient(self, output_grad)


def batch_normalization_op(x, scale, bias, momentum=0.99, eps=0.01, ctx=None):
    return BatchNormOp(x, scale, bias, momentum, eps, ctx=ctx)


def layer_normalization_op(x, scale, bias, eps=0.01, ctx=None):
    """LayerNorm over the last dim (reference gpu_ops/LayerNorm.py)."""
    def f(a, s, b):
        mean = jnp.mean(a, axis=-1, keepdims=True)
        var = jnp.var(a, axis=-1, keepdims=True)
        return (a - mean) * jax.lax.rsqrt(var + eps) * s + b
    return _simple("LayerNorm", f, x, scale, bias, ctx=ctx)


def instance_normalization2d_op(x, eps=1e-7, ctx=None):
    def f(a):
        mean = jnp.mean(a, axis=(2, 3), keepdims=True)
        var = jnp.var(a, axis=(2, 3), keepdims=True)
        return (a - mean) * jax.lax.rsqrt(var + eps)
    return _simple("InstanceNorm2d", f, x, ctx=ctx)


class DropoutOp(Op):
    """Inverted dropout with per-step RNG from the trace context; identity
    in eval mode (reference gpu_ops/Dropout.py keeps a seed per op —
    here the key is fold_in(step_key, node.id), so backward recomputation
    inside VJP sees the identical mask)."""

    def __init__(self, x, keep_prob, spatial=False, ctx=None):
        super().__init__(x, name="Dropout", ctx=ctx)
        self.keep_prob = keep_prob
        self.spatial = spatial

    def compute(self, input_vals, tc: TraceContext):
        (x,) = input_vals
        if not tc.training or self.keep_prob >= 1.0:
            return x
        shape = (x.shape[0], x.shape[1], 1, 1) if self.spatial else x.shape
        mask = jax.random.bernoulli(tc.rng_for(self), self.keep_prob, shape)
        return jnp.where(mask, x / self.keep_prob, 0.0).astype(x.dtype)

    def gradient(self, output_grad):
        from .node import vjp_gradient
        return vjp_gradient(self, output_grad)


def dropout_op(x, keep_prob, ctx=None):
    return DropoutOp(x, keep_prob, ctx=ctx)


def dropout2d_op(x, keep_prob, ctx=None):
    return DropoutOp(x, keep_prob, spatial=True, ctx=ctx)
