"""Reverse-mode autodiff over the dataflow graph.

Mirrors the reference ``gradients()`` (gpu_ops/executor.py:1071-1189):
reverse topo walk, per-node ``gradient()`` building backward nodes, partial
adjoints merged with SumOp (executor.py:1393 sum_node_list).  Sparse
(IndexedSlices) adjoints pass through un-merged when single, densified when
summed — same policy as executor.py:1119-1127.

The backward2forward / forward2backward maps are preserved because the
pipeline partitioner uses them (reference: gpipe partition at
pipeline_subexecutor.py:29-81).
"""

from __future__ import annotations

from .node import Op
from .ops_misc import OnesLikeOp, SumOp, PlaceholderOp
from .ops_embed import IndexedSlicesOp


def find_topo_sort(node_list):
    visited = set()
    topo = []

    def dfs(n):
        if id(n) in visited:
            return
        visited.add(id(n))
        for i in n.inputs:
            dfs(i)
        topo.append(n)

    for n in node_list:
        dfs(n)
    return topo


def sum_node_list(node_list):
    node_list = [n for n in node_list if n is not None]
    if not node_list:
        return None
    if len(node_list) == 1:
        return node_list[0]
    if all(isinstance(n, IndexedSlicesOp) for n in node_list) and \
            len({id(n.inputs[0]) for n in node_list}) == 1:
        # several lookups into one table: keep the adjoint SPARSE by
        # concatenating (ids, rows) — consumers merge duplicates
        from .ops_embed import merge_indexed_slices
        return merge_indexed_slices(node_list)
    return SumOp(node_list)


def gradients(output_node, node_list, insert_grad=None, return_all=False):
    """Build gradient nodes of ``output_node`` w.r.t. each node in
    ``node_list``.  ``insert_grad`` seeds a custom output adjoint
    (reference executor.py:1071 signature parity)."""
    if insert_grad is None:
        insert_grad = OnesLikeOp(output_node)
    node_to_grads = {id(output_node): [insert_grad]}
    node_to_grad = {}
    key_to_node = {id(output_node): output_node}

    reverse_topo = list(reversed(find_topo_sort([output_node])))
    backward2forward = {}
    forward2backward = {}

    for node in reverse_topo:
        grads = node_to_grads.get(id(node))
        if grads is None:
            continue
        # merge partial adjoints; keep sparse adjoints sparse when single
        grad = sum_node_list(grads)
        if grad is None:
            continue
        node_to_grad[id(node)] = grad
        key_to_node[id(node)] = node
        if isinstance(node, PlaceholderOp):
            continue
        if isinstance(node, (OnesLikeOp,)):
            continue
        try:
            input_grads = node.gradient(grad)
        except NotImplementedError:
            from .node import vjp_gradient
            input_grads = vjp_gradient(node, grad)
        if input_grads is None:
            continue
        assert len(input_grads) == len(node.inputs), (
            f"{node}: gradient returned {len(input_grads)} for "
            f"{len(node.inputs)} inputs")
        forward2backward[node] = [g for g in input_grads if g is not None]
        for inp, g in zip(node.inputs, input_grads):
            if g is None:
                continue
            backward2forward[g] = (node, inp)
            node_to_grads.setdefault(id(inp), []).append(g)

    results = []
    for n in node_list:
        g = node_to_grad.get(id(n))
        assert g is not None, f"no gradient path from output to {n}"
        results.append(g)
    if return_all:
        return results, backward2forward, forward2backward
    return results
