"""Matmul-family op factories — the MXU workhorses.

Reference: gpu_ops/MatrixMult.py (cublasSgemm via src/ops/MatrixMult.cu),
Linear.py, BatchMatrixMult.py, Baddbmm.py, Addmm.py, MatrixDot.py, Outer.py,
CuSparse.py (csrmm/csrmv).  All lower to ``jax.lax.dot_general`` which XLA
tiles onto the 128x128 systolic array; ``preferred_element_type`` keeps
accumulation in fp32 when activations are bf16.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .ops_math import _simple


def _mm(x, y, ta, tb):
    if ta:
        x = x.T
    if tb:
        y = y.T
    return jnp.matmul(x, y, preferred_element_type=jnp.float32).astype(x.dtype)


def matmul_op(a, b, trans_A=False, trans_B=False, ctx=None):
    return _simple("Matmul", lambda x, y: _mm(x, y, trans_A, trans_B), a, b,
                   ctx=ctx)


def linear_op(a, w, bias, trans_A=False, trans_B=False, ctx=None):
    """x @ w + bias fused (reference gpu_ops/Linear.py)."""
    return _simple("Linear",
                   lambda x, y, b: _mm(x, y, trans_A, trans_B) + b,
                   a, w, bias, ctx=ctx)


def batch_matmul_op(a, b, trans_A=False, trans_B=False, ctx=None):
    def f(x, y):
        if trans_A:
            x = jnp.swapaxes(x, -1, -2)
        if trans_B:
            y = jnp.swapaxes(y, -1, -2)
        return jnp.matmul(x, y, preferred_element_type=jnp.float32).astype(x.dtype)
    return _simple("BatchMatmul", f, a, b, ctx=ctx)


def baddbmm_op(inp, a, b, alpha=1.0, beta=1.0, ctx=None):
    return _simple("Baddbmm",
                   lambda i, x, y: beta * i + alpha * jnp.matmul(x, y),
                   inp, a, b, ctx=ctx)


def addmm_op(inp, a, b, alpha=1.0, beta=1.0, ctx=None):
    return _simple("Addmm",
                   lambda i, x, y: beta * i + alpha * jnp.matmul(x, y),
                   inp, a, b, ctx=ctx)


def addmm_gradient_op(grad, axis=0, ctx=None):
    """Sum the bias adjoint over rows if bias was broadcast."""
    return _simple("AddmmGrad", lambda g: jnp.sum(g, axis=axis), grad, ctx=ctx)


def matrix_dot_op(a, b, ctx=None):
    """Elementwise product summed over rows? Reference MatrixDot = elementwise
    multiply (per gpu_ops/MatrixDot.py kernel semantics)."""
    return _simple("MatrixDot", lambda x, y: x * y, a, b, ctx=ctx)


def outer_op(a, b, ctx=None):
    return _simple("Outer", lambda x, y: jnp.outer(x, y), a, b, ctx=ctx)


# sparse @ dense — TPU has no cuSPARSE; CSR inputs are densified via
# segment-sum, which XLA handles well for the moderate sparsities the
# reference targets (CTR feature matrices).

def csrmv_op(data, row, col, mat_shape, vec, trans=False, ctx=None):
    def f(d, r, c, v):
        dense = jnp.zeros(mat_shape, v.dtype).at[r.astype(jnp.int32),
                                                 c.astype(jnp.int32)].add(d)
        m = dense.T if trans else dense
        return m @ v
    return _simple("CsrMV", f, data, row, col, vec, ctx=ctx)


def csrmm_op(data, row, col, mat_shape, mat, trans=False, ctx=None):
    def f(d, r, c, m2):
        dense = jnp.zeros(mat_shape, m2.dtype).at[r.astype(jnp.int32),
                                                  c.astype(jnp.int32)].add(d)
        m = dense.T if trans else dense
        return m @ m2
    return _simple("CsrMM", f, data, row, col, mat, ctx=ctx)
