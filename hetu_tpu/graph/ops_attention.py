"""Attention graph ops backed by the Pallas flash kernel.

No reference counterpart (the reference builds attention from
batch_matmul/softmax inline, examples/nlp/bert/hetu_bert.py); this is the
fused fast path.  Gradient flows through the kernel's custom_vjp via the
generic VJPOp fallback.
"""

from __future__ import annotations

from .node import Op, SimpleOp


class CausalMaskOp(Op):
    """Additive causal mask ``(1, 1, S, S)`` built in-trace from iota
    comparisons (as the flash kernel does) — never materialized as a stored
    Variable, so it costs no checkpoint bytes and is fused by XLA into the
    consuming add.  Emits the trace's mixed-precision policy dtype, exactly
    as a stored-Variable mask would have entered via the executor's input
    cast — otherwise a f32 mask would silently promote the whole unfused
    attention tail under a bf16 policy."""

    def __init__(self, seq_len, neg, ctx=None):
        super().__init__(name="CausalMask", ctx=ctx)
        self.seq_len = seq_len
        self.neg = neg

    def compute(self, input_vals, tc):
        import jax
        import jax.numpy as jnp
        S = self.seq_len
        dtype = (getattr(tc.config, "mixed_precision", None)
                 if tc.config is not None else None) or jnp.float32
        i = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
        j = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
        return jnp.where(j <= i, 0.0, self.neg).astype(dtype)[None, None]

    def gradient(self, output_grad):
        return []


def causal_mask_op(seq_len, neg=None, ctx=None):
    if neg is None:
        from ..kernels.flash_attention import NEG_INF
        neg = NEG_INF
    return CausalMaskOp(seq_len, neg, ctx=ctx)


def flash_attention_op(q, k, v, causal=False, kv_lens=None, block_q=None,
                       block_k=None, ctx=None):
    """Fused attention on [B, S, H, D] q/k/v nodes -> [B, S, H, D].

    ``kv_lens``: optional [B] int node — keys/values at positions >=
    kv_lens[b] are masked (padding mask).  block_q/block_k default to
    the kernel's tuned values (single source of truth in
    kernels/flash_attention.py)."""
    from ..kernels.flash_attention import flash_attention

    kw = {}
    if block_q is not None:
        kw["block_q"] = block_q
    if block_k is not None:
        kw["block_k"] = block_k

    def fn(q, k, v, lens=None):
        return flash_attention(q, k, v, causal=causal, kv_lens=lens, **kw)

    inputs = (q, k, v) + ((kv_lens,) if kv_lens is not None else ())
    return SimpleOp(fn, *inputs, name="FlashAttention", ctx=ctx)


def ring_attention_op(q, k, v, mesh, axis="cp", causal=False, impl=None,
                      ctx=None):
    """Ring attention over a sequence-sharded 'cp' mesh axis (long-context
    path, SURVEY.md §5.7 — new capability vs the reference).  ``impl``:
    'flash' (fused Pallas block kernel — the TPU default), 'exact', or
    None = auto by backend."""
    from ..parallel.context_parallel import ring_attention

    def fn(q, k, v):
        return ring_attention(q, k, v, mesh=mesh, axis=axis, causal=causal,
                              impl=impl)

    return SimpleOp(fn, q, k, v, name="RingAttention", ctx=ctx)
