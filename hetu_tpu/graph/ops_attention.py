"""Attention graph ops backed by the Pallas flash kernel.

No reference counterpart (the reference builds attention from
batch_matmul/softmax inline, examples/nlp/bert/hetu_bert.py); this is the
fused fast path.  Gradient flows through the kernel's custom_vjp via the
generic VJPOp fallback.
"""

from __future__ import annotations

from .node import SimpleOp


def flash_attention_op(q, k, v, causal=False, kv_lens=None, block_q=None,
                       block_k=None, ctx=None):
    """Fused attention on [B, S, H, D] q/k/v nodes -> [B, S, H, D].

    ``kv_lens``: optional [B] int node — keys/values at positions >=
    kv_lens[b] are masked (padding mask).  block_q/block_k default to
    the kernel's tuned values (single source of truth in
    kernels/flash_attention.py)."""
    from ..kernels.flash_attention import flash_attention

    kw = {}
    if block_q is not None:
        kw["block_q"] = block_q
    if block_k is not None:
        kw["block_k"] = block_k

    def fn(q, k, v, lens=None):
        return flash_attention(q, k, v, causal=causal, kv_lens=lens, **kw)

    inputs = (q, k, v) + ((kv_lens,) if kv_lens is not None else ())
    return SimpleOp(fn, *inputs, name="FlashAttention", ctx=ctx)


def ring_attention_op(q, k, v, mesh, axis="cp", causal=False, ctx=None):
    """Ring attention over a sequence-sharded 'cp' mesh axis (long-context
    path, SURVEY.md §5.7 — new capability vs the reference)."""
    from ..parallel.context_parallel import ring_attention

    def fn(q, k, v):
        return ring_attention(q, k, v, mesh=mesh, axis=axis, causal=causal)

    return SimpleOp(fn, q, k, v, name="RingAttention", ctx=ctx)
