"""Loss op factories.

Reference: gpu_ops/SoftmaxCrossEntropy.py, SoftmaxCrossEntropySparse.py,
CrossEntropy.py, CrossEntropySparse.py, BinaryCrossEntropy.py, NllLoss.py
(kernels src/ops/SoftmaxCrossEntropy.cu etc.).  Reference ops return the
per-example loss vector (reduction happens via reduce_mean in user code),
and we preserve that.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .ops_math import _simple


def softmaxcrossentropy_op(a, labels, ctx=None):
    """One-hot labels; returns per-example loss (N,)."""
    def f(x, y):
        lse = jax.nn.log_softmax(x, axis=-1)
        return -jnp.sum(y * lse, axis=-1)
    return _simple("SoftmaxCrossEntropy", f, a, labels,
                   grad_rule=lambda n, g: _sce_grad(n, g), ctx=ctx)


def _sce_grad(node, g):
    x, y = node.inputs

    def f(gr, xx, yy):
        p = jax.nn.softmax(xx, axis=-1)
        return gr[..., None] * (p - yy)
    return [_simple("SoftmaxCrossEntropyGrad", f, g, x, y), None]


def softmaxcrossentropy_sparse_op(a, labels, ignored_index=-1, ctx=None):
    """Integer labels; entries equal to ignored_index contribute 0."""
    def f(x, y):
        y = y.astype(jnp.int32)
        lse = jax.nn.log_softmax(x, axis=-1)
        safe = jnp.where(y == ignored_index, 0, y)
        ll = jnp.take_along_axis(lse, safe[..., None], axis=-1)[..., 0]
        return jnp.where(y == ignored_index, 0.0, -ll)
    return _simple("SoftmaxCrossEntropySparse", f, a, labels,
                   grad_rule=lambda n, g: _sce_sparse_grad(n, g, ignored_index),
                   ctx=ctx)


def _sce_sparse_grad(node, g, ignored_index):
    x, y = node.inputs

    def f(gr, xx, yy):
        yy = yy.astype(jnp.int32)
        p = jax.nn.softmax(xx, axis=-1)
        onehot = jax.nn.one_hot(jnp.where(yy == ignored_index, 0, yy),
                                xx.shape[-1], dtype=xx.dtype)
        grad = gr[..., None] * (p - onehot)
        return jnp.where((yy == ignored_index)[..., None], 0.0, grad)
    return [_simple("SoftmaxCrossEntropySparseGrad", f, g, x, y), None]


def crossentropy_op(probs, labels, ctx=None):
    """-sum(y * log p) given probabilities (reference CrossEntropy.py)."""
    def f(p, y):
        return -jnp.sum(y * jnp.log(jnp.maximum(p, 1e-12)), axis=-1)
    return _simple("CrossEntropy", f, probs, labels, ctx=ctx)


def crossentropy_sparse_op(probs, labels, ignored_index=-1, ctx=None):
    def f(p, y):
        y = y.astype(jnp.int32)
        safe = jnp.where(y == ignored_index, 0, y)
        pl = jnp.take_along_axis(p, safe[..., None], axis=-1)[..., 0]
        loss = -jnp.log(jnp.maximum(pl, 1e-12))
        return jnp.where(y == ignored_index, 0.0, loss)
    return _simple("CrossEntropySparse", f, probs, labels, ctx=ctx)


def binarycrossentropy_op(preds, labels, ctx=None):
    def f(p, y):
        p = jnp.clip(p, 1e-12, 1 - 1e-12)
        return -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
    return _simple("BinaryCrossEntropy", f, preds, labels, ctx=ctx)


def binarycrossentropywithlogits_op(logits, labels, ctx=None):
    def f(z, y):
        return jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
    return _simple("BCEWithLogits", f, logits, labels, ctx=ctx)


def nll_loss_op(log_probs, labels, ctx=None):
    def f(lp, y):
        y = y.astype(jnp.int32)
        return -jnp.take_along_axis(lp, y[..., None], axis=-1)[..., 0]
    return _simple("NllLoss", f, log_probs, labels, ctx=ctx)


def mseloss_op(preds, labels, ctx=None):
    return _simple("MSELoss", lambda p, y: jnp.mean((p - y) ** 2), preds, labels,
                   ctx=ctx)
