"""Loss op factories.

Reference: gpu_ops/SoftmaxCrossEntropy.py, SoftmaxCrossEntropySparse.py,
CrossEntropy.py, CrossEntropySparse.py, BinaryCrossEntropy.py, NllLoss.py
(kernels src/ops/SoftmaxCrossEntropy.cu etc.).  Reference ops return the
per-example loss vector (reduction happens via reduce_mean in user code),
and we preserve that.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .ops_math import _simple


def softmaxcrossentropy_op(a, labels, ctx=None):
    """One-hot labels; returns per-example loss (N,)."""
    def f(x, y):
        lse = jax.nn.log_softmax(x, axis=-1)
        return -jnp.sum(y * lse, axis=-1)
    return _simple("SoftmaxCrossEntropy", f, a, labels,
                   grad_rule=lambda n, g: _sce_grad(n, g), ctx=ctx)


def _sce_grad(node, g):
    x, y = node.inputs

    def f(gr, xx, yy):
        p = jax.nn.softmax(xx, axis=-1)
        return gr[..., None] * (p - yy)
    return [_simple("SoftmaxCrossEntropyGrad", f, g, x, y), None]


def softmaxcrossentropy_sparse_op(a, labels, ignored_index=-1, ctx=None):
    """Integer labels; entries equal to ignored_index contribute 0."""
    def f(x, y):
        y = y.astype(jnp.int32)
        lse = jax.nn.log_softmax(x, axis=-1)
        safe = jnp.where(y == ignored_index, 0, y)
        ll = jnp.take_along_axis(lse, safe[..., None], axis=-1)[..., 0]
        return jnp.where(y == ignored_index, 0.0, -ll)
    return _simple("SoftmaxCrossEntropySparse", f, a, labels,
                   grad_rule=lambda n, g: _sce_sparse_grad(n, g, ignored_index),
                   ctx=ctx)


def _sce_sparse_grad(node, g, ignored_index):
    x, y = node.inputs

    def f(gr, xx, yy):
        yy = yy.astype(jnp.int32)
        p = jax.nn.softmax(xx, axis=-1)
        onehot = jax.nn.one_hot(jnp.where(yy == ignored_index, 0, yy),
                                xx.shape[-1], dtype=xx.dtype)
        grad = gr[..., None] * (p - onehot)
        return jnp.where((yy == ignored_index)[..., None], 0.0, grad)
    return [_simple("SoftmaxCrossEntropySparseGrad", f, g, x, y), None]


def crossentropy_op(probs, labels, ctx=None):
    """-sum(y * log p) given probabilities (reference CrossEntropy.py)."""
    def f(p, y):
        return -jnp.sum(y * jnp.log(jnp.maximum(p, 1e-12)), axis=-1)
    return _simple("CrossEntropy", f, probs, labels, ctx=ctx)


def crossentropy_sparse_op(probs, labels, ignored_index=-1, ctx=None):
    def f(p, y):
        y = y.astype(jnp.int32)
        safe = jnp.where(y == ignored_index, 0, y)
        pl = jnp.take_along_axis(p, safe[..., None], axis=-1)[..., 0]
        loss = -jnp.log(jnp.maximum(pl, 1e-12))
        return jnp.where(y == ignored_index, 0.0, loss)
    return _simple("CrossEntropySparse", f, probs, labels, ctx=ctx)


def binarycrossentropy_op(preds, labels, ctx=None):
    def f(p, y):
        p = jnp.clip(p, 1e-12, 1 - 1e-12)
        return -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
    return _simple("BinaryCrossEntropy", f, preds, labels, ctx=ctx)


def binarycrossentropywithlogits_op(logits, labels, ctx=None):
    def f(z, y):
        return jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
    return _simple("BCEWithLogits", f, logits, labels, ctx=ctx)


def nll_loss_op(log_probs, labels, ctx=None):
    def f(lp, y):
        y = y.astype(jnp.int32)
        return -jnp.take_along_axis(lp, y[..., None], axis=-1)[..., 0]
    return _simple("NllLoss", f, log_probs, labels, ctx=ctx)


def mseloss_op(preds, labels, ctx=None):
    return _simple("MSELoss", lambda p, y: jnp.mean((p - y) ** 2), preds, labels,
                   ctx=ctx)


# --------------------------------------------------------------------- #
# fused LM-head + softmax-xent (chunked over rows)
# --------------------------------------------------------------------- #

def _xent_chunk_shapes(N, n_chunks):
    C = -(-N // n_chunks)
    return C, C * n_chunks - N


def _chunked_xent_fwd(h, W, b, y, ignored_index, n_chunks):
    """Per-row loss of ``softmax_xent(h @ W.T + b, y)`` without ever
    materializing the full [N, V] logits: a scan over row chunks keeps
    only one [C, V] block live.  The block stays in the compute dtype
    (bf16 under mixed precision, matching the unfused path's numerics);
    the logsumexp/softmax reductions run in fp32 via casts that fuse
    into the reductions."""
    N, H = h.shape
    C, pad = _xent_chunk_shapes(N, n_chunks)
    y = y.astype(jnp.int32)
    if pad:
        h = jnp.pad(h, ((0, pad), (0, 0)))
        y = jnp.pad(y, (0, pad), constant_values=ignored_index)
    hs = h.reshape(n_chunks, C, H)
    ys = y.reshape(n_chunks, C)

    def body(_, hy):
        hc, yc = hy
        # logits stay in the compute dtype (matching the unfused path's
        # numerics under bf16 mixed precision); the f32 upcast fuses
        # into the reductions so no f32 [C, V] buffer materializes
        logits = jnp.matmul(hc, W.T,
                            preferred_element_type=jnp.float32) \
            .astype(hc.dtype) + b
        lse = jax.scipy.special.logsumexp(
            logits.astype(jnp.float32), axis=-1)
        safe = jnp.where(yc == ignored_index, 0, yc)
        ll = jnp.take_along_axis(logits, safe[:, None],
                                 axis=-1)[:, 0].astype(jnp.float32)
        return None, jnp.where(yc == ignored_index, 0.0, lse - ll)

    _, losses = jax.lax.scan(body, None, (hs, ys))
    return losses.reshape(n_chunks * C)[:N]


def _chunked_xent_bwd(gr, h, W, b, y, ignored_index, n_chunks):
    """(dh, dW, db) for _chunked_xent_fwd, recomputing each logits chunk
    instead of reading a stored [N, V] gradient tensor.  dW/db
    accumulate in fp32 scan carries."""
    N, H = h.shape
    V = W.shape[0]
    C, pad = _xent_chunk_shapes(N, n_chunks)
    y = y.astype(jnp.int32)
    if pad:
        h = jnp.pad(h, ((0, pad), (0, 0)))
        y = jnp.pad(y, (0, pad), constant_values=ignored_index)
        gr = jnp.pad(gr, (0, pad))
    hs = h.reshape(n_chunks, C, H)
    ys = y.reshape(n_chunks, C)
    grs = gr.reshape(n_chunks, C)

    def body(carry, hyg):
        dW, db = carry
        hc, yc, gc = hyg
        logits = jnp.matmul(hc, W.T,
                            preferred_element_type=jnp.float32) \
            .astype(hc.dtype) + b
        # softmax with f32 reductions but a compute-dtype [C, V] buffer
        # (the f32 casts fuse into the reductions/matmul epilogues)
        m = jnp.max(logits.astype(jnp.float32), axis=-1, keepdims=True)
        e = jnp.exp(logits.astype(jnp.float32) - m)
        p = e / e.sum(axis=-1, keepdims=True)
        safe = jnp.where(yc == ignored_index, 0, yc)
        onehot = jax.nn.one_hot(safe, V, dtype=p.dtype)
        live = (yc != ignored_index).astype(p.dtype) * gc.astype(p.dtype)
        dlog_mm = ((p - onehot) * live[:, None]).astype(W.dtype)
        dh_c = jnp.matmul(dlog_mm, W,
                          preferred_element_type=jnp.float32)
        dW = dW + jnp.matmul(dlog_mm.T, hc,
                             preferred_element_type=jnp.float32)
        db = db + dlog_mm.astype(jnp.float32).sum(axis=0)
        return (dW, db), dh_c.astype(h.dtype)

    (dW, db), dhs = jax.lax.scan(
        body, (jnp.zeros((V, H), jnp.float32),
               jnp.zeros((V,), jnp.float32)), (hs, ys, grs))
    dh = dhs.reshape(n_chunks * C, H)[:N]
    return dh, dW.astype(W.dtype), db.astype(b.dtype)


def tied_lm_head_xent_op(h, table, bias, labels, ignored_index=-1,
                         n_chunks=8, ctx=None):
    """Fused LM head + sparse softmax cross-entropy, chunked over rows.

    Equivalent to ``softmaxcrossentropy_sparse_op(linear_op(h, table,
    bias, trans_B=True), labels)`` but the [N, V] logits (and their
    gradient) never hit HBM in full — at BERT scale that tensor chain is
    gigabytes per step, pure memory-bandwidth cost the reference pays
    with a dedicated CUDA kernel pair instead
    (src/ops/SoftmaxCrossEntropySparse.cu).  The three gradient nodes
    share one recompute scan (XLA CSE merges their identical bodies, the
    same mechanism VJPOp relies on — ops_misc.py:92).
    """
    def f(hh, W, b, yy):
        return _chunked_xent_fwd(hh, W, b, yy, ignored_index, n_chunks)

    def grad_rule(n, g):
        hh, W, b, yy = n.inputs

        def mk(idx, name):
            return _simple(
                name,
                lambda gv, hv, Wv, bv, yv:
                _chunked_xent_bwd(gv, hv, Wv, bv, yv,
                                  ignored_index, n_chunks)[idx],
                g, hh, W, b, yy)
        return [mk(0, "TiedXentGradH"), mk(1, "TiedXentGradW"),
                mk(2, "TiedXentGradB"), None]

    return _simple("TiedXentChunked", f, h, table, bias, labels,
                   grad_rule=grad_rule, ctx=ctx)
