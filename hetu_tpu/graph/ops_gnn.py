"""GNN ops: 1.5-D partitioned GCN layer (reference
gpu_ops/DistGCN_15d.py).

The reference computes ``Z = (A @ H) @ W`` over a (size/replication) x
replication process grid: features H row-partitioned across row groups,
staged block broadcasts along columns, cuSPARSE csrmm per block, then an
allreduce over each row group (DistGCN_15d.py:20-72 ``broad_func``).

TPU-native mapping: the staged broadcasts + allreduce collapse into
sharding annotations + one ``psum`` —

    A : (N, N) sharded P(row_axis, col_axis)
    H : (N, F) sharded P(col_axis, None)   (replicated over row_axis)
    partial = A_blk @ H_blk                 (local MXU matmul)
    Z = psum(partial, col_axis)             (N/row, F) sharded P(row_axis)

which is the same 1.5-D communication volume (H replicated over the
short axis, partial sums reduced over the long one) with XLA choosing
the collective implementation.  Inside pjit (no explicit axis env) the
op is the plain dense composition and XLA derives the collectives from
the operand shardings.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .node import Op, TraceContext
from .ops_math import _simple


class DistGCN15dOp(Op):
    """Z = (A @ H) @ W with 1.5-D sharding when mesh axes are present."""

    def __init__(self, a, h, w, row_axis="dp", col_axis="tp", ctx=None):
        super().__init__(a, h, w, name="DistGCN15d", ctx=ctx)
        self.row_axis = row_axis
        self.col_axis = col_axis

    def compute(self, input_vals, tc: TraceContext):
        a, h, w = input_vals
        if tc.has_axis(self.col_axis):
            partial = a @ h
            z = jax.lax.psum(partial, self.col_axis)
            return z @ w
        return (a @ h) @ w

    def gradient(self, output_grad):
        from .node import vjp_gradient
        return vjp_gradient(self, output_grad)


def distgcn_15d_op(node_A, node_B, node_C, node_Count_Self=None,
                   node_Count_All=None, size=None, replication=None,
                   device_id=None, comm=None, comm_groups=None,
                   need_W=True, row_axis="dp", col_axis="tp", ctx=None):
    """Factory matching the reference op name/arg order
    (DistGCN_15d.py:75: node_A=adjacency, node_B=features, node_C=weight).
    The process-grid arguments (size/replication/device_id/comm*) are
    accepted for API parity but subsumed by mesh axis names on TPU."""
    if not need_W:
        return _simple("DistGCN15dNoW", lambda a, h: a @ h, node_A,
                       node_B, ctx=ctx)
    return DistGCN15dOp(node_A, node_B, node_C, row_axis=row_axis,
                        col_axis=col_axis, ctx=ctx)


def gcn_layer_shard_specs(row_axis="dp", col_axis="tp"):
    """The shardings to place on (A, H, W) for the 1.5-D layout."""
    return (P(row_axis, col_axis), P(col_axis, None), P(None, None))
