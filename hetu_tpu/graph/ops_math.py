"""Elementwise math + activation op factories.

Covers the reference's arithmetic/activation op surface
(gpu_ops/__init__.py exports; kernels in src/ops/*.cu): every op is a thin
jnp/lax composition — XLA fuses chains of these into single kernels, which
replaces the reference's per-op CUDA kernel launches.  Gradients come from
the generic VJP fallback unless a rule is attached.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .node import Op, SimpleOp, TraceContext


def _simple(name, fn, *inputs, grad_rule=None, nondiff=False, ctx=None):
    op = SimpleOp(fn, *inputs, name=name, grad_rule=grad_rule, ctx=ctx)
    if nondiff:
        op.gradient = lambda output_grad: [None] * len(op.inputs)
    return op


def _bb(g, x):
    """Reduce a broadcasted adjoint back to x's shape (numpy-style rules)."""
    from .ops_shape import broadcast_reduce_op
    return broadcast_reduce_op(g, x)


# ----------------------------------------------------------------------- #
# binary arithmetic (broadcasting like the reference's elementwise kernels)
# ----------------------------------------------------------------------- #

def add_op(a, b, ctx=None):
    return _simple("Add", lambda x, y: x + y, a, b,
                   grad_rule=lambda n, g: [_bb(g, n.inputs[0]), _bb(g, n.inputs[1])],
                   ctx=ctx)


def minus_op(a, b, ctx=None):
    return _simple("Minus", lambda x, y: x - y, a, b,
                   grad_rule=lambda n, g: [_bb(g, n.inputs[0]),
                                           _bb(opposite_op(g), n.inputs[1])],
                   ctx=ctx)


def mul_op(a, b, ctx=None):
    return _simple("Mul", lambda x, y: x * y, a, b,
                   grad_rule=lambda n, g: [_bb(mul_op(g, n.inputs[1]), n.inputs[0]),
                                           _bb(mul_op(g, n.inputs[0]), n.inputs[1])],
                   ctx=ctx)


def div_op(a, b, ctx=None):
    return _simple("Div", lambda x, y: x / y, a, b, ctx=ctx)


def addbyconst_op(a, c, ctx=None):
    return _simple("AddConst", lambda x: x + c, a,
                   grad_rule=lambda n, g: [g], ctx=ctx)


def minus_byconst_op(c, a, ctx=None):
    """const - node (reference gpu_ops/MinusByConst.py)."""
    return _simple("MinusByConst", lambda x: c - x, a,
                   grad_rule=lambda n, g: [opposite_op(g)], ctx=ctx)


def mul_byconst_op(a, c, ctx=None):
    return _simple("MulConst", lambda x: x * c, a,
                   grad_rule=lambda n, g: [mul_byconst_op(g, c)], ctx=ctx)


def div_const_op(c, a, ctx=None):
    """const / node (reference gpu_ops/Division.py div_const_op)."""
    return _simple("DivConst", lambda x: c / x, a, ctx=ctx)


def opposite_op(a, ctx=None):
    return _simple("Opposite", lambda x: -x, a,
                   grad_rule=lambda n, g: [opposite_op(g)], ctx=ctx)


# ----------------------------------------------------------------------- #
# unary math
# ----------------------------------------------------------------------- #

def abs_op(a, ctx=None):
    return _simple("Abs", jnp.abs, a, ctx=ctx)


def abs_gradient_op(grad, a, ctx=None):
    return _simple("AbsGrad", lambda g, x: g * jnp.sign(x), grad, a, ctx=ctx)


def exp_op(a, ctx=None):
    return _simple("Exp", jnp.exp, a, ctx=ctx)


def log_op(a, eps=0.0, ctx=None):
    return _simple("Log", lambda x: jnp.log(x + eps) if eps else jnp.log(x), a, ctx=ctx)


def log_grad_op(grad, a, ctx=None):
    return _simple("LogGrad", lambda g, x: g / x, grad, a, ctx=ctx)


def pow_op(a, p, ctx=None):
    return _simple("Pow", lambda x: jnp.power(x, p), a, ctx=ctx)


def pow_gradient_op(grad, a, p, ctx=None):
    return _simple("PowGrad", lambda g, x: g * p * jnp.power(x, p - 1), grad, a, ctx=ctx)


def const_pow_op(c, a, ctx=None):
    return _simple("ConstPow", lambda x: jnp.power(c, x), a, ctx=ctx)


def const_pow_gradient_op(grad, a, c, ctx=None):
    import math
    return _simple("ConstPowGrad",
                   lambda g, x: g * jnp.power(c, x) * math.log(c), grad, a, ctx=ctx)


def sqrt_op(a, ctx=None):
    return _simple("Sqrt", jnp.sqrt, a, ctx=ctx)


def rsqrt_op(a, ctx=None):
    return _simple("ReciprocalSqrt", jax.lax.rsqrt, a, ctx=ctx)


def sin_op(a, ctx=None):
    return _simple("Sin", jnp.sin, a, ctx=ctx)


def cos_op(a, ctx=None):
    return _simple("Cos", jnp.cos, a, ctx=ctx)


def floor_op(a, ctx=None):
    return _simple("Floor", jnp.floor, a, nondiff=True, ctx=ctx)


def ceil_op(a, ctx=None):
    return _simple("Ceil", jnp.ceil, a, nondiff=True, ctx=ctx)


def clamp_op(a, mmin=None, mmax=None, ctx=None):
    return _simple("Clamp", lambda x: jnp.clip(x, mmin, mmax), a, ctx=ctx)


def bool_op(a, b, cond=0, ctx=None):
    """Elementwise comparison (reference gpu_ops/Bool.py): cond 0 '=', 1 '<',
    2 '>', 3 '<=', 4 '>='; returns float mask like the reference kernel."""
    fns = {
        0: lambda x, y: (x == y),
        1: lambda x, y: (x < y),
        2: lambda x, y: (x > y),
        3: lambda x, y: (x <= y),
        4: lambda x, y: (x >= y),
    }
    f = fns[cond]
    return _simple("Bool", lambda x, y: f(x, y).astype(jnp.float32), a, b,
                   nondiff=True, ctx=ctx)


def where_op(cond, a, b, ctx=None):
    def _grad(n, g):
        c = n.inputs[0]
        ga = _simple("WhereGradA",
                     lambda gr, cc: jnp.where(cc.astype(bool), gr, 0.0), g, c)
        gb = _simple("WhereGradB",
                     lambda gr, cc: jnp.where(cc.astype(bool), 0.0, gr), g, c)
        return [None, _bb(ga, n.inputs[1]), _bb(gb, n.inputs[2])]

    return _simple("Where", lambda c, x, y: jnp.where(c.astype(bool), x, y),
                   cond, a, b, grad_rule=_grad, ctx=ctx)


def where_const_op(cond, a, const_attr, ctx=None):
    return _simple("WhereConst",
                   lambda c, x: jnp.where(c.astype(bool), x, const_attr),
                   cond, a, ctx=ctx)


def masked_fill_op(a, mask, val=0.0, ctx=None):
    """Reference gpu_ops/MaskedFill.py: fill where mask is set."""
    return _simple("MaskedFill",
                   lambda x, m: jnp.where(m.astype(bool), jnp.asarray(val, x.dtype), x),
                   a, mask, ctx=ctx)


def sign_op(a, ctx=None):
    return _simple("Sign", jnp.sign, a, nondiff=True, ctx=ctx)


def max_op(a, b, ctx=None):
    return _simple("Max", jnp.maximum, a, b, ctx=ctx)


def min_op(a, b, ctx=None):
    return _simple("Min", jnp.minimum, a, b, ctx=ctx)


# ----------------------------------------------------------------------- #
# activations (reference: src/ops/Relu.cu, Gelu.cu, ... via gpu_ops/*)
# ----------------------------------------------------------------------- #

def relu_op(a, ctx=None):
    return _simple("Relu", jax.nn.relu, a,
                   grad_rule=lambda n, g: [relu_gradient_op(n.inputs[0], g)],
                   ctx=ctx)


def relu_gradient_op(a, grad, ctx=None):
    return _simple("ReluGrad", lambda x, g: g * (x > 0).astype(g.dtype),
                   a, grad, ctx=ctx)


def leaky_relu_op(a, alpha=0.01, ctx=None):
    return _simple("LeakyRelu", lambda x: jax.nn.leaky_relu(x, alpha), a, ctx=ctx)


def leaky_relu_gradient_op(a, grad, alpha=0.01, ctx=None):
    return _simple("LeakyReluGrad",
                   lambda x, g: g * jnp.where(x > 0, 1.0, alpha), a, grad, ctx=ctx)


def gelu_op(a, ctx=None):
    # tanh approximation, matching the reference kernel (src/ops/Gelu.cu)
    return _simple("Gelu", lambda x: jax.nn.gelu(x, approximate=True), a, ctx=ctx)


def gelu_gradient_op(a, grad, ctx=None):
    def f(x, g):
        _, vjp = jax.vjp(lambda y: jax.nn.gelu(y, approximate=True), x)
        return vjp(g)[0]
    return _simple("GeluGrad", f, a, grad, ctx=ctx)


def sigmoid_op(a, ctx=None):
    return _simple("Sigmoid", jax.nn.sigmoid, a, ctx=ctx)


def tanh_op(a, ctx=None):
    return _simple("Tanh", jnp.tanh, a, ctx=ctx)


def tanh_gradient_op(forward, grad, ctx=None):
    """grad wrt input given the forward *output* (reference TanhGrad kernel)."""
    return _simple("TanhGrad", lambda y, g: g * (1.0 - y * y), forward, grad, ctx=ctx)


def softmax_func(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def softmax_op(a, ctx=None):
    return _simple("Softmax", lambda x: jax.nn.softmax(x, axis=-1), a, ctx=ctx)


def softmax_gradient_op(forward, grad, ctx=None):
    def f(y, g):
        return y * (g - jnp.sum(g * y, axis=-1, keepdims=True))
    return _simple("SoftmaxGrad", f, forward, grad, ctx=ctx)


def log_softmax_op(a, ctx=None):
    return _simple("LogSoftmax", lambda x: jax.nn.log_softmax(x, axis=-1), a, ctx=ctx)
