"""Shape / indexing / reduction op factories.

Reference counterparts: gpu_ops/{Reshape,Transpose,Broadcast,BroadcastShape,
ReduceSum,ReduceMean,Slice,SliceAssign,Split,Concat,Concatenate,Pad,Gather,
Scatter,Roll,Repeat,Interpolate,OneHot,Argmax,Argsort,TopK*,CumSum,Norm,
Tile,...}.py — each here is a jnp composition; XLA handles layout.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .node import Op, SimpleOp
from .ops_math import _simple


# ----------------------------------------------------------------------- #
# broadcast / reduce
# ----------------------------------------------------------------------- #

class BroadcastReduceOp(Op):
    """Sum a (possibly broadcasted) adjoint back down to the shape of a
    target node — used by binary-op gradients for numpy broadcasting.
    Shape resolution happens at trace time from the concrete values."""

    def __init__(self, grad, target, ctx=None):
        super().__init__(grad, target, name="BroadcastReduce", ctx=ctx)

    def jax_fn(self, g, x):
        if g.shape == x.shape:
            return g
        # sum leading extra dims, then keepdims-sum broadcasted dims
        extra = g.ndim - x.ndim
        if extra > 0:
            g = jnp.sum(g, axis=tuple(range(extra)))
        axes = tuple(i for i, (gs, xs) in enumerate(zip(g.shape, x.shape))
                     if gs != xs)
        if axes:
            g = jnp.sum(g, axis=axes, keepdims=True)
        return g.astype(x.dtype)

    def gradient(self, output_grad):
        raise NotImplementedError


def broadcast_reduce_op(grad, target, ctx=None):
    return BroadcastReduceOp(grad, target, ctx=ctx)


def broadcastto_op(a, target, ctx=None):
    """Broadcast a to target's shape (reference gpu_ops/Broadcast.py;
    adds trailing-dim alignment like the kernel: bias (C,) -> (N,C))."""
    def f(x, t):
        return jnp.broadcast_to(x, t.shape).astype(x.dtype)
    return _simple("BroadcastTo", f, a, target,
                   grad_rule=lambda n, g: [broadcast_reduce_op(g, n.inputs[0]), None],
                   ctx=ctx)


def broadcast_shape_op(a, shape, add_axes=None, ctx=None):
    """Broadcast to an explicit shape (reference gpu_ops/BroadcastShape.py).
    ``add_axes`` lists axes of the *output* that are new (reference semantics:
    input dims map to the non-added axes in order)."""
    shape = tuple(shape)
    if add_axes:
        add_axes = tuple(sorted(add_axes))

        def f(x):
            for ax in add_axes:
                x = jnp.expand_dims(x, ax)
            return jnp.broadcast_to(x, shape)
    else:
        def f(x):
            return jnp.broadcast_to(x, shape)
    return _simple("BroadcastShape", f, a, ctx=ctx)


def reduce_sum_op(a, axes=None, keepdims=False, ctx=None):
    if axes is not None and not isinstance(axes, (list, tuple)):
        axes = [axes]
    axes = tuple(axes) if axes is not None else None
    return _simple("ReduceSum",
                   lambda x: jnp.sum(x, axis=axes, keepdims=bool(keepdims)), a,
                   ctx=ctx)


def reduce_mean_op(a, axes=None, keepdims=False, ctx=None):
    if axes is not None and not isinstance(axes, (list, tuple)):
        axes = [axes]
    axes = tuple(axes) if axes is not None else None
    return _simple("ReduceMean",
                   lambda x: jnp.mean(x, axis=axes, keepdims=bool(keepdims)), a,
                   ctx=ctx)


def reducesumaxiszero_op(a, ctx=None):
    return _simple("ReduceSumAxisZero", lambda x: jnp.sum(x, axis=0), a, ctx=ctx)


def reduce_min_op(a, axes=None, keepdims=False, ctx=None):
    if axes is not None and not isinstance(axes, (list, tuple)):
        axes = [axes]
    axes = tuple(axes) if axes is not None else None
    return _simple("ReduceMin",
                   lambda x: jnp.min(x, axis=axes, keepdims=bool(keepdims)), a,
                   ctx=ctx)


def reduce_norm1_op(a, axes=None, keepdims=False, ctx=None):
    if axes is not None and not isinstance(axes, (list, tuple)):
        axes = [axes]
    axes = tuple(axes) if axes is not None else None
    return _simple("ReduceNorm1",
                   lambda x: jnp.sum(jnp.abs(x), axis=axes, keepdims=bool(keepdims)),
                   a, ctx=ctx)


def reduce_norm2_op(a, axes=None, keepdims=False, ctx=None):
    if axes is not None and not isinstance(axes, (list, tuple)):
        axes = [axes]
    axes = tuple(axes) if axes is not None else None
    return _simple("ReduceNorm2",
                   lambda x: jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=bool(keepdims))),
                   a, ctx=ctx)


def norm_op(a, axis=None, p=2, ctx=None):
    return _simple("Norm",
                   lambda x: jnp.linalg.norm(x.reshape(-1) if axis is None else x,
                                             ord=p, axis=axis),
                   a, ctx=ctx)


# ----------------------------------------------------------------------- #
# reshape / transpose / slice / concat / split / pad
# ----------------------------------------------------------------------- #

def array_reshape_op(a, shape, ctx=None):
    shape = tuple(int(s) for s in shape)
    return _simple("Reshape", lambda x: jnp.reshape(x, shape), a, ctx=ctx)


def transpose_op(a, perm=None, ctx=None):
    perm = tuple(perm) if perm is not None else None
    return _simple("Transpose", lambda x: jnp.transpose(x, perm), a, ctx=ctx)


def squeeze_op(a, axis, ctx=None):
    """Drop a size-1 axis without needing the other dims statically
    (array_reshape_op would; the QA span head squeezes [N,S,1]->[N,S])."""
    axis = int(axis)
    return _simple("Squeeze", lambda x: jnp.squeeze(x, axis=axis), a,
                   ctx=ctx)


def slice_op(a, begin, size, ctx=None):
    """size entries of -1 mean "to the end" (reference gpu_ops/Slice.py)."""
    begin = tuple(int(b) for b in begin)
    size = tuple(int(s) for s in size)

    def f(x):
        idx = tuple(slice(b, None if s == -1 else b + s)
                    for b, s in zip(begin, size))
        return x[idx]
    return _simple("Slice", f, a, ctx=ctx)


def slice_assign_op(a, val_const, begin, size, ctx=None):
    begin = tuple(int(b) for b in begin)
    size = tuple(int(s) for s in size)
    idx = tuple(slice(b, b + s) for b, s in zip(begin, size))
    return _simple("SliceAssign", lambda x: x.at[idx].set(val_const), a, ctx=ctx)


def slice_assign_matrix_op(a, b, begin_a, size, begin_b, ctx=None):
    idx_a = tuple(slice(s, s + z) for s, z in zip(begin_a, size))
    idx_b = tuple(slice(s, s + z) for s, z in zip(begin_b, size))
    return _simple("SliceAssignMatrix",
                   lambda x, y: x.at[idx_a].set(y[idx_b]), a, b, ctx=ctx)


def slice_by_matrix_op(a, idx0, idx1, ctx=None):
    """a[idx0, idx1] advanced indexing (reference gpu_ops/SliceByMatrix.py)."""
    return _simple("SliceByMatrix",
                   lambda x, i, j: x[i.astype(jnp.int32), j.astype(jnp.int32)],
                   a, idx0, idx1, ctx=ctx)


def split_op(a, axes, indices, splits, ctx=None):
    """Take one piece of an even split (reference gpu_ops/Split.py:
    per-axis number of splits and which index to keep)."""
    if not isinstance(axes, (list, tuple)):
        axes, indices, splits = [axes], [indices], [splits]

    def f(x):
        for ax, ind, spl in zip(axes, indices, splits):
            part = x.shape[ax] // spl
            x = jax.lax.slice_in_dim(x, ind * part, (ind + 1) * part, axis=ax)
        return x
    return _simple("Split", f, a, ctx=ctx)


def concat_op(a, b, axis=0, ctx=None):
    return _simple("Concat", lambda x, y: jnp.concatenate([x, y], axis=axis),
                   a, b, ctx=ctx)


def concatenate_op(nodes, axis=0, ctx=None):
    return _simple("Concatenate",
                   lambda *xs: jnp.concatenate(list(xs), axis=axis), *nodes,
                   ctx=ctx)


def pad_op(a, paddings, mode="CONSTANT", constant_values=0.0, ctx=None):
    pads = tuple((int(p[0]), int(p[1])) for p in paddings)
    jmode = {"CONSTANT": "constant", "REFLECT": "reflect", "SYMMETRIC": "symmetric"}[mode.upper()]

    def f(x):
        if jmode == "constant":
            return jnp.pad(x, pads, mode=jmode, constant_values=constant_values)
        return jnp.pad(x, pads, mode=jmode)
    return _simple("Pad", f, a, ctx=ctx)


def flatten_op(a, ctx=None):
    return _simple("Flatten", lambda x: x.reshape(x.shape[0], -1), a, ctx=ctx)


def tile_op(a, reps, ctx=None):
    return _simple("Tile", lambda x: jnp.tile(x, reps), a, ctx=ctx)


def repeat_op(a, repeats, axis=None, ctx=None):
    return _simple("Repeat", lambda x: jnp.repeat(x, repeats, axis=axis), a, ctx=ctx)


def roll_op(a, shift, axis=None, ctx=None):
    return _simple("Roll", lambda x: jnp.roll(x, shift, axis=axis), a, ctx=ctx)


def interpolate_op(a, scale_factor=None, size=None, mode="bilinear",
                   align_corners=False, ctx=None):
    """NCHW spatial resize (reference gpu_ops/Interpolate.py)."""
    def f(x):
        n, c, h, w = x.shape
        if size is not None:
            oh, ow = size
        else:
            oh, ow = int(h * scale_factor), int(w * scale_factor)
        method = {"bilinear": "bilinear", "nearest": "nearest"}[mode]
        return jax.image.resize(x, (n, c, oh, ow), method=method)
    return _simple("Interpolate", f, a, ctx=ctx)


# ----------------------------------------------------------------------- #
# gather / scatter / indexing
# ----------------------------------------------------------------------- #

def gather_op(a, axis, index, ctx=None):
    """torch.gather semantics (reference gpu_ops/Gather.py)."""
    return _simple("Gather",
                   lambda x, i: jnp.take_along_axis(x, i.astype(jnp.int32), axis=axis),
                   a, index,
                   grad_rule=lambda n, g: _gather_grad(n, g, axis),
                   ctx=ctx)


def _gather_grad(node, g, axis):
    x, index = node.inputs

    def f(gr, xx, ii):
        z = jnp.zeros_like(xx)
        ii = ii.astype(jnp.int32)
        return _scatter_add_along_axis(z, ii, gr, axis)
    return [_simple("GatherGrad", f, g, x, index), None]


def _scatter_add_along_axis(z, idx, src, axis):
    # build open mesh of indices, replace `axis` with idx
    ind = list(jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij"))
    ind[axis] = idx
    return z.at[tuple(ind)].add(src)


def scatter_op(a, axis, index, src, ctx=None):
    """torch.scatter: write src rows into a at index along axis."""
    def f(x, i, s):
        i = i.astype(jnp.int32)
        ind = list(jnp.meshgrid(*[jnp.arange(d) for d in i.shape], indexing="ij"))
        ind[axis] = i
        return x.at[tuple(ind)].set(s)
    return _simple("Scatter", f, a, index, src, ctx=ctx)


def scatter1d_op(a, index, src, ctx=None):
    return _simple("Scatter1D",
                   lambda x, i, s: x.at[i.astype(jnp.int32)].set(s),
                   a, index, src, ctx=ctx)


def indexing_op(a, index, ctx=None):
    return _simple("Indexing",
                   lambda x, i: x[i.astype(jnp.int32)], a, index, ctx=ctx)


def one_hot_op(indices, num_classes, ctx=None):
    return _simple("OneHot",
                   lambda i: jax.nn.one_hot(i.astype(jnp.int32), num_classes),
                   indices, nondiff=True, ctx=ctx)


def argmax_op(a, dim=-1, ctx=None):
    return _simple("Argmax",
                   lambda x: jnp.argmax(x, axis=dim).astype(jnp.float32), a,
                   nondiff=True, ctx=ctx)


def argsort_op(a, dim=-1, descending=False, ctx=None):
    def f(x):
        s = jnp.argsort(-x if descending else x, axis=dim)
        return s.astype(jnp.float32)
    return _simple("Argsort", f, a, nondiff=True, ctx=ctx)


def argmax_partial_op(a, mask, dim=-1, ctx=None):
    def f(x, m):
        neg = jnp.finfo(x.dtype).min
        return jnp.argmax(jnp.where(m.astype(bool), x, neg), axis=dim).astype(jnp.float32)
    return _simple("ArgmaxPartial", f, a, mask, nondiff=True, ctx=ctx)


def cumsum_with_bias_op(a, bias=0.0, dim=0, ctx=None):
    """cumsum(x) + bias along dim (reference gpu_ops/CumSum.py; used by MoE
    position computation, TopGate.py).  The bias is added ONCE per element
    after the inclusive cumsum — with bias=-1 over a one-hot routing mask
    this yields each token's 0-based arrival position at its expert, which
    LayoutTransformOp scatters as ``expert * capacity + location``.
    (cumsum(x + bias) would accumulate the bias t+1 times and send almost
    every location negative, silently dropping the token at dispatch.)"""
    return _simple("CumsumWithBias",
                   lambda x: jnp.cumsum(x, axis=dim) + bias, a, ctx=ctx)


def cumsum_op(a, dim=0, ctx=None):
    return _simple("Cumsum", lambda x: jnp.cumsum(x, axis=dim), a, ctx=ctx)


def topk_idx_op(a, topk=None, dim=-1, ctx=None, k=None):
    """Indices of top-k along last dim, as float (reference
    gpu_ops/TopKIdx.py; keyword is ``topk`` there, ``k`` also accepted)."""
    k = topk if topk is not None else k
    assert k is not None, "topk_idx_op needs topk="
    assert dim in (-1, None), "top-k over non-last dims: transpose first"

    def f(x):
        _, idx = jax.lax.top_k(x, k)
        return idx.astype(jnp.float32)
    return _simple("TopKIdx", f, a, nondiff=True, ctx=ctx)


def topk_val_op(a, topk=None, dim=-1, ctx=None, k=None):
    k = topk if topk is not None else k
    assert k is not None, "topk_val_op needs topk="

    def f(x):
        val, _ = jax.lax.top_k(x, k)
        return val
    return _simple("TopKVal", f, a, ctx=ctx)


def min_dist_op(lookup, key, indices, ctx=None):
    """Nearest-codebook-entry lookup used by quantized embeddings."""
    def f(table, q, idx):
        d = jnp.abs(table[None, :] - q[:, None])
        return jnp.argmin(d, axis=-1).astype(jnp.float32)
    return _simple("MinDist", f, lookup, key, indices, nondiff=True, ctx=ctx)
