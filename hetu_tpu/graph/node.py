"""Graph IR core: the Op node base class and trace machinery.

TPU-native counterpart of the reference's ``python/hetu/gpu_ops/Node.py``
(Op base at Node.py:18).  The reference executes each node eagerly by
launching a CUDA kernel per op per step; here every node instead carries a
pure ``jax_fn`` and the executor *traces* a whole named subgraph once into a
single jitted XLA program (SURVEY.md §1 "Key structural facts").  Placement
hooks (forward_hook's H2D/D2H insertion, Node.py:192-213) are unnecessary:
XLA owns transfers; ``raw_ctx`` survives as a sharding/stage hint.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..context import get_current_context

# rng stream index space: [0, n_topo) for topo-tracked nodes; untracked
# nodes are shifted far above any realistic topo position (see rng_for)
_UNTRACKED_RNG_OFFSET = 1 << 24


class ShapeInferenceError(ValueError):
    """``Op.infer_shape`` failed: the message names the node, its op
    type, and its input shapes/dtypes (the graph-wide verifier in
    ``hetu_tpu.analysis.verify`` wraps whole-graph walks the same way —
    this covers standalone per-node use)."""


class TraceContext:
    """Per-trace state threaded through ``Op.compute`` calls.

    Replaces the reference's per-op stream/event plumbing
    (executor.py:1039-1058): under jit there are no streams to order, but
    ops still need RNG keys, the training/inference flag, mesh info, and
    access to variable values.
    """

    def __init__(self, params=None, rng=None, training=True, mesh=None,
                 axis_env=(), config=None, step=None):
        self.params = params or {}
        self._rng = rng
        self.training = training
        self.mesh = mesh
        # tuple of mesh axis names currently visible as collective axes
        # (non-empty only inside shard_map traces)
        self.axis_env = tuple(axis_env)
        self.config = config
        self.step = step
        self.extra_outputs = {}
        # node.id -> stable stream index (topo position).  The raw global
        # id counter differs between two builds of the same graph (e.g.
        # checkpoint resume in a process that built a graph before), so
        # executors install topo positions here to keep dropout/rand
        # streams — and therefore resumed trajectories — build-invariant.
        self.rng_ids = {}

    def rng_for(self, node) -> jax.Array:
        assert self._rng is not None, (
            "op %s needs an RNG key but the trace has none" % node)
        stream = self.rng_ids.get(node.id)
        if stream is None:
            # Untracked node: raw global ids share the small-int range with
            # topo positions, so fold in a disjoint offset — otherwise an
            # untracked rng consumer could silently share a dropout stream
            # with a topo-indexed one.
            stream = node.id + _UNTRACKED_RNG_OFFSET
        return jax.random.fold_in(self._rng, stream)

    def has_axis(self, name) -> bool:
        return name in self.axis_env


class Op:
    """A node in the dataflow graph.

    Mirrors the reference Op (gpu_ops/Node.py:18-76): ``inputs``,
    ``raw_ctx`` placement hint, operator overloading; but ``compute`` is a
    pure function over jax values evaluated at trace time instead of a CUDA
    kernel launch.
    """

    _next_id = 0

    def __init__(self, *inputs, name=None, ctx=None, dtype=None):
        for i, x in enumerate(inputs):
            assert isinstance(x, Op), (
                f"input {i} of {type(self).__name__} is {type(x)}; "
                "wrap constants with ht.Variable or *_byconst ops")
        self.inputs = list(inputs)
        self.id = Op._next_id
        Op._next_id += 1
        base = name if name is not None else type(self).__name__.replace("Op", "")
        self.name = f"{base}_{self.id}"
        self.raw_ctx = ctx if ctx is not None else get_current_context()
        self.dtype = dtype

    # ------------------------------------------------------------------ #
    # evaluation
    # ------------------------------------------------------------------ #

    def jax_fn(self, *input_vals):
        raise NotImplementedError(f"{type(self).__name__} has no jax_fn")

    def compute(self, input_vals, tc: TraceContext):
        """Evaluate this node given already-evaluated input values.

        Default delegates to the stateless ``jax_fn``; ops that need RNG,
        the training flag, collective axes, or variable state override this.
        """
        return self.jax_fn(*input_vals)

    def gradient(self, output_grad):
        """Build backward-graph nodes for each input (reference: each op
        file's ``gradient``).  Return a list aligned with ``self.inputs``;
        ``None`` entries mean no gradient flows to that input."""
        raise NotImplementedError(
            f"{type(self).__name__} has no gradient rule")

    # ------------------------------------------------------------------ #
    # shape/dtype inference — free via jax.eval_shape (the reference hand
    # writes infer_shape per op, e.g. Node.py + every gpu_ops file)
    # ------------------------------------------------------------------ #

    def infer_shape(self, input_shapes, input_dtypes=None):
        if input_dtypes is None:
            input_dtypes = [jnp.float32] * len(input_shapes)
        args = [
            jax.ShapeDtypeStruct(tuple(s), d)
            for s, d in zip(input_shapes, input_dtypes)
        ]
        tc = TraceContext(rng=None, training=False)
        try:
            out = jax.eval_shape(lambda *a: self.compute(list(a), tc),
                                 *args)
        except Exception as e:
            ins = ", ".join(
                f"{jnp.dtype(d).name}{tuple(s)}"
                for s, d in zip(input_shapes, input_dtypes))
            raise ShapeInferenceError(
                f"shape inference failed at node {self.name!r} (op "
                f"{type(self).__name__}) with inputs [{ins}]"
                + (f" produced by {[i.name for i in self.inputs]}"
                   if self.inputs else "")
                + f": {type(e).__name__}: {e}") from e
        return out.shape

    # ------------------------------------------------------------------ #
    # sugar — reference Node.py:48-76
    # ------------------------------------------------------------------ #

    def __add__(self, other):
        from . import ops_math as m
        if isinstance(other, Op):
            return m.add_op(self, other)
        return m.addbyconst_op(self, other)

    __radd__ = __add__

    def __sub__(self, other):
        from . import ops_math as m
        if isinstance(other, Op):
            return m.minus_op(self, other)
        return m.addbyconst_op(self, -other)

    def __rsub__(self, other):
        from . import ops_math as m
        return m.addbyconst_op(m.opposite_op(self), other)

    def __neg__(self):
        from . import ops_math as m
        return m.opposite_op(self)

    def __mul__(self, other):
        from . import ops_math as m
        if isinstance(other, Op):
            return m.mul_op(self, other)
        return m.mul_byconst_op(self, other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        from . import ops_math as m
        if isinstance(other, Op):
            return m.div_op(self, other)
        return m.mul_byconst_op(self, 1.0 / other)

    def __rtruediv__(self, other):
        from . import ops_math as m
        return m.div_const_op(self, other)

    def __repr__(self):
        return self.name

    __str__ = __repr__


class SimpleOp(Op):
    """An Op wrapping a closed-over pure function — the workhorse for the
    ~100-op factory surface (reference gpu_ops/__init__.py exports)."""

    def __init__(self, fn, *inputs, name=None, grad_rule=None, ctx=None):
        super().__init__(*inputs, name=name, ctx=ctx)
        self._fn = fn
        self._grad_rule = grad_rule

    def jax_fn(self, *input_vals):
        return self._fn(*input_vals)

    def gradient(self, output_grad):
        if self._grad_rule is None:
            return vjp_gradient(self, output_grad)
        return self._grad_rule(self, output_grad)


def vjp_gradient(node: Op, output_grad: Op):
    """Fallback gradient: one VJPOp per differentiable input, each computing
    the cotangent via ``jax.vjp`` of the node's own compute at trace time.
    XLA CSE merges the duplicated forward computations, so this costs
    nothing extra in the compiled program — this replaces dozens of
    hand-written backward kernels in the reference (src/ops/*.cu)."""
    from .ops_misc import VJPOp
    return [VJPOp(node, output_grad, i) for i in range(len(node.inputs))]
