"""Embedding lookup with IndexedSlices-style sparse gradients.

Reference: gpu_ops/EmbeddingLookUp.py + src/ops/EmbeddingLookup.cu;
IndexedSlices dedup/to-dense in python/hetu/ndarray.py:507-606 and
src/ops/IndexedSlices.cu.  Here the sparse adjoint is a graph-level
``IndexedSlicesOp`` carrying (ids, rows); the optimizer consumes it with a
row-wise scatter update (XLA scatter-add), never materializing the dense
vocab-sized gradient.  ``to_dense`` exists for the generic path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .node import Op, TraceContext
from .ops_math import _simple


class EmbeddingLookupOp(Op):
    def __init__(self, table, ids, ctx=None):
        super().__init__(table, ids, name="EmbeddingLookup", ctx=ctx)
        table.is_embed = True

    def jax_fn(self, table, ids):
        return jnp.take(table, ids.astype(jnp.int32), axis=0)

    def gradient(self, output_grad):
        return [IndexedSlicesOp(self.inputs[0], self.inputs[1], output_grad),
                None]


def embedding_lookup_op(table, ids, ctx=None):
    return EmbeddingLookupOp(table, ids, ctx=ctx)


class IndexedSlicesOp(Op):
    """Sparse adjoint of an embedding table: rows ``values`` at ``ids``.

    When *evaluated* it densifies (scatter-add into a zero table) — but the
    optimizer recognizes the node type and instead applies a row-sparse
    update, mirroring the reference's IndexedSlices path
    (optimizer.py sparse updates + src/ops/OptimizersSparse.cu).
    """

    sparse = True

    def __init__(self, table, ids, values, ctx=None):
        super().__init__(table, ids, values, name="IndexedSlices", ctx=ctx)

    @property
    def ids_node(self):
        return self.inputs[1]

    @property
    def values_node(self):
        return self.inputs[2]

    def jax_fn(self, table, ids, values):
        ids = ids.astype(jnp.int32).reshape(-1)
        vals = values.reshape(-1, values.shape[-1])
        return jnp.zeros_like(table).at[ids].add(vals)

    def gradient(self, output_grad):
        raise NotImplementedError


def merge_indexed_slices(slices, ctx=None):
    """Sparse SUM of several IndexedSlices adjoints on the SAME table:
    concatenate (ids, rows) — scatter-add is order-free, and every
    consumer (optimizer sparse update, PS side-output, densify) already
    merges duplicate ids.  This keeps multi-lookup embedding tables
    sparse end-to-end (reference densifies via executor.py:1119-1127
    SumOp; its IndexedSlices dedup kernel then re-sparsifies)."""
    table = slices[0].inputs[0]
    assert all(s.inputs[0] is table for s in slices)

    def cat_ids(*xs):
        return jnp.concatenate(
            [x.astype(jnp.int32).reshape(-1) for x in xs])

    def cat_rows(*xs):
        return jnp.concatenate(
            [x.reshape(-1, x.shape[-1]) for x in xs])

    ids = _simple("ConcatIds", cat_ids, *[s.ids_node for s in slices],
                  nondiff=True, ctx=ctx)
    vals = _simple("ConcatRows", cat_rows,
                   *[s.values_node for s in slices], ctx=ctx)
    return IndexedSlicesOp(table, ids, vals, ctx=ctx)


def unique_indices_op(ids, ctx=None):
    """Deduplicated indices padded with -1 (reference ndarray.py deduplicate).
    Static output shape = input shape (worst case all-unique)."""
    def f(i):
        flat = i.astype(jnp.int32).reshape(-1)
        uniq, _ = jnp.unique(flat, size=flat.shape[0], fill_value=-1,
                             return_index=True)
        return uniq.astype(jnp.float32)
    return _simple("UniqueIndices", f, ids, nondiff=True, ctx=ctx)
