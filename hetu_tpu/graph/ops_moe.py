"""MoE ops: capacity dispatch/combine, all-to-all, gating helpers.

Reference: gpu_ops/LayoutTransform.py (Tutel-style fast dispatch; kernels
src/ops/LayoutTransform.cu), ReverseLayoutTransform.py, AllToAll.py,
HAllToAll.py (hierarchical A2A via node-leader staging,
src/communication/mpi_nccl_communication.cu:152-243), BalanceAssignment.py
(auction assignment), SamGroupSum.cu / SamMax.cu / GroupTopKIdx.cu (SAM
gate), Dispatch.py (model-parallel annotation).

TPU-native: dispatch/combine default to the GShard-style one-hot-matmul
formulation (_scatter_rows) — MXU work with no data-dependent writes —
with the row-scatter form behind HETU_MOE_SCATTER_DISPATCH=1; the MoE
bench A/Bs both on-chip (an earlier round measured scatter dispatch at
3.5 ms of a 67 ms step on the v5e; a fused Pallas dispatch kernel
remains not worth it either way).  Combine stays a gather (fast on
TPU).  All-to-all is ``jax.lax.all_to_all`` over the 'ep' mesh axis
inside shard_map; hierarchical A2A decomposes over ('dcn', 'ici') axes —
the natural mapping of the reference's gather→exchange→scatter staging.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .node import Op, TraceContext
from .ops_math import _simple


def _flat_int(x):
    return x.reshape(-1).astype(jnp.int32)


def _slot_weights(pos_valid_weight, n_slots, dtype):
    """[N, n_slots] slot-assignment weight matrix from (pos, valid,
    weight) triples — the GShard-style dense dispatch mask (the dispatch
    einsum of GShard, arXiv:2006.16668, and Tutel).  Invalid
    (capacity-dropped) rows map to class -1 == an all-zero one-hot
    row."""
    W = None
    for pos, valid, w in pos_valid_weight:
        safe = jnp.where(valid, pos, -1)
        oh = jax.nn.one_hot(safe, n_slots, dtype=dtype)
        if w is not None:
            oh = oh * w.reshape(-1, 1).astype(dtype)
        W = oh if W is None else W + oh
    return W


# above this many mask elements (N * E * cap) the one-hot formulation's
# [N, n_slots] operand becomes the dominant memory/FLOP cost and the
# scatter form wins regardless of its lowering: 2^27 elems = 256 MB bf16
_ONEHOT_DISPATCH_MAX_ELEMS = 1 << 27


def _force_scatter_dispatch():
    from ..envvars import get_bool
    return get_bool("HETU_MOE_SCATTER_DISPATCH")


def _scatter_rows(terms, n_slots, src, dtype, force_scatter=False):
    """Rows of ``src`` summed into ``n_slots`` buckets.

    Default: one-hot MXU matmul (sum_i onehot(pos_i, weighted)^T @ src)
    — row scatter-adds can lower to a serialized scatter on TPU, while
    this formulation is pure matmul work.  The .at[].add scatter form is
    used instead when (a) the caller forces it (the op reads
    ``HETU_MOE_SCATTER_DISPATCH=1`` ONCE at construction — the MoE bench
    A/Bs both on-chip), or (b) the [N, n_slots] mask would exceed
    _ONEHOT_DISPATCH_MAX_ELEMS, past which the mask's memory/FLOPs
    dominate the experts themselves (at top-k capacity, mask elements
    grow as k*N^2)."""
    N = src.shape[0]
    if force_scatter or N * n_slots > _ONEHOT_DISPATCH_MAX_ELEMS:
        out = jnp.zeros((n_slots, src.shape[-1]), dtype)
        for pos, valid, w in terms:
            rows = src if w is None else w.reshape(-1, 1).astype(dtype) * src
            safe = jnp.where(valid, pos, n_slots)
            out = out.at[safe].add(rows, mode="drop")
        return out
    W = _slot_weights(terms, n_slots, dtype)
    return jnp.matmul(W.T, src,
                      preferred_element_type=jnp.float32).astype(dtype)


class LayoutTransformOp(Op):
    """Capacity dispatch: tokens (N,D) -> expert buffers (E*capacity, D).

    Signature parity: layout_transform_op(input, indices_s, location_s,
    capacity, total_experts) (LayoutTransform.py:13-24); top-1 and top-2.
    Tokens whose location >= capacity are dropped (scatter mode='drop').
    """

    def __init__(self, inp, indices_s, location_s, capacity, total_experts,
                 ctx=None):
        super().__init__(inp, *indices_s, *location_s, name="LayoutTransform",
                         ctx=ctx)
        self.capacity = int(capacity)
        self.topK = len(indices_s)
        self.total_experts = int(total_experts)
        self.force_scatter = _force_scatter_dispatch()

    def jax_fn(self, x, *idx_loc):
        k, cap = self.topK, self.capacity
        terms = []
        for i in range(k):
            idx = _flat_int(idx_loc[i])
            loc = _flat_int(idx_loc[k + i])
            terms.append((idx * cap + loc, loc < cap, None))
        return _scatter_rows(terms, self.total_experts * cap, x, x.dtype,
                             force_scatter=self.force_scatter)

    def gradient(self, output_grad):
        k = self.topK
        grads = [
            layout_transform_gradient_op(
                output_grad, self.inputs[1 + i], self.inputs[1 + k + i],
                self.capacity, ctx=self.raw_ctx)
            for i in range(k)
        ]
        total = grads[0]
        for g in grads[1:]:
            total = total + g
        return [total] + [None] * (2 * k)


class LayoutTransformGradientOp(Op):
    """grad_in[token] = grad_out[idx*cap + loc] (0 when dropped)."""

    def __init__(self, grad, indice, location, capacity, ctx=None):
        super().__init__(grad, indice, location,
                         name="LayoutTransformGrad", ctx=ctx)
        self.capacity = int(capacity)

    def jax_fn(self, g, indice, location):
        idx = _flat_int(indice)
        loc = _flat_int(location)
        pos = idx * self.capacity + loc
        rows = jnp.take(g, jnp.clip(pos, 0, g.shape[0] - 1), axis=0)
        return jnp.where((loc < self.capacity)[:, None], rows, 0.0)

    def gradient(self, output_grad):
        raise NotImplementedError


def layout_transform_op(inp, indices_s, location_s, capacity, total_experts,
                        ctx=None):
    return LayoutTransformOp(inp, indices_s, location_s, capacity,
                             total_experts, ctx=ctx)


def layout_transform_gradient_op(grad, indice, location, capacity, ctx=None):
    return LayoutTransformGradientOp(grad, indice, location, capacity, ctx=ctx)


class ReverseLayoutTransformOp(Op):
    """Weighted combine: expert buffers (E*cap, D) -> tokens (N, D).

    out[t] = sum_k gate_k[t] * data[idx_k[t]*cap + loc_k[t]]
    (ReverseLayoutTransform.py:12-40).
    """

    def __init__(self, inp, indices_s, location_s, gates, capacity,
                 num_experts, ctx=None):
        super().__init__(inp, *indices_s, *location_s, *gates,
                         name="ReverseLayoutTransform", ctx=ctx)
        self.capacity = int(capacity)
        self.topK = len(indices_s)
        self.num_experts = int(num_experts)

    def jax_fn(self, data, *rest):
        k, cap = self.topK, self.capacity
        indices = rest[:k]
        locations = rest[k:2 * k]
        gates = rest[2 * k:]
        out = None
        for i in range(k):
            idx = _flat_int(indices[i])
            loc = _flat_int(locations[i])
            pos = idx * cap + loc
            rows = jnp.take(data, jnp.clip(pos, 0, data.shape[0] - 1), axis=0)
            rows = jnp.where((loc < cap)[:, None], rows, 0.0)
            term = gates[i].reshape(-1, 1) * rows
            out = term if out is None else out + term
        return out

    def gradient(self, output_grad):
        k = self.topK
        grad_data = reverse_layout_transform_gradient_data_op(
            output_grad, list(self.inputs[1:1 + k]),
            list(self.inputs[1 + k:1 + 2 * k]),
            list(self.inputs[1 + 2 * k:]), self.capacity, self.num_experts,
            ctx=self.raw_ctx)
        grad_gates = [
            reverse_layout_transform_gradient_gate_op(
                output_grad, self.inputs[0], self.inputs[1 + i],
                self.inputs[1 + k + i], self.capacity, ctx=self.raw_ctx)
            for i in range(k)
        ]
        return [grad_data] + [None] * (2 * k) + grad_gates


class ReverseLayoutTransformGradientDataOp(Op):
    """grad wrt expert buffers: scatter gate-weighted token grads back."""

    def __init__(self, grad, indices_s, location_s, gates, capacity,
                 num_experts, ctx=None):
        super().__init__(grad, *indices_s, *location_s, *gates,
                         name="ReverseLayoutTransformGradData", ctx=ctx)
        self.capacity = int(capacity)
        self.topK = len(indices_s)
        self.num_experts = int(num_experts)
        self.force_scatter = _force_scatter_dispatch()

    def jax_fn(self, g, *rest):
        k, cap = self.topK, self.capacity
        indices = rest[:k]
        locations = rest[k:2 * k]
        gates = rest[2 * k:]
        terms = []
        for i in range(k):
            idx = _flat_int(indices[i])
            loc = _flat_int(locations[i])
            terms.append((idx * cap + loc, loc < cap,
                          gates[i].reshape(-1)))
        return _scatter_rows(terms, self.num_experts * cap, g, g.dtype,
                             force_scatter=self.force_scatter)

    def gradient(self, output_grad):
        raise NotImplementedError


class ReverseLayoutTransformGradientGateOp(Op):
    """grad wrt gate_k: dot(token grad, dispatched row)."""

    def __init__(self, grad, data, indice, location, capacity, ctx=None):
        super().__init__(grad, data, indice, location,
                         name="ReverseLayoutTransformGradGate", ctx=ctx)
        self.capacity = int(capacity)

    def jax_fn(self, g, data, indice, location):
        idx = _flat_int(indice)
        loc = _flat_int(location)
        pos = idx * self.capacity + loc
        rows = jnp.take(data, jnp.clip(pos, 0, data.shape[0] - 1), axis=0)
        rows = jnp.where((loc < self.capacity)[:, None], rows, 0.0)
        return jnp.sum(g * rows, axis=-1)

    def gradient(self, output_grad):
        raise NotImplementedError


def reverse_layout_transform_op(inp, indices_s, location_s, gates, capacity,
                                num_experts, ctx=None):
    return ReverseLayoutTransformOp(inp, indices_s, location_s, gates,
                                    capacity, num_experts, ctx=ctx)


def reverse_layout_transform_gradient_data_op(grad, indices_s, location_s,
                                              gates, capacity, num_experts,
                                              ctx=None):
    return ReverseLayoutTransformGradientDataOp(
        grad, indices_s, location_s, gates, capacity, num_experts, ctx=ctx)


def reverse_layout_transform_gradient_gate_op(grad, data, indice, location,
                                              capacity, ctx=None):
    return ReverseLayoutTransformGradientGateOp(
        grad, data, indice, location, capacity, ctx=ctx)


def reverse_layout_transform_no_gate_op(inp, indices_s, location_s, capacity,
                                        num_experts, ctx=None):
    """Combine without gate weighting (ReverseLayoutTransformNoGate,
    ReverseLayoutTransform.py:140)."""
    k = len(indices_s)

    class _NoGate(Op):
        def __init__(self):
            super().__init__(inp, *indices_s, *location_s,
                             name="ReverseLayoutTransformNoGate", ctx=ctx)
            self.capacity = int(capacity)
            self.num_experts = int(num_experts)

        def jax_fn(self, data, *rest):
            out = None
            for i in range(k):
                idx = _flat_int(rest[i])
                loc = _flat_int(rest[k + i])
                pos = idx * self.capacity + loc
                rows = jnp.take(data, jnp.clip(pos, 0, data.shape[0] - 1),
                                axis=0)
                rows = jnp.where((loc < self.capacity)[:, None], rows, 0.0)
                out = rows if out is None else out + rows
            return out

        def gradient(self, output_grad):
            # adjoint of the gather-combine is the scatter-dispatch
            total = LayoutTransformOp(
                output_grad, list(self.inputs[1:1 + k]),
                list(self.inputs[1 + k:1 + 2 * k]), self.capacity,
                self.num_experts, ctx=self.raw_ctx)
            return [total] + [None] * (2 * k)

    return _NoGate()


def _pin_dim0(x, mesh, axes):
    """pjit-mode a2a marker: constrain dim 0 over the given mesh axes
    (those present), ordered as the MESH orders them (outer-major — the
    device-order truth), so the constraint matches the expert-weight
    sharding convention and GSPMD materializes the token exchange at this
    site.  Returns x unchanged when no named axis is usable."""
    present = tuple(ax for ax in mesh.axis_names if ax in axes)
    total = 1
    for ax in present:
        total *= mesh.shape[ax]
    if not present or x.shape[0] % total:
        return x
    from jax.sharding import NamedSharding, PartitionSpec
    spec = [None] * x.ndim
    spec[0] = present if len(present) > 1 else present[0]
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec(*spec)))


class AllToAllOp(Op):
    """Expert-parallel all-to-all (gpu_ops/AllToAll.py:8-50; NCCL send/recv
    loop mpi_nccl_communication.cu:245-275).

    Input (E_total*cap, D): rows grouped by destination expert.  Inside
    shard_map over the 'ep' axis this runs jax.lax.all_to_all so each device
    ends with the rows destined for its local experts; under pjit it is an
    identity marker (XLA inserts the reshuffle from shardings).
    """

    def __init__(self, node, axis="ep", ctx=None):
        super().__init__(node, name="AllToAll", ctx=ctx)
        self.axis = axis

    def compute(self, input_vals, tc: TraceContext):
        (x,) = input_vals
        if tc.has_axis(self.axis):
            n = jax.lax.axis_size(self.axis)
            parts = x.reshape(n, x.shape[0] // n, *x.shape[1:])
            out = jax.lax.all_to_all(parts, self.axis, split_axis=0,
                                     concat_axis=0, tiled=False)
            return out.reshape(x.shape)
        if tc.mesh is not None:
            # pjit mode: pin the expert-major dim to the 'ep' axis so GSPMD
            # must materialize the redistribution (the actual all-to-all)
            # between the token-sharded dispatch and the expert compute
            return _pin_dim0(x, tc.mesh, (self.axis,))
        return x

    def gradient(self, output_grad):
        return [AllToAllOp(output_grad, axis=self.axis, ctx=self.raw_ctx)]


def alltoall_op(node, comm=None, axis="ep", ctx=None):
    return AllToAllOp(node, axis=axis, ctx=ctx)


class HAllToAllOp(Op):
    """Hierarchical all-to-all (gpu_ops/HAllToAll.py:24-50): the reference
    stages intra-node gather -> leader exchange -> scatter.  On TPU the same
    economy comes from running all_to_all per mesh axis: first over the
    intra-slice 'ici' axis, then over the cross-slice 'dcn' axis."""

    def __init__(self, node, axes=("ici", "dcn"), ctx=None):
        super().__init__(node, name="HAllToAll", ctx=ctx)
        self.axes = tuple(axes)

    def compute(self, input_vals, tc: TraceContext):
        (x,) = input_vals
        present = [ax for ax in self.axes if tc.has_axis(ax)]
        if len(present) == 2:
            # Two-stage exchange equal to one flat all-to-all over the
            # (outer, inner) superaxis: view local rows as
            # [outer_dest, inner_dest, r, ...] and exchange each stage over
            # its OWN destination dim — splitting dim 0 twice (naive
            # composition) interleaves blocks wrongly.
            a_inner, a_outer = self.axes
            n_in = jax.lax.axis_size(a_inner)
            n_out = jax.lax.axis_size(a_outer)
            r = x.shape[0] // (n_in * n_out)
            parts = x.reshape(n_out, n_in, r, *x.shape[1:])
            parts = jax.lax.all_to_all(parts, a_inner, split_axis=1,
                                       concat_axis=1)
            parts = jax.lax.all_to_all(parts, a_outer, split_axis=0,
                                       concat_axis=0)
            return parts.reshape(x.shape)
        for ax in present:
            n = jax.lax.axis_size(ax)
            parts = x.reshape(n, x.shape[0] // n, *x.shape[1:])
            x = jax.lax.all_to_all(parts, ax, split_axis=0,
                                   concat_axis=0).reshape(x.shape)
        if not present and tc.mesh is not None:
            x = _pin_dim0(x, tc.mesh, self.axes)
        return x

    def gradient(self, output_grad):
        return [HAllToAllOp(output_grad, axes=self.axes, ctx=self.raw_ctx)]


def halltoall_op(node, comm=None, axes=("ici", "dcn"), ctx=None):
    return HAllToAllOp(node, axes=axes, ctx=ctx)


def balance_assignment_op(scores, max_iterations=100, ctx=None):
    """Balanced assignment (BalanceAssignment.py:87; used by BalanceGate /
    BalanceAssignmentLayer, layers/moe_layer.py:95-133): assign each of N
    tokens to E experts with exactly-equal load N/E, maximizing score.

    Output parity with the reference kernel: a *permutation of token
    indices* of shape (N,) — the concatenation over experts of the token
    ids assigned to each expert — consumed downstream by
    ``indexing_op(tokens, indice)``.

    Implemented as auction price refinement (bounded fori_loop) followed by
    a capacity-enforcing greedy pass (lax.scan over tokens in priority
    order), which guarantees the equal-load contract the auction alone does
    not.
    """

    def f(s):
        n, e = s.shape
        cap = n // e
        eps = 1e-4

        def price_round(_, prices):
            net = s - prices[None, :]
            choice = jnp.argmax(net, axis=1)
            load = jnp.zeros((e,), jnp.float32).at[choice].add(1.0)
            return prices + jnp.where(load > cap, eps * (load - cap), 0.0)

        prices = jax.lax.fori_loop(0, max_iterations, price_round,
                                   jnp.zeros((e,), jnp.float32))
        net = s - prices[None, :]
        # greedy capacity-respecting pass: tokens in descending order of
        # their best net score each take their best expert with a free slot
        best = jnp.max(net, axis=1)
        token_order = jnp.argsort(-best)

        def take(counts, tok):
            avail = counts < cap
            sc = jnp.where(avail, net[tok], -jnp.inf)
            c = jnp.argmax(sc)
            return counts.at[c].add(1), c

        _, choice_sorted = jax.lax.scan(
            take, jnp.zeros((e,), jnp.int32), token_order)
        choice = jnp.zeros((n,), jnp.int32).at[token_order].set(choice_sorted)
        # flatten per-expert token lists: stable sort of token ids by expert
        perm = jnp.argsort(choice, stable=True)
        return perm.astype(jnp.float32)

    return _simple("BalanceAssignment", f, scores, nondiff=True, ctx=ctx)


def group_topk_idx_op(a, top1_group, topk=1, num_local_gpus=8, ctx=None):
    """Top-k expert indices restricted to the token's chosen group
    (GroupTopKIdx.cu: searches [group*num_local_gpus,(group+1)*num_local_gpus))."""
    def f(x, grp):
        g = _flat_int(grp)
        n, e = x.shape
        cols = jnp.arange(e)[None, :]
        lo = (g * num_local_gpus)[:, None]
        hi = ((g + 1) * num_local_gpus)[:, None]
        masked = jnp.where((cols >= lo) & (cols < hi), x,
                           jnp.full_like(x, -1e4))
        _, idx = jax.lax.top_k(masked, topk)
        return idx.astype(jnp.float32)
    return _simple("GroupTopKIdx", f, a, top1_group, nondiff=True, ctx=ctx)


def sam_group_sum_op(gate, num_local_gpus, ctx=None):
    """Per-node gate mass: (N, E) -> (N, G) summing contiguous expert groups
    (SamGroupSum.cu)."""
    def f(x):
        n, e = x.shape
        return x.reshape(n, num_local_gpus, e // num_local_gpus).sum(-1)
    return _simple("SamGroupSum", f, gate, ctx=ctx)


class SamMaxOp(Op):
    """SamMax.cu: outside the chosen group, keep (x - x[topk_idx]) where
    positive; zero inside the group."""

    def __init__(self, a, top1_group, topk_indice, num_local_gpus, ctx=None):
        super().__init__(a, top1_group, topk_indice, name="SamMax", ctx=ctx)
        self.num_local_gpus = num_local_gpus

    def jax_fn(self, x, grp, tki):
        g = _flat_int(grp)
        t = _flat_int(tki)
        n, e = x.shape
        ref = jnp.take_along_axis(x, t[:, None], axis=1)
        cols = jnp.arange(e)[None, :]
        in_group = (cols >= (g * self.num_local_gpus)[:, None]) & \
                   (cols < ((g + 1) * self.num_local_gpus)[:, None])
        out = jnp.where((x > ref) & ~in_group, x - ref, 0.0)
        return out

    def gradient(self, output_grad):
        from .node import vjp_gradient
        g = vjp_gradient(self, output_grad)
        return [g[0], None, None]


def sam_max_op(a, top1_group, topk_indice, num_local_gpus, ctx=None):
    return SamMaxOp(a, top1_group, topk_indice, num_local_gpus, ctx=ctx)


class DispatchOp(Op):
    """Model-parallel annotation (gpu_ops/Dispatch.py:5-34).  In the
    reference this fed a graph-splitting pass absent from the fork
    (SURVEY.md §2.5 TP caveat); here it attaches a PartitionSpec hint and is
    identity at trace time — pjit consumes the sharding."""

    def __init__(self, node, parts, ctx=None):
        super().__init__(node, name="Dispatch", ctx=ctx)
        self.parts = parts

    def compute(self, input_vals, tc: TraceContext):
        (x,) = input_vals
        if tc.mesh is not None:
            from jax.sharding import PartitionSpec as P
            from jax.lax import with_sharding_constraint
            try:
                spec = _parts_to_spec(self.parts, x.ndim, tc.mesh)
                return with_sharding_constraint(x, spec)
            except Exception:
                return x
        return x

    def gradient(self, output_grad):
        return [output_grad]


def _parts_to_spec(parts, ndim, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = [None] * ndim
    if isinstance(parts, dict):
        for dim, axis in parts.items():
            spec[dim] = axis if isinstance(axis, str) else "tp"
    return NamedSharding(mesh, P(*spec))


def dispatch(node, parts, ctx=None):
    return DispatchOp(node, parts, ctx=ctx)
