"""Graph IR: op node base + the full op-factory surface.

The factory names mirror the reference's gpu_ops/__init__.py exports
(Appendix A of SURVEY.md) so reference example scripts map 1:1.
"""

from .node import Op, SimpleOp, TraceContext
from .autodiff import gradients, find_topo_sort, sum_node_list
from .ops_misc import (
    PlaceholderOp, Variable, placeholder_op, VJPOp, SumOp, sum_op,
    OnesLikeOp, ZerosLikeOp, oneslike_op, zeroslike_op, full_op,
    full_like_op, arange_op, rand_op,
)
from .ops_math import (
    add_op, minus_op, mul_op, div_op, addbyconst_op, minus_byconst_op,
    mul_byconst_op, div_const_op, opposite_op, abs_op, abs_gradient_op,
    exp_op, log_op, log_grad_op, pow_op, pow_gradient_op, const_pow_op,
    const_pow_gradient_op, sqrt_op, rsqrt_op, sin_op, cos_op, floor_op,
    ceil_op, clamp_op, bool_op, where_op, where_const_op, masked_fill_op,
    sign_op, max_op, min_op, relu_op, relu_gradient_op, leaky_relu_op,
    leaky_relu_gradient_op, gelu_op, gelu_gradient_op, sigmoid_op, tanh_op,
    tanh_gradient_op, softmax_op, softmax_gradient_op, softmax_func,
    log_softmax_op,
)
from .ops_matmul import (
    matmul_op, linear_op, batch_matmul_op, baddbmm_op, addmm_op,
    addmm_gradient_op, matrix_dot_op, outer_op, csrmv_op, csrmm_op,
)
from .ops_conv import (
    conv2d_op, conv2d_add_bias_op, conv2d_broadcastto_op,
    conv2d_reducesum_op, max_pool2d_op, avg_pool2d_op,
    batch_normalization_op, layer_normalization_op,
    instance_normalization2d_op, dropout_op, dropout2d_op, BatchNormOp,
    DropoutOp,
)
from .ops_shape import (
    broadcast_reduce_op, broadcastto_op, broadcast_shape_op, reduce_sum_op,
    reduce_mean_op, reducesumaxiszero_op, reduce_min_op, reduce_norm1_op,
    reduce_norm2_op, norm_op, array_reshape_op, transpose_op, squeeze_op,
    slice_op,
    slice_assign_op, slice_assign_matrix_op, slice_by_matrix_op, split_op,
    concat_op, concatenate_op, pad_op, flatten_op, tile_op, repeat_op,
    roll_op, interpolate_op, gather_op, scatter_op, scatter1d_op,
    indexing_op, one_hot_op, argmax_op, argsort_op, argmax_partial_op,
    cumsum_with_bias_op, cumsum_op, topk_idx_op, topk_val_op, min_dist_op,
)
from .ops_loss import (
    softmaxcrossentropy_op, softmaxcrossentropy_sparse_op, crossentropy_op,
    crossentropy_sparse_op, binarycrossentropy_op,
    binarycrossentropywithlogits_op, nll_loss_op, mseloss_op,
    tied_lm_head_xent_op,
)
from .ops_embed import (
    EmbeddingLookupOp, embedding_lookup_op, IndexedSlicesOp,
    unique_indices_op,
)
from .ops_gnn import (
    DistGCN15dOp, distgcn_15d_op, gcn_layer_shard_specs,
)
