"""Communication ops: the reference comm-op surface, TPU-native semantics.

Reference: gpu_ops/AllReduceCommunicate.py, AllGatherCommunicate.py,
ReduceScatterCommunicate.py, BroadcastCommunicate.py, ReduceCommunicate.py,
AllToAll.py, HAllToAll.py, PipelineSend.py/PipelineReceive.py,
ParameterServerCommunicate.py, DataTransfer.py.

TPU-native semantics (SURVEY.md §2.2 "TPU equivalent"): under pjit with
sharding annotations, XLA inserts the collectives — so inside a plain jit
trace these ops are *annotation markers* (identity + sharding constraint).
Inside a shard_map trace (tc.axis_env non-empty) they execute the real
``jax.lax`` collective over the named mesh axis.  This dual behavior means
the same user graph runs under either execution style.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .node import Op, TraceContext


class CollectiveOp(Op):
    """Base: collective over a mesh axis; identity annotation under pjit."""

    axis_default = "dp"

    def __init__(self, node, axis=None, name=None, ctx=None):
        super().__init__(node, name=name, ctx=ctx)
        self.axis = axis or self.axis_default

    def collective(self, x, axis):
        raise NotImplementedError

    def compute(self, input_vals, tc: TraceContext):
        (x,) = input_vals
        if tc.has_axis(self.axis):
            return self.collective(x, self.axis)
        return x  # pjit mode: XLA inserts the collective from shardings

    def gradient(self, output_grad):
        # gradient of psum is psum (identity in pjit mode) — reference
        # AllReduceCommunicate has no gradient (applied to grads already)
        return [output_grad]


class AllReduceCommunicateOp(CollectiveOp):
    def collective(self, x, axis):
        return jax.lax.psum(x, axis)


class GroupAllReduceCommunicateOp(AllReduceCommunicateOp):
    pass


class AllGatherCommunicateOp(CollectiveOp):
    axis_default = "tp"

    def collective(self, x, axis):
        return jax.lax.all_gather(x, axis, tiled=True)


class ReduceScatterCommunicateOp(CollectiveOp):
    axis_default = "tp"

    def collective(self, x, axis):
        return jax.lax.psum_scatter(x, axis, tiled=True)


class BroadcastCommunicateOp(CollectiveOp):
    def __init__(self, node, root=0, axis=None, ctx=None):
        super().__init__(node, axis=axis, ctx=ctx)
        self.root = root

    def collective(self, x, axis):
        idx = jax.lax.axis_index(axis)
        n = jax.lax.axis_size(axis)
        src = jnp.where(idx == self.root, x, jnp.zeros_like(x))
        return jax.lax.psum(src, axis)


class ReduceCommunicateOp(CollectiveOp):
    def __init__(self, node, root=0, axis=None, ctx=None):
        super().__init__(node, axis=axis, ctx=ctx)
        self.root = root

    def collective(self, x, axis):
        return jax.lax.psum(x, axis)  # all ranks get it; root semantics free


def allreduceCommunicate_op(node, comm=None, axis="dp", ctx=None):
    return AllReduceCommunicateOp(node, axis=axis, ctx=ctx)


def allreduceCommunicatep2p_op(node, comm=None, axis="dp", ctx=None):
    return AllReduceCommunicateOp(node, axis=axis, ctx=ctx)


def groupallreduceCommunicate_op(node, comm=None, axis="dp", ctx=None):
    return GroupAllReduceCommunicateOp(node, axis=axis, ctx=ctx)


def allgatherCommunicate_op(node, comm=None, axis="tp", ctx=None):
    return AllGatherCommunicateOp(node, axis=axis, ctx=ctx)


def reducescatterCommunicate_op(node, comm=None, axis="tp", ctx=None):
    return ReduceScatterCommunicateOp(node, axis=axis, ctx=ctx)


def broadcastCommunicate_op(node, comm=None, root=0, axis="dp", ctx=None):
    return BroadcastCommunicateOp(node, root=root, axis=axis, ctx=ctx)


def reduceCommunicate_op(node, comm=None, root=0, axis="dp", ctx=None):
    return ReduceCommunicateOp(node, root=root, axis=axis, ctx=ctx)


# --------------------------------------------------------------------- #
# quantized collective pair (HETU_COMM_QUANT=int8; EQuARX lineage)
# --------------------------------------------------------------------- #
#
# A quantized gradient aggregation is THREE nodes, so the static
# checkers can see (and reject a broken) pairing before compile:
#
#     QuantizeCommOp  ->  QuantAllReduceCommunicateOp  ->  DequantizeCommOp
#     f32 -> (int8,scales)    all_gather the pair          decode + sum
#
# int8 cannot be psum'd directly (overflow, and the scales would sum
# wrong), so the collective is an all_gather of the (payload, scales)
# pytree — the interconnect carries int8 bytes — and the dequantize side
# decodes each participant's contribution and reduces in f32.  Under
# shard_map execution (tc.has_axis) this is the real quantized
# collective; under pjit, where XLA owns collective insertion and the
# plain CollectiveOp degrades to an annotation, the pair degrades to a
# shape-preserving fake-quant of the gradient (EQuARX does the int8
# rewrite inside XLA itself, which is exactly the part we cannot reach
# from op level).  ``analysis/shard_check.check_quantized_collectives``
# rejects any quantize without its paired dequantize across the
# collective; emit the trio via :func:`quantized_allreduce_op`.

class QuantizeCommOp(Op):
    """Encode a float tensor to (int8 payload, f32 scales) for a
    quantized collective.  Output is a 2-tuple pytree; its ONLY legal
    consumer is a quantized collective (shard_check enforces this)."""

    def __init__(self, node, axis=None, chunk=None, ctx=None):
        super().__init__(node, name="QuantizeComm", ctx=ctx)
        self.axis = axis or "dp"
        from .. import quant as _quant
        self.chunk = int(chunk or _quant.wire_chunk())

    def compute(self, input_vals, tc: TraceContext):
        from .. import quant as _quant
        (x,) = input_vals
        flat = x.astype(jnp.float32).reshape(-1)
        pad = (-flat.shape[0]) % self.chunk
        if pad:
            flat = jnp.pad(flat, (0, pad))
        return _quant.quantize_jax(flat, self.chunk)

    def gradient(self, output_grad):
        return [output_grad]


class QuantAllReduceCommunicateOp(CollectiveOp):
    """The collective leg of the pair: all_gather the (int8, scales)
    pytree over ``axis`` so the wire moves quantized bytes.  Always
    emits a leading participant dim (size 1 under pjit, where the
    collective is an annotation) so the dequantize side can reduce
    uniformly."""

    axis_default = "dp"

    def compute(self, input_vals, tc: TraceContext):
        (pair,) = input_vals
        if tc.has_axis(self.axis):
            return jax.lax.all_gather(pair, self.axis)
        return jax.tree_util.tree_map(lambda a: a[None], pair)

    def gradient(self, output_grad):
        return [output_grad]


class DequantizeCommOp(Op):
    """Decode the gathered (int8, scales) pair and reduce: each
    participant's contribution dequantizes to f32 and the sum is the
    quantized AllReduce's result, reshaped back to the original
    gradient shape."""

    def __init__(self, node, shape, axis=None, chunk=None, ctx=None):
        super().__init__(node, name="DequantizeComm", ctx=ctx)
        self.axis = axis or "dp"
        self.shape = tuple(int(d) for d in shape)
        from .. import quant as _quant
        self.chunk = int(chunk or _quant.wire_chunk())

    def compute(self, input_vals, tc: TraceContext):
        from .. import quant as _quant
        (pair,) = input_vals
        q, scales = pair                       # [n, padded], [n, chunks]
        out = _quant.dequantize_jax(
            q.reshape(-1, q.shape[-1]), scales.reshape(-1, scales.shape[-1]),
            self.chunk).sum(axis=0)
        n = 1
        for d in self.shape:
            n *= d
        return out[:n].reshape(self.shape)

    def infer_shape(self, input_shapes, input_dtypes=None):
        return self.shape

    def gradient(self, output_grad):
        return [output_grad]


def quantized_allreduce_op(node, axis="dp", chunk=None, shape=None,
                           ctx=None):
    """Emit the quantize→all_gather→dequantize trio for one gradient
    (``shape`` = the gradient's shape; taken from ``node.shape`` when
    the node carries one).  Returns the DequantizeCommOp head."""
    if shape is None:
        shape = getattr(node, "shape", None)
    if shape is None:
        raise ValueError(
            f"quantized_allreduce_op needs the gradient shape for "
            f"{node!r}: pass shape= (the node carries none)")
    q = QuantizeCommOp(node, axis=axis, chunk=chunk, ctx=ctx)
    g = QuantAllReduceCommunicateOp(q, axis=axis, ctx=ctx)
    return DequantizeCommOp(g, shape, axis=axis, chunk=q.chunk, ctx=ctx)


class PipelineSendOp(Op):
    """P2P send to the next pipeline stage.  Under the scan-based pipeline
    executor these become ppermute rotations (parallel/pipeline.py); as a
    standalone node it is a ppermute by +1 on the 'pp' axis.
    Reference: gpu_ops/PipelineSend.py (NCCL send on p2p stream)."""

    def __init__(self, node, dst=None, axis="pp", ctx=None):
        super().__init__(node, name="PipelineSend", ctx=ctx)
        self.dst = dst
        self.axis = axis

    def compute(self, input_vals, tc: TraceContext):
        (x,) = input_vals
        if tc.has_axis(self.axis):
            n = jax.lax.axis_size(self.axis)
            perm = [(i, (i + 1) % n) for i in range(n)]
            return jax.lax.ppermute(x, self.axis, perm)
        return x

    def gradient(self, output_grad):
        return [PipelineReceiveOp(output_grad, axis=self.axis)]


class PipelineReceiveOp(Op):
    """P2P receive from the previous stage (ppermute by -1)."""

    def __init__(self, node, src=None, axis="pp", ctx=None):
        super().__init__(node, name="PipelineReceive", ctx=ctx)
        self.src = src
        self.axis = axis

    def compute(self, input_vals, tc: TraceContext):
        (x,) = input_vals
        if tc.has_axis(self.axis):
            n = jax.lax.axis_size(self.axis)
            perm = [(i, (i - 1) % n) for i in range(n)]
            return jax.lax.ppermute(x, self.axis, perm)
        return x

    def gradient(self, output_grad):
        return [PipelineSendOp(output_grad, axis=self.axis)]


def pipeline_send_op(node, dst=None, comm=None, stream=None, ctx=None):
    return PipelineSendOp(node, dst=dst, ctx=ctx)


def pipeline_receive_op(node, src=None, comm=None, stream=None, ctx=None):
    return PipelineReceiveOp(node, src=src, ctx=ctx)


class ParameterServerCommunicateOp(Op):
    """PS push-pull of a gradient (reference ParameterServerCommunicate.py).
    The TPU build routes PS traffic through the host-side KV server
    (hetu_tpu.ps); in-graph this is an annotation consumed by the executor's
    hybrid path, identity otherwise."""

    def __init__(self, node, ps_table=None, ctx=None):
        super().__init__(node, name="PSCommunicate", ctx=ctx)
        self.ps_table = ps_table

    def jax_fn(self, x):
        return x

    def gradient(self, output_grad):
        return [output_grad]


def parameterServerCommunicate_op(node, comm=None, optimizer=None, ctx=None):
    return ParameterServerCommunicateOp(node, ctx=ctx)


class ParameterServerSparsePullOp(Op):
    def __init__(self, node, ids, ctx=None):
        super().__init__(node, ids, name="PSSparsePull", ctx=ctx)

    def jax_fn(self, table, ids):
        return jnp.take(table, ids.astype(jnp.int32), axis=0)

    def gradient(self, output_grad):
        from .ops_embed import IndexedSlicesOp
        return [IndexedSlicesOp(self.inputs[0], self.inputs[1], output_grad),
                None]


def parameterServerSparsePull_op(node, ids, ctx=None):
    return ParameterServerSparsePullOp(node, ids, ctx=ctx)


# Host<->device transfers are owned by XLA/PJRT; kept as identity for parity
# (reference gpu_ops/DataTransfer.py).

class DataTransferOp(Op):
    def __init__(self, node, ctx=None, name="DataTransfer"):
        super().__init__(node, name=name, ctx=ctx)

    def jax_fn(self, x):
        return x

    def gradient(self, output_grad):
        return [output_grad]


def datah2d_op(node, ctx=None):
    return DataTransferOp(node, ctx=ctx, name="DataH2D")


def datad2h_op(node, ctx=None):
    return DataTransferOp(node, ctx=ctx, name="DataD2H")
