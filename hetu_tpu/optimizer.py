"""Optimizers: SGD / Momentum / AdaGrad / Adam / AdamW / Lamb.

Reference: python/hetu/optimizer.py (SGDOptimizer:171 ... LambOptimizer:493,
OptimizerOp:103, minimize:69-89) with fused CUDA kernels in
src/ops/Optimizers.cu and row-sparse variants in OptimizersSparse.cu.

TPU-native design: each optimizer is a *pure* update function applied inside
the jitted step (XLA fuses the whole update chain); the reference's
backward_hook graph-splicing of AllReduce/PS comm ops (optimizer.py:145-164)
is unnecessary — gradient reduction comes from sharding annotations, and
embedding-table updates take the row-sparse path when the adjoint is an
IndexedSlicesOp.

Optimizer slot state (momentum/m/v buffers) is checkpointable — strictly
better than the reference, which loses it on save (SURVEY.md §5.4).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .graph.node import Op, TraceContext
from .graph.autodiff import gradients, find_topo_sort
from .graph.ops_misc import PlaceholderOp
from .graph.ops_embed import IndexedSlicesOp


def merge_duplicate_rows(ids, rows):
    """Sum rows sharing an id so every duplicate carries the identical
    total (reference: IndexedSlices.deduplicate, ndarray.py:507-606 /
    src/ops/IndexedSlices.cu — but jit-compatible: static shapes, no
    compaction; duplicate positions stay, carrying equal merged values)."""
    order = jnp.argsort(ids)
    sid = ids[order]
    srows = rows[order]
    first = jnp.concatenate([jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    group = jnp.cumsum(first) - 1
    totals = jnp.zeros_like(srows).at[group].add(srows)
    trows = totals[group]
    inv = jnp.zeros_like(order).at[order].set(jnp.arange(order.shape[0]))
    return ids, trows[inv]


class Optimizer:
    def __init__(self, learning_rate, l2reg=0.0):
        self.learning_rate = learning_rate
        self.l2reg = l2reg
        # max global gradient norm (None = off).  An attribute rather
        # than a per-subclass kwarg: set it on any optimizer instance
        # (`opt.clip_grad_norm = 1.0`) before minimize(); the clip
        # factor folds into the step's grad scaling inside the jitted
        # program (OptimizerOp.apply), so it reaches dense, row-sparse
        # AND PS-routed gradients uniformly.  The reference has no
        # clipping; standard for LM training.
        self.clip_grad_norm = None
        self.name = type(self).__name__

    # ------------------------------------------------------------------ #
    # graph-side API (reference optimizer.py:36-101)
    # ------------------------------------------------------------------ #

    def get_var_list(self, loss):
        if isinstance(loss, list):
            topo = find_topo_sort(loss)
        else:
            topo = find_topo_sort([loss])
        return [n for n in topo
                if isinstance(n, PlaceholderOp) and n.trainable]

    def minimize(self, loss, var_list=None):
        if var_list is None:
            var_list = self.get_var_list(loss)
        grads = gradients(loss, var_list)
        return OptimizerOp(grads, var_list, self)

    # ------------------------------------------------------------------ #
    # pure update functions (jit-traced)
    # ------------------------------------------------------------------ #

    def lr_value(self, step):
        lr = self.learning_rate
        if hasattr(lr, "value"):
            return lr.value(step)
        return jnp.asarray(lr, jnp.float32)

    def init_state_one(self, p):
        """Slot state pytree for one parameter (None = stateless)."""
        return None

    def update_one(self, p, g, s, lr, step):
        raise NotImplementedError

    def sparse_update_one(self, p, ids, rows, s, lr, step):
        """Row-sparse update: default densifies; subclasses override with a
        gather-update-scatter on touched rows only (lazy update, matching
        src/ops/OptimizersSparse.cu semantics).

        Contract: ``rows`` are pre-merged per id (duplicate ids carry the
        identical summed row — see ``merge_duplicate_rows``), so overrides
        may use set-style scatters; duplicate writes are identical."""
        dense = jnp.zeros_like(p).at[ids].set(rows)
        return self.update_one(p, dense, s, lr, step)

    def _apply_l2(self, p, g):
        if self.l2reg > 0:
            return g + self.l2reg * p
        return g

    def server_opt_spec(self):
        """(name, kwargs) of the matching PS server-side optimizer
        (ps/server.py SERVER_OPTIMIZERS), or None when no server
        counterpart exists (AdamW/Lamb).  Used by the executor's PS/Hybrid
        comm modes: the worker pushes raw grads and the server applies this
        optimizer (reference server/optimizer.h:36-275 semantics)."""
        return None


class SGDOptimizer(Optimizer):
    """reference optimizer.py:171."""

    def update_one(self, p, g, s, lr, step):
        g = self._apply_l2(p, g)
        return p - lr * g, s

    def sparse_update_one(self, p, ids, rows, s, lr, step):
        # rows are merged per id; set-style write is duplicate-safe
        if self.l2reg > 0:
            rows = rows + self.l2reg * p[ids]
        return p.at[ids].set(p[ids] - lr * rows), s

    def server_opt_spec(self):
        if hasattr(self.learning_rate, "value"):   # schedules stay local
            return None
        return "sgd", {"learning_rate": float(self.learning_rate)}


class MomentumOptimizer(Optimizer):
    """reference optimizer.py:229 (momentum + nesterov flag)."""

    def __init__(self, learning_rate, momentum=0.9, nesterov=False, l2reg=0.0):
        super().__init__(learning_rate, l2reg)
        self.momentum = momentum
        self.nesterov = nesterov

    def init_state_one(self, p):
        return {"v": jnp.zeros_like(p)}

    def update_one(self, p, g, s, lr, step):
        g = self._apply_l2(p, g)
        v = self.momentum * s["v"] - lr * g
        if self.nesterov:
            p = p + self.momentum * v - lr * g
        else:
            p = p + v
        return p, {"v": v}

    def sparse_update_one(self, p, ids, rows, s, lr, step):
        """Lazy momentum: velocity advances only for touched rows
        (reference OptimizersSparse.cu semantics; also what the PS
        server-side momentum does)."""
        if self.l2reg > 0:
            rows = rows + self.l2reg * p[ids]
        v_rows = self.momentum * s["v"][ids] - lr * rows
        v = s["v"].at[ids].set(v_rows)
        if self.nesterov:
            upd = self.momentum * v_rows - lr * rows
        else:
            upd = v_rows
        return p.at[ids].set(p[ids] + upd), {"v": v}

    def server_opt_spec(self):
        if hasattr(self.learning_rate, "value"):
            return None
        return ("momentum", {"learning_rate": float(self.learning_rate),
                             "momentum": self.momentum,
                             "nesterov": self.nesterov})


class AdaGradOptimizer(Optimizer):
    """reference optimizer.py:293."""

    def __init__(self, learning_rate, initial_accumulator_value=0.0,
                 eps=1e-7, l2reg=0.0):
        super().__init__(learning_rate, l2reg)
        self.initial_accumulator_value = initial_accumulator_value
        self.eps = eps

    def init_state_one(self, p):
        return {"acc": jnp.full_like(p, self.initial_accumulator_value)}

    def update_one(self, p, g, s, lr, step):
        g = self._apply_l2(p, g)
        acc = s["acc"] + g * g
        return p - lr * g / (jnp.sqrt(acc) + self.eps), {"acc": acc}

    def sparse_update_one(self, p, ids, rows, s, lr, step):
        if self.l2reg > 0:
            rows = rows + self.l2reg * p[ids]
        acc = s["acc"].at[ids].set(s["acc"][ids] + rows * rows)
        denom = jnp.sqrt(acc[ids]) + self.eps
        return p.at[ids].set(p[ids] - lr * rows / denom), {"acc": acc}

    def server_opt_spec(self):
        if hasattr(self.learning_rate, "value"):
            return None
        return ("adagrad", {"learning_rate": float(self.learning_rate),
                            "initial_accumulator_value":
                                self.initial_accumulator_value,
                            "eps": self.eps})


class AdamOptimizer(Optimizer):
    """reference optimizer.py:356 (beta1/beta2/epsilon; bias-corrected)."""

    def __init__(self, learning_rate=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-7, l2reg=0.0, amsgrad=False):
        super().__init__(learning_rate, l2reg)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.amsgrad = amsgrad

    def init_state_one(self, p):
        s = {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p)}
        if self.amsgrad:
            s["vmax"] = jnp.zeros_like(p)
        return s

    def update_one(self, p, g, s, lr, step):
        g = self._apply_l2(p, g)
        t = step.astype(jnp.float32) + 1.0
        m = self.beta1 * s["m"] + (1 - self.beta1) * g
        v = self.beta2 * s["v"] + (1 - self.beta2) * g * g
        mhat = m / (1 - self.beta1 ** t)
        ns = {"m": m, "v": v}
        if self.amsgrad:
            vmax = jnp.maximum(s["vmax"], v)
            ns["vmax"] = vmax
            vhat = vmax / (1 - self.beta2 ** t)
        else:
            vhat = v / (1 - self.beta2 ** t)
        return p - lr * mhat / (jnp.sqrt(vhat) + self.epsilon), ns

    def sparse_update_one(self, p, ids, rows, s, lr, step):
        """Lazy Adam: only touched rows update their moments."""
        if self.l2reg > 0:
            rows = rows + self.l2reg * p[ids]
        t = step.astype(jnp.float32) + 1.0
        m_rows = self.beta1 * s["m"][ids] + (1 - self.beta1) * rows
        v_rows = self.beta2 * s["v"][ids] + (1 - self.beta2) * rows * rows
        m = s["m"].at[ids].set(m_rows)
        v = s["v"].at[ids].set(v_rows)
        mhat = m_rows / (1 - self.beta1 ** t)
        ns = {"m": m, "v": v}
        if self.amsgrad:
            vmax_rows = jnp.maximum(s["vmax"][ids], v_rows)
            ns["vmax"] = s["vmax"].at[ids].set(vmax_rows)
            vhat = vmax_rows / (1 - self.beta2 ** t)
        else:
            vhat = v_rows / (1 - self.beta2 ** t)
        upd = -lr * mhat / (jnp.sqrt(vhat) + self.epsilon)
        return p.at[ids].set(p[ids] + upd), ns

    def server_opt_spec(self):
        if hasattr(self.learning_rate, "value") or self.amsgrad:
            return None
        return ("adam", {"learning_rate": float(self.learning_rate),
                         "beta1": self.beta1, "beta2": self.beta2,
                         "epsilon": self.epsilon})


class AdamWOptimizer(AdamOptimizer):
    """reference optimizer.py:429 — decoupled weight decay."""

    def __init__(self, learning_rate=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-7, weight_decay=0.01):
        super().__init__(learning_rate, beta1, beta2, epsilon, l2reg=0.0)
        self.weight_decay = weight_decay

    def update_one(self, p, g, s, lr, step):
        t = step.astype(jnp.float32) + 1.0
        m = self.beta1 * s["m"] + (1 - self.beta1) * g
        v = self.beta2 * s["v"] + (1 - self.beta2) * g * g
        mhat = m / (1 - self.beta1 ** t)
        vhat = v / (1 - self.beta2 ** t)
        p = p - lr * (mhat / (jnp.sqrt(vhat) + self.epsilon)
                      + self.weight_decay * p)
        return p, {"m": m, "v": v}

    def sparse_update_one(self, p, ids, rows, s, lr, step):
        """Lazy AdamW: decoupled decay applied to the touched rows only
        (matching the reference's row-sparse optimizer semantics,
        src/ops/OptimizersSparse.cu)."""
        t = step.astype(jnp.float32) + 1.0
        m_rows = self.beta1 * s["m"][ids] + (1 - self.beta1) * rows
        v_rows = self.beta2 * s["v"][ids] + (1 - self.beta2) * rows * rows
        m = s["m"].at[ids].set(m_rows)
        v = s["v"].at[ids].set(v_rows)
        mhat = m_rows / (1 - self.beta1 ** t)
        vhat = v_rows / (1 - self.beta2 ** t)
        upd = -lr * (mhat / (jnp.sqrt(vhat) + self.epsilon)
                     + self.weight_decay * p[ids])
        return p.at[ids].set(p[ids] + upd), {"m": m, "v": v}

    def server_opt_spec(self):
        return None  # decoupled decay has no server-side counterpart


class LambOptimizer(AdamOptimizer):
    """reference optimizer.py:493 — layerwise trust-ratio Adam."""

    def __init__(self, learning_rate=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-7, weight_decay=0.01):
        super().__init__(learning_rate, beta1, beta2, epsilon, l2reg=0.0)
        self.weight_decay = weight_decay

    def update_one(self, p, g, s, lr, step):
        m = self.beta1 * s["m"] + (1 - self.beta1) * g
        v = self.beta2 * s["v"] + (1 - self.beta2) * g * g
        update = m / (jnp.sqrt(v) + self.epsilon) + self.weight_decay * p
        wnorm = jnp.linalg.norm(p.reshape(-1))
        unorm = jnp.linalg.norm(update.reshape(-1))
        ratio = jnp.where(wnorm > 0, jnp.where(unorm > 0, wnorm / unorm, 1.0), 1.0)
        return p - lr * ratio * update, {"m": m, "v": v}

    def sparse_update_one(self, p, ids, rows, s, lr, step):
        """Row-sparse Lamb: per-row trust ratio over the touched rows."""
        m_rows = self.beta1 * s["m"][ids] + (1 - self.beta1) * rows
        v_rows = self.beta2 * s["v"][ids] + (1 - self.beta2) * rows * rows
        m = s["m"].at[ids].set(m_rows)
        v = s["v"].at[ids].set(v_rows)
        p_rows = p[ids]
        upd = m_rows / (jnp.sqrt(v_rows) + self.epsilon) \
            + self.weight_decay * p_rows
        wnorm = jnp.linalg.norm(p_rows, axis=-1, keepdims=True)
        unorm = jnp.linalg.norm(upd, axis=-1, keepdims=True)
        ratio = jnp.where((wnorm > 0) & (unorm > 0), wnorm / unorm, 1.0)
        return p.at[ids].set(p[ids] - lr * ratio * upd), {"m": m, "v": v}

    def server_opt_spec(self):
        return None  # trust-ratio needs whole-param norms; stays local


class OptimizerOp(Op):
    """Terminal graph node applying parameter updates.

    Reference OptimizerOp (optimizer.py:103-168) splices comm ops in its
    backward_hook; here ``compute`` consumes traced gradient values and
    emits new (param, slot-state) values via tc.extra_outputs — the executor
    threads them out of the jitted step function (with buffer donation, so
    updates are in-place in HBM).
    """

    def __init__(self, grads, var_list, optimizer):
        super().__init__(*grads, name="Optimizer")
        # checkpoint-stable name: derived from the optimizer class and the
        # variable names, NOT the global node-id counter — otherwise saved
        # optimizer state cannot be keyed back in a fresh process (the old
        # key-set remapping collided when two optimizers covered identical
        # param-name sets)
        import hashlib
        digest = hashlib.sha1(
            "|".join(sorted(v.name for v in var_list)).encode()
        ).hexdigest()[:10]
        self.name = f"opt_{type(optimizer).__name__}_{digest}"
        self.var_list = var_list
        self.optimizer = optimizer
        # sparse adjoints are consumed structurally, not evaluated densely
        self.sparse_inputs = {i for i, g in enumerate(grads)
                              if isinstance(g, IndexedSlicesOp)}

    def compute(self, input_vals, tc: TraceContext):
        raise AssertionError("OptimizerOp is handled by the executor")

    def apply(self, grad_vals, tc: TraceContext, opt_state, grad_scale=None,
              ps_vars=frozenset(), side_outputs=None):
        """grad_vals[i] is either a dense array or (ids, rows) for sparse.

        Vars named in ``ps_vars`` are parameter-server-managed (Hybrid/PS
        comm modes): their update is NOT applied here; the (scaled) grad is
        emitted through ``side_outputs`` and the executor pushes it to the
        PS after the jitted step (reference optimizer.py:145-164
        backward_hook routing, ParameterServerCommunicate.py:38-57)."""
        opt = self.optimizer
        lr = opt.lr_value(tc.step)
        clip_cfg = getattr(opt, "clip_grad_norm", None)
        if clip_cfg is not None and clip_cfg <= 0:
            raise ValueError(
                f"clip_grad_norm must be positive, got {clip_cfg}")
        if clip_cfg is not None:
            # global-norm clip folded into grad_scale so every grad kind
            # (dense / sparse rows / PS-routed) scales identically.  For
            # sparse adjoints the norm uses per-position rows BEFORE
            # duplicate-id merging — an upper bound on the merged-grad
            # norm when ids repeat, i.e. clipping is (slightly)
            # conservative there.
            sq = jnp.asarray(0.0, jnp.float32)
            for i in range(len(grad_vals)):
                if i in self.sparse_inputs:
                    _ids, rows = grad_vals[i]
                    sq = sq + jnp.sum(rows.astype(jnp.float32) ** 2)
                else:
                    sq = sq + jnp.sum(
                        grad_vals[i].astype(jnp.float32) ** 2)
            if grad_scale is not None:
                sq = sq * jnp.asarray(grad_scale, jnp.float32) ** 2
            gnorm = jnp.sqrt(sq)
            factor = jnp.minimum(
                1.0, opt.clip_grad_norm / (gnorm + 1e-6))
            grad_scale = factor if grad_scale is None \
                else grad_scale * factor
        new_state = dict(opt_state)
        for i, var in enumerate(self.var_list):
            if var.name in ps_vars:
                if i in self.sparse_inputs:
                    ids, rows = grad_vals[i]
                    ids = ids.astype(jnp.int32).reshape(-1)
                    rows = rows.reshape(-1, rows.shape[-1])
                    if grad_scale is not None:
                        rows = rows * grad_scale
                    # (vocab ids, per-position rows): the executor's
                    # device-side dedup maps ids -> unique-row slots, so
                    # several lookups into one table compose (their
                    # adjoints arrive concatenated)
                    side_outputs[var.name] = (ids,
                                              rows.astype(jnp.float32))
                else:
                    g = grad_vals[i]
                    if grad_scale is not None:
                        g = g * grad_scale
                    side_outputs[var.name] = g.astype(jnp.float32)
                new_state[var.name] = opt_state.get(var.name)
                continue
            p = tc.params[var]
            s = opt_state.get(var.name)
            if i in self.sparse_inputs:
                ids, rows = grad_vals[i]
                ids = ids.astype(jnp.int32).reshape(-1)
                rows = rows.reshape(-1, rows.shape[-1])
                if grad_scale is not None:
                    rows = rows * grad_scale
                ids, rows = merge_duplicate_rows(ids, rows)
                new_p, ns = opt.sparse_update_one(p, ids, rows, s, lr, tc.step)
            else:
                g = grad_vals[i]
                if grad_scale is not None:
                    g = g * grad_scale
                new_p, ns = opt.update_one(p, g.astype(p.dtype), s, lr, tc.step)
            tc.extra_outputs[var] = new_p
            new_state[var.name] = ns
        return new_state

    def gradient(self, output_grad):
        raise NotImplementedError

    def init_state(self, params, skip=()):
        """``skip``: PS-managed var names whose slot state lives on the
        server (ps/server.py ServerOptimizer.init_state), not here."""
        return {var.name: (None if var.name in skip
                           else self.optimizer.init_state_one(params[var]))
                for var in self.var_list}
