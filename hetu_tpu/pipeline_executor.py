"""Pipeline-as-an-executor-mode: ``Executor(pipeline='gpipe'|'1f1b'|...)``.

Reference behavior being matched: ``Executor(..., pipeline='gpipe')``
partitions the built graph at recv/send boundaries and drives microbatch
schedules over the partitions (gpipe_subexecutor.py:33-111,
pipedream_subexecutor.py:51-372, partition logic
pipeline_subexecutor.py:29-81).  The reference choreographs per-op sends
and receives over NCCL from the host; on TPU the whole schedule lives
inside ONE jitted XLA program.

Two lowerings, chosen automatically from the partitioner's plan
(parallel/partition.py):

1. **SPMD scan pipeline** — mesh has a 'pp' axis, the graph has a uniform
   repeated body (e.g. N identical transformer blocks), and the mode is a
   synchronous schedule ('gpipe'/'1f1b').  Body-block params are stacked
   ``[S, R/S, ...]`` and sharded over 'pp'; microbatches flow through
   the scan+ppermute pipeline; the non-uniform ends — embedding in
   front, head+loss behind — run OUTSIDE the pipeline loop, vmapped over
   microbatches (the reference folds them into first/last stage; here
   their big tensors are instead SHARDED over the otherwise-idle 'pp'
   axis, see ``_shard_end_params_over_pp``, so neither their params nor
   their optimizer state are replicated per stage).  Two schedules:

   * 'gpipe' (``spmd_pipeline``): differentiate through the forward
     scan; activation high-water O(M + S) saved boundary carries.
   * '1f1b' (``spmd_pipeline_1f1b``): custom-VJP staggered
     one-forward-one-backward schedule; activation high-water O(S)
     in-flight boundary slots per device — the real PipeDream/1F1B
     memory property (pipedream_subexecutor.py:25-48), proven by
     ``profiler.memory_analysis`` in test_pipeline_executor.

2. **Microbatch scan** — no 'pp' mesh axis or no uniform body.  The step
   jits a ``lax.scan`` over microbatches: 'gpipe'/'1f1b' accumulate grads
   and update once (their loss trajectory is IDENTICAL to the
   non-pipelined step, which is what the reference's tier-2 equivalence
   suite asserts; with no 'pp' axis there are no stages, so '1f1b' has
   no schedule to stagger and is gpipe by construction); 'pipedream'
   applies per-microbatch updates in the scan
   carry (reference per-in-flight-microbatch weight semantics collapse to
   sequential per-microbatch SGD when the program is a single SPMD step);
   'hetpipe' is 'pipedream' plus a host-side PS delta-sync every
   ``sync_every`` batches (pipedream_subexecutor.py:317-328).

Parameter storage stays name-keyed and unstacked (per-layer masters);
the SPMD path stacks in-trace under a 'pp' sharding constraint.  That
keeps checkpointing, load_dict, and eval subgraphs untouched; the cost is
replicated masters (a stacked-storage optimization can come later without
changing this interface).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .graph.node import Op, TraceContext
from .graph.autodiff import find_topo_sort
from .graph.ops_misc import PlaceholderOp
from .optimizer import OptimizerOp
from .parallel.partition import partition
from .parallel.pipeline import spmd_pipeline, spmd_pipeline_1f1b


def _tree_add(a, b):
    return jax.tree_util.tree_map(jnp.add, a, b)


class PipelineSubExecutor:
    """Training subgraph driven through a pipeline schedule."""

    def __init__(self, name, eval_nodes, executor):
        self.name = name
        self.eval_nodes = eval_nodes
        self.executor = executor
        cfg = executor.config
        self.mode = cfg.pipeline

        if cfg.comm_mode in ("PS", "Hybrid"):
            raise NotImplementedError(
                "pipeline mode with comm_mode='PS'/'Hybrid' is not wired; "
                "'hetpipe' provides the PS-synced pipeline path")

        opts = [n for n in eval_nodes if isinstance(n, OptimizerOp)]
        if len(opts) != 1:
            raise NotImplementedError(
                f"Executor(pipeline=...) drives exactly one optimizer per "
                f"training subgraph (got {len(opts)} in '{name}')")
        self.opt_op = opts[0]
        losses = [n for n in eval_nodes if not isinstance(n, OptimizerOp)]
        if len(losses) != 1:
            raise NotImplementedError(
                "pipeline-mode eval nodes must be [loss, train_op]")
        self.loss_node = losses[0]
        self.optimizer_ops = [self.opt_op]
        self.training = True
        self.ps_var_names = frozenset()

        self.topo = find_topo_sort([self.loss_node])
        self.non_batch_feeds = frozenset(cfg.non_batch_feeds or ())
        # stateful layers (BN running stats): their updates must chain
        # microbatch-to-microbatch through the scan carry
        self.state_var_names = sorted({
            sv.name for n in self.topo
            for sv in getattr(n, "state_vars", [])})
        from .dataloader import DataloaderOp
        self.dataloader_ops = [n for n in self.topo
                               if isinstance(n, DataloaderOp)]
        self.feeds = [n for n in self.topo
                      if isinstance(n, PlaceholderOp) and not n.is_variable]

        mesh = executor.mesh
        if mesh is not None and "pp" in mesh.axis_names:
            self.num_stages = mesh.shape["pp"]
            if cfg.num_stages not in (None, self.num_stages):
                raise ValueError(
                    f"num_stages={cfg.num_stages} != mesh pp axis "
                    f"{mesh.shape['pp']}")
        else:
            self.num_stages = cfg.num_stages or 2
        self.num_microbatches = cfg.num_microbatches or self.num_stages

        self.plan = partition(self.loss_node, self.num_stages)
        # stateful ops (BN running stats) thread extra_outputs, which the
        # SPMD lowering drops — those graphs take the microbatch-scan path
        has_state = any(getattr(n, "state_vars", []) for n in self.topo)
        self.spmd = (mesh is not None and "pp" in mesh.axis_names
                     and self.plan.uniform and not has_state
                     and self.mode in ("gpipe", "1f1b"))

        # hetpipe: host-side PS delta sync every sync_every batches
        self._batches_seen = 0
        self._ps_snapshot = None
        if self.mode == "hetpipe":
            if cfg.ps_comm is None:
                from .ps.client import PSClient
                cfg.ps_comm = PSClient.get()
            self.sync_every = getattr(cfg, "sync_every", None) \
                or self.num_stages
        self._compiled = {}

    # ------------------------------------------------------------------ #
    # graph segment tracing
    # ------------------------------------------------------------------ #

    def _trace_nodes(self, nodes, params, feeds, tc, seed_vals=None):
        """Evaluate a topo slice; returns the vals map."""
        vals = dict(seed_vals or {})
        from .dataloader import DataloaderOp
        mp = self.executor.config.mixed_precision

        def cast(v):
            if mp is not None and hasattr(v, "dtype") \
                    and jnp.issubdtype(v.dtype, jnp.floating):
                return v.astype(mp)
            return v

        def bind(node):
            if isinstance(node, DataloaderOp):
                return cast(feeds[node.name])
            src = params if node.is_variable else feeds
            return cast(src[node.name])

        for node in nodes:
            if id(node) in vals:
                continue
            if isinstance(node, (PlaceholderOp, DataloaderOp)):
                vals[id(node)] = bind(node)
            else:
                ins = []
                for i in node.inputs:
                    if id(i) not in vals:
                        # a placeholder that topologically lives in another
                        # segment (e.g. embedding weights tied into the
                        # post-body LM head) — globally available, bind here
                        if isinstance(i, (PlaceholderOp, DataloaderOp)):
                            vals[id(i)] = bind(i)
                        else:
                            raise KeyError(
                                f"pipeline segment references value "
                                f"{i.name} produced outside the segment "
                                f"(input of {node.name}); the partitioner "
                                f"should have prevented this cut")
                    ins.append(vals[id(i)])
                vals[id(node)] = node.compute(ins, tc)
        return vals

    def _stable_rng_ids(self):
        from .executor import stable_rng_ids
        return stable_rng_ids(self)

    def _forward_loss(self, params, feeds, rng, step):
        """Full-graph forward for one microbatch -> (loss, extra_outputs)."""
        from .executor import _ParamView
        tc = TraceContext(params=_ParamView(params), rng=rng, training=True,
                          mesh=self.executor.mesh,
                          config=self.executor.config, step=step)
        tc.rng_ids = self._stable_rng_ids()
        tc.extra_outputs = {}
        vals = self._trace_nodes(self.topo, params, feeds, tc)
        loss = vals[id(self.loss_node)]
        extras = {k.name if isinstance(k, Op) else k: v
                  for k, v in tc.extra_outputs.items()}
        return loss.astype(jnp.float32), extras

    def _apply_template_block(self, param_vals, x, tc):
        """Apply body block 0's structure with another block's params —
        positional binding is sound because the partitioner only admits
        blocks with identical signatures (op types+attrs, param shapes)."""
        tmpl = self.plan.body_blocks[0]
        vals = {id(self.plan.body_entry): x}
        for ph, v in zip(tmpl.params, param_vals):
            vals[id(ph)] = v
        for node in tmpl.nodes:
            if isinstance(node, PlaceholderOp):
                continue
            vals[id(node)] = node.compute(
                [vals[id(i)] for i in node.inputs], tc)
        return vals[id(tmpl.boundary_out)]

    # ------------------------------------------------------------------ #
    # optimizer
    # ------------------------------------------------------------------ #

    def _apply_opt(self, params, grads, opt_state, step):
        opt = self.opt_op.optimizer
        lr = opt.lr_value(step)
        new_params = dict(params)
        new_state = dict(opt_state)
        for var in self.opt_op.var_list:
            p = params[var.name]
            g = grads[var.name]
            new_p, ns = opt.update_one(p, g.astype(p.dtype),
                                       opt_state.get(var.name), lr, step)
            new_params[var.name] = new_p
            new_state[var.name] = ns
        return new_params, new_state

    # ------------------------------------------------------------------ #
    # step compilation
    # ------------------------------------------------------------------ #

    def _split_microbatches(self, feeds):
        """Batched feeds -> [M, mb, ...]; feeds named in
        config.non_batch_feeds (per-step constants like attention masks)
        are NOT split — each microbatch sees them whole."""
        M = self.num_microbatches
        skip = self.non_batch_feeds
        split, whole = {}, {}
        for k, v in feeds.items():
            if k in skip:
                whole[k] = v
            elif v.ndim == 0 or v.shape[0] % M:
                raise ValueError(
                    f"feed '{k}' batch dim {v.shape} not divisible by "
                    f"num_microbatches={M}; if it is a per-step constant "
                    f"rather than a batch, list it in "
                    f"HetuConfig(non_batch_feeds=...)")
            else:
                split[k] = v.reshape(M, v.shape[0] // M, *v.shape[1:])
        return split, whole

    def _make_step_fn(self):
        ex = self.executor
        M = self.num_microbatches
        train_names = [v.name for v in self.opt_op.var_list]
        opt_name = self.opt_op.name

        def split_params(params):
            tp = {k: params[k] for k in train_names}
            frozen = {k: v for k, v in params.items()
                      if k not in train_names}
            return tp, frozen

        if self.spmd:
            loss_of = self._spmd_loss_fn()
        else:
            loss_of = None

        def step_fn(params, opt_states, step, rng, feeds):
            mb, whole = self._split_microbatches(feeds)
            rngs = jax.random.split(rng, M)
            tp, frozen = split_params(params)
            ostate = opt_states[opt_name]

            state0 = {k: params[k] for k in self.state_var_names}

            def advance_state(st, extras):
                # BN updates chain sequentially microbatch-to-microbatch
                # (the reference's per-microbatch compute does the same)
                return {k: extras[k].astype(st[k].dtype)
                        if k in extras else st[k] for k in st}

            if self.mode in ("gpipe", "1f1b"):
                if loss_of is not None:
                    def total_loss(tp_):
                        return loss_of({**frozen, **tp_}, mb, whole,
                                       rngs, step)
                    loss, grads = jax.value_and_grad(total_loss)(tp)
                    state_fin = state0
                else:
                    def body(carry, xs):
                        acc, st = carry
                        fmb, r = xs

                        def mb_loss(tp_):
                            return self._forward_loss(
                                {**frozen, **st, **tp_},
                                {**fmb, **whole}, r, step)
                        (l, ex_), g = jax.value_and_grad(
                            mb_loss, has_aux=True)(tp)
                        return (_tree_add(acc, g),
                                advance_state(st, ex_)), l
                    zeros = jax.tree_util.tree_map(
                        lambda x: jnp.zeros(x.shape, jnp.float32), tp)
                    (grads, state_fin), losses = jax.lax.scan(
                        body, (zeros, state0), (mb, rngs))
                    grads = jax.tree_util.tree_map(lambda g: g / M, grads)
                    loss = losses.mean()
                new_tp, new_ostate = self._apply_opt(tp, grads, ostate, step)
                new_params = {**frozen, **state_fin, **new_tp}
            else:   # pipedream / hetpipe: per-microbatch updates
                def body(carry, xs):
                    tp_c, ostate_c, st = carry
                    fmb, r = xs

                    def mb_loss(tp_):
                        return self._forward_loss(
                            {**frozen, **st, **tp_},
                            {**fmb, **whole}, r, step)
                    (l, ex_), g = jax.value_and_grad(
                        mb_loss, has_aux=True)(tp_c)
                    tp_n, ostate_n = self._apply_opt(tp_c, g, ostate_c, step)
                    return (tp_n, ostate_n, advance_state(st, ex_)), l
                (new_tp, new_ostate, state_fin), losses = jax.lax.scan(
                    body, (tp, ostate, state0), (mb, rngs))
                loss = losses.mean()
                new_params = {**frozen, **state_fin, **new_tp}
            new_opt = dict(opt_states)
            new_opt[opt_name] = new_ostate
            return new_params, new_opt, step + 1, loss

        return step_fn

    def _spmd_loss_fn(self):
        """Loss over all microbatches via the SPMD scan pipeline."""
        ex = self.executor
        mesh = ex.mesh
        plan = self.plan
        S = self.num_stages
        R = plan.num_body_blocks()
        rps = R // S
        n_pos = len(plan.body_blocks[0].params)

        def loss_of(params, mb, whole, rngs, step):
            cfg = ex.config

            def pre_one(fmb, r):
                tc = TraceContext(params={}, rng=r, training=True,
                                  mesh=mesh, config=cfg, step=step)
                tc.rng_ids = self._stable_rng_ids()
                vals = self._trace_nodes(plan.pre_nodes, params,
                                         {**fmb, **whole}, tc)
                return vals[id(plan.body_entry)]

            xs = jax.vmap(pre_one)(mb, rngs)     # [M, mb, ...]

            # stack body params [R, ...] -> [S, R/S, ...], 'pp'-sharded;
            # mixed precision casts at graph entry (masters stay fp32)
            mp = cfg.mixed_precision

            def entry_cast(v):
                if mp is not None and jnp.issubdtype(v.dtype,
                                                    jnp.floating):
                    return v.astype(mp)
                return v

            stacked = []
            for pos in range(n_pos):
                tmpl = plan.body_params[0][pos]
                # the stacked constraint can express only ONE spec per
                # position: require per-layer specs to be uniform, or the
                # template's would silently override the others.
                # (normalize: P('tp') == P('tp', None))
                def _norm(spec):
                    t = tuple(spec) if spec is not None else ()
                    while t and t[-1] is None:
                        t = t[:-1]
                    return t
                specs = {_norm(getattr(plan.body_params[r][pos],
                                       "sharding_spec", None))
                         for r in range(R)}
                if len(specs) > 1:
                    raise ValueError(
                        f"pipeline body param position {pos} "
                        f"({tmpl.name}-like) has non-uniform sharding "
                        f"specs across layers ({sorted(map(str, specs))}); "
                        f"give "
                        f"every body layer the same spec")
                leaves = [entry_cast(params[plan.body_params[r][pos].name])
                          for r in range(R)]
                st = jnp.stack(leaves).reshape(S, rps, *leaves[0].shape)
                # shard_map is manual over 'pp' ONLY; the per-layer tp/dp
                # specs carry into the stacked dims and GSPMD partitions
                # the in-stage matmuls (true pp x tp composition)
                var_spec = getattr(tmpl, "sharding_spec", None)
                tail = tuple(var_spec) if var_spec is not None \
                    else (None,) * (st.ndim - 2)
                st = jax.lax.with_sharding_constraint(
                    st, NamedSharding(mesh, P("pp", None, *tail)))
                stacked.append(st)
            stacked = tuple(stacked)

            base_rng = jax.random.fold_in(rngs[0], 7)

            def stage_fn(plist, x, m):
                # plist leaves [rps, ...].  RNG decorrelates over stage,
                # microbatch index, and block index — without this every
                # block/microbatch would reuse the template nodes'
                # dropout masks.  Keyed by MICROBATCH (not tick) so the
                # 1F1B backward's recompute reproduces the forward's
                # randomness exactly.
                r = jax.random.fold_in(base_rng, jax.lax.axis_index("pp"))
                r = jax.random.fold_in(r, m)

                def blk(h, pr_bi):
                    pr, bi = pr_bi
                    tc = TraceContext(params={},
                                      rng=jax.random.fold_in(r, bi),
                                      training=True, mesh=mesh, config=cfg,
                                      step=step, axis_env=mesh.axis_names)
                    tc.rng_ids = self._stable_rng_ids()
                    return self._apply_template_block(list(pr), h, tc), None
                h, _ = jax.lax.scan(blk, x, (plist, jnp.arange(rps)))
                return h

            if self.mode == "1f1b":
                # real staggered 1F1B: O(S) activation high-water via the
                # custom-VJP schedule (vs gpipe's O(M+S) saved carries)
                ys = spmd_pipeline_1f1b(stage_fn, stacked, xs, mesh=mesh,
                                        axis="pp",
                                        mb_spec=P(*([None] * (xs.ndim))),
                                        manual_axes={"pp"})
            else:
                ys = spmd_pipeline(stage_fn, stacked, xs, mesh=mesh,
                                   axis="pp",
                                   mb_spec=P(*([None] * (xs.ndim))),
                                   stage_takes_index=True,
                                   manual_axes={"pp"})

            def post_one(y, fmb, r):
                tc = TraceContext(params={}, rng=jax.random.fold_in(r, 13),
                                  training=True, mesh=mesh, config=cfg,
                                  step=step)
                tc.rng_ids = self._stable_rng_ids()
                seed = {id(plan.body_blocks[-1].boundary_out): y}
                vals = self._trace_nodes(plan.post_nodes, params,
                                         {**fmb, **whole}, tc,
                                         seed_vals=seed)
                return vals[id(self.loss_node)].astype(jnp.float32)

            losses = jax.vmap(post_one)(ys, mb, rngs)
            return losses.mean()

        return loss_of

    def _compile(self, feed_sig):
        ex = self.executor
        inner = self._make_step_fn()

        def step_fn(params, opt_states, step, rng, feeds):
            # rng splits INSIDE the jitted program (an eager per-step
            # split is a full host<->device round trip on a tunneled TPU)
            new_rng, sub = jax.random.split(rng)
            p, o, s, loss = inner(params, opt_states, step, sub, feeds)
            return p, o, s, new_rng, loss

        jit_kwargs = dict(donate_argnums=(0, 1))
        if ex.mesh is not None:
            from .executor import _opt_sharding_like
            param_sh = {k: ex.param_sharding(k) for k in ex.var_values}
            feed_sh = {name: ex.feed_sharding(name, shape)
                       for name, shape, _ in feed_sig}
            rep = NamedSharding(ex.mesh, P())
            opt_sh = _opt_sharding_like(ex, ex.opt_states)
            jit_kwargs["in_shardings"] = (
                param_sh, opt_sh, rep, rep, feed_sh)
            jit_kwargs["out_shardings"] = (param_sh, opt_sh, rep, rep, None)
        return jax.jit(step_fn, **jit_kwargs)

    # ------------------------------------------------------------------ #

    @property
    def batch_num(self):
        nums = [dl.get_batch_num(self.name) for dl in self.dataloader_ops]
        nums = [n for n in nums if n is not None]
        return min(nums) if nums else None

    def run(self, feed_dict, convert_to_numpy_ret_vals=False):
        from .executor import gather_feeds
        ex = self.executor
        feeds = gather_feeds(self, feed_dict)
        feed_sig = tuple(sorted(
            (k, tuple(v.shape), str(v.dtype)) for k, v in feeds.items()))
        if feed_sig not in self._compiled:
            # same pre-trace gate as SubExecutor.run: fail with the node
            # named before the pipeline trace (HETU_VALIDATE=1)
            from .analysis import validate_subgraph_feeds
            validate_subgraph_feeds(ex, self, feeds)
            self._compiled[feed_sig] = self._compile(feed_sig)
        fn = self._compiled[feed_sig]
        if ex.mesh is not None:
            feeds = {k: ex.device_put_feed(k, v) for k, v in feeds.items()}
        ex.var_values, ex.opt_states, ex.step, ex.rng, loss = fn(
            ex.var_values, ex.opt_states, ex.step, ex.rng, feeds)
        self._batches_seen += 1
        if self.mode == "hetpipe" and \
                self._batches_seen % self.sync_every == 0:
            self._hetpipe_sync()
        results = []
        for n in self.eval_nodes:
            if isinstance(n, OptimizerOp):
                results.append(None)
            elif convert_to_numpy_ret_vals:
                results.append(np.asarray(loss))
            else:
                results.append(loss)
        return results

    # ------------------------------------------------------------------ #
    # HetPipe PS delta-sync (reference pipedream_subexecutor.py:317-328:
    # local updates between syncs, push accumulated delta to the PS every
    # pp_nrank batches; the server accumulates pushes into the param)
    # ------------------------------------------------------------------ #

    def _hetpipe_sync(self):
        from .parallel.pipeline import ps_delta_sync
        ex = self.executor
        cur = {v.name: np.array(ex.var_values[v.name], copy=True)
               for v in self.opt_op.var_list}
        merged, self._ps_snapshot = ps_delta_sync(
            ex.config.ps_comm, cur, self._ps_snapshot)
        for k, v in merged.items():
            ex.var_values[k] = self._replace(k, v)

    def _replace(self, name, value):
        arr = jnp.asarray(value)
        if self.executor.mesh is not None:
            arr = jax.device_put(arr, self.executor.param_sharding(name))
        return arr
