"""Process-wide metrics registry: thread-safe counters/gauges/histograms.

The reference ships per-subsystem counters (HetuProfiler per-op tables,
cstable perf counters, NCCLProfiler) that each invent their own storage
and read path; here every layer records into ONE registry that
``telemetry.snapshot()`` serializes for tests, the suite's trace stage,
and the bench artifacts.  Metrics are named with dotted paths
(``ps.rpc.retries``, ``cache.hits``) plus an optional ``[tag]`` suffix
for low-cardinality breakdowns (``ps.rpc.calls[host:port]``).

Cost model: one ``locks.TracedLock`` per metric (plain pass-through
unless the lockdep sanitizer is on), plain python arithmetic
under it — ~1 µs per record, invisible next to a training step.  The
hot-path guard lives one level up (``telemetry.enabled()``): when
``HETU_TELEMETRY=0`` the instrumented call sites skip the registry
entirely, which is what keeps the disabled overhead near zero.

Histograms keep running count/sum/min/max plus a bounded reservoir of
the most recent samples (default 512) for percentiles — enough for the
p50/p99 the serving and PS layers report without unbounded memory on a
million-step run.
"""

from __future__ import annotations

import collections

from .. import locks

_RESERVOIR = 512


def percentile(xs, q):
    """THE repo percentile: linear interpolation between closest ranks
    (numpy's default method), pure python, None on empty input.

    Before this helper the repo had two disagreeing implementations —
    nearest-rank here in ``Histogram`` and ``np.percentile`` in
    ``serving/metrics.py`` — whose p99s diverged visibly on the small
    reservoirs serving actually has.  Both now call this one; accepts
    any sequence (sorts a copy, so pre-sorted callers pay one no-op
    pass)."""
    xs = sorted(float(x) for x in xs)
    if not xs:
        return None
    if len(xs) == 1:
        return xs[0]
    pos = float(q) / 100.0 * (len(xs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(xs) - 1)
    return xs[lo] + (xs[hi] - xs[lo]) * (pos - lo)


class Counter:
    """Monotonic int counter."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name):
        self.name = name
        self.value = 0
        self._lock = locks.TracedLock("metrics.counter")

    def inc(self, n=1):
        with self._lock:
            self.value += n
        return self

    def get(self):
        return self.value


class Gauge:
    """Last-write-wins scalar (queue depth, ring fill, live slots)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name):
        self.name = name
        self.value = None
        self._lock = locks.TracedLock("metrics.gauge")

    def set(self, v):
        with self._lock:
            self.value = v
        return self

    def get(self):
        return self.value


class Histogram:
    """Running stats + bounded reservoir of recent samples."""

    __slots__ = ("name", "count", "total", "min", "max", "_recent",
                 "_lock")

    def __init__(self, name, reservoir=_RESERVOIR):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._recent = collections.deque(maxlen=reservoir)
        self._lock = locks.TracedLock("metrics.hist")

    def observe(self, v):
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self._recent.append(v)
        return self

    def summary(self):
        with self._lock:
            recent = sorted(self._recent)
            count, total = self.count, self.total
            mn, mx = self.min, self.max
        return {
            "count": count,
            "sum": round(total, 6),
            "min": mn,
            "max": mx,
            "mean": round(total / count, 6) if count else None,
            "p50": percentile(recent, 50),
            "p95": percentile(recent, 95),
            "p99": percentile(recent, 99),
        }


class MetricsRegistry:
    """Name -> metric, created on first touch (prometheus-client style:
    call sites never pre-register, a typo makes a new metric rather than
    a crash on the hot path)."""

    def __init__(self):
        self._lock = locks.TracedLock("metrics.registry")
        self._metrics = {}

    def _get(self, name, cls):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.setdefault(name, cls(name))
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, asked for {cls.__name__}")
        return m

    def counter(self, name) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self):
        with self._lock:
            items = list(self._metrics.items())
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in sorted(items):
            if isinstance(m, Counter):
                out["counters"][name] = m.get()
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.get()
            else:
                out["histograms"][name] = m.summary()
        return out

    def reset(self):
        with self._lock:
            self._metrics.clear()


# the process-wide registry every layer records into
REGISTRY = MetricsRegistry()
