"""Unified telemetry: run-wide spans, a metrics registry, ONE event
pipeline, and measurement health gates.

Every layer emits into this subsystem and every tool reads from it:

- :mod:`.events` — the single ``emit()`` every JSONL record flows
  through (streams: failure/serve/validate/telemetry; legacy
  ``HETU_FAILURE_LOG``-style sinks plus the merged
  ``$HETU_TELEMETRY_LOG``), ``span()`` context managers, and the
  event-shape contract.
- :mod:`.metrics` — thread-safe counters/gauges/histograms behind
  ``snapshot()``.
- :mod:`.health` — banking gates: sibling-consistency, physics
  ceiling, live-vs-banked provenance stamps (bench.py wires them).
- :mod:`.slo` — declarative serving SLOs (TTFT / per-stream tok/s)
  with sliding-window burn rates behind the engine's ``health()``.
- :mod:`.flight` — the chaos flight recorder: an always-on bounded
  ring of recent records dumped to ``$HETU_FLIGHT_LOG`` on faults.
- :mod:`.trace` — merge/tail the streams, export Perfetto traces
  (``bin/hetu_trace.py``); request-lifecycle tracks + counter tracks.
- :mod:`.top` — the live terminal dashboard (``bin/hetu_top.py``).

``HETU_TELEMETRY=0`` turns spans and metric recording into no-ops.
"""

from . import flight, health, metrics, slo, top, trace  # noqa: F401
from .events import (  # noqa: F401
    REQUIRED_FIELDS, STREAMS, TelemetrySink, counter, emit, enabled,
    gauge, get_sink, histogram, inc, make_record, observe, reset,
    set_gauge, snapshot, span, validate_record,
)
from .metrics import REGISTRY, percentile  # noqa: F401

__all__ = [
    "REQUIRED_FIELDS", "STREAMS", "REGISTRY", "TelemetrySink",
    "counter", "emit", "enabled", "flight", "gauge", "get_sink",
    "health", "histogram", "inc", "make_record", "metrics", "observe",
    "percentile", "reset", "set_gauge", "slo", "snapshot", "span",
    "top", "trace", "validate_record",
]
