"""Unified telemetry: run-wide spans, a metrics registry, ONE event
pipeline, and measurement health gates.

Every layer emits into this subsystem and every tool reads from it:

- :mod:`.events` — the single ``emit()`` every JSONL record flows
  through (streams: failure/serve/validate/telemetry; legacy
  ``HETU_FAILURE_LOG``-style sinks plus the merged
  ``$HETU_TELEMETRY_LOG``), ``span()`` context managers, and the
  event-shape contract.
- :mod:`.metrics` — thread-safe counters/gauges/histograms behind
  ``snapshot()``.
- :mod:`.health` — banking gates: sibling-consistency, physics
  ceiling, live-vs-banked provenance stamps (bench.py wires them).
- :mod:`.trace` — merge/tail the streams, export Perfetto traces
  (``bin/hetu_trace.py``).

``HETU_TELEMETRY=0`` turns spans and metric recording into no-ops.
"""

from . import health, metrics, trace  # noqa: F401  (submodule surface)
from .events import (  # noqa: F401
    REQUIRED_FIELDS, STREAMS, TelemetrySink, counter, emit, enabled,
    gauge, get_sink, histogram, inc, make_record, observe, reset,
    set_gauge, snapshot, span, validate_record,
)
from .metrics import REGISTRY  # noqa: F401

__all__ = [
    "REQUIRED_FIELDS", "STREAMS", "REGISTRY", "TelemetrySink",
    "counter", "emit", "enabled", "gauge", "get_sink", "health",
    "histogram", "inc", "make_record", "metrics", "observe", "reset",
    "set_gauge", "snapshot", "span", "trace", "validate_record",
]
