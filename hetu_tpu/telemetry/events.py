"""The one event pipeline: every JSONL record in the repo flows here.

Before this subsystem four emitters — launcher ``_event``,
``serving/metrics.py``, ``analysis/report.py``, and ad-hoc bench
records — each opened their own file and happened to agree on the
``{"t": <epoch, 3 decimals>, "event": <kind>, **fields}`` shape.  Now
there is exactly one ``emit()`` (lint rule ``event-emit`` keeps it
that way, the same way ``env-registry`` keeps the env registry
authoritative), and the shape is a CONTRACT (:data:`REQUIRED_FIELDS`,
asserted by one shared schema test) instead of four conventions.

Streams and sinks: each record belongs to a *stream* (``failure`` /
``serve`` / ``validate`` / ``telemetry``).  A record is appended to its
stream's legacy env-var path (``HETU_FAILURE_LOG`` etc. — existing
tail/jq pipelines keep working) AND to ``$HETU_TELEMETRY_LOG``, the
merged run-wide file ``bin/hetu_trace.py`` tails and exports to a
Perfetto trace.  Writes are best-effort: an unwritable log must never
take down a run that computed fine.

Spans: ``with span("exec.phase_a", subgraph="train"):`` times a region,
feeds a histogram (``span.exec.phase_a``) in the metrics registry, and
— when a telemetry log is configured — emits a ``span`` record carrying
the START time plus ``ms``/``pid``/``tid``, which the trace exporter
turns into a Chrome ``"X"`` duration event.  With ``HETU_TELEMETRY=0``
``span()`` returns a shared no-op and the instrumented call sites skip
the registry: near-zero overhead is the contract (asserted as a <2%
smoke-tier bound).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

from .. import envvars, locks
from . import flight
from .metrics import REGISTRY

# stream -> legacy per-stream JSONL env var (None = merged log only)
STREAMS = {
    "failure": "HETU_FAILURE_LOG",
    "serve": "HETU_SERVE_LOG",
    "validate": "HETU_VALIDATE_LOG",
    "telemetry": None,
}

# per-kind required fields on top of the base {"t", "event"} pair —
# THE event contract, shared by every stream and asserted by one
# schema test (tests/test_telemetry.py) instead of four conventions.
REQUIRED_FIELDS = {
    # launcher / supervisor (failure stream)
    "worker_exit": ("rank", "rc"),
    "worker_restart": ("rank",),
    "worker_restart_scheduled": ("rank",),
    "worker_failed": ("rank", "rc"),
    "ps_restart": ("index",),
    "ps_restart_failed": ("index",),
    "ps_server_exit": ("index", "rc"),
    "ps_server_dead": ("index", "rc"),
    "ps_resynced": ("index",),
    "ps_resync_failed": ("index",),
    "ps_wedged_kill": ("index",),
    # sharded PS client (failure stream)
    "ps_shard_failover": ("shard",),
    "ps_shard_resynced": ("shard",),
    "ps_replica_write_failed": ("shard",),
    "ps_replica_rebuild_failed": ("shard",),
    # serving engine (serve stream)
    "serve_submit": ("request", "queue_depth"),
    "serve_queue_reject": ("request", "queue_depth"),
    "serve_admit": ("request", "slot", "ttft_s"),
    "serve_prefill": ("n", "bucket", "prefill_ms"),
    "serve_step": ("live", "queue_depth", "decode_ms"),
    "serve_finish": ("request", "reason", "n_generated"),
    # embedding serving engine (serve stream; per-wave cache gather)
    "serve_gather": ("n", "rows", "gather_ms"),
    # static checks (validate stream)
    "graph_verified": ("subgraph", "phase"),
    "graph_verify_error": ("kind", "error"),
    "serving_verified": ("model",),
    # concurrency sanitizer (hetu_tpu/locks.py; validate stream):
    # kind = order (lock-order inversion) / held_across (blocking work
    # under a lock) / long_hold (> HETU_LOCKDEP_HOLD_MS); any one in a
    # merged stream turns hetu_trace --check red
    "lockdep_violation": ("kind", "lock"),
    # request lifecycle (serve stream; ISSUE 7)
    "req_span": ("request", "phase", "ms"),
    "req_retire": ("request", "ttft_ms"),
    # SLO monitor (telemetry/slo.py)
    "slo_violation": ("slo", "value", "target"),
    "slo_health": ("state",),
    # serving fleet: supervised replicas (failure stream; ISSUE 8)
    "replica_start": ("replica",),
    "replica_exit": ("replica", "rc"),
    "replica_restart_scheduled": ("replica", "attempt"),
    "replica_restart": ("replica", "attempt"),
    "replica_failed": ("replica", "rc"),
    "replica_wedged_kill": ("replica",),
    "replica_drain": ("replica", "requeued"),
    # serving fleet: router request path (serve stream; ISSUE 8)
    "router_route": ("request", "replica"),
    "router_hop": ("request", "to_replica"),
    "router_shed": ("request", "slo_class"),
    "router_breaker": ("replica", "state"),
    "router_deadline": ("request",),
    "router_retry_exhausted": ("request",),
    # serving fleet: KV directory + prefill/decode handoff (ISSUE 12;
    # out/in pair per moved span — hetu_trace --check enforces the
    # pairing; drop = a failed import that degraded to cold admission)
    "kv_handoff_out": ("request", "replica", "to_replica"),
    "kv_handoff_in": ("request", "replica", "from_replica"),
    "kv_handoff_drop": ("request", "replica"),
    "directory_killed": ("reason",),
    # live weight sync (serving/weight_sync.py; ISSUE 15): the rolling
    # quiesce->drain->swap->probe->readmit cycle per replica (serve
    # stream) plus rollout lifecycle; failures (stale push, mid-swap
    # death) ride the failure stream
    "weight_swap": ("version",),
    "swap_quiesce": ("replica", "version"),
    "swap_drained": ("replica", "version"),
    "swap_probe": ("replica", "version", "ok"),
    "swap_readmit": ("replica", "version"),
    "swap_rejected_stale": ("version", "committed"),
    "rollout_start": ("version", "replicas"),
    "rollout_advance": ("version", "done", "replicas"),
    "rollout_done": ("version", "swapped"),
    "rollout_failed": ("version", "reason"),
    "rollout_rollback": ("version", "replicas"),
    "ps_version_skew": ("before", "after"),
    # elastic fleet (serving/autoscaler.py + router add/retire; ISSUE
    # 16): scale actions and per-replica lifecycle transitions (failure
    # stream).  hetu_trace --check pairs every scale_up with a
    # replica_ready and every scale_down with a replica_retired whose
    # drained rids each retire exactly once on a peer.
    "scale_up": ("replica", "reason"),
    "scale_down": ("replica", "reason"),
    "replica_warming": ("replica",),
    "replica_ready": ("replica",),
    "replica_draining": ("replica",),
    "replica_retired": ("replica", "requeued"),
    # tiered KV (serving/kv_tiers.py; ISSUE 17): every kv_spill opens a
    # tier residency for one prefix; exactly one terminal kv_fetch
    # (re-admitted into a pool) or kv_tier_drop (ring overflow past a
    # dead PS, corruption, shutdown) closes it.  hetu_trace --check
    # tier-balance enforces the pairing.  kvtier_ps_killed (failure
    # stream) marks the one-shot PS-rung death that degrades the
    # ladder to drop-on-evict.
    "kv_spill": ("prefix", "tier", "length"),
    "kv_fetch": ("prefix", "tier", "length"),
    "kv_tier_drop": ("prefix", "tier"),
    "kvtier_ps_killed": ("reason",),
    # flight recorder dump header (telemetry/flight.py)
    "flight_dump": ("reason",),
    # telemetry core + bench
    "span": ("name", "ms"),
    "gauge": ("name", "value"),
    "bench_row": ("config",),
    "bench_probe_health": ("ok",),
}


def validate_record(rec):
    """Contract check for one record; returns a list of problems
    (empty = conforming).  Unknown kinds only need the base shape —
    the registry constrains kinds we HAVE agreed on, it does not ban
    new ones."""
    problems = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not a dict"]
    if not isinstance(rec.get("t"), (int, float)):
        problems.append("missing/non-numeric 't'")
    kind = rec.get("event")
    if not isinstance(kind, str):
        problems.append("missing/non-string 'event'")
        return problems
    for field in REQUIRED_FIELDS.get(kind, ()):
        if field not in rec:
            problems.append(f"{kind!r} record missing field {field!r}")
    return problems


def enabled() -> bool:
    """Master switch for spans + metric instrumentation
    (``HETU_TELEMETRY``, default on).  Explicit event streams
    (failure/serve/validate) flow regardless — they predate the switch
    and are low-frequency by construction."""
    return envvars.get_bool("HETU_TELEMETRY")


def make_record(event, t=None, **fields):
    """One contract-shaped record: {"t": ..., "event": event, **fields}."""
    return {"t": round(time.time() if t is None else t, 3),
            "event": event, **fields}


class TelemetrySink:
    """Process-wide sink: bounded in-memory ring + JSONL fan-out."""

    def __init__(self):
        self._lock = locks.TracedLock("telemetry.sink")
        self._buffer = collections.deque(
            maxlen=max(1, envvars.get_int("HETU_TELEMETRY_BUFFER")))
        self.emitted = 0
        self.dropped_writes = 0

    # ------------------------------------------------------------- #

    def _targets(self, stream, path):
        """The files one record lands in: explicit override or the
        stream's legacy env path, plus the merged telemetry log."""
        out = []
        if path:
            out.append(os.path.expanduser(str(path)))
        else:
            env = STREAMS.get(stream)
            if env:
                p = envvars.get_path(env)
                if p:
                    out.append(p)
        merged = envvars.get_path("HETU_TELEMETRY_LOG")
        if merged and merged not in out:
            out.append(merged)
        return out

    def _write(self, records, targets):
        for target in targets:
            try:
                with open(target, "a") as f:
                    for rec in records:
                        f.write(json.dumps(rec, default=str) + "\n")
            except OSError:
                self.dropped_writes += 1

    def emit(self, event, stream="telemetry", path=None, t=None,
             **fields):
        """Append one record to the ring and its sinks; returns it."""
        rec = make_record(event, t=t, **fields)
        with self._lock:
            self._buffer.append(rec)
            self.emitted += 1
        flight.RECORDER.record(rec)   # the always-on black box
        self._write([rec], self._targets(stream, path))
        return rec

    def emit_prebuilt(self, records, stream="telemetry", path=None):
        """Route already-shaped records (``make_record`` output) —
        the analysis layer batches its reports."""
        records = list(records)
        if not records:
            return records
        with self._lock:
            self._buffer.extend(records)
            self.emitted += len(records)
        flight.RECORDER.extend(records)
        self._write(records, self._targets(stream, path))
        return records

    def recent(self, n=None, kind=None):
        with self._lock:
            events = list(self._buffer)
        if kind is not None:
            events = [e for e in events if e.get("event") == kind]
        return events[-n:] if n else events

    def reset(self):
        with self._lock:
            self._buffer = collections.deque(
                maxlen=max(1, envvars.get_int("HETU_TELEMETRY_BUFFER")))
            self.emitted = 0
            self.dropped_writes = 0


_SINK = TelemetrySink()


def get_sink() -> TelemetrySink:
    return _SINK


def emit(event, _stream="telemetry", _path=None, _t=None, **fields):
    """Module-level emit — THE one event pipeline."""
    return _SINK.emit(event, stream=_stream, path=_path, t=_t, **fields)


# ------------------------------------------------------------------- #
# spans
# ------------------------------------------------------------------- #

class _Span:
    __slots__ = ("name", "fields", "_t0", "_epoch")

    def __init__(self, name, fields):
        self.name = name
        self.fields = fields

    def __enter__(self):
        self._epoch = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        ms = (time.perf_counter() - self._t0) * 1e3
        REGISTRY.histogram("span." + self.name).observe(ms)
        # JSONL only when a merged log is configured: per-step span
        # records are trace-export payload, not an always-on cost
        if envvars.is_set("HETU_TELEMETRY_LOG"):
            _SINK.emit("span", stream="telemetry", t=self._epoch,
                       name=self.name, ms=round(ms, 3),
                       pid=os.getpid(),
                       tid=threading.current_thread().name,
                       **self.fields)
        return False


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP_SPAN = _NoopSpan()


def span(name, **fields):
    """Timed region context manager; no-op when telemetry is off."""
    if not enabled():
        return _NOOP_SPAN
    return _Span(name, fields)


# ------------------------------------------------------------------- #
# guarded metric helpers (the instrumentation call-site surface)
# ------------------------------------------------------------------- #

def inc(name, n=1):
    if enabled():
        REGISTRY.counter(name).inc(n)


def observe(name, v):
    if enabled():
        REGISTRY.histogram(name).observe(v)


def set_gauge(name, v):
    if enabled():
        REGISTRY.gauge(name).set(v)
        # gauges are the only metric kind with a time dimension worth
        # exporting (occupancy, queue depth, blocks_free over the run),
        # so a configured merged log also gets a JSONL sample per set —
        # the trace exporter renders them as Chrome "C" counter tracks
        if envvars.is_set("HETU_TELEMETRY_LOG"):
            _SINK.emit("gauge", stream="telemetry", name=name, value=v)


def counter(name):
    return REGISTRY.counter(name)


def gauge(name):
    return REGISTRY.gauge(name)


def histogram(name):
    return REGISTRY.histogram(name)


def snapshot():
    """JSON-able view tests and tools assert against: every metric plus
    the event-ring status."""
    out = REGISTRY.snapshot()
    out["enabled"] = enabled()
    out["events_emitted"] = _SINK.emitted
    out["events_buffered"] = len(_SINK.recent())
    out["dropped_writes"] = _SINK.dropped_writes
    return out


def reset():
    """Clear metrics + the event ring + the flight ring (test
    isolation)."""
    REGISTRY.reset()
    _SINK.reset()
    flight.RECORDER.reset()
