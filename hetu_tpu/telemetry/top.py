"""hetu_top: a live terminal dashboard over the merged telemetry stream.

``bin/hetu_top.py`` is the CLI.  It tails the same contract-shaped JSONL
files ``hetu_trace`` merges (default: every ``HETU_*_LOG`` configured in
the environment) and renders the serving engine's vitals in place:

- engine: batch occupancy, live slots, queue depth, fused-step count;
- paged KV pool: blocks free / shared, registered prefixes (the
  ``gauge`` records kv_manager emits);
- latency: TTFT and TPOT percentiles over the visible window (TPOT
  from per-step emitted-token counts — ``serve_step.new_tokens`` — so
  speculative waves emitting several tokens per step are weighted
  correctly; old logs without the field fall back to per-request
  retire records);
- speculation: drafted vs accepted token counts, acceptance rate, and
  mean per-wave draft length (the ``spec_*`` fields speculative
  engines stamp on every ``serve_step``);
- SLO: current health state (ok/degraded/breach), burn rate, violation
  count — the same signal ``ServingEngine.health()`` returns;
- incidents: flight-recorder dumps and queue rejections.

Everything is derived from the log records alone (no live process
hookup): point ``hetu_top`` at a dead run's log and it renders the
final state — the "what was it doing" companion to the flight
recorder's "what happened".  ``--once`` renders a single frame and
exits (scripts, tests); otherwise the screen refreshes every
``--interval`` seconds until Ctrl-C.

``--fleet`` switches to the ServingRouter view: one row per replica
(state/health, prefill/decode/mixed role, occupancy, queue depth,
breaker state, routed/requeue/reject/death counts, directory hit
rate) assembled from the ``replica``-tagged serve events plus the
router's ``router_route``/``router_hop``/``router_breaker`` and the
supervisor's ``replica_*`` failure records, with fleet totals (shed
by class, requeues, pressure, prefix-directory hits/misses/steals,
KV handoffs) underneath.  The role column reads the ``role`` tag the
replica's engine stamps on its serve events; the directory columns
read the ``directory=hit/steal/miss/stale`` verdicts the router
stamps on its ``router_route`` records (ISSUE 12).

Live weight sync (ISSUE 15): the single-engine view shows the current
``weight_version`` and the last-swap timestamp (from ``weight_swap``
records); ``--fleet`` grows a per-replica ``ver`` column (the
``weight_version`` tag riding each replica's serve events) and a
rollout-progress footer (``rollout   rolling 1/2 → v7``) assembled
from the coordinator's ``rollout_*`` records.

MoE serving (ISSUE 20): MoE engines stamp ``moe_*`` fields on every
``serve_step`` — the single-engine view grows an ``experts`` panel
(routed/dropped assignments, max/mean load imbalance, drop rate from
the ``serve.expert_load``/``serve.expert_drops`` counters' step-level
twins) and ``--fleet`` grows per-replica ``imb``/``drop%`` columns;
dense replicas render "-".

Elastic fleet (ISSUE 16): ``--fleet`` grows a per-replica ``life``
column (warming/serving/draining/retired, from the router's
``replica_warming``/``replica_ready``/``replica_draining``/
``replica_retired`` lifecycle records) and an autoscale footer —
last scale action + reason, target vs. actual replicas, and the
worst-burn / pressure signal that drove it (``scale_up``/
``scale_down`` events plus the ``fleet.burn``/``fleet.replicas``
gauges the autoscaler emits each tick).
"""

from __future__ import annotations

import argparse
import time

from .metrics import percentile
from .trace import configured_logs, read_events


def _pct_ms(xs, q):
    v = percentile(xs, q) if xs else None
    return None if v is None else v


def summarize(events, window=512):
    """Dashboard stats from the newest ``window`` records of a merged,
    time-sorted stream (``read_events`` output)."""
    events = events[-window:] if window else events
    gauges = {}
    ttft_ms, tpot_ms = [], []
    counts = {"submitted": 0, "finished": 0, "rejected": 0}
    steps = []
    slo = {"state": None, "burn_rate": None, "violations": 0}
    flight_dumps = 0
    workload = None
    weight_version = None
    last_swap_t = None
    for e in events:
        kind = e.get("event")
        # live weight sync: the weight_version tag rides every serve
        # event once the engine is version-stamped; a weight_swap
        # record marks the last rolling-swap instant
        if e.get("weight_version") is not None:
            weight_version = e.get("weight_version")
        if kind == "weight_swap":
            last_swap_t = e.get("t")
            if e.get("version") is not None:
                weight_version = e.get("version")
        # the workload tag embed engines stamp on every serve event;
        # untagged streams (GPT engines predate the tag) default "gpt"
        if kind and kind.startswith("serve_") and \
                e.get("workload") is not None:
            workload = e.get("workload")
        if kind == "gauge":
            gauges[e.get("name")] = e.get("value")
        elif kind == "serve_step":
            steps.append(e)
        elif kind == "serve_submit":
            counts["submitted"] += 1
        elif kind == "serve_finish":
            counts["finished"] += 1
        elif kind == "serve_queue_reject":
            counts["rejected"] += 1
        elif kind == "serve_admit":
            if isinstance(e.get("ttft_s"), (int, float)):
                ttft_ms.append(e["ttft_s"] * 1e3)
        elif kind == "req_retire":
            n = e.get("n_generated")
            d = e.get("decode_ms")
            if isinstance(n, int) and n > 1 and \
                    isinstance(d, (int, float)) and d > 0:
                tpot_ms.append(d / (n - 1))
        elif kind == "slo_health":
            slo["state"] = e.get("state")
            slo["burn_rate"] = e.get("burn_rate")
        elif kind == "slo_violation":
            slo["violations"] += 1
        elif kind == "flight_dump":
            flight_dumps += 1
    last = steps[-1] if steps else {}
    occupancy = gauges.get("serve.occupancy")
    if occupancy is None and isinstance(last.get("live"), int) and \
            isinstance(last.get("slots"), int) and last["slots"]:
        occupancy = round(last["live"] / last["slots"], 4)
    tok_s = None
    if len(steps) >= 2:
        span = steps[-1].get("t", 0) - steps[0].get("t", 0)
        if span > 0:
            tok_s = round(sum(s.get("new_tokens", s.get("live", 0))
                              for s in steps) / span, 1)
    # TPOT from real per-step token counts (a speculative wave emits
    # up to k+1 per slot); retire-record fallback for old logs
    step_tpot = []
    for s in steps:
        n, d = s.get("new_tokens"), s.get("decode_ms")
        if isinstance(n, int) and n > 0 and isinstance(d, (int, float)):
            step_tpot.extend([d / n] * n)
    if step_tpot:
        tpot_ms = step_tpot
    drafted = accepted = 0
    spec_ks = []
    # mixed-mode ragged dispatch ($HETU_SERVE_RAGGED): serve_step
    # events carry the wave's per-mode q-token split — how many query
    # rows were prompt prefill vs spec-verify vs plain decode
    mix_tot = {"q_prefill": 0, "q_verify": 0, "q_decode": 0}
    mix_steps = 0
    for s in steps:
        if isinstance(s.get("spec_proposed"), int):
            drafted += s["spec_proposed"]
            accepted += s.get("spec_accepted", 0)
            if isinstance(s.get("spec_k"), int):
                spec_ks.append(s["spec_k"])
        if isinstance(s.get("q_prefill"), int):
            mix_steps += 1
            for f in mix_tot:
                mix_tot[f] += s.get(f, 0) or 0
    mix = {**mix_tot, "steps": mix_steps} if mix_steps else None
    # MoE serving (ISSUE 20): serve_step events from MoE engines carry
    # the wave's routing outcome — expert-load imbalance (max/mean) is
    # THE MoE production failure mode, so it gets a panel
    moe_routed = moe_dropped = 0
    moe_imb = None
    moe_steps = 0
    for s in steps:
        if isinstance(s.get("moe_routed"), int):
            moe_steps += 1
            moe_routed += s["moe_routed"]
            moe_dropped += s.get("moe_dropped", 0) or 0
            if isinstance(s.get("moe_imb"), (int, float)):
                moe_imb = s["moe_imb"]
    if moe_imb is None:
        moe_imb = gauges.get("serve.expert_imbalance")
    moe = None
    if moe_steps:
        tot = moe_routed + moe_dropped
        moe = {"routed": moe_routed, "dropped": moe_dropped,
               "imbalance": moe_imb,
               "drop_rate": round(moe_dropped / tot, 4) if tot else 0.0}
    spec = {
        "drafted": drafted,
        "accepted": accepted,
        "acceptance": round(accepted / drafted, 4) if drafted else None,
        "mean_k": (round(sum(spec_ks) / len(spec_ks), 2)
                   if spec_ks else None),
    }
    if slo["burn_rate"] is None:
        slo["burn_rate"] = gauges.get("serve.slo_burn")
    if slo["state"] is None:
        slo["state"] = {0: "ok", 1: "degraded", 2: "breach"}.get(
            gauges.get("serve.health"), "ok")
    return {
        "records": len(events),
        "workload": workload or "gpt",
        "occupancy": occupancy,
        "live": last.get("live"),
        "slots": last.get("slots"),
        "queue_depth": last.get("queue_depth"),
        "steps": len(steps),
        "tokens_per_sec": tok_s,
        "blocks_free": gauges.get("serve.blocks_free"),
        "blocks_shared": gauges.get("serve.blocks_shared"),
        "prefix_entries": gauges.get("serve.prefix_entries"),
        "ttft_p50_ms": _pct_ms(ttft_ms, 50),
        "ttft_p95_ms": _pct_ms(ttft_ms, 95),
        "ttft_p99_ms": _pct_ms(ttft_ms, 99),
        "tpot_p50_ms": _pct_ms(tpot_ms, 50),
        "tpot_p99_ms": _pct_ms(tpot_ms, 99),
        "requests": counts,
        "spec": spec,
        "mix": mix,
        "moe": moe,
        "slo": slo,
        "flight_dumps": flight_dumps,
        "weight_version": weight_version,
        "last_swap_t": last_swap_t,
    }


def summarize_fleet(events, window=4096):
    """Per-replica dashboard rows from a merged fleet stream: serve
    events tagged ``replica=<k>`` (each router replica's engine stamps
    its records), router placement/breaker events, and the
    supervisor's replica_* failure records."""
    events = events[-window:] if window else events
    per = {}

    def row(k):
        return per.setdefault(k, {
            "replica": k, "state": "up", "health": "ok", "role": None,
            "life": None, "workload": None, "version": None,
            "live": None, "slots": None, "queue_depth": None,
            "steps": 0, "breaker": "closed", "routed": 0,
            "requeued": 0, "rejects": 0, "deaths": 0, "restarts": 0,
            "finished": 0, "drafted": 0, "accepted": 0,
            "dir_lookups": 0, "dir_hits": 0,
            "q_prefill": 0, "q_verify": 0, "q_decode": 0,
            "moe_routed": 0, "moe_dropped": 0, "moe_imb": None,
        })

    shed = {"latency": 0, "throughput": 0}
    prefix = {"hits": 0, "misses": 0, "steals": 0, "stale": 0}
    # tiered KV (ISSUE 17): spill/fetch/drop ledger events plus the
    # directory's "tier" routing verdict (warm in a tier, no pool)
    tier = {"spills": 0, "fetches": 0, "drops": 0, "routed": 0,
            "ps_killed": 0}
    hops = handoffs = 0
    pressure = None
    rollout = None          # live-weight-sync progress footer
    autoscale = None        # elastic-fleet footer (scale_* events)
    fleet_burn = None       # latest fleet.burn gauge
    for e in events:
        kind = e.get("event")
        rep = e.get("replica")
        # the engine's metrics tags ride every serve event — a
        # role-tagged record pins the replica's prefill/decode/mixed kind
        if rep is not None and e.get("role") is not None:
            row(rep)["role"] = e.get("role")
        # the workload tag (embed engines stamp workload="embed" on
        # every serve event; untagged GPT streams render as "gpt")
        if rep is not None and e.get("workload") is not None:
            row(rep)["workload"] = e.get("workload")
        # the weight_version tag (live weight sync): the newest stamp
        # per replica is its current version
        if rep is not None and e.get("weight_version") is not None:
            row(rep)["version"] = e.get("weight_version")
        if kind == "serve_step" and rep is not None:
            r = row(rep)
            r["live"] = e.get("live")
            r["slots"] = e.get("slots")
            r["queue_depth"] = e.get("queue_depth")
            r["steps"] += 1
            if isinstance(e.get("spec_proposed"), int):
                r["drafted"] += e["spec_proposed"]
                r["accepted"] += e.get("spec_accepted", 0)
            if isinstance(e.get("q_prefill"), int):
                # mixed-mode wave: per-replica mode split
                r["q_prefill"] += e["q_prefill"]
                r["q_verify"] += e.get("q_verify", 0) or 0
                r["q_decode"] += e.get("q_decode", 0) or 0
            if isinstance(e.get("moe_routed"), int):
                # MoE serving: per-replica expert routing outcome —
                # the newest imbalance stamp is the replica's current
                # max/mean expert-load ratio
                r["moe_routed"] += e["moe_routed"]
                r["moe_dropped"] += e.get("moe_dropped", 0) or 0
                if isinstance(e.get("moe_imb"), (int, float)):
                    r["moe_imb"] = e["moe_imb"]
        elif kind == "slo_health" and rep is not None:
            row(rep)["health"] = e.get("state")
        elif kind == "serve_finish" and rep is not None:
            row(rep)["finished"] += 1
        elif kind == "serve_queue_reject" and rep is not None:
            row(rep)["rejects"] += 1
        elif kind == "router_route" and rep is not None:
            r = row(rep)
            r["routed"] += 1
            # directory verdict stamped on decode-phase placements:
            # hit/steal routed the request TO this replica's cached span
            d = e.get("directory")
            if d is not None:
                r["dir_lookups"] += 1
                if d in ("hit", "steal"):
                    r["dir_hits"] += 1
                if d == "hit":
                    prefix["hits"] += 1
                elif d == "steal":
                    prefix["steals"] += 1
                elif d == "stale":
                    prefix["stale"] += 1
                elif d == "miss":
                    prefix["misses"] += 1
                elif d == "tier":
                    tier["routed"] += 1
        elif kind == "kv_handoff_in":
            handoffs += 1
        elif kind == "kv_spill":
            tier["spills"] += 1
        elif kind == "kv_fetch":
            tier["fetches"] += 1
        elif kind == "kv_tier_drop":
            tier["drops"] += 1
        elif kind == "kvtier_ps_killed":
            tier["ps_killed"] += 1
        elif kind == "router_hop":
            hops += 1
            to = e.get("to_replica")
            if to is not None:
                r = row(to)
                r["routed"] += 1
                r["requeued"] += 1
        elif kind == "router_breaker" and rep is not None:
            row(rep)["breaker"] = e.get("state")
        elif kind == "rollout_start":
            rollout = {"version": e.get("version"), "done": 0,
                       "replicas": e.get("replicas"),
                       "state": ("rolling"
                                 if e.get("phase") != "rollback"
                                 else "rolling back")}
        elif kind == "rollout_advance" and rollout is not None:
            rollout["done"] = e.get("done", rollout["done"])
        elif kind == "rollout_done" and rollout is not None:
            rollout["state"] = ("done"
                                if e.get("phase") != "rollback"
                                else "rolled back")
        elif kind == "rollout_failed" and rollout is not None:
            rollout["state"] = "failed"
        elif kind == "router_shed":
            cls = e.get("slo_class")
            if cls in shed:
                shed[cls] += 1
        elif kind == "replica_start" and rep is not None:
            row(rep)["state"] = "up"
        elif kind == "replica_exit" and rep is not None:
            r = row(rep)
            r["deaths"] += 1
            r["state"] = "dead"
        elif kind == "replica_restart" and rep is not None:
            r = row(rep)
            r["restarts"] = e.get("attempt", r["restarts"] + 1)
            r["state"] = "up"
        elif kind == "replica_failed" and rep is not None:
            row(rep)["state"] = "failed"
        elif kind in ("scale_up", "scale_down"):
            # elastic fleet: the newest scale action wins the footer
            autoscale = {
                "action": kind, "replica": rep,
                "reason": e.get("reason"),
                "target": e.get("target"), "actual": e.get("actual"),
                "burn": e.get("burn"), "pressure": e.get("pressure"),
            }
        elif kind == "replica_warming" and rep is not None:
            row(rep)["life"] = "warming"
        elif kind == "replica_ready" and rep is not None:
            row(rep)["life"] = "serving"
        elif kind == "replica_draining" and rep is not None:
            row(rep)["life"] = "draining"
        elif kind == "replica_retired" and rep is not None:
            r = row(rep)
            r["life"] = "retired"
            r["state"] = "retired"
        elif kind == "gauge" and e.get("name") == "fleet.burn":
            fleet_burn = e.get("value")
        elif kind == "gauge" and e.get("name") == "fleet.replicas":
            if autoscale is not None:
                autoscale["actual"] = e.get("value")
        elif kind == "gauge" and e.get("name") == "router.pressure":
            pressure = e.get("value")
    for r in per.values():
        if isinstance(r["live"], int) and isinstance(r["slots"], int) \
                and r["slots"]:
            r["occupancy"] = round(r["live"] / r["slots"], 4)
        else:
            r["occupancy"] = None
        r["acceptance"] = (round(r["accepted"] / r["drafted"], 4)
                           if r["drafted"] else None)
        r["dir_hit_rate"] = (round(r["dir_hits"] / r["dir_lookups"], 4)
                             if r["dir_lookups"] else None)
        moe_tot = r["moe_routed"] + r["moe_dropped"]
        r["moe_drop_rate"] = (round(r["moe_dropped"] / moe_tot, 4)
                              if moe_tot else None)
    return {
        "records": len(events),
        "replicas": [per[k] for k in sorted(per)],
        "shed": shed,
        "requeues": hops,
        "prefix": prefix,
        "tier": tier,
        "handoffs": handoffs,
        "pressure": pressure,
        "rollout": rollout,
        "autoscale": autoscale,
        "fleet_burn": fleet_burn,
    }


def render_fleet(stats, clock=None):
    """One fleet frame as a string: a row per replica + fleet totals."""
    lines = [
        f"hetu_top --fleet — "
        f"{time.strftime('%H:%M:%S', time.gmtime(clock))} UTC"
        f"  ({stats['records']} records)",
        "-" * 72,
        f"{'rep':>3} {'state':<7} {'life':<8} {'role':<8} {'wkld':<6} "
        f"{'ver':>4} "
        f"{'health':<9} {'occ':>5} "
        f"{'live':>4} {'queue':>5} {'breaker':<9} {'routed':>6} "
        f"{'requeued':>8} {'rejects':>7} {'deaths':>6} "
        f"{'drafted':>7} {'acc':>5} {'dir%':>5} "
        f"{'qpre':>6} {'qver':>6} {'qdec':>6} "
        f"{'imb':>5} {'drop%':>6}",
    ]
    for r in stats["replicas"]:
        ver = r.get("version")
        # mixed-mode columns stay "-" for phase-split replicas (their
        # serve_step events carry no per-mode q split)
        mixed = (r.get("q_prefill", 0) or r.get("q_verify", 0)
                 or r.get("q_decode", 0))
        lines.append(
            f"{r['replica']:>3} {r['state']:<7} "
            f"{str(r.get('life') or '-'):<8} "
            f"{str(r.get('role') or '-'):<8} "
            f"{str(r.get('workload') or 'gpt'):<6} "
            f"{('v' + str(ver)) if ver is not None else '-':>4} "
            f"{str(r['health']):<9} "
            f"{_fmt(r['occupancy'], nd=2):>5} {_fmt(r['live']):>4} "
            f"{_fmt(r['queue_depth']):>5} {r['breaker']:<9} "
            f"{r['routed']:>6} {r['requeued']:>8} {r['rejects']:>7} "
            f"{r['deaths']:>6} {r['drafted']:>7} "
            f"{_fmt(r['acceptance'], nd=2):>5} "
            f"{_fmt(r.get('dir_hit_rate'), nd=2):>5} "
            f"{_fmt(r['q_prefill'] if mixed else None):>6} "
            f"{_fmt(r['q_verify'] if mixed else None):>6} "
            f"{_fmt(r['q_decode'] if mixed else None):>6} "
            # MoE columns stay "-" for dense replicas (their
            # serve_step events carry no moe_* fields)
            f"{_fmt(r.get('moe_imb'), nd=2):>5} "
            f"{_fmt(r.get('moe_drop_rate'), nd=4):>6}")
    shed = stats["shed"]
    pre = stats.get("prefix") or {}
    lines.append("-" * 72)
    lines.append(
        f"fleet     requeues {stats['requeues']}"
        f"  shed latency {shed['latency']}"
        f" / throughput {shed['throughput']}"
        f"  pressure {_fmt(stats['pressure'], nd=2)}")
    lines.append(
        f"prefix    hits {pre.get('hits', 0)}"
        f"  misses {pre.get('misses', 0)}"
        f"  steals {pre.get('steals', 0)}"
        f"  stale {pre.get('stale', 0)}"
        f"  handoffs {stats.get('handoffs', 0)}")
    tr = stats.get("tier") or {}
    if any(tr.values()):
        # tiered KV panel — only when the ladder saw traffic
        lines.append(
            f"kv-tier   spills {tr.get('spills', 0)}"
            f"  fetches {tr.get('fetches', 0)}"
            f"  drops {tr.get('drops', 0)}"
            f"  routed {tr.get('routed', 0)}"
            + ("  PS DEAD" if tr.get("ps_killed") else ""))
    ro = stats.get("rollout")
    if ro is not None:
        # "rollout   rolling 1/2 → v7" while in flight; terminal
        # states render as done/failed/rolled back
        lines.append(
            f"rollout   {ro['state']} {ro.get('done', 0)}"
            f"/{_fmt(ro.get('replicas'))} → v{_fmt(ro.get('version'))}")
    asc = stats.get("autoscale")
    if asc is not None:
        # elastic fleet: last scale action (target vs. actual replicas
        # + the signal that drove it) and the worst burn gauge
        lines.append(
            f"autoscale {asc['action']} r{_fmt(asc.get('replica'))}"
            f" ({_fmt(asc.get('reason'))})"
            f"  target {_fmt(asc.get('target'))}"
            f" actual {_fmt(asc.get('actual'))}"
            f"  burn {_fmt(stats.get('fleet_burn'), nd=2)}"
            f"  pressure {_fmt(asc.get('pressure'), nd=2)}")
    return "\n".join(lines)


def _fmt(v, suffix="", nd=1):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}{suffix}"
    return f"{v}{suffix}"


def render(stats, clock=None):
    """One dashboard frame as a string (ANSI-free: the CLI owns the
    clear-screen escape so tests can assert on plain text)."""
    s = stats
    r = s["requests"]
    slo = s["slo"]
    state = slo["state"] or "ok"
    badge = {"ok": "[ OK ]", "degraded": "[DEGR]",
             "breach": "[BRCH]"}.get(state, f"[{state}]")
    lines = [
        f"hetu_top — {time.strftime('%H:%M:%S', time.gmtime(clock))} UTC"
        f"  ({s['records']} records)",
        "-" * 64,
        f"engine    workload {s.get('workload') or 'gpt'}"
        f"  occupancy {_fmt(s['occupancy'])}"
        f"  live {_fmt(s['live'])}/{_fmt(s['slots'])}"
        f"  queue {_fmt(s['queue_depth'])}"
        f"  steps {_fmt(s['steps'])}"
        f"  tok/s {_fmt(s['tokens_per_sec'])}",
        f"weights   version "
        f"{('v' + str(s['weight_version'])) if s.get('weight_version') is not None else '-'}"
        f"  last_swap "
        + (time.strftime('%H:%M:%S', time.gmtime(s['last_swap_t']))
           if s.get('last_swap_t') else '-'),
        f"kv pool   blocks_free {_fmt(s['blocks_free'])}"
        f"  blocks_shared {_fmt(s['blocks_shared'])}"
        f"  prefixes {_fmt(s['prefix_entries'])}",
        f"requests  submitted {r['submitted']}"
        f"  finished {r['finished']}  rejected {r['rejected']}",
        f"TTFT ms   p50 {_fmt(s['ttft_p50_ms'])}"
        f"  p95 {_fmt(s['ttft_p95_ms'])}"
        f"  p99 {_fmt(s['ttft_p99_ms'])}",
        f"TPOT ms   p50 {_fmt(s['tpot_p50_ms'])}"
        f"  p99 {_fmt(s['tpot_p99_ms'])}",
        f"SLO       {badge} burn {_fmt(slo['burn_rate'], nd=2)}"
        f"  violations {slo['violations']}"
        f"  flight_dumps {s['flight_dumps']}",
    ]
    sp = s.get("spec") or {}
    if sp.get("drafted"):
        lines.insert(-1, (
            f"spec      drafted {sp['drafted']}"
            f"  accepted {sp['accepted']}"
            f"  acceptance {_fmt(sp['acceptance'], nd=2)}"
            f"  mean_k {_fmt(sp['mean_k'], nd=1)}"))
    mx = s.get("mix")
    if mx:
        # mixed-mode ragged dispatch: the per-step prefill/verify/
        # decode q-token split of the unified waves
        lines.insert(-1, (
            f"mixed     q_prefill {mx['q_prefill']}"
            f"  q_verify {mx['q_verify']}"
            f"  q_decode {mx['q_decode']}"
            f"  waves {mx['steps']}"))
    me = s.get("moe")
    if me:
        # MoE serving: routed/dropped expert assignments, load
        # imbalance (max/mean — 1.0 = perfectly balanced), drop rate
        lines.insert(-1, (
            f"experts   routed {me['routed']}"
            f"  dropped {me['dropped']}"
            f"  imbalance {_fmt(me['imbalance'], nd=2)}"
            f"  drop_rate {_fmt(me['drop_rate'], nd=4)}"))
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="hetu_top",
        description="Live terminal dashboard over the merged telemetry "
                    "JSONL stream (occupancy, queue, KV pool, TTFT/TPOT "
                    "percentiles, SLO health).")
    ap.add_argument("paths", nargs="*",
                    help="JSONL files (default: every HETU_*_LOG / "
                         "HETU_TELEMETRY_LOG set in the environment)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (scripts/tests)")
    ap.add_argument("--window", type=int, default=512, metavar="N",
                    help="newest N records the frame is computed over")
    ap.add_argument("--fleet", action="store_true",
                    help="per-replica rows for a ServingRouter fleet "
                         "(state, health, role, occupancy, queue, "
                         "breaker, routed/requeue/reject/death counts, "
                         "directory hit rate + fleet prefix totals)")
    args = ap.parse_args(argv)

    paths = args.paths or configured_logs()
    if not paths:
        ap.error("no paths given and no HETU_*_LOG configured")
    while True:
        events, _bad = read_events(paths)
        if args.fleet:
            frame = render_fleet(
                summarize_fleet(events, window=max(args.window, 4096)),
                clock=time.time())
        else:
            frame = render(summarize(events, window=args.window),
                           clock=time.time())
        if args.once:
            print(frame)
            return 0
        print("\x1b[2J\x1b[H" + frame, flush=True)
        try:
            time.sleep(max(args.interval, 0.1))
        except KeyboardInterrupt:
            return 0
