"""Merge/tail the JSONL telemetry streams; export a Perfetto trace.

``bin/hetu_trace.py`` is the CLI.  Input is any number of
contract-shaped JSONL files (``{"t", "event", ...}`` — the merged
``$HETU_TELEMETRY_LOG`` or the per-stream legacy logs); with no paths
given, every stream log currently configured in the environment is
read.  Output:

- default: the merged, time-sorted stream as JSONL on stdout (the
  one ``tail | jq`` pipeline, now across all streams at once);
- ``--export trace.json``: a Chrome/Perfetto-loadable trace —
  duration-carrying records (``span``/``serve_step``/``serve_prefill``/
  ``req_span``) become ``"X"`` complete events laid out per pid/thread
  track, ``gauge`` records become ``"C"`` counter tracks (occupancy,
  queue depth, blocks_free render as time series; ``serve_step``
  records contribute ``serve.queue_depth``/``serve.live`` counters
  too), everything else an ``"i"`` instant — plus a one-line summary
  on stdout.

Request-lifecycle tracks: ``req_span`` records (serving/metrics.py, one
per queue/kv_alloc/prefill/decode/requeue phase of each request) land
on a per-request track named ``req:<request_id>``, so one request's
whole lifecycle reads as a lane; flow arrows (``s``/``t``/``f`` events
keyed by the request id) connect its decode span into every engine
fused-step wave it participated in (``serve_step`` records carry the
per-wave request list).

Durations: a ``span``/``req_span`` record's ``t`` is its START epoch
and ``ms`` its length (events.py writes them that way); serving
step/prefill records timestamp the END of the phase, so the exporter
backdates their start by the duration field.

``--check`` validates every record against the event contract AND the
span-balance rule: every ``serve_admit`` must have a matching
``serve_finish`` (a request admitted but never retired is a leaked
slot or a crashed scheduler loop).  Fleet streams (``replica``-tagged
serve events from a ServingRouter) additionally pair admit/finish PER
REPLICA, with requests the router requeued off a dead replica
(``router_hop`` records) exempt — they must finish on *some* replica.
Balance is skipped when the input contains a ``flight_dump`` header —
a flight recording is by definition a mid-flight snapshot.

``--check`` also enforces the mixed-quantization rule: every
``bench_row`` in the stream must carry the same ``quant`` stamp
(``hetu_tpu.quant.active_modes()``) — quantized and exact measurements
can never be compared silently.

``--check`` also enforces the speculative-attribution rule: a
``req_retire`` record carrying spec fields must satisfy
``spec_accepted + spec_bonus + 1 == n_generated`` — every retired
token is the prefill sample, an accepted draft, or a bonus sample.
Rejected drafts (``spec_proposed - spec_accepted``) are exempt: they
cost compute, never sequence length.

``--check`` also enforces the KV-handoff pairing rule (ISSUE 12):
every ``kv_handoff_out`` must pair with a ``kv_handoff_in`` for the
same request (blocks that left a replica must land on one), and a
handed-off request must retire exactly once per router admission —
two ``serve_finish`` records (prefill clone + real request), with
``router_hop``-carrying requests exempt the same way span-balance
exempts them.

``--check`` also enforces the version-coherence rule (ISSUE 15): all
of one request's ``weight_version``-stamped records must agree on a
single version — a rolling weight swap only lands on a drained
replica, so a request that spans two versions without a ``router_hop``
requeue (or a handoff pair) means a swap landed under a live request.

``--check`` also enforces the scale-balance rule (ISSUE 16): every
``scale_up`` must pair with a ``replica_ready`` on the same replica
(the bring-up probe admitted it) and every ``scale_down`` with a
``replica_retired`` there, and each rid the retirement names as
drained must retire exactly once AFTER the drain, on a peer — never
on the draining replica itself, never twice, never zero times
(deadline-expired rids excepted).

``--check`` also enforces the lockdep rule (ISSUE 19): any
``lockdep_violation`` record fails the gate outright — the sanitizer
(``hetu_tpu/locks.py`` under ``HETU_LOCKDEP=1``) only emits one after
proving a lock-order inversion, a blocking call under a held lock, or
a hold past ``HETU_LOCKDEP_HOLD_MS``, so presence is the finding.
"""

from __future__ import annotations

import argparse
import json
import os

from .. import envvars
from .events import STREAMS, validate_record

# kind -> (duration field in ms, track name); t marks the end for the
# serving kinds (their emitter stamps after the phase completes)
_DUR_FIELDS = {
    "span": ("ms", None),              # name comes from the record
    "req_span": ("ms", None),          # name = the lifecycle phase
    "serve_prefill": ("prefill_ms", "serve.prefill"),
    "serve_step": ("decode_ms", "serve.decode"),
}
_T_IS_END = ("serve_prefill", "serve_step")

# serve_step fields worth a counter track alongside the wave span
_STEP_COUNTERS = (("queue_depth", "serve.queue_depth"),
                  ("live", "serve.live"))


def configured_logs():
    """Every stream log path currently set in the environment."""
    paths = []
    for env in list(STREAMS.values()) + ["HETU_TELEMETRY_LOG"]:
        if env:
            p = envvars.get_path(env)
            if p and p not in paths:
                paths.append(p)
    return paths


def read_events(paths, strict=False):
    """Parse + merge JSONL files, time-sorted.  Bad lines are counted,
    not fatal (a crashed writer may leave a torn tail) unless
    ``strict``."""
    events, bad = [], 0
    for path in paths:
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            continue
        for ln in lines:
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except ValueError:
                bad += 1
                if strict:
                    raise
                continue
            if isinstance(rec, dict) and "t" in rec and "event" in rec:
                rec["_src"] = os.path.basename(path)
                events.append(rec)
            else:
                bad += 1
    events.sort(key=lambda r: (r.get("t", 0.0)))
    return events, bad


def to_chrome_trace(events):
    """Chrome trace-event JSON (Perfetto-loadable): spans as complete
    ("X") events, gauges + serve_step depths as counter ("C") tracks,
    request lifecycles as per-request ``req:<id>`` tracks with flow
    arrows into the engine's fused-step wave spans, point events as
    instants ("i"), with thread-name metadata so tracks read as the
    emitting thread."""
    out = []
    tids = {}
    waves = []          # (start_us, end_us, pid, tid, request ids)
    decode_spans = {}   # request id -> (start_us, end_us, pid, tid)

    def tid_for(pid, name):
        key = (pid, name)
        if key not in tids:
            tids[key] = len(tids) + 1
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tids[key], "args": {"name": str(name)}})
        return tids[key]

    n_spans = 0
    for rec in events:
        kind = rec.get("event")
        pid = int(rec.get("pid", 0))
        if kind == "req_span":
            # lifecycle phases live on the request's own track
            track = f"req:{rec.get('request')}"
        else:
            track = rec.get("tid", rec.get("_src", "events"))
        tid = tid_for(pid, track)
        ts_us = float(rec.get("t", 0.0)) * 1e6
        args = {k: v for k, v in rec.items()
                if k not in ("t", "event", "pid", "tid", "_src")
                and isinstance(v, (int, float, str, bool))}
        if kind == "gauge":
            out.append({"name": str(rec.get("name")), "cat": "gauge",
                        "ph": "C", "ts": ts_us, "pid": pid,
                        "tid": tid_for(pid, "counters"),
                        "args": {"value": rec.get("value")}})
            continue
        dur_spec = _DUR_FIELDS.get(kind)
        dur_ms = (rec.get(dur_spec[0])
                  if dur_spec is not None else None)
        if isinstance(dur_ms, (int, float)):
            dur_us = float(dur_ms) * 1e3
            if kind in _T_IS_END:
                ts_us -= dur_us
            name = (rec.get("name") or rec.get("phase")
                    or dur_spec[1] or kind)
            out.append({"name": str(name), "cat": kind, "ph": "X",
                        "ts": ts_us, "dur": dur_us, "pid": pid,
                        "tid": tid, "args": args})
            n_spans += 1
            if kind == "serve_step":
                for field, cname in _STEP_COUNTERS:
                    if isinstance(rec.get(field), (int, float)):
                        out.append({
                            "name": cname, "cat": "gauge", "ph": "C",
                            "ts": ts_us, "pid": pid,
                            "tid": tid_for(pid, "counters"),
                            "args": {"value": rec[field]}})
                reqs = rec.get("requests")
                if isinstance(reqs, (list, tuple)):
                    waves.append((ts_us, ts_us + dur_us, pid, tid,
                                  [str(r) for r in reqs]))
            elif kind == "req_span" and rec.get("phase") == "decode":
                decode_spans[str(rec.get("request"))] = \
                    (ts_us, ts_us + dur_us, pid, tid)
        else:
            out.append({"name": str(kind), "cat": "event", "ph": "i",
                        "s": "t", "ts": ts_us, "pid": pid, "tid": tid,
                        "args": args})
    # flow arrows: each request's decode span -> the engine wave spans
    # it participated in (s on the request track, t bound inside each
    # wave slice, f back on the request track at retire)
    n_flows = 0
    for rid, (d0, d1, rpid, rtid) in sorted(decode_spans.items()):
        hits = [(w0, wpid, wtid) for w0, w1, wpid, wtid, reqs in waves
                if rid in reqs]
        if not hits:
            continue
        flow = {"name": "req_flow", "cat": "req", "id": rid}
        out.append({**flow, "ph": "s", "ts": d0, "pid": rpid,
                    "tid": rtid})
        for w0, wpid, wtid in sorted(hits):
            # clamp into the decode span: the wave's backdated start
            # can drift past the request's retire stamp by scheduler-
            # loop overhead (the two are stamped at different points of
            # the same iteration), and flow steps must stay s <= t <= f
            out.append({**flow, "ph": "t",
                        "ts": min(max(w0, d0), d1),
                        "pid": wpid, "tid": wtid})
        out.append({**flow, "ph": "f", "bp": "e", "ts": d1,
                    "pid": rpid, "tid": rtid})
        n_flows += 1
    return {"traceEvents": out, "displayTimeUnit": "ms"}, n_spans


def check_span_balance(events):
    """The request span-balance rule: every ``serve_admit`` must pair
    with a ``serve_finish`` for the same request id (and vice versa —
    a finish with no admit is a torn or miswired log).  Returns problem
    strings; empty on a balanced stream.  A stream containing a
    ``flight_dump`` header is a mid-flight snapshot and is exempt.

    Fleet streams (serve events tagged ``replica=<k>`` by the router's
    engines) are checked per replica too: an admit on replica k must
    finish ON replica k — a leaked slot on one replica is invisible to
    the set-based rule once a same-id request retires elsewhere —
    UNLESS a ``router_hop`` record shows the router requeued the
    request off a dead replica, in which case finishing on *some*
    replica is the contract (requeue hops are exempt from the
    per-replica pairing, like flight dumps are from the whole rule)."""
    if any(e.get("event") == "flight_dump" for e in events):
        return []
    admits, finishes = {}, {}     # request id -> set of replica tags
    hopped = set()                # requests the router requeued
    for e in events:
        kind = e.get("event")
        if kind == "serve_admit":
            admits.setdefault(e.get("request"), set()).add(
                e.get("replica"))
        elif kind == "serve_finish":
            finishes.setdefault(e.get("request"), set()).add(
                e.get("replica"))
        elif kind == "router_hop":
            hopped.add(e.get("request"))
    problems = []
    for rid in sorted(str(r) for r in set(admits) - set(finishes)):
        problems.append(f"span-balance: request {rid!r} admitted but "
                        f"never finished/retired")
    for rid in sorted(str(r) for r in set(finishes) - set(admits)):
        problems.append(f"span-balance: request {rid!r} finished "
                        f"without a matching admit")
    for rid in sorted(admits, key=str):
        if rid not in finishes or rid in hopped:
            continue
        for rep in sorted(admits[rid] - finishes[rid],
                          key=lambda x: str(x)):
            if rep is None:
                continue   # untagged single-engine stream: set rule
            problems.append(
                f"span-balance: request {rid!r} admitted on replica "
                f"{rep} but finished elsewhere with no router_hop "
                f"(leaked slot?)")
    return problems


def check_gather_balance(events):
    """The gather-phase rule (embedding serving): every ``req_retire``
    carrying a ``gather_ms`` component must pair with a ``req_span``
    record of phase "gather" for the same request — a retirement that
    billed gather time without tracing the phase is a torn lifecycle
    (and the reverse, a gather span with no retirement, a leaked
    request).  GPT retirements (no ``gather_ms`` field) are skipped;
    flight-dump streams are exempt (mid-flight snapshot)."""
    if any(e.get("event") == "flight_dump" for e in events):
        return []
    retired, spanned = set(), set()
    for e in events:
        kind = e.get("event")
        if kind == "req_retire" and e.get("gather_ms") is not None:
            retired.add(e.get("request"))
        elif kind == "req_span" and e.get("phase") == "gather":
            spanned.add(e.get("request"))
    problems = []
    for rid in sorted(str(r) for r in retired - spanned):
        problems.append(
            f"gather-balance: request {rid!r} retired with a "
            f"gather_ms component but no req_span phase=gather")
    for rid in sorted(str(r) for r in spanned - retired):
        problems.append(
            f"gather-balance: request {rid!r} traced a gather phase "
            f"but never retired with a gather_ms component")
    return problems


def check_handoff_balance(events):
    """The KV-handoff pairing rule (ISSUE 12): every ``kv_handoff_out``
    must pair with a ``kv_handoff_in`` for the same request — blocks
    that left a replica must land on one — and vice versa (an import
    with no export is a miswired log); the out/in counts must match
    (one landing per departure).  A handed-off request must also still
    retire exactly ONCE per router admission: the prefill clone and the
    real request each admit+finish on their engines, so its stream
    carries exactly two ``serve_finish`` records — more means a
    duplicate retirement leaked through, fewer a lost phase.  Requests
    with a ``router_hop`` are exempt from the finish count (a requeue
    legitimately re-runs a phase — the same exemption the per-replica
    span-balance rule grants), and flight-dump streams are exempt
    entirely (mid-flight snapshot)."""
    if any(e.get("event") == "flight_dump" for e in events):
        return []
    outs, ins, finishes = {}, {}, {}
    hopped = set()
    for e in events:
        kind = e.get("event")
        rid = e.get("request")
        if kind == "kv_handoff_out":
            outs[rid] = outs.get(rid, 0) + 1
        elif kind == "kv_handoff_in":
            ins[rid] = ins.get(rid, 0) + 1
        elif kind == "serve_finish":
            finishes[rid] = finishes.get(rid, 0) + 1
        elif kind == "router_hop":
            hopped.add(rid)
    problems = []
    for rid in sorted(str(r) for r in set(outs) - set(ins)):
        problems.append(f"handoff: request {rid!r} exported KV "
                        f"(kv_handoff_out) that never landed "
                        f"(no kv_handoff_in)")
    for rid in sorted(str(r) for r in set(ins) - set(outs)):
        problems.append(f"handoff: request {rid!r} imported KV "
                        f"(kv_handoff_in) that was never exported")
    for rid in sorted(set(outs) & set(ins), key=str):
        if outs[rid] != ins[rid]:
            problems.append(
                f"handoff: request {rid!r} has {outs[rid]} exports "
                f"but {ins[rid]} imports")
    for rid in sorted(set(outs) & set(ins), key=str):
        n = finishes.get(rid, 0)
        if rid in hopped or n == 0:
            continue    # requeue re-runs a phase / engine log absent
        if n != 2:
            problems.append(
                f"handoff: request {rid!r} was handed off but "
                f"retired {n} time(s) — expected exactly 2 "
                f"(prefill clone + real request)")
    return problems


def check_scale_balance(events):
    """The elastic-fleet pairing rule (ISSUE 16): every ``scale_up``
    must pair with a ``replica_ready`` on the same replica (the
    bring-up probe passed and the replica was admitted) and every
    ``scale_down`` with a ``replica_retired`` there (the drain
    completed) — an unpaired scale event is a membership change that
    never finished.  Replica indexes are never reused (a retired slot's
    index stays burned), so one pairing per index is exact.  Each rid a
    ``replica_retired`` names as drained must retire exactly once on a
    PEER: never on the draining replica itself (a finish there after
    the drain means the corpse kept serving), never twice fleet-wide,
    and never zero times (a lost drain).  Rids that expired at their
    deadline (``router_deadline``) are exempt — expiry is an accounted
    outcome, not a loss — and streams without any ``serve_finish``
    records skip the rid-level audit (the engine log was not merged
    in).  The audit is ORDER-aware over the merged stream: a finish
    BEFORE the drain (a handed-off rid's prefill clone, say) is
    legitimate; what must hold is exactly one finish AFTER it, on a
    peer.  Flight-dump streams are mid-flight snapshots: exempt
    entirely."""
    if any(e.get("event") == "flight_dump" for e in events):
        return []
    ups, downs, ready, retired = set(), set(), set(), set()
    drained = {}          # rid -> retiring replica index
    post = {}             # rid -> [replica finishing AFTER the drain]
    deadline = set()
    have_finish = False
    for e in events:
        kind = e.get("event")
        rep = e.get("replica")
        if kind == "scale_up":
            ups.add(rep)
        elif kind == "scale_down":
            downs.add(rep)
        elif kind == "replica_ready":
            ready.add(rep)
        elif kind == "replica_retired":
            retired.add(rep)
            for rid in e.get("rids") or ():
                drained[rid] = rep
                post.setdefault(rid, [])
        elif kind == "serve_finish":
            have_finish = True
            rid = e.get("request")
            if rid in drained:
                post[rid].append(rep)
        elif kind == "router_deadline":
            deadline.add(e.get("request"))
    problems = []
    for rep in sorted(ups - ready, key=str):
        problems.append(
            f"scale: scale_up of replica {rep} never reached "
            f"replica_ready — the bring-up probe failed or the scale "
            f"action was abandoned")
    for rep in sorted(downs - retired, key=str):
        problems.append(
            f"scale: scale_down of replica {rep} never reached "
            f"replica_retired — the drain was abandoned")
    if have_finish:
        for rid in sorted(drained, key=str):
            if rid in deadline:
                continue
            where = post[rid]
            if not where:
                problems.append(
                    f"scale: request {rid!r} was drained off retiring "
                    f"replica {drained[rid]} but never retired "
                    f"anywhere — a lost drain")
            elif drained[rid] in where:
                problems.append(
                    f"scale: request {rid!r} retired on replica "
                    f"{drained[rid]} AFTER it was drained off it — "
                    f"the draining replica kept serving")
            elif len(where) > 1:
                problems.append(
                    f"scale: drained request {rid!r} retired "
                    f"{len(where)} times after the drain (replicas "
                    f"{sorted(where)}) — expected exactly once on a "
                    f"peer")
    return problems


def check_tier_balance(events):
    """The tiered-KV pairing rule (ISSUE 17): a ``kv_spill`` opens a
    tier residency for its prefix; exactly ONE terminal event closes
    it — a ``kv_fetch`` (the payload was re-admitted into a pool) or a
    ``kv_tier_drop`` (ring overflow past a dead/absent PS, corruption,
    shutdown).  The audit is ORDER-aware per prefix hash over the
    merged stream: a second spill while the first residency is still
    open is a double-spill (a refresh must NOT re-emit); a fetch or
    drop with no open residency closes nothing (a fabricated fetch);
    and a residency still open at end-of-stream is a leak — completed
    runs call ``TieredKVStore.close()``, which drops every resident.
    Note a host->PS demotion inside the ladder is NOT an event (the
    residency merely moved rungs).  Flight-dump streams are mid-flight
    snapshots: exempt entirely."""
    if any(e.get("event") == "flight_dump" for e in events):
        return []
    open_res = {}          # prefix hash -> count of open residencies
    problems = []
    for e in events:
        kind = e.get("event")
        if kind not in ("kv_spill", "kv_fetch", "kv_tier_drop"):
            continue
        h = e.get("prefix")
        n = open_res.get(h, 0)
        if kind == "kv_spill":
            if n > 0:
                problems.append(
                    f"tier-balance: prefix {h!r} spilled while already "
                    f"tier-resident — a refresh re-emitted kv_spill")
            open_res[h] = n + 1
        else:
            if n <= 0:
                problems.append(
                    f"tier-balance: prefix {h!r} saw {kind} with no "
                    f"open tier residency — nothing was spilled")
            else:
                open_res[h] = n - 1
    for h in sorted(k for k, n in open_res.items() if n > 0):
        problems.append(
            f"tier-balance: prefix {h!r} still tier-resident at end "
            f"of stream — no terminal kv_fetch/kv_tier_drop (close() "
            f"not called?)")
    return problems


def check_quant_consistency(events):
    """The mixed-quantization rule: every ``bench_row`` record in one
    stream must carry the SAME ``quant`` stamp (rows predating the
    stamp count as "off" — they were measured exact).  A stream mixing
    int8-wire/int8-KV rows with exact rows is not comparable: the
    quantized run moves ~4x fewer bytes, so ranking them side by side
    silently rewards the lossy configuration.  Returns problem strings;
    empty when consistent (or when there are no bench rows)."""
    by_quant = {}
    for e in events:
        if e.get("event") != "bench_row":
            continue
        by_quant.setdefault(str(e.get("quant") or "off"), []).append(
            str(e.get("config")))
    if len(by_quant) <= 1:
        return []
    detail = "; ".join(f"{q}: {sorted(set(c))}"
                       for q, c in sorted(by_quant.items()))
    return [f"quant-mix: bench rows were measured under different "
            f"quantization modes and cannot be compared ({detail}) — "
            f"re-run one side or split the streams"]


def check_spec_attribution(events):
    """The speculative-attribution rule: per retired request, accepted
    draft tokens + bonus samples + the prefill token must equal the
    retired sequence length (``n_generated``) — a mismatch means the
    engine emitted tokens it never accounted for, or rolled back tokens
    it already reported.  Records WITHOUT spec fields (non-speculative
    engines) are skipped; rejected drafts are exempt by construction
    (they are not part of the sum).  Returns problem strings."""
    problems = []
    for e in events:
        if e.get("event") != "req_retire":
            continue
        acc = e.get("spec_accepted")
        if acc is None:
            continue
        bonus = e.get("spec_bonus", 0)
        n = e.get("n_generated")
        if not all(isinstance(v, int) for v in (acc, bonus, n)):
            problems.append(
                f"spec-attribution: request {e.get('request')!r} "
                f"carries non-integer spec fields")
            continue
        if acc + bonus + 1 != n:
            problems.append(
                f"spec-attribution: request {e.get('request')!r} "
                f"retired {n} tokens but accounts for "
                f"{acc} accepted + {bonus} bonus + 1 prefill "
                f"= {acc + bonus + 1}")
    return problems


def check_moe_attribution(events):
    """The MoE routing-attribution rule (ISSUE 20): per ``serve_step``
    record, routed + dropped expert assignments must equal the wave's
    token count × top_k × MoE layer count — capacity overflow re-routes
    a token to the residual path (``moe_dropped``), it NEVER vanishes
    from the ledger, so the two sides always balance.  Records without
    ``moe_routed`` (dense engines) are exempt; a MoE record missing any
    of its companion fields is itself a violation.  Returns problem
    strings."""
    problems = []
    for e in events:
        if e.get("event") != "serve_step":
            continue
        routed = e.get("moe_routed")
        if routed is None:
            continue
        fields = {k: e.get(f"moe_{k}")
                  for k in ("tokens", "dropped", "k", "layers")}
        if not all(isinstance(v, int) for v in fields.values()) \
                or not isinstance(routed, int):
            problems.append(
                f"moe-attribution: step {e.get('step')!r} carries "
                f"moe_routed without complete integer companions "
                f"{sorted(k for k, v in fields.items() if not isinstance(v, int))}")
            continue
        want = fields["tokens"] * fields["k"] * fields["layers"]
        if routed + fields["dropped"] != want:
            problems.append(
                f"moe-attribution: step {e.get('step')!r} routed "
                f"{routed} + dropped {fields['dropped']} = "
                f"{routed + fields['dropped']} expert assignments but "
                f"{fields['tokens']} tokens x top_k {fields['k']} x "
                f"{fields['layers']} MoE layer(s) = {want} — a token "
                f"left the routing ledger")
    return problems


def check_lockdep(events):
    """The lockdep rule (ISSUE 19): a ``lockdep_violation`` record in
    the stream IS a finding — the sanitizer only emits after it proved
    a lock-order inversion (a cycle in the acquisition graph), a
    blocking call (PS RPC, multi-MB wire encode) under a held lock, or
    a hold longer than ``HETU_LOCKDEP_HOLD_MS``.  Presence fails the
    gate; the record's ``kind``/``lock``/``other``/``site`` fields and
    the in-process report (``analysis.concurrency.lockdep_report``)
    carry both acquisition stacks."""
    problems = []
    for e in events:
        if e.get("event") != "lockdep_violation":
            continue
        msg = (f"lockdep: {e.get('kind')} violation on lock "
               f"{e.get('lock')!r}")
        if e.get("other"):
            msg += f" vs {e.get('other')!r}"
        if e.get("site"):
            msg += f" at {e.get('site')}"
        problems.append(msg)
    return problems


def check_version_coherence(events):
    """The live-weight-sync rule (ISSUE 15): no retirement may mix
    tokens from two weight versions.  Every per-request record
    (``serve_submit``/``serve_admit``/``serve_finish``, ``req_span``,
    ``req_retire``) carries the ``weight_version`` tag of the engine
    that emitted it, and a rolling swap only lands on a DRAINED
    replica — so all of one request's records must agree on a single
    version.  The one legal exception is a router requeue
    (``router_hop`` names the request): a request admitted pre-swap
    that loses its replica legitimately re-admits — token-identically
    — on a peer that may already run the new version.  A prefill ->
    decode handoff pair is exempt the same way (each phase admits on
    its own replica; a rollout may pass between them).  Streams from a
    flight-recorder dump are mid-flight snapshots and are exempt, as
    are unversioned fleets (no ``weight_version`` tags anywhere)."""
    if any(e.get("event") == "flight_dump" for e in events):
        return []
    versions, exempt = {}, set()
    for e in events:
        kind = e.get("event")
        rid = e.get("request")
        if kind in ("router_hop", "kv_handoff_out", "kv_handoff_in"):
            exempt.add(rid)
            continue
        v = e.get("weight_version")
        if rid is None or v is None:
            continue
        versions.setdefault(rid, set()).add(v)
    problems = []
    for rid in sorted(versions, key=str):
        vs = versions[rid]
        if len(vs) > 1 and rid not in exempt:
            problems.append(
                f"version-coherence: request {rid!r} carries records "
                f"from weight versions {sorted(vs)} with no router "
                f"requeue — a swap landed under a live request")
    return problems


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="hetu_trace",
        description="Merge the telemetry JSONL streams; optionally "
                    "export a Chrome/Perfetto trace of the spans.")
    ap.add_argument("paths", nargs="*",
                    help="JSONL files (default: every HETU_*_LOG / "
                         "HETU_TELEMETRY_LOG set in the environment)")
    ap.add_argument("--export", metavar="TRACE_JSON",
                    help="write a Perfetto-loadable trace.json and "
                         "print a summary line instead of the stream")
    ap.add_argument("--last", type=int, default=None, metavar="N",
                    help="only the newest N records (tail semantics)")
    ap.add_argument("--events", default=None,
                    help="comma-separated kind filter "
                         "(e.g. span,serve_step)")
    ap.add_argument("--check", action="store_true",
                    help="validate every record against the event "
                         "contract AND the request span-balance rule "
                         "(every serve_admit has a serve_finish), the "
                         "quant-mix rule, the speculative-attribution "
                         "rule (accepted + bonus + 1 == n_generated "
                         "per retired request), and the KV-handoff "
                         "pairing rule (every kv_handoff_out has a "
                         "kv_handoff_in, one retirement per "
                         "admission), the gather-balance rule "
                         "(every embed retirement billing gather_ms "
                         "traced a gather phase), and the "
                         "version-coherence rule (no retirement mixes "
                         "weight versions; a request only changes "
                         "version across a router requeue), and the "
                         "scale-balance rule (every scale_up pairs "
                         "with a replica_ready, every scale_down with "
                         "a replica_retired whose drained rids each "
                         "retire exactly once on a peer), and the "
                         "tier-balance rule (every kv_spill closes "
                         "with exactly one kv_fetch or kv_tier_drop "
                         "for its prefix), and the lockdep rule (any "
                         "lockdep_violation record — a proved lock-"
                         "order inversion, blocking-under-lock, or "
                         "long hold — fails the gate), and the MoE "
                         "routing-attribution rule (per serve_step, "
                         "routed + dropped == tokens x top_k x MoE "
                         "layers; dense steps exempt); exit 1 on "
                         "violations")
    args = ap.parse_args(argv)

    paths = args.paths or configured_logs()
    if not paths:
        ap.error("no paths given and no HETU_*_LOG configured")
    events, bad = read_events(paths)
    if args.events:
        kinds = {k.strip() for k in args.events.split(",") if k.strip()}
        events = [e for e in events if e.get("event") in kinds]
    if args.last:
        events = events[-args.last:]

    if args.check:
        problems = []
        for rec in events:
            for p in validate_record(rec):
                problems.append(f"{rec.get('_src')}: {p}: "
                                f"{json.dumps(rec)[:160]}")
        balance = check_span_balance(events)
        problems.extend(balance)
        qmix = check_quant_consistency(events)
        problems.extend(qmix)
        spec = check_spec_attribution(events)
        problems.extend(spec)
        handoff = check_handoff_balance(events)
        problems.extend(handoff)
        gather = check_gather_balance(events)
        problems.extend(gather)
        version = check_version_coherence(events)
        problems.extend(version)
        scale = check_scale_balance(events)
        problems.extend(scale)
        tier = check_tier_balance(events)
        problems.extend(tier)
        lockdep = check_lockdep(events)
        problems.extend(lockdep)
        moe = check_moe_attribution(events)
        problems.extend(moe)
        for p in problems:
            print(p)
        print(json.dumps({"records": len(events), "bad_lines": bad,
                          "contract_violations": len(problems),
                          "span_balance_violations": len(balance),
                          "quant_mix_violations": len(qmix),
                          "spec_attribution_violations": len(spec),
                          "handoff_violations": len(handoff),
                          "gather_violations": len(gather),
                          "version_violations": len(version),
                          "scale_balance_violations": len(scale),
                          "tier_balance_violations": len(tier),
                          "lockdep_violations": len(lockdep),
                          "moe_attribution_violations": len(moe)}))
        return 1 if problems or bad else 0

    if args.export:
        trace, n_spans = to_chrome_trace(events)
        with open(args.export, "w") as f:
            json.dump(trace, f)
        print(json.dumps({
            "records": len(events), "bad_lines": bad,
            "spans": n_spans,
            "trace_events": len(trace["traceEvents"]),
            "out": args.export}))
        return 0

    for rec in events:
        print(json.dumps(rec))
    return 0
