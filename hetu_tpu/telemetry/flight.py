"""Chaos flight recorder: the last N telemetry records, always.

Post-mortem debugging of a killed or wedged process needs the records
*leading up to* the fault, but the merged JSONL log is opt-in
(``$HETU_TELEMETRY_LOG``) and a SIGKILL'd process never gets to flush a
buffering writer.  The flight recorder closes that gap the way an
aircraft FDR does: every record that flows through the one event
pipeline (``events.TelemetrySink.emit``) is ALSO appended to a bounded
in-memory ring — one locked deque append, always on, cheap even under
``HETU_TELEMETRY=0`` (explicit failure/serve/validate events still flow
through ``emit()`` with telemetry off; only spans/metrics go quiet) —
and ``dump()`` writes the ring to ``$HETU_FLIGHT_LOG`` as contract-shaped
JSONL the moment something goes wrong.

Dump triggers wired across the repo:

- serving engine: an exception escaping ``ServingEngine.step`` and a
  QueueFull storm (sustained admission rejection);
- chaos harness: ``ps/faults.py`` dumps synchronously BEFORE a
  ``kill=`` event SIGKILLs the process (the dump is the kill's black
  box);
- PS client: retry exhaustion (``PSConnectionError`` — the reset/drop
  storm surface);
- launcher: terminal supervisor events (worker budget spent, PS server
  dead).

The dump file is append-mode JSONL: a ``flight_dump`` header record
(``reason`` + record count) followed by the ring's records, oldest
first — so repeated dumps into one file read as consecutive incidents
and ``bin/hetu_trace.py`` can merge/validate the file like any other
stream.  With ``$HETU_FLIGHT_LOG`` unset, ``dump()`` is a no-op
returning None: recording is always on, persistence is opt-in.
"""

from __future__ import annotations

import collections
import json
import os
import time

from .. import envvars, locks


class FlightRecorder:
    """Bounded ring of recent contract-shaped records + dump-to-JSONL.

    ``record()`` is the hot path — one lock acquire + one deque append,
    no env read.  The lock is NOT optional: ``list(deque)`` raises
    ``RuntimeError: deque mutated during iteration`` when another
    thread appends mid-snapshot, so the old lock-free append could
    break ``dump()`` at exactly the moment it matters (a dying process
    snapshotting its black box under emit load) and lose the in-flight
    record.  Under the lock, a dump is an exact point-in-time snapshot.
    ``dump()`` is the cold path: snapshot under the lock, then write
    header + records with an fsync OUTSIDE it, because the usual caller
    is about to die (chaos kill) or raise."""

    def __init__(self, depth=None):
        self._lock = locks.TracedLock("telemetry.flight")
        self._ring = collections.deque(
            maxlen=max(1, depth or envvars.get_int("HETU_FLIGHT_DEPTH")))
        self.dumps = 0

    def record(self, rec):
        with self._lock:
            self._ring.append(rec)

    def extend(self, recs):
        with self._lock:
            self._ring.extend(recs)

    def recent(self):
        with self._lock:
            return list(self._ring)

    def __len__(self):
        return len(self._ring)

    def dump(self, reason, path=None, **fields):
        """Write a ``flight_dump`` header + the ring to ``path`` (or
        ``$HETU_FLIGHT_LOG``); returns the path, or None when no sink is
        configured or the write fails (a dying process must never die
        HARDER because its black box was unwritable)."""
        path = path or envvars.get_path("HETU_FLIGHT_LOG")
        if not path:
            return None
        recs = self.recent()
        header = {"t": round(time.time(), 3), "event": "flight_dump",
                  "reason": str(reason), "records": len(recs),
                  "pid": os.getpid(), **fields}
        try:
            with open(path, "a") as f:
                f.write(json.dumps(header, default=str) + "\n")
                for rec in recs:
                    f.write(json.dumps(rec, default=str) + "\n")
                f.flush()
                os.fsync(f.fileno())   # SIGKILL may follow immediately
        except (OSError, ValueError):
            return None
        self.dumps += 1
        return path

    def reset(self):
        """Re-create the ring at the current env depth (test isolation)."""
        with self._lock:
            self._ring = collections.deque(
                maxlen=max(1, envvars.get_int("HETU_FLIGHT_DEPTH")))
            self.dumps = 0


# the process-wide recorder events.TelemetrySink feeds
RECORDER = FlightRecorder()


def dump(reason, path=None, **fields):
    """Module-level dump of the process-wide ring."""
    return RECORDER.dump(reason, path=path, **fields)
