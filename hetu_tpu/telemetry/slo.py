"""Declarative serving SLOs with sliding-window burn-rate tracking.

An :class:`SLO` states one objective over finished requests:

- ``latency``: TTFT must be <= ``target`` milliseconds;
- ``throughput``: the request's per-stream decode rate must be >=
  ``target`` tokens/second (TPOT inverted — what a streaming client
  experiences once tokens start).

plus an ``objective`` — the fraction of requests that must meet the
target (default 0.99, i.e. a 1% error budget).

:class:`SLOMonitor` holds one sliding window of pass/fail samples per
SLO (last ``window`` finished requests) and reports the classic SRE
*burn rate*: the window's failing fraction divided by the error budget.
Burn 1.0 means the budget is being consumed exactly as provisioned;
above it the budget is burning faster than it refills.  The monitor's
``health()`` collapses the worst burn rate across SLOs into the
three-state admission signal the serving engine exposes (and the
multi-replica router will consume — ROADMAP item 1):

- ``ok``        worst burn < 1 (inside budget)
- ``degraded``  1 <= worst burn < ``breach_burn`` (default 2)
- ``breach``    worst burn >= ``breach_burn``

Every failing sample emits an ``slo_violation`` event and every state
change an ``slo_health`` event through the one event pipeline, so
violations land in the serve stream next to the request records that
caused them (``bin/hetu_top.py`` tails both).

Env construction (``SLOMonitor.from_env``): ``HETU_SLO_TTFT_MS`` /
``HETU_SLO_TPS`` declare the two SLO kinds, ``HETU_SLO_OBJECTIVE`` the
shared objective, ``HETU_SLO_WINDOW`` the window size.  With neither
target set the monitor is empty and ``health()`` is always ``ok``.
"""

from __future__ import annotations

import collections

from .. import envvars
from . import events

OK, DEGRADED, BREACH = "ok", "degraded", "breach"
_LEVEL = {OK: 0, DEGRADED: 1, BREACH: 2}


class SLO:
    """One declarative objective over finished requests."""

    __slots__ = ("name", "kind", "target", "objective")

    def __init__(self, name, kind, target, objective=0.99):
        if kind not in ("latency", "throughput"):
            raise ValueError(
                f"SLO kind must be 'latency' or 'throughput', got {kind!r}")
        if not 0.0 < float(objective) < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {objective}")
        self.name = str(name)
        self.kind = kind
        self.target = float(target)
        self.objective = float(objective)

    def evaluate(self, ttft_ms=None, tok_s=None):
        """(value, ok) for one finished request, or None when the sample
        lacks this SLO's measurement (e.g. a one-token request has no
        decode rate)."""
        if self.kind == "latency":
            if ttft_ms is None:
                return None
            return float(ttft_ms), float(ttft_ms) <= self.target
        if tok_s is None:
            return None
        return float(tok_s), float(tok_s) >= self.target

    def describe(self):
        op = "<=" if self.kind == "latency" else ">="
        unit = "ms" if self.kind == "latency" else "tok/s"
        return (f"{self.name}: {self.kind} {op} {self.target:g}{unit} "
                f"for {self.objective:.2%} of requests")


class SLOMonitor:
    """Sliding-window burn-rate tracker over a set of SLOs.

    ``emit_fn(kind, **fields)`` routes the ``slo_violation`` /
    ``slo_health`` events; the serving engine points it at
    ``ServingMetrics.event`` so they land in the serve stream (and its
    legacy log) alongside the request records.  Default: the merged
    telemetry stream."""

    def __init__(self, slos=(), window=None, breach_burn=2.0,
                 emit_fn=None):
        self.slos = list(slos)
        self.window = int(window or envvars.get_int("HETU_SLO_WINDOW"))
        self.breach_burn = float(breach_burn)
        self.emit_fn = emit_fn or (
            lambda kind, **f: events.emit(kind, _stream="serve", **f))
        self._windows = {s.name: collections.deque(maxlen=self.window)
                        for s in self.slos}
        self._state = OK
        self.violations = 0
        self.observed = 0

    @classmethod
    def from_env(cls, emit_fn=None):
        """The env-declared monitor (``HETU_SLO_*``); empty (always ok)
        when no target is set."""
        objective = envvars.get_float("HETU_SLO_OBJECTIVE")
        slos = []
        ttft = envvars.get_float("HETU_SLO_TTFT_MS")
        if ttft is not None:
            slos.append(SLO("ttft", "latency", ttft, objective))
        tps = envvars.get_float("HETU_SLO_TPS")
        if tps is not None:
            slos.append(SLO("stream_tok_s", "throughput", tps, objective))
        return cls(slos, emit_fn=emit_fn)

    # ------------------------------------------------------------- #

    def observe(self, request_id=None, ttft_ms=None, tok_s=None):
        """Record one finished request against every SLO; emits an
        ``slo_violation`` per failing objective and re-derives health.
        Returns the (possibly updated) health state."""
        self.observed += 1
        for slo in self.slos:
            out = slo.evaluate(ttft_ms=ttft_ms, tok_s=tok_s)
            if out is None:
                continue
            value, ok = out
            self._windows[slo.name].append(bool(ok))
            if not ok:
                self.violations += 1
                self.emit_fn("slo_violation", slo=slo.name,
                             slo_kind=slo.kind, value=round(value, 3),
                             target=slo.target, request=request_id)
        return self._update_state()

    def burn_rate(self, name):
        """Failing fraction of the window divided by the error budget
        (0.0 on an empty window — no evidence is not a breach)."""
        w = self._windows[name]
        if not w:
            return 0.0
        slo = next(s for s in self.slos if s.name == name)
        bad = 1.0 - sum(w) / len(w)
        return bad / max(1.0 - slo.objective, 1e-9)

    def health(self):
        return self._state

    def _update_state(self):
        worst = max((self.burn_rate(s.name) for s in self.slos),
                    default=0.0)
        if worst < 1.0:
            state = OK
        elif worst < self.breach_burn:
            state = DEGRADED
        else:
            state = BREACH
        if state != self._state:
            self.emit_fn("slo_health", state=state, prev=self._state,
                         burn_rate=round(worst, 3))
        events.set_gauge("serve.slo_burn", round(worst, 4))
        events.set_gauge("serve.health", _LEVEL[state])
        self._state = state
        return state

    def snapshot(self):
        """JSON-able view: per-SLO burn rate + window fill, the overall
        state, and counts (``hetu_top`` and the bench artifact read
        this)."""
        return {
            "health": self._state,
            "observed": self.observed,
            "violations": self.violations,
            "window": self.window,
            "slos": {
                s.name: {
                    "kind": s.kind,
                    "target": s.target,
                    "objective": s.objective,
                    "burn_rate": round(self.burn_rate(s.name), 4),
                    "samples": len(self._windows[s.name]),
                    "describe": s.describe(),
                } for s in self.slos
            },
        }
