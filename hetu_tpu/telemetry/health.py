"""Measurement health gates: refuse to trust readings that cannot be real.

VERDICT r5's three top weaknesses were all measurement-trust failures,
not code failures: a wedged 64.6-samples/s batch probe banked next to
216/223 siblings, a 3.2x bert4l regression nobody reconciled, and a
headline record labeled ``cpu-fallback`` around on-chip values.  These
gates codify the banking rules so a degraded tunnel window can no
longer silently become a headline row:

- **sibling consistency** — a probe >2x below the median of its
  batch-size neighbors is a wedged reading, not a slow config; it is
  excluded from winner selection and reported as degraded.
- **physics ceiling** — a throughput implying MFU above 1.0 (or an
  achieved TFLOP/s above the chip's CALIBRATION_TPU.json measured
  matmul peak) is impossible; the row is rejected, whatever it claims.
- **provenance stamping** — every banked-vs-live decision is explicit:
  records carry ``provenance: live|banked`` (+ the banked row's own
  ``measured_at``), so "which rows did THIS run measure" is a field,
  not archaeology.

All checks return JSON-able verdict dicts (never raise on a bad
reading — the bench must record the rejection, not crash) and emit a
``bench_probe_health`` event into the telemetry stream.
"""

from __future__ import annotations

import json
import os

from .events import emit

SIBLING_TOL = 2.0        # VERDICT's rule: >2x off neighbors = wedged
MFU_CEILING = 1.0        # honest-accounting MFU can approach, not pass
CEILING_MARGIN = 1.02    # 2% timer/accounting slack before "impossible"

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
CALIBRATION_FILE = os.path.join(_REPO, "CALIBRATION_TPU.json")


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    if not n:
        return None
    mid = n // 2
    return xs[mid] if n % 2 else 0.5 * (xs[mid - 1] + xs[mid])


def check_sibling_consistency(probes, tol=SIBLING_TOL):
    """Flag wedged probes in ``{key: samples_per_sec}``.

    A probe is *wedged* when the median of its siblings is more than
    ``tol``x its own reading (the Aug-2 case: batch 48 at 64.6 against
    216/223 — ratio 3.4).  Slow-but-real configs survive: a genuine 2x
    spread between batch sizes has never been observed on this
    hardware, a wedged tunnel produces 3-10x.  Returns a verdict dict;
    ``ok`` is False when any probe is wedged (the whole window is
    suspect, per VERDICT next-#1's banking rule)."""
    numeric = {k: float(v) for k, v in probes.items()
               if isinstance(v, (int, float))}
    wedged, clean = {}, {}
    for k, v in numeric.items():
        siblings = [x for kk, x in numeric.items() if kk != k]
        med = _median(siblings)
        if med is not None and v > 0 and med / v > tol:
            wedged[str(k)] = {"value": v,
                              "siblings_median": round(med, 3),
                              "ratio": round(med / v, 3)}
        else:
            clean[str(k)] = v
    verdict = {"check": "sibling-consistency", "tol": tol,
               "ok": not wedged, "wedged": wedged, "clean": clean}
    emit("bench_probe_health", ok=verdict["ok"],
         check="sibling-consistency",
         wedged=sorted(wedged), n_probes=len(numeric))
    return verdict


def _calibrated_peak_tflops():
    """The measured bf16 matmul peak from CALIBRATION_TPU.json (max
    over the dim ladder), or None when no calibration exists."""
    try:
        with open(CALIBRATION_FILE) as f:
            art = json.load(f)
        curve = art.get("matmul_tflops_bf16") or {}
        vals = [float(v) for v in curve.values()
                if isinstance(v, (int, float))]
        return max(vals) if vals else None
    except (OSError, ValueError):
        return None


def check_physics_ceiling(mfu=None, tflops_chip=None, platform=None,
                          margin=CEILING_MARGIN):
    """Reject readings that exceed what the silicon can do.

    ``mfu`` is checked against 1.0 (the honest-accounting numerator can
    approach but never pass peak); ``tflops_chip`` against the
    calibration artifact's measured matmul peak.  CPU platforms make no
    chip claim (their MFU field is None by construction), so they pass
    with a note rather than a fake ceiling."""
    if platform in ("cpu", "cpu-fallback"):
        return {"check": "physics-ceiling", "ok": True,
                "note": "cpu platform: no chip ceiling claimed"}
    violations = []
    if mfu is not None and float(mfu) > MFU_CEILING * margin:
        violations.append(
            f"MFU {float(mfu):.3f} > {MFU_CEILING} — impossible under "
            f"honest accounting (timer or FLOP-count defect)")
    peak = _calibrated_peak_tflops()
    if tflops_chip is not None and peak is not None \
            and float(tflops_chip) > peak * margin:
        violations.append(
            f"achieved {float(tflops_chip):.1f} TFLOP/s/chip > "
            f"calibrated matmul peak {peak:.1f} "
            f"({os.path.basename(CALIBRATION_FILE)})")
    return {"check": "physics-ceiling", "ok": not violations,
            **({"violations": violations} if violations else {})}


def stamp_provenance(record, live, measured_at=None):
    """Mark a record live-vs-banked IN the record (satellite: headline
    BENCH rows must say which they are, explicitly).  Banked rows keep
    their own ``measured_at`` so the reader knows how stale they are."""
    record["provenance"] = "live" if live else "banked"
    if not live and measured_at and "measured_at" not in record:
        record["measured_at"] = measured_at
    return record
