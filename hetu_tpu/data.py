"""Dataset helpers: mnist/cifar10/cifar100 + one-hot utils.

Reference: python/hetu/data.py:5-153 (downloads + normalization).  Network
egress may be unavailable; loaders look for local files first and fall back
to deterministic synthetic data shaped exactly like the real set so
benchmarks and tests run hermetically (the reference's accuracy numbers
obviously require the real data).
"""

from __future__ import annotations

import gzip
import os
import pickle

import numpy as np

_DATA_HOME = os.environ.get("HETU_DATA_HOME", os.path.expanduser("~/.hetu_data"))


def one_hot(labels, num_classes):
    labels = np.asarray(labels, np.int64).reshape(-1)
    out = np.zeros((len(labels), num_classes), np.float32)
    out[np.arange(len(labels)), labels] = 1.0
    return out


def normalize_cifar(x):
    mean = np.array([0.4914, 0.4822, 0.4465], np.float32).reshape(1, 3, 1, 1)
    std = np.array([0.2470, 0.2435, 0.2616], np.float32).reshape(1, 3, 1, 1)
    return (x - mean) / std


def _synthetic(n, shape, num_classes, seed):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, *shape).astype(np.float32)
    y = rng.randint(0, num_classes, size=(n,))
    return x, y


def mnist(path=None, onehot=True, n_train=60000, n_valid=10000):
    """Returns (train_x, train_y, valid_x, valid_y); x flat (N, 784)."""
    path = path or os.path.join(_DATA_HOME, "mnist.pkl.gz")
    if os.path.exists(path):
        with gzip.open(path, "rb") as f:
            train, valid, _test = pickle.load(f, encoding="latin1")
        tx, ty = train
        vx, vy = valid
    else:
        tx, ty = _synthetic(n_train, (784,), 10, 0)
        vx, vy = _synthetic(n_valid, (784,), 10, 1)
    if onehot:
        ty, vy = one_hot(ty, 10), one_hot(vy, 10)
    return tx.astype(np.float32), ty, vx.astype(np.float32), vy


def _load_cifar_batches(dirname, files):
    xs, ys = [], []
    for fn in files:
        with open(os.path.join(dirname, fn), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        xs.append(d[b"data"])
        ys.extend(d[b"labels" if b"labels" in d else b"fine_labels"])
    x = np.concatenate(xs).reshape(-1, 3, 32, 32).astype(np.float32) / 255.0
    return x, np.asarray(ys)


def cifar10(path=None, onehot=True, n_train=50000, n_valid=10000):
    """Returns (train_x, train_y, valid_x, valid_y); x (N, 3, 32, 32)."""
    path = path or os.path.join(_DATA_HOME, "cifar-10-batches-py")
    if os.path.isdir(path):
        tx, ty = _load_cifar_batches(
            path, [f"data_batch_{i}" for i in range(1, 6)])
        vx, vy = _load_cifar_batches(path, ["test_batch"])
        tx, vx = normalize_cifar(tx), normalize_cifar(vx)
    else:
        tx, ty = _synthetic(n_train, (3, 32, 32), 10, 0)
        vx, vy = _synthetic(n_valid, (3, 32, 32), 10, 1)
    if onehot:
        ty, vy = one_hot(ty, 10), one_hot(vy, 10)
    return tx, ty, vx, vy


def cifar100(path=None, onehot=True, n_train=50000, n_valid=10000):
    path = path or os.path.join(_DATA_HOME, "cifar-100-python")
    if os.path.isdir(path):
        tx, ty = _load_cifar_batches(path, ["train"])
        vx, vy = _load_cifar_batches(path, ["test"])
        tx, vx = normalize_cifar(tx), normalize_cifar(vx)
    else:
        tx, ty = _synthetic(n_train, (3, 32, 32), 100, 0)
        vx, vy = _synthetic(n_valid, (3, 32, 32), 100, 1)
    if onehot:
        ty, vy = one_hot(ty, 100), one_hot(vy, 100)
    return tx, ty, vx, vy
