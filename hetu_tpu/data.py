"""Dataset helpers: mnist/cifar10/cifar100 + one-hot utils.

Reference: python/hetu/data.py:5-153 (downloads + normalization).  Network
egress may be unavailable; loaders look for local files first and fall back
to deterministic synthetic data shaped exactly like the real set so
benchmarks and tests run hermetically (the reference's accuracy numbers
obviously require the real data).
"""

from __future__ import annotations

import gzip
import os
import pickle

from . import envvars

import numpy as np

_DATA_HOME = envvars.get_path("HETU_DATA_HOME")


def one_hot(labels, num_classes):
    labels = np.asarray(labels, np.int64).reshape(-1)
    out = np.zeros((len(labels), num_classes), np.float32)
    out[np.arange(len(labels)), labels] = 1.0
    return out


def normalize_cifar(x):
    mean = np.array([0.4914, 0.4822, 0.4465], np.float32).reshape(1, 3, 1, 1)
    std = np.array([0.2470, 0.2435, 0.2616], np.float32).reshape(1, 3, 1, 1)
    return (x - mean) / std


def _synthetic(n, shape, num_classes, seed):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, *shape).astype(np.float32)
    y = rng.randint(0, num_classes, size=(n,))
    return x, y


def mnist(path=None, onehot=True, n_train=60000, n_valid=10000):
    """Returns (train_x, train_y, valid_x, valid_y); x flat (N, 784)."""
    path = path or os.path.join(_DATA_HOME, "mnist.pkl.gz")
    if os.path.exists(path):
        with gzip.open(path, "rb") as f:
            train, valid, _test = pickle.load(f, encoding="latin1")
        tx, ty = train
        vx, vy = valid
    else:
        tx, ty = _synthetic(n_train, (784,), 10, 0)
        vx, vy = _synthetic(n_valid, (784,), 10, 1)
    if onehot:
        ty, vy = one_hot(ty, 10), one_hot(vy, 10)
    return tx.astype(np.float32), ty, vx.astype(np.float32), vy


def _load_cifar_batches(dirname, files):
    xs, ys = [], []
    for fn in files:
        with open(os.path.join(dirname, fn), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        xs.append(d[b"data"])
        ys.extend(d[b"labels" if b"labels" in d else b"fine_labels"])
    x = np.concatenate(xs).reshape(-1, 3, 32, 32).astype(np.float32) / 255.0
    return x, np.asarray(ys)


def cifar10(path=None, onehot=True, n_train=50000, n_valid=10000):
    """Returns (train_x, train_y, valid_x, valid_y); x (N, 3, 32, 32)."""
    path = path or os.path.join(_DATA_HOME, "cifar-10-batches-py")
    if os.path.isdir(path):
        tx, ty = _load_cifar_batches(
            path, [f"data_batch_{i}" for i in range(1, 6)])
        vx, vy = _load_cifar_batches(path, ["test_batch"])
        tx, vx = normalize_cifar(tx), normalize_cifar(vx)
    else:
        tx, ty = _synthetic(n_train, (3, 32, 32), 10, 0)
        vx, vy = _synthetic(n_valid, (3, 32, 32), 10, 1)
    if onehot:
        ty, vy = one_hot(ty, 10), one_hot(vy, 10)
    return tx, ty, vx, vy


def cifar100(path=None, onehot=True, n_train=50000, n_valid=10000):
    path = path or os.path.join(_DATA_HOME, "cifar-100-python")
    if os.path.isdir(path):
        tx, ty = _load_cifar_batches(path, ["train"])
        vx, vy = _load_cifar_batches(path, ["test"])
        tx, vx = normalize_cifar(tx), normalize_cifar(vx)
    else:
        tx, ty = _synthetic(n_train, (3, 32, 32), 100, 0)
        vx, vy = _synthetic(n_valid, (3, 32, 32), 100, 1)
    if onehot:
        ty, vy = one_hot(ty, 100), one_hot(vy, 100)
    return tx, ty, vx, vy


# --------------------------------------------------------------------- #
# Real-dataset parsers (VERDICT r2 item 9): read reference-format local
# files when present; callers keep their synthetic fallbacks.  Formats
# match /root/reference/examples/ctr/models/load_data.py and
# examples/rec/movielens.py so files prepared for the reference drop in
# unchanged.  Pure numpy/csv — no pandas/sklearn dependency.
# --------------------------------------------------------------------- #

def _label_encode_columns(cols):
    """Per-column label-encode with CUMULATIVE offsets (reference
    process_sparse_feats: each column's ids live in a disjoint range, so
    one flat embedding table serves all fields).  Vectorized via
    np.unique — a Python dict loop is unusable at Criteo scale (45.8M
    rows x 26 columns)."""
    out = np.empty((len(cols[0]), len(cols)), np.int32)
    offset = 0
    for j, col in enumerate(cols):
        uniq, inv = np.unique(np.asarray(col), return_inverse=True)
        out[:, j] = inv + offset
        offset += len(uniq)
    return out, offset


def load_criteo(path, nrows=None, return_val=False):
    """Criteo display-advertising data from ``path``.

    Accepted layouts (reference load_data.py):
      * preprocessed arrays ``train_dense_feats.npy`` /
        ``train_sparse_feats.npy`` / ``train_labels.npy``
        (+ ``test_*`` when ``return_val``) — process_all_criteo_data;
      * ``sampled_dense_feats.npy``/... — process_sampled_criteo_data;
      * raw ``train.txt`` (tab-separated, no header: label, 13 ints,
        26 hex categoricals) or ``train.csv`` (same with header) —
        dense gets log(x+1) for x > -1, categoricals label-encode with
        cumulative offsets (process_dense_feats/process_sparse_feats).

    Returns ``(dense [N,13] f32, sparse [N,26] i32, labels [N,1] f32)``
    (tuples of train/test arrays per position when ``return_val``).
    Raises FileNotFoundError when nothing usable is present — callers
    keep their synthetic fallback.
    """
    pre = [os.path.join(path, f) for f in (
        "train_dense_feats.npy", "train_sparse_feats.npy",
        "train_labels.npy")]
    if all(os.path.exists(p) for p in pre):
        train = [np.load(p) for p in pre]
        if return_val:
            test = [np.load(os.path.join(path, f)) for f in (
                "test_dense_feats.npy", "test_sparse_feats.npy",
                "test_labels.npy")]
            return tuple(zip(train, test))
        return tuple(train)
    sampled = [os.path.join(path, f) for f in (
        "sampled_dense_feats.npy", "sampled_sparse_feats.npy",
        "sampled_labels.npy")]
    if all(os.path.exists(p) for p in sampled):
        return tuple(np.load(p) for p in sampled)

    txt = os.path.join(path, "train.txt")
    csvf = os.path.join(path, "train.csv")
    if os.path.exists(txt):
        rows_iter = (line.rstrip("\n").split("\t") for line in open(txt))
    elif os.path.exists(csvf):
        import csv as _csv
        rdr = _csv.reader(open(csvf))
        next(rdr)                       # header
        rows_iter = rdr
    else:
        raise FileNotFoundError(
            f"no criteo data under {path!r} (expected train_*.npy, "
            f"sampled_*.npy, train.txt or train.csv)")
    labels, dense, sparse_raw = [], [], []
    for i, parts in enumerate(rows_iter):
        if nrows is not None and i >= nrows:
            break
        labels.append(float(parts[0] or 0))
        dense.append([float(v) if v not in ("", None) else 0.0
                      for v in parts[1:14]])
        sparse_raw.append([v or "-1" for v in parts[14:40]])
    dense = np.asarray(dense, np.float32)
    dense = np.where(dense > -1, np.log(dense + 1,
                                        where=dense > -1), -1.0)
    sparse, _ = _label_encode_columns(
        [np.array([r[j] for r in sparse_raw]) for j in range(26)])
    labels = np.asarray(labels, np.float32).reshape(-1, 1)
    out = (dense.astype(np.float32), sparse, labels)
    if return_val:
        n_test = max(len(labels) // 10, 1)
        return tuple((a[:-n_test], a[-n_test:]) for a in out)
    return out


_ADULT_COLUMNS = [
    "age", "workclass", "fnlwgt", "education", "education_num",
    "marital_status", "occupation", "relationship", "race", "gender",
    "capital_gain", "capital_loss", "hours_per_week", "native_country",
    "income_bracket"]
_ADULT_EMBED = ["workclass", "education", "marital_status", "occupation",
                "relationship", "race", "gender", "native_country"]
_ADULT_CONT = ["age", "capital_gain", "capital_loss", "hours_per_week"]
_ADULT_CROSS = (("education", "occupation"),
                ("native_country", "occupation"))
WDL_ADULT_WIDE_DIM = 809


def load_adult(path, wide_dim=WDL_ADULT_WIDE_DIM):
    """Adult census data for wdl_adult: ``train.csv`` (and optionally
    ``test.csv``) under ``path`` in the UCI adult.data column layout
    (reference maybe_download COLUMNS; files may carry a header).

    Returns ``(X_deep [N,12] f32, X_wide [N,wide_dim] f32, y [N,2])``:
    X_deep = 8 label-encoded embedding columns + 4 standardized
    continuous (reference load_adult_data deep_cols order); X_wide =
    one-hot of the wide columns (categoricals + age bucket + the two
    crossed columns).  The reference's fitted one-hot happens to span
    809 dims on the full UCI set; other files yield a different span, so
    the encoding is padded/truncated to ``wide_dim`` to keep the
    wdl_adult contract.
    """
    import csv as _csv
    f = os.path.join(path, "train.csv")
    if not os.path.exists(f):
        raise FileNotFoundError(f"no {f}")
    rows = []
    with open(f) as fh:
        for parts in _csv.reader(fh, skipinitialspace=True):
            if not parts or parts[0] == "age":
                continue                       # header / blank
            if len(parts) < len(_ADULT_COLUMNS):
                continue
            rows.append(dict(zip(_ADULT_COLUMNS, parts)))
    col = {c: np.array([r[c] for r in rows]) for c in _ADULT_COLUMNS}
    y = np.array([1 if ">50K" in v else 0
                  for v in col["income_bracket"]], np.int32)
    # deep: embeddings + standardized continuous
    embed, _ = _label_encode_columns([col[c] for c in _ADULT_EMBED])
    cont = np.stack([col[c].astype(np.float32)
                     for c in _ADULT_CONT], axis=1)
    cont = (cont - cont.mean(axis=0)) / (cont.std(axis=0) + 1e-8)
    x_deep = np.concatenate([embed.astype(np.float32), cont], axis=1)
    # wide: one-hot of categoricals + age bucket + crossed columns
    age = col["age"].astype(np.float32)
    age_group = np.digitize(age, [25, 65]).astype(str)
    wide_cols = [col[c] for c in _ADULT_EMBED] + [age_group]
    for a, b in _ADULT_CROSS:
        wide_cols.append(np.char.add(np.char.add(
            col[a].astype(str), "-"), col[b].astype(str)))
    enc, total = _label_encode_columns(wide_cols)
    x_wide = np.zeros((len(rows), max(total, wide_dim)), np.float32)
    x_wide[np.arange(len(rows))[:, None], enc] = 1.0
    x_wide = x_wide[:, :wide_dim]
    y2 = np.eye(2, dtype=np.float32)[y]
    return x_deep, x_wide, y2


def load_movielens(path, num_negatives=4, seed=0):
    """MovieLens implicit-feedback training triples from ``path``.

    Accepts ``ratings.csv`` (ml-20m/25m: header, comma-separated
    userId,movieId,rating,timestamp) or ``ratings.dat`` (ml-1m:
    ``::``-separated, no header).  Reference movielens.py semantics:
    ratings > 0 are positives, items are densely re-indexed in first-seen
    order, each user's LATEST rating is held out for testing, and
    ``num_negatives`` unseen items are sampled per positive.

    Returns ``(users [M] i32, items [M] i32, labels [M] f32,
    num_users, num_items)``.
    """
    csvf = os.path.join(path, "ratings.csv")
    datf = os.path.join(path, "ratings.dat")
    if os.path.exists(csvf):
        lines = open(csvf).read().splitlines()[1:]
        rows = [ln.split(",") for ln in lines if ln]
    elif os.path.exists(datf):
        rows = [ln.split("::") for ln in
                open(datf).read().splitlines() if ln]
    else:
        raise FileNotFoundError(
            f"no ratings.csv / ratings.dat under {path!r}")
    item_map = {}
    seen = {}
    latest = {}
    triples = []
    for parts in rows:
        u = int(parts[0]) - 1
        raw_item = int(parts[1])
        if raw_item not in item_map:
            item_map[raw_item] = len(item_map)
        if float(parts[2]) <= 0:
            continue
        it = item_map[raw_item]
        ts = float(parts[3]) if len(parts) > 3 else 0.0
        triples.append((u, it))
        seen.setdefault(u, set()).add(it)
        if ts >= latest.get(u, (-1.0, None))[0]:
            latest[u] = (ts, it)
    num_users = max(t[0] for t in triples) + 1
    num_items = len(item_map)
    rng = np.random.RandomState(seed)
    users, items, labels = [], [], []
    for u, it in triples:
        if latest.get(u, (None, None))[1] == it:
            continue                    # held out for eval
        users.append(u)
        items.append(it)
        labels.append(1.0)
        if len(seen[u]) >= num_items:
            continue                    # user saw everything: no negative
        for _ in range(num_negatives):
            j = rng.randint(num_items)
            tries = 0
            while j in seen[u] and tries < 100:
                j = rng.randint(num_items)
                tries += 1
            if j in seen[u]:
                continue                # dense user: skip this negative
            users.append(u)
            items.append(j)
            labels.append(0.0)
    return (np.asarray(users, np.int32), np.asarray(items, np.int32),
            np.asarray(labels, np.float32), num_users, num_items)
