// HET-style client-side embedding cache: native core.
//
// TPU-native counterpart of the reference's C++ hetu_cache
// (src/hetu_cache/include/cache.h:21-60 CacheBase with pull/push staleness
// bounds; lru_cache.h:17 / lfu_cache.h:17 / lfuopt_cache.h:18 policies;
// embedding.h:19 per-row Line with version).  Re-designed, not translated:
// one flat C ABI (ctypes-friendly, no pybind11 in this image), row storage
// in a single contiguous float buffer (slot-indexed, so lookups produce a
// gather the caller can ship to the TPU in one host->device transfer), and
// policy bookkeeping in intrusive lists over slot indices.
//
// Policies:
//   0 = LRU    doubly-linked recency list, O(1) touch/evict
//   1 = LFU    frequency buckets (freq -> LRU list), O(1) touch/evict
//   2 = LFUOpt LFU whose counters age on insert pressure (evict scans the
//              minimum bucket but halves frequencies when the min bucket
//              drains), approximating the reference's optimized LFU.
//
// Build: g++ -O3 -shared -fPIC cache.cpp -o libhetu_cache.so

#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <map>
#include <list>
#include <vector>

namespace {

struct Line {
  int64_t id;
  int64_t version;      // server version at fetch time
  int64_t updates;      // local (unpushed) update count
  bool dirty;
  int64_t freq;         // LFU counter
  std::list<int64_t>::iterator pos;  // position in its recency/freq list
};

struct Cache {
  int policy;           // 0 LRU, 1 LFU, 2 LFUOpt
  int64_t limit;        // max rows
  int64_t width;        // row width (floats)
  std::vector<float> rows;       // limit x width value storage
  std::vector<float> grads;      // limit x width accumulated updates
  std::unordered_map<int64_t, int64_t> slot_of;  // id -> slot
  std::vector<Line> lines;       // slot -> metadata
  std::vector<int64_t> free_slots;
  // LRU: one list (front = most recent).  LFU: per-freq lists.
  std::list<int64_t> lru;
  std::map<int64_t, std::list<int64_t>> buckets;
  int64_t hits = 0, misses = 0, evictions = 0;
  int64_t max_upd = 0;  // running max of per-line unpushed updates

  explicit Cache(int policy_, int64_t limit_, int64_t width_)
      : policy(policy_), limit(limit_), width(width_) {
    rows.resize(size_t(limit) * width);
    grads.assign(size_t(limit) * width, 0.f);
    lines.resize(limit);
    free_slots.reserve(limit);
    for (int64_t s = limit - 1; s >= 0; --s) free_slots.push_back(s);
  }

  void touch(int64_t slot) {
    Line &ln = lines[slot];
    if (policy == 0) {
      lru.erase(ln.pos);
      lru.push_front(slot);
      ln.pos = lru.begin();
    } else {
      auto &from = buckets[ln.freq];
      from.erase(ln.pos);
      if (from.empty()) buckets.erase(ln.freq);
      ln.freq += 1;
      auto &to = buckets[ln.freq];
      to.push_front(slot);
      ln.pos = to.begin();
    }
  }

  void attach(int64_t slot, int64_t freq0) {
    Line &ln = lines[slot];
    if (policy == 0) {
      lru.push_front(slot);
      ln.pos = lru.begin();
    } else {
      ln.freq = freq0;
      auto &b = buckets[freq0];
      b.push_front(slot);
      ln.pos = b.begin();
    }
  }

  // pick the victim slot per policy (caller guarantees non-empty)
  int64_t victim() {
    if (policy == 0) return lru.back();
    auto it = buckets.begin();
    int64_t v = it->second.back();
    if (policy == 2 && it->second.size() == 1) {
      // LFUOpt aging: when the min bucket is about to drain, halve all
      // frequencies so long-lived-but-cold lines can't pin the cache
      age();
    }
    return v;
  }

  void age() {
    std::map<int64_t, std::list<int64_t>> fresh;
    for (auto &kv : buckets) {
      int64_t nf = kv.first / 2;
      auto &dst = fresh[nf];
      for (auto s : kv.second) {
        lines[s].freq = nf;
        dst.push_back(s);
        lines[s].pos = std::prev(dst.end());
      }
    }
    buckets.swap(fresh);
  }

  void detach(int64_t slot) {
    Line &ln = lines[slot];
    if (policy == 0) {
      lru.erase(ln.pos);
    } else {
      auto &b = buckets[ln.freq];
      b.erase(ln.pos);
      if (b.empty()) buckets.erase(ln.freq);
    }
  }
};

}  // namespace

extern "C" {

void *cache_create(int policy, int64_t limit, int64_t width) {
  return new Cache(policy, limit, width);
}

void cache_destroy(void *h) { delete static_cast<Cache *>(h); }

int64_t cache_size(void *h) {
  return static_cast<int64_t>(static_cast<Cache *>(h)->slot_of.size());
}

void cache_counters(void *h, int64_t *hits, int64_t *misses,
                    int64_t *evictions) {
  Cache *c = static_cast<Cache *>(h);
  *hits = c->hits;
  *misses = c->misses;
  *evictions = c->evictions;
}

// Lookup n ids; copy hit rows into out (n x width) and set hit[i] = 1.
// Misses leave their out row untouched and hit[i] = 0.
void cache_lookup(void *h, const int64_t *ids, int64_t n, float *out,
                  uint8_t *hit) {
  Cache *c = static_cast<Cache *>(h);
  for (int64_t i = 0; i < n; ++i) {
    auto it = c->slot_of.find(ids[i]);
    if (it == c->slot_of.end()) {
      hit[i] = 0;
      c->misses++;
      continue;
    }
    hit[i] = 1;
    c->hits++;
    c->touch(it->second);
    std::memcpy(out + i * c->width, c->rows.data() + it->second * c->width,
                sizeof(float) * c->width);
  }
}

// Versions of cached ids (-1 when not cached).
void cache_versions(void *h, const int64_t *ids, int64_t n, int64_t *vers) {
  Cache *c = static_cast<Cache *>(h);
  for (int64_t i = 0; i < n; ++i) {
    auto it = c->slot_of.find(ids[i]);
    vers[i] = it == c->slot_of.end() ? -1 : c->lines[it->second].version;
  }
}

// Insert/refresh n rows.  Evicted dirty lines are reported through
// evicted_ids/evicted_grads (each sized max_evicted x width); returns the
// number of evicted dirty lines written (the caller pushes them to the PS —
// reference: eviction flushes pending updates, hetu_client.cc).
int64_t cache_insert(void *h, const int64_t *ids, int64_t n,
                     const float *rows, const int64_t *versions,
                     int64_t *evicted_ids, float *evicted_grads,
                     int64_t max_evicted) {
  Cache *c = static_cast<Cache *>(h);
  int64_t n_ev = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t id = ids[i];
    auto it = c->slot_of.find(id);
    int64_t slot;
    if (it != c->slot_of.end()) {
      slot = it->second;  // refresh value + version, keep policy position
      c->touch(slot);
    } else {
      if ((int64_t)c->slot_of.size() >= c->limit) {
        int64_t v = c->victim();
        Line &vl = c->lines[v];
        if (vl.dirty && n_ev < max_evicted) {
          evicted_ids[n_ev] = vl.id;
          std::memcpy(evicted_grads + n_ev * c->width,
                      c->grads.data() + v * c->width,
                      sizeof(float) * c->width);
          n_ev++;
        }
        c->detach(v);
        c->slot_of.erase(vl.id);
        std::memset(c->grads.data() + v * c->width, 0,
                    sizeof(float) * c->width);
        c->free_slots.push_back(v);
        c->evictions++;
      }
      slot = c->free_slots.back();
      c->free_slots.pop_back();
      c->slot_of.emplace(id, slot);
      Line &ln = c->lines[slot];
      ln.id = id;
      ln.dirty = false;
      ln.updates = 0;
      c->attach(slot, 1);
    }
    Line &ln = c->lines[slot];
    ln.version = versions ? versions[i] : 0;
    std::memcpy(c->rows.data() + slot * c->width, rows + i * c->width,
                sizeof(float) * c->width);
  }
  return n_ev;
}

// Accumulate grads into cached lines (ids must be cached; unknown ids are
// ignored and counted in the return value so the caller can route them
// straight to the PS).  Updates the local value too (write-back cache).
int64_t cache_update(void *h, const int64_t *ids, int64_t n,
                     const float *grads) {
  Cache *c = static_cast<Cache *>(h);
  int64_t missed = 0;
  for (int64_t i = 0; i < n; ++i) {
    auto it = c->slot_of.find(ids[i]);
    if (it == c->slot_of.end()) {
      missed++;
      continue;
    }
    int64_t slot = it->second;
    Line &ln = c->lines[slot];
    float *g = c->grads.data() + slot * c->width;
    float *v = c->rows.data() + slot * c->width;
    const float *src = grads + i * c->width;
    for (int64_t j = 0; j < c->width; ++j) {
      g[j] += src[j];
      v[j] += src[j];
    }
    ln.dirty = true;
    ln.updates += 1;
    if (ln.updates > c->max_upd) c->max_upd = ln.updates;
    c->touch(slot);
  }
  return missed;
}

// Max local update count over cached lines (push-bound staleness check,
// reference cache.h push_bound_).  O(1): maintained by cache_update,
// reset by cache_collect_dirty.
int64_t cache_max_updates(void *h) {
  return static_cast<Cache *>(h)->max_upd;
}

// Dirty flags for n ids (0 for unknown ids).
void cache_dirty(void *h, const int64_t *ids, int64_t n, uint8_t *out) {
  Cache *c = static_cast<Cache *>(h);
  for (int64_t i = 0; i < n; ++i) {
    auto it = c->slot_of.find(ids[i]);
    out[i] = it != c->slot_of.end() && c->lines[it->second].dirty;
  }
}

// Drain dirty lines: fill ids/grads (up to max_n), clear dirty+updates,
// zero grad accumulators.  Returns count.
int64_t cache_collect_dirty(void *h, int64_t *ids_out, float *grads_out,
                            int64_t max_n) {
  Cache *c = static_cast<Cache *>(h);
  int64_t k = 0;
  for (auto &kv : c->slot_of) {
    if (k >= max_n) break;
    Line &ln = c->lines[kv.second];
    if (!ln.dirty) continue;
    ids_out[k] = ln.id;
    float *g = c->grads.data() + kv.second * c->width;
    std::memcpy(grads_out + k * c->width, g, sizeof(float) * c->width);
    std::memset(g, 0, sizeof(float) * c->width);
    ln.dirty = false;
    ln.updates = 0;
    k++;
  }
  if (k > 0) {
    // recompute the running max only over lines still dirty (those that
    // did not fit in max_n)
    c->max_upd = 0;
    for (auto &kv : c->slot_of) {
      const Line &ln = c->lines[kv.second];
      if (ln.dirty && ln.updates > c->max_upd) c->max_upd = ln.updates;
    }
  }
  return k;
}

// Overwrite rows+versions for already-cached ids (server refresh after a
// kSyncEmbedding round; unknown ids ignored).
void cache_refresh(void *h, const int64_t *ids, int64_t n, const float *rows,
                   const int64_t *versions) {
  Cache *c = static_cast<Cache *>(h);
  for (int64_t i = 0; i < n; ++i) {
    auto it = c->slot_of.find(ids[i]);
    if (it == c->slot_of.end()) continue;
    int64_t slot = it->second;
    std::memcpy(c->rows.data() + slot * c->width, rows + i * c->width,
                sizeof(float) * c->width);
    c->lines[slot].version = versions[i];
  }
}

}  // extern "C"
