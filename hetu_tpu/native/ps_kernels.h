// Shared server-side sparse-optimizer kernels.
//
// Both native tiers apply these to the SAME numpy-owned buffers — the
// ctypes entry points in ps_core.cpp (called by the python PSServer)
// and the TCP van in ps_van.cpp (serving workers directly from C++
// threads).  The two tiers must stay bit-identical forever, so the row
// loops live ONCE, here (ADVICE r4 / review r5: the van originally
// re-implemented them).
//
// Reference: ps-lite include/ps/server/optimizer.h:36-275 sparse paths;
// duplicate-id handling mirrors IndexedSlices deduplicate
// (src/ops/IndexedSlices.cu) — stateful optimizers must see each row
// once per request, so sparse entry points first merge duplicate ids'
// gradients, then apply per unique row.

#ifndef HETU_TPU_NATIVE_PS_KERNELS_H_
#define HETU_TPU_NATIVE_PS_KERNELS_H_

#include <cmath>
#include <cstdint>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace hetu_ps {

// Dedup-merge duplicate ids (sum of their rows), first-seen order.
inline void merge_rows(const int64_t* ids, const float* rows, int64_t k,
                       int64_t cols, std::vector<int64_t>& uniq,
                       std::vector<float>& merged) {
    std::unordered_map<int64_t, int64_t> pos;
    pos.reserve((size_t)k * 2);
    uniq.clear();
    merged.clear();
    for (int64_t i = 0; i < k; ++i) {
        auto it = pos.find(ids[i]);
        int64_t j;
        if (it == pos.end()) {
            j = (int64_t)uniq.size();
            pos.emplace(ids[i], j);
            uniq.push_back(ids[i]);
            merged.insert(merged.end(), cols, 0.0f);
        } else {
            j = it->second;
        }
        float* dst = merged.data() + j * cols;
        const float* src = rows + i * cols;
        for (int64_t c = 0; c < cols; ++c) dst[c] += src[c];
    }
}

inline void sparse_sgd(float* value, const int64_t* ids,
                       const float* rows, int64_t k, int64_t cols,
                       float lr) {
    // stateless: no dedup needed, updates are additive
    for (int64_t i = 0; i < k; ++i) {
        float* dst = value + ids[i] * cols;
        const float* src = rows + i * cols;
        for (int64_t c = 0; c < cols; ++c) dst[c] -= lr * src[c];
    }
}

// Velocity convention matches the python fallback (v carries -lr*g) so
// slot state stays interchangeable between engines.
inline void sparse_momentum(float* value, float* vel, const int64_t* ids,
                            const float* rows, int64_t k, int64_t cols,
                            float lr, float momentum, int nesterov) {
    std::vector<int64_t> uniq;
    std::vector<float> merged;
    merge_rows(ids, rows, k, cols, uniq, merged);
    for (size_t u = 0; u < uniq.size(); ++u) {
        float* val = value + uniq[u] * cols;
        float* vl = vel + uniq[u] * cols;
        const float* g = merged.data() + u * cols;
        if (nesterov) {
            for (int64_t c = 0; c < cols; ++c) {
                vl[c] = momentum * vl[c] - lr * g[c];
                val[c] += momentum * vl[c] - lr * g[c];
            }
        } else {
            for (int64_t c = 0; c < cols; ++c) {
                vl[c] = momentum * vl[c] - lr * g[c];
                val[c] += vl[c];
            }
        }
    }
}

inline void sparse_adagrad(float* value, float* acc, const int64_t* ids,
                           const float* rows, int64_t k, int64_t cols,
                           float lr, float eps) {
    std::vector<int64_t> uniq;
    std::vector<float> merged;
    merge_rows(ids, rows, k, cols, uniq, merged);
    for (size_t u = 0; u < uniq.size(); ++u) {
        float* val = value + uniq[u] * cols;
        float* a = acc + uniq[u] * cols;
        const float* g = merged.data() + u * cols;
        for (int64_t c = 0; c < cols; ++c) {
            a[c] += g[c] * g[c];
            val[c] -= lr * g[c] / (std::sqrt(a[c]) + eps);
        }
    }
}

inline void sparse_adam(float* value, float* m, float* v,
                        const int64_t* ids, const float* rows, int64_t k,
                        int64_t cols, float lr, float b1, float b2,
                        float eps, int64_t t) {
    // lazy/per-row bias correction with the global step, matching the
    // reference's sparse Adam (src/ops/OptimizersSparse.cu semantics)
    std::vector<int64_t> uniq;
    std::vector<float> merged;
    merge_rows(ids, rows, k, cols, uniq, merged);
    const float bc1 = 1.0f - std::pow(b1, (float)t);
    const float bc2 = 1.0f - std::pow(b2, (float)t);
    for (size_t u = 0; u < uniq.size(); ++u) {
        float* val = value + uniq[u] * cols;
        float* mm = m + uniq[u] * cols;
        float* vv = v + uniq[u] * cols;
        const float* g = merged.data() + u * cols;
        for (int64_t c = 0; c < cols; ++c) {
            mm[c] = b1 * mm[c] + (1.0f - b1) * g[c];
            vv[c] = b2 * vv[c] + (1.0f - b2) * g[c] * g[c];
            val[c] -= lr * (mm[c] / bc1) / (std::sqrt(vv[c] / bc2) + eps);
        }
    }
}

// bump version counters ONCE per unique id (HET cache bookkeeping,
// src/hetu_cache embedding.h Line::version) — staleness counters must
// not diverge by tier
inline void bump_versions(int64_t* versions, const int64_t* ids,
                          int64_t k) {
    std::unordered_set<int64_t> seen;
    seen.reserve((size_t)k * 2);
    for (int64_t i = 0; i < k; ++i) {
        if (seen.insert(ids[i]).second) versions[ids[i]] += 1;
    }
}

}  // namespace hetu_ps

#endif  // HETU_TPU_NATIVE_PS_KERNELS_H_
