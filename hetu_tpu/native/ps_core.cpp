// Native PS server core: fused server-side optimizer updates.
//
// Reference: ps-lite server optimizers (include/ps/server/optimizer.h:36-275
// SGD/Momentum/Nesterov/AdaGrad/Adam, dense + per-row sparse) applied by
// PSHandler on push (PSFHandle.h).  Here the same updates are C loops over
// the server's numpy-owned buffers, called via ctypes from
// hetu_tpu/ps/server.py; the Python implementations remain as fallback
// when no compiler exists.
//
// Sparse pushes may carry duplicate ids; stateful optimizers must see each
// row once (reference dedups via IndexedSlices deduplicate,
// src/ops/IndexedSlices.cu), so sparse entry points first merge duplicate
// ids' gradients, then apply per unique row.

#include <cstdint>
#include <cstring>
#include <cmath>

#include "ps_kernels.h"

extern "C" {

// ------------------------------------------------------------ dense

void ps_dense_sgd(float* value, const float* grad, int64_t n, float lr) {
    for (int64_t i = 0; i < n; ++i) value[i] -= lr * grad[i];
}

// Velocity convention matches the Python fallback (v carries -lr*g) so
// slot state stays interchangeable between the two engines.
void ps_dense_momentum(float* value, float* vel, const float* grad,
                       int64_t n, float lr, float momentum, int nesterov) {
    if (nesterov) {
        for (int64_t i = 0; i < n; ++i) {
            vel[i] = momentum * vel[i] - lr * grad[i];
            value[i] += momentum * vel[i] - lr * grad[i];
        }
    } else {
        for (int64_t i = 0; i < n; ++i) {
            vel[i] = momentum * vel[i] - lr * grad[i];
            value[i] += vel[i];
        }
    }
}

void ps_dense_adagrad(float* value, float* acc, const float* grad,
                      int64_t n, float lr, float eps) {
    for (int64_t i = 0; i < n; ++i) {
        acc[i] += grad[i] * grad[i];
        value[i] -= lr * grad[i] / (std::sqrt(acc[i]) + eps);
    }
}

void ps_dense_adam(float* value, float* m, float* v, const float* grad,
                   int64_t n, float lr, float b1, float b2, float eps,
                   int64_t t) {
    const float bc1 = 1.0f - std::pow(b1, (float)t);
    const float bc2 = 1.0f - std::pow(b2, (float)t);
    for (int64_t i = 0; i < n; ++i) {
        m[i] = b1 * m[i] + (1.0f - b1) * grad[i];
        v[i] = b2 * v[i] + (1.0f - b2) * grad[i] * grad[i];
        value[i] -= lr * (m[i] / bc1) / (std::sqrt(v[i] / bc2) + eps);
    }
}

// ------------------------------------------------------------ sparse
// ids: (k,) int64 row indices (may repeat); rows: (k, cols) gradients.
// The row kernels live in ps_kernels.h, SHARED with the TCP van
// (ps_van.cpp) — both tiers mutate the same buffers and must stay
// bit-identical, so the loops exist once.

void ps_sparse_sgd(float* value, const int64_t* ids, const float* rows,
                   int64_t k, int64_t cols, float lr) {
    hetu_ps::sparse_sgd(value, ids, rows, k, cols, lr);
}

void ps_sparse_momentum(float* value, float* vel, const int64_t* ids,
                        const float* rows, int64_t k, int64_t cols,
                        float lr, float momentum, int nesterov) {
    hetu_ps::sparse_momentum(value, vel, ids, rows, k, cols, lr,
                             momentum, nesterov);
}

void ps_sparse_adagrad(float* value, float* acc, const int64_t* ids,
                       const float* rows, int64_t k, int64_t cols,
                       float lr, float eps) {
    hetu_ps::sparse_adagrad(value, acc, ids, rows, k, cols, lr, eps);
}

void ps_sparse_adam(float* value, float* m, float* v, const int64_t* ids,
                    const float* rows, int64_t k, int64_t cols, float lr,
                    float b1, float b2, float eps, int64_t t) {
    hetu_ps::sparse_adam(value, m, v, ids, rows, k, cols, lr, b1, b2,
                         eps, t);
}

// plain accumulate (no optimizer): value[ids] += rows, dup-safe
void ps_sparse_accum(float* value, const int64_t* ids, const float* rows,
                     int64_t k, int64_t cols) {
    for (int64_t i = 0; i < k; ++i) {
        float* dst = value + ids[i] * cols;
        const float* src = rows + i * cols;
        for (int64_t c = 0; c < cols; ++c) dst[c] += src[c];
    }
}

// gather rows: out[i] = value[ids[i]]
void ps_sparse_gather(const float* value, const int64_t* ids, float* out,
                      int64_t k, int64_t cols) {
    for (int64_t i = 0; i < k; ++i) {
        std::memcpy(out + i * cols, value + ids[i] * cols,
                    (size_t)cols * sizeof(float));
    }
}

// bump version counters for the unique ids (HET cache bookkeeping,
// src/hetu_cache embedding.h Line::version)
void ps_bump_versions(int64_t* versions, const int64_t* ids, int64_t k) {
    hetu_ps::bump_versions(versions, ids, k);
}

}  // extern "C"
