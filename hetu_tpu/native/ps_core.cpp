// Native PS server core: fused server-side optimizer updates.
//
// Reference: ps-lite server optimizers (include/ps/server/optimizer.h:36-275
// SGD/Momentum/Nesterov/AdaGrad/Adam, dense + per-row sparse) applied by
// PSHandler on push (PSFHandle.h).  Here the same updates are C loops over
// the server's numpy-owned buffers, called via ctypes from
// hetu_tpu/ps/server.py; the Python implementations remain as fallback
// when no compiler exists.
//
// Sparse pushes may carry duplicate ids; stateful optimizers must see each
// row once (reference dedups via IndexedSlices deduplicate,
// src/ops/IndexedSlices.cu), so sparse entry points first merge duplicate
// ids' gradients, then apply per unique row.

#include <cstdint>
#include <cstring>
#include <cmath>
#include <unordered_map>
#include <unordered_set>
#include <vector>

extern "C" {

// ------------------------------------------------------------ dense

void ps_dense_sgd(float* value, const float* grad, int64_t n, float lr) {
    for (int64_t i = 0; i < n; ++i) value[i] -= lr * grad[i];
}

// Velocity convention matches the Python fallback (v carries -lr*g) so
// slot state stays interchangeable between the two engines.
void ps_dense_momentum(float* value, float* vel, const float* grad,
                       int64_t n, float lr, float momentum, int nesterov) {
    if (nesterov) {
        for (int64_t i = 0; i < n; ++i) {
            vel[i] = momentum * vel[i] - lr * grad[i];
            value[i] += momentum * vel[i] - lr * grad[i];
        }
    } else {
        for (int64_t i = 0; i < n; ++i) {
            vel[i] = momentum * vel[i] - lr * grad[i];
            value[i] += vel[i];
        }
    }
}

void ps_dense_adagrad(float* value, float* acc, const float* grad,
                      int64_t n, float lr, float eps) {
    for (int64_t i = 0; i < n; ++i) {
        acc[i] += grad[i] * grad[i];
        value[i] -= lr * grad[i] / (std::sqrt(acc[i]) + eps);
    }
}

void ps_dense_adam(float* value, float* m, float* v, const float* grad,
                   int64_t n, float lr, float b1, float b2, float eps,
                   int64_t t) {
    const float bc1 = 1.0f - std::pow(b1, (float)t);
    const float bc2 = 1.0f - std::pow(b2, (float)t);
    for (int64_t i = 0; i < n; ++i) {
        m[i] = b1 * m[i] + (1.0f - b1) * grad[i];
        v[i] = b2 * v[i] + (1.0f - b2) * grad[i] * grad[i];
        value[i] -= lr * (m[i] / bc1) / (std::sqrt(v[i] / bc2) + eps);
    }
}

// ------------------------------------------------------------ sparse
// ids: (k,) int64 row indices (may repeat); rows: (k, cols) gradients.
// merge duplicates, then apply the optimizer row-wise.

static void merge_rows(const int64_t* ids, const float* rows, int64_t k,
                       int64_t cols, std::vector<int64_t>& uniq,
                       std::vector<float>& merged) {
    std::unordered_map<int64_t, int64_t> pos;
    pos.reserve((size_t)k * 2);
    for (int64_t i = 0; i < k; ++i) {
        auto it = pos.find(ids[i]);
        int64_t j;
        if (it == pos.end()) {
            j = (int64_t)uniq.size();
            pos.emplace(ids[i], j);
            uniq.push_back(ids[i]);
            merged.insert(merged.end(), cols, 0.0f);
        } else {
            j = it->second;
        }
        float* dst = merged.data() + j * cols;
        const float* src = rows + i * cols;
        for (int64_t c = 0; c < cols; ++c) dst[c] += src[c];
    }
}

void ps_sparse_sgd(float* value, const int64_t* ids, const float* rows,
                   int64_t k, int64_t cols, float lr) {
    // stateless: no dedup needed, updates are additive
    for (int64_t i = 0; i < k; ++i) {
        float* dst = value + ids[i] * cols;
        const float* src = rows + i * cols;
        for (int64_t c = 0; c < cols; ++c) dst[c] -= lr * src[c];
    }
}

void ps_sparse_momentum(float* value, float* vel, const int64_t* ids,
                        const float* rows, int64_t k, int64_t cols,
                        float lr, float momentum, int nesterov) {
    std::vector<int64_t> uniq;
    std::vector<float> merged;
    merge_rows(ids, rows, k, cols, uniq, merged);
    for (size_t u = 0; u < uniq.size(); ++u) {
        float* val = value + uniq[u] * cols;
        float* vl = vel + uniq[u] * cols;
        const float* g = merged.data() + u * cols;
        if (nesterov) {
            for (int64_t c = 0; c < cols; ++c) {
                vl[c] = momentum * vl[c] - lr * g[c];
                val[c] += momentum * vl[c] - lr * g[c];
            }
        } else {
            for (int64_t c = 0; c < cols; ++c) {
                vl[c] = momentum * vl[c] - lr * g[c];
                val[c] += vl[c];
            }
        }
    }
}

void ps_sparse_adagrad(float* value, float* acc, const int64_t* ids,
                       const float* rows, int64_t k, int64_t cols,
                       float lr, float eps) {
    std::vector<int64_t> uniq;
    std::vector<float> merged;
    merge_rows(ids, rows, k, cols, uniq, merged);
    for (size_t u = 0; u < uniq.size(); ++u) {
        float* val = value + uniq[u] * cols;
        float* a = acc + uniq[u] * cols;
        const float* g = merged.data() + u * cols;
        for (int64_t c = 0; c < cols; ++c) {
            a[c] += g[c] * g[c];
            val[c] -= lr * g[c] / (std::sqrt(a[c]) + eps);
        }
    }
}

void ps_sparse_adam(float* value, float* m, float* v, const int64_t* ids,
                    const float* rows, int64_t k, int64_t cols, float lr,
                    float b1, float b2, float eps, int64_t t) {
    // lazy/per-row bias correction with the global step, matching the
    // reference's sparse Adam (src/ops/OptimizersSparse.cu semantics)
    std::vector<int64_t> uniq;
    std::vector<float> merged;
    merge_rows(ids, rows, k, cols, uniq, merged);
    const float bc1 = 1.0f - std::pow(b1, (float)t);
    const float bc2 = 1.0f - std::pow(b2, (float)t);
    for (size_t u = 0; u < uniq.size(); ++u) {
        float* val = value + uniq[u] * cols;
        float* mm = m + uniq[u] * cols;
        float* vv = v + uniq[u] * cols;
        const float* g = merged.data() + u * cols;
        for (int64_t c = 0; c < cols; ++c) {
            mm[c] = b1 * mm[c] + (1.0f - b1) * g[c];
            vv[c] = b2 * vv[c] + (1.0f - b2) * g[c] * g[c];
            val[c] -= lr * (mm[c] / bc1) / (std::sqrt(vv[c] / bc2) + eps);
        }
    }
}

// plain accumulate (no optimizer): value[ids] += rows, dup-safe
void ps_sparse_accum(float* value, const int64_t* ids, const float* rows,
                     int64_t k, int64_t cols) {
    for (int64_t i = 0; i < k; ++i) {
        float* dst = value + ids[i] * cols;
        const float* src = rows + i * cols;
        for (int64_t c = 0; c < cols; ++c) dst[c] += src[c];
    }
}

// gather rows: out[i] = value[ids[i]]
void ps_sparse_gather(const float* value, const int64_t* ids, float* out,
                      int64_t k, int64_t cols) {
    for (int64_t i = 0; i < k; ++i) {
        std::memcpy(out + i * cols, value + ids[i] * cols,
                    (size_t)cols * sizeof(float));
    }
}

// bump version counters for the unique ids (HET cache bookkeeping,
// src/hetu_cache embedding.h Line::version)
void ps_bump_versions(int64_t* versions, const int64_t* ids, int64_t k) {
    std::unordered_set<int64_t> seen;
    seen.reserve((size_t)k * 2);
    for (int64_t i = 0; i < k; ++i) {
        if (seen.insert(ids[i]).second) versions[ids[i]] += 1;
    }
}

}  // extern "C"
