"""Native (C++) runtime components, built on demand with g++.

The reference keeps its PS transport, server, and embedding cache in C++
(ps-lite, src/hetu_cache — SURVEY.md §2.2/2.3).  Here the host-side systems
code that survives on TPU is likewise native: this package builds small
C++ shared libraries at first import (cached next to the source) and loads
them via ctypes.  Every consumer has a pure-Python fallback so the
framework works where no toolchain exists.
"""

from __future__ import annotations

import os
import subprocess
import ctypes

_DIR = os.path.dirname(os.path.abspath(__file__))


def build_and_load(src_name, lib_name, extra_flags=(), deps=()):
    """Compile ``src_name`` to ``lib_name`` (if stale) and dlopen it.
    ``deps`` are additional files (headers) whose mtimes also count for
    staleness.  Returns the ctypes.CDLL or None when no compiler is
    available."""
    src = os.path.join(_DIR, src_name)
    lib = os.path.join(_DIR, lib_name)
    try:
        newest = max([os.path.getmtime(src)]
                     + [os.path.getmtime(os.path.join(_DIR, d))
                        for d in deps])
        if (not os.path.exists(lib)
                or os.path.getmtime(lib) < newest):
            cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                   *extra_flags, src, "-o", lib]
            subprocess.run(cmd, check=True, capture_output=True)
        return ctypes.CDLL(lib)
    except (OSError, subprocess.CalledProcessError):
        return None
