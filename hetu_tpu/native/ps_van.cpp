// Native PS "van": a C++ TCP serving loop for the sparse hot path.
//
// Reference: ps-lite's Van tier (ps-lite/src/zmq_van.h, p3_van.h) — the
// reference serves its KV traffic entirely from C++ threads, with the
// full server-optimizer family applied in-kernel
// (ps-lite/include/ps/server/optimizer.h:36-275).  The Python PSServer
// here is the correctness/feature surface (full PSFunc API, SSP/BSP,
// cache sync); this van is the THROUGHPUT tier for the pattern that
// dominates CTR training: sparse push / pull / push-pull on embedding
// tables with a server-side optimizer (SGD / Momentum / Nesterov /
// AdaGrad / Adam — same family as the reference's C++ tier).
//
// Design:
//   * the table's numpy buffers are REGISTERED (pointers + shape) — the
//     value AND the optimizer slot state (velocity / accumulator / m,v
//     and the Adam step counter) are the SAME memory the Python tier
//     uses, so the two tiers may serve one table interchangeably;
//   * one acceptor thread + one thread per connection (worker counts
//     are small); blocking I/O, one reusable buffer per connection;
//   * binary little-endian framing (u32 len | u8 op | u32 key | u32 n |
//     i64 ids[n] | f32 rows[n*dim]); responses are (u32 len | u8 ok |
//     f32 rows...) — no Python, no pickle, no text on the wire.  The
//     9-byte header is read separately from the body so ids/rows land
//     on the allocator's (16-byte) alignment — no misaligned int64 / f32
//     loads (frames put ids at offset 9, which is NOT 8-aligned);
//   * requests and responses are both capped at 1 GiB: a pull whose
//     n*dim*4 exceeds the cap is REJECTED (ok=0) before any gather, so
//     the u32 response length can never truncate and the gather can
//     never outrun the output buffer;
//   * per-table mutex, also exported (van_table_lock/unlock) so Python
//     paths touching a registered table can coordinate;
//   * duplicate ids: SGD scatters sequentially (order-insensitive sum,
//     exactly the Python tier's dedup-merge result); the stateful
//     optimizers dedup-MERGE first so each touched row's slot state
//     advances once per request, matching ServerMomentum/AdaGrad/Adam
//     ._sparse_rows (ps/server.py) and the reference's sparse kernels.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -pthread ps_van.cpp

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

#include "ps_kernels.h"

namespace {

constexpr size_t kFrameCap = 1ull << 30;  // 1 GiB, both directions

enum OptKind : int {
  kOptSGD = 0,
  kOptMomentum = 1,   // nesterov is a flag on momentum
  kOptAdaGrad = 2,
  kOptAdam = 3,
  kOptAccum = 4,      // optimizer-less table: push ACCUMULATES
};                    // (value[ids] += rows — the HET cache tables)

struct Table {
  float* value = nullptr;
  int64_t nrows = 0;
  int64_t dim = 0;
  int opt = kOptSGD;
  float lr = 0.0f;
  float hp1 = 0.0f;      // momentum | adam beta1
  float hp2 = 0.0f;      // adam beta2
  float eps = 0.0f;      // adagrad/adam epsilon
  int nesterov = 0;
  float* s1 = nullptr;   // velocity | accumulator | adam m   [nrows*dim]
  float* s2 = nullptr;   // adam v                            [nrows*dim]
  int64_t* step = nullptr;   // adam step counter (shared with python)
  int64_t* versions = nullptr;  // optional HET version counters
  std::mutex mu;
};

struct Van {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> running{false};
  std::thread acceptor;
  std::vector<std::thread> conns;
  std::vector<int> conn_fds;        // for shutdown() at stop
  std::mutex conns_mu;
  std::map<uint32_t, Table*> tables;
  std::mutex tables_mu;
  ~Van() {
    for (auto& kv : tables) delete kv.second;
  }
};

bool read_exact(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

enum Op : uint8_t { kPush = 1, kPull = 2, kPushPull = 3, kSyncEmb = 4 };

// Apply the table's server-side optimizer to a pushed batch.  Caller
// holds t->mu.  The row kernels are the SAME code the python tier's
// ctypes path runs (ps_kernels.h, also compiled into ps_core.cpp) —
// the slot state is shared memory, so the tiers cannot diverge.
void apply_push(Table* t, const int64_t* ids, const float* rows,
                uint32_t n) {
  const int64_t k = static_cast<int64_t>(n);
  switch (t->opt) {
    case kOptSGD:
      hetu_ps::sparse_sgd(t->value, ids, rows, k, t->dim, t->lr);
      break;
    case kOptMomentum:
      hetu_ps::sparse_momentum(t->value, t->s1, ids, rows, k, t->dim,
                               t->lr, t->hp1, t->nesterov);
      break;
    case kOptAdaGrad:
      hetu_ps::sparse_adagrad(t->value, t->s1, ids, rows, k, t->dim,
                              t->lr, t->eps);
      break;
    case kOptAdam:
      // one step bump per request (ServerAdam.apply_sparse) — counter
      // memory is shared with python state["t"]
      hetu_ps::sparse_adam(t->value, t->s1, t->s2, ids, rows, k, t->dim,
                           t->lr, t->hp1, t->hp2, t->eps, ++(*t->step));
      break;
    case kOptAccum: {
      // optimizer-less accumulate (PSServer.sparse_push's np.add.at
      // branch): the HET cache write-back path, workers pre-scale
      const int64_t dim = t->dim;
      for (int64_t i = 0; i < k; ++i) {
        float* dst = t->value + ids[i] * dim;
        const float* src = rows + i * dim;
        for (int64_t d = 0; d < dim; ++d) dst[d] += src[d];
      }
      break;
    }
  }
}

void serve_conn(Van* van, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  std::vector<char> body;     // ids + rows, 16-byte aligned at data()
  std::vector<char> out;
  auto send_reject = [&]() {  // the one ok=0 wire shape, both paths
    out.resize(5);
    uint32_t out_len = 1;
    std::memcpy(out.data(), &out_len, 4);
    out[4] = 0;
    return write_all(fd, out.data(), out.size());
  };
  while (van->running.load()) {
    uint32_t len = 0;
    if (!read_exact(fd, &len, 4)) break;
    if (len < 9) break;     // malformed header: protocol desync, drop
    if (len > kFrameCap) {
      // oversize frame: DRAIN it and reply ok=0 so the client sees a
      // clean rejection (closing mid-request would read as
      // "maybe-applied" and needlessly abort the caller's step)
      char sink[1 << 16];
      size_t left = len;
      bool drained = true;
      while (left > 0) {
        size_t chunk = left < sizeof(sink) ? left : sizeof(sink);
        if (!read_exact(fd, sink, chunk)) { drained = false; break; }
        left -= chunk;
      }
      if (!drained) break;
      if (!send_reject()) break;
      continue;
    }
    char hdr[9];
    if (!read_exact(fd, hdr, 9)) break;
    size_t body_len = len - 9;
    body.resize(body_len);
    if (body_len > 0 && !read_exact(fd, body.data(), body_len)) break;
    uint8_t op = static_cast<uint8_t>(hdr[0]);
    uint32_t key, n;
    std::memcpy(&key, hdr + 1, 4);
    std::memcpy(&n, hdr + 5, 4);
    Table* t = nullptr;
    {
      std::lock_guard<std::mutex> g(van->tables_mu);
      auto it = van->tables.find(key);
      if (it != van->tables.end()) t = it->second;
    }
    size_t ids_bytes = static_cast<size_t>(n) * 8;
    // body.data() comes from operator new (16-aligned); ids sit at
    // offset 0 and rows (f32) or stored_versions (i64, at 8n) stay
    // naturally aligned
    const int64_t* ids = reinterpret_cast<const int64_t*>(body.data());
    bool ok = t != nullptr && ids_bytes <= body_len;
    size_t row_bytes = 0;
    if (ok && op == kSyncEmb) {
      // HET cache sync (PSServer.sync_embedding): body = ids[n] i64 |
      // stored_versions[n] i64 | bound i64.  Response: u32 m |
      // stale_ids m*8 | rows m*dim*4 | server_versions m*8 — only rows
      // whose server version exceeds the stored one by more than bound.
      // The response is BUILT under the table mutex but WRITTEN after
      // releasing it (matching push/pull): a slow client reader must
      // not stall every other connection on the table.
      {
        std::lock_guard<std::mutex> g(t->mu);
        const int64_t* stored =
            reinterpret_cast<const int64_t*>(body.data() + ids_bytes);
        int64_t bound = 0;
        ok = t->versions != nullptr && body_len == 2 * ids_bytes + 8;
        if (ok) {
          std::memcpy(&bound, body.data() + 2 * ids_bytes, 8);
          // worst-case response must fit the u32-framed 1 GiB cap
          ok = 4 + static_cast<size_t>(n) * (16 + t->dim * 4)
               <= kFrameCap;
        }
        if (ok) {
          for (uint32_t i = 0; i < n; ++i)
            if (ids[i] < 0 || ids[i] >= t->nrows) { ok = false; break; }
        }
        if (ok) {
          std::vector<uint32_t> stale;
          stale.reserve(n);
          for (uint32_t i = 0; i < n; ++i)
            if (t->versions[ids[i]] - stored[i] > bound)
              stale.push_back(i);
          const uint32_t m = static_cast<uint32_t>(stale.size());
          const int64_t dim = t->dim;
          size_t payload = 4 + static_cast<size_t>(m) * (16 + dim * 4);
          out.resize(4 + 1 + payload);
          uint32_t out_len = static_cast<uint32_t>(1 + payload);
          std::memcpy(out.data(), &out_len, 4);
          out[4] = 1;
          char* p = out.data() + 5;
          std::memcpy(p, &m, 4);
          p += 4;
          for (uint32_t j = 0; j < m; ++j)
            std::memcpy(p + j * 8, &ids[stale[j]], 8);
          p += static_cast<size_t>(m) * 8;
          for (uint32_t j = 0; j < m; ++j)
            std::memcpy(p + static_cast<int64_t>(j) * dim * 4,
                        t->value + ids[stale[j]] * dim, dim * 4);
          p += static_cast<size_t>(m) * dim * 4;
          for (uint32_t j = 0; j < m; ++j)
            std::memcpy(p + j * 8, &t->versions[ids[stale[j]]], 8);
        }
      }
      if (!ok) {
        if (!send_reject()) break;
        continue;
      }
      if (!write_all(fd, out.data(), out.size())) break;
      continue;
    }
    if (ok) {
      // the WHOLE request — shape reads, bounds validation, scatter,
      // gather — runs under the table mutex: an in-place re-register
      // may change value/nrows/dim between any two of those steps
      std::lock_guard<std::mutex> g(t->mu);
      row_bytes = static_cast<size_t>(n) * t->dim * 4;
      const float* rows =
          reinterpret_cast<const float*>(body.data() + ids_bytes);
      if (op == kPush || op == kPushPull)
        ok = ids_bytes + row_bytes == body_len;
      else if (op == kPull)
        ok = ids_bytes == body_len;
      else
        ok = false;        // unknown op: reject, don't silently ack
      // a pull response must itself fit the u32-length frame protocol:
      // reject oversized gathers up front (n is client-controlled and a
      // pull frame carries only ids, so row_bytes is unbounded by len)
      if (ok && (op == kPull || op == kPushPull))
        ok = row_bytes <= kFrameCap;
      if (ok) {
        for (uint32_t i = 0; i < n; ++i)
          if (ids[i] < 0 || ids[i] >= t->nrows) { ok = false; break; }
      }
      uint32_t out_payload =
          ok && (op == kPull || op == kPushPull)
              ? static_cast<uint32_t>(row_bytes) : 0;
      out.resize(4 + 1 + out_payload);
      uint32_t out_len = 1 + out_payload;
      std::memcpy(out.data(), &out_len, 4);
      out[4] = ok ? 1 : 0;
      if (ok) {
        if (op == kPush || op == kPushPull) {
          apply_push(t, ids, rows, n);
          if (t->versions != nullptr)
            hetu_ps::bump_versions(t->versions, ids,
                                   static_cast<int64_t>(n));
        }
        if (op == kPull || op == kPushPull) {
          const int64_t dim = t->dim;
          float* dst = reinterpret_cast<float*>(out.data() + 5);
          for (uint32_t i = 0; i < n; ++i)
            std::memcpy(dst + static_cast<int64_t>(i) * dim,
                        t->value + ids[i] * dim, dim * 4);
        }
      }
    } else {
      if (!send_reject()) break;
      continue;
    }
    if (!write_all(fd, out.data(), out.size())) break;
  }
  ::close(fd);
}

void accept_loop(Van* van) {
  while (van->running.load()) {
    int fd = ::accept(van->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (!van->running.load()) break;
      continue;
    }
    std::lock_guard<std::mutex> g(van->conns_mu);
    van->conn_fds.push_back(fd);
    van->conns.emplace_back(serve_conn, van, fd);
  }
}

}  // namespace

extern "C" {

void* van_create() { return new Van(); }

// 0 on failure; the bound port otherwise (pass port=0 for ephemeral).
// bind_all=0 binds loopback (same-host workers); 1 binds INADDR_ANY so
// remote heturun workers can reach the fast tier directly.
int van_listen(void* h, int port, int bind_all) {
  Van* van = static_cast<Van*>(h);
  van->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (van->listen_fd < 0) return 0;
  int one = 1;
  ::setsockopt(van->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one,
               sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(bind_all ? INADDR_ANY : INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(van->listen_fd, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) != 0)
    return 0;
  if (::listen(van->listen_fd, 64) != 0) return 0;
  socklen_t alen = sizeof(addr);
  ::getsockname(van->listen_fd, reinterpret_cast<sockaddr*>(&addr),
                &alen);
  van->port = ntohs(addr.sin_port);
  van->running.store(true);
  van->acceptor = std::thread(accept_loop, van);
  return van->port;
}

// Register (or in-place re-register) a table with its server optimizer.
// opt_kind: 0=SGD 1=Momentum(+nesterov flag) 2=AdaGrad 3=Adam.
// s1/s2/step are the optimizer slot buffers (may be null per kind);
// they alias the Python tier's state arrays.
void van_register_table(void* h, uint32_t key, float* value,
                        int64_t nrows, int64_t dim, int opt_kind,
                        float lr, float hp1, float hp2, float eps,
                        int nesterov, float* s1, float* s2,
                        int64_t* step, int64_t* versions) {
  // one field-filler for both branches: a hyperparameter added to
  // Table can't silently go stale on the re-register path
  auto fill = [&](Table* t) {
    t->value = value;
    t->nrows = nrows;
    t->dim = dim;
    t->opt = opt_kind;
    t->lr = lr;
    t->hp1 = hp1;
    t->hp2 = hp2;
    t->eps = eps;
    t->nesterov = nesterov;
    t->s1 = s1;
    t->s2 = s2;
    t->step = step;
    t->versions = versions;
  };
  Van* van = static_cast<Van*>(h);
  Table* existing = nullptr;
  {
    std::lock_guard<std::mutex> g(van->tables_mu);
    auto it = van->tables.find(key);
    if (it == van->tables.end()) {
      Table* t = new Table();
      fill(t);
      van->tables[key] = t;
      return;
    }
    existing = it->second;
  }
  // re-register updates IN PLACE under the table mutex, which is taken
  // AFTER releasing tables_mu: holding both here would ABBA-deadlock
  // against van_table_unlock (holds t->mu, then looks up via
  // tables_mu).  Tables are never deleted, so `existing` stays valid.
  std::lock_guard<std::mutex> tg(existing->mu);
  fill(existing);
}

// Back-compat shim: the original SGD-only registration entry point.
void van_register_sgd_table(void* h, uint32_t key, float* value,
                            int64_t nrows, int64_t dim, float lr,
                            int64_t* versions) {
  van_register_table(h, key, value, nrows, dim, kOptSGD, lr, 0.0f,
                     0.0f, 0.0f, 0, nullptr, nullptr, nullptr,
                     versions);
}

// Python paths touching a registered table's buffer coordinate here
void van_table_lock(void* h, uint32_t key) {
  Van* van = static_cast<Van*>(h);
  Table* t = nullptr;
  {
    std::lock_guard<std::mutex> g(van->tables_mu);
    auto it = van->tables.find(key);
    if (it == van->tables.end()) return;
    t = it->second;
  }
  t->mu.lock();
}

void van_table_unlock(void* h, uint32_t key) {
  Van* van = static_cast<Van*>(h);
  Table* t = nullptr;
  {
    std::lock_guard<std::mutex> g(van->tables_mu);
    auto it = van->tables.find(key);
    if (it == van->tables.end()) return;
    t = it->second;
  }
  t->mu.unlock();
}

void van_stop(void* h) {
  Van* van = static_cast<Van*>(h);
  if (!van->running.exchange(false)) return;
  if (van->listen_fd >= 0) ::shutdown(van->listen_fd, SHUT_RDWR);
  if (van->listen_fd >= 0) ::close(van->listen_fd);
  if (van->acceptor.joinable()) van->acceptor.join();
  {
    // unblock readers; their own close() runs at thread exit
    std::lock_guard<std::mutex> g(van->conns_mu);
    for (int fd : van->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  for (auto& th : van->conns)
    if (th.joinable()) th.join();
  van->conns.clear();
  van->conn_fds.clear();
  van->listen_fd = -1;
}

void van_destroy(void* h) {
  van_stop(h);
  delete static_cast<Van*>(h);
}

}  // extern "C"
