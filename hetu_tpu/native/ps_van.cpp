// Native PS "van": a C++ TCP serving loop for the sparse hot path.
//
// Reference: ps-lite's Van tier (ps-lite/src/zmq_van.h, p3_van.h) — the
// reference serves its KV traffic entirely from C++ threads; the Python
// PSServer here is the correctness/feature surface (full PSFunc API,
// SSP/BSP, cache sync), and this van is the THROUGHPUT tier for the one
// pattern that dominates CTR training: sparse push / pull / push-pull
// on embedding tables with a server-side optimizer.
//
// Design:
//   * the table's numpy buffer is REGISTERED (pointer + shape) — zero
//     serialization between the van and the Python-visible array;
//   * one acceptor thread + one thread per connection (worker counts
//     are small); blocking I/O, one reusable buffer per connection;
//   * binary little-endian framing (u32 len | u8 op | u32 key | u32 n |
//     i64 ids[n] | f32 rows[n*dim]); responses are (u32 len | u8 ok |
//     f32 rows...) — no Python, no pickle, no text on the wire;
//   * per-table mutex, also exported (van_table_lock/unlock) so Python
//     paths touching a registered table can coordinate;
//   * sequential scatter handles duplicate ids exactly like the Python
//     server's dedup-merge does for SGD (order-insensitive sum).
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -pthread ps_van.cpp

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <thread>
#include <unordered_set>
#include <vector>

namespace {

struct Table {
  float* value = nullptr;
  int64_t nrows = 0;
  int64_t dim = 0;
  float lr = 0.0f;           // server-side SGD step
  int64_t* versions = nullptr;  // optional HET version counters
  std::mutex mu;
};

struct Van {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> running{false};
  std::thread acceptor;
  std::vector<std::thread> conns;
  std::vector<int> conn_fds;        // for shutdown() at stop
  std::mutex conns_mu;
  std::map<uint32_t, Table*> tables;
  std::mutex tables_mu;
  ~Van() {
    for (auto& kv : tables) delete kv.second;
  }
};

bool read_exact(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

enum Op : uint8_t { kPush = 1, kPull = 2, kPushPull = 3 };

void serve_conn(Van* van, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  std::vector<char> buf;
  std::vector<char> out;
  while (van->running.load()) {
    uint32_t len = 0;
    if (!read_exact(fd, &len, 4)) break;
    if (len < 9 || len > (1u << 30)) break;   // 1 GiB frame cap
    buf.resize(len);
    if (!read_exact(fd, buf.data(), len)) break;
    uint8_t op = static_cast<uint8_t>(buf[0]);
    uint32_t key, n;
    std::memcpy(&key, buf.data() + 1, 4);
    std::memcpy(&n, buf.data() + 5, 4);
    Table* t = nullptr;
    {
      std::lock_guard<std::mutex> g(van->tables_mu);
      auto it = van->tables.find(key);
      if (it != van->tables.end()) t = it->second;
    }
    size_t ids_bytes = static_cast<size_t>(n) * 8;
    const int64_t* ids =
        reinterpret_cast<const int64_t*>(buf.data() + 9);
    bool ok = t != nullptr && 9 + ids_bytes <= len;
    if (ok) {
      // the WHOLE request — shape reads, bounds validation, scatter,
      // gather — runs under the table mutex: an in-place re-register
      // may change value/nrows/dim between any two of those steps
      std::lock_guard<std::mutex> g(t->mu);
      size_t row_bytes = static_cast<size_t>(n) * t->dim * 4;
      const float* rows =
          reinterpret_cast<const float*>(buf.data() + 9 + ids_bytes);
      if (op == kPush || op == kPushPull)
        ok = 9 + ids_bytes + row_bytes == len;
      else
        ok = 9 + ids_bytes == len;
      if (ok) {
        for (uint32_t i = 0; i < n; ++i)
          if (ids[i] < 0 || ids[i] >= t->nrows) { ok = false; break; }
      }
      uint32_t out_payload =
          ok && (op == kPull || op == kPushPull)
              ? static_cast<uint32_t>(row_bytes) : 0;
      out.resize(4 + 1 + out_payload);
      uint32_t out_len = 1 + out_payload;
      std::memcpy(out.data(), &out_len, 4);
      out[4] = ok ? 1 : 0;
      if (ok) {
        if (op == kPush || op == kPushPull) {
          const int64_t dim = t->dim;
          for (uint32_t i = 0; i < n; ++i) {
            float* dst = t->value + ids[i] * dim;
            const float* src = rows + static_cast<int64_t>(i) * dim;
            const float lr = t->lr;
            for (int64_t d = 0; d < dim; ++d) dst[d] -= lr * src[d];
          }
          if (t->versions != nullptr) {
            // one bump per UNIQUE id, matching the python tier's
            // ps_bump_versions dedup — HET staleness counters must not
            // diverge by tier
            std::unordered_set<int64_t> seen;
            seen.reserve(n);
            for (uint32_t i = 0; i < n; ++i)
              if (seen.insert(ids[i]).second) ++t->versions[ids[i]];
          }
        }
        if (op == kPull || op == kPushPull) {
          const int64_t dim = t->dim;
          float* dst = reinterpret_cast<float*>(out.data() + 5);
          for (uint32_t i = 0; i < n; ++i)
            std::memcpy(dst + static_cast<int64_t>(i) * dim,
                        t->value + ids[i] * dim, dim * 4);
        }
      }
    } else {
      out.resize(5);
      uint32_t out_len = 1;
      std::memcpy(out.data(), &out_len, 4);
      out[4] = 0;
    }
    if (!write_all(fd, out.data(), out.size())) break;
  }
  ::close(fd);
}

void accept_loop(Van* van) {
  while (van->running.load()) {
    int fd = ::accept(van->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (!van->running.load()) break;
      continue;
    }
    std::lock_guard<std::mutex> g(van->conns_mu);
    van->conn_fds.push_back(fd);
    van->conns.emplace_back(serve_conn, van, fd);
  }
}

}  // namespace

extern "C" {

void* van_create() { return new Van(); }

// 0 on failure; the bound port otherwise (pass port=0 for ephemeral)
int van_listen(void* h, int port) {
  Van* van = static_cast<Van*>(h);
  van->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (van->listen_fd < 0) return 0;
  int one = 1;
  ::setsockopt(van->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one,
               sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(van->listen_fd, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) != 0)
    return 0;
  if (::listen(van->listen_fd, 64) != 0) return 0;
  socklen_t alen = sizeof(addr);
  ::getsockname(van->listen_fd, reinterpret_cast<sockaddr*>(&addr),
                &alen);
  van->port = ntohs(addr.sin_port);
  van->running.store(true);
  van->acceptor = std::thread(accept_loop, van);
  return van->port;
}

void van_register_sgd_table(void* h, uint32_t key, float* value,
                            int64_t nrows, int64_t dim, float lr,
                            int64_t* versions) {
  Van* van = static_cast<Van*>(h);
  Table* existing = nullptr;
  {
    std::lock_guard<std::mutex> g(van->tables_mu);
    auto it = van->tables.find(key);
    if (it == van->tables.end()) {
      Table* t = new Table();
      t->value = value;
      t->nrows = nrows;
      t->dim = dim;
      t->lr = lr;
      t->versions = versions;
      van->tables[key] = t;
      return;
    }
    existing = it->second;
  }
  // re-register updates IN PLACE under the table mutex, which is taken
  // AFTER releasing tables_mu: holding both here would ABBA-deadlock
  // against van_table_unlock (holds t->mu, then looks up via
  // tables_mu).  Tables are never deleted, so `existing` stays valid.
  std::lock_guard<std::mutex> tg(existing->mu);
  existing->value = value;
  existing->nrows = nrows;
  existing->dim = dim;
  existing->lr = lr;
  existing->versions = versions;
}

// Python paths touching a registered table's buffer coordinate here
void van_table_lock(void* h, uint32_t key) {
  Van* van = static_cast<Van*>(h);
  Table* t = nullptr;
  {
    std::lock_guard<std::mutex> g(van->tables_mu);
    auto it = van->tables.find(key);
    if (it == van->tables.end()) return;
    t = it->second;
  }
  t->mu.lock();
}

void van_table_unlock(void* h, uint32_t key) {
  Van* van = static_cast<Van*>(h);
  Table* t = nullptr;
  {
    std::lock_guard<std::mutex> g(van->tables_mu);
    auto it = van->tables.find(key);
    if (it == van->tables.end()) return;
    t = it->second;
  }
  t->mu.unlock();
}

void van_stop(void* h) {
  Van* van = static_cast<Van*>(h);
  if (!van->running.exchange(false)) return;
  if (van->listen_fd >= 0) ::shutdown(van->listen_fd, SHUT_RDWR);
  if (van->listen_fd >= 0) ::close(van->listen_fd);
  if (van->acceptor.joinable()) van->acceptor.join();
  {
    // unblock readers; their own close() runs at thread exit
    std::lock_guard<std::mutex> g(van->conns_mu);
    for (int fd : van->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  for (auto& th : van->conns)
    if (th.joinable()) th.join();
  van->conns.clear();
  van->conn_fds.clear();
  van->listen_fd = -1;
}

void van_destroy(void* h) {
  van_stop(h);
  delete static_cast<Van*>(h);
}

}  // extern "C"
