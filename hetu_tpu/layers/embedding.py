"""Embedding layer (reference layers/embedding.py)."""

from .base import BaseLayer
from .. import initializers as init
from ..graph import embedding_lookup_op
from ..graph.ops_misc import PlaceholderOp


class Embedding(BaseLayer):
    def __init__(self, num_embeddings, embedding_dim, initializer=None,
                 name="embedding", ctx=None):
        self.embedding_table = PlaceholderOp(
            name + "_table",
            initializer=initializer or init.XavierNormalInit(
                (num_embeddings, embedding_dim)),
            trainable=True, ctx=ctx)
        self.embedding_table.is_embed = True

    def __call__(self, x):
        return embedding_lookup_op(self.embedding_table, x)
