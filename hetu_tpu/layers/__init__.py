"""Layer toolkit over the op factories (reference python/hetu/layers/)."""

from .base import BaseLayer, Sequence
from .linear import Linear
from .conv import Conv2d
from .norm import BatchNorm, LayerNorm
from .dropout import DropOut
from .activations import Relu, Gelu, Tanh, Sigmoid
from .embedding import Embedding
from .pooling import MaxPool2d, AvgPool2d
from .reshape import Reshape
from .moe import Expert, MoELayer, StackedExperts, TopKGate, HashGate, \
    KTop1Gate, SAMGate, BalanceGate
from .attention import MultiHeadAttention
