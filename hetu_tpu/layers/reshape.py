"""Reshape layer (reference layers/reshape.py)."""

from .base import BaseLayer
from ..graph import array_reshape_op


class Reshape(BaseLayer):
    def __init__(self, shape):
        self.shape = shape

    def __call__(self, x):
        return array_reshape_op(x, self.shape)
