"""Reshape layer (reference layers/reshape.py) + small shape helpers."""

from .base import BaseLayer
from ..graph import array_reshape_op
from ..graph.node import SimpleOp


class Reshape(BaseLayer):
    def __init__(self, shape):
        self.shape = shape

    def __call__(self, x):
        return array_reshape_op(x, self.shape)


def lens_to_additive_mask(kv_lens, seq_len):
    """[B] int lengths -> additive (B, 1, 1, S) mask (0 where live,
    NEG_INF where padded) for the unfused attention path."""
    import jax.numpy as jnp
    from ..kernels.flash_attention import NEG_INF

    def fn(lens):
        live = jnp.arange(seq_len)[None, :] < lens[:, None]
        return jnp.where(live, 0.0, NEG_INF).astype(jnp.float32)[
            :, None, None, :]

    return SimpleOp(fn, kv_lens, name="LensMask")


def zero_empty_rows(ctxv, kv_lens, seq_len):
    """Zero the attention context of fully-padded sequences (kv_lens==0):
    an all-masked softmax degenerates to uniform weights, which would
    leak a mean-of-V output (and grads) out of empty rows — the flash
    kernel emits exactly 0 there, and both paths must agree."""
    import jax.numpy as jnp

    def fn(c, lens):
        live = jnp.repeat(lens > 0, seq_len).astype(c.dtype)
        return c * live[:, None]

    return SimpleOp(fn, ctxv, kv_lens, name="ZeroEmptyRows")
