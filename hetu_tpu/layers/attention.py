"""Multi-head attention layer composed from the op surface.

The reference has no attention layer class (BERT builds attention inline in
examples/nlp/bert/hetu_bert.py); we provide one because transformer models
are first-class here.  This graph-level layer stays op-compositional so it
works under every executor mode; a fused flash-attention Pallas kernel
(hetu_tpu.kernels) replaces the softmax(QK^T)V chain where available.
"""

from __future__ import annotations

import math

from .base import BaseLayer
from .. import initializers as init
from ..graph import (
    matmul_op, batch_matmul_op, array_reshape_op, transpose_op, softmax_op,
    mul_byconst_op, broadcastto_op, dropout_op, linear_op,
    concatenate_op, slice_op,
)


class MultiHeadAttention(BaseLayer):
    """Multi-head attention with an optional fused Pallas flash path.

    ``use_flash`` guidance (measured on the v5e, round 3): the fused
    kernel wins from seq ~1024 up (1.8x at 4k, 2.4x at 8k causal, and
    it is what makes 32k trainable); at seq 512 XLA's batched attention
    measured ~8% FASTER fwd+bwd — the kernel's per-block matmuls
    contract over only head_dim while the probs traffic it saves is
    ~1 ms/layer.  Default block sizes follow the on-chip calibration
    (CALIBRATION_TPU.json flash_blocks)."""

    def __init__(self, hidden_size, num_heads, seq_len, batch_size,
                 dropout_rate=0.0, initializer=None, name="attn",
                 use_flash=False, causal=False, block_q=512, block_k=1024,
                 fused_qkv=True):
        assert hidden_size % num_heads == 0
        self.h = hidden_size
        self.nh = num_heads
        self.hd = hidden_size // num_heads
        self.seq = seq_len
        # batch_size is accepted for API parity but no longer baked into
        # the graph: reshapes use -1 so the layer works at any (local)
        # batch, e.g. inside a dp-sharded shard_map pipeline body
        self.keep_prob = 1.0 - dropout_rate
        self.name = name
        self.use_flash = use_flash
        self.causal = causal
        self.block_q = block_q
        self.block_k = block_k
        ini = initializer or init.GenXavierUniform()
        self.wq = ini(shape=(self.h, self.h), name=name + "_q_weight")
        self.wk = ini(shape=(self.h, self.h), name=name + "_k_weight")
        self.wv = ini(shape=(self.h, self.h), name=name + "_v_weight")
        self.wo = ini(shape=(self.h, self.h), name=name + "_proj_weight")
        self.bq = init.zeros((self.h,), name=name + "_q_bias")
        self.bk = init.zeros((self.h,), name=name + "_k_bias")
        self.bv = init.zeros((self.h,), name=name + "_v_bias")
        self.bo = init.zeros((self.h,), name=name + "_proj_bias")
        self.fused_qkv = fused_qkv

    def _qkv(self, x):
        """(q, k, v) projections of x, each [B*S, H].

        fused_qkv: ONE [N,H]@[H,3H] matmul on a concat of the three
        weights, sliced back into q/k/v — bitwise the same math as three
        matmuls (each output column block accumulates over the same
        contraction), same parameter names/checkpoints, but a single
        larger MXU call."""
        if not self.fused_qkv:
            return (linear_op(x, self.wq, self.bq),
                    linear_op(x, self.wk, self.bk),
                    linear_op(x, self.wv, self.bv))
        if not hasattr(self, "_qkv_concat"):
            self._qkv_concat = (
                concatenate_op([self.wq, self.wk, self.wv], axis=1),
                concatenate_op([self.bq, self.bk, self.bv], axis=0))
        w, b = self._qkv_concat
        qkv = linear_op(x, w, b)
        return (slice_op(qkv, [0, 0], [-1, self.h]),
                slice_op(qkv, [0, self.h], [-1, self.h]),
                slice_op(qkv, [0, 2 * self.h], [-1, self.h]))

    def _causal_mask(self):
        # built in-trace (iota comparisons) rather than stored as a
        # Variable: an SxS float triangle per layer would be donated
        # through every step and serialized into every checkpoint
        node = getattr(self, "_causal_mask_node", None)
        if node is None:
            from ..graph.ops_attention import causal_mask_op
            node = self._causal_mask_node = causal_mask_op(self.seq)
        return node

    def _split_heads(self, x):
        # (B*S, H) -> (B, nh, S, hd).  -1 keeps the batch dim symbolic:
        # under a dp-sharded shard_map (e.g. the SPMD pipeline body) the
        # layer sees the LOCAL batch, so baking batch_size would break.
        x = array_reshape_op(x, [-1, self.seq, self.nh, self.hd])
        return transpose_op(x, [0, 2, 1, 3])

    def __call__(self, x, attention_mask=None, kv_lens=None):
        """x: (B*S, H) flattened hidden states; mask: additive (B,1,1,S).
        ``kv_lens``: [B] int node of valid key/value lengths — the
        BERT-style padding mask in the form the flash kernel consumes
        (mutually exclusive with ``attention_mask``)."""
        assert attention_mask is None or kv_lens is None, (
            "pass either an additive attention_mask or kv_lens, not both")
        if self.use_flash and attention_mask is None \
                and self.keep_prob == 1.0:
            from ..graph.ops_attention import flash_attention_op
            # [B*S, H] -> [B, S, nh, hd] (kernel layout)
            def bshd(node):
                return array_reshape_op(
                    node, [-1, self.seq, self.nh, self.hd])
            qp, kp, vp = self._qkv(x)
            q, k, v = bshd(qp), bshd(kp), bshd(vp)
            o = flash_attention_op(q, k, v, causal=self.causal,
                                   kv_lens=kv_lens,
                                   block_q=self.block_q,
                                   block_k=self.block_k)
            o = array_reshape_op(o, [-1, self.h])
            return linear_op(o, self.wo, self.bo)
        if kv_lens is not None:
            # unfused fallback: lens -> additive (B, 1, 1, S) mask
            from .reshape import lens_to_additive_mask
            attention_mask = lens_to_additive_mask(kv_lens, self.seq)
        qp, kp, vp = self._qkv(x)
        q = self._split_heads(qp)
        k = self._split_heads(kp)
        v = self._split_heads(vp)
        scores = batch_matmul_op(q, k, trans_B=True)
        scores = mul_byconst_op(scores, 1.0 / math.sqrt(self.hd))
        if self.causal:
            # the flash path masks inside the kernel; the unfused chain
            # needs the explicit additive triangle
            scores = scores + broadcastto_op(self._causal_mask(), scores)
        if attention_mask is not None:
            scores = scores + broadcastto_op(attention_mask, scores)
        probs = softmax_op(scores)
        if self.keep_prob < 1.0:
            probs = dropout_op(probs, self.keep_prob)
        ctxv = batch_matmul_op(probs, v)  # (B, nh, S, hd)
        ctxv = transpose_op(ctxv, [0, 2, 1, 3])
        ctxv = array_reshape_op(ctxv, [-1, self.h])
        if kv_lens is not None:
            from .reshape import zero_empty_rows
            ctxv = zero_empty_rows(ctxv, kv_lens, self.seq)
        return linear_op(ctxv, self.wo, self.bo)
