"""Normalization layers (reference layers/normalization.py)."""

from .base import BaseLayer
from .. import initializers as init
from ..graph import batch_normalization_op, layer_normalization_op


class BatchNorm(BaseLayer):
    def __init__(self, num_channels, momentum=0.99, eps=0.01, name="batchnorm"):
        self.scale = init.ones((num_channels,), name=name + "_scale")
        self.bias = init.zeros((num_channels,), name=name + "_bias")
        self.momentum = momentum
        self.eps = eps

    def __call__(self, x):
        return batch_normalization_op(x, self.scale, self.bias,
                                      momentum=self.momentum, eps=self.eps)


class LayerNorm(BaseLayer):
    def __init__(self, num_channels, eps=1e-5, name="layernorm"):
        self.scale = init.ones((num_channels,), name=name + "_scale")
        self.bias = init.zeros((num_channels,), name=name + "_bias")
        self.eps = eps

    def __call__(self, x):
        return layer_normalization_op(x, self.scale, self.bias, eps=self.eps)
