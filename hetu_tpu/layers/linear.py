"""Linear layer (reference layers/linear.py)."""

from .base import BaseLayer
from .. import initializers as init
from ..graph import matmul_op, linear_op


class Linear(BaseLayer):
    def __init__(self, in_features, out_features,
                 initializer=None, bias=True, activation=None,
                 name="linear"):
        self.in_features = in_features
        self.out_features = out_features
        self.initializer = initializer or init.XavierUniformInit(
            (in_features, out_features))
        self.bias = bias
        self.activation = activation
        self.name = name
        from ..graph.ops_misc import PlaceholderOp
        self.weight_var = PlaceholderOp(
            name + "_weight", initializer=self.initializer, trainable=True)
        if bias:
            self.bias_var = init.zeros((out_features,), name=name + "_bias")

    def __call__(self, x):
        if self.bias:
            out = linear_op(x, self.weight_var, self.bias_var)
        else:
            out = matmul_op(x, self.weight_var)
        if self.activation is not None:
            out = self.activation(out)
        return out
