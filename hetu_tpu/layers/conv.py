"""Conv layer (reference layers/conv.py)."""

from .base import BaseLayer
from .. import initializers as init
from ..graph import conv2d_op, conv2d_add_bias_op
from ..graph.ops_misc import PlaceholderOp


class Conv2d(BaseLayer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, initializer=None, bias=True, activation=None,
                 name="conv2d"):
        if not isinstance(kernel_size, (list, tuple)):
            kernel_size = (kernel_size, kernel_size)
        self.stride = stride
        self.padding = padding
        self.activation = activation
        self.name = name
        shape = (out_channels, in_channels) + tuple(kernel_size)
        self.weight_var = PlaceholderOp(
            name + "_weight",
            initializer=initializer or init.HeNormalInit(shape),
            trainable=True)
        self.bias = bias
        if bias:
            self.bias_var = init.zeros((out_channels,), name=name + "_bias")

    def __call__(self, x):
        if self.bias:
            out = conv2d_add_bias_op(x, self.weight_var, self.bias_var,
                                     stride=self.stride, padding=self.padding)
        else:
            out = conv2d_op(x, self.weight_var, stride=self.stride,
                            padding=self.padding)
        if self.activation is not None:
            out = self.activation(out)
        return out
