"""Dropout layer (reference layers/dropout.py)."""

from .base import BaseLayer
from ..graph import dropout_op


class DropOut(BaseLayer):
    def __init__(self, p=0.5):
        self.keep_prob = 1.0 - p

    def __call__(self, x):
        return dropout_op(x, self.keep_prob)
