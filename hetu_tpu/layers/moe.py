"""MoE layers: Expert, MoELayer, and the gate family.

Reference: layers/moe_layer.py (Expert :6-44, MoELayer :45-133) and
layers/TopGate.py (topkgating :14-54, TopKGate :56-78, HashGate,
KTop1Gate, SAMGate, BalanceGate).  Graph structure preserved: gate ->
layout_transform capacity dispatch -> alltoall over 'ep' -> per-local-expert
FFN -> alltoall back -> reverse_layout_transform weighted combine.
"""

from __future__ import annotations

import math

import numpy as np

from .base import BaseLayer
from .. import initializers as init
from ..graph import (
    softmax_op, topk_idx_op, split_op, one_hot_op, array_reshape_op,
    cumsum_with_bias_op, reduce_sum_op, reduce_mean_op, reducesumaxiszero_op,
    mul_op, matmul_op, broadcastto_op, concatenate_op, relu_op, gelu_op,
    mul_byconst_op,
    indexing_op, scatter1d_op, addbyconst_op, add_op,
)
from ..graph.ops_misc import Variable
from ..graph.ops_moe import (
    layout_transform_op, reverse_layout_transform_op, alltoall_op,
    halltoall_op, balance_assignment_op, group_topk_idx_op, sam_group_sum_op,
    sam_max_op,
)


def balance_loss(gates, mask, num_experts):
    """Aux load-balance loss (reference TopGate.py:6-12)."""
    me = reduce_mean_op(gates, axes=0)
    ce = reduce_mean_op(mask, axes=0)
    return mul_byconst_op(reducesumaxiszero_op(me * ce), float(num_experts))


def topkgating(logits, k, capacity_factor, num_tokens, num_experts, embed_dim):
    """Top-k gating with static capacity (reference TopGate.py:14-54).
    Returns (l_aux, indices_s, location_s, gates_s, capacity)."""
    gates = softmax_op(logits)
    capacity = k * math.ceil((num_tokens / num_experts) * capacity_factor)
    topk_indices = topk_idx_op(gates, topk=k)
    indices_s = [split_op(topk_indices, axes=[1], indices=[i], splits=[k])
                 for i in range(k)]
    mask_topk = [array_reshape_op(
        one_hot_op(indices_s[i], num_classes=num_experts), [-1, num_experts])
        for i in range(k)]

    l_aux = balance_loss(gates, mask_topk[0], num_experts)

    locations1 = cumsum_with_bias_op(mask_topk[0], bias=-1, dim=0)
    location_s = [reduce_sum_op(locations1 * mask_topk[0], axes=1)]

    acc_base = None
    for i in range(1, k):
        inc = reduce_sum_op(mask_topk[i - 1], axes=0, keepdims=True)
        acc_base = inc if acc_base is None else acc_base + inc
        locations2 = cumsum_with_bias_op(mask_topk[i], bias=-1, dim=0)
        locations2 = locations2 + broadcastto_op(acc_base, locations2)
        location_s.append(reduce_sum_op(locations2 * mask_topk[i], axes=1))
        l_aux = l_aux + balance_loss(gates, mask_topk[i], num_experts)

    gates_s = [reduce_sum_op(mul_op(gates, m), axes=1) for m in mask_topk]
    return l_aux, indices_s, location_s, gates_s, capacity


class TopKGate(BaseLayer):
    """reference TopGate.py:56-78."""

    def __init__(self, embed_dim, num_tokens, num_experts, k=1,
                 capacity_factor=1.0, eval_capacity_factor=1.0,
                 initializer=None, name="TopK_Gate"):
        self.embed_dim = embed_dim
        self.num_experts = num_experts
        self.top_k = k
        self.num_tokens = num_tokens
        self.capacity_factor = capacity_factor
        self.initializer = initializer or init.GenXavierUniform()
        self.name = name
        # params created once here (not per __call__) so the gate is
        # shared across train/eval subgraphs
        self.weight = self.initializer(
            shape=(self.embed_dim, self.num_experts),
            name=self.name + "_linear_weight")
        self.bias = init.zeros(shape=(1, self.num_experts),
                               name=self.name + "_linear_bias")

    def __call__(self, x):
        logits = matmul_op(x, self.weight)
        logits = logits + broadcastto_op(self.bias, logits)
        return topkgating(logits, self.top_k, self.capacity_factor,
                          self.num_tokens, self.num_experts, self.embed_dim)


class HashGate(BaseLayer):
    """Deterministic hash routing (reference TopGate.py HashGate): expert =
    token_id mod num_experts; gates are 1."""

    def __init__(self, embed_dim, num_tokens, num_experts,
                 capacity_factor=1.0, name="Hash_Gate"):
        self.num_tokens = num_tokens
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.name = name
        idx_val = (np.arange(num_tokens) % num_experts).astype(
            np.float32).reshape(-1, 1)
        self.indices = Variable(name + "_hash_idx", value=idx_val,
                                trainable=False)
        self.ones = Variable(name + "_ones",
                             value=np.ones((num_tokens,), np.float32),
                             trainable=False)

    def __call__(self, x):
        n, e = self.num_tokens, self.num_experts
        capacity = math.ceil((n / e) * self.capacity_factor)
        mask = array_reshape_op(one_hot_op(self.indices, num_classes=e),
                                [-1, e])
        locations = cumsum_with_bias_op(mask, bias=-1, dim=0)
        location_s = [reduce_sum_op(locations * mask, axes=1)]
        return None, [self.indices], location_s, [self.ones], capacity


class KTop1Gate(BaseLayer):
    """Grouped top-1 gating (reference TopGate.py KTop1Gate): pick the top
    group by aggregate mass, then top-1 expert inside the group."""

    def __init__(self, embed_dim, num_tokens, num_experts, num_local_gpus=8,
                 capacity_factor=1.0, initializer=None, name="KTop1_Gate"):
        self.embed_dim = embed_dim
        self.num_tokens = num_tokens
        self.num_experts = num_experts
        self.num_local_gpus = num_local_gpus
        self.capacity_factor = capacity_factor
        self.initializer = initializer or init.GenXavierUniform()
        self.name = name
        self.weight = self.initializer(shape=(embed_dim, num_experts),
                                       name=name + "_linear_weight")

    def __call__(self, x):
        e = self.num_experts
        logits = matmul_op(x, self.weight)
        gates = softmax_op(logits)
        group_mass = sam_group_sum_op(gates, self.num_local_gpus)
        top1_group = topk_idx_op(group_mass, topk=1)
        group_size = e // self.num_local_gpus
        idx = group_topk_idx_op(gates, top1_group, topk=1,
                                num_local_gpus=group_size)
        capacity = math.ceil(
            (self.num_tokens / e) * self.capacity_factor)
        mask = array_reshape_op(one_hot_op(idx, num_classes=e), [-1, e])
        l_aux = balance_loss(gates, mask, e)
        locations = cumsum_with_bias_op(mask, bias=-1, dim=0)
        location_s = [reduce_sum_op(locations * mask, axes=1)]
        gates_s = [reduce_sum_op(mul_op(gates, mask), axes=1)]
        return l_aux, [idx], location_s, gates_s, capacity


class SAMGate(KTop1Gate):
    """SAM gate (reference TopGate.py SAMGate + SamMax kernels): grouped
    top-1 with margin-based re-weighting of out-of-group experts."""

    def __call__(self, x):
        e = self.num_experts
        logits = matmul_op(x, self.weight)
        gates = softmax_op(logits)
        group_mass = sam_group_sum_op(gates, self.num_local_gpus)
        top1_group = topk_idx_op(group_mass, topk=1)
        group_size = e // self.num_local_gpus
        idx = group_topk_idx_op(gates, top1_group, topk=1,
                                num_local_gpus=group_size)
        margin = sam_max_op(gates, top1_group, idx, group_size)
        capacity = math.ceil((self.num_tokens / e) * self.capacity_factor)
        mask = array_reshape_op(one_hot_op(idx, num_classes=e), [-1, e])
        l_aux = balance_loss(gates, mask, e) + reduce_mean_op(
            reduce_sum_op(margin, axes=1), axes=0)
        locations = cumsum_with_bias_op(mask, bias=-1, dim=0)
        location_s = [reduce_sum_op(locations * mask, axes=1)]
        gates_s = [reduce_sum_op(mul_op(gates, mask), axes=1)]
        return l_aux, [idx], location_s, gates_s, capacity


class BalanceGate(BaseLayer):
    """Optimal balanced assignment gate (reference moe_layer.py:95-133):
    auction-solve a token->expert assignment with perfectly even load."""

    def __init__(self, embed_dim, num_tokens, num_experts, initializer=None,
                 name="Balance_Gate"):
        self.embed_dim = embed_dim
        self.num_tokens = num_tokens
        self.num_experts = num_experts
        self.initializer = initializer or init.GenXavierUniform()
        self.name = name
        self.centroid = self.initializer(
            shape=(embed_dim, num_experts), name=name + "_centroid")

    def __call__(self, x):
        scores = matmul_op(x, self.centroid)
        indice = balance_assignment_op(scores)
        return indice, self.centroid


class Expert(BaseLayer):
    """Two-matmul FFN expert (reference moe_layer.py:6-44)."""

    def __init__(self, embed_dim, ffn_dim, dropout_rate=0.0, initializer=None,
                 bias=False, activation=None, name="expert"):
        self.embed_dim = embed_dim
        self.ffn_dim = ffn_dim
        self.keep_prob = 1 - dropout_rate
        self.bias = bias
        if isinstance(activation, str):
            activation = {"relu": relu_op, "gelu": gelu_op}[activation]
        self.activation = activation
        self.initializer = initializer or init.GenXavierUniform()
        self.name = name
        self.w1 = self.initializer(shape=(embed_dim, ffn_dim),
                                   name=name + "_weight_1")
        self.w2 = self.initializer(shape=(ffn_dim, embed_dim),
                                   name=name + "_weight_2")

    def __call__(self, x):
        x = array_reshape_op(x, [-1, self.embed_dim])
        x = matmul_op(x, self.w1)
        if self.activation is not None:
            x = self.activation(x)
        x = matmul_op(x, self.w2)
        return x


class StackedExperts(BaseLayer):
    """All experts as stacked weights [E, D, F] — the expert-parallel
    formulation: one batched einsum instead of a per-expert python loop,
    with the leading expert dim sharded over the 'ep' mesh axis
    (ExpertParallel matches the '*expert*' names + leading dim).  GSPMD
    partitions the expert matmuls by expert and materializes the token
    redistribution (all-to-all) at the alltoall_op markers.

    Mirrors the math of reference moe_layer.py:6-44 Expert (two matmuls,
    optional activation) batched over experts."""

    def __init__(self, num_experts, embed_dim, ffn_dim, activation=None,
                 initializer=None, name="experts"):
        self.num_experts = int(num_experts)
        self.embed_dim = embed_dim
        self.ffn_dim = ffn_dim
        if isinstance(activation, str):
            activation = {"relu": relu_op, "gelu": gelu_op}[activation]
        self.activation = activation
        ini = initializer or init.GenXavierUniform()
        self.w1 = ini(shape=(self.num_experts, embed_dim, ffn_dim),
                      name=name + "_expert_stack_w1")
        self.w2 = ini(shape=(self.num_experts, ffn_dim, embed_dim),
                      name=name + "_expert_stack_w2")

    def __call__(self, x):
        """x: [E, cap, D] -> [E, cap, D]."""
        from ..graph import batch_matmul_op
        h = batch_matmul_op(x, self.w1)
        if self.activation is not None:
            h = self.activation(h)
        return batch_matmul_op(h, self.w2)


class MoELayer(BaseLayer):
    """reference moe_layer.py:45-133 (both 'MoELayer' and
    'BalanceAssignmentLayer' modes).  Pass ``experts=StackedExperts(...)``
    for the expert-parallel (mesh-shardable) formulation."""

    def __init__(self, gate=None, experts=None, num_tokens=None,
                 embed_dim=None, all2all_size=None, name="MoELayer",
                 device_id=None, top=None, hierarchical=False):
        self.name = name
        self.gate = gate
        self.experts = experts
        self.stacked = experts if isinstance(experts, StackedExperts) \
            else None
        if self.stacked is not None:
            assert all2all_size in (None, 1), (
                "StackedExperts already hold the GLOBAL expert set; "
                "all2all_size only applies to the per-local-expert list "
                "formulation")
        self.num_local_experts = (self.stacked.num_experts
                                  if self.stacked else len(experts))
        self.num_tokens = num_tokens
        self.embed_dim = embed_dim
        self.all2all_size = all2all_size or 1
        self.top = top
        self.hierarchical = hierarchical
        if name == "BalanceAssignmentLayer":
            self.arange_array = Variable(
                "arange_array",
                value=np.arange(num_tokens).astype(np.float32),
                trainable=False)

    def _a2a(self, x):
        if self.hierarchical:
            return halltoall_op(x)
        return alltoall_op(x)

    def __call__(self, x):
        if self.name == "BalanceAssignmentLayer":
            return self._balance_forward(x)
        if self.stacked is not None:
            return self._stacked_forward(x)
        reshaped = array_reshape_op(x, [-1, self.embed_dim])
        l_aux, indices_s, location_s, gates_s, capacity = self.gate(reshaped)
        total_experts = self.num_local_experts * self.all2all_size
        dispatched = layout_transform_op(
            reshaped, indices_s, location_s, capacity, total_experts)
        dispatched = self._a2a(dispatched)
        dispatched = array_reshape_op(
            dispatched,
            [self.all2all_size, self.num_local_experts, -1, self.embed_dim])
        outputs = []
        for i in range(self.num_local_experts):
            token_i = split_op(dispatched, axes=[1], indices=[i],
                               splits=[self.num_local_experts])
            outputs.append(self.experts[i](token_i))
        expert_output = concatenate_op(outputs, axis=0)
        expert_output = self._a2a(expert_output)
        expert_output = array_reshape_op(expert_output, [-1, self.embed_dim])
        combined = reverse_layout_transform_op(
            expert_output, indices_s, location_s, gates_s, capacity,
            total_experts)
        return combined, l_aux

    def _stacked_forward(self, x):
        """Expert-parallel path: dispatch -> a2a -> batched expert FFN ->
        a2a -> combine.  The a2a markers pin expert-major sharding over
        'ep' (or ('ici','dcn') when hierarchical), forcing GSPMD to emit
        the token exchange there; under shard_map they run lax.all_to_all
        (reference moe_layer.py:74 alltoall placement)."""
        reshaped = array_reshape_op(x, [-1, self.embed_dim])
        l_aux, indices_s, location_s, gates_s, capacity = self.gate(reshaped)
        total_experts = self.stacked.num_experts
        dispatched = layout_transform_op(
            reshaped, indices_s, location_s, capacity, total_experts)
        d = array_reshape_op(
            dispatched, [total_experts, capacity, self.embed_dim])
        d = self._a2a(d)
        h = self.stacked(d)                       # [E, cap, D]
        h = self._a2a(h)
        expert_output = array_reshape_op(h, [-1, self.embed_dim])
        combined = reverse_layout_transform_op(
            expert_output, indices_s, location_s, gates_s, capacity,
            total_experts)
        return combined, l_aux

    def _balance_forward(self, x):
        reshaped = array_reshape_op(x, [-1, self.embed_dim])
        # indice is a permutation of token ids: per-expert contiguous blocks
        # of N/E tokens each (balance_assignment_op output parity)
        indice, centroid = self.gate(reshaped)
        reverse_indice = scatter1d_op(self.arange_array, indice,
                                      self.arange_array)
        routed_input = indexing_op(reshaped, indice)
        routed_input = self._a2a(routed_input)
        reshaped_routed = array_reshape_op(
            routed_input,
            [self.all2all_size, self.num_local_experts, -1, self.embed_dim])
        outputs = []
        for i in range(self.num_local_experts):
            token_i = split_op(reshaped_routed, axes=[1], indices=[i],
                               splits=[self.num_local_experts])
            outputs.append(self.experts[i](token_i))
        expert_output = concatenate_op(outputs, axis=0)
        # routed position j belongs to expert j // capacity
        e_total = self.num_experts_total()
        cap = self.num_tokens // e_total
        expert_of_pos = Variable(
            f"{self.name}_expert_of_pos",
            value=np.eye(e_total, dtype=np.float32)[
                np.repeat(np.arange(e_total), cap)],
            trainable=False)
        alpha = softmax_op(matmul_op(routed_input, centroid))
        alpha_sel = reduce_sum_op(mul_op(alpha, expert_of_pos), axes=1)
        w = broadcastto_op(array_reshape_op(alpha_sel, [-1, 1]), expert_output)
        final = w * expert_output + (1.0 - w) * routed_input
        final = indexing_op(final, reverse_indice)
        final = self._a2a(final)
        return final

    def num_experts_total(self):
        return self.num_local_experts * self.all2all_size
