"""Pooling layers (reference layers/pooling.py)."""

from .base import BaseLayer
from ..graph import max_pool2d_op, avg_pool2d_op


class MaxPool2d(BaseLayer):
    def __init__(self, kernel_size, stride=1, padding=0):
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding

    def __call__(self, x):
        return max_pool2d_op(x, self.kernel_size, self.kernel_size,
                             padding=self.padding, stride=self.stride)


class AvgPool2d(BaseLayer):
    def __init__(self, kernel_size, stride=1, padding=0):
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding

    def __call__(self, x):
        return avg_pool2d_op(x, self.kernel_size, self.kernel_size,
                             padding=self.padding, stride=self.stride)
