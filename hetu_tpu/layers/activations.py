"""Activation layers (reference layers/activations.py)."""

from .base import BaseLayer
from ..graph import relu_op, gelu_op, tanh_op, sigmoid_op


class Relu(BaseLayer):
    def __call__(self, x):
        return relu_op(x)


class Gelu(BaseLayer):
    def __call__(self, x):
        return gelu_op(x)


class Tanh(BaseLayer):
    def __call__(self, x):
        return tanh_op(x)


class Sigmoid(BaseLayer):
    def __call__(self, x):
        return sigmoid_op(x)
