"""Layer base (reference python/hetu/layers/base.py)."""


class BaseLayer(object):
    def __call__(self, *args, **kwargs):
        raise NotImplementedError

    def make_name(self, default):
        return getattr(self, "name", None) or default


class Sequence(BaseLayer):
    def __init__(self, *layers):
        self.layers = layers

    def __call__(self, x):
        for layer in self.layers:
            x = layer(x)
        return x
