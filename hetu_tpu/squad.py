"""SQuAD processor + span-extraction metrics.

Reference surface: the BERT example suite stages SQuAD v1.1/v2.0
(examples/nlp/bert/data/SquadDownloader.py:1, data/bertPrep.py:1 —
download + shard only; the feature/eval shapes below follow the
published SQuAD recipe those files feed).  This module is the
counterpart of glue.py for span prediction:

* ``read_squad_examples`` parses the official JSON into whitespace
  doc tokens with char→word offsets;
* ``convert_examples_to_features`` encodes sliding windows
  ([CLS] question [SEP] context-span [SEP]) with doc-stride overlap,
  wordpiece-refined answer spans, and window-relative start/end
  positions (0 = CLS when the answer falls outside the window);
* ``features_to_arrays`` emits the dense [N, S] numpy arrays the
  ``BertForQuestionAnswering`` head feeds;
* ``extract_predictions`` maps (start_logits, end_logits) back to
  answer text through the n-best span search;
* ``squad_evaluate`` scores predictions with the official
  normalization (lowercase, strip articles/punctuation) → EM / F1.
"""

from __future__ import annotations

import collections
import json
import re
import string

import numpy as np


def _is_whitespace(c):
    return c in " \t\r\n" or ord(c) == 0x202F


class SquadExample:
    """One question over one paragraph, tokenized at whitespace level."""

    __slots__ = ("qas_id", "question_text", "doc_tokens",
                 "orig_answer_text", "start_position", "end_position",
                 "is_impossible", "answers")

    def __init__(self, qas_id, question_text, doc_tokens,
                 orig_answer_text=None, start_position=None,
                 end_position=None, is_impossible=False, answers=()):
        self.qas_id = qas_id
        self.question_text = question_text
        self.doc_tokens = doc_tokens
        self.orig_answer_text = orig_answer_text
        self.start_position = start_position
        self.end_position = end_position
        self.is_impossible = is_impossible
        self.answers = list(answers)       # all gold texts (dev eval)


def read_squad_examples(path_or_data, is_training=True):
    """Official SQuAD JSON → SquadExamples.  ``is_training`` selects
    whether gold spans are required and char-aligned; v2.0's
    ``is_impossible`` entries get the (0, 0) null span."""
    if isinstance(path_or_data, (str, bytes)):
        with open(path_or_data, "r", encoding="utf-8") as f:
            data = json.load(f)
    else:
        data = path_or_data
    examples = []
    for entry in data["data"]:
        for para in entry["paragraphs"]:
            text = para["context"]
            doc_tokens = []
            char_to_word = []
            prev_ws = True
            for c in text:
                if _is_whitespace(c):
                    prev_ws = True
                else:
                    if prev_ws:
                        doc_tokens.append(c)
                    else:
                        doc_tokens[-1] += c
                    prev_ws = False
                char_to_word.append(len(doc_tokens) - 1)
            for qa in para["qas"]:
                start = end = None
                orig_answer = None
                impossible = bool(qa.get("is_impossible", False))
                answers = [a["text"] for a in qa.get("answers", [])]
                if is_training:
                    if impossible or not qa["answers"]:
                        start = end = 0 if impossible else None
                        if not impossible:
                            continue     # unanswerable in a v1.1 file
                        orig_answer = ""
                    else:
                        a = qa["answers"][0]
                        orig_answer = a["text"]
                        a_start = a["answer_start"]
                        start = char_to_word[a_start]
                        end = char_to_word[a_start + len(orig_answer) - 1]
                        # drop misaligned annotations (official recipe
                        # logs and skips when the span text mismatches)
                        actual = " ".join(doc_tokens[start:end + 1])
                        cleaned = " ".join(orig_answer.strip().split())
                        if cleaned not in actual:
                            continue
                examples.append(SquadExample(
                    qa["id"], qa["question"], doc_tokens, orig_answer,
                    start, end, impossible, answers))
    return examples


class SquadFeatures:
    """One max_seq_length window over one example."""

    __slots__ = ("unique_id", "example_index", "doc_span_index",
                 "tokens", "token_to_orig_map", "token_is_max_context",
                 "input_ids", "input_mask", "segment_ids",
                 "start_position", "end_position")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw[k])


def _improve_answer_span(doc_tokens, start, end, tokenizer, orig_text):
    """Wordpiece-tighten the span: the char-aligned whitespace span may
    include trailing punctuation the gold answer lacks."""
    tok_answer = " ".join(tokenizer.tokenize(orig_text))
    for new_start in range(start, end + 1):
        for new_end in range(end, new_start - 1, -1):
            span = " ".join(doc_tokens[new_start:new_end + 1])
            if span == tok_answer:
                return new_start, new_end
    return start, end


def _check_is_max_context(doc_spans, cur_index, position):
    """A token appearing in several overlapping windows scores only in
    the one where it has the most surrounding context."""
    best_score, best_index = None, None
    for i, (span_start, span_len) in enumerate(doc_spans):
        end = span_start + span_len - 1
        if position < span_start or position > end:
            continue
        score = (min(position - span_start, end - position)
                 + 0.01 * span_len)
        if best_score is None or score > best_score:
            best_score, best_index = score, i
    return best_index == cur_index


def convert_examples_to_features(examples, tokenizer, max_seq_length=384,
                                 doc_stride=128, max_query_length=64,
                                 is_training=True):
    features = []
    unique_id = 1000000000
    for ex_index, ex in enumerate(examples):
        query_tokens = tokenizer.tokenize(ex.question_text)
        # the question may never eat the whole window: keep >= 1 token
        # of context budget or the stride loop below cannot advance
        query_tokens = query_tokens[:min(max_query_length,
                                         max_seq_length - 4)]
        # wordpiece the whole doc once, remembering origins
        tok_to_orig = []
        orig_to_tok = []
        all_doc_tokens = []
        for i, tok in enumerate(ex.doc_tokens):
            orig_to_tok.append(len(all_doc_tokens))
            for sub in tokenizer.tokenize(tok):
                tok_to_orig.append(i)
                all_doc_tokens.append(sub)
        tok_start = tok_end = None
        if is_training and not ex.is_impossible:
            tok_start = orig_to_tok[ex.start_position]
            tok_end = (orig_to_tok[ex.end_position + 1] - 1
                       if ex.end_position < len(ex.doc_tokens) - 1
                       else len(all_doc_tokens) - 1)
            tok_start, tok_end = _improve_answer_span(
                all_doc_tokens, tok_start, tok_end, tokenizer,
                ex.orig_answer_text)
        # sliding windows of the remaining budget
        max_ctx = max_seq_length - len(query_tokens) - 3
        doc_spans = []
        offset = 0
        while offset < len(all_doc_tokens):
            length = min(len(all_doc_tokens) - offset, max_ctx)
            doc_spans.append((offset, length))
            if offset + length >= len(all_doc_tokens):
                break
            offset += min(length, doc_stride)
        for span_index, (span_start, span_len) in enumerate(doc_spans):
            tokens = ["[CLS]"] + query_tokens + ["[SEP]"]
            segment_ids = [0] * len(tokens)
            token_to_orig_map = {}
            token_is_max_context = {}
            for i in range(span_len):
                pos = span_start + i
                token_to_orig_map[len(tokens)] = tok_to_orig[pos]
                token_is_max_context[len(tokens)] = _check_is_max_context(
                    doc_spans, span_index, pos)
                tokens.append(all_doc_tokens[pos])
                segment_ids.append(1)
            tokens.append("[SEP]")
            segment_ids.append(1)
            input_ids = tokenizer.convert_tokens_to_ids(tokens)
            input_mask = [1] * len(input_ids)
            pad = max_seq_length - len(input_ids)
            input_ids += [0] * pad
            input_mask += [0] * pad
            segment_ids += [0] * pad
            start_position = end_position = 0
            if is_training and not ex.is_impossible:
                span_end = span_start + span_len - 1
                if tok_start >= span_start and tok_end <= span_end:
                    doc_offset = len(query_tokens) + 2
                    start_position = tok_start - span_start + doc_offset
                    end_position = tok_end - span_start + doc_offset
                # else: answer outside this window → (0, 0) = CLS
            features.append(SquadFeatures(
                unique_id=unique_id, example_index=ex_index,
                doc_span_index=span_index, tokens=tokens,
                token_to_orig_map=token_to_orig_map,
                token_is_max_context=token_is_max_context,
                input_ids=input_ids, input_mask=input_mask,
                segment_ids=segment_ids, start_position=start_position,
                end_position=end_position))
            unique_id += 1
    return features


def features_to_arrays(features):
    """Dense arrays for BertForQuestionAnswering: ids/mask/segments
    [N, S] int32 + start/end positions [N] int32."""
    return {
        "input_ids": np.asarray([f.input_ids for f in features],
                                np.int32),
        "input_mask": np.asarray([f.input_mask for f in features],
                                 np.int32),
        "segment_ids": np.asarray([f.segment_ids for f in features],
                                  np.int32),
        "start_positions": np.asarray(
            [f.start_position for f in features], np.int32),
        "end_positions": np.asarray(
            [f.end_position for f in features], np.int32),
    }


def _best_indexes(logits, n_best_size):
    return list(np.argsort(np.asarray(logits))[::-1][:n_best_size])


def extract_predictions(examples, features, start_logits, end_logits,
                        n_best_size=20, max_answer_length=30):
    """(start_logits, end_logits) [N, S] → {qas_id: answer_text} via
    the n-best valid-span search over each example's windows."""
    by_example = collections.defaultdict(list)
    for i, f in enumerate(features):
        by_example[f.example_index].append((f, i))
    predictions = {}
    for ex_index, ex in enumerate(examples):
        best_score, best_text = None, ""
        for f, i in by_example.get(ex_index, ()):
            s_logits = np.asarray(start_logits[i])
            e_logits = np.asarray(end_logits[i])
            for s in _best_indexes(s_logits, n_best_size):
                for e in _best_indexes(e_logits, n_best_size):
                    if s not in f.token_to_orig_map:
                        continue
                    if e not in f.token_to_orig_map:
                        continue
                    if not f.token_is_max_context.get(s, False):
                        continue
                    if e < s or e - s + 1 > max_answer_length:
                        continue
                    score = float(s_logits[s] + e_logits[e])
                    if best_score is None or score > best_score:
                        orig_text = " ".join(
                            ex.doc_tokens[f.token_to_orig_map[s]:
                                          f.token_to_orig_map[e] + 1])
                        best_score, best_text = score, orig_text
        predictions[ex.qas_id] = best_text
    return predictions


# ------------------------- official metrics ------------------------- #

def normalize_answer(s):
    """Lower, strip punctuation/articles, collapse whitespace (the
    official evaluate-v1.1 normalization)."""
    s = s.lower()
    s = "".join(c for c in s if c not in string.punctuation)
    s = re.sub(r"\b(a|an|the)\b", " ", s)
    return " ".join(s.split())


def exact_match_score(prediction, ground_truth):
    return float(normalize_answer(prediction)
                 == normalize_answer(ground_truth))


def f1_score(prediction, ground_truth):
    pred_tokens = normalize_answer(prediction).split()
    gold_tokens = normalize_answer(ground_truth).split()
    if not pred_tokens or not gold_tokens:
        # v2 no-answer convention: empty matches only empty
        return float(pred_tokens == gold_tokens)
    common = (collections.Counter(pred_tokens)
              & collections.Counter(gold_tokens))
    num_same = sum(common.values())
    if num_same == 0:
        return 0.0
    precision = num_same / len(pred_tokens)
    recall = num_same / len(gold_tokens)
    return 2 * precision * recall / (precision + recall)


def _metric_max_over_ground_truths(metric, prediction, ground_truths):
    return max(metric(prediction, gt) for gt in ground_truths)


def squad_evaluate(examples, predictions):
    """{exact_match, f1} percentages.  Gold answers come from the
    dev-style ``answers`` lists (falling back to the training span);
    v2.0 ``is_impossible`` questions score against the empty string —
    the official v2 metric counts them, crediting only an empty
    prediction."""
    em = f1 = count = 0
    for ex in examples:
        golds = ex.answers or (
            [ex.orig_answer_text] if ex.orig_answer_text else [])
        if ex.is_impossible:
            golds = [""]
        if not golds:
            continue
        pred = predictions.get(ex.qas_id, "")
        em += _metric_max_over_ground_truths(exact_match_score, pred,
                                             golds)
        f1 += _metric_max_over_ground_truths(f1_score, pred, golds)
        count += 1
    if count == 0:
        return {"exact_match": 0.0, "f1": 0.0}
    return {"exact_match": 100.0 * em / count,
            "f1": 100.0 * f1 / count}
