"""Array facade for API parity with the reference's ndarray module.

Reference: python/hetu/ndarray.py (NDArray:140, ND_Sparse_Array:460,
IndexedSlices:507, array/empty/sparse_array:405-504).  On TPU, jax.Array
already provides device arrays, lazy views, and dlpack interop; this module
keeps the reference's construction helpers so example scripts and tests run
unchanged.  ``NDArray`` IS ``jax.Array`` (alias), and ``array()`` accepts a
DLContext placement.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .context import (  # re-export placement helpers (reference parity)
    DLContext, cpu, gpu, tpu, rcpu, rgpu, rtpu, is_gpu_ctx,
)

NDArray = jax.Array


def _device_for(ctx):
    if ctx is None:
        return None
    if isinstance(ctx, DLContext):
        if ctx.device_type == "cpu":
            cpus = jax.devices("cpu") if jax.default_backend() != "cpu" else jax.devices()
            return cpus[min(ctx.device_id, len(cpus) - 1)]
        devs = jax.devices()
        return devs[min(ctx.device_id, len(devs) - 1)]
    return ctx


def array(arr, ctx=None, dtype=jnp.float32):
    """reference ndarray.array(arr, ctx)"""
    a = jnp.asarray(np.asarray(arr), dtype=dtype)
    dev = _device_for(ctx)
    return jax.device_put(a, dev) if dev is not None else a


def empty(shape, ctx=None, dtype=jnp.float32):
    a = jnp.zeros(tuple(shape), dtype=dtype)
    dev = _device_for(ctx)
    return jax.device_put(a, dev) if dev is not None else a


def numpyasdlarrayhandle(arr):  # reference parity (ndarray.py)
    return jnp.asarray(arr)


class IndexedSlices:
    """Host-side sparse pair (indices, values) — reference ndarray.py:507.

    Graph-level sparse adjoints use graph.ops_embed.IndexedSlicesOp; this
    class serves the PS/dataloader paths that pass sparse host data.
    """

    def __init__(self, indices=None, values=None, dense_shape=None):
        self.indices = indices
        self.values = values
        self.dense_shape = dense_shape

    def get_dense_shape(self):
        assert self.dense_shape is not None
        return self.dense_shape

    def deduplicate(self):
        """Merge duplicate indices (reference ndarray.py:deduplicate)."""
        idx = np.asarray(self.indices).reshape(-1)
        vals = np.asarray(self.values).reshape(idx.shape[0], -1)
        uniq, inv = np.unique(idx, return_inverse=True)
        merged = np.zeros((uniq.shape[0], vals.shape[1]), vals.dtype)
        np.add.at(merged, inv, vals)
        self.indices, self.values = uniq, merged
        return self

    def to_dense(self):
        self.deduplicate()
        assert self.dense_shape is not None
        dense = np.zeros(self.dense_shape, np.float32)
        dense[np.asarray(self.indices)] = np.asarray(self.values)
        return jnp.asarray(dense)


class ND_Sparse_Array:
    """CSR sparse array (reference ndarray.py:460) kept as host-side COO/CSR
    triplets; consumed by csrmm/csrmv ops which densify on device."""

    def __init__(self, data, row, col, nrow, ncol):
        self.data = data
        self.row = row
        self.col = col
        self.nrow = nrow
        self.ncol = ncol

    @property
    def shape(self):
        return (self.nrow, self.ncol)


def sparse_array(values, indices, shape, ctx=None):
    """COO constructor (reference ndarray.sparse_array)."""
    row, col = indices
    return ND_Sparse_Array(jnp.asarray(values), jnp.asarray(row),
                           jnp.asarray(col), shape[0], shape[1])
