"""Request/Result contracts for the serving engines.

A Request carries everything that makes its output reproducible in
isolation: prompt, sampling settings, and a PER-REQUEST rng seed — so
the engine's outputs are a pure function of the request, independent of
arrival order, slot assignment, or what else shares the batch (the
scheduler-determinism tests pin this).

Two request kinds share one lifecycle core (:class:`RequestCore`):
the GPT :class:`Request` (token prompt + sampling payload) and the
recommendation :class:`EmbedRequest` (sparse-id + dense-feature
payload).  The core owns everything the serving substrate — queue
admission, SLO classes, the fleet router, deadline accounting —
needs, so ``ServingRouter`` can host either engine kind without
knowing the payload shape.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Callable, List, Optional, Sequence

import numpy as np

_ids = itertools.count()


class RequestCore:
    """Model-agnostic request lifecycle mixin: identity, SLO class,
    session affinity, deadline, and submit/first-result stamps.

    Payload dataclasses call :meth:`_init_core` from their
    ``__post_init__`` AFTER payload validation, so error ordering (and
    messages) stay exactly what each workload's tests pin.  The mixin
    is deliberately not a dataclass base: default-valued core fields
    would precede the payload's positional fields and break
    ``Request(prompt, max_new_tokens)`` construction.
    """

    #: stamped onto serving telemetry so hetu_top can tell workloads
    #: apart in one merged stream
    workload: str = "gpt"

    def _init_core(self):
        if self.slo_class not in ("latency", "throughput"):
            raise ValueError(
                f"slo_class must be 'latency' or 'throughput', "
                f"got {self.slo_class!r}")
        if self.request_id is None:
            self.request_id = f"req-{next(_ids)}"

    def capacity_tokens(self) -> Optional[int]:
        """Sequence capacity this request needs from its engine (prompt
        + budget for a GPT engine), or None when the workload has no
        per-request sequence bound (embedding waves size by rows, not
        tokens) — the router skips the s_max check for those."""
        return None


@dataclasses.dataclass
class Request(RequestCore):
    """One generation request.

    prompt: non-empty token ids; max_new_tokens: tokens to generate
    (the EOS, when hit, counts as the last one); temperature/top_k:
    per-request sampling settings (0/0 = greedy) — both traced in the
    fused step, so mixed settings share one compile; eos_id: stop
    sampling once this id is emitted past the prompt; seed: the
    request's own rng stream; stream_cb: called as cb(request, token)
    for every generated token as it lands (iteration-level streaming).

    Fleet fields (serving/router.py; a bare engine ignores them):
    slo_class "latency" or "throughput" — under overload the router
    sheds throughput-class traffic first; session_id keys session
    affinity (same session -> same replica, so its shared-prefix KV
    blocks stay hot); deadline_s bounds how long the router may hold
    the request across retries/requeues before expiring it.
    """

    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    eos_id: Optional[int] = None
    seed: int = 0
    stream_cb: Optional[Callable] = None
    request_id: Optional[str] = None
    # fleet routing (serving/router.py)
    slo_class: str = "throughput"
    session_id: Optional[str] = None
    deadline_s: Optional[float] = None
    # set by the engine
    submitted_at: Optional[float] = None
    first_token_at: Optional[float] = None

    def __post_init__(self):
        self.prompt = [int(t) for t in np.asarray(self.prompt).reshape(-1)]
        if not self.prompt:
            raise ValueError("prompt must hold at least one token")
        if int(self.max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}")
        self.max_new_tokens = int(self.max_new_tokens)
        self._init_core()

    def capacity_tokens(self) -> Optional[int]:
        return len(self.prompt) + self.max_new_tokens


@dataclasses.dataclass
class Result:
    """A finished request: ``tokens`` is prompt + generated (numpy
    int32, EOS included when that's what stopped it — no padding, unlike
    the offline path's fixed span); ``finish_reason`` is "eos" or
    "length"."""

    request_id: str
    tokens: np.ndarray
    prompt_len: int
    finish_reason: str
    n_generated: int
    ttft_s: float
    latency_s: float
    slot: int
    # speculative-decoding attribution (0/0 on a non-speculative
    # engine): drafted tokens this request accepted vs was proposed —
    # accepted + bonus samples + the prefill token == n_generated
    spec_accepted: int = 0
    spec_proposed: int = 0
    # the weight version the request was ADMITTED under (None on an
    # unversioned engine) — a swap never lands mid-request, so every
    # generated token is this version's
    weight_version: Optional[int] = None

    @property
    def generated(self) -> List[int]:
        return [int(t) for t in self.tokens[self.prompt_len:]]


@dataclasses.dataclass
class EmbedRequest(RequestCore):
    """One recommendation-scoring request: ``item_ids`` is the sparse
    feature-id matrix ([n, n_fields] for the CTR towers, [n] item ids
    for NCF), ``user_ids`` the per-pair user ids (NCF only — CTR
    towers fold the user into the sparse fields), ``dense_features``
    the [n, n_dense] dense block (CTR only).  All n pairs in one
    request are scored in the same wave and retire together.

    The lifecycle fields mirror :class:`Request` exactly — the router
    and SLO monitor never see the payload.
    """

    user_ids: Optional[Sequence[int]] = None
    item_ids: Optional[Sequence[int]] = None
    dense_features: Optional[Sequence[float]] = None
    seed: int = 0
    request_id: Optional[str] = None
    # fleet routing (serving/router.py)
    slo_class: str = "throughput"
    session_id: Optional[str] = None
    deadline_s: Optional[float] = None
    # set by the engine
    submitted_at: Optional[float] = None
    first_token_at: Optional[float] = None

    workload = "embed"

    def __post_init__(self):
        if self.item_ids is None:
            raise ValueError("item_ids must hold at least one row")
        self.item_ids = np.asarray(self.item_ids, dtype=np.int64)
        if self.item_ids.size == 0:
            raise ValueError("item_ids must hold at least one row")
        if self.user_ids is not None:
            self.user_ids = np.asarray(self.user_ids,
                                       dtype=np.int64).reshape(-1)
            if len(self.user_ids) != self.n_pairs:
                raise ValueError(
                    f"user_ids has {len(self.user_ids)} rows, "
                    f"item_ids has {self.n_pairs}")
        if self.dense_features is not None:
            self.dense_features = np.asarray(self.dense_features,
                                             dtype=np.float32)
            if self.dense_features.ndim == 1:
                self.dense_features = self.dense_features[None, :]
            if len(self.dense_features) != self.n_pairs:
                raise ValueError(
                    f"dense_features has {len(self.dense_features)} "
                    f"rows, item_ids has {self.n_pairs}")
        self._init_core()

    @property
    def n_pairs(self) -> int:
        """Rows this request scores (its wave-capacity cost)."""
        return int(self.item_ids.shape[0])


@dataclasses.dataclass
class EmbedResult:
    """A scored request: ``scores`` is the [n_pairs] float32 CTR/rating
    vector, row-aligned with the request's pairs; ``finish_reason`` is
    "scored" (or "shed"/"expired" when the fleet dropped it)."""

    request_id: str
    scores: np.ndarray
    n_pairs: int
    finish_reason: str
    ttft_s: float
    latency_s: float
    slot: int
    cache_hit_rate: float = 0.0
    # the weight version the scoring wave ran under (None unversioned)
    weight_version: Optional[int] = None
