"""Request/Result contracts for the serving engine.

A Request carries everything that makes its output reproducible in
isolation: prompt, sampling settings, and a PER-REQUEST rng seed — so
the engine's outputs are a pure function of the request, independent of
arrival order, slot assignment, or what else shares the batch (the
scheduler-determinism tests pin this).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Callable, List, Optional, Sequence

import numpy as np

_ids = itertools.count()


@dataclasses.dataclass
class Request:
    """One generation request.

    prompt: non-empty token ids; max_new_tokens: tokens to generate
    (the EOS, when hit, counts as the last one); temperature/top_k:
    per-request sampling settings (0/0 = greedy) — both traced in the
    fused step, so mixed settings share one compile; eos_id: stop
    sampling once this id is emitted past the prompt; seed: the
    request's own rng stream; stream_cb: called as cb(request, token)
    for every generated token as it lands (iteration-level streaming).

    Fleet fields (serving/router.py; a bare engine ignores them):
    slo_class "latency" or "throughput" — under overload the router
    sheds throughput-class traffic first; session_id keys session
    affinity (same session -> same replica, so its shared-prefix KV
    blocks stay hot); deadline_s bounds how long the router may hold
    the request across retries/requeues before expiring it.
    """

    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    eos_id: Optional[int] = None
    seed: int = 0
    stream_cb: Optional[Callable] = None
    request_id: Optional[str] = None
    # fleet routing (serving/router.py)
    slo_class: str = "throughput"
    session_id: Optional[str] = None
    deadline_s: Optional[float] = None
    # set by the engine
    submitted_at: Optional[float] = None
    first_token_at: Optional[float] = None

    def __post_init__(self):
        self.prompt = [int(t) for t in np.asarray(self.prompt).reshape(-1)]
        if not self.prompt:
            raise ValueError("prompt must hold at least one token")
        if int(self.max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}")
        self.max_new_tokens = int(self.max_new_tokens)
        if self.slo_class not in ("latency", "throughput"):
            raise ValueError(
                f"slo_class must be 'latency' or 'throughput', "
                f"got {self.slo_class!r}")
        if self.request_id is None:
            self.request_id = f"req-{next(_ids)}"


@dataclasses.dataclass
class Result:
    """A finished request: ``tokens`` is prompt + generated (numpy
    int32, EOS included when that's what stopped it — no padding, unlike
    the offline path's fixed span); ``finish_reason`` is "eos" or
    "length"."""

    request_id: str
    tokens: np.ndarray
    prompt_len: int
    finish_reason: str
    n_generated: int
    ttft_s: float
    latency_s: float
    slot: int
    # speculative-decoding attribution (0/0 on a non-speculative
    # engine): drafted tokens this request accepted vs was proposed —
    # accepted + bonus samples + the prefill token == n_generated
    spec_accepted: int = 0
    spec_proposed: int = 0

    @property
    def generated(self) -> List[int]:
        return [int(t) for t in self.tokens[self.prompt_len:]]
