"""One supervised serving replica: a ServingEngine under the
launcher's respawn/backoff budget pattern.

``launcher.run_cluster`` keeps training alive by watching child exit
codes and respawning dead PS servers/workers under an exponential-
backoff restart budget (``HETU_RESTART_LIMIT`` / ``HETU_RESTART_BACKOFF``)
with structured JSONL failure events.  This module is the serving-side
analog of one of those supervisor slots: a :class:`Replica` owns one
engine incarnation, absorbs its death (an exception escaping the
scheduler, or a chaos-injected kill), and respawns a FRESH engine from
the factory under the same budget semantics, emitting the same style of
failure events (``replica_exit`` / ``replica_restart_scheduled`` /
``replica_restart`` / ``replica_failed``) through ``telemetry.emit``.

The harness is cooperative (in-process): ``step()`` advances the
wrapped engine one scheduler iteration and stamps a heartbeat.  Death
loses the incarnation's queue and in-flight slots exactly the way a
SIGKILL'd process loses its memory — the ROUTER (serving/router.py)
owns the request-level recovery, requeueing everything the dead
incarnation had not retired onto peers from its own assignment records
(it never needs to introspect the corpse).

Chaos: each ``step()`` draws one decision from the ``HETU_CHAOS`` plan
with this replica's role (``replica<k>``) passed explicitly — several
replica roles share one process, so the env-var role is not enough —
and ``inline=True`` so a drawn ``kill`` comes back as a Fault instead
of SIGKILLing the whole fleet.  ``kill=<n>`` then means "this replica's
n-th step dies"; ``wedge=<n>`` means it stops progressing AND stops
heartbeating (silently — detection is the router's stale-heartbeat
check, the serving analog of ``HETU_LIVENESS_STALE``).  The flight
recorder dumps before the death, exactly like the transport-seam kill.
"""

from __future__ import annotations

import time

from .. import envvars, telemetry
from ..ps import faults
from ..telemetry import flight
from .engine import QueueFull

# replica lifecycle states
UP = "up"              # serving traffic
WEDGED = "wedged"      # alive, not progressing, not heartbeating
BACKOFF = "backoff"    # dead, respawn scheduled
DEAD = "dead"          # dead; drain pending or budget spent (terminal
#                        once next_at is +inf)
RETIRED = "retired"    # orderly scale-down exit: terminal by intent,
#                        never respawned (elastic fleet, ISSUE 16)


class Replica:
    """One supervised engine slot in a router fleet.

    ``factory(index)`` builds a fresh ServingEngine for incarnation
    after incarnation (the router passes one that stamps shared weights
    + config and the ``replica=<index>`` event tag).  ``emit_fn`` routes
    the failure events; default is the failure stream (same sink as the
    launcher's supervisor records).
    """

    def __init__(self, index, factory, *, restart_limit=None,
                 restart_backoff=None, emit_fn=None, kind="mixed",
                 on_start=None):
        self.index = int(index)
        self.role = f"replica{self.index}"
        # serving role for prefill/decode disaggregation
        # ("prefill"/"decode"/"mixed" — HETU_ROUTER_ROLES via the
        # router); distinct from ``role``, the chaos-plan label
        self.kind = str(kind)
        # per-incarnation wiring callback (router: directory feed +
        # handoff export hook) — re-fires on every respawn so a fresh
        # engine is never left unwired
        self.on_start = on_start
        self.factory = factory
        self.restart_limit = (
            restart_limit if restart_limit is not None
            else envvars.get_int("HETU_RESTART_LIMIT"))
        self.backoff0 = (
            restart_backoff if restart_backoff is not None
            else envvars.get_float("HETU_RESTART_BACKOFF"))
        self.emit = emit_fn or (
            lambda kind, **f: telemetry.emit(kind, _stream="failure",
                                             **f))
        self.engine = None
        self.state = DEAD
        self.restarts = 0        # respawns beyond the first incarnation
        self.exit_code = None
        self.exit_error = None
        self.next_at = None      # backoff deadline (perf_counter clock)
        self.last_beat = None    # heartbeat stamp (perf_counter clock)
        self.steps = 0           # lifetime step count (all incarnations)
        self.drained = True      # router has recovered our requests
        # elastic-fleet lifecycle phase (warming/serving/draining/
        # retired) — the router's add/retire paths drive it; a
        # statically constructed replica is simply serving
        self.lifecycle = "serving"
        self._start()
        self.emit("replica_start", replica=self.index)

    # ------------------------------------------------------------- #
    # lifecycle
    # ------------------------------------------------------------- #

    def _start(self):
        """Spawn a fresh engine incarnation (the supervisor's respawn)."""
        self.engine = self.factory(self.index)
        self.engine.metrics.tags.setdefault("replica", self.index)
        self.engine.metrics.tags.setdefault("role", self.kind)
        self.state = UP
        self.exit_code = None
        self.exit_error = None
        self.next_at = None
        self.last_beat = time.perf_counter()
        self.drained = True
        if self.on_start is not None:
            self.on_start(self)

    def die(self, rc, error=None):
        """The incarnation is gone: its queue and in-flight slots are
        lost with it (the router requeues from its own records —
        ``drained`` flips once it has).  Emits ``replica_exit`` in the
        launcher's record shape."""
        self.engine = None
        self.state = DEAD
        self.exit_code = int(rc)
        self.exit_error = error
        self.drained = False
        fields = {"rc": int(rc), "restarts": self.restarts}
        if error:
            fields["error"] = str(error)[:200]
        self.emit("replica_exit", replica=self.index, **fields)

    def retire(self):
        """Orderly scale-down exit: drop the incarnation and CLOSE the
        supervisor slot for good (no respawn — retirement is intent,
        not failure, so the restart budget is not consulted).  The
        router owns the drain: by the time this fires, every request
        the incarnation held has been requeued onto peers and its hot
        prefixes exported, so the engine is dropped with nothing left
        to lose."""
        self.engine = None
        self.state = RETIRED
        self.lifecycle = "retired"
        self.next_at = float("inf")
        self.drained = True

    def schedule_restart(self, now=None):
        """Enter the backoff window, or go terminal when the budget is
        spent (``replica_failed`` + a flight dump: a replica the fleet
        can never get back is a router terminal failure)."""
        now = time.perf_counter() if now is None else now
        if self.restarts >= self.restart_limit:
            self.emit("replica_failed", replica=self.index,
                      rc=self.exit_code if self.exit_code is not None
                      else -1, restarts=self.restarts)
            flight.RECORDER.dump("replica_budget_spent",
                                 replica=self.index,
                                 restarts=self.restarts)
            self.next_at = float("inf")
            return False
        self.restarts += 1
        backoff = self.backoff0 * 2 ** (self.restarts - 1)
        self.state = BACKOFF
        self.next_at = now + backoff
        self.emit("replica_restart_scheduled", replica=self.index,
                  attempt=self.restarts, backoff_s=round(backoff, 3))
        return True

    def maybe_respawn(self, now=None):
        """Respawn once the backoff window has elapsed."""
        now = time.perf_counter() if now is None else now
        if self.state == BACKOFF and now >= self.next_at:
            self._start()
            self.emit("replica_restart", replica=self.index,
                      attempt=self.restarts)
            return True
        return False

    @property
    def terminal(self):
        """Never coming back: restart budget spent, or retired by an
        orderly scale-down."""
        return self.state in (DEAD, RETIRED) \
            and self.next_at == float("inf")

    @property
    def alive(self):
        return self.state in (UP, WEDGED)

    # ------------------------------------------------------------- #
    # serving
    # ------------------------------------------------------------- #

    def submit(self, request):
        """Forward to the engine (QueueFull propagates to the router's
        placement loop); only valid while routable."""
        if self.state != UP:
            raise QueueFull(f"replica {self.index} is {self.state}")
        return self.engine.submit(request)

    def step(self):
        """One engine scheduler iteration; returns the Results that
        retired.  Draws one chaos decision first (role-scoped,
        inline): a kill dumps the flight ring then kills THIS replica
        only; a wedge freezes it silently.  Any exception escaping the
        engine is a death too (the engine already dumped its own flight
        ring on the way out)."""
        if self.state != UP:
            return []
        fault = self._chaos()
        if fault == "kill":
            # the kill's black box, with the replica attributed — the
            # router-side analog of the transport seam's chaos_kill dump
            flight.RECORDER.dump("replica_chaos_kill",
                                 replica=self.index, step=self.steps)
            self.die(rc=-9, error="chaos kill")
            return []
        if fault == "wedge":
            # silent: a wedged replica does not announce itself — the
            # router's stale-heartbeat check is the detection path
            self.state = WEDGED
            return []
        try:
            done = self.engine.step()
        except QueueFull:
            raise
        except Exception as e:  # noqa: BLE001 — a crash IS the event
            self.die(rc=1, error=f"{type(e).__name__}: {e}")
            return []
        self.steps += 1
        self.last_beat = time.perf_counter()
        return done

    def _chaos(self):
        """One decision from the env chaos plan at this replica's step
        seam; returns "kill"/"wedge"/None."""
        plan = faults.plan_from_env()
        if plan is None:
            return None
        f = plan.draw(method=f"{self.role}.step",
                      kinds=("kill", "wedge"), role=self.role,
                      inline=True)
        return f.kind if f.kind in ("kill", "wedge") else None

    # ------------------------------------------------------------- #
    # signals the router reads
    # ------------------------------------------------------------- #

    def health(self):
        """The engine's SLO health while up; the state name otherwise."""
        return self.engine.health() if self.state == UP else self.state

    @property
    def queue_depth(self):
        return self.engine.queue_depth if self.state == UP else 0

    @property
    def live(self):
        """Sequences currently holding slots."""
        return len(self.engine.kv.live()) if self.state == UP else 0

    @property
    def occupancy(self):
        if self.state != UP:
            return 0.0
        return self.live / max(self.engine.kv.n_slots, 1)

    def stale(self, stale_s, now=None):
        """True when the heartbeat is older than ``stale_s`` (the
        wedged-replica detection the router runs; a wedged replica
        stopped beating but still reads as alive)."""
        if not self.alive or self.last_beat is None:
            return False
        now = time.perf_counter() if now is None else now
        return (now - self.last_beat) > stale_s

    def snapshot(self):
        """JSON-able row for router snapshots / hetu_top --fleet."""
        return {
            "replica": self.index,
            "state": self.state,
            "lifecycle": self.lifecycle,
            "role": self.kind,
            "health": self.health(),
            "restarts": self.restarts,
            "steps": self.steps,
            "queue_depth": self.queue_depth,
            "live": self.live,
            "occupancy": round(self.occupancy, 4),
            "exit_code": self.exit_code,
            "weight_version": (getattr(self.engine, "weight_version",
                                       None)
                               if self.engine is not None else None),
        }
