"""Tiered KV: fleet-global prefix capacity behind the paged pool.

PR 11's directory can only route to prefix blocks that happened to
survive their replica's LRU — a prefix evicted from the HBM pool is
simply GONE, and the next request holding that prompt pays a full cold
prefill.  This module turns eviction-to-drop into eviction-to-tier,
the HET discipline (PAPER.md: hot embeddings local, cold ones on the
PS) applied to the KV side, where it is strictly easier: KV is
immutable once written, so tiering is EXACT — no staleness budget, no
version fences, a fetched span is token-identical to the original
prefill by construction.

The ladder::

    HBM pool (PagedKVManager)        <- refcounted, token-verified
      | evict (LRU, pool pressure)      export_prefix: wire payload
      v
    host-RAM ring (this module)      <- LRU by bytes, HETU_KV_HOST_BYTES
      | overflow                        payload dicts, int8 wire form
      v
    sharded-PS cold store            <- HETU_KV_PS_TIER; kv_put/kv_get
                                        keyed by prefix hash, versioned

and the miss path escalates the other way: local pool (match_prefix)
-> peer-replica steal (the PR 11 directory hint + handoff) -> host
ring -> PS fetch -> cold prefill.  Fetches re-admit through
``import_blocks`` with the prompt re-registered, so the engine's
admission attaches the blocks refcounted exactly as if the prefix had
never left.

Ledger discipline (``hetu_trace --check`` tier-balance): one
``kv_spill`` opens a residency when a prefix ENTERS the ladder; exactly
one terminal event closes it — ``kv_fetch`` (re-admitted to a pool;
the pool copy re-spills on its next eviction) or ``kv_tier_drop`` (ring
overflow with the PS rung off/dead, corruption, store close).
Re-spilling an already-resident prefix refreshes its LRU stamp without
a second ``kv_spill``; host->PS demotion moves the payload without
touching the ledger (the residency is one, wherever it lives).

Degradation contract (chaos role ``kvtier``): a drawn kill at the
``kvtier.ps_put``/``kvtier.ps_get`` seams takes the PS rung down —
resident cold entries get their terminal drop, future spills stop at
the host ring — and a drawn drop/reset at ``kvtier.ring_get`` corrupts
the ring entry (dropped, counted, the request admits cold).  Both
degrade to today's drop-on-evict with ZERO request loss: a tier miss
is a cold prefill, never an error.
"""

from __future__ import annotations

from .. import envvars, locks, telemetry
from ..ps import faults
from ..telemetry import flight
from .prefix_directory import prefix_hash

__all__ = ["TieredKVStore", "PS_NAMESPACE"]

# PS-side key namespace for cold prefix payloads: disjoint from every
# param/table key by prefix, so a cold store can share servers with a
# training job without collisions
PS_NAMESPACE = "__kvcold__"


class _RingEntry:
    """One host-ring resident: the prefix tokens (fetch needs them to
    re-register), its wire payload, and the payload's byte size."""

    __slots__ = ("tokens", "payload", "nbytes")

    def __init__(self, tokens, payload):
        self.tokens = tokens
        self.payload = payload
        self.nbytes = int(payload["nbytes"])


class TieredKVStore:
    """The spill/fetch ladder.  One store serves a whole fleet (the
    router builds it and :meth:`attach`-wires every replica incarnation)
    or a single standalone engine.  Knobs default to the registry
    (``HETU_KV_HOST_BYTES`` / ``HETU_KV_PS_TIER``); pass ``ps=`` any
    client with ``kv_put``/``kv_get``/``kv_del`` (PSClient,
    ShardedPSClient, or a test double) — unset, the first PS use
    resolves ``PSClient.get()``."""

    def __init__(self, *, host_bytes=None, ps_tier=None, ps=None,
                 directory=None):
        self.host_bytes = int(
            host_bytes if host_bytes is not None
            else envvars.get_int("HETU_KV_HOST_BYTES"))
        self.ps_tier = bool(
            ps_tier if ps_tier is not None
            else envvars.get_bool("HETU_KV_PS_TIER"))
        self.ps = ps
        self.directory = directory     # PrefixDirectory or None: gets
        self.block = None              # the tier column stamped
        self.ps_dead = False
        # one reentrant guard over the whole ladder: spill/fetch from
        # replica threads race each other on the ring dict and its
        # byte counter, and a transport death inside fetch/_ps_put
        # re-enters through kill_ps.  Reentrant, not plain: kill_ps is
        # both a public entry point and an under-lock internal.  (The
        # PS rung RPC runs under the lock; with an in-process server
        # that is a dict op, and with a real TCP transport lockdep's
        # held-across seam flags it — by design.)
        self._mu = locks.TracedRLock("kv.tiers")
        self._ring = {}                # hash -> _RingEntry (dict IS
        self._ring_bytes = 0           # the LRU: insertion-ordered,
        #                                re-insert on refresh)
        self._ps_index = {}            # hash -> (tokens, length,
        self._ps_version = 0           #          nbytes, version)
        # per-tier counters (stats surface; hetu_top tier panel reads
        # the event-stream twin)
        self.spills = {"host": 0, "ps": 0}
        self.fetches = {"host": 0, "ps": 0}
        self.drops = {"host": 0, "ps": 0}
        self.refreshes = 0             # re-spill of a resident prefix
        self.demotes = 0               # host-ring overflow -> PS
        self.corruptions = 0           # chaos-corrupted ring reads
        self.spill_rejects = 0         # ladder full/off: plain drop
        self.import_failed = 0         # fetched but the pool was full
        self.lookup_hits = 0
        self.lookup_misses = 0
        self.spill_bytes = 0
        self.fetch_bytes = 0

    @classmethod
    def from_env(cls, **kw):
        """The router's constructor hook: a store when either rung is
        enabled, else None (tiering off = byte-identical drop-on-evict
        — no hooks are wired anywhere)."""
        host = envvars.get_int("HETU_KV_HOST_BYTES")
        ps = envvars.get_bool("HETU_KV_PS_TIER")
        if host <= 0 and not ps:
            return None
        return cls(host_bytes=host, ps_tier=ps, **kw)

    @property
    def enabled(self):
        return self.host_bytes > 0 or self.ps_tier

    # ------------------------------------------------------------- #
    # wiring
    # ------------------------------------------------------------- #

    def attach(self, replica, kv):
        """Wire one ``PagedKVManager`` into the ladder: its LRU prefix
        evictions spill here (the manager exports BEFORE freeing), and
        its engine's admission path fetches through ``kv.tier_store``.
        Called per incarnation, like the directory attach; a
        non-sharing or block-mismatched pool attaches as a no-op."""
        if not self.enabled or not getattr(kv, "prefix_share", False):
            return
        block = getattr(kv, "block", None)
        if block is None:
            return
        if self.block is None:
            self.block = int(block)
        elif int(block) != self.block:
            return   # a payload cut at one block size cannot land in
            #          a pool cut at another
        kv.tier_store = self
        kv.on_prefix_spill = \
            lambda toks, payload, _r=replica: self.spill(
                toks, payload, replica=_r)

    # ------------------------------------------------------------- #
    # spill: HBM -> host ring -> PS
    # ------------------------------------------------------------- #

    def spill(self, tokens, payload, *, replica=None):
        """Accept an evicted prefix's wire payload into the ladder;
        True when it is now tier-resident (False = the caller's drop
        proceeds, exactly today's behavior).  An already-resident
        prefix refreshes its LRU stamp — one residency, one ledger
        entry."""
        if payload is None or not self.enabled:
            return False
        toks = tuple(int(t) for t in tokens)
        h = prefix_hash(toks)
        with self._mu:
            e = self._ring.pop(h, None)
            if e is not None:
                # refresh: newest payload (byte-identical for
                # immutable KV, but the re-export is authoritative),
                # MRU position
                self._ring_bytes -= e.nbytes
                ne = _RingEntry(toks, payload)
                self._ring[h] = ne
                self._ring_bytes += ne.nbytes
                self.refreshes += 1
                return True
            if h in self._ps_index:
                self.refreshes += 1   # already cold-resident: nothing
                return True           # to move (payload is identical)
            nbytes = int(payload["nbytes"])
            if self.host_bytes > 0 and nbytes <= self.host_bytes:
                self._ring[h] = _RingEntry(toks, payload)
                self._ring_bytes += nbytes
                self._note_spill(h, payload, "host", replica)
                if self.directory is not None:
                    self.directory.set_tier(toks, "host")
                self._shrink_ring()
                return True
            if self._ps_put(h, toks, payload):
                self._note_spill(h, payload, "ps", replica)
                if self.directory is not None:
                    self.directory.set_tier(toks, "ps")
                return True
            self.spill_rejects += 1
            return False

    def _note_spill(self, h, payload, tier, replica):
        self.spills[tier] += 1
        self.spill_bytes += int(payload["nbytes"])
        telemetry.inc(f"kvtier.spill_{tier}")
        self._event("kv_spill", prefix=h, tier=tier,
                    length=int(payload["length"]),
                    bytes=int(payload["nbytes"]),
                    **({"replica": replica} if replica is not None
                       else {}))

    def _shrink_ring(self):
        """LRU-evict the ring back under its byte budget: each victim
        demotes to the PS rung when it can, else takes its terminal
        drop (the ledger closes; drop-on-evict beyond the ring)."""
        while self._ring_bytes > self.host_bytes and self._ring:
            h = next(iter(self._ring))        # oldest insertion
            e = self._ring.pop(h)
            self._ring_bytes -= e.nbytes
            if self._ps_put(h, e.tokens, e.payload):
                self.demotes += 1
                telemetry.inc("kvtier.demotes")
                if self.directory is not None:
                    self.directory.set_tier(e.tokens, "ps")
            else:
                self._drop(h, e.tokens, "host", "ring_full")

    def _drop(self, h, tokens, tier, reason):
        """Terminal drop: the residency ends without a fetch (ring
        overflow past a dead/absent PS rung, corruption, close)."""
        self.drops[tier] += 1
        telemetry.inc(f"kvtier.drop_{tier}")
        self._event("kv_tier_drop", prefix=h, tier=tier, reason=reason)
        if self.directory is not None:
            self.directory.clear_tier(tokens)

    # ------------------------------------------------------------- #
    # lookup + fetch: host ring -> PS -> miss
    # ------------------------------------------------------------- #

    def lookup(self, prompt, block=None):
        """Longest block-aligned tier-resident prefix of ``prompt``:
        ``(tokens, length, tier)`` or None.  Token-verified (the hash
        only indexes), probing block cuts longest-first like the
        directory — the usable share is capped below the last prompt
        position, so the full prompt is never probed."""
        block = self.block if block is None else int(block)
        with self._mu:
            if not self.enabled or block is None \
                    or (not self._ring and not self._ps_index):
                return None
            p = [int(t) for t in prompt]
            if len(p) < 2:
                return None
            top = ((len(p) - 1) // block) * block
            for n in range(top, 0, -block):
                cut = p[:n]
                h = prefix_hash(cut)
                e = self._ring.get(h)
                if e is not None and list(e.tokens) == cut:
                    self.lookup_hits += 1
                    return tuple(cut), n, "host"
                cold = self._ps_index.get(h)
                if cold is not None and list(cold[0]) == cut:
                    self.lookup_hits += 1
                    return tuple(cut), n, "ps"
            self.lookup_misses += 1
            return None

    def fetch(self, tokens, *, replica=None):
        """Pop a resident prefix's payload back out of the ladder —
        host ring first, then the PS cold store — ending its residency
        (the re-admitted pool copy re-spills on its next eviction,
        which is what keeps the ledger exact).  Returns the wire
        payload or None: a miss, a chaos corruption, or a dead PS all
        degrade to a cold prefill at the caller."""
        toks = tuple(int(t) for t in tokens)
        h = prefix_hash(toks)
        with self._mu:
            e = self._ring.get(h)
            if e is not None:
                if self._chaos_corrupt("kvtier.ring_get"):
                    # corrupted host copy: never land garbage KV —
                    # drop the residency and admit cold (zero loss,
                    # warmth lost)
                    del self._ring[h]
                    self._ring_bytes -= e.nbytes
                    self.corruptions += 1
                    telemetry.inc("kvtier.corruptions")
                    self._drop(h, toks, "host", "corrupt")
                    return None
                del self._ring[h]
                self._ring_bytes -= e.nbytes
                self._note_fetch(h, e.payload, "host", replica)
                if self.directory is not None:
                    self.directory.clear_tier(toks)
                return e.payload
            cold = self._ps_index.get(h)
            if cold is None:
                return None
            _toks0, _length, _nbytes, version = cold
            if self._chaos_kill("kvtier.ps_get"):
                return None        # kill_ps just dropped every cold
                #                    residency, this one included
            try:
                got = self._ps_client().kv_get(PS_NAMESPACE + h)
            except Exception as err:  # noqa: BLE001 — transport death
                self.kill_ps(reason=f"kv_get: {type(err).__name__}")
                return None
            if got is None or int(got[1]) != version:
                # vanished or overwritten behind our back: a cold
                # entry we cannot vouch for must not land — drop the
                # residency
                del self._ps_index[h]
                self._drop(h, toks, "ps", "version_skew"
                           if got is not None else "missing")
                return None
            payload = got[0]
            del self._ps_index[h]
            try:
                self._ps_client().kv_del(PS_NAMESPACE + h)
            except Exception:  # noqa: BLE001 — the payload is in
                pass           # hand; a failed delete only leaks a
                #                cold blob
            self._note_fetch(h, payload, "ps", replica)
            if self.directory is not None:
                self.directory.clear_tier(toks)
            return payload

    def _note_fetch(self, h, payload, tier, replica):
        self.fetches[tier] += 1
        self.fetch_bytes += int(payload["nbytes"])
        telemetry.inc(f"kvtier.fetch_{tier}")
        self._event("kv_fetch", prefix=h, tier=tier,
                    length=int(payload["length"]),
                    bytes=int(payload["nbytes"]),
                    **({"replica": replica} if replica is not None
                       else {}))

    def note_import_failed(self):
        """The caller fetched but its pool could not hold the import:
        the residency already ended (honest — the warmth is gone), this
        only counts the degradation."""
        self.import_failed += 1
        telemetry.inc("kvtier.import_failed")

    # ------------------------------------------------------------- #
    # PS rung
    # ------------------------------------------------------------- #

    def _ps_client(self):
        if self.ps is None:
            from ..ps.client import PSClient
            self.ps = PSClient.get()
        return self.ps

    def _ps_put(self, h, tokens, payload):
        """Park a payload in the cold store (versioned, so a fetch can
        refuse an entry someone overwrote).  Any failure — chaos kill,
        transport death — takes the whole PS rung down rather than
        retrying into it: degrade once, degrade honestly."""
        if not self.ps_tier or self.ps_dead:
            return False
        if self._chaos_kill("kvtier.ps_put"):
            return False
        self._ps_version += 1
        version = self._ps_version
        try:
            self._ps_client().kv_put(PS_NAMESPACE + h, payload, version)
        except Exception as err:  # noqa: BLE001 — any transport death
            self.kill_ps(reason=f"kv_put: {type(err).__name__}")
            return False
        self._ps_index[h] = (tuple(tokens), int(payload["length"]),
                             int(payload["nbytes"]), version)
        return True

    def kill_ps(self, reason="killed"):
        """The PS rung is gone: every cold residency takes its terminal
        drop (unreachable warmth is not warmth) and future spills stop
        at the host ring — beyond it, today's drop-on-evict.  Zero
        request loss by construction: a tier miss is a cold prefill."""
        with self._mu:
            if self.ps_dead:
                return
            self.ps_dead = True
            for h, (toks, _l, _n, _v) in list(self._ps_index.items()):
                del self._ps_index[h]
                self._drop(h, toks, "ps", "ps_killed")
        telemetry.emit("kvtier_ps_killed", _stream="failure",
                       reason=reason)
        flight.RECORDER.dump("kvtier_ps_killed", detail=reason)

    # ------------------------------------------------------------- #
    # chaos seams (role "kvtier")
    # ------------------------------------------------------------- #

    def _chaos_kill(self, method):
        plan = faults.plan_from_env()
        if plan is None:
            return False
        f = plan.draw(method=method, kinds=("kill",), role="kvtier",
                      inline=True)
        if f is not None and f.kind == "kill":
            self.kill_ps(reason=f"chaos at {method}")
            return True
        return False

    def _chaos_corrupt(self, method):
        plan = faults.plan_from_env()
        if plan is None:
            return False
        f = plan.draw(method=method, kinds=("drop", "reset"),
                      role="kvtier", inline=True)
        return f is not None

    # ------------------------------------------------------------- #

    def close(self, reason="shutdown"):
        """Retire the store: every still-resident entry takes its
        terminal drop so a COMPLETED run's spill/fetch ledger balances
        (the tier-balance trace rule treats an open residency at end
        of stream as a violation).  PS blobs are best-effort deleted."""
        with self._mu:
            for h in list(self._ring):
                e = self._ring.pop(h)
                self._ring_bytes -= e.nbytes
                self._drop(h, e.tokens, "host", reason)
            for h, (toks, _l, _n, _v) in list(self._ps_index.items()):
                del self._ps_index[h]
                if not self.ps_dead:
                    try:
                        self._ps_client().kv_del(PS_NAMESPACE + h)
                    except Exception:  # noqa: BLE001
                        pass
                self._drop(h, toks, "ps", reason)

    def _event(self, kind, **fields):
        telemetry.emit(kind, _stream="serve", **fields)

    def stats(self):
        """JSON-able ladder view (router snapshot / bench rows)."""
        with self._mu:
            return self._stats()

    def _stats(self):
        return {
            "enabled": self.enabled,
            "host_bytes": self.host_bytes,
            "host_used_bytes": self._ring_bytes,
            "host_entries": len(self._ring),
            "ps_tier": self.ps_tier,
            "ps_dead": self.ps_dead,
            "ps_entries": len(self._ps_index),
            "spills": dict(self.spills),
            "fetches": dict(self.fetches),
            "drops": dict(self.drops),
            "refreshes": self.refreshes,
            "demotes": self.demotes,
            "corruptions": self.corruptions,
            "spill_rejects": self.spill_rejects,
            "import_failed": self.import_failed,
            "lookup_hits": self.lookup_hits,
            "lookup_misses": self.lookup_misses,
            "spill_bytes": self.spill_bytes,
            "fetch_bytes": self.fetch_bytes,
        }
