"""Live weight sync: zero-downtime rolling weight swaps over the fleet.

``WeightSyncCoordinator`` takes a new version-stamped param pytree
(pulled from the sharded PS via ``begin_from_ps`` or handed in
directly) and rolls it across a ``ServingRouter``'s replicas ONE AT A
TIME with zero request loss.  Per replica the cycle is

    quiesce -> drain -> swap -> probe -> readmit

- **quiesce**: the router stops routing to the replica (the same
  exclusion model as an open circuit breaker — ``_candidates`` skips
  it; in-flight work keeps stepping).
- **drain**: every request the router assigned to the replica retires
  (or requeues off it if it dies) and the engine's own queue empties.
  Draining is bounded by ``HETU_SWAP_DRAIN_STEPS`` router steps.
- **swap**: ``engine.swap_params`` replaces the param dict between
  steps — no recompile (the jitted step takes params as arguments),
  and the spec-decode truncated-layer draft inherits the swap for free
  because it shares the target's param dict.
- **probe**: a version-tagged greedy decode (``HETU_SWAP_PROBE_TOKENS``
  tokens) must retire on the NEW version before the replica serves
  traffic again — the half-open readmission check of the breaker,
  applied to weights.
- **readmit**: the hold lifts; the rollout advances to the next
  replica.  When the last replica readmits, the new pytree+version
  become COMMITTED.

Failure is a first-class path, not an afterthought.  Chaos seams
(``HETU_CHAOS`` with ``role=swap``) cover the swap lifecycle: a kill
drawn mid-drain or mid-swap (after the buffers moved, before the
probe) kills the target replica, and a ``drop``/``reset`` drawn at the
version-push seam models a corrupt/stale version read.  Every failure
degrades cleanly: the dead replica respawns on the LAST COMMITTED
version (the coordinator wraps the replica factories), the coordinator
marks the rollout failed, auto-rolls any already-swapped replicas back
(``HETU_SWAP_ROLLBACK``), and the flight recorder dumps the swap
timeline.  A stale push (incoming version <= committed) never touches
an engine.

Versions are stamped end to end: ``engine.metrics.tags`` carries
``weight_version`` so EVERY serve event is stamped, retired ``Result``s
carry the admission version, and ``hetu_trace --check`` enforces the
version-coherence rule (no retirement mixes versions; a request only
changes version across a ``router_hop`` requeue).
"""

from __future__ import annotations

import time

from .. import envvars
from ..ps import faults
from ..telemetry import flight
from .request import Request
from .replica import RETIRED, UP

__all__ = ["WeightSyncCoordinator"]

_DEFAULT_PROBE_PROMPT = (1, 2, 3)


class WeightSyncCoordinator:
    """Rolls version-stamped weight swaps across a router's fleet.

    Construct with the fleet's CURRENT params and version — that pair
    is the committed baseline every respawn and rollback returns to::

        coord = WeightSyncCoordinator(router, params, version=1)
        coord.begin(new_params, version=2)   # or begin_from_ps(ps, keys)
        out = router.run(trace)              # swap rolls mid-trace
        coord.drain()                        # finish a quiet-fleet tail
        assert coord.state == "done" and coord.committed_version == 2

    The coordinator attaches itself as ``router.weight_sync``; the
    router calls ``tick()`` once per ``step()``, so a rollout advances
    exactly as fast as the fleet serves — there is no second thread
    and no lock.
    """

    def __init__(self, router, params, version, *, probe_tokens=None,
                 drain_steps=None, rollback=None, probe_prompt=None,
                 probe_factory=None):
        self.router = router
        self.committed_params = dict(params)
        self.committed_version = int(version)
        self.probe_tokens = int(
            probe_tokens if probe_tokens is not None
            else envvars.get_int("HETU_SWAP_PROBE_TOKENS"))
        self.drain_steps = int(
            drain_steps if drain_steps is not None
            else envvars.get_int("HETU_SWAP_DRAIN_STEPS"))
        self.rollback = bool(
            rollback if rollback is not None
            else envvars.get_bool("HETU_SWAP_ROLLBACK"))
        self.probe_prompt = list(probe_prompt or _DEFAULT_PROBE_PROMPT)
        # fn(replica_index, version) -> Request/EmbedRequest: overrides
        # the default greedy-GPT probe (an embed fleet's probe payload
        # is model-shaped, so the caller supplies it; without one an
        # embed replica readmits on the version stamp alone)
        self.probe_factory = probe_factory
        self.active = None     # in-flight rollout dict
        self.last = None       # most recent terminal rollout status
        self.rollouts = 0      # begun (incl. rejected)
        router.weight_sync = self
        # respawns come back on the LAST COMMITTED version, whatever
        # params the user's factory bakes in — and every incarnation
        # (re-)stamps its version so the serve stream never goes
        # unversioned after a death
        for rep in router.replicas:
            rep.factory = self._committed_factory(rep.factory)
            if rep.engine is not None:
                rep.engine.set_weight_version(self.committed_version)

    def adopt(self, rep):
        """Version-pin a replica that JOINED the fleet live (elastic
        scale-up): wrap its factory so every incarnation respawns on
        the committed version, stamp the live engine onto the committed
        params/version NOW — admission on the committed version is the
        bring-up contract — and, when a rollout is in flight, extend
        the rollout order to cover it so the fleet still converges on
        the new version after the commit."""
        rep.factory = self._committed_factory(rep.factory)
        if rep.engine is not None:
            rep.engine.swap_params(self.committed_params,
                                   version=self.committed_version)
        ro = self.active
        if ro is not None and rep.index not in ro["order"]:
            ro["order"].append(rep.index)
            self._mark("rollout_adopt", replica=rep.index,
                       version=ro["version"])

    # ------------------------------------------------------------- #
    # entry points
    # ------------------------------------------------------------- #

    def begin(self, params, version, *, _phase="rollout", _order=None):
        """Start rolling ``params`` (stamped ``version``) across the
        fleet.  Monotonicity is enforced: a stale push (version <=
        committed) is rejected without touching any engine.  Returns
        True when the rollout is admitted."""
        if self.active is not None:
            raise RuntimeError(
                f"rollout to v{self.active['version']} still in flight")
        self.rollouts += 1
        version = int(version)
        plan = faults.plan_from_env()
        corrupt = False
        if plan is not None and _phase == "rollout":
            f = plan.draw(method="swap.version_push",
                          kinds=("drop", "reset"), role="swap",
                          inline=True)
            corrupt = f.kind in ("drop", "reset")
        if _phase == "rollout" and \
                (corrupt or version <= self.committed_version):
            self.router._fail_event(
                "swap_rejected_stale", version=version,
                committed=self.committed_version,
                reason="chaos corrupt" if corrupt else "stale")
            self.last = {"version": version, "phase": _phase,
                         "state": "rejected_stale", "swapped": []}
            return False
        order = (list(_order) if _order is not None
                 else [r.index for r in self.router.replicas
                       if r.state != RETIRED])
        self.active = {
            "version": version, "params": dict(params), "phase": _phase,
            "order": order, "i": 0, "state": "quiesce",
            "swapped": [], "drain_ticks": 0, "restarts0": None,
            "timeline": [], "t0": time.perf_counter(),
        }
        self.router._event("rollout_start", version=version,
                           replicas=len(order), phase=_phase)
        self._mark("rollout_start", replicas=len(order))
        return True

    def begin_from_ps(self, ps, keys):
        """Pull ``keys`` (torn-read-guarded) plus the fleet version
        stamp from a ``ShardedPSClient`` and start that rollout."""
        params, version = ps.pull_versioned(keys)
        if version is None:
            raise ValueError(
                "PS holds no __weights_version__ stamp; "
                "set_weights_version() must accompany the weight push")
        return self.begin(params, version)

    # ------------------------------------------------------------- #
    # the state machine (driven from router.step)
    # ------------------------------------------------------------- #

    def tick(self, now=None):
        """Advance the rollout by at most one replica-state transition.
        Called by ``router.step()`` before the death-drain pass, so a
        chaos kill fired here requeues the victim's requests within the
        SAME router iteration (zero loss)."""
        ro = self.active
        if ro is None:
            return
        rep = self.router.replicas[ro["order"][ro["i"]]]
        st = ro["state"]
        if st == "quiesce":
            self._quiesce(ro, rep)
        elif st == "drain":
            self._drain(ro, rep)
        elif st == "swap":
            self._swap_and_probe(ro, rep)

    def drain(self, max_steps=10_000):
        """Step the router until the in-flight rollout (and any
        rollback it triggers) reaches a terminal state.  Returns True
        when nothing is left in flight."""
        steps = 0
        while self.active is not None and steps < max_steps:
            self.router.step()
            steps += 1
        return self.active is None

    # -- per-state handlers ---------------------------------------- #

    def _quiesce(self, ro, rep):
        idx = rep.index
        self.router._swap_hold.add(idx)
        ro["restarts0"] = rep.restarts
        ro["drain_ticks"] = 0
        ro["state"] = "drain"
        self.router._event("swap_quiesce", replica=idx,
                           version=ro["version"])
        self._mark("swap_quiesce", replica=idx)
        if self._chaos_kill(ro, rep, seam="swap.drain",
                            reason="mid_drain_kill"):
            return

    def _drain(self, ro, rep):
        idx = rep.index
        if rep.state != UP or rep.restarts != ro["restarts0"]:
            self._fail(ro, f"replica {idx} died while draining")
            return
        held = any(not self.router._routed[rid].done
                   for rid in self.router._assigned[idx])
        if held or rep.engine.pending:
            ro["drain_ticks"] += 1
            if ro["drain_ticks"] > self.drain_steps:
                self._fail(ro, f"replica {idx} failed to drain within "
                               f"{self.drain_steps} steps")
            return
        ro["state"] = "swap"
        self.router._event("swap_drained", replica=idx,
                           version=ro["version"],
                           ticks=ro["drain_ticks"])
        self._mark("swap_drained", replica=idx)

    def _swap_and_probe(self, ro, rep):
        idx = rep.index
        if rep.state != UP or rep.restarts != ro["restarts0"]:
            self._fail(ro, f"replica {idx} died before the swap")
            return
        eng = rep.engine
        try:
            eng.swap_params(ro["params"], version=ro["version"])
        except Exception as e:  # noqa: BLE001 — corrupt pytree path
            self._fail(ro, f"swap on replica {idx} rejected: {e}")
            return
        self._mark("swap_applied", replica=idx)
        # the mid-swap black box: buffers already moved, probe not run
        if self._chaos_kill(ro, rep, seam="swap.apply",
                            reason="mid_swap_kill", swapped=True):
            return
        ok = self._probe(ro, rep)
        self.router._event("swap_probe", replica=idx,
                           version=ro["version"], ok=ok)
        self._mark("swap_probe", replica=idx, ok=ok)
        if not ok:
            ro["swapped"].append(idx)   # new weights ARE live: roll back
            self._fail(ro, f"probe decode failed on replica {idx}")
            return
        ro["swapped"].append(idx)
        self.router._swap_hold.discard(idx)
        self.router._event("swap_readmit", replica=idx,
                           version=ro["version"])
        self._mark("swap_readmit", replica=idx)
        ro["i"] += 1
        self.router._event("rollout_advance", version=ro["version"],
                           done=ro["i"], replicas=len(ro["order"]))
        if ro["i"] >= len(ro["order"]):
            self._commit(ro)
        else:
            ro["state"] = "quiesce"

    def _probe(self, ro, rep):
        """One greedy decode on the quiesced, freshly swapped engine:
        it must retire, and its Result must carry the new version."""
        eng = rep.engine
        if self.probe_factory is not None:
            probe = self.probe_factory(rep.index, ro["version"])
        elif hasattr(eng, "tables"):
            # embed engine, no caller-supplied probe payload: the
            # version stamp swap_params just applied is the check
            return eng.weight_version == ro["version"]
        else:
            rid = f"swap-probe-r{rep.index}-v{ro['version']}"
            probe = Request(prompt=list(self.probe_prompt),
                            max_new_tokens=max(self.probe_tokens, 1),
                            temperature=0.0, request_id=rid, seed=0)
        try:
            res = eng.run([probe]).get(probe.request_id)
        except Exception:  # noqa: BLE001 — a crashing probe is a veto
            res = None
        rep.last_beat = time.perf_counter()
        produced = getattr(res, "n_generated", None) or \
            getattr(res, "n_pairs", 0)
        return (res is not None and produced >= 1
                and res.weight_version == ro["version"])

    # -- terminal transitions -------------------------------------- #

    def _commit(self, ro):
        if ro["phase"] == "rollout":
            self.committed_params = ro["params"]
            self.committed_version = ro["version"]
        self.router._event("rollout_done", version=ro["version"],
                           swapped=len(ro["swapped"]),
                           phase=ro["phase"])
        self._mark("rollout_done")
        state = "done" if ro["phase"] == "rollout" else "rolled_back"
        self.last = {"version": ro["version"], "phase": ro["phase"],
                     "state": state, "swapped": list(ro["swapped"])}
        self.active = None

    def _fail(self, ro, reason):
        idx = ro["order"][ro["i"]]
        self.router._swap_hold.discard(idx)
        self._mark("rollout_failed", reason=reason)
        flight.RECORDER.dump(
            "swap_rollout_failed", version=ro["version"],
            phase=ro["phase"], why=reason,
            swapped=list(ro["swapped"]), timeline=list(ro["timeline"]))
        self.router._fail_event(
            "rollout_failed", version=ro["version"], reason=reason,
            phase=ro["phase"], swapped=len(ro["swapped"]))
        self.last = {"version": ro["version"], "phase": ro["phase"],
                     "state": "failed", "reason": reason,
                     "swapped": list(ro["swapped"])}
        self.active = None
        if ro["phase"] != "rollout":
            return  # a failing rollback does not recurse; respawns
            # (committed-version factories) still converge the fleet
        # roll already-swapped, still-alive replicas back to committed
        # (a dead one respawns on committed by itself)
        back = [i for i in ro["swapped"]
                if self.router.replicas[i].state == UP
                and self.router.replicas[i].engine.weight_version
                == ro["version"]]
        if self.rollback and back:
            self.router._event("rollout_rollback",
                               version=self.committed_version,
                               replicas=len(back))
            self.begin(self.committed_params, self.committed_version,
                       _phase="rollback", _order=back)
        elif not back:
            # nothing swapped stayed up: the fleet is already entirely
            # on the committed version — a clean rollback by vacuity
            self.last["state"] = "rolled_back"

    # -- chaos + bookkeeping --------------------------------------- #

    def _chaos_kill(self, ro, rep, *, seam, reason, swapped=False):
        """Draw the role=swap kill seam; on a hit the TARGET replica
        dies (the router requeues its work this same step) and the
        rollout fails over to rollback."""
        if ro["phase"] != "rollout":
            return False   # rollback is the recovery path: no seams
        plan = faults.plan_from_env()
        if plan is None:
            return False
        f = plan.draw(method=seam, kinds=("kill",), role="swap",
                      inline=True)
        if f.kind != "kill":
            return False
        if swapped:
            ro["swapped"].append(rep.index)
        flight.RECORDER.dump("swap_chaos_kill", replica=rep.index,
                             seam=seam, version=ro["version"])
        rep.die(rc=-9, error=f"chaos swap kill ({seam})")
        self._fail(ro, reason)
        return True

    def _committed_factory(self, orig):
        def factory(index):
            eng = orig(index)
            if self.committed_version is not None:
                eng.swap_params(self.committed_params,
                                version=self.committed_version)
            return eng
        return factory

    def _mark(self, event, **fields):
        if self.active is not None:
            self.active["timeline"].append(dict(
                t=round(time.perf_counter() - self.active["t0"], 6),
                event=event, **fields))

    # ------------------------------------------------------------- #
    # observability
    # ------------------------------------------------------------- #

    @property
    def state(self):
        """'rolling' / 'rolling_back' while in flight, else the last
        terminal state ('done'/'failed'/'rolled_back'/
        'rejected_stale'), or 'idle' before any rollout."""
        if self.active is not None:
            return ("rolling" if self.active["phase"] == "rollout"
                    else "rolling_back")
        return self.last["state"] if self.last else "idle"

    def fleet_versions(self):
        """{replica index -> weight_version} for UP replicas."""
        return {r.index: r.engine.weight_version
                for r in self.router.replicas if r.state == UP}

    def snapshot(self):
        """JSON-able rollout view (rides ``router.snapshot()``)."""
        out = {"committed_version": self.committed_version,
               "state": self.state, "rollouts": self.rollouts}
        if self.active is not None:
            out["rolling"] = {
                "version": self.active["version"],
                "phase": self.active["phase"],
                "done": self.active["i"],
                "replicas": len(self.active["order"]),
                "replica_state": self.active["state"],
            }
        if self.last is not None:
            out["last"] = {k: v for k, v in self.last.items()
                           if k != "params"}
        return out
