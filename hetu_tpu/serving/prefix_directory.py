"""Fleet-wide prefix-cache directory: WHO holds WHICH prompt prefix.

PR 8's router is session-affine by hash — it keeps one session's warm
blocks on one replica but has no idea which replica actually holds
which prefix, so N tenants sharing a system prompt prefill it once PER
REPLICA.  ``PrefixDirectory`` closes that gap: a fleet-shared map of
prefix-hash → {replica: last-use} fed by each replica's refcounted
prefix table (``PagedKVManager.register_prefix`` fires
``on_prefix_register``/``on_prefix_evict`` callbacks the directory
wires at :meth:`attach`).  The router consults :meth:`lookup` BEFORE
the affinity hash — a request whose prompt prefix is resident on
replica R routes to R (a *directory hit*) and reuses the blocks
instead of recomputing them.

Entries are HINTS, never truth: the replica's own token-verified
``match_prefix`` is still the only thing that attaches KV, so a stale
hit (replica restarted, prefix LRU-evicted a microsecond ago, TTL
expired) degrades to a normal cold admission — never an error.
Killing the directory outright (chaos role "directory") degrades the
whole fleet to exact PR 8 session-affinity behavior.  Counters:

- ``hits``    — placed on the replica the directory suggested
- ``misses``  — no entry covered the prompt
- ``stale``   — only TTL-expired entries covered it (skipped)
- ``steals``  — the directory knew a holder but placement landed
  elsewhere (holder dead/breaker-open/full); the prefix is recomputed
  and re-registered at the new home — "stolen"

Hit/steal are stamped by the router at placement time (only it knows
where the request actually landed); miss/stale are counted here.

The map is shared mutable state: replica callbacks (register/evict)
and the router's lookup can run on different threads once engines
step concurrently, and ``lookup``/``drop_replica`` iterate dicts the
callbacks mutate.  One ``locks.TracedLock`` guards every entry-table
touch; ``_drop_replica`` is the caller-holds-the-lock internal
(attach reuses it under the same acquisition).
"""

from __future__ import annotations

import hashlib
import time

import numpy as np

from .. import envvars, locks


def prefix_hash(tokens):
    """Stable 64-bit digest of a token prefix (hex).  Collisions are
    harmless — the replica's ``match_prefix`` verifies tokens before
    attaching anything — so 64 bits is plenty for a routing hint."""
    arr = np.asarray([int(t) for t in tokens], np.int64)
    return hashlib.blake2b(arr.tobytes(), digest_size=8).hexdigest()


class _DirEntry:
    """One known prefix: its length/block span (for introspection),
    the replicas holding it with per-replica last-use stamps, and the
    tier column — which rung of the tiered store (serving/kv_tiers.py)
    holds a spilled copy ("host"/"ps"; None = HBM-resident or gone)."""

    __slots__ = ("length", "blocks", "refs", "replicas", "tier")

    def __init__(self, length, blocks):
        self.length = length
        self.blocks = blocks
        self.refs = 0                    # lifetime registrations
        self.replicas = {}               # replica index -> last-use t
        self.tier = None                 # "host" / "ps" / None


class PrefixDirectory:
    """The fleet map.  ``ttl`` seconds bound how long an un-refreshed
    entry stays routable (``$HETU_DIRECTORY_TTL``; 0 = hints never
    expire — the token-verified degradation path still catches every
    lie, TTL just caps how often it has to)."""

    def __init__(self, *, ttl=None, now=None):
        if ttl is None:
            ttl = envvars.get_float("HETU_DIRECTORY_TTL")
        self.ttl = float(ttl or 0.0)
        self._now = now or time.perf_counter
        self._mu = locks.TracedLock("prefix.dir")
        self._entries = {}               # hash -> _DirEntry
        self._block = None               # fleet block size (from attach)
        self.hits = 0
        self.misses = 0
        self.stale = 0
        self.steals = 0
        self.registrations = 0
        self.evictions = 0
        # tiered KV (ISSUE 17): the router flips ``tiered`` when a
        # TieredKVStore is wired — evictions then DEMOTE entries whose
        # spilled copy is tier-resident instead of deleting them, so
        # lookup keeps answering "warm somewhere"; with tiering off the
        # delete semantics are exactly as before
        self.tiered = False
        self.demotions = 0
        self.tier_hits = 0

    # ------------------------------------------------------------- #
    # replica feed
    # ------------------------------------------------------------- #

    def attach(self, replica, kv):
        """Wire a replica's paged manager into the directory.  Called
        on every (re)start: a respawned replica's old entries are
        dropped first — its fresh pool holds nothing.  A contiguous or
        non-sharing manager attaches as a no-op (the fleet then simply
        never produces directory hits for that replica)."""
        with self._mu:
            self._drop_replica(replica)
        if not getattr(kv, "prefix_share", False):
            return
        block = getattr(kv, "block", None)
        if block is None:
            return
        self._block = int(block)
        kv.on_prefix_register = \
            lambda toks, e, _r=replica: self.register(_r, toks, e)
        kv.on_prefix_evict = \
            lambda toks, _r=replica: self.evict(_r, toks)

    def register(self, replica, tokens, entry=None):
        """Record that ``replica`` now holds the prefix ``tokens``
        (or refresh its last-use stamp)."""
        h = prefix_hash(tokens)
        with self._mu:
            e = self._entries.get(h)
            if e is None:
                blocks = len(entry.blocks) if entry is not None else 0
                e = self._entries[h] = _DirEntry(len(tokens), blocks)
            e.refs += 1
            e.replicas[replica] = self._now()
            self.registrations += 1

    def evict(self, replica, tokens):
        """Drop ``replica``'s claim on ``tokens`` (LRU eviction on the
        replica).  With tiering off the entry dies with its last holder
        (delete semantics, exactly as before); with tiering on, an
        entry whose spilled copy is tier-resident DEMOTES instead —
        the tier column keeps it routable until the tier fetch/drop
        clears it."""
        h = prefix_hash(tokens)
        with self._mu:
            e = self._entries.get(h)
            if e is None:
                return
            e.replicas.pop(replica, None)
            if not e.replicas:
                if self.tiered and e.tier is not None:
                    self.demotions += 1
                else:
                    del self._entries[h]
            self.evictions += 1

    def set_tier(self, tokens, tier):
        """Stamp the tier column: a spilled copy of this prefix now
        lives in ``tier``.  Creates the entry when eviction already
        deleted it — spill and evict race by a callback ordering the
        directory must not depend on."""
        h = prefix_hash(tokens)
        with self._mu:
            e = self._entries.get(h)
            if e is None:
                e = self._entries[h] = _DirEntry(len(tokens), 0)
            e.tier = tier

    def clear_tier(self, tokens):
        """Drop the tier stamp (the copy was fetched back up or tier-
        dropped); the entry dies when no replica claims it either —
        delete semantics resume once nothing holds the prefix
        anywhere."""
        h = prefix_hash(tokens)
        with self._mu:
            e = self._entries.get(h)
            if e is None:
                return
            e.tier = None
            if not e.replicas:
                del self._entries[h]

    def known(self, tokens):
        """True when ANY replica currently claims this exact prefix.
        The elastic-fleet warm/export paths use it to move only
        prefixes the directory can actually route — a prefix no entry
        names attracts no directed traffic, so its blocks are not
        worth the wire bytes."""
        with self._mu:
            return prefix_hash(tokens) in self._entries

    def drop_replica(self, replica):
        """Purge every entry naming ``replica`` (death/respawn) —
        except tier-demoted ones: a spilled copy outlives the replica
        that spilled it (that is the point of the tier ladder)."""
        with self._mu:
            self._drop_replica(replica)

    def _drop_replica(self, replica):
        # caller holds self._mu (attach purges under its acquisition)
        dead = []
        for h, e in self._entries.items():
            e.replicas.pop(replica, None)
            if not e.replicas and not (self.tiered
                                       and e.tier is not None):
                dead.append(h)
        for h in dead:
            del self._entries[h]

    # ------------------------------------------------------------- #
    # routing consult
    # ------------------------------------------------------------- #

    def _expired(self, stamp, now):
        return self.ttl > 0 and (now - stamp) > self.ttl

    def lookup(self, prompt, now=None):
        """Longest block-aligned registered prefix of ``prompt``.
        Probes block-boundary cuts longest-first (registrations are
        keyed there, and the usable share is capped below the last
        prompt position anyway); of several holders the most recently
        used wins.  Returns ``(hint, outcome)``: ``hint`` is
        ``(replica, cached_len)`` or None; ``outcome`` is None when a
        fresh holder was found (the router stamps hit/steal once it
        knows where placement landed), "tier" when NO replica holds the
        cut but a spilled copy is tier-resident (``hint`` is then
        ``(None, cached_len)`` — warm somewhere, fetched at engine
        admission), else "miss" (nothing known) or "stale" (only
        TTL-expired claims) — all but hit/steal counted here."""
        with self._mu:
            if self._block is None or len(prompt) < 2:
                self.misses += 1
                return None, "miss"
            now = self._now() if now is None else now
            p = [int(t) for t in prompt]
            top = ((len(p) - 1) // self._block) * self._block
            saw_stale = False
            for n in range(top, 0, -self._block):
                e = self._entries.get(prefix_hash(p[:n]))
                if e is None:
                    continue
                fresh = {r: ts for r, ts in e.replicas.items()
                         if not self._expired(ts, now)}
                if fresh:
                    return (max(fresh, key=fresh.get), n), None
                if e.tier is not None:
                    # no pool holds this cut but the tier ladder
                    # does: route normally — the landing replica's
                    # admission fetch re-imports the span (tier
                    # column = "warm somewhere", not "warm at")
                    self.tier_hits += 1
                    return (None, n), "tier"
                saw_stale = True
            if saw_stale:
                self.stale += 1
                return None, "stale"
            self.misses += 1
            return None, "miss"

    # ------------------------------------------------------------- #

    @property
    def lookups(self):
        return self.hits + self.misses + self.stale + self.steals

    @property
    def hit_rate(self):
        return self.hits / max(1, self.lookups)

    def snapshot(self):
        """JSON-able directory view (router snapshot / hetu_top)."""
        with self._mu:
            return self._snapshot()

    def _snapshot(self):
        return {
            "entries": len(self._entries),
            "ttl": self.ttl,
            "hits": self.hits,
            "misses": self.misses,
            "stale": self.stale,
            "steals": self.steals,
            "hit_rate": round(self.hit_rate, 4),
            "registrations": self.registrations,
            "evictions": self.evictions,
            "tiered": self.tiered,
            "tier_entries": sum(1 for e in self._entries.values()
                                if e.tier is not None),
            "tier_hits": self.tier_hits,
            "demotions": self.demotions,
        }
