"""Multi-replica serving router: health-aware routing, dead-replica
drain + requeue, and SLO-class load shedding over N supervised engines.

One ServingEngine is a single scheduler loop; a fleet is N of them
behind this router, which owns everything a fleet adds to the problem:

- **Health-aware weighted routing.**  Each placement scores the
  routable replicas by their SLO health (``engine.health()`` —
  ok/degraded/breach, PR 7's burn-rate signal) discounted by current
  load (queue depth + live slots) and picks the best, so a degraded
  replica sheds weight before it breaches and an empty replica absorbs
  bursts.  Deterministic: same fleet state, same pick.

- **Session affinity.**  ``Request.session_id`` hashes to a home
  replica (stable across the fleet's lifetime), so a returning user's
  shared-prefix KV blocks (PR 6's refcounted prefix cache) stay hot on
  the replica that already holds them.  When the home replica is
  unroutable the session is remapped to the best peer and the
  ``affinity_prefix_misses`` counter records the cold start
  (``prefix_misses`` is kept as a back-compat snapshot key).

- **Fleet prefix-cache directory.**  Session affinity only guesses
  where warm KV lives; the :class:`PrefixDirectory` KNOWS — each
  replica's refcounted prefix table feeds it registration/eviction
  events, and placement consults it BEFORE the affinity hash: a
  request whose prompt prefix is resident on replica R routes to R (a
  *directory hit*) and attaches the blocks instead of recomputing
  them, falling back to affinity on miss.  Entries are hints: a stale
  hit degrades to a cold admission (the replica's token-verified
  ``match_prefix`` is the only thing that attaches KV), and killing
  the directory (``kill_directory()`` / chaos role "directory")
  degrades the fleet to exact affinity-only behavior —
  ``HETU_ROUTER_DIRECTORY=0`` pins that mode.

- **Prefill/decode disaggregation with KV handoff.**  With
  ``HETU_ROUTER_ROLES`` marking replicas prefill-heavy or
  decode-heavy, a long prompt with no resident prefix anywhere first
  runs as a one-token prefill clone on a prefill-heavy replica; at its
  retirement the router exports the slot's KV blocks
  (``PagedKVManager.export_blocks`` — an int8 pool ships its payload +
  scale planes natively, ~4x cheaper than f32, and
  ``HETU_HANDOFF_QUANT=int8`` forces that wire for exact pools), then
  places the real request on a decode-heavy replica and imports the
  blocks there (``import_blocks`` re-registers the prompt prefix, so
  admission attaches them refcounted).  ``kv_handoff_out``/
  ``kv_handoff_in`` events pair per handoff (a trace --check rule),
  the detour's wall time lands in the ``handoff_ms`` lifecycle
  component, and every failure mode — export short, import short, no
  decode replica up — degrades to a normal cold admission, never an
  error.

- **Supervised replicas with drain + requeue.**  Replicas die (chaos
  kill, scheduler exception) and wedge (alive, silent).  Death is
  detected by state, wedge by stale heartbeat (``HETU_ROUTER_STALE``,
  the serving analog of ``HETU_LIVENESS_STALE``) — either way the
  router DRAINS the corpse from its own assignment records (a dead
  process cannot be introspected) and requeues every unretired request
  onto peers: **no request is lost**, and because outputs are a pure
  function of the Request (seed-derived rng), a requeued request's
  tokens are identical to an undisturbed run.  The lost wall time is
  attributed: a ``router_hop`` event per re-placement plus the
  ``router_hop_ms`` lifecycle component in the peer engine's
  ``ServingMetrics.snapshot()``.  The replica respawns under the
  launcher's exponential-backoff budget (``HETU_RESTART_LIMIT`` /
  ``HETU_RESTART_BACKOFF``); a spent budget is terminal
  (``replica_failed`` + flight dump).

- **Per-replica circuit breaker.**  ``HETU_ROUTER_BREAKER`` consecutive
  failures eject the replica from routing (state "open"); after a
  cooldown one half-open PROBE request is let through — retiring it
  closes the breaker, another failure reopens it with a doubled
  cooldown.  A flapping replica stops eating traffic even while the
  supervisor keeps respawning it.

- **Bounded retry + deadlines.**  A request the router holds (requeued
  off a corpse, or unplaceable) retries with exponential backoff
  (``HETU_ROUTER_RETRY_BACKOFF``) up to ``HETU_ROUTER_RETRY_LIMIT``
  times; exhaustion is a router terminal failure (event + flight dump).
  ``Request.deadline_s`` bounds how long the router may hold it before
  expiring it (``router_deadline``) instead of serving uselessly late.

- **SLO-class load shedding + backpressure.**  Under pressure (fleet
  queue fill >= ``HETU_ROUTER_SHED_QUEUE``, or any replica's SLO state
  at breach with ``HETU_ROUTER_SHED_ON_SLO``) throughput-class
  submissions are shed (:class:`RouterShed`) while latency-class
  requests keep admitting until the fleet is hard-full — keeping
  latency-class TTFT inside budget by sacrificing the traffic that
  only cares about aggregate tokens.  When every routable replica's
  queue is at capacity, ``submit`` raises plain QueueFull: the
  replicas' backpressure propagates up through the router unchanged.

- **Dynamic fleet membership (elastic fleet).**  ``add_replica()``
  grows the fleet live: the new replica spawns under the same
  supervised respawn budget, admits on the COMMITTED weight version
  (the weight-sync coordinator adopts it), prefix-warms from its peers
  through the directory-led ``export_prefix``/``import_blocks``
  handoff — only prefixes the directory can actually route — and must
  pass a half-open greedy probe decode (the breaker's readmission
  model, via the same ``_swap_hold`` quiesce set) before taking
  traffic.  ``retire_replica()`` shrinks it: the victim quiesces,
  exports its hottest prefixes to the best peer (int8-capable codec),
  then every request it held requeues onto peers via the death-drain
  path — ZERO loss, no breaker penalty (retirement is intent, not
  failure) — and its directory entries drop.  A
  :class:`~hetu_tpu.serving.autoscaler.FleetAutoscaler` attached as
  ``router.autoscaler`` gets one tick per ``step()`` and drives both
  ends from SLO burn + queue pressure; chaos seams (``HETU_CHAOS``
  ``role=autoscale``) kill the busiest peer mid-bring-up
  (``autoscale.scale_up``) or the retiring replica mid-drain
  (``autoscale.drain``).

Single-threaded by design: ``step()`` advances supervision, placement,
and every live replica exactly once, which makes chaos runs
seed-deterministic (the integration tests replay a kill and assert
zero loss).  On chip, replicas would live on separate hosts; this
in-process harness is the semantics testbed, the same way the launcher
tests supervise local processes.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import time

from .. import envvars, telemetry
from ..ps import faults
from ..telemetry import flight
from .engine import QueueFull, _STORM_REJECTS
from .kv_tiers import TieredKVStore
from .prefix_directory import PrefixDirectory
from .replica import (  # noqa: F401
    BACKOFF, DEAD, RETIRED, UP, WEDGED, Replica,
)
from .request import Request

# health-state weights for the routing score (breach still gets a
# trickle: it may be the only replica, and starving it entirely would
# turn a soft breach into a hard outage)
_HEALTH_W = {"ok": 1.0, "degraded": 0.5, "breach": 0.25}
_LEVEL = {"ok": 0, "degraded": 1, "breach": 2}

# role-fit rank per placement phase (stable sort: score order is kept
# within a rank) — a prefill-phase placement prefers prefill-heavy
# replicas, the real (decode) placement prefers decode-heavy ones,
# mixed replicas serve both
_ROLE_RANK = {
    "prefill": {"prefill": 0, "mixed": 1, "decode": 2},
    "decode": {"decode": 0, "mixed": 1, "prefill": 2},
}

_ROLES = ("prefill", "decode", "mixed")


class RouterShed(QueueFull):
    """SLO-class load shed: the fleet is under pressure and this
    request's class is the one provisioned to lose.  Subclasses
    QueueFull so a caller's backpressure handling needs no new case."""


class _Routed:
    """Router-side record of one submitted request."""

    __slots__ = ("request", "t_submit", "t_assigned", "replica",
                 "prev_replica", "hops", "retries", "next_at", "done",
                 "lost", "result", "phase", "prefill_req", "handoff",
                 "handoff_src", "t_phase")

    def __init__(self, request, t_submit):
        self.request = request
        self.t_submit = t_submit     # router clock (perf_counter)
        self.t_assigned = None       # last successful placement
        self.replica = None          # current replica index
        self.prev_replica = None     # where the last hop came from
        self.hops = 0                # requeues off dead replicas
        self.retries = 0             # failed placement attempts
        self.next_at = 0.0           # retry-backoff deadline
        self.done = False
        self.lost = False            # retry budget exhausted
        self.result = None
        # prefill/decode disaggregation: "decode" is the normal
        # lifecycle; "prefill" means a one-token clone is running (or
        # queued) on a prefill-heavy replica and the real request
        # places only after its KV blocks are exported
        self.phase = "decode"
        self.prefill_req = None      # the max_new_tokens=1 clone
        self.handoff = None          # exported KV payload in transit
        self.handoff_src = None      # replica the payload came from
        self.t_phase = None          # prefill-detour start (handoff_ms)


def _session_hash(session_id, n):
    """Stable home-replica index for a session (blake2, not python's
    salted hash(), so affinity survives process restarts)."""
    h = hashlib.blake2b(str(session_id).encode(), digest_size=8)
    return int.from_bytes(h.digest(), "little") % n


class ServingRouter:
    """Load-balance requests across N supervised ServingEngine
    replicas (see module docstring for the robustness contract).

    ``factory(index)`` builds one replica's engine — every incarnation,
    including post-death respawns, comes from it.  All engines must
    share one config (the router pre-validates prompt lengths against
    the first incarnation's ``s_max``).  Knobs default to the
    ``HETU_ROUTER_*`` / launcher env registry entries; constructor
    arguments override.
    """

    def __init__(self, factory, replicas=None, *, session_affinity=None,
                 stale=None, breaker_threshold=None,
                 breaker_cooldown=None, retry_limit=None,
                 retry_backoff=None, shed_queue=None, shed_on_slo=None,
                 restart_limit=None, restart_backoff=None,
                 directory=None, directory_ttl=None, roles=None,
                 handoff_quant=None, kv_tiers=None, log_path=None):
        n = int(replicas if replicas is not None
                else envvars.get_int("HETU_REPLICAS"))
        if n < 1:
            raise ValueError(f"a fleet needs >= 1 replica, got {n}")
        self.session_affinity = (
            session_affinity if session_affinity is not None
            else envvars.get_bool("HETU_ROUTER_AFFINITY"))
        self.stale = float(stale if stale is not None
                           else envvars.get_float("HETU_ROUTER_STALE"))
        self.breaker_threshold = int(
            breaker_threshold if breaker_threshold is not None
            else envvars.get_int("HETU_ROUTER_BREAKER"))
        self.breaker_cooldown = float(
            breaker_cooldown if breaker_cooldown is not None
            else envvars.get_float("HETU_ROUTER_BREAKER_COOLDOWN"))
        self.retry_limit = int(
            retry_limit if retry_limit is not None
            else envvars.get_int("HETU_ROUTER_RETRY_LIMIT"))
        self.retry_backoff = float(
            retry_backoff if retry_backoff is not None
            else envvars.get_float("HETU_ROUTER_RETRY_BACKOFF"))
        self.shed_queue = float(
            shed_queue if shed_queue is not None
            else envvars.get_float("HETU_ROUTER_SHED_QUEUE"))
        self.shed_on_slo = (
            shed_on_slo if shed_on_slo is not None
            else envvars.get_bool("HETU_ROUTER_SHED_ON_SLO"))
        self.log_path = log_path
        # fleet prefix-cache directory (must exist before the replicas:
        # each incarnation wires itself in via _wire_replica)
        use_dir = (directory if directory is not None
                   else envvars.get_bool("HETU_ROUTER_DIRECTORY"))
        self.directory = (PrefixDirectory(ttl=directory_ttl)
                          if use_dir else None)
        self.directory_killed = False
        # tiered KV (ISSUE 17): one fleet-shared spill/fetch ladder
        # behind every replica's pool — evicted prefix blocks tier to
        # the host ring / PS cold store instead of dropping.  None =
        # today's drop-on-evict, byte-identical (no hooks wired).
        # Must exist before the replicas: _wire_replica attaches each
        # incarnation's pool
        self.kv_tiers = (kv_tiers if kv_tiers is not None
                         else TieredKVStore.from_env())
        if self.kv_tiers is not None:
            self.kv_tiers.directory = self.directory
            if self.directory is not None:
                self.directory.tiered = True
        # prefill/decode roles, one per replica index; unlisted = mixed
        raw = roles if roles is not None \
            else envvars.get_str("HETU_ROUTER_ROLES")
        parsed = [s.strip().lower()
                  for s in str(raw or "").split(",") if s.strip()]
        for s in parsed:
            if s not in _ROLES:
                raise ValueError(
                    f"unknown replica role {s!r} (expected one of "
                    f"{_ROLES})")
        self.roles = (parsed + ["mixed"] * n)[:n]
        # handoffs need both phases represented somewhere in the fleet
        self._roles_active = ("prefill" in self.roles
                              and "decode" in self.roles)
        self.handoff_quant = handoff_quant
        # dynamic membership (add_replica) builds later replicas from
        # the same factory/budget the constructor fleet got
        self._factory = factory
        self._restart_limit = restart_limit
        self._restart_backoff = restart_backoff
        self.replicas = [
            Replica(i, factory, restart_limit=restart_limit,
                    restart_backoff=restart_backoff,
                    emit_fn=self._fail_event, kind=self.roles[i],
                    on_start=self._wire_replica)
            for i in range(n)]
        self.s_max = self.replicas[0].engine.kv.s_max
        self._routed = {}                      # rid -> _Routed
        self._assigned = {i: {} for i in range(n)}  # idx -> ordered rids
        self._pending = collections.deque()    # router-held, to place
        self._breaker = [
            {"state": "closed", "failures": 0, "open_until": 0.0,
             "probe": None, "opens": 0} for _ in range(n)]
        # live weight sync: replicas quiesced for a rolling swap are
        # excluded from placement exactly like an open breaker; the
        # WeightSyncCoordinator (router.weight_sync) owns the set and
        # gets a tick per step to advance its rollout
        self._swap_hold = set()
        self.weight_sync = None
        # elastic fleet: a FleetAutoscaler attaches itself here and
        # gets one tick per step; None = today's static behavior
        self.autoscaler = None
        self._scale_seq = 0       # unique bring-up probe request ids
        self._reject_streak = [0] * n
        self._session_last = {}                # session_id -> replica
        # counters (snapshot surface)
        self.submitted = 0
        self.finished = 0
        self.shed = 0
        self.shed_by_class = {"latency": 0, "throughput": 0}
        self.requeued = 0
        self.expired = 0
        self.lost = 0
        self.duplicates = 0
        self.affinity_prefix_misses = 0
        self.handoffs = 0
        self.handoff_failed = 0
        self.handoffs_skipped = 0
        self.handoff_bytes = 0
        self._placed = [0] * n
        self._rejects = [0] * n
        self._lat = []                         # fleet e2e latency (s)
        self._ttft = []                        # fleet submit->token1 (s)
        self._ttft_by_class = {"latency": [], "throughput": []}

    @property
    def prefix_misses(self):
        """Back-compat alias: before the directory split this counter
        (affinity remaps only) was named ``prefix_misses``."""
        return self.affinity_prefix_misses

    # ------------------------------------------------------------- #
    # directory + handoff wiring
    # ------------------------------------------------------------- #

    def _wire_replica(self, rep):
        """Per-incarnation wiring (fires from ``Replica._start``, so
        respawns rewire themselves): feed the fresh engine's prefix
        registrations into the directory and install the retire hook
        that exports a prefill-phase slot's KV before release."""
        eng = rep.engine
        if eng is None:
            return
        if self.directory is not None:
            self.directory.attach(rep.index, eng.kv)
        if self.kv_tiers is not None:
            # evictions on this incarnation's pool spill to the fleet
            # ladder; its admission path fetches back through it
            self.kv_tiers.attach(rep.index, eng.kv)
        eng.retire_hook = \
            lambda req, slot, _rep=rep: self._on_retire(_rep, req, slot)

    def _on_retire(self, rep, req, slot):
        """Engine retire hook: a prefill-phase clone is retiring with
        its slot still live — export the KV blocks now (release frees
        them a moment later)."""
        routed = self._routed.get(req.request_id)
        if routed is None or routed.phase != "prefill":
            return
        try:
            routed.handoff = rep.engine.kv.export_blocks(
                slot, self.handoff_quant)
            routed.handoff_src = rep.index
        except ValueError:
            # can't serialize (already released?): the real request
            # admits cold — degradation, not failure
            routed.handoff = None

    def kill_directory(self, reason="killed"):
        """Drop the directory: the fleet degrades to exact PR 8
        session-affinity routing (and, roles aside, no new handoffs
        start — in-flight payloads still land).  The chaos gate drives
        this mid-trace and asserts zero token loss."""
        if self.directory is None:
            return
        self.directory = None
        self.directory_killed = True
        if self.kv_tiers is not None:
            # the tier ladder survives a directory kill (engine-level
            # fetches consult the store's own index) — it just stops
            # stamping tier columns on a corpse
            self.kv_tiers.directory = None
        self._fail_event("directory_killed", reason=reason)
        flight.RECORDER.dump("directory_killed")

    def _directory_lookup(self, req, now):
        """One routing consult; returns (hint, outcome) — see
        ``PrefixDirectory.lookup``.  The chaos seam lives here: a drawn
        kill (role "directory") drops the directory mid-lookup."""
        if self.directory is None or \
                getattr(req, "prompt", None) is None:
            # payloads without a token prompt (embedding requests)
            # have no prefix to look up
            return None, None
        plan = faults.plan_from_env()
        if plan is not None:
            f = plan.draw(method="router.directory_lookup",
                          kinds=("kill",), role="directory", inline=True)
            if f is not None and f.kind == "kill":
                self.kill_directory(reason="chaos")
                return None, None
        return self.directory.lookup(req.prompt, now)

    def _handoff_applies(self, req):
        """A prefill->decode handoff is worth starting only when both
        roles exist in the fleet, the engines run the paged
        prefix-sharing layout, and the prompt spans at least one full
        block (``match_prefix`` caps sharing below the last prompt
        position, so a sub-block prompt hands off nothing)."""
        if not self._roles_active or \
                getattr(req, "prompt", None) is None:
            return False
        for r in self.replicas:
            if r.engine is not None:
                kv = r.engine.kv
                block = getattr(kv, "block", None)
                return (getattr(kv, "prefix_share", False)
                        and block is not None
                        and len(req.prompt) > block)
        return False

    def _import_handoff(self, routed, rep, now):
        """The real request just placed on ``rep``: land its prefilled
        KV there.  Emits the paired ``kv_handoff_out``/``kv_handoff_in``
        records only when the blocks actually move — an import the pool
        cannot hold degrades to a cold admission (counted, flight-
        visible, never an error)."""
        payload, src = routed.handoff, routed.handoff_src
        routed.handoff = None
        req = routed.request
        rid = req.request_id
        if rep.index == src:
            # placement landed back on the prefill replica: the clone
            # already registered the prefix there — nothing to move
            self.handoffs_skipped += 1
            return
        kv = rep.engine.kv
        slot = None
        if (getattr(kv, "prefix_share", False)
                and payload.get("layout") == "paged"
                and payload.get("block") == getattr(kv, "block", None)):
            try:
                slot = kv.import_blocks(payload, f"{rid}~handoff",
                                        prompt=req.prompt)
            except ValueError:
                slot = None
        if slot is None:
            self.handoff_failed += 1
            self._event("kv_handoff_drop", request=rid,
                        replica=rep.index, from_replica=src)
            return
        # the import slot was only a write vehicle: release it — the
        # re-registered prefix keeps the blocks alive (refcounted), and
        # this request's admission attaches them
        kv.release(slot)
        self.handoffs += 1
        nbytes = int(payload["nbytes"])
        self.handoff_bytes += nbytes
        blocks = -(-int(payload["length"]) // int(payload["block"]))
        hand_ms = (now - (routed.t_phase
                          if routed.t_phase is not None
                          else routed.t_submit)) * 1e3
        rep.engine.metrics.lc_handoff(rid, hand_ms)
        telemetry.inc("router.handoffs")
        self._event("kv_handoff_out", request=rid, replica=src,
                    to_replica=rep.index, bytes=nbytes, blocks=blocks,
                    quant=payload["quant"] or "off")
        self._event("kv_handoff_in", request=rid, replica=rep.index,
                    from_replica=src, bytes=nbytes,
                    handoff_ms=round(hand_ms, 3))

    # ------------------------------------------------------------- #
    # events
    # ------------------------------------------------------------- #

    def _event(self, kind, **fields):
        """Router request-path events ride the serve stream, next to
        the engines' records."""
        return telemetry.emit(kind, _stream="serve", _path=self.log_path,
                              **fields)

    def _fail_event(self, kind, **fields):
        """Supervision events ride the failure stream, in the
        launcher's record shape."""
        return telemetry.emit(kind, _stream="failure", **fields)

    # ------------------------------------------------------------- #
    # fleet signals
    # ------------------------------------------------------------- #

    def health(self):
        """Worst SLO health across serving replicas ("breach" when
        nothing is up: a fleet with no capacity is past degraded)."""
        states = [r.health() for r in self.replicas if r.state == UP]
        if not states:
            return "breach"
        return max(states, key=lambda s: _LEVEL.get(s, 2))

    def queue_pressure(self):
        """Aggregate queue fill fraction across serving replicas
        (1.0 with nothing up — no capacity IS full)."""
        depth = cap = 0
        for r in self.replicas:
            if r.state == UP:
                depth += r.queue_depth
                cap += r.engine.queue_limit
        return (depth / cap) if cap else 1.0

    @property
    def pending(self):
        """Submitted requests not yet retired (router-held + on
        replicas)."""
        return sum(1 for rt in self._routed.values() if not rt.done)

    def _all_terminal(self):
        return all(r.terminal for r in self.replicas)

    # ------------------------------------------------------------- #
    # circuit breaker
    # ------------------------------------------------------------- #

    def _breaker_allows(self, idx, now):
        b = self._breaker[idx]
        if b["state"] == "closed":
            return True
        if b["state"] == "open":
            if now >= b["open_until"]:
                b["state"] = "half_open"
                b["probe"] = None
                self._event("router_breaker", replica=idx,
                            state="half_open")
                return True
            return False
        # half_open: exactly one outstanding probe
        return b["probe"] is None

    def _breaker_failure(self, idx, now):
        b = self._breaker[idx]
        b["failures"] += 1
        b["probe"] = None
        if b["failures"] >= self.breaker_threshold:
            # exponential cooldown in the number of EXTRA failures: a
            # replica that keeps dying backs out of rotation for longer
            cool = self.breaker_cooldown * 2 ** (
                b["failures"] - self.breaker_threshold)
            b["open_until"] = now + cool
            if b["state"] != "open":
                b["opens"] += 1
            b["state"] = "open"
            self._event("router_breaker", replica=idx, state="open",
                        failures=b["failures"],
                        cooldown_s=round(cool, 3))

    def _breaker_success(self, idx, rid):
        b = self._breaker[idx]
        if b["state"] == "half_open" and b["probe"] == rid:
            b["state"] = "closed"
            b["failures"] = 0
            b["probe"] = None
            self._event("router_breaker", replica=idx, state="closed")
        elif b["state"] == "closed":
            b["failures"] = 0   # consecutive-failure semantics

    # ------------------------------------------------------------- #
    # placement
    # ------------------------------------------------------------- #

    def _score(self, r):
        """Health-weighted inverse-load score (higher = better)."""
        w = _HEALTH_W.get(r.health(), 0.25)
        return w / (1.0 + r.queue_depth + r.live)

    def _candidates(self, routed, now):
        """Routable replicas, best first.  With roles active the
        placement phase partitions first (prefill-phase -> prefill-
        heavy replicas lead; decode -> decode-heavy; stable, so score
        order holds within a role rank).  The session's home replica
        (stable hash) leads a decode-phase placement when affinity
        applies and it is routable — a prefill clone has no warmth to
        return to, so affinity skips it, and so does a request
        carrying an exported KV payload (the handoff brings its own
        warmth wherever it lands; the role rank should pick a
        decode-heavy home, not the session hash)."""
        cands = [r for r in self.replicas
                 if r.state == UP and r.index not in self._swap_hold
                 and self._breaker_allows(r.index, now)]
        cands.sort(key=lambda r: (-self._score(r), r.index))
        if self._roles_active:
            rank = _ROLE_RANK[routed.phase]
            cands.sort(key=lambda r: rank.get(r.kind, 1))
        sid = routed.request.session_id
        if self.session_affinity and sid is not None and cands \
                and routed.phase == "decode" and routed.handoff is None:
            home = _session_hash(sid, len(self.replicas))
            for i, r in enumerate(cands):
                if r.index == home:
                    cands.insert(0, cands.pop(i))
                    break
        return cands

    def _place(self, routed, now):
        """Try to put the request on a replica; returns True on
        success.  Placement order: directory hint first (the replica
        that HOLDS the prompt's prefix), then role fit, then session
        affinity, then health-weighted score.  A long prompt no
        replica holds, in a role-split fleet, flips the record into
        its prefill phase here (a one-token clone places instead; the
        real request follows the exported KV).  Emits router_route
        (first placement) or router_hop (requeue) and credits the
        hop's wall time to the peer engine's lifecycle tracker."""
        req = routed.request
        rid = req.request_id
        hint = outcome = None
        if routed.phase == "decode" and routed.handoff is None:
            # prefill-phase placements CREATE a prefix (nothing to look
            # up), and a request carrying a handoff payload already
            # knows where its KV is going
            hint, outcome = self._directory_lookup(req, now)
            if outcome == "tier":
                # warm somewhere, but in the tier ladder, not a pool:
                # no replica to prefer and no hit/steal to stamp — the
                # landing replica's admission fetch re-imports the span
                # (and a prefill-phase split would only recompute what
                # the fetch lands for free, so don't flip phases)
                hint = None
            elif (hint is None and routed.hops == 0
                    and routed.retries == 0
                    and self._handoff_applies(req)):
                routed.phase = "prefill"
                routed.prefill_req = dataclasses.replace(
                    req, max_new_tokens=1, stream_cb=None)
                routed.t_phase = now
        wire_req = (routed.prefill_req if routed.phase == "prefill"
                    else req)
        cands = self._candidates(routed, now)
        if hint is not None:
            for i, r in enumerate(cands):
                if r.index == hint[0]:
                    cands.insert(0, cands.pop(i))
                    break
        for r in cands:
            try:
                r.submit(wire_req)
            except QueueFull:
                self._note_reject(r.index)
                continue
            self._reject_streak[r.index] = 0
            self._placed[r.index] += 1
            b = self._breaker[r.index]
            if b["state"] == "half_open" and b["probe"] is None:
                b["probe"] = rid
            sid = req.session_id
            affinity = None
            if self.session_affinity and sid is not None \
                    and routed.phase == "decode":
                last = self._session_last.get(sid)
                affinity = "hit" if last in (None, r.index) else "miss"
                if affinity == "miss" and routed.handoff is None:
                    # the session's warm prefix blocks live elsewhere:
                    # this placement pays the cold prefill (a handoff
                    # payload is exempt — it ships the warmth along)
                    self.affinity_prefix_misses += 1
                    telemetry.inc("router.prefix_miss")
                self._session_last[sid] = r.index
            if hint is not None:
                if r.index == hint[0]:
                    outcome = "hit"
                    if self.directory is not None:
                        self.directory.hits += 1
                else:
                    # the directory knew a holder but placement landed
                    # elsewhere: the prefix gets recomputed (and
                    # re-registered) at the new home — "stolen"
                    outcome = "steal"
                    if self.directory is not None:
                        self.directory.steals += 1
            self._assigned[r.index][rid] = None
            if routed.hops:
                hop_ms = (now - (routed.t_assigned
                                 if routed.t_assigned is not None
                                 else routed.t_submit)) * 1e3
                r.engine.metrics.lc_hop(rid, hop_ms)
                self._event("router_hop", request=rid,
                            to_replica=r.index,
                            from_replica=routed.prev_replica,
                            hop=routed.hops, hop_ms=round(hop_ms, 3))
            else:
                self._event("router_route", request=rid,
                            replica=r.index, slo_class=req.slo_class,
                            phase=routed.phase,
                            **({"affinity": affinity}
                               if affinity else {}),
                            **({"directory": outcome}
                               if outcome else {}))
            routed.replica = r.index
            routed.t_assigned = now
            if routed.handoff is not None:
                self._import_handoff(routed, r, now)
            return True
        return False

    def _note_reject(self, idx):
        """Per-replica QueueFull streak -> one flight dump per storm
        (the engine-global storm detector cannot tell WHICH replica is
        drowning in a fleet)."""
        self._rejects[idx] += 1
        self._reject_streak[idx] += 1
        if self._reject_streak[idx] == _STORM_REJECTS:
            flight.RECORDER.dump(
                "replica_queue_storm", replica=idx,
                rejects=self._reject_streak[idx],
                pressure=round(self.queue_pressure(), 4))

    # ------------------------------------------------------------- #
    # shedding
    # ------------------------------------------------------------- #

    def _should_shed(self, slo_class):
        """Throughput-class traffic sheds first: under queue pressure
        or an SLO breach anywhere in the fleet, rejecting the traffic
        that only cares about aggregate tokens is what keeps
        latency-class TTFT inside budget.  Latency-class requests are
        only ever refused by hard QueueFull."""
        if slo_class == "latency":
            return False
        if self.queue_pressure() >= self.shed_queue:
            return True
        return self.shed_on_slo and self.health() == "breach"

    # ------------------------------------------------------------- #
    # the public surface (mirrors ServingEngine)
    # ------------------------------------------------------------- #

    def submit(self, request):
        """Route one Request into the fleet.  Raises :class:`RouterShed`
        (a QueueFull) when its SLO class is being shed, plain QueueFull
        when every routable replica's queue is at capacity
        (backpressure propagated up), ValueError when it can never fit,
        RuntimeError when the whole fleet is terminally dead."""
        req = request
        # capacity pre-check through the model-agnostic hook: GPT
        # requests bound prompt+budget against the fleet's S_max;
        # workloads with no sequence bound (embedding waves) return
        # None on either side and skip it
        total = req.capacity_tokens()
        if total is not None and self.s_max is not None \
                and total > self.s_max:
            raise ValueError(
                f"prompt + max_new_tokens = {total} exceeds the "
                f"fleet's S_max {self.s_max}")
        if self._all_terminal():
            raise RuntimeError(
                "fleet terminal: every replica's restart budget is "
                "spent")
        now = time.perf_counter()
        if self._should_shed(req.slo_class):
            self.shed += 1
            self.shed_by_class[req.slo_class] += 1
            self._event("router_shed", request=req.request_id,
                        slo_class=req.slo_class,
                        pressure=round(self.queue_pressure(), 4),
                        health=self.health())
            raise RouterShed(
                f"shedding {req.slo_class}-class traffic "
                f"(pressure {self.queue_pressure():.2f}, "
                f"health {self.health()})")
        routed = _Routed(req, now)
        if not self._place(routed, now):
            raise QueueFull(
                "every routable replica's queue is at capacity")
        self._routed[req.request_id] = routed
        self.submitted += 1
        return req

    def step(self):
        """One fleet iteration: respawn due replicas, detect wedges by
        stale heartbeat, drain + requeue corpses, place router-held
        requests, then advance every serving replica one scheduler
        step.  Returns the Results that retired this iteration."""
        now = time.perf_counter()
        for r in self.replicas:
            r.maybe_respawn(now)
        if self.stale > 0:
            for r in self.replicas:
                if r.alive and r.stale(self.stale, now):
                    # wedged: alive but silent — the mid-run hang.  Kill
                    # it so the death path (drain/requeue/respawn) takes
                    # over, like the launcher's HETU_LIVENESS_STALE.
                    self._fail_event(
                        "replica_wedged_kill", replica=r.index,
                        age_s=round(now - r.last_beat, 3))
                    r.die(rc=-9, error="stale heartbeat")
        if self.weight_sync is not None:
            # advance a rolling weight swap BEFORE the death drain: a
            # chaos kill the coordinator fires here requeues the
            # victim's requests within this same iteration (zero loss)
            self.weight_sync.tick(now)
        if self.autoscaler is not None:
            # the elasticity control loop rides the same single-threaded
            # step as the rollout: a scale-up's chaos kill or a retire's
            # requeue lands BEFORE this iteration's death drain + flush,
            # so displaced requests re-place with zero extra latency
            self.autoscaler.tick(now)
        for r in self.replicas:
            if r.state == DEAD and not r.drained:
                self._on_death(r, now)
        self._flush_pending(now)
        results = []
        for r in self.replicas:
            if r.state != UP:
                continue
            for res in r.step():
                out = self._finish(res, r.index)
                if out is not None:
                    results.append(out)
            if r.state == DEAD and not r.drained:
                # died mid-step: drain NOW so its requests can requeue
                # within this same router iteration
                self._on_death(r, time.perf_counter())
        telemetry.set_gauge("router.pressure",
                            round(self.queue_pressure(), 4))
        return results

    def run(self, requests=()):
        """Submit ``requests`` (stepping through backpressure) then
        step until everything retires; returns {request_id: Result}.
        Shed requests are recorded and dropped — the caller reads
        ``snapshot()['shed']`` — and never appear in the output."""
        out = {}
        for req in requests:
            while True:
                try:
                    self.submit(req)
                    break
                except RouterShed:
                    break
                except QueueFull:
                    for res in self.step():
                        out[res.request_id] = res
        while self.pending:
            for res in self.step():
                out[res.request_id] = res
        return out

    # ------------------------------------------------------------- #
    # elastic fleet membership (live add / retire)
    # ------------------------------------------------------------- #

    def add_replica(self, kind="mixed", *, warm_prefixes=None,
                    probe=True):
        """Grow the fleet live: spawn a fresh supervised replica under
        the same factory/respawn budget the constructor fleet got, at
        the next index (indexes are never reused — a retired slot's
        index stays burned, so the event stream pairs uniquely).

        Bring-up is gated before the replica takes any traffic:

        1. **committed-version admission** — the weight-sync coordinator
           (when wired) adopts it: factory wrapped so every incarnation
           respawns on the committed version, live engine stamped NOW,
           and an in-flight rollout extends its order to cover it;
        2. **prefix warming** — peers' hottest directory-known prefixes
           land via the export/import handoff codec while the replica
           is quiesced (``_swap_hold``), so its first requests hit warm
           blocks instead of cold prefill;
        3. **half-open probe** — one greedy decode must retire on the
           quiesced engine (the breaker's readmission model); a failed
           probe kills the incarnation and hands it to the supervisor
           instead of admitting a replica that cannot serve.

        The ``autoscale.scale_up`` chaos seam (role ``autoscale``)
        draws here: a drawn kill takes out the BUSIEST PEER mid-
        bring-up — the hard case, because the joining replica must
        absorb the victim's requeued load the moment it is ready.
        Returns the new replica's index."""
        if kind not in _ROLES:
            raise ValueError(f"unknown replica kind {kind!r}")
        idx = len(self.replicas)
        self.roles.append(kind)
        self._assigned[idx] = {}
        self._breaker.append({"state": "closed", "failures": 0,
                              "open_until": 0.0, "probe": None,
                              "opens": 0})
        self._reject_streak.append(0)
        self._placed.append(0)
        self._rejects.append(0)
        rep = Replica(idx, self._factory,
                      restart_limit=self._restart_limit,
                      restart_backoff=self._restart_backoff,
                      emit_fn=self._fail_event, kind=kind,
                      on_start=self._wire_replica)
        self.replicas.append(rep)
        self._roles_active = ("prefill" in self.roles
                              and "decode" in self.roles)
        if self.weight_sync is not None:
            self.weight_sync.adopt(rep)
        rep.lifecycle = "warming"
        self._swap_hold.add(idx)
        self._fail_event("replica_warming", replica=idx, role=kind)
        self._chaos_scale_kill(exclude=idx)
        warmed = self._warm_replica(rep, warm_prefixes)
        ok = self._probe_replica(rep) if probe else True
        self._swap_hold.discard(idx)
        if ok:
            rep.lifecycle = "serving"
            self._fail_event("replica_ready", replica=idx,
                             warmed_prefixes=warmed)
        else:
            # bring-up probe failed: never admit — treat it as a death
            # and let the supervisor own the respawn (which re-wires
            # and re-stamps the committed weights via the adopted
            # factory), leaving the scale_up unpaired in the stream:
            # exactly the incident the trace checker flags
            rep.die(rc=1, error="bring-up probe failed")
        return idx

    def retire_replica(self, idx, reason="manual"):
        """Shrink the fleet live, with zero request loss: quiesce the
        victim (``_swap_hold`` — no new placements), export its hottest
        directory-known prefixes to the best UP peer (its warmth must
        not die with it), requeue every request it still held through
        the death-drain records — WITHOUT a breaker penalty or a
        respawn: retirement is intent, not failure — then drop its
        directory entries and close the supervisor slot for good.

        The ``autoscale.drain`` chaos seam draws here: a drawn kill
        takes out the DRAINING replica itself mid-drain.  Zero loss
        must hold anyway — the requeue below reads the router's own
        assignment records, never the corpse (prefix export is skipped:
        the pool died with the engine; honest degradation).

        Returns the number of requeued requests."""
        rep = self.replicas[idx]
        if rep.state == RETIRED:
            return 0
        peers = [r for r in self.replicas
                 if r.index != idx and r.state == UP]
        if not peers:
            raise ValueError(
                f"cannot retire replica {idx}: no UP peer to absorb "
                f"its traffic")
        rep.lifecycle = "draining"
        self._swap_hold.add(idx)
        self._fail_event("replica_draining", replica=idx, reason=reason)
        killed = self._chaos_drain_kill(rep)
        exported, spilled = ((0, 0) if killed
                             else self._export_hot_prefixes(rep))
        assigned = self._assigned[idx]
        rids = [rid for rid in assigned if not self._routed[rid].done]
        self._assigned[idx] = {}
        for rid in rids:
            routed = self._routed[rid]
            routed.hops += 1
            routed.prev_replica = idx
            routed.replica = None
            routed.next_at = 0.0
            self.requeued += 1
            telemetry.inc("router.requeues")
            self._pending.append(routed)
        if self.directory is not None:
            self.directory.drop_replica(idx)
        rep.retire()
        self._swap_hold.discard(idx)
        self._fail_event("replica_retired", replica=idx,
                         requeued=len(rids), exported_prefixes=exported,
                         spilled_prefixes=spilled,
                         reason=reason, rids=list(rids))
        return len(rids)

    def _chaos_scale_kill(self, *, exclude):
        """``autoscale.scale_up`` seam: kill the busiest UP peer while
        the new replica (``exclude``) is mid-bring-up."""
        plan = faults.plan_from_env()
        if plan is None:
            return False
        f = plan.draw(method="autoscale.scale_up", kinds=("kill",),
                      role="autoscale", inline=True)
        if f is None or f.kind != "kill":
            return False
        peers = [r for r in self.replicas
                 if r.state == UP and r.index != exclude]
        if not peers:
            return False
        victim = max(peers,
                     key=lambda r: (r.queue_depth + r.live, -r.index))
        flight.RECORDER.dump("autoscale_chaos_kill",
                             replica=victim.index,
                             seam="autoscale.scale_up")
        victim.die(rc=-9, error="chaos autoscale kill (scale_up)")
        return True

    def _chaos_drain_kill(self, rep):
        """``autoscale.drain`` seam: kill the draining replica itself
        mid-drain (a retire that loses its subject half-way)."""
        plan = faults.plan_from_env()
        if plan is None or rep.state != UP:
            return False
        f = plan.draw(method="autoscale.drain", kinds=("kill",),
                      role="autoscale", inline=True)
        if f is None or f.kind != "kill":
            return False
        flight.RECORDER.dump("autoscale_chaos_kill", replica=rep.index,
                             seam="autoscale.drain")
        rep.die(rc=-9, error="chaos autoscale kill (drain)")
        return True

    def _ship_prefix(self, src, dst, toks, rid):
        """Move one registered prefix ``src`` replica -> ``dst``
        replica through the export/import handoff codec (int8 wire
        when ``HETU_HANDOFF_QUANT`` says so); True when the blocks
        landed.  Emits the paired ``kv_handoff_out``/``kv_handoff_in``
        records under a synthetic warm/retire rid — no request finish
        ever pairs with them, which the trace checker's handoff rule
        already tolerates (0-finish rids are exempt)."""
        try:
            payload = src.engine.kv.export_prefix(
                toks, self.handoff_quant)
        except ValueError:
            payload = None
        if payload is None:
            return False
        kv = dst.engine.kv
        try:
            slot = kv.import_blocks(payload, rid, prompt=list(toks))
        except ValueError:
            slot = None
        if slot is None:
            return False
        # the slot was only a write vehicle: the re-registered prefix
        # keeps the blocks alive (refcounted)
        kv.release(slot)
        self.handoffs += 1
        nbytes = int(payload["nbytes"])
        self.handoff_bytes += nbytes
        blocks = -(-int(payload["length"]) // int(payload["block"]))
        self._event("kv_handoff_out", request=rid, replica=src.index,
                    to_replica=dst.index, bytes=nbytes, blocks=blocks,
                    quant=payload["quant"] or "off")
        self._event("kv_handoff_in", request=rid, replica=dst.index,
                    from_replica=src.index, bytes=nbytes)
        return True

    def _warm_prefix_ok(self, rep):
        """Can this replica's engine take part in a prefix move?"""
        eng = rep.engine
        kv = getattr(eng, "kv", None) if eng is not None else None
        return kv is not None and getattr(kv, "prefix_share", False)

    def _warm_replica(self, rep, budget=None):
        """Prefix-warm a joining replica BEFORE it takes traffic:
        import its peers' hottest DIRECTORY-KNOWN prefixes (a prefix no
        directory entry names attracts no routed traffic — not worth
        the wire bytes).  Returns how many prefixes landed."""
        if budget is None:
            budget = envvars.get_int("HETU_AUTOSCALE_WARM_PREFIXES")
        if budget <= 0 or not self._warm_prefix_ok(rep):
            return 0
        block = rep.engine.kv.block
        cands = []
        for peer in self.replicas:
            if peer.index == rep.index or peer.state != UP \
                    or not self._warm_prefix_ok(peer) \
                    or peer.engine.kv.block != block:
                continue
            for toks, e in peer.engine.kv._prefix.items():
                if self.directory is not None \
                        and not self.directory.known(toks):
                    continue
                cands.append((-e.used, peer.index, toks))
        cands.sort()
        warmed = 0
        seen = set()
        for _hot, pidx, toks in cands:
            if warmed >= budget:
                break
            if toks in seen:
                continue
            seen.add(toks)
            peer = self.replicas[pidx]
            if peer.state != UP:
                continue
            rid = f"warm-r{rep.index}-{warmed}"
            if self._ship_prefix(peer, rep, toks, rid):
                warmed += 1
        return warmed

    def _export_hot_prefixes(self, rep, budget=None):
        """A retiring replica's warmth must not die with it: export its
        hottest directory-known prefixes to the best-scoring UP peer
        through the same codec warming uses.  Runs BEFORE the directory
        drop, so the peer registers as a holder while the entries that
        made these prefixes routable still exist.  A prefix no peer can
        take — no peer at all, or the best peer's pool has no room —
        SPILLS to the tier ladder instead of dying with the pool
        (pre-tier behavior dropped it outright).  Returns
        ``(exported, spilled)``."""
        if budget is None:
            budget = envvars.get_int("HETU_AUTOSCALE_WARM_PREFIXES")
        if budget <= 0 or not self._warm_prefix_ok(rep):
            return 0, 0
        kv = rep.engine.kv
        peers = [r for r in self.replicas
                 if r.index != rep.index and r.state == UP
                 and self._warm_prefix_ok(r)
                 and r.engine.kv.block == kv.block]
        hot = sorted(kv._prefix.items(), key=lambda kvp: -kvp[1].used)
        exported = spilled = 0
        for toks, _e in hot:
            if exported + spilled >= budget:
                break
            if self.directory is not None \
                    and not self.directory.known(toks):
                continue
            if peers:
                peer = max(peers,
                           key=lambda r: (self._score(r), -r.index))
                if toks in peer.engine.kv._prefix:
                    continue   # the best peer already holds it
                rid = f"retire-r{rep.index}-{exported}"
                if self._ship_prefix(rep, peer, toks, rid):
                    exported += 1
                    continue
            if self._spill_prefix(rep, toks):
                spilled += 1
        return exported, spilled

    def _spill_prefix(self, rep, toks):
        """Retire-path fallback: no peer could absorb this prefix —
        tier it (host ring / PS cold store) instead of letting it die
        with the retiring pool.  False when tiering is off or the
        ladder declined (today's drop)."""
        if self.kv_tiers is None:
            return False
        try:
            payload = rep.engine.kv.export_prefix(toks, count=False)
        except ValueError:
            payload = None
        if payload is None:
            return False
        return self.kv_tiers.spill(toks, payload, replica=rep.index)

    def _probe_replica(self, rep):
        """Half-open bring-up probe: one greedy decode must retire on
        the quiesced engine — on the committed weight version, when a
        coordinator is wired — before the replica takes fleet traffic.
        Embedding engines (no decode loop) admit on the version stamp
        alone."""
        eng = rep.engine
        if eng is None:
            return False
        if hasattr(eng, "tables"):
            return True
        self._scale_seq += 1
        rid = f"scale-probe-r{rep.index}-{self._scale_seq}"
        req = Request(prompt=[1, 2, 3], max_new_tokens=1,
                      temperature=0.0, request_id=rid, seed=0)
        try:
            res = eng.run([req]).get(rid)
        except Exception:  # noqa: BLE001 — a probe crash IS a failure
            res = None
        if res is None or res.n_generated < 1:
            return False
        if self.weight_sync is not None \
                and res.weight_version != self.weight_sync.committed_version:
            return False
        rep.last_beat = time.perf_counter()
        return True

    # ------------------------------------------------------------- #
    # failure handling
    # ------------------------------------------------------------- #

    def _on_death(self, r, now):
        """Drain a dead replica from the router's own records: every
        request it had not retired requeues onto peers (no loss), the
        breaker notes the failure, and the supervisor schedules the
        respawn (or goes terminal)."""
        self._breaker_failure(r.index, now)
        if self.directory is not None:
            # its pool died with it: every hint naming it is now a lie
            self.directory.drop_replica(r.index)
        assigned = self._assigned[r.index]
        lost = [rid for rid in assigned
                if not self._routed[rid].done]
        self._assigned[r.index] = {}
        for rid in lost:
            routed = self._routed[rid]
            routed.hops += 1
            routed.prev_replica = r.index
            routed.replica = None
            routed.next_at = 0.0
            self.requeued += 1
            telemetry.inc("router.requeues")
            self._pending.append(routed)
        r.drained = True
        self._fail_event("replica_drain", replica=r.index,
                         requeued=len(lost), rc=r.exit_code)
        r.schedule_restart(now)

    def _flush_pending(self, now):
        """Place router-held requests (requeued off corpses or backed
        off): deadline-expire, honor retry backoff, and give up —
        terminally, with a flight dump — only past the retry budget."""
        still = collections.deque()
        while self._pending:
            routed = self._pending.popleft()
            if routed.done:
                continue
            req = routed.request
            waited = now - routed.t_submit
            if req.deadline_s is not None and waited > req.deadline_s:
                routed.done = True
                self.expired += 1
                self._event("router_deadline", request=req.request_id,
                            waited_s=round(waited, 3),
                            deadline_s=req.deadline_s,
                            slo_class=req.slo_class)
                continue
            if now < routed.next_at:
                still.append(routed)
                continue
            if self._place(routed, now):
                continue
            routed.retries += 1
            if routed.retries > self.retry_limit:
                # router terminal failure for this request: budget
                # spent with nowhere to put it.  Record loudly.
                routed.done = True
                routed.lost = True
                self.lost += 1
                self._event("router_retry_exhausted",
                            request=req.request_id,
                            retries=routed.retries, hops=routed.hops)
                flight.RECORDER.dump("router_retry_exhausted",
                                     request=req.request_id,
                                     retries=routed.retries)
                continue
            routed.next_at = now + self.retry_backoff * 2 ** (
                routed.retries - 1)
            still.append(routed)
        self._pending = still

    def _finish(self, res, idx):
        """Bookkeeping for one retired Result; returns it, or None for
        a duplicate (a request must retire exactly once fleet-wide)."""
        routed = self._routed.get(res.request_id)
        if routed is None:
            return res           # not router-managed (direct submit)
        if routed.done:
            self.duplicates += 1
            return None
        if routed.phase == "prefill":
            # the one-token prefill clone retired (its KV export rode
            # the retire hook): the request is NOT finished — place the
            # real request, payload in hand, on a decode-heavy replica
            self._assigned[idx].pop(res.request_id, None)
            self._breaker_success(idx, res.request_id)
            routed.phase = "decode"
            now = time.perf_counter()
            if not self._place(routed, now):
                # decode side full right now: the retry loop owns it
                self._pending.append(routed)
            return None
        routed.done = True
        routed.result = res
        self._assigned[idx].pop(res.request_id, None)
        self.finished += 1
        now = time.perf_counter()
        self._lat.append(now - routed.t_submit)
        req = routed.request
        if req.first_token_at is not None:
            # fleet-clock TTFT: router submit -> first token, hops and
            # requeues included (the engine's ttft_s restarts per hop)
            ttft = req.first_token_at - routed.t_submit
            self._ttft.append(ttft)
            self._ttft_by_class[req.slo_class].append(ttft)
        self._breaker_success(idx, res.request_id)
        return res

    # ------------------------------------------------------------- #

    def snapshot(self):
        """JSON-able fleet view: routing/shedding/requeue counters,
        fleet-clock latency percentiles (per SLO class too), and a row
        per replica (state, health, load, breaker, restarts)."""
        pct = telemetry.percentile

        def _p(xs, q):
            v = pct(list(xs), q) if xs else None
            return round(v, 6) if v is not None else None

        classes = {}
        for cls, xs in self._ttft_by_class.items():
            classes[cls] = {
                "finished": len(xs),
                "shed": self.shed_by_class[cls],
                "ttft_p50_s": _p(xs, 50),
                "ttft_p95_s": _p(xs, 95),
                "ttft_p99_s": _p(xs, 99),
            }
        rows = []
        for r in self.replicas:
            row = r.snapshot()
            b = self._breaker[r.index]
            row["breaker"] = b["state"]
            row["breaker_opens"] = b["opens"]
            row["routed"] = self._placed[r.index]
            row["rejects"] = self._rejects[r.index]
            row["swap_hold"] = r.index in self._swap_hold
            rows.append(row)
        return {
            "replicas": rows,
            "health": self.health(),
            "queue_pressure": round(self.queue_pressure(), 4),
            "submitted": self.submitted,
            "finished": self.finished,
            "pending": self.pending,
            "shed": self.shed,
            "requeued": self.requeued,
            "expired": self.expired,
            "lost": self.lost,
            "duplicates": self.duplicates,
            # back-compat key: pre-directory dashboards read the
            # affinity remap count under this name
            "prefix_misses": self.affinity_prefix_misses,
            "affinity_prefix_misses": self.affinity_prefix_misses,
            "roles": list(self.roles),
            "directory": (self.directory.snapshot()
                          if self.directory is not None else None),
            "directory_killed": self.directory_killed,
            "directory_hit_rate": (
                round(self.directory.hit_rate, 4)
                if self.directory is not None else None),
            "handoffs": self.handoffs,
            "handoff_failed": self.handoff_failed,
            "handoffs_skipped": self.handoffs_skipped,
            "handoff_bytes": self.handoff_bytes,
            "kv_tiers": (self.kv_tiers.stats()
                         if self.kv_tiers is not None else None),
            "weight_sync": (self.weight_sync.snapshot()
                            if self.weight_sync is not None else None),
            "autoscaler": (self.autoscaler.snapshot()
                           if self.autoscaler is not None else None),
            "latency_p50_s": _p(self._lat, 50),
            "latency_p95_s": _p(self._lat, 95),
            "latency_p99_s": _p(self._lat, 99),
            "ttft_p50_s": _p(self._ttft, 50),
            "ttft_p95_s": _p(self._ttft, 95),
            "ttft_p99_s": _p(self._ttft, 99),
            "classes": classes,
        }
