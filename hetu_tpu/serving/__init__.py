"""hetu_tpu.serving: continuous-batching inference over the KV-cached
decode path.

The offline path (``models/gpt_decode.generate_fast``) compiles one
whole-generation scan per (batch, S_max) — every request in the batch
enters and leaves together, padded to the longest.  This package is the
online counterpart: an iteration-level scheduler (Orca-style continuous
batching) that admits and retires sequences BETWEEN fused decode steps,
over a slot-structured KV cache, sharing ``_decode_step`` — the same
compiled arithmetic — with the offline path.

    engine.py     ServingEngine: admission queue with backpressure, the
                  per-step admit -> prefill -> fused-decode -> retire loop
    kv_manager.py KVCacheManager: free-slot allocation + per-slot filled
                  lengths over one preallocated [L, B_slots, S_max, H, Dh]
                  cache pair, pow2-bucketed shapes; PagedKVManager: the
                  block-table paged pool (free-list block allocator,
                  refcounted copy-on-write prefix sharing, chunked
                  prefill support) — paged=/$HETU_KV_BLOCK selects it
    request.py    Request / Result dataclasses
    metrics.py    ServingMetrics: TTFT, tok/s, occupancy; JSONL events
                  (per-step prefill_ms/decode_ms attribution)

Both phases have a ragged fast path (``fast_path=``/``$HETU_SERVE_FAST``,
auto-on on TPU): admission prefills whole same-bucket GROUPS in one
batched flash-attention pass, and the fused decode step runs the paged
decode-attention kernel (kernels/decode_attention.py) so each slot
fetches only ceil(filled/block_k) KV blocks instead of streaming all of
S_max.  The masked/scan path remains the reference — greedy outputs are
token-identical between the two.

Quickstart (greedy results are token-identical to ``generate_fast``):

    from hetu_tpu.serving import ServingEngine, Request
    eng = ServingEngine(ex.var_values, cfg, slots=8)
    eng.submit(Request(prompt=[7, 8, 9], max_new_tokens=32, eos_id=50256))
    results = eng.run()           # {request_id: Result}
"""

from .request import Request, Result
from .kv_manager import (
    KVCacheManager, PagedKVManager, resolve_kv_block, round_up_pow2,
)
from .metrics import ServingMetrics
from .engine import ServingEngine, QueueFull

__all__ = [
    "ServingEngine", "QueueFull", "Request", "Result",
    "KVCacheManager", "PagedKVManager", "ServingMetrics",
    "resolve_kv_block", "round_up_pow2",
]
