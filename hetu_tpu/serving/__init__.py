"""hetu_tpu.serving: continuous-batching inference over the KV-cached
decode path.

The offline path (``models/gpt_decode.generate_fast``) compiles one
whole-generation scan per (batch, S_max) — every request in the batch
enters and leaves together, padded to the longest.  This package is the
online counterpart: an iteration-level scheduler (Orca-style continuous
batching) that admits and retires sequences BETWEEN fused decode steps,
over a slot-structured KV cache, sharing ``_decode_step`` — the same
compiled arithmetic — with the offline path.

    engine.py     ServingEngine: admission queue with backpressure, the
                  per-step admit -> prefill -> fused-decode -> retire loop
    embed_engine.py
                  EmbedServingEngine: the recommendation workload —
                  waves of (user_ids, item_ids, dense_features)
                  requests gather embeddings through CacheSparseTable
                  (int8 PS pull on miss under HETU_PS_QUANT) and score
                  in one jitted WDL/DCN/NCF tower forward; degrades
                  through a PS outage exactly like training
                  (stale-serving + replay), zero request loss
    router.py     ServingRouter: the FLEET tier — health-aware weighted
                  routing over N supervised replicas, session affinity
                  (session_id -> home replica, warm prefix blocks),
                  per-replica circuit breakers, dead/wedged-replica
                  drain + requeue with zero request loss, bounded
                  retry/deadlines, SLO-class load shedding
                  (throughput-class first), QueueFull backpressure
                  propagated up
    replica.py    Replica: one supervised engine slot — respawn under
                  the launcher's HETU_RESTART_LIMIT/BACKOFF budget,
                  chaos kill/wedge at the step seam (HETU_CHAOS
                  role=replica<k>), heartbeat for wedge detection
    kv_manager.py KVCacheManager: free-slot allocation + per-slot filled
                  lengths over one preallocated [L, B_slots, S_max, H, Dh]
                  cache pair, pow2-bucketed shapes; PagedKVManager: the
                  block-table paged pool (free-list block allocator,
                  refcounted copy-on-write prefix sharing, chunked
                  prefill support) — paged=/$HETU_KV_BLOCK selects it
    kv_tiers.py   TieredKVStore: fleet-global prefix capacity — the
                  eviction-to-tier ladder behind every paged pool
                  (HBM pool -> host-RAM LRU ring sized by
                  HETU_KV_HOST_BYTES -> sharded-PS cold store under
                  HETU_KV_PS_TIER, keyed by prefix hash, versioned);
                  refcount-zero evictions spill the int8 handoff wire
                  payload down, admission misses fetch it back up
                  token-identically via import_blocks; a dead/killed
                  PS degrades to drop-on-evict with zero request loss
    prefix_directory.py
                  PrefixDirectory: the fleet-wide prefix-cache map
                  (prefix hash -> which replica holds the KV span),
                  fed by each replica's PagedKVManager register/evict
                  callbacks; the router consults it BEFORE the
                  affinity hash, so any replica's warm cache attracts
                  matching traffic (hit/steal), with TTL staleness and
                  graceful degradation to plain affinity when killed
    weight_sync.py
                  WeightSyncCoordinator: zero-downtime rolling weight
                  swaps — quiesce one replica (breaker-style routing
                  exclusion), drain its in-flight work, swap the param
                  dict under the engine (no recompile; the spec draft
                  inherits it), probe-decode on the new version, then
                  readmit; version-stamped end to end (every serve
                  event/Result carries weight_version), chaos-gated
                  (HETU_CHAOS role=swap), auto-rollback to the last
                  committed version on any mid-swap failure
    autoscaler.py FleetAutoscaler: SLO-burn-driven elasticity — one
                  control tick per router step watching worst-replica
                  burn rate + queue pressure, scaling the fleet live
                  between HETU_FLEET_MIN/MAX with hysteresis and
                  cooldown via router.add_replica (committed-version
                  admission, prefix warming, half-open bring-up probe)
                  / router.retire_replica (quiesce, prefix export,
                  zero-loss drain onto peers); never shrinks
                  mid-rollout; chaos-gated (HETU_CHAOS role=autoscale);
                  disabled == byte-identical to the static fleet
    traffic.py    TrafficGenerator: seeded diurnal/zipf/flash traffic
                  shapes rendered to replayable TrafficSpec traces
                  (chat / long-context / CTR-shaped classes), plus
                  replay() — virtual-clock playback into a router
    request.py    Request / Result dataclasses
    metrics.py    ServingMetrics: TTFT/TPOT percentiles, tok/s,
                  occupancy; JSONL events (per-step prefill_ms/
                  decode_ms attribution); per-request LIFECYCLE tracing
                  (queue/kv_alloc/prefill/decode/requeue req_span
                  records -> per-request Perfetto tracks) with a
                  component breakdown per retirement and
                  explain_tail() naming what owns the p99 TTFT

Observability: the engine's ``health()`` reports the SLO monitor's
ok/degraded/breach state (telemetry/slo.py, ``HETU_SLO_*`` knobs or an
explicit ``slo=``), ``bin/hetu_top.py`` renders the live dashboard, and
the flight recorder (telemetry/flight.py) dumps the records leading
into an engine exception or QueueFull storm to ``$HETU_FLIGHT_LOG``.

Speculative decoding (``spec=``/``$HETU_SPEC_K``): a truncated-layer
draft — the target's own first blocks, no separate weights — proposes
up to k tokens per slot in one scanned dispatch, the target verifies
all k+1 positions in ONE batched step (the multi-token verify kernels
in kernels/decode_attention.py), longest-prefix acceptance + a bonus
token emit 1..k+1 tokens per wave, and rejected positions roll back via
``kv.truncate`` — outputs stay token-identical to plain decoding
(greedy AND sampled), with an adaptive-k controller riding a sliding
acceptance-rate window (``$HETU_SPEC_ADAPT``).

Both phases have a ragged fast path (``fast_path=``/``$HETU_SERVE_FAST``,
auto-on on TPU): admission prefills whole same-bucket GROUPS in one
batched flash-attention pass, and the fused decode step runs the paged
decode-attention kernel (kernels/decode_attention.py) so each slot
fetches only ceil(filled/block_k) KV blocks instead of streaming all of
S_max.  The masked/scan path remains the reference — greedy outputs are
token-identical between the two.

Quickstart (greedy results are token-identical to ``generate_fast``):

    from hetu_tpu.serving import ServingEngine, Request
    eng = ServingEngine(ex.var_values, cfg, slots=8)
    eng.submit(Request(prompt=[7, 8, 9], max_new_tokens=32, eos_id=50256))
    results = eng.run()           # {request_id: Result}
"""

from ..telemetry.slo import SLO, SLOMonitor
from .autoscaler import FleetAutoscaler
from .request import EmbedRequest, EmbedResult, Request, RequestCore, Result
from .kv_manager import (
    KVCacheManager, PagedKVManager, resolve_handoff_quant,
    resolve_kv_block, resolve_kv_quant, round_up_pow2,
)
from .metrics import (
    COMPONENTS, EMBED_COMPONENTS, EmbedServingMetrics, ServingMetrics,
)
from .engine import ServingEngine, QueueFull
from .embed_engine import EmbedServingEngine
from .kv_tiers import TieredKVStore
from .prefix_directory import PrefixDirectory, prefix_hash
from .replica import Replica
from .router import RouterShed, ServingRouter
from .traffic import TrafficGenerator, TrafficSpec, replay
from .weight_sync import WeightSyncCoordinator

__all__ = [
    "ServingEngine", "EmbedServingEngine", "ServingRouter", "Replica",
    "WeightSyncCoordinator", "FleetAutoscaler",
    "TrafficGenerator", "TrafficSpec", "replay",
    "QueueFull", "RouterShed", "Request", "RequestCore", "Result",
    "EmbedRequest", "EmbedResult",
    "KVCacheManager", "PagedKVManager", "ServingMetrics",
    "EmbedServingMetrics", "COMPONENTS", "EMBED_COMPONENTS",
    "SLO", "SLOMonitor", "PrefixDirectory", "TieredKVStore",
    "prefix_hash", "resolve_handoff_quant",
    "resolve_kv_block", "resolve_kv_quant", "round_up_pow2",
]
