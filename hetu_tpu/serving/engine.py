"""The continuous-batching serving engine.

Iteration-level scheduling (Orca-style): between fused decode steps the
engine retires finished sequences, frees their slots, and admits queued
requests into the holes — a short request leaves the batch the moment
it finishes instead of padding along until the longest one is done, and
a new one takes its slot on the very next step.  Prefill interleaves
with decode: each admission runs one teacher-forced prefill scan into
its slot (bucketed prompt lengths), then joins the shared fused step.

Division of labor: the DEVICE holds only the big cache pair and the
model weights; the HOST owns every piece of scheduling state (queue,
positions, current tokens, per-request rng keys, sampling settings) as
small numpy arrays passed into each jitted call — admission and
retirement are plain python between steps, no recompilation, no
device<->host cache traffic.

Determinism: each request samples from its own seed-derived rng stream
with its own traced temperature/top_k, so outputs are a pure function
of the request — identical across arrival orders and slot assignments;
greedy outputs are token-identical to offline ``generate_fast``.
"""

from __future__ import annotations

import collections
import time

import numpy as np
import jax
import jax.numpy as jnp

from .. import envvars, telemetry
from ..telemetry import flight
from ..telemetry import slo as slo_mod
from ..models.gpt_decode import (
    _infer_name, _prep_param, _pow2, _resolve_fast, resolve_draft_layers,
    resolve_serve_ragged, resolve_spec_k, serve_decode_fn,
    serve_decode_paged_fn, serve_mixed_fn, serve_mixed_paged_fn,
    serve_prefill_batch_fn, serve_prefill_batch_paged_fn,
    serve_prefill_chunk_fn, serve_prefill_fn, serve_verify_fn,
    serve_verify_paged_fn, spec_propose_fn,
)
from .kv_manager import (KVCacheManager, PagedKVManager,
                         assemble_mixed_wave, resolve_kv_block,
                         resolve_kv_quant)
from .metrics import ServingMetrics
from .request import Request, Result


class QueueFull(RuntimeError):
    """Admission backpressure: the bounded request queue is at capacity.
    Callers shed load or retry after draining (``engine.step()``)."""


# consecutive QueueFull rejections that count as a storm: the flight
# recorder dumps once per storm so the black box captures the records
# leading into sustained overload, not just the steady-state spam
_STORM_REJECTS = 8


class ServingEngine:
    """Continuous-batching engine over one model's weights.

    params: {name: array} (``executor.var_values`` or ``hf.convert_*``
    output — same contract as ``generate_fast``); config: GPTConfig;
    slots: concurrent sequences (pow2-bucketed); queue_limit: bounded
    admission queue — ``submit`` raises QueueFull beyond it;
    max_seq_len: cap on prompt+generation (defaults to the model's
    max_position_embeddings; bucketed, so nearby deployments share
    compiles); dtype: jnp.bfloat16 halves weights AND cache — default
    FOLLOWS the params' own dtype (bf16 params → bf16 cache);
    kv_quant: "int8" (default ``$HETU_KV_QUANT``) stores the KV cache
    as int8 + per-(position, head) f32 scales, ~3.7x more tokens per
    HBM byte — the decode kernels dequantize inside the online-softmax
    loop, greedy outputs stay top-1-identical on the parity gates, and
    the capacity win composes multiplicatively with paged prefix
    sharing; log_path:
    JSONL event stream (default ``$HETU_SERVE_LOG``); donate: donate the
    cache pair to the jitted steps so XLA updates it in place (default
    True — without it every step copies the whole cache, ~3ms per 100MB;
    measured 320x on the scatter alone on the CPU harness); fast_path:
    True runs the ragged serving fast path — flash prefill (one batched
    full-prompt pass per admission group) + the paged decode-attention
    kernel (each slot fetches only ceil(filled/block_k) KV blocks
    instead of streaming all of S_max) — False the masked/scan
    reference, default consults ``$HETU_SERVE_FAST`` then auto-selects
    fast on TPU (greedy outputs are identical either way; the parity
    suite pins it in interpret mode); spec: > 0 enables SPECULATIVE
    DECODING (default ``$HETU_SPEC_K``) — a truncated-layer draft
    (``spec_draft_layers`` of the target's own blocks + the shared
    final LN/tied head; default ``$HETU_SPEC_DRAFT_LAYERS`` or
    max(1, L // 4)) proposes up to ``spec`` tokens per slot per wave
    in ONE scanned dispatch, the target verifies all proposals plus
    the carried token in ONE batched step, and longest-prefix
    acceptance + the bonus token emit 1..spec+1 tokens per wave —
    outputs stay TOKEN-IDENTICAL to the non-speculative engine (greedy
    and sampled alike: accepted tokens are the target's own sequential
    samples from each request's rng stream), rejected positions roll
    back via ``kv.truncate``; spec_adapt (``$HETU_SPEC_ADAPT``, default
    on) moves the per-wave draft length through the pow2 ladder
    1..spec on a sliding acceptance-rate window.  Speculation composes
    with paged/prefix-shared/chunked/int8 KV, the fast path, TP, and
    the fleet router; the draft keeps its own small contiguous cache.

    ragged (``$HETU_SERVE_RAGGED``, auto = mixed on TPU): MIXED-MODE
    RAGGED DISPATCH — every scheduler iteration packs fresh-prompt
    prefills, chunk continuations, spec-verify blocks, and plain
    decode into ONE ragged wave (per-slot ``q_len``) and launches ONE
    fused step, instead of the phase-split prefill-then-decode
    cadence.  Decode slots no longer stall behind another request's
    prompt chunks (the ``chunk_stall`` lifecycle component collapses
    to ~0) and a step costs one dispatch regardless of the mode mix.
    Greedy outputs stay token-identical to the phase-split scheduler
    across every layout (contiguous/paged, int8, chunked, prefix
    sharing, speculation) — the parity suite pins it.

    Composes with ``tp_shard_params``: pass the placed dict and the
    fused step runs tensor-parallel (``_prep_param`` preserves the
    NamedShardings; GSPMD propagates them through prefill and decode).

    Observability: every request is lifecycle-traced (queue/kv_alloc/
    prefill/decode/requeue component breakdown per retirement —
    ``metrics.snapshot()["components"]`` and
    ``metrics.explain_tail()``); ``slo=`` takes an
    ``SLOMonitor``/list of ``SLO`` (default: the ``HETU_SLO_*``
    env-declared monitor) and ``health()`` reports its
    ok/degraded/breach state; exceptions escaping ``step()`` and
    QueueFull storms dump the flight recorder to ``$HETU_FLIGHT_LOG``.
    """

    def __init__(self, params, config, *, slots=8, queue_limit=64,
                 max_seq_len=None, name=None, dtype=None, log_path=None,
                 donate=True, fast_path=None, paged=None, kv_block=None,
                 pool_blocks=None, prefix_share=None, prefill_chunk=None,
                 kv_quant=None, slo=None, tags=None, spec=None,
                 spec_adapt=None, spec_draft_layers=None, ragged=None):
        c = config
        self._name = _infer_name(params, name)
        # dtype=None FOLLOWS the params: bf16 weights stay bf16 and the
        # cache below inherits that dtype (the old f32 default silently
        # upcast bf16 params and doubled the cache)
        self.params = {k: _prep_param(v, dtype) for k, v in params.items()
                       if k.startswith(self._name + "_")}
        # static checks (HETU_VALIDATE=1): params/config consistency
        # validated BEFORE the cache allocation and jit compiles below
        # (analysis/integration.py; no-op when validation is off)
        from ..analysis import validate_serving
        validate_serving(self.params, c, self._name)
        Dh = c.hidden_size // c.num_attention_heads
        want = int(max_seq_len or c.max_position_embeddings)
        cdtype = self.params[f"{self._name}_wte_table"].dtype
        # kv_quant="int8" (or $HETU_KV_QUANT) stores the cache as int8
        # payload + per-(position, head) f32 scales — ~3.7x more tokens
        # per HBM byte, dequantized inside the decode kernels
        self.kv_quant = resolve_kv_quant(kv_quant)
        kv_dtype = self.kv_quant or cdtype
        block = resolve_kv_block(paged, kv_block)
        self.paged = block > 0
        self.fast_path = _resolve_fast(fast_path)
        if self.paged:
            self.kv = PagedKVManager(
                layers=c.num_hidden_layers, heads=c.num_attention_heads,
                head_dim=Dh, slots=slots, max_seq_len=want,
                pos_cap=c.max_position_embeddings, dtype=kv_dtype,
                block=block, pool_blocks=pool_blocks,
                prefix_share=prefix_share)
            chunk = (prefill_chunk if prefill_chunk is not None
                     else envvars.get_int("HETU_KV_CHUNK"))
            self.chunk = max(int(chunk or 0), 0)
            self._prefill = None
            self._prefill_chunk = serve_prefill_chunk_fn(donate)
            self._prefill_batch = (serve_prefill_batch_paged_fn(donate)
                                   if self.fast_path else None)
            self._decode = serve_decode_paged_fn(
                donate, "ragged" if self.fast_path else "masked")
        else:
            self.kv = KVCacheManager(
                layers=c.num_hidden_layers, heads=c.num_attention_heads,
                head_dim=Dh, slots=slots, max_seq_len=want,
                pos_cap=c.max_position_embeddings, dtype=kv_dtype)
            self.chunk = 0
            self._prefill = serve_prefill_fn(donate)
            self._prefill_batch = (serve_prefill_batch_fn(donate)
                                   if self.fast_path else None)
            self._decode = serve_decode_fn(
                donate, "ragged" if self.fast_path else "masked")
        self.cfg_tuple = (self._name, c.num_hidden_layers,
                          c.num_attention_heads, Dh, self.kv.s_max)
        # ---- MoE serving (models/moe_decode.py): a MoEDecodeConfig
        # rides the SAME compiled cores — the hashable MoESpec joins
        # the static cfg_tuple and every serve wrapper appends one
        # trailing (load, drop, tokens) stats element the scheduler
        # strips + accounts below (_moe_take).  Dense configs leave
        # self.moe None and nothing here changes. ---- #
        from ..models.moe_decode import moe_spec_of
        self.moe = moe_spec_of(c)
        if self.moe is not None:
            self.cfg_tuple = self.cfg_tuple + (self.moe,)
            E = self.moe.num_experts
            # lifetime per-expert routing outcome (int64 — these count
            # token-assignments, top_k per token per MoE layer)
            self.expert_load = np.zeros(E, np.int64)
            self.expert_drops = np.zeros(E, np.int64)
            self.moe_tokens = 0
            self._moe_layers = self.moe.moe_layers(c.num_hidden_layers)
            self._moe_step = None   # per-step [load, drop, tokens]
        self.prefill_dispatches = 0   # jitted prefill calls (the
        # batched-admission win: a burst of k same-bucket arrivals on
        # the fast path costs ONE dispatch, not k)
        self.prefill_chunks = 0       # chunked-prefill dispatches (paged)
        self.peak_live = 0            # max concurrent admitted slots
        self.queue_limit = int(queue_limit)
        self._queue = collections.deque()
        # tags (e.g. replica=<k> from the fleet router) ride on every
        # event so N engines sharing one merged stream stay separable
        self.metrics = ServingMetrics(log_path, tags=tags)
        # optional fn(request, slot) called at retirement while the
        # slot is still live — the router's KV-handoff export seam
        self.retire_hook = None
        # SLO monitor: explicit SLOMonitor / list of SLOs / default
        # env-declared (HETU_SLO_*; empty = always "ok").  Violations
        # and health transitions route through metrics.event so they
        # land in the serve stream next to the request records.
        if isinstance(slo, slo_mod.SLOMonitor):
            self.slo = slo
            self.slo.emit_fn = self.metrics.event
        elif slo is not None:
            self.slo = slo_mod.SLOMonitor(slo,
                                          emit_fn=self.metrics.event)
        else:
            self.slo = slo_mod.SLOMonitor.from_env(
                emit_fn=self.metrics.event)
        self._reject_streak = 0
        B = self.kv.n_slots
        self._pos = np.zeros(B, np.int32)     # input position per slot
        self._tok = np.zeros(B, np.int32)     # next input token per slot
        self._temp = np.zeros(B, np.float32)
        self._topk = np.zeros(B, np.int32)
        self._keys = np.zeros((B, 2), np.uint32)
        self._reqs = [None] * B
        self._gen = [None] * B               # generated ids per slot
        # live weight sync (serving/weight_sync.py): the version the
        # current param dict is stamped with (None = unversioned) and
        # the per-slot ADMISSION version a retirement reports — the
        # coordinator only swaps a drained engine, so the two agree
        # unless something upstream broke (exactly what the trace
        # version-coherence rule exists to catch)
        self.weight_version = None
        self.last_swap_at = None
        self._slot_version = [None] * B
        self._prefill_off = np.zeros(B, np.int32)  # paged: next prompt
        self._prompt_arr = [None] * B              # position to prefill
        self.steps = 0
        # ---- speculative decoding (spec=/$HETU_SPEC_K) ---- #
        self.spec_k = resolve_spec_k(spec)
        self.spec_adapt = False
        if self.spec_k:
            dl = resolve_draft_layers(spec_draft_layers,
                                      c.num_hidden_layers)
            self.spec_draft_layers = dl
            self.cfg_tuple_draft = (self._name, dl,
                                    c.num_attention_heads, Dh,
                                    self.kv.s_max)
            if self.moe is not None:
                # the draft SKIPS ROUTING entirely (ISSUE 20): its
                # truncated blocks run attention-only on MoE layers
                # and its wrappers append no stats element
                self.cfg_tuple_draft = self.cfg_tuple_draft + (
                    self.moe._replace(draft=True),)
            adapt = (spec_adapt if spec_adapt is not None
                     else envvars.get_bool("HETU_SPEC_ADAPT"))
            self.spec_adapt = bool(adapt) and self.spec_k > 1
            # adaptive runs ramp up from mid-ladder; pinned runs start
            # (and stay) at the configured k
            self._spec_kcur = (max(1, self.spec_k // 2)
                               if self.spec_adapt else self.spec_k)
            # the draft's OWN cache: always the small contiguous
            # layout (L_draft rows, never quantized) regardless of the
            # target's paging/quant — rollback there is pure position
            # bookkeeping, rejected rows are masked until overwritten
            dshape = (dl, B, self.kv.s_max, c.num_attention_heads, Dh)
            self._draft_ck = jnp.zeros(dshape, cdtype)
            self._draft_cv = jnp.zeros(dshape, cdtype)
            self._propose = spec_propose_fn(donate)
            self._draft_prefill = serve_prefill_fn(donate)
            attn = "ragged" if self.fast_path else "masked"
            self._verify = (serve_verify_paged_fn(donate, attn)
                            if self.paged else
                            serve_verify_fn(donate, attn))
            self._acc_window = collections.deque(maxlen=32)
            self.spec_proposed = 0    # draft tokens scored
            self.spec_accepted = 0    # draft tokens emitted
            self.spec_emitted = 0     # tokens emitted by verify waves
            self.spec_waves = 0
            self.spec_k_sum = 0       # sum of per-wave k (mean_k)
            self.spec_draft_prefills = 0
            self._spec_acc = np.zeros(B, np.int64)
            self._spec_prop = np.zeros(B, np.int64)
            self._spec_bonus = np.zeros(B, np.int64)
        # ---- mixed-mode ragged dispatch (ragged=/$HETU_SERVE_RAGGED):
        # arrivals, chunk continuations, spec-verify, and decode pack
        # into ONE ragged wave per step (see class docstring) ---- #
        self.ragged = resolve_serve_ragged(ragged)
        if self.ragged:
            attn = "ragged" if self.fast_path else "masked"
            self._mixed = (serve_mixed_paged_fn(donate, attn)
                           if self.paged else serve_mixed_fn(donate, attn))
            # tells the lifecycle accountant the wave IS the prefill:
            # chunk_stall residue is asserted near-zero and folded
            self.metrics.mixed_mode = True
        if envvars.get_bool("HETU_VALIDATE"):
            # recompile sentinel: snapshot()/assert_no_recompile() can
            # now prove the steady state stays ONE compiled core
            from ..analysis import jit_audit
            jit_audit.register_engine(self)

    # ------------------------------------------------------------- #
    # live weight sync (serving/weight_sync.py)
    # ------------------------------------------------------------- #

    def set_weight_version(self, version):
        """Stamp the CURRENT params with ``version``: rides
        ``metrics.tags`` so every subsequent serve event carries
        ``weight_version`` (the A/B and trace-coherence key)."""
        self.weight_version = int(version)
        self.metrics.tags["weight_version"] = self.weight_version

    def swap_params(self, params, *, version=None):
        """Replace the weights under the engine between steps — the
        rolling-swap primitive.  No recompile: every jitted step takes
        the param dict as an argument, so the next wave simply sees the
        new buffers (the spec-decode draft shares this dict and
        inherits the swap for free).  The new pytree must match the old
        one key-for-key and shape-for-shape (a corrupt push fails HERE,
        before any buffer moves); dtypes follow the resident params so
        the KV cache dtype contract survives the swap.  Call only on a
        drained engine (the coordinator's job) — live slots would mix
        versions mid-request."""
        name = self._name
        new = {}
        for k, v in params.items():
            if not k.startswith(name + "_"):
                continue
            old = self.params.get(k)
            p = _prep_param(v, old.dtype if old is not None else None)
            if old is not None and tuple(p.shape) != tuple(old.shape):
                raise ValueError(
                    f"swap_params: {k} has shape {tuple(p.shape)}, "
                    f"resident is {tuple(old.shape)}")
            new[k] = p
        if set(new) != set(self.params):
            missing = sorted(set(self.params) - set(new))
            extra = sorted(set(new) - set(self.params))
            raise ValueError(
                f"swap_params key mismatch: missing {missing[:4]}, "
                f"unexpected {extra[:4]}")
        self.params = new
        self.last_swap_at = time.perf_counter()
        if version is not None:
            self.set_weight_version(version)
        self.metrics.event("weight_swap", version=self.weight_version)

    # ------------------------------------------------------------- #
    # MoE accounting (models/moe_decode.py)
    # ------------------------------------------------------------- #

    def _moe_take(self, out):
        """Strip + account the trailing ``(load, drop, tokens)`` stats
        element the serve wrappers append under a MoE ``cfg_tuple``.
        Identity on dense engines, so every TARGET-cfg dispatch site
        wraps its call unconditionally; draft dispatches stay unwrapped
        (the draft spec appends nothing — it skips routing)."""
        if self.moe is None:
            return out
        load, drop, tokens = out[-1]
        load = np.asarray(load, np.int64)
        drop = np.asarray(drop, np.int64)
        tokens = int(tokens)
        self.expert_load += load
        self.expert_drops += drop
        self.moe_tokens += tokens
        if self._moe_step is None:
            self._moe_step = [load.copy(), drop.copy(), tokens]
        else:
            self._moe_step[0] += load
            self._moe_step[1] += drop
            self._moe_step[2] += tokens
        telemetry.inc("serve.expert_load", int(load.sum()))
        telemetry.inc("serve.expert_drops", int(drop.sum()))
        return out[:-1]

    def _moe_record(self):
        """Drain the per-step accumulator into a ``record_step``
        payload (None on dense engines or MoE steps that routed
        nothing).  ``routed + dropped == tokens * k * layers`` is the
        hetu_trace attribution invariant; ``imb`` (max/mean expert
        load) and ``drop_rate`` are THE MoE health observables and land
        as gauges for hetu_top."""
        if self.moe is None or self._moe_step is None:
            return None
        load, drop, tokens = self._moe_step
        self._moe_step = None
        routed = int(load.sum())
        dropped = int(drop.sum())
        mean = float(load.mean())
        imb = float(load.max()) / mean if mean > 0 else 0.0
        total = routed + dropped
        rate = dropped / total if total else 0.0
        telemetry.set_gauge("serve.expert_imbalance", imb)
        telemetry.set_gauge("serve.expert_drop_rate", rate)
        return {"tokens": tokens, "routed": routed, "dropped": dropped,
                "k": self.moe.top_k, "layers": self._moe_layers,
                "imb": imb, "drop_rate": rate,
                "load": [int(x) for x in load],
                "drop": [int(x) for x in drop]}

    @property
    def expert_imbalance(self):
        """Lifetime max/mean expert-load ratio (None on dense engines
        or before any routed token)."""
        if self.moe is None:
            return None
        mean = float(self.expert_load.mean())
        return float(self.expert_load.max()) / mean if mean > 0 else 0.0

    @property
    def expert_drop_rate(self):
        """Lifetime dropped / (routed + dropped) (None when dense)."""
        if self.moe is None:
            return None
        total = int(self.expert_load.sum() + self.expert_drops.sum())
        return int(self.expert_drops.sum()) / total if total else 0.0

    # ------------------------------------------------------------- #

    def submit(self, request):
        """Enqueue a Request; raises QueueFull at ``queue_limit``
        pending admissions (bounded-queue backpressure), ValueError if
        it can never fit the cache.  Returns the request."""
        req = request
        total = len(req.prompt) + req.max_new_tokens
        if total > self.kv.s_max:
            raise ValueError(
                f"prompt + max_new_tokens = {total} exceeds the "
                f"engine's S_max {self.kv.s_max}")
        if self.paged and \
                self.kv.blocks_needed(total) > self.kv.capacity_blocks:
            raise ValueError(
                f"request needs {self.kv.blocks_needed(total)} KV "
                f"blocks; the pool holds {self.kv.capacity_blocks}")
        if len(self._queue) >= self.queue_limit:
            self.metrics.record_reject(req.request_id, len(self._queue))
            self._reject_streak += 1
            if self._reject_streak == _STORM_REJECTS:
                # once per storm: the streak resets on the next accept
                flight.RECORDER.dump(
                    "queue_storm", rejects=self._reject_streak,
                    queue_depth=len(self._queue),
                    queue_limit=self.queue_limit)
            raise QueueFull(
                f"admission queue at capacity ({self.queue_limit})")
        self._reject_streak = 0
        req.submitted_at = time.perf_counter()
        self._queue.append(req)
        self.metrics.record_submit(req.request_id, len(self._queue))
        return req

    @property
    def pending(self):
        """Requests not yet finished (queued + in slots)."""
        return len(self._queue) + len(self.kv.live())

    @property
    def queue_depth(self):
        """Admissions waiting in the bounded queue (the router's
        backpressure/shedding signal, alongside ``health()``)."""
        return len(self._queue)

    # ------------------------------------------------------------- #

    def step(self):
        """One scheduler iteration: admit+prefill into free slots, then
        one fused decode step over every live slot, retiring finished
        sequences as their tokens land.  Returns the Results that
        completed this iteration.

        Admission runs in WAVES: each wave claims every free slot,
        groups its admissions by prompt-length bucket, and prefills one
        group per jitted dispatch (fast path — the masked reference
        keeps its per-request scan); a request that finishes AT prefill
        frees its slot for the next wave of the same step.

        An exception escaping the scheduler dumps the flight recorder
        (``$HETU_FLIGHT_LOG``) before propagating — the black box holds
        the records leading into the fault."""
        try:
            if self.ragged:
                return self._step_mixed()
            if self.paged:
                return self._step_paged()
            return self._step_contiguous()
        except QueueFull:
            raise
        except Exception as e:   # noqa: BLE001 — dump-and-reraise
            flight.RECORDER.dump(
                "engine_exception",
                error=f"{type(e).__name__}: {e}"[:200],
                step=self.steps, live=len(self.kv.live()),
                queue_depth=len(self._queue))
            raise

    def _step_contiguous(self):
        done = []
        prefill_s = 0.0
        while True:
            admits = []
            while self._queue and self.kv.free_slots:
                req = self._queue.popleft()
                t_a = time.perf_counter()
                slot = self.kv.alloc(req.request_id, len(req.prompt))
                self.metrics.lc_claimed(
                    req.request_id,
                    (time.perf_counter() - t_a) * 1e3)
                admits.append((req, slot))
            if not admits:
                break
            telemetry.inc("serve.admission_waves")
            groups = {}
            for req, slot in admits:
                pb = self.kv.bucket_prompt(len(req.prompt))
                groups.setdefault(pb, []).append((req, slot))
            for pb, group in sorted(groups.items()):
                t0 = time.perf_counter()
                if self.fast_path:
                    firsts, keys = self._prefill_group_flash(pb, group)
                else:
                    firsts, keys = self._prefill_group_ref(pb, group)
                dt = time.perf_counter() - t0
                prefill_s += dt
                self.metrics.record_prefill(
                    len(group), pb, dt, batched=self.fast_path)
                for req, _slot in group:
                    self.metrics.lc_prefill(req.request_id, dt)
                for (req, slot), tok0, key in zip(group, firsts, keys):
                    if self.spec_k:
                        t_d = time.perf_counter()
                        self._draft_prefill_slot(slot, req.prompt)
                        d_dt = time.perf_counter() - t_d
                        prefill_s += d_dt
                        self.metrics.lc_prefill(req.request_id, d_dt)
                    now = time.perf_counter()
                    req.first_token_at = now
                    self._pos[slot] = len(req.prompt)
                    self._tok[slot] = tok0
                    self._temp[slot] = req.temperature
                    self._topk[slot] = req.top_k
                    self._keys[slot] = key
                    self._reqs[slot] = req
                    self._slot_version[slot] = self.weight_version
                    self._gen[slot] = [tok0]
                    self.metrics.record_admit(
                        req.request_id, slot, now - req.submitted_at,
                        now - req.submitted_at)
                    if req.stream_cb:
                        req.stream_cb(req, tok0)
                    r = self._maybe_finish(slot, tok0)
                    if r:
                        done.append(r)   # frees the slot: next wave
        # ---- one fused decode step over all live slots ---- #
        live = self.kv.live()
        self.peak_live = max(self.peak_live, len(live))
        if live and self.spec_k:
            done.extend(self._spec_wave(live, prefill_s))
        elif live:
            wave_reqs = [self._reqs[s].request_id for s in live]
            # MoE: free (dead) slots ride the fused step but must not
            # compete for expert capacity — the live mask gates them
            # out of routing (dense engines ignore it)
            mask = np.zeros(self.kv.n_slots, bool)
            mask[live] = True
            t0 = time.perf_counter()
            sampled, ck, cv, keys = self._moe_take(self._decode(
                self.params, self.cfg_tuple,
                self.kv.cache_k, self.kv.cache_v,
                self._pos, self._tok, self._temp, self._topk, self._keys,
                live=mask))
            self.kv.cache_k, self.kv.cache_v = ck, cv
            sampled = np.asarray(sampled)
            # np.array copies: np.asarray on a jax array is a read-only
            # view, and admission writes per-slot rows into _keys
            self._keys = np.array(keys, np.uint32)
            dt = time.perf_counter() - t0
            for slot in live:
                req = self._reqs[slot]
                t = int(sampled[slot])
                self._pos[slot] += 1
                self._tok[slot] = t
                self._gen[slot].append(t)
                self.kv.advance(slot)
                if req.stream_cb:
                    req.stream_cb(req, t)
                r = self._maybe_finish(slot, t)
                if r:
                    done.append(r)
            self.steps += 1
            self.metrics.record_step(
                live=len(live), slots=self.kv.n_slots,
                queue_depth=len(self._queue), dt_s=dt,
                new_tokens=len(live), prefill_s=prefill_s,
                step=self.steps, requests=wave_reqs,
                end_perf=t0 + dt, moe=self._moe_record())
        return done

    # ------------------------------------------------------------- #

    def _prefill_group_ref(self, pb, group):
        """Reference admission: one teacher-forced prefill scan per
        request (the pre-fast-path behavior, kept bit-identical)."""
        firsts, keys = [], []
        for req, slot in group:
            P = len(req.prompt)
            prompt = np.zeros(pb, np.int32)
            prompt[:P] = req.prompt
            key = np.asarray(jax.random.PRNGKey(req.seed), np.uint32)
            first, ck, cv, key = self._moe_take(self._prefill(
                self.params, self.cfg_tuple,
                self.kv.cache_k, self.kv.cache_v,
                np.int32(slot), prompt, np.int32(P),
                np.float32(req.temperature), np.int32(req.top_k), key))
            self.kv.cache_k, self.kv.cache_v = ck, cv
            self.prefill_dispatches += 1
            firsts.append(int(first))
            keys.append(np.asarray(key))
        return firsts, keys

    def _prefill_group_flash(self, pb, group):
        """Fast-path admission: the whole same-bucket group in ONE
        batched flash-prefill dispatch.  The group size is pow2-bucketed
        (bounding the compile ladder) by REPLICATING entry 0 into the
        pad rows — duplicate cache-scatter indices then write identical
        values, so padding is order-safe and its outputs are simply
        dropped."""
        n = len(group)
        nb = min(_pow2(n), self.kv.n_slots)
        rows = list(range(n)) + [0] * (nb - n)
        prompts = np.zeros((nb, pb), np.int32)
        lens = np.zeros(nb, np.int32)
        slots = np.zeros(nb, np.int32)
        temps = np.zeros(nb, np.float32)
        topks = np.zeros(nb, np.int32)
        keys = np.zeros((nb, 2), np.uint32)
        for row, i in enumerate(rows):
            req, slot = group[i]
            P = len(req.prompt)
            prompts[row, :P] = req.prompt
            lens[row] = P
            slots[row] = slot
            temps[row] = req.temperature
            topks[row] = req.top_k
            keys[row] = np.asarray(jax.random.PRNGKey(req.seed),
                                   np.uint32)
        first, ck, cv, new_keys = self._moe_take(self._prefill_batch(
            self.params, self.cfg_tuple,
            self.kv.cache_k, self.kv.cache_v,
            slots, prompts, lens, temps, topks, keys,
            row_valid=(np.arange(nb) < n)))
        self.kv.cache_k, self.kv.cache_v = ck, cv
        self.prefill_dispatches += 1
        first = np.asarray(first)
        new_keys = np.array(new_keys, np.uint32)
        return ([int(first[i]) for i in range(n)],
                [new_keys[i] for i in range(n)])

    # ------------------------------------------------------------- #
    # paged scheduler
    # ------------------------------------------------------------- #

    def _step_paged(self):
        """One paged scheduler iteration: admit into block tables,
        advance every mid-prefill slot by one chunk (long prompts fill
        their blocks INTERLEAVED with decode waves instead of stalling
        them), then one fused block-table decode step over the slots
        whose prompts are fully written.  A request finishing at
        prefill frees capacity for another admission wave within the
        same step."""
        done = []
        prefill_s = 0.0
        while True:
            self._admit_paged()
            fin, dt = self._prefill_wave_paged()
            prefill_s += dt
            done.extend(fin)
            if not fin:
                break   # nothing retired at prefill -> no freed
                # capacity -> no further admissions this step: decode
        # a request deferred for a prefix that REGISTERED this step can
        # claim its (shared) blocks now and prefill next step
        self._admit_paged()
        # ---- fused decode over fully-prefilled slots; mid-prefill
        # slots ride along pointed at the scratch block ---- #
        live = self.kv.live()
        decoding = [s for s in live if self._gen[s] is not None]
        self.peak_live = max(self.peak_live, len(live))
        if decoding and self.spec_k:
            done.extend(self._spec_wave(decoding, prefill_s))
        elif decoding:
            wave_reqs = [self._reqs[s].request_id for s in decoding]
            B = self.kv.n_slots
            mask = np.zeros(B, bool)
            mask[decoding] = True
            t0 = time.perf_counter()
            sampled, ck, cv, keys = self._moe_take(self._decode(
                self.params, self.cfg_tuple,
                self.kv.cache_k, self.kv.cache_v,
                self.kv.tables.copy(), self._pos, mask, self._tok,
                self._temp, self._topk, self._keys))
            self.kv.cache_k, self.kv.cache_v = ck, cv
            sampled = np.asarray(sampled)
            new_keys = np.array(keys, np.uint32)
            # ONLY decoding slots consumed their rng stream: a slot
            # mid-prefill splits its key exactly once, at its final
            # prefill chunk — restore the ride-along splits
            new_keys[~mask] = self._keys[~mask]
            self._keys = new_keys
            dt = time.perf_counter() - t0
            for slot in decoding:
                req = self._reqs[slot]
                t = int(sampled[slot])
                self._pos[slot] += 1
                self._tok[slot] = t
                self._gen[slot].append(t)
                self.kv.advance(slot)
                if req.stream_cb:
                    req.stream_cb(req, t)
                r = self._maybe_finish(slot, t)
                if r:
                    done.append(r)
            self.steps += 1
            self.metrics.record_step(
                live=len(decoding), slots=self.kv.n_slots,
                queue_depth=len(self._queue), dt_s=dt,
                new_tokens=len(decoding), prefill_s=prefill_s,
                step=self.steps, requests=wave_reqs,
                end_perf=t0 + dt, moe=self._moe_record())
        return done

    def _admit_paged(self):
        """Claim slots + block tables for queued requests, FIFO, until
        slots or pool blocks run short (the head request then waits —
        backpressure, not loss).  Prefix sharing happens here: a prompt
        starting with a registered prefix attaches those blocks
        refcounted and only prefills the tail."""
        admitted = []
        with telemetry.span("serve.kv_alloc", queue=len(self._queue)):
            while self._queue:
                req = self._queue[0]
                if self._defer_for_prefix(req):
                    # waiting on another slot's in-flight prefill: the
                    # requeue clock starts at the FIRST deferral
                    self.metrics.lc_blocked(req.request_id)
                    break
                # tiered KV: a prompt no local prefix covers may be
                # warm in the host ring / PS cold store — fetch and
                # re-import it now so alloc below attaches the blocks
                self._tier_admit(req)
                t_a = time.perf_counter()
                slot, cached = self.kv.alloc(
                    req.request_id, req.prompt,
                    len(req.prompt) + req.max_new_tokens)
                if slot is None:
                    # pool/slot exhaustion: head request waits admitted
                    # capacity frees up (backpressure, not loss)
                    self.metrics.lc_blocked(req.request_id)
                    break
                self.metrics.lc_claimed(
                    req.request_id, (time.perf_counter() - t_a) * 1e3)
                self._queue.popleft()
                self._reqs[slot] = req
                self._slot_version[slot] = self.weight_version
                self._gen[slot] = None
                self._prompt_arr[slot] = np.asarray(req.prompt, np.int32)
                self._prefill_off[slot] = cached
                self._pos[slot] = 0
                self._tok[slot] = 0
                self._temp[slot] = req.temperature
                self._topk[slot] = req.top_k
                self._keys[slot] = np.asarray(
                    jax.random.PRNGKey(req.seed), np.uint32)
                admitted.append(slot)
        if admitted:
            telemetry.inc("serve.admission_waves")
        return admitted

    def _tier_admit(self, req):
        """Tier miss-escalation at admission (serving/kv_tiers.py):
        when the tier ladder holds a longer prefix of this prompt than
        the local pool does, fetch it and re-admit through
        ``import_blocks`` — token-identical to the original prefill —
        so the ``kv.alloc`` that follows attaches the blocks
        refcounted.  Local warmth always wins (a fetch never displaces
        an equal-or-longer resident prefix), and every failure mode —
        tier miss, chaos corruption, a pool too full to hold the
        import — degrades to a cold prefill, never an error.  Returns
        True when a span landed."""
        store = getattr(self.kv, "tier_store", None)
        if store is None or not getattr(self.kv, "prefix_share", False) \
                or getattr(req, "prompt", None) is None:
            return False
        hit = store.lookup(req.prompt, self.kv.block)
        if hit is None:
            return False
        toks, length, _tier = hit
        _, cached = self.kv.match_prefix(req.prompt)
        if cached >= length:
            return False   # the pool already covers at least as much
        payload = store.fetch(toks)
        if payload is None:
            return False
        try:
            slot = self.kv.import_blocks(
                payload, f"{req.request_id}~tierfetch",
                prompt=list(toks))
        except ValueError:
            slot = None
        if slot is None:
            store.note_import_failed()
            return False
        # the slot was only a write vehicle: the re-registered prefix
        # keeps the blocks alive (refcounted) for this admission
        self.kv.release(slot)
        return True

    def _defer_for_prefix(self, req):
        """True when ``req`` should WAIT one step rather than duplicate
        work: its first KV block of prompt matches a prompt another slot
        is prefilling right now, and no registered prefix covers it yet
        — once that prefill registers, this request admits with the
        blocks attached instead of recomputing them (this is what makes
        a BURST of same-system-prompt requests store the prefix once)."""
        if not self.kv.prefix_share:
            return False
        bs = self.kv.block
        pr = [int(t) for t in req.prompt]
        if len(pr) <= bs:
            return False
        _, cached = self.kv.match_prefix(pr)
        if cached >= bs:
            return False
        head = pr[:bs]
        for s in self.kv.live():
            if self._gen[s] is None and self._prompt_arr[s] is not None \
                    and len(self._prompt_arr[s]) >= bs \
                    and [int(t) for t in self._prompt_arr[s][:bs]] == head:
                telemetry.inc("serve.prefix_deferrals")
                return True
        return False

    def _prefill_wave_paged(self):
        """Advance every mid-prefill slot: fresh whole-prompt slots go
        through the batched flash dispatch on the fast path (grouped by
        prompt bucket, K/V scattered straight into their blocks); slots
        with a shared-prefix tail or a chunked long prompt advance one
        chunk through the chunk kernel.  Returns (finished Results,
        prefill seconds)."""
        t_all = time.perf_counter()
        fin = []
        pre = [s for s in self.kv.live() if self._gen[s] is None]
        if not pre:
            return fin, 0.0
        flash, chunked = [], []
        for s in pre:
            P = len(self._prompt_arr[s])
            whole = self.chunk == 0 or P <= self.chunk
            if (self.fast_path and self._prefill_off[s] == 0 and whole):
                flash.append(s)
            else:
                chunked.append(s)
        groups = {}
        for s in flash:
            pb = self.kv.bucket_prompt(len(self._prompt_arr[s]))
            groups.setdefault(pb, []).append(s)
        for pb, group in sorted(groups.items()):
            t0 = time.perf_counter()
            firsts, keys = self._flash_group_paged(pb, group)
            dt = time.perf_counter() - t0
            self.metrics.record_prefill(len(group), pb, dt, batched=True)
            for s in group:
                self.metrics.lc_prefill(self._reqs[s].request_id, dt)
            for s, tok0, key in zip(group, firsts, keys):
                r = self._finish_prefill(s, tok0, key)
                if r:
                    fin.append(r)
        for s in chunked:
            out = self._chunk_advance(s)
            if out is not None:
                r = self._finish_prefill(s, out[0], out[1])
                if r:
                    fin.append(r)
        return fin, time.perf_counter() - t_all

    def _finish_prefill(self, slot, tok0, key):
        """Prompt fully written: the slot joins the decode wave (or
        retires right here on max_new_tokens=1/instant EOS).  Registers
        the prompt's blocks for prefix sharing."""
        req = self._reqs[slot]
        if self.spec_k:
            t_d = time.perf_counter()
            self._draft_prefill_slot(slot, self._prompt_arr[slot])
            self.metrics.lc_prefill(req.request_id,
                                    time.perf_counter() - t_d)
        now = time.perf_counter()
        req.first_token_at = now
        P = len(self._prompt_arr[slot])
        self._pos[slot] = P
        self._tok[slot] = tok0
        self._keys[slot] = key
        self._gen[slot] = [tok0]
        if self.paged:
            self.kv.register_prefix(self._prompt_arr[slot], slot)
        self.metrics.record_admit(
            req.request_id, slot, now - req.submitted_at,
            now - req.submitted_at)
        if req.stream_cb:
            req.stream_cb(req, tok0)
        return self._maybe_finish(slot, tok0)

    def _chunk_advance(self, slot):
        """One prefill chunk for one slot; returns (first_token,
        new_key) when this chunk completed the prompt, else None."""
        req = self._reqs[slot]
        prompt = self._prompt_arr[slot]
        P = len(prompt)
        off = int(self._prefill_off[slot])
        if self.chunk > 0:
            C_b = min(_pow2(self.chunk, floor=8), self.kv.s_max)
            take = min(self.chunk, C_b, P - off)
        else:
            C_b = self.kv.bucket_prompt(P - off)
            take = P - off
        tokens = np.zeros(C_b, np.int32)
        tokens[:take] = prompt[off:off + take]
        bs = self.kv.block
        wblk = np.zeros(C_b, np.int32)
        woff = np.zeros(C_b, np.int32)
        for j in range(take):
            p = off + j
            wblk[j] = self.kv.tables[slot, p // bs]
            woff[j] = p % bs
        t0 = time.perf_counter()
        first, ck, cv, nk = self._moe_take(self._prefill_chunk(
            self.params, self.cfg_tuple,
            self.kv.cache_k, self.kv.cache_v,
            self.kv.tables[slot].copy(), tokens, np.int32(off),
            np.int32(take), np.float32(req.temperature),
            np.int32(req.top_k), self._keys[slot].copy(), wblk, woff))
        self.kv.cache_k, self.kv.cache_v = ck, cv
        self.prefill_dispatches += 1
        self.prefill_chunks += 1
        telemetry.inc("serve.prefill_chunks")
        self.kv.advance(slot, take)
        self._prefill_off[slot] = off + take
        dt = time.perf_counter() - t0
        self.metrics.record_prefill(1, C_b, dt, batched=False)
        self.metrics.lc_prefill(req.request_id, dt)
        if off + take >= P:
            return int(first), np.asarray(nk, np.uint32)
        return None

    def _flash_group_paged(self, pb, group):
        """Batched flash prefill into BLOCKS: one dispatch for the
        whole same-bucket group, pow2-padded by replicating entry 0
        (identical duplicate block writes — order-safe), with host-built
        (block, offset) scatter maps routing each position's K/V into
        its slot's table (pad tails hit scratch block 0)."""
        n = len(group)
        nb = min(_pow2(n), self.kv.n_slots)
        rows = list(range(n)) + [0] * (nb - n)
        prompts = np.zeros((nb, pb), np.int32)
        lens = np.zeros(nb, np.int32)
        temps = np.zeros(nb, np.float32)
        topks = np.zeros(nb, np.int32)
        keys = np.zeros((nb, 2), np.uint32)
        wblk = np.zeros((nb, pb), np.int32)
        woff = np.zeros((nb, pb), np.int32)
        bs = self.kv.block
        for row, i in enumerate(rows):
            slot = group[i]
            req = self._reqs[slot]
            P = len(self._prompt_arr[slot])
            prompts[row, :P] = self._prompt_arr[slot]
            lens[row] = P
            temps[row] = req.temperature
            topks[row] = req.top_k
            keys[row] = self._keys[slot]
            for j in range(P):
                wblk[row, j] = self.kv.tables[slot, j // bs]
                woff[row, j] = j % bs
        first, ck, cv, new_keys = self._moe_take(self._prefill_batch(
            self.params, self.cfg_tuple,
            self.kv.cache_k, self.kv.cache_v,
            prompts, lens, temps, topks, keys, wblk, woff,
            row_valid=(np.arange(nb) < n)))
        self.kv.cache_k, self.kv.cache_v = ck, cv
        self.prefill_dispatches += 1
        first = np.asarray(first)
        new_keys = np.array(new_keys, np.uint32)
        for slot in group:
            self.kv.advance(slot, len(self._prompt_arr[slot]))
            self._prefill_off[slot] = len(self._prompt_arr[slot])
        return ([int(first[i]) for i in range(n)],
                [new_keys[i] for i in range(n)])

    # ------------------------------------------------------------- #
    # mixed-mode ragged dispatch (ragged=/$HETU_SERVE_RAGGED)
    # ------------------------------------------------------------- #

    def _admit_contiguous_mixed(self):
        """Contiguous admission WITHOUT the eager prefill: the claimed
        slot's prompt joins this step's mixed wave as one ragged
        q-block (``_gen = None`` marks it mid-prefill, exactly like the
        paged scheduler's chunk slots)."""
        admitted = []
        while self._queue and self.kv.free_slots:
            req = self._queue.popleft()
            t_a = time.perf_counter()
            slot = self.kv.alloc(req.request_id, len(req.prompt))
            self.metrics.lc_claimed(
                req.request_id, (time.perf_counter() - t_a) * 1e3)
            self._reqs[slot] = req
            self._slot_version[slot] = self.weight_version
            self._gen[slot] = None
            self._prompt_arr[slot] = np.asarray(req.prompt, np.int32)
            self._prefill_off[slot] = 0
            self._pos[slot] = 0
            self._tok[slot] = 0
            self._temp[slot] = req.temperature
            self._topk[slot] = req.top_k
            self._keys[slot] = np.asarray(
                jax.random.PRNGKey(req.seed), np.uint32)
            admitted.append(slot)
        if admitted:
            telemetry.inc("serve.admission_waves")
        return admitted

    def _step_mixed(self):
        """One MIXED-MODE scheduler iteration: admissions, chunk
        continuations, spec-verify blocks, and plain decode pack into
        ONE ragged wave descriptor (per-slot ``q_len``/``first_row``)
        and launch as ONE fused dispatch — no prefill/decode phase
        barrier, so a decode slot never stalls behind another
        request's prompt chunks.  Token-identical to the phase-split
        schedulers: every slot's write positions, attention masks, and
        rng splits reproduce exactly what its mode's dedicated step
        would have done."""
        done = []
        # admission reuses the phase-split claim paths unchanged
        # (prefix sharing/COW, tier fetch, deferral, backpressure) —
        # minus the eager prefill: prompts join THIS step's wave
        if self.paged:
            self._admit_paged()
        else:
            self._admit_contiguous_mixed()
        live = self.kv.live()
        if not live:
            return done
        self.peak_live = max(self.peak_live, len(live))
        B = self.kv.n_slots
        pre = [s for s in live if self._gen[s] is None]
        decoding = [s for s in live if self._gen[s] is not None]
        wave_reqs = [self._reqs[s].request_id for s in live]
        t0 = time.perf_counter()
        # speculative draft rides AHEAD of the wave exactly as in the
        # phase-split spec scheduler (mid-prefill slots' rows are dead)
        k_cur = 0
        draft = None
        if decoding and self.spec_k:
            k_cur = self._spec_kcur
            draft, dck, dcv = self._propose(
                self.params, self.cfg_tuple_draft,
                self._draft_ck, self._draft_cv,
                self._pos.copy(), self._tok.copy(), k=k_cur)
            self._draft_ck, self._draft_cv = dck, dcv
            draft = np.asarray(draft)
        entries = {}
        chunk_take = {}   # slot -> (take, final) for prefill q-blocks
        for s in pre:
            prompt = self._prompt_arr[s]
            P = len(prompt)
            off = int(self._prefill_off[s])
            if self.paged and self.chunk > 0:
                C_b = min(_pow2(self.chunk, floor=8), self.kv.s_max)
                take = min(self.chunk, C_b, P - off)
            else:
                take = P - off
            final = off + take >= P
            # only the final chunk samples (and splits the rng) — at
            # its last row; mid-prompt chunks pass first_row == q_len
            entries[s] = ([int(t) for t in prompt[off:off + take]],
                          off, take - 1 if final else take, self.paged)
            chunk_take[s] = (take, final)
        qlen_v = {}
        for s in decoding:
            if k_cur:
                rem = self._reqs[s].max_new_tokens - len(self._gen[s])
                ql = min(k_cur + 1, rem,
                         self.kv.s_max - int(self._pos[s]))
                toks = ([int(self._tok[s])]
                        + [int(t) for t in draft[s, :ql - 1]])
                qlen_v[s] = ql
            else:
                toks = [int(self._tok[s])]
            entries[s] = (toks, int(self._pos[s]), 0, False)
        wave = assemble_mixed_wave(B, entries)
        if self.paged:
            sampled, ck, cv, after = self._moe_take(self._mixed(
                self.params, self.cfg_tuple,
                self.kv.cache_k, self.kv.cache_v,
                self.kv.tables.copy(), wave["pos"], wave["tokens"],
                wave["q_len"], wave["first_row"], wave["self_fresh"],
                self._temp, self._topk, self._keys,
                has_fresh=bool(pre)))
        else:
            sampled, ck, cv, after = self._moe_take(self._mixed(
                self.params, self.cfg_tuple,
                self.kv.cache_k, self.kv.cache_v,
                wave["pos"], wave["tokens"], wave["q_len"],
                wave["first_row"], wave["self_fresh"],
                self._temp, self._topk, self._keys))
        self.kv.cache_k, self.kv.cache_v = ck, cv
        sampled = np.asarray(sampled)
        after = np.array(after, np.uint32)
        dt = time.perf_counter() - t0
        # ---- per-mode unpack: prefill q-blocks ---- #
        q_pre = 0
        pre_credit = {}
        if pre:
            self.prefill_dispatches += 1
        for s in pre:
            req = self._reqs[s]
            take, final = chunk_take[s]
            q_pre += take
            if self.paged:
                self.kv.advance(s, take)
                self.prefill_chunks += 1
                telemetry.inc("serve.prefill_chunks")
            self._prefill_off[s] += take
            # the whole fused wave IS this request's prefill compute —
            # there is no separate decode phase to stall behind, so
            # the lifecycle's chunk_stall residue collapses to ~0.
            # Credit the elapsed wall since dispatch, not just dt:
            # an earlier slot's _finish_prefill in this same loop can
            # compile the draft prefill (~100s of ms once per process)
            # and that wall sits inside THIS request's prefill span
            # too; _retire clamps the credit to the observed wall, so
            # over-crediting is safe and the stall residue stays ~0.
            # (A LATER slot's compile is covered by the end-of-wave
            # top-up below — this eager credit exists so a request
            # that retires AT prefill still carries its share.)
            e = time.perf_counter() - t0
            self.metrics.lc_prefill(req.request_id, e)
            pre_credit[req.request_id] = e
            if final:
                r = self._finish_prefill(
                    s, int(sampled[s, take - 1]),
                    np.asarray(after[s, take - 1], np.uint32))
                if r:
                    done.append(r)
        if pre:
            self.metrics.record_prefill(len(pre), wave["q"], dt,
                                        batched=True)
        # ---- verify / decode q-blocks ---- #
        n_dec = 0
        wave_emit = wave_acc = wave_prop = 0
        for s in decoding:
            req = self._reqs[s]
            if k_cur:
                ql = qlen_v[s]
                toks = entries[s][0]
                a = 0
                while a < ql - 1 and sampled[s, a] == toks[a + 1]:
                    a += 1
                emit = [int(t) for t in sampled[s, :a + 1]]
                if req.eos_id is not None and req.eos_id in emit:
                    emit = emit[:emit.index(req.eos_id) + 1]
                n_emit = len(emit)
                accepted = min(a, n_emit)
                wave_emit += n_emit
                wave_acc += accepted
                wave_prop += ql - 1
                self._spec_acc[s] += accepted
                self._spec_prop[s] += ql - 1
                self._spec_bonus[s] += n_emit - accepted
                base = int(self._pos[s])
                self.kv.advance(s, ql)
                self.kv.truncate(s, base + n_emit)
                self._pos[s] = base + n_emit
                self._tok[s] = emit[-1]
                self._keys[s] = after[s, n_emit - 1]
                self._gen[s].extend(emit)
                if req.stream_cb:
                    for t in emit:
                        req.stream_cb(req, t)
                r = self._maybe_finish(s, emit[-1])
            else:
                t = int(sampled[s, 0])
                n_dec += 1
                self._pos[s] += 1
                self._tok[s] = t
                self._keys[s] = after[s, 0]
                self._gen[s].append(t)
                self.kv.advance(s)
                if req.stream_cb:
                    req.stream_cb(req, t)
                r = self._maybe_finish(s, t)
            if r:
                done.append(r)
        if pre_credit:
            # top every still-live prefill rider up to the FULL wave
            # elapsed: a later slot's _finish_prefill (draft-prefill
            # compile) or the verify/decode unpack runs after the
            # rider's eager credit above but inside its prefill wall —
            # without this the difference surfaces as a phantom
            # chunk_stall residue (lc_prefill no-ops for requests that
            # already retired; _retire clamps over-credit to the wall)
            t_wave = time.perf_counter() - t0
            for rid, e in pre_credit.items():
                if t_wave > e:
                    self.metrics.lc_prefill(rid, t_wave - e,
                                            count=False)
        self.steps += 1
        spec = None
        if k_cur:
            self.spec_waves += 1
            self.spec_k_sum += k_cur
            self.spec_proposed += wave_prop
            self.spec_accepted += wave_acc
            self.spec_emitted += wave_emit
            self._acc_window.append((wave_acc, wave_prop))
            self._adapt_k()
            spec = {"k": k_cur, "proposed": wave_prop,
                    "accepted": wave_acc}
        q_ver = sum(qlen_v.values())
        q_tot = max(q_pre + q_ver + n_dec, 1)
        self.metrics.record_step(
            live=len(live), slots=B, queue_depth=len(self._queue),
            dt_s=dt, new_tokens=wave_emit if k_cur else n_dec,
            prefill_s=dt * q_pre / q_tot, step=self.steps,
            requests=wave_reqs, end_perf=t0 + dt, spec=spec,
            mix={"q_prefill": q_pre, "q_verify": q_ver,
                 "q_decode": n_dec}, moe=self._moe_record())
        return done

    # ------------------------------------------------------------- #
    # speculative decoding (spec=/$HETU_SPEC_K)
    # ------------------------------------------------------------- #

    def _draft_prefill_slot(self, slot, prompt):
        """Prefill the truncated-layer draft's contiguous cache row for
        a newly admitted slot (one teacher-forced scan over the prompt
        bucket; the sampled token and rng split are discarded — the
        draft only ever proposes greedily from its own cache).  Also
        zeroes the slot's per-request speculation attribution: this is
        the one point both schedulers pass through exactly once per
        admission."""
        P = len(prompt)
        pb = self.kv.bucket_prompt(P)
        arr = np.zeros(pb, np.int32)
        arr[:P] = [int(t) for t in prompt]
        _, dck, dcv, _ = self._draft_prefill(
            self.params, self.cfg_tuple_draft,
            self._draft_ck, self._draft_cv,
            np.int32(slot), arr, np.int32(P), np.float32(0.0),
            np.int32(0), np.asarray(jax.random.PRNGKey(0), np.uint32))
        self._draft_ck, self._draft_cv = dck, dcv
        self.spec_draft_prefills += 1
        self._spec_acc[slot] = 0
        self._spec_prop[slot] = 0
        self._spec_bonus[slot] = 0

    def _adapt_k(self):
        """Sliding-window acceptance-rate controller: raise the draft
        length through the pow2 ladder while acceptance stays high
        (more free tokens per wave), back off while it stays low (a
        rejected draft is a wasted draft step AND a rolled-back verify
        position).  The window clears on every move so the new k is
        judged on its own evidence."""
        if not self.spec_adapt or len(self._acc_window) < 8:
            return
        prop = sum(p for _, p in self._acc_window)
        if prop == 0:
            return
        rate = sum(a for a, _ in self._acc_window) / prop
        if rate >= 0.75 and self._spec_kcur < self.spec_k:
            self._spec_kcur = min(self._spec_kcur * 2, self.spec_k)
            self._acc_window.clear()
        elif rate <= 0.35 and self._spec_kcur > 1:
            self._spec_kcur = max(self._spec_kcur // 2, 1)
            self._acc_window.clear()

    def _spec_wave(self, decoding, prefill_s):
        """One speculative wave over the decoding slots: draft-propose
        (k_cur greedy steps in ONE scanned dispatch), batched verify
        (ONE target step over all k_cur+1 positions), longest-prefix
        acceptance + bonus token, KV rollback of rejected positions.
        Emits 1..k_cur+1 tokens per slot; outputs are token-identical
        to the non-speculative wave (greedy AND sampled — accepted
        tokens are the target's own sequential samples, and the slot's
        rng stream resumes at exactly the accepted count via the
        per-position keys the verify returns).  Returns the Results
        finished this wave."""
        B = self.kv.n_slots
        Q = self.spec_k + 1
        k_cur = self._spec_kcur
        wave_reqs = [self._reqs[s].request_id for s in decoding]
        t0 = time.perf_counter()
        draft, dck, dcv = self._propose(
            self.params, self.cfg_tuple_draft,
            self._draft_ck, self._draft_cv,
            self._pos.copy(), self._tok.copy(), k=k_cur)
        self._draft_ck, self._draft_cv = dck, dcv
        draft = np.asarray(draft)
        tokens = np.zeros((B, Q), np.int32)
        tokens[:, 0] = self._tok
        tokens[:, 1:1 + k_cur] = draft
        qlen = np.zeros(B, np.int32)
        for s in decoding:
            rem = self._reqs[s].max_new_tokens - len(self._gen[s])
            qlen[s] = min(k_cur + 1, rem,
                          self.kv.s_max - int(self._pos[s]))
        if self.paged:
            sampled, ck, cv, after = self._moe_take(self._verify(
                self.params, self.cfg_tuple,
                self.kv.cache_k, self.kv.cache_v,
                self.kv.tables.copy(), self._pos, tokens, qlen,
                self._temp, self._topk, self._keys))
        else:
            sampled, ck, cv, after = self._moe_take(self._verify(
                self.params, self.cfg_tuple,
                self.kv.cache_k, self.kv.cache_v,
                self._pos, tokens, qlen, self._temp, self._topk,
                self._keys))
        self.kv.cache_k, self.kv.cache_v = ck, cv
        sampled = np.asarray(sampled)
        after = np.array(after, np.uint32)
        dt = time.perf_counter() - t0
        done = []
        wave_emit = wave_acc = wave_prop = 0
        for s in decoding:
            req = self._reqs[s]
            ql = int(qlen[s])
            a = 0
            while a < ql - 1 and sampled[s, a] == tokens[s, a + 1]:
                a += 1
            emit = [int(t) for t in sampled[s, :a + 1]]
            if req.eos_id is not None and req.eos_id in emit:
                emit = emit[:emit.index(req.eos_id) + 1]
            n_emit = len(emit)
            accepted = min(a, n_emit)   # emitted tokens that WERE the
            # draft's (the rest — at most one — is the bonus sample)
            wave_emit += n_emit
            wave_acc += accepted
            wave_prop += ql - 1
            self._spec_acc[s] += accepted
            self._spec_prop[s] += ql - 1
            self._spec_bonus[s] += n_emit - accepted
            base = int(self._pos[s])
            # the verify wrote all ql positions; keep the accepted
            # prefix + bonus, roll the rejected tail back
            self.kv.advance(s, ql)
            self.kv.truncate(s, base + n_emit)
            self._pos[s] = base + n_emit
            self._tok[s] = emit[-1]
            self._keys[s] = after[s, n_emit - 1]
            self._gen[s].extend(emit)
            if req.stream_cb:
                for t in emit:
                    req.stream_cb(req, t)
            r = self._maybe_finish(s, emit[-1])
            if r:
                done.append(r)
        self.steps += 1
        self.spec_waves += 1
        self.spec_k_sum += k_cur
        self.spec_proposed += wave_prop
        self.spec_accepted += wave_acc
        self.spec_emitted += wave_emit
        self._acc_window.append((wave_acc, wave_prop))
        self._adapt_k()
        self.metrics.record_step(
            live=len(decoding), slots=B,
            queue_depth=len(self._queue), dt_s=dt,
            new_tokens=wave_emit, prefill_s=prefill_s,
            step=self.steps, requests=wave_reqs, end_perf=t0 + dt,
            spec={"k": k_cur, "proposed": wave_prop,
                  "accepted": wave_acc}, moe=self._moe_record())
        return done

    @property
    def spec_acceptance(self):
        """Lifetime draft acceptance rate (None before any proposal)."""
        if not self.spec_k or not self.spec_proposed:
            return None
        return self.spec_accepted / self.spec_proposed

    @property
    def spec_mean_k(self):
        """Mean per-wave draft length (adaptation's observable)."""
        if not self.spec_k or not self.spec_waves:
            return None
        return self.spec_k_sum / self.spec_waves

    def run(self, requests=()):
        """Submit ``requests`` then step until everything (including
        already-pending work) drains; returns {request_id: Result}."""
        for r in requests:
            self.submit(r)
        out = {}
        while self.pending:
            for res in self.step():
                out[res.request_id] = res
        return out

    # ------------------------------------------------------------- #

    def _maybe_finish(self, slot, last_token):
        req = self._reqs[slot]
        n = len(self._gen[slot])
        if req.eos_id is not None and last_token == req.eos_id:
            reason = "eos"
        elif n >= req.max_new_tokens:
            reason = "length"
        else:
            return None
        now = time.perf_counter()
        tokens = np.concatenate([
            np.asarray(req.prompt, np.int32),
            np.asarray(self._gen[slot], np.int32)])
        spec = None
        if self.spec_k:
            # per-request speculation attribution: every generated
            # token is the prefill sample, an accepted draft, or a
            # bonus sample — accepted + bonus + 1 == n_generated, the
            # invariant hetu_trace --check enforces (rejected drafts,
            # proposed - accepted, are exempt: they cost compute, not
            # sequence length)
            spec = {"accepted": int(self._spec_acc[slot]),
                    "proposed": int(self._spec_prop[slot]),
                    "bonus": int(self._spec_bonus[slot])}
        res = Result(
            request_id=req.request_id, tokens=tokens,
            prompt_len=len(req.prompt), finish_reason=reason,
            n_generated=n, ttft_s=req.first_token_at - req.submitted_at,
            latency_s=now - req.submitted_at, slot=slot,
            spec_accepted=spec["accepted"] if spec else 0,
            spec_proposed=spec["proposed"] if spec else 0,
            weight_version=self._slot_version[slot])
        self.metrics.record_finish(req.request_id, reason, n,
                                   res.latency_s, spec=spec)
        decode_s = now - req.first_token_at
        self.slo.observe(
            request_id=req.request_id, ttft_ms=res.ttft_s * 1e3,
            tok_s=((n - 1) / decode_s
                   if n > 1 and decode_s > 0 else None))
        if self.retire_hook is not None:
            # last look at the LIVE slot (the router's KV-handoff
            # export rides this) — release frees the blocks next
            self.retire_hook(req, slot)
        self._reqs[slot] = None
        self._gen[slot] = None
        self._slot_version[slot] = None
        self.kv.release(slot)
        return res

    def health(self):
        """The admission signal: the SLO monitor's worst-burn state —
        "ok" / "degraded" / "breach" (always "ok" with no SLOs
        declared).  A router shifts or sheds load on "breach"; see
        telemetry/slo.py for the burn-rate semantics."""
        return self.slo.health()
