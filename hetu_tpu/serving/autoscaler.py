"""SLO-burn-driven fleet autoscaler: grow, shrink, and rebalance a
ServingRouter fleet live, between ``HETU_FLEET_MIN`` and
``HETU_FLEET_MAX`` replicas.

The control signal is deliberately small: the worst SLO burn rate
across the fleet's monitors (telemetry/slo.py — burn >= 1 means an
error budget is being spent faster than it refills) plus the router's
aggregate queue pressure.  Galvatron-style cost-aware placement
(PAPERS.md) stays with the planner roadmap item; here cost is simply
REPLICA-SECONDS, the thing a static fleet burns all day to cover its
peak minute.

Control loop (one :meth:`tick` per ``router.step()``, exactly like the
weight-sync coordinator — no second thread, no lock):

- **scale up** after ``HETU_AUTOSCALE_UP_TICKS`` consecutive hot ticks
  (burn >= ``HETU_AUTOSCALE_UP_BURN`` or pressure >=
  ``HETU_AUTOSCALE_UP_PRESSURE``): ``router.add_replica()`` spawns a
  fresh supervised replica that admits on the COMMITTED weight version,
  prefix-warms from its peers, and probe-decodes before taking traffic.
- **scale down** after ``HETU_AUTOSCALE_DOWN_TICKS`` consecutive idle
  ticks (burn < 1 and pressure <= ``HETU_AUTOSCALE_DOWN_PRESSURE`` and
  nothing router-held): ``router.retire_replica()`` drains the
  least-loaded replica onto its peers with zero request loss.  Never
  fires mid-rollout (the version-committed quorum must hold) and never
  targets a quiesced replica.
- **hysteresis**: both streaks reset on any action and a
  ``HETU_AUTOSCALE_COOLDOWN``-tick refractory window follows, so a
  bursty signal cannot flap the fleet.

Tick-counted (not wall-clock) hysteresis keeps chaos runs and the
virtual-time traffic replay (serving/traffic.py) seed-deterministic.

Every action emits a ``scale_up``/``scale_down`` failure-stream event
(paired with ``replica_ready``/``replica_retired`` by the
``hetu_trace --check`` scale-balance rule), appends to an in-memory
scale ``timeline``, and dumps the flight ring — the scale history IS
the incident record when elasticity goes wrong.  ``enabled=False``
makes every tick a no-op: the fleet behaves byte-identically to the
static router (the degradation contract, regression-tested).
"""

from __future__ import annotations

import time

from .. import envvars, telemetry
from ..telemetry import flight
from .replica import RETIRED, UP

__all__ = ["FleetAutoscaler"]


class FleetAutoscaler:
    """Rides ``router.step()`` and resizes the fleet (see module
    docstring for the control contract).  Constructor knobs default to
    the ``HETU_FLEET_*`` / ``HETU_AUTOSCALE_*`` registry entries;
    attaching sets ``router.autoscaler`` so the router ticks it once
    per step, after supervision and placement."""

    def __init__(self, router, *, fleet_min=None, fleet_max=None,
                 up_burn=None, up_pressure=None, up_ticks=None,
                 down_pressure=None, down_ticks=None, cooldown=None,
                 warm_prefixes=None, enabled=True):
        self.router = router
        self.fleet_min = int(fleet_min if fleet_min is not None
                             else envvars.get_int("HETU_FLEET_MIN"))
        self.fleet_max = int(fleet_max if fleet_max is not None
                             else envvars.get_int("HETU_FLEET_MAX"))
        if not 1 <= self.fleet_min <= self.fleet_max:
            raise ValueError(
                f"need 1 <= fleet_min <= fleet_max, got "
                f"{self.fleet_min}..{self.fleet_max}")
        self.up_burn = float(
            up_burn if up_burn is not None
            else envvars.get_float("HETU_AUTOSCALE_UP_BURN"))
        self.up_pressure = float(
            up_pressure if up_pressure is not None
            else envvars.get_float("HETU_AUTOSCALE_UP_PRESSURE"))
        self.up_ticks = int(
            up_ticks if up_ticks is not None
            else envvars.get_int("HETU_AUTOSCALE_UP_TICKS"))
        self.down_pressure = float(
            down_pressure if down_pressure is not None
            else envvars.get_float("HETU_AUTOSCALE_DOWN_PRESSURE"))
        self.down_ticks = int(
            down_ticks if down_ticks is not None
            else envvars.get_int("HETU_AUTOSCALE_DOWN_TICKS"))
        self.cooldown = int(
            cooldown if cooldown is not None
            else envvars.get_int("HETU_AUTOSCALE_COOLDOWN"))
        self.warm_prefixes = int(
            warm_prefixes if warm_prefixes is not None
            else envvars.get_int("HETU_AUTOSCALE_WARM_PREFIXES"))
        self.enabled = bool(enabled)
        self.ticks = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.deferred_rollout = 0   # scale-downs skipped mid-rollout
        self.replica_seconds = 0.0  # wall-clock cost surface
        self.replica_ticks = 0      # virtual-clock twin: sum of actual
                                    # per tick — deterministic under
                                    # traffic.replay, so the A/B floor
                                    # compares it, not wall seconds
        self.peak_replicas = self.actual()
        self.last_action = None
        self.last_burn = 0.0
        self.last_pressure = 0.0
        self.timeline = []
        self._up_streak = 0
        self._down_streak = 0
        self._cool = 0
        self._last_now = None
        router.autoscaler = self

    # ------------------------------------------------------------- #
    # fleet signals
    # ------------------------------------------------------------- #

    def actual(self):
        """Replicas still IN the fleet (warming and backoff-respawning
        included; retired and budget-spent slots are gone for good)."""
        return sum(1 for r in self.router.replicas
                   if r.state != RETIRED and not r.terminal)

    def worst_burn(self):
        """Max burn rate across every UP replica's SLO monitors (0.0
        with no monitors configured — no evidence is not a breach)."""
        worst = 0.0
        for r in self.router.replicas:
            if r.state != UP or r.engine is None:
                continue
            mon = getattr(r.engine, "slo", None)
            if mon is None:
                continue
            for s in mon.slos:
                worst = max(worst, mon.burn_rate(s.name))
        return worst

    # ------------------------------------------------------------- #
    # the control loop
    # ------------------------------------------------------------- #

    def tick(self, now=None):
        """One control decision (the router calls this per step).
        Disabled = a strict no-op: no gauges, no events, no membership
        changes — byte-identical to a router with no autoscaler."""
        if not self.enabled:
            return
        now = time.perf_counter() if now is None else now
        self.ticks += 1
        actual = self.actual()
        if self._last_now is not None:
            # replica-seconds integrate ACTUAL membership over wall
            # time: a warming replica costs money before it serves
            self.replica_seconds += actual * max(now - self._last_now,
                                                 0.0)
        self._last_now = now
        self.replica_ticks += actual
        self.peak_replicas = max(self.peak_replicas, actual)
        burn = self.worst_burn()
        pressure = self.router.queue_pressure()
        self.last_burn, self.last_pressure = burn, pressure
        telemetry.set_gauge("fleet.replicas", actual)
        telemetry.set_gauge("fleet.burn", round(burn, 4))
        hot = burn >= self.up_burn or pressure >= self.up_pressure
        idle = (burn < 1.0 and pressure <= self.down_pressure
                and not self.router._pending)
        self._up_streak = self._up_streak + 1 if hot else 0
        self._down_streak = self._down_streak + 1 if idle else 0
        if self._cool > 0:
            self._cool -= 1
            return
        if self._up_streak >= self.up_ticks and actual < self.fleet_max:
            self._scale_up(burn, pressure)
        elif self._down_streak >= self.down_ticks \
                and actual > self.fleet_min:
            ws = self.router.weight_sync
            if ws is not None and ws.active is not None:
                # never drop below the version-committed quorum while a
                # rollout is in flight: retiring a replica mid-rollout
                # would shrink the set the commit is defined over
                self.deferred_rollout += 1
                return
            self._scale_down(burn, pressure)

    def _scale_up(self, burn, pressure):
        reason = "burn" if burn >= self.up_burn else "pressure"
        idx = len(self.router.replicas)   # the index add_replica takes
        self._emit("scale_up", idx, reason, burn, pressure,
                   target=min(self.actual() + 1, self.fleet_max))
        self.router.add_replica(warm_prefixes=self.warm_prefixes)
        self.scale_ups += 1
        self._settle("scale_up", idx, reason)

    def _scale_down(self, burn, pressure):
        victim = self._victim()
        if victim is None:
            return
        self._emit("scale_down", victim.index, "idle", burn, pressure,
                   target=max(self.actual() - 1, self.fleet_min))
        self.router.retire_replica(victim.index, reason="scale_down")
        self.scale_downs += 1
        self._settle("scale_down", victim.index, "idle")

    def _victim(self):
        """Least-loaded serving replica; newest breaks ties (it holds
        the least session/prefix warmth).  Quiesced (swap-held) and
        non-UP replicas are never retired from under their owner."""
        cands = [r for r in self.router.replicas
                 if r.state == UP
                 and r.index not in self.router._swap_hold]
        if len(cands) < 2:
            return None   # retiring the last UP replica strands traffic
        return min(cands,
                   key=lambda r: (r.queue_depth + r.live, -r.index))

    # ------------------------------------------------------------- #
    # bookkeeping
    # ------------------------------------------------------------- #

    def _emit(self, action, idx, reason, burn, pressure, target):
        self.router._fail_event(
            action, replica=idx, reason=reason, target=target,
            actual=self.actual(), burn=round(burn, 4),
            pressure=round(pressure, 4))
        self.timeline.append({
            "tick": self.ticks, "action": action, "replica": idx,
            "reason": reason, "burn": round(burn, 4),
            "pressure": round(pressure, 4)})

    def _settle(self, action, idx, reason):
        self.last_action = {"action": action, "replica": idx,
                            "reason": reason, "tick": self.ticks}
        self._up_streak = self._down_streak = 0
        self._cool = self.cooldown
        # the scale timeline is the incident black box: what the fleet
        # believed (burn/pressure per action) when it resized itself
        flight.RECORDER.dump(action, replica=idx, cause=reason,
                             timeline=list(self.timeline[-8:]))

    def snapshot(self):
        """JSON-able view (rides ``router.snapshot()['autoscaler']``;
        ``hetu_top --fleet`` renders the event-stream twin)."""
        return {
            "enabled": self.enabled,
            "min": self.fleet_min,
            "max": self.fleet_max,
            "actual": self.actual(),
            "peak_replicas": self.peak_replicas,
            "ticks": self.ticks,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "deferred_rollout": self.deferred_rollout,
            "replica_seconds": round(self.replica_seconds, 4),
            "replica_ticks": self.replica_ticks,
            "burn": round(self.last_burn, 4),
            "pressure": round(self.last_pressure, 4),
            "cooldown_left": self._cool,
            "last_action": self.last_action,
        }
