"""Serving telemetry: per-request lifecycle tracing + latency
aggregates + engine gauges.

Structured events flow through the ONE telemetry sink
(telemetry/events.py): ``{"t": <epoch>, "event": <kind>, **fields}``
records kept in memory and appended as JSONL to the ``serve`` stream —
``$HETU_SERVE_LOG`` (legacy path, one tail/jq pipeline with the failure
log) plus the merged ``$HETU_TELEMETRY_LOG``.

Request lifecycle (ISSUE 7 tentpole): every request is tracked through
submit -> queue -> kv_alloc -> prefill (per chunk) -> decode -> retire.
At retirement the tracker emits one ``req_span`` record per phase
(``t`` = the phase's START epoch, ``ms`` its length — the exact shape
``span`` records use, so ``hetu_trace --export`` renders each request
as its own Perfetto track) plus a ``req_retire`` record carrying the
full component breakdown:

    queue_ms        submit -> first admission attempt
    requeue_ms      head-of-queue wait while blocked (paged pool
                    exhaustion / prefix deferral); 0 when never blocked
    router_hop_ms   wall time lost to a FAILED placement before this
                    engine saw the request (the fleet router requeued
                    it off a dead/wedged replica — serving/router.py
                    credits the hop at re-submission); 0 un-routed
    handoff_ms      prefill->decode disaggregation detour before this
                    engine saw the request: clone prefill on the
                    prefill-heavy replica + KV export/import (the
                    router credits it when the imported blocks land);
                    0 without a handoff
    kv_alloc_ms     slot + block-table claim
    prefill_ms      prompt compute actually dispatched for this request
    chunk_stall_ms  prefill-phase wall not spent computing (chunked
                    prefill interleaving with decode waves)
    decode_ms       first token -> retirement

``snapshot()`` aggregates each component at p50/p95/p99 and
``explain_tail()`` names the component that dominates the p99-TTFT
tail — the "why was this request 40x the median" answer.

Memory: ``events`` is the full history only when a log path is
configured (the run is being deliberately observed and the JSONL has
it anyway); otherwise it is a bounded ring (``HETU_TELEMETRY_BUFFER``)
— a long-running engine no longer leaks one dict per record.
"""

from __future__ import annotations

import collections
import time

from .. import envvars, telemetry
from ..telemetry.metrics import percentile

import numpy as np

COMPONENTS = ("queue_ms", "requeue_ms", "router_hop_ms", "handoff_ms",
              "kv_alloc_ms", "prefill_ms", "chunk_stall_ms", "decode_ms")


def _pct(xs, q):
    """Seconds-valued percentile via THE shared interpolating helper
    (telemetry.metrics.percentile) — serving and the metrics registry
    now agree on what a p99 is."""
    xs = list(xs)
    return percentile(xs, q) if xs else None


class _Lifecycle:
    """Perf-counter timeline of one request, engine-side."""

    __slots__ = ("t_submit", "t_blocked", "t_claim", "kv_alloc_ms",
                 "prefill_ms", "t_first", "n_prefills", "hop_ms",
                 "handoff_ms")

    def __init__(self, t_submit):
        self.t_submit = t_submit
        self.t_blocked = None     # first blocked admission attempt
        self.t_claim = None       # slot + KV claimed
        self.kv_alloc_ms = 0.0
        self.prefill_ms = 0.0     # dispatched prompt compute
        self.n_prefills = 0       # dispatches (chunks) it rode in
        self.t_first = None       # first token landed
        self.hop_ms = 0.0         # router requeue hops before us
        self.handoff_ms = 0.0     # prefill->decode handoff detour


class MetricsCore:
    """Model-agnostic serving-telemetry base: the event pipeline,
    clock plumbing, and the submit/reject lifecycle every engine kind
    shares.  :class:`ServingMetrics` (GPT decode) and
    :class:`EmbedServingMetrics` (recommendation scoring) both build
    on this, so the fleet router / hetu_top / span-balance tooling
    read one event vocabulary regardless of workload."""

    def __init__(self, log_path=None, tags=None):
        self.log_path = (log_path if log_path is not None
                         else envvars.get_path("HETU_SERVE_LOG"))
        # fields stamped onto EVERY event this engine emits (the fleet
        # router tags each replica's engine with replica=<k>, which is
        # what lets hetu_top --fleet and the per-replica span-balance
        # rule tell N same-process engines apart in one merged stream)
        self.tags = dict(tags or {})
        cap = max(1, envvars.get_int("HETU_TELEMETRY_BUFFER"))
        # full in-memory history only when the run keeps a JSONL log
        # (deliberate observation); ring-buffered otherwise so a
        # long-running engine's memory stays bounded
        self.events = ([] if self.log_path
                       else collections.deque(maxlen=cap))
        self.submitted = 0
        self.rejected = 0
        self.finished = 0
        self._lc = {}              # request_id -> lifecycle record
        self._t0 = None
        self._t_last = None

    # ------------------------------------------------------------- #

    def event(self, kind, **fields):
        # a "t" field overrides the record's timestamp (req_span records
        # are START-stamped like `span` records)
        rec = telemetry.emit(kind, _stream="serve", _path=self.log_path,
                             _t=fields.pop("t", None),
                             **{**self.tags, **fields})
        self.events.append(rec)
        return rec

    def _mark(self):
        now = time.perf_counter()
        if self._t0 is None:
            self._t0 = now
        self._t_last = now

    # ONE epoch<->perf_counter offset for the whole process: deriving
    # it per call (time.time() - perf_counter() read back to back) let
    # scheduler preemption between the two clock reads skew req_span
    # stamps against serve_step stamps by milliseconds, which pushed
    # flow-arrow bindings outside their wave spans on loaded boxes —
    # a shared offset makes every exported timestamp mutually
    # consistent by construction (long-run clock drift is irrelevant
    # at trace granularity)
    _PERF_TO_EPOCH = time.time() - time.perf_counter()

    @classmethod
    def _epoch(cls, perf_t):
        """Map a perf_counter stamp onto the epoch clock the telemetry
        stream uses (so req_span tracks align with span tracks)."""
        return cls._PERF_TO_EPOCH + perf_t

    def _make_lc(self, t_submit):
        """Workload-specific lifecycle record for one request."""
        raise NotImplementedError

    def record_submit(self, request_id, queue_depth):
        self.submitted += 1
        self._lc[request_id] = self._make_lc(time.perf_counter())
        self.event("serve_submit", request=request_id,
                   queue_depth=queue_depth)

    def record_reject(self, request_id, queue_depth):
        self.rejected += 1
        self.event("serve_queue_reject", request=request_id,
                   queue_depth=queue_depth)

    def lc_hop(self, request_id, hop_ms):
        """Credit wall time the fleet router lost placing this request
        on a replica that died/wedged before it could retire (called by
        the router right after the re-submission; accumulates across
        hops)."""
        lc = self._lc.get(request_id)
        if lc is not None:
            lc.hop_ms += float(hop_ms)


class ServingMetrics(MetricsCore):
    def __init__(self, log_path=None, tags=None):
        super().__init__(log_path=log_path, tags=tags)
        self.tokens_generated = 0
        self.ttfts = []            # seconds, submit -> first token
        self.latencies = []        # seconds, submit -> finish
        self.tpots = []            # per-request decode s/token means
        self.step_live = []        # live slots per fused step
        self.step_queue = []       # queue depth per fused step
        self.step_dt = []          # seconds per fused decode step
        self.step_tokens = []      # tokens EMITTED per fused step (==
        # live without speculation; 1..(k+1)*live with it — the TPOT
        # percentiles are computed from these real per-step counts)
        self.step_prefill = []     # prefill seconds folded into a step
        self.prefill_dt = []       # seconds per prefill dispatch
        self.prefill_reqs = 0      # requests prefilled
        self.prefill_batched = 0   # batched (fast-path) dispatches
        self.components = {c: [] for c in COMPONENTS}
        # mixed-mode ragged dispatch ($HETU_SERVE_RAGGED): the engine
        # sets this when every step is ONE unified wave — prefill
        # attribution then covers the whole ragged dispatch, so the
        # chunk_stall component is asserted near-zero at retirement and
        # folded to exactly 0 (kept in COMPONENTS for back-compat:
        # dashboards and the tail report keep their schema)
        self.mixed_mode = False
        # per-request breakdowns explain_tail() slices (ring: the tail
        # report is about RECENT behavior, same cap as the event ring)
        cap = max(1, envvars.get_int("HETU_TELEMETRY_BUFFER"))
        self.breakdowns = collections.deque(maxlen=cap)
        self._slots = None

    def _make_lc(self, t_submit):
        return _Lifecycle(t_submit)

    # ------------------------------------------------------------- #
    # lifecycle marks (the engine calls these at phase boundaries)
    # ------------------------------------------------------------- #

    def lc_blocked(self, request_id):
        """The head-of-queue request could not admit this attempt
        (pool/slot exhaustion or prefix deferral): starts its requeue
        clock.  Idempotent — only the FIRST block mark counts."""
        lc = self._lc.get(request_id)
        if lc is not None and lc.t_blocked is None:
            lc.t_blocked = time.perf_counter()

    def lc_claimed(self, request_id, kv_alloc_ms):
        """Slot + KV claimed (queue/requeue phases end here)."""
        lc = self._lc.get(request_id)
        if lc is not None:
            lc.t_claim = time.perf_counter()
            lc.kv_alloc_ms = float(kv_alloc_ms)

    def lc_prefill(self, request_id, dt_s, count=True):
        """Attribute one prefill dispatch's wall time to this request
        (a chunked prompt accumulates across chunks).  ``count=False``
        adds wall without counting a dispatch — the mixed-mode engine
        uses it to top a rider up to the full wave elapsed after the
        wave's unpack completes."""
        lc = self._lc.get(request_id)
        if lc is not None:
            lc.prefill_ms += dt_s * 1e3
            if count:
                lc.n_prefills += 1

    def lc_handoff(self, request_id, handoff_ms):
        """Credit the prefill->decode disaggregation detour: wall time
        between the router flipping this request into its prefill
        phase and the exported KV blocks landing on THIS engine's pool
        (called by the router right after the import)."""
        lc = self._lc.get(request_id)
        if lc is not None:
            lc.handoff_ms += float(handoff_ms)

    # ------------------------------------------------------------- #

    def record_admit(self, request_id, slot, queue_wait_s, ttft_s):
        self._mark()
        self.ttfts.append(ttft_s)
        self.tokens_generated += 1          # prefill emits token #1
        lc = self._lc.get(request_id)
        if lc is not None:
            lc.t_first = time.perf_counter()
        self.event("serve_admit", request=request_id, slot=slot,
                   queue_wait_s=round(queue_wait_s, 6),
                   ttft_s=round(ttft_s, 6))

    def record_prefill(self, n, bucket, dt_s, batched=False):
        """One prefill dispatch: ``n`` requests admitted in one jitted
        call (n > 1 only on the batched fast path) at prompt bucket
        ``bucket``."""
        self._mark()
        self.prefill_dt.append(dt_s)
        self.prefill_reqs += n
        if batched:
            self.prefill_batched += 1
        self.event("serve_prefill", n=n, bucket=bucket,
                   prefill_ms=round(dt_s * 1e3, 3), batched=bool(batched))

    def record_step(self, live, slots, queue_depth, dt_s, new_tokens,
                    prefill_s=0.0, step=None, requests=None,
                    end_perf=None, spec=None, mix=None, moe=None):
        """One fused decode step; ``prefill_s`` is the prefill wall time
        this scheduler iteration paid before decoding, so the per-step
        JSONL event attributes the phases separately (the masked vs
        ragged A/B reads these).  ``step``/``requests`` identify the
        wave and its participants — the trace exporter draws flow
        arrows from each request's lifecycle track into the wave.
        ``end_perf`` is the decode's end perf-stamp: the event's ``t``
        then marks the true phase end (the exporter backdates the wave
        start by ``decode_ms``) instead of the emission time, which
        trails it by the retire loop.

        ``new_tokens`` is the step's REAL emitted-token count (a
        speculative wave emits up to k+1 per slot): it lands in the
        event, in ``step_tokens``, and in the ``serve.tokens_per_step``
        histogram — TPOT is computed from these, never from a
        one-token-per-step assumption.  ``spec`` (a
        {k, proposed, accepted} dict) stamps a speculative wave's
        draft accounting onto the event.

        ``mix`` (a {q_prefill, q_verify, q_decode} dict, mixed-mode
        engines only) stamps the wave's per-mode q-token split onto the
        event — how many of the ragged dispatch's query rows were
        prompt prefill, spec-verify, and plain decode (hetu_top's
        mixed-wave columns and the tail report read these).

        ``moe`` (a {tokens, routed, dropped, k, layers, imb,
        drop_rate} dict, MoE engines only) stamps the step's expert
        routing outcome — ``routed + dropped == tokens * k * layers``
        is the invariant hetu_trace --check enforces, ``imb`` and
        ``drop_rate`` feed hetu_top's expert columns.  Dense steps
        carry no moe_* fields and the checker exempts them."""
        self._mark()
        self._slots = slots
        self.step_live.append(live)
        self.step_queue.append(queue_depth)
        self.step_dt.append(dt_s)
        self.step_prefill.append(prefill_s)
        self.step_tokens.append(int(new_tokens))
        self.tokens_generated += new_tokens
        telemetry.observe("serve.tokens_per_step", int(new_tokens))
        fields = {}
        if step is not None:
            fields["step"] = step
        if requests is not None:
            fields["requests"] = list(requests)
        if end_perf is not None:
            fields["t"] = self._epoch(end_perf)
        if spec is not None:
            fields["spec_k"] = int(spec.get("k", 0))
            fields["spec_proposed"] = int(spec.get("proposed", 0))
            fields["spec_accepted"] = int(spec.get("accepted", 0))
        if mix is not None:
            fields["q_prefill"] = int(mix.get("q_prefill", 0))
            fields["q_verify"] = int(mix.get("q_verify", 0))
            fields["q_decode"] = int(mix.get("q_decode", 0))
        if moe is not None:
            fields["moe_tokens"] = int(moe.get("tokens", 0))
            fields["moe_routed"] = int(moe.get("routed", 0))
            fields["moe_dropped"] = int(moe.get("dropped", 0))
            fields["moe_k"] = int(moe.get("k", 0))
            fields["moe_layers"] = int(moe.get("layers", 0))
            fields["moe_imb"] = round(float(moe.get("imb", 0.0)), 4)
            fields["moe_drop_rate"] = round(
                float(moe.get("drop_rate", 0.0)), 6)
        self.event("serve_step", live=live, queue_depth=queue_depth,
                   slots=slots, new_tokens=int(new_tokens),
                   prefill_ms=round(prefill_s * 1e3, 3),
                   decode_ms=round(dt_s * 1e3, 3), **fields)

    def record_finish(self, request_id, reason, n_generated, latency_s,
                      spec=None):
        """``spec`` ({accepted, proposed, bonus}, speculative engines
        only) rides into the req_retire record so hetu_trace --check
        can assert accepted + bonus + 1 == n_generated per request."""
        self._mark()
        self.finished += 1
        self.latencies.append(latency_s)
        self.event("serve_finish", request=request_id, reason=reason,
                   n_generated=n_generated, latency_s=round(latency_s, 6))
        return self._retire(request_id, n_generated, spec=spec)

    # ------------------------------------------------------------- #
    # retirement: component breakdown + per-phase req_span records
    # ------------------------------------------------------------- #

    def _retire(self, request_id, n_generated, spec=None):
        lc = self._lc.pop(request_id, None)
        if lc is None or lc.t_claim is None or lc.t_first is None:
            return None
        now = time.perf_counter()
        claim_end = lc.t_claim
        claim_start = claim_end - lc.kv_alloc_ms / 1e3
        queue_end = lc.t_blocked if lc.t_blocked is not None \
            else claim_start
        queue_ms = max(queue_end - lc.t_submit, 0.0) * 1e3
        requeue_ms = (max(claim_start - lc.t_blocked, 0.0) * 1e3
                      if lc.t_blocked is not None else 0.0)
        prefill_wall_ms = max(lc.t_first - claim_end, 0.0) * 1e3
        prefill_ms = min(lc.prefill_ms, prefill_wall_ms)
        chunk_stall_ms = max(prefill_wall_ms - prefill_ms, 0.0)
        if self.mixed_mode:
            # unified wave: the whole ragged dispatch IS this request's
            # prefill compute — any residue is host bookkeeping between
            # claim and dispatch, noise-scale by construction.  Assert
            # that (an accounting regression shows up HERE, not as a
            # quietly wrong dashboard) and fold the component to 0.
            assert chunk_stall_ms <= max(50.0, 0.5 * prefill_wall_ms), (
                f"mixed-mode chunk_stall residue {chunk_stall_ms:.1f}ms "
                f"of {prefill_wall_ms:.1f}ms prefill wall for "
                f"{request_id}: wave attribution is broken")
            chunk_stall_ms = 0.0
        decode_ms = max(now - lc.t_first, 0.0) * 1e3 \
            if n_generated > 1 else 0.0
        ttft_ms = max(lc.t_first - lc.t_submit, 0.0) * 1e3
        comp = {"queue_ms": queue_ms, "requeue_ms": requeue_ms,
                "router_hop_ms": lc.hop_ms, "handoff_ms": lc.handoff_ms,
                "kv_alloc_ms": lc.kv_alloc_ms, "prefill_ms": prefill_ms,
                "chunk_stall_ms": chunk_stall_ms, "decode_ms": decode_ms}
        for k, v in comp.items():
            self.components[k].append(v)
        if n_generated > 1 and decode_ms > 0:
            # per-request decode MEAN (wall over tokens) — a valid
            # average either way, but NOT the TPOT percentile source:
            # snapshot() builds that from real per-step token counts
            self.tpots.append(decode_ms / 1e3 / (n_generated - 1))
        breakdown = {"request": request_id, "ttft_ms": ttft_ms,
                     **{k: round(v, 3) for k, v in comp.items()}}
        self.breakdowns.append(breakdown)
        # one span per phase, start-stamped like `span` records so the
        # exporter lays the request out as its own track
        phases = [("queue", lc.t_submit, queue_ms, {}),
                  ("kv_alloc", claim_start, lc.kv_alloc_ms, {})]
        if lc.t_blocked is not None:
            phases.insert(1, ("requeue", lc.t_blocked, requeue_ms, {}))
        if lc.handoff_ms > 0:
            # like the hop: the detour ended at this engine's submit —
            # backdate so the track reads handoff -> queue -> ...
            phases.insert(0, ("handoff",
                              lc.t_submit - lc.handoff_ms / 1e3,
                              lc.handoff_ms, {}))
        if lc.hop_ms > 0:
            # the hop happened BEFORE this engine's submit: backdate
            # its span so the request's track reads hop -> queue -> ...
            phases.insert(0, ("router_hop",
                              lc.t_submit - lc.hop_ms / 1e3,
                              lc.hop_ms, {}))
        phases.append(("prefill", claim_end, prefill_wall_ms,
                       {"compute_ms": round(prefill_ms, 3),
                        "stall_ms": round(chunk_stall_ms, 3),
                        "dispatches": lc.n_prefills}))
        if decode_ms > 0:
            phases.append(("decode", lc.t_first, decode_ms,
                           {"n_tokens": n_generated - 1}))
        for phase, t_start, ms, extra in phases:
            self.event("req_span", request=request_id, phase=phase,
                       ms=round(ms, 3), t=self._epoch(t_start), **extra)
        spec_fields = {}
        if spec is not None:
            spec_fields = {"spec_accepted": int(spec.get("accepted", 0)),
                           "spec_proposed": int(spec.get("proposed", 0)),
                           "spec_bonus": int(spec.get("bonus", 0))}
        self.event("req_retire", request=request_id,
                   ttft_ms=round(ttft_ms, 3),
                   n_generated=n_generated, **spec_fields,
                   **breakdown_fields(comp))
        return breakdown

    # ------------------------------------------------------------- #

    def snapshot(self):
        """Aggregate view (JSON-able): throughput, TTFT/TPOT
        percentiles, mean batch occupancy over fused steps, queue
        stats, and the per-component tail decomposition."""
        wall = ((self._t_last - self._t0)
                if self._t0 is not None and self._t_last > self._t0
                else None)
        occ = ([l / self._slots for l in self.step_live]
               if self._slots else [])
        # TPOT from REAL per-step emitted-token counts: a step emitting
        # n tokens contributes n samples of dt/n — correct with and
        # without speculation (the old per-request decode_ms/(n-1)
        # assumed one token per wave and skewed the percentiles the
        # moment waves emitted more)
        tpot = []
        for dt, n in zip(self.step_dt, self.step_tokens):
            if n > 0:
                tpot.extend([dt / n] * n)
        comps = {}
        for name, xs in self.components.items():
            if xs:
                comps[name] = {
                    "p50_ms": round(_pct(xs, 50), 3),
                    "p95_ms": round(_pct(xs, 95), 3),
                    "p99_ms": round(_pct(xs, 99), 3),
                    "mean_ms": round(float(np.mean(xs)), 3),
                }
        return {
            "requests_submitted": self.submitted,
            "requests_rejected": self.rejected,
            "requests_finished": self.finished,
            "tokens_generated": self.tokens_generated,
            "wall_s": round(wall, 6) if wall else None,
            "tokens_per_sec": (round(self.tokens_generated / wall, 2)
                               if wall else None),
            "ttft_p50_s": _pct(self.ttfts, 50),
            "ttft_p95_s": _pct(self.ttfts, 95),
            "ttft_p99_s": _pct(self.ttfts, 99),
            "ttft_mean_s": (float(np.mean(self.ttfts))
                            if self.ttfts else None),
            "tpot_p50_s": _pct(tpot, 50),
            "tpot_p99_s": _pct(tpot, 99),
            "tpot_req_mean_p50_s": _pct(self.tpots, 50),
            "tokens_per_step_mean": (float(np.mean(self.step_tokens))
                                     if self.step_tokens else None),
            "step_p50_s": _pct(self.step_dt, 50),
            "step_p99_s": _pct(self.step_dt, 99),
            "decode_ms_p50": (round(_pct(self.step_dt, 50) * 1e3, 3)
                              if self.step_dt else None),
            "prefill_ms_p50": (round(_pct(self.prefill_dt, 50) * 1e3, 3)
                               if self.prefill_dt else None),
            "prefill_total_s": (round(float(np.sum(self.prefill_dt)), 6)
                                if self.prefill_dt else None),
            "decode_total_s": (round(float(np.sum(self.step_dt)), 6)
                               if self.step_dt else None),
            "prefill_dispatches": len(self.prefill_dt),
            "prefill_batched_dispatches": self.prefill_batched,
            "steps": len(self.step_live),
            "mean_batch_occupancy": (float(np.mean(occ)) if occ else None),
            "mean_queue_depth": (float(np.mean(self.step_queue))
                                 if self.step_queue else None),
            "components": comps,
        }

    def explain_tail(self, q=99):
        """Name the component that dominates the TTFT tail: slice the
        requests at or above the q-th TTFT percentile and average their
        breakdowns.  The dominant component is the report's headline —
        "p99 TTFT is queue-bound" is an actionable statement (admission
        control) where "p99 TTFT is 40x p50" is not.  Returns None with
        no finished requests."""
        rows = [b for b in self.breakdowns if b.get("ttft_ms") is not None]
        if not rows:
            return None
        ttfts = [b["ttft_ms"] for b in rows]
        cut = _pct(ttfts, q)
        tail = [b for b in rows if b["ttft_ms"] >= cut]
        means = {c: float(np.mean([b[c] for b in tail]))
                 for c in COMPONENTS}
        # decode is not part of TTFT — the tail is decomposed over the
        # submit->first-token phases only
        ttft_parts = {c: v for c, v in means.items() if c != "decode_ms"}
        dominant = max(ttft_parts, key=ttft_parts.get)
        total = sum(ttft_parts.values()) or 1.0
        share = ttft_parts[dominant] / total
        report = {
            "q": q,
            "ttft_p_ms": round(cut, 3),
            "ttft_p50_ms": round(_pct(ttfts, 50), 3),
            "n_requests": len(rows),
            "n_tail": len(tail),
            "dominant_component": dominant,
            "dominant_ms": round(ttft_parts[dominant], 3),
            "dominant_share": round(share, 4),
            "components_mean_ms": {c: round(v, 3)
                                   for c, v in means.items()},
            "tail_requests": [b["request"] for b in tail[:8]],
            "mixed_mode": self.mixed_mode,
        }
        report["summary"] = (
            f"p{q} TTFT {cut:.1f}ms ({len(tail)}/{len(rows)} requests): "
            f"dominated by {dominant.replace('_ms', '')} "
            f"({ttft_parts[dominant]:.1f}ms, {share:.0%} of the "
            f"pre-token wall)")
        if self.mixed_mode:
            # the unified wave carries all modes: prefill_ms here means
            # "ragged dispatches this prompt rode in" and chunk_stall
            # is 0 by construction (folded at retirement)
            report["summary"] += (
                " [mixed-mode: prefill attributed to unified ragged "
                "waves; chunk_stall folded to 0]")
        return report


EMBED_COMPONENTS = ("queue_ms", "router_hop_ms", "gather_ms",
                    "forward_ms")


class _EmbedLifecycle:
    """Perf-counter timeline of one scoring request: submit -> wave
    claim -> gather (embedding fetch) -> forward (tower) -> retire."""

    __slots__ = ("t_submit", "t_claim", "gather_ms", "t_first",
                 "hop_ms")

    def __init__(self, t_submit):
        self.t_submit = t_submit
        self.t_claim = None       # wave claimed the request
        self.gather_ms = 0.0      # embedding gather attributed to it
        self.t_first = None       # scores landed
        self.hop_ms = 0.0         # router requeue hops before us


class EmbedServingMetrics(MetricsCore):
    """Embedding-engine telemetry: the GPT lifecycle with the KV
    phases replaced by ``gather_ms`` (CacheSparseTable fetch) and
    ``forward_ms`` (the jitted tower).  Emits the SAME event kinds the
    GPT engine does — serve_submit/serve_admit/serve_step/serve_finish
    plus per-phase req_span and req_retire — so hetu_trace --check's
    span-balance rule, hetu_top, and the SLO monitor work unmodified;
    the one new kind is the per-wave ``serve_gather`` record.  Every
    event carries ``workload="embed"`` (hetu_top's workload column)."""

    def __init__(self, log_path=None, tags=None):
        super().__init__(log_path=log_path, tags=tags)
        self.tags.setdefault("workload", "embed")
        self.pairs_scored = 0
        self.ttfts = []            # seconds, submit -> scores landed
        self.latencies = []        # == ttfts shape-wise; kept separate
        # so snapshot() reads like the GPT one
        self.step_live = []        # requests per wave
        self.step_queue = []       # queue depth per wave
        self.step_dt = []          # seconds per wave (gather+forward)
        self.step_rows = []        # pairs scored per wave
        self.gather_dt = []        # seconds per wave gather
        self.hit_rates = []        # cache hit-rate per wave gather
        self.components = {c: [] for c in EMBED_COMPONENTS}
        cap = max(1, envvars.get_int("HETU_TELEMETRY_BUFFER"))
        self.breakdowns = collections.deque(maxlen=cap)
        self._slots = None

    def _make_lc(self, t_submit):
        return _EmbedLifecycle(t_submit)

    # ------------------------------------------------------------- #

    def lc_claimed(self, request_id):
        """The wave claimed this request off the queue (queue phase
        ends here; gather starts)."""
        lc = self._lc.get(request_id)
        if lc is not None:
            lc.t_claim = time.perf_counter()

    def record_gather(self, n, rows, gather_s, hit_rate, requests=()):
        """One wave's embedding gather: ``n`` requests, ``rows`` total
        pairs fetched through the cache in ``gather_s`` seconds at
        ``hit_rate``.  Attributes the wall to every participant."""
        self._mark()
        self.gather_dt.append(gather_s)
        self.hit_rates.append(float(hit_rate))
        for rid in requests:
            lc = self._lc.get(rid)
            if lc is not None:
                lc.gather_ms += gather_s * 1e3
        self.event("serve_gather", n=n, rows=rows,
                   gather_ms=round(gather_s * 1e3, 3),
                   hit_rate=round(float(hit_rate), 4))

    def record_admit(self, request_id, slot, queue_wait_s, ttft_s):
        """Scores landed for this request (embed waves emit the whole
        result at once, so admit == first-result)."""
        self._mark()
        self.ttfts.append(ttft_s)
        lc = self._lc.get(request_id)
        if lc is not None:
            lc.t_first = time.perf_counter()
        self.event("serve_admit", request=request_id, slot=slot,
                   queue_wait_s=round(queue_wait_s, 6),
                   ttft_s=round(ttft_s, 6))

    def record_step(self, live, slots, queue_depth, dt_s, rows,
                    gather_s=0.0, step=None, requests=None):
        """One scoring wave: ``dt_s`` is the wave wall (gather +
        forward), ``rows`` the pairs it scored.  Shapes the serve_step
        event like a GPT decode wave (decode_ms = the forward wall) so
        hetu_top and the trace exporter render waves unmodified."""
        self._mark()
        self._slots = slots
        self.step_live.append(live)
        self.step_queue.append(queue_depth)
        self.step_dt.append(dt_s)
        self.step_rows.append(int(rows))
        self.pairs_scored += int(rows)
        telemetry.observe("serve.pairs_per_wave", int(rows))
        fields = {}
        if step is not None:
            fields["step"] = step
        if requests is not None:
            fields["requests"] = list(requests)
        self.event("serve_step", live=live, queue_depth=queue_depth,
                   slots=slots, rows=int(rows),
                   gather_ms=round(gather_s * 1e3, 3),
                   decode_ms=round(max(dt_s - gather_s, 0.0) * 1e3, 3),
                   **fields)

    def record_finish(self, request_id, reason, n_pairs, latency_s):
        self._mark()
        self.finished += 1
        self.latencies.append(latency_s)
        self.event("serve_finish", request=request_id, reason=reason,
                   n_generated=n_pairs, latency_s=round(latency_s, 6))
        return self._retire(request_id)

    def _retire(self, request_id):
        lc = self._lc.pop(request_id, None)
        if lc is None or lc.t_claim is None or lc.t_first is None:
            return None
        queue_ms = max(lc.t_claim - lc.t_submit, 0.0) * 1e3
        wave_wall_ms = max(lc.t_first - lc.t_claim, 0.0) * 1e3
        gather_ms = min(lc.gather_ms, wave_wall_ms)
        forward_ms = max(wave_wall_ms - gather_ms, 0.0)
        ttft_ms = max(lc.t_first - lc.t_submit, 0.0) * 1e3
        comp = {"queue_ms": queue_ms, "router_hop_ms": lc.hop_ms,
                "gather_ms": gather_ms, "forward_ms": forward_ms}
        for k, v in comp.items():
            self.components[k].append(v)
        breakdown = {"request": request_id, "ttft_ms": ttft_ms,
                     **{k: round(v, 3) for k, v in comp.items()}}
        self.breakdowns.append(breakdown)
        phases = [("queue", lc.t_submit, queue_ms, {}),
                  ("gather", lc.t_claim, gather_ms, {}),
                  ("forward", lc.t_claim + gather_ms / 1e3,
                   forward_ms, {})]
        if lc.hop_ms > 0:
            # the hop happened BEFORE this engine's submit: backdate
            # its span so the request's track reads hop -> queue -> ...
            phases.insert(0, ("router_hop",
                              lc.t_submit - lc.hop_ms / 1e3,
                              lc.hop_ms, {}))
        for phase, t_start, ms, extra in phases:
            self.event("req_span", request=request_id, phase=phase,
                       ms=round(ms, 3), t=self._epoch(t_start), **extra)
        self.event("req_retire", request=request_id,
                   ttft_ms=round(ttft_ms, 3),
                   **breakdown_fields(comp))
        return breakdown

    # ------------------------------------------------------------- #

    def snapshot(self):
        wall = ((self._t_last - self._t0)
                if self._t0 is not None and self._t_last > self._t0
                else None)
        occ = ([l / self._slots for l in self.step_live]
               if self._slots else [])
        comps = {}
        for name, xs in self.components.items():
            if xs:
                comps[name] = {
                    "p50_ms": round(_pct(xs, 50), 3),
                    "p95_ms": round(_pct(xs, 95), 3),
                    "p99_ms": round(_pct(xs, 99), 3),
                    "mean_ms": round(float(np.mean(xs)), 3),
                }
        return {
            "requests_submitted": self.submitted,
            "requests_rejected": self.rejected,
            "requests_finished": self.finished,
            "pairs_scored": self.pairs_scored,
            "wall_s": round(wall, 6) if wall else None,
            "qps": (round(self.finished / wall, 2) if wall else None),
            "pairs_per_sec": (round(self.pairs_scored / wall, 2)
                              if wall else None),
            "latency_p50_s": _pct(self.latencies, 50),
            "latency_p95_s": _pct(self.latencies, 95),
            "latency_p99_s": _pct(self.latencies, 99),
            "latency_mean_s": (float(np.mean(self.latencies))
                               if self.latencies else None),
            "gather_ms_p50": (round(_pct(self.gather_dt, 50) * 1e3, 3)
                              if self.gather_dt else None),
            "wave_ms_p50": (round(_pct(self.step_dt, 50) * 1e3, 3)
                            if self.step_dt else None),
            "cache_hit_rate_mean": (float(np.mean(self.hit_rates))
                                    if self.hit_rates else None),
            "steps": len(self.step_live),
            "rows_per_wave_mean": (float(np.mean(self.step_rows))
                                   if self.step_rows else None),
            "mean_batch_occupancy": (float(np.mean(occ)) if occ else None),
            "mean_queue_depth": (float(np.mean(self.step_queue))
                                 if self.step_queue else None),
            "components": comps,
        }

    def explain_tail(self, q=99):
        """Name the component dominating the latency tail (the embed
        twin of ServingMetrics.explain_tail — same report shape, over
        queue/hop/gather/forward instead of the KV phases)."""
        rows = [b for b in self.breakdowns if b.get("ttft_ms") is not None]
        if not rows:
            return None
        ttfts = [b["ttft_ms"] for b in rows]
        cut = _pct(ttfts, q)
        tail = [b for b in rows if b["ttft_ms"] >= cut]
        means = {c: float(np.mean([b[c] for b in tail]))
                 for c in EMBED_COMPONENTS}
        dominant = max(means, key=means.get)
        total = sum(means.values()) or 1.0
        share = means[dominant] / total
        return {
            "q": q,
            "ttft_p_ms": round(cut, 3),
            "ttft_p50_ms": round(_pct(ttfts, 50), 3),
            "n_requests": len(rows),
            "n_tail": len(tail),
            "dominant_component": dominant,
            "dominant_ms": round(means[dominant], 3),
            "dominant_share": round(share, 4),
            "components_mean_ms": {c: round(v, 3)
                                   for c, v in means.items()},
            "tail_requests": [b["request"] for b in tail[:8]],
            "summary": (
                f"p{q} latency {cut:.1f}ms ({len(tail)}/{len(rows)} "
                f"requests): dominated by {dominant.replace('_ms', '')} "
                f"({means[dominant]:.1f}ms, {share:.0%} of the wall)"),
        }


def breakdown_fields(comp):
    """Flatten a component dict for the req_retire record (scalar
    fields survive the trace exporter's args filter; a nested dict
    would be dropped)."""
    return {k: round(v, 3) for k, v in comp.items()}
