"""Serving telemetry: per-request latency aggregates + engine gauges.

Structured events flow through the ONE telemetry sink
(telemetry/events.py): ``{"t": <epoch>, "event": <kind>, **fields}``
records kept in memory and appended as JSONL to the ``serve`` stream —
``$HETU_SERVE_LOG`` (legacy path, one tail/jq pipeline with the failure
log) plus the merged ``$HETU_TELEMETRY_LOG``.

Aggregates answer the serving questions: TTFT percentiles (queue wait
included — measured from submit to first token), decode tokens/s, mean
batch occupancy (how full the fused step ran), queue depth.
"""

from __future__ import annotations

import time

from .. import envvars, telemetry

import numpy as np


def _pct(xs, q):
    return float(np.percentile(np.asarray(xs, np.float64), q)) if xs else None


class ServingMetrics:
    def __init__(self, log_path=None):
        self.log_path = (log_path if log_path is not None
                         else envvars.get_path("HETU_SERVE_LOG"))
        self.events = []
        self.submitted = 0
        self.rejected = 0
        self.finished = 0
        self.tokens_generated = 0
        self.ttfts = []            # seconds, submit -> first token
        self.latencies = []        # seconds, submit -> finish
        self.step_live = []        # live slots per fused step
        self.step_queue = []       # queue depth per fused step
        self.step_dt = []          # seconds per fused decode step
        self.step_prefill = []     # prefill seconds folded into a step
        self.prefill_dt = []       # seconds per prefill dispatch
        self.prefill_reqs = 0      # requests prefilled
        self.prefill_batched = 0   # batched (fast-path) dispatches
        self._slots = None
        self._t0 = None
        self._t_last = None

    # ------------------------------------------------------------- #

    def event(self, kind, **fields):
        rec = telemetry.emit(kind, _stream="serve", _path=self.log_path,
                             **fields)
        self.events.append(rec)
        return rec

    def _mark(self):
        now = time.perf_counter()
        if self._t0 is None:
            self._t0 = now
        self._t_last = now

    # ------------------------------------------------------------- #

    def record_submit(self, request_id, queue_depth):
        self.submitted += 1
        self.event("serve_submit", request=request_id,
                   queue_depth=queue_depth)

    def record_reject(self, request_id, queue_depth):
        self.rejected += 1
        self.event("serve_queue_reject", request=request_id,
                   queue_depth=queue_depth)

    def record_admit(self, request_id, slot, queue_wait_s, ttft_s):
        self._mark()
        self.ttfts.append(ttft_s)
        self.tokens_generated += 1          # prefill emits token #1
        self.event("serve_admit", request=request_id, slot=slot,
                   queue_wait_s=round(queue_wait_s, 6),
                   ttft_s=round(ttft_s, 6))

    def record_prefill(self, n, bucket, dt_s, batched=False):
        """One prefill dispatch: ``n`` requests admitted in one jitted
        call (n > 1 only on the batched fast path) at prompt bucket
        ``bucket``."""
        self._mark()
        self.prefill_dt.append(dt_s)
        self.prefill_reqs += n
        if batched:
            self.prefill_batched += 1
        self.event("serve_prefill", n=n, bucket=bucket,
                   prefill_ms=round(dt_s * 1e3, 3), batched=bool(batched))

    def record_step(self, live, slots, queue_depth, dt_s, new_tokens,
                    prefill_s=0.0):
        """One fused decode step; ``prefill_s`` is the prefill wall time
        this scheduler iteration paid before decoding, so the per-step
        JSONL event attributes the phases separately (the masked vs
        ragged A/B reads these)."""
        self._mark()
        self._slots = slots
        self.step_live.append(live)
        self.step_queue.append(queue_depth)
        self.step_dt.append(dt_s)
        self.step_prefill.append(prefill_s)
        self.tokens_generated += new_tokens
        self.event("serve_step", live=live, queue_depth=queue_depth,
                   prefill_ms=round(prefill_s * 1e3, 3),
                   decode_ms=round(dt_s * 1e3, 3))

    def record_finish(self, request_id, reason, n_generated, latency_s):
        self._mark()
        self.finished += 1
        self.latencies.append(latency_s)
        self.event("serve_finish", request=request_id, reason=reason,
                   n_generated=n_generated, latency_s=round(latency_s, 6))

    # ------------------------------------------------------------- #

    def snapshot(self):
        """Aggregate view (JSON-able): throughput, TTFT p50/p99, mean
        batch occupancy over fused steps, queue stats."""
        wall = ((self._t_last - self._t0)
                if self._t0 is not None and self._t_last > self._t0
                else None)
        occ = ([l / self._slots for l in self.step_live]
               if self._slots else [])
        return {
            "requests_submitted": self.submitted,
            "requests_rejected": self.rejected,
            "requests_finished": self.finished,
            "tokens_generated": self.tokens_generated,
            "wall_s": round(wall, 6) if wall else None,
            "tokens_per_sec": (round(self.tokens_generated / wall, 2)
                               if wall else None),
            "ttft_p50_s": _pct(self.ttfts, 50),
            "ttft_p99_s": _pct(self.ttfts, 99),
            "ttft_mean_s": (float(np.mean(self.ttfts))
                            if self.ttfts else None),
            "step_p50_s": _pct(self.step_dt, 50),
            "step_p99_s": _pct(self.step_dt, 99),
            "decode_ms_p50": (round(_pct(self.step_dt, 50) * 1e3, 3)
                              if self.step_dt else None),
            "prefill_ms_p50": (round(_pct(self.prefill_dt, 50) * 1e3, 3)
                               if self.prefill_dt else None),
            "prefill_total_s": (round(float(np.sum(self.prefill_dt)), 6)
                                if self.prefill_dt else None),
            "decode_total_s": (round(float(np.sum(self.step_dt)), 6)
                               if self.step_dt else None),
            "prefill_dispatches": len(self.prefill_dt),
            "prefill_batched_dispatches": self.prefill_batched,
            "steps": len(self.step_live),
            "mean_batch_occupancy": (float(np.mean(occ)) if occ else None),
            "mean_queue_depth": (float(np.mean(self.step_queue))
                                 if self.step_queue else None),
        }
